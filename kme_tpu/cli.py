"""Command-line entry points.

The reference splits its operational surface across three Node scripts and
a JVM main (topic.js / exchange_test.js / consumer.js / KProcessor.main,
README.md:10-30); here each role is one subcommand over a shared config.

Commands grow as the framework does; anything not yet wired reports
itself clearly instead of half-working.
"""

from __future__ import annotations

import argparse
import sys


def _not_yet(what: str) -> "int":
    print(f"kme_tpu: {what} is not wired up yet in this build", file=sys.stderr)
    return 2


def loadgen_main(argv=None) -> int:
    """Workload generator — the exchange_test.js role: emit a seeded wire
    stream (JSON lines) to stdout or a transport."""
    p = argparse.ArgumentParser(prog="kme-loadgen", description=loadgen_main.__doc__)
    p.add_argument("--events", type=int, default=1000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--accounts", type=int, default=10)
    p.add_argument("--symbols", type=int, default=3)
    p.add_argument("--validate", action="store_true",
                   help="clamp prices/sizes to the fixed-mode domain")
    p.add_argument("--fix-payout-opcode", action="store_true",
                   help="emit real PAYOUT (200) instead of the reference "
                        "harness's action=4 bug (Q5)")
    p.add_argument("--broker", default=None, metavar="HOST:PORT",
                   help="produce to MatchIn on this broker instead of "
                        "printing to stdout (the exchange_test.js role)")
    args = p.parse_args(argv)
    from kme_tpu.wire import dumps_order
    from kme_tpu.workload import harness_stream

    msgs = harness_stream(args.events, seed=args.seed,
                          num_accounts=args.accounts,
                          num_symbols=args.symbols,
                          payout_opcode_bug=not args.fix_payout_opcode,
                          validate=args.validate)
    if args.broker is not None:
        from kme_tpu.bridge.provision import provision
        from kme_tpu.bridge.service import TOPIC_IN
        from kme_tpu.bridge.tcp import TcpBroker, parse_addr

        host, port = parse_addr(args.broker)
        client = TcpBroker(host, port)
        try:
            provision(client)  # idempotent: both topics must exist
            for lo in range(0, len(msgs), 4096):
                client.produce_batch(
                    TOPIC_IN, [(None, dumps_order(m))
                               for m in msgs[lo:lo + 4096]])
        finally:
            client.close()
        print(f"kme-loadgen: produced {len(msgs)} records to MatchIn",
              file=sys.stderr)
        return 0
    for m in msgs:
        print(dumps_order(m))
    return 0


def oracle_main(argv=None) -> int:
    """Reference-replica engine over stdin/stdout: read order JSON lines,
    print the 'IN {...}' / 'OUT {...}' stream consumer.js would show."""
    p = argparse.ArgumentParser(prog="kme-oracle", description=oracle_main.__doc__)
    p.add_argument("--compat", choices=("java", "fixed"), default="java")
    args = p.parse_args(argv)
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.wire import parse_order

    eng = OracleEngine(args.compat)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        for rec in eng.process(parse_order(line)):
            print(rec.wire())
    return 0


def bench_main(argv=None) -> int:
    """Benchmark harness (bench.py at the repo root drives the same code)."""
    try:
        from kme_tpu.benchmarks import main as _main
    except ImportError:
        return _not_yet("the benchmark suite")
    return _main(argv)


def serve_main(argv=None) -> int:
    """Engine service speaking the reference Kafka wire contract."""
    try:
        from kme_tpu.bridge.serve import main as _main
    except ImportError:
        return _not_yet("the transport bridge")
    return _main(argv)


def consume_main(argv=None) -> int:
    """Fill-stream consumer — the consumer.js role."""
    try:
        from kme_tpu.bridge.consume import main as _main
    except ImportError:
        return _not_yet("the transport bridge")
    return _main(argv)


def provision_main(argv=None) -> int:
    """Topic provisioner — the topic.js role."""
    try:
        from kme_tpu.bridge.provision import main as _main
    except ImportError:
        return _not_yet("the transport bridge")
    return _main(argv)


def supervise_main(argv=None) -> int:
    """Failure detection + supervised restart of kme-serve."""
    try:
        from kme_tpu.bridge.supervise import main as _main
    except ImportError:
        return _not_yet("the supervisor")
    return _main(argv)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m kme_tpu.cli")
    p.add_argument("command", choices=(
        "loadgen", "oracle", "bench", "serve", "consume", "provision",
        "supervise"))
    args, rest = p.parse_known_args(argv)
    try:
        return {
            "loadgen": loadgen_main, "oracle": oracle_main,
            "bench": bench_main, "serve": serve_main,
            "consume": consume_main, "provision": provision_main,
            "supervise": supervise_main,
        }[args.command](rest)
    except BrokenPipeError:
        # downstream closed the pipe (e.g. `| head`) — the Unix-polite
        # exit; point both std streams at devnull so interpreter-shutdown
        # flushes can't re-raise on the broken descriptors
        import os

        fd = os.open(os.devnull, os.O_WRONLY)
        os.dup2(fd, sys.stdout.fileno())
        os.dup2(fd, sys.stderr.fileno())
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
