"""Engine configuration.

The reference hardcodes all of its knobs (broker address and app id in
KProcessor.java:24-29, topic names topic.js:17-21, workload shape
exchange_test.js:18-20). Here every capacity and mode is one dataclass,
used by the host runtime, the device engine, and the CLIs.
"""

from __future__ import annotations

import dataclasses


# The reference's price domain: 126 levels, 0..125, packed as two 63-bit
# halves of a UUID bitmap (KProcessor.java:391-394 splits at price < 63;
# bit 63 of the LSB long is unused — quirk Q8). We keep the same domain.
PRICE_LEVELS = 126

# Margin model (KProcessor.java:176): buys reserve `price` per unit, sells
# reserve `100 - price` per unit; PAYOUT settles at `100 - rake` per long
# contract (exchange_test.js:76-79).
SETTLE_BASE = 100


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static shape + semantics configuration for one engine instance."""

    # Capacity (static shapes — XLA compiles one program per config)
    num_symbols: int = 8          # S: symbol lanes (sharded axis)
    num_accounts: int = 64        # A: dense account capacity
    book_capacity: int = 128      # CAP: resting-order slots per book side
    steps_per_batch: int = 32     # T: lax.scan steps per device dispatch
    max_trades_per_op: int = 32   # E: fill-event buffer slots per op

    # Semantics: 'java' replicates the reference byte-for-byte including its
    # quirk ledger (SURVEY.md §2.5 Q1..Q10); 'fixed' is the corrected mode
    # (separate sid=0 books, correct crossing guard, working
    # REMOVE_SYMBOL/PAYOUT, input validation).
    compat: str = "java"

    # Parallelism: number of mesh shards over the symbol axis. 1 = single
    # device. num_symbols must be divisible by mesh_shards.
    mesh_shards: int = 1

    # Use the Pallas TPU kernel for the per-lane match/insert scan instead
    # of the pure-XLA lowering (ops/match_pallas.py).
    use_pallas: bool = False

    def __post_init__(self) -> None:
        if self.compat not in ("java", "fixed"):
            raise ValueError(f"compat must be 'java' or 'fixed', got {self.compat!r}")
        if self.num_symbols % self.mesh_shards != 0:
            raise ValueError(
                f"num_symbols={self.num_symbols} not divisible by "
                f"mesh_shards={self.mesh_shards}"
            )
        for field in ("num_symbols", "num_accounts", "book_capacity",
                      "steps_per_batch", "max_trades_per_op"):
            if getattr(self, field) <= 0:
                raise ValueError(f"{field} must be positive")

    @property
    def java_compat(self) -> bool:
        return self.compat == "java"

    @property
    def symbols_per_shard(self) -> int:
        return self.num_symbols // self.mesh_shards

    def validate_for_workload(self, num_symbols: int, num_accounts: int) -> None:
        if num_symbols > self.num_symbols:
            raise ValueError(
                f"workload uses {num_symbols} symbols, config capacity "
                f"is {self.num_symbols}")
        if num_accounts > self.num_accounts:
            raise ValueError(
                f"workload uses {num_accounts} accounts, config capacity "
                f"is {self.num_accounts}")


def round_up_pow2(n: int) -> int:
    """Smallest power of two >= n (exact integer math — float log2 rounds
    down for n just above a large power of two)."""
    return 1 << max(1, n - 1).bit_length() if n > 1 else 1
