"""Protocol opcodes — the reference's wire opcode table.

Mirrors the constants of KProcessor.MatchingEngine
(/root/reference/src/main/java/KProcessor.java:65-75). These are wire-level
values: they appear in the JSON `action` field on input and output.

The device engine uses a separate dense internal op encoding (`DevOp`)
because wire opcodes are sparse (100, 101, 200) and some ops never reach
the device (host-synthesized rejects).
"""

# Wire opcodes (KProcessor.java:65-75)
ADD_SYMBOL = 0
REMOVE_SYMBOL = 1
BUY = 2
SELL = 3
CANCEL = 4
BOUGHT = 5
SOLD = 6
REJECT = 7
CREATE_BALANCE = 100
TRANSFER = 101
PAYOUT = 200

WIRE_ACTIONS = frozenset(
    {ADD_SYMBOL, REMOVE_SYMBOL, BUY, SELL, CANCEL, CREATE_BALANCE, TRANSFER, PAYOUT}
)


class DevOp:
    """Dense device-side op encoding (int32 `action` lane field).

    NOP lanes are padding: a scheduler step rarely fills every symbol lane.
    """

    NOP = 0
    BUY = 1
    SELL = 2
    CANCEL = 3
    CREATE_BALANCE = 4
    TRANSFER = 5
    ADD_SYMBOL = 6
    REMOVE_SYMBOL = 7  # barrier
    PAYOUT = 8  # barrier

    BARRIER_OPS = (REMOVE_SYMBOL, PAYOUT)


WIRE_TO_DEV = {
    BUY: DevOp.BUY,
    SELL: DevOp.SELL,
    CANCEL: DevOp.CANCEL,
    CREATE_BALANCE: DevOp.CREATE_BALANCE,
    TRANSFER: DevOp.TRANSFER,
    ADD_SYMBOL: DevOp.ADD_SYMBOL,
    REMOVE_SYMBOL: DevOp.REMOVE_SYMBOL,
    PAYOUT: DevOp.PAYOUT,
}

DEV_TO_WIRE = {v: k for k, v in WIRE_TO_DEV.items()}
