"""Golden scalar oracle: a quirk-faithful pure-Python replica of the
reference matching-engine semantics (KProcessor.java:63-445), used as the
parity judge for the TPU engine. See oracle/engine.py."""

from kme_tpu.oracle.engine import OracleEngine, ReferenceHang  # noqa: F401
