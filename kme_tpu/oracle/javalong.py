"""Exact Java 64-bit two's-complement arithmetic for the oracle.

Python ints are unbounded; the reference's codecs do Java `long` bit
twiddling (shifts mask the count to 6 bits, overflow wraps). Every helper
here reproduces Java semantics exactly so the oracle matches the JVM
bit-for-bit even on adversarial inputs (negative prices from the workload
generator's unclamped normals, exchange_test.js:110-115).

Bit-scan note (SURVEY.md §2.5 Q7): the reference finds first/last set bits
with double-precision log10 math (KProcessor.java:371-377). IEEE-754
doubles behave identically in Java and CPython, and
tests/test_javalong.py::test_float_bitscan_equivalence proves the float
formulas agree with exact integer scans over the whole used range
(single-set-bit longs for first-bit, arbitrary non-negative longs for
last-bit). The oracle therefore uses the float formulas directly — they ARE
the reference semantics — and the device engine uses exact integer ops,
with the test as the bridge.
"""

from __future__ import annotations

import math

_MASK64 = (1 << 64) - 1
_SIGN = 1 << 63


def jlong(x: int) -> int:
    """Wrap an unbounded int to Java signed 64-bit."""
    x &= _MASK64
    return x - (1 << 64) if x & _SIGN else x


def jint(x: int) -> int:
    """Wrap to Java signed 32-bit."""
    x &= 0xFFFFFFFF
    return x - (1 << 32) if x & 0x80000000 else x


def jshl(n: int, k: int) -> int:
    """Java `n << k` on long: shift count masked to 6 bits."""
    return jlong(n << (k & 63))


def jshr(n: int, k: int) -> int:
    """Java `n >> k` (arithmetic) on long."""
    return jlong(n) >> (k & 63)


def jor(a: int, b: int) -> int:
    return jlong(jlong(a) | jlong(b))


def jand(a: int, b: int) -> int:
    return jlong(jlong(a) & jlong(b))


def jnot(a: int) -> int:
    return jlong(~jlong(a))


def jneg(a: int) -> int:
    return jlong(-jlong(a))


def jadd(a: int, b: int) -> int:
    return jlong(a + b)


def jmul(a: int, b: int) -> int:
    return jlong(a * b)


# --- bit ops exactly as KProcessor.java:406-416 ---

def get_bit(n: int, k: int) -> bool:
    """KProcessor.java:406-408: `1L == ((n >> k) & 1L)`."""
    return 1 == (jshr(n, k) & 1)


def set_bit(n: int, k: int) -> int:
    """KProcessor.java:410-412: `n | (1L << k)`."""
    return jor(n, jshl(1, k))


def unset_bit(n: int, k: int) -> int:
    """KProcessor.java:414-416: `n & ~(1L << k)`."""
    return jand(n, jnot(jshl(1, k)))


# --- float bit scans exactly as KProcessor.java:371-377 ---

def first_set_bit_pos_float(n: int) -> int:
    """KProcessor.java:371-373: `(int)((log10(n & -n)) / log10(2))`.

    Java double semantics: log10 of 0 is -inf (-inf/x = -inf, (int)-inf =
    Integer.MIN_VALUE); of negative is NaN ((int)NaN = 0).
    """
    v = jand(n, jneg(n))
    return _java_int_of_log_ratio(v)


def last_set_bit_pos_float(n: int) -> int:
    """KProcessor.java:375-377: `(int)((log10(n)) / log10(2))`."""
    return _java_int_of_log_ratio(jlong(n))


def _java_int_of_log_ratio(v: int) -> int:
    if v < 0:
        return 0  # (int) NaN == 0 in Java
    if v == 0:
        return -(1 << 31)  # (int) -Infinity == Integer.MIN_VALUE
    return int(math.log10(v) / math.log10(2.0))


def first_set_bit_pos(n: int) -> int:
    """Exact-integer equivalent of first_set_bit_pos_float for n with at
    least one set bit (proven equivalent by test_float_bitscan_equivalence)."""
    v = jand(n, jneg(n)) & _MASK64
    return v.bit_length() - 1


def last_set_bit_pos(n: int) -> int:
    """Exact-integer equivalent of last_set_bit_pos_float for n > 0."""
    return jlong(n).bit_length() - 1
