"""Golden scalar oracle — an exact behavioral replica of the reference
matching engine (KProcessor.MatchingEngine,
/root/reference/src/main/java/KProcessor.java:63-445).

This is the parity judge for the TPU engine (SURVEY.md §4, §7 step 1): a
pure-Python, one-message-at-a-time engine that reproduces the reference's
observable behavior — the "IN"/"OUT"-keyed output stream — byte for byte,
including the quirk ledger (SURVEY.md §2.5):

  Q1  sid=0 buy/sell books collide (book key is -sid; -0 == 0)
  Q2  `&&`/ternary precedence: sell takers skip the size>0 guard and
      zero-size buy takers use the sell-side crossing comparison
  Q3  removeSymbol returns inverted (False when books exist)
  Q4  removeAllOrders infinite-loops on any non-empty book (raised here
      as ReferenceHang — the JVM would spin forever, mutating balances)
  Q5/Q6  payout's return value is ignored: the OUT echo is always REJECT
  Q7  float log10 bit scans (faithfully reproduced; the max-scan
      overshoots on dense books with top bit >= 47, which makes the
      reference NPE — raised here as ReferenceCrash)
  Q9  the OUT echo leaks residual size and the intrusive `prev` pointer
  Q10 (per-record commit — a durability property, no output effect)
  Q11 positions value-as-key corruption: fillOrder's update/delete branch
      and postRemoveAdjustments' adj-write call the 2-arg
      setPosition(UUID position, ...) / positions.delete(position) where
      `position` is the VALUE UUID(amount, available)
      (KProcessor.java:283-284, 332 vs the put at :434-436) — so after the
      first fill, the real (aid,sid) entry is never updated by fills;
      updates land on garbage keys UUID(amount, available), which can
      collide with real (aid,sid) keys and are visible to payout scans.
      checkBalance's adj-write (:179) uses the 3-arg form and stays
      correct. Replicated here in java mode; fixed mode uses true keys.

compat='fixed' is the corrected semantics mode: side-tagged book keys
(no Q1 merge), correct crossing guard (no Q2 ghost trades), working
REMOVE_SYMBOL and PAYOUT with margin release (no Q3/Q4/Q5/Q6), and input
validation (price in [0,126), size > 0). PAYOUT in fixed mode follows the
harness's evident intent (exchange_test.js:76-79): positive sid = YES
resolution crediting `amount * size` per long contract, negative sid = NO
resolution deleting positions uncredited; both wipe the symbol.

Store-copy discipline: the reference's RocksDB-backed stores deserialize a
fresh object on every `get` and serialize on every `put`
(KProcessor.java:477-530) — there is no aliasing between a stored order
and a held reference. The oracle reproduces that by copying on get/put.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from kme_tpu import opcodes as op
from kme_tpu.oracle import javalong as jl
from kme_tpu.wire import OrderMsg, OutRecord


class ReferenceHang(Exception):
    """The reference would enter an infinite loop here (Q4:
    KProcessor.java:344 sets an already-set bit, so the min-price scan
    never advances, re-refunding the same bucket's margins forever)."""


class ReferenceCrash(Exception):
    """The reference would throw (NPE / serialization failure) here and
    the Streams thread would die."""


@dataclasses.dataclass
class _StoredOrder:
    """The persisted Order record (KProcessor.java:448-475)."""

    action: int
    oid: int
    aid: int
    sid: int
    price: int
    size: int
    next: Optional[int] = None
    prev: Optional[int] = None

    def copy(self) -> "_StoredOrder":
        return dataclasses.replace(self)


def _book_min_price(book: Tuple[int, int]) -> int:
    """getMinPriceBucketPointer (KProcessor.java:359-363). book=(msb,lsb)."""
    msb, lsb = book
    if lsb == 0 and msb == 0:
        return -1
    if lsb == 0:
        return jl.first_set_bit_pos_float(msb) + 63
    return jl.first_set_bit_pos_float(lsb)


def _book_max_price(book: Tuple[int, int]) -> int:
    """getMaxPriceBucketPointer (KProcessor.java:365-369)."""
    msb, lsb = book
    if msb == 0 and lsb == 0:
        return -1
    if msb == 0:
        return jl.last_set_bit_pos_float(lsb)
    return jl.last_set_bit_pos_float(msb) + 63


def _check_bit(book: Tuple[int, int], price: int) -> bool:
    """checkBit (KProcessor.java:391-394): LSB long carries prices < 63,
    MSB carries the rest at offset price-63 (Q8: bit 63 of LSB unused)."""
    msb, lsb = book
    if price < 63:
        return jl.get_bit(lsb, price)
    return jl.get_bit(msb, price - 63)


def _with_bit_set(book: Tuple[int, int], price: int) -> Tuple[int, int]:
    """getWithBitSet (KProcessor.java:396-399)."""
    msb, lsb = book
    if price < 63:
        return (msb, jl.set_bit(lsb, price))
    return (jl.set_bit(msb, price - 63), lsb)


def _with_bit_unset(book: Tuple[int, int], price: int) -> Tuple[int, int]:
    """getWithBitUnset (KProcessor.java:401-404)."""
    msb, lsb = book
    if price < 63:
        return (msb, jl.unset_bit(lsb, price))
    return (jl.unset_bit(msb, price - 63), lsb)


class OracleEngine:
    """process() one wire message at a time, returning the forwarded
    records in forward order: IN echo, fill events, OUT echo
    (KProcessor.java:97, 272-273, 124)."""

    def __init__(self, compat: str = "java",
                 book_slots: Optional[int] = None,
                 max_fills: Optional[int] = None) -> None:
        """book_slots / max_fills: the CAPACITY ENVELOPE mirroring the
        lane engine's static shapes (engine/lanes.py LaneConfig slots /
        max_fills). When set (fixed mode only), a BUY/SELL that would
        rest beyond `book_slots` resting orders on its (sid, side) or
        sweep more than `max_fills` makers is rejected as a unit — no
        fills, no state change, OUT REJECT — exactly the device engine's
        per-message H2/H3 overflow policy. None = unbounded (the
        reference's own linked lists are unbounded)."""
        if compat not in ("java", "fixed"):
            raise ValueError(compat)
        self.java = compat == "java"
        if self.java and (book_slots is not None or max_fills is not None):
            raise ValueError("capacity envelope is a fixed-mode concept")
        self.book_slots = book_slots
        self.max_fills = max_fills
        # The five stores (KProcessor.java:30-49). Book/bucket keys follow
        # the reference's signed-sid codec in java mode; fixed mode uses
        # explicit side-tagged keys (2*sid + side), removing Q1.
        self.balances: Dict[int, int] = {}
        self.positions: Dict[Tuple[int, int], Tuple[int, int]] = {}
        self.orders: Dict[int, _StoredOrder] = {}
        self.books: Dict[int, Tuple[int, int]] = {}
        self.buckets: Dict[int, Tuple[int, int]] = {}
        self._out: List[OutRecord] = []

    # ------------------------------------------------------------------
    # key codecs

    def _order_book_key(self, sid: int, is_buy: bool) -> int:
        """Book key for an order path. Java: signed sid — `sid * (action ==
        BUY ? 1 : -1)` (KProcessor.java:201, 227, 292), merging both sides
        of sid=0 (Q1). Fixed: 2*sid + side, always disjoint."""
        if self.java:
            return jl.jmul(sid, 1 if is_buy else -1)
        return jl.jlong(2 * sid + (0 if is_buy else 1))

    def _bucket_key(self, book_key: int, price: int) -> int:
        """getBucketPointer (KProcessor.java:379-381): (key << 8) | price
        with Java promotion — a negative price sign-extends and floods the
        high bits. Fixed mode: price is validated to [0,126) so plain
        base-256 packing is exact."""
        if self.java:
            return jl.jor(jl.jshl(book_key, 8), jl.jlong(price))
        return jl.jlong(book_key * 256 + price)

    # ------------------------------------------------------------------
    # public entry

    def process(self, msg: OrderMsg) -> List[OutRecord]:
        """Replicates MatchingEngine.process (KProcessor.java:95-126),
        optionally under the capacity envelope (see __init__)."""
        envelope = (self.book_slots is not None or self.max_fills is not None)
        if envelope and msg.action in (op.BUY, op.SELL):
            return self._process_enveloped(msg)
        return self._process_inner(msg)

    def _process_enveloped(self, msg: OrderMsg) -> List[OutRecord]:
        """Run a trade message, then roll the whole message back into an
        OUT REJECT if it violated the capacity envelope. Store values are
        immutable (tuples / copied records), so shallow dict snapshots
        are exact."""
        orig = msg.copy()
        snap = (dict(self.balances), dict(self.positions), dict(self.orders),
                dict(self.books), dict(self.buckets))
        out = self._process_inner(msg)
        violated = False
        if self.max_fills is not None:
            # OUT records = 2 per executed trade + 1 result echo
            ntrades = (sum(1 for r in out if r.key == "OUT") - 1) // 2
            violated = ntrades > self.max_fills
        if not violated and self.book_slots is not None:
            rested = self.orders.get(orig.oid)
            if rested is not None and rested.sid == orig.sid \
                    and rested.action == orig.action:
                n_side = sum(1 for r in self.orders.values()
                             if r.sid == orig.sid and r.action == orig.action)
                violated = n_side > self.book_slots
        if not violated:
            return out
        (self.balances, self.positions, self.orders,
         self.books, self.buckets) = snap
        rej = orig.copy()
        rej.action = op.REJECT
        return [OutRecord("IN", orig.copy()), OutRecord("OUT", rej)]

    def _process_inner(self, msg: OrderMsg) -> List[OutRecord]:
        order = msg.copy()
        self._out = [OutRecord("IN", order.copy())]
        result = False
        a = order.action
        if a == op.ADD_SYMBOL:
            result = self._add_symbol(order.sid)
        elif a == op.REMOVE_SYMBOL:
            result = self._remove_symbol(order.sid)
        elif a in (op.BUY, op.SELL):
            result = self._add_order(order)
        elif a == op.CANCEL:
            result = self._remove_order(order.oid, order.aid)
        elif a == op.PAYOUT:
            r = self._payout(order)
            # Q5/Q6: the return value is discarded (KProcessor.java:113-115)
            if not self.java:
                result = r
        elif a == op.CREATE_BALANCE:
            result = self._create_balance(order)
        elif a == op.TRANSFER:
            result = self._transfer(order)
        # unknown action: no handler, result stays False -> REJECT
        if not result:
            order.action = op.REJECT
        self._out.append(OutRecord("OUT", order.copy()))
        return self._out

    # ------------------------------------------------------------------
    # account ledger (KProcessor.java:131-146)

    def _create_balance(self, order: OrderMsg) -> bool:
        """createBalance (KProcessor.java:131-138): idempotent create at 0."""
        if order.aid not in self.balances:
            self.balances[order.aid] = 0
            return True
        return False

    def _transfer(self, order: OrderMsg) -> bool:
        """transfer (KProcessor.java:140-146): deposit/withdraw guarded by
        `balance < -size`."""
        bal = self.balances.get(order.aid)
        # `-order.size` is Java int negation: wraps at int32 (stays INT_MIN
        # for size = INT_MIN) before promotion to long for the comparison
        if bal is None or bal < jl.jint(-order.size):
            return False
        self.balances[order.aid] = jl.jadd(bal, order.size)
        return True

    # ------------------------------------------------------------------
    # symbol lifecycle (KProcessor.java:184-198, 335-357)

    def _add_symbol(self, sid: int) -> bool:
        """addSymbol (KProcessor.java:184-191): empty buy book at sid and
        sell book at -sid (merged for sid=0 in java compat — Q1)."""
        if self.java:
            if jl.jlong(sid) in self.books:
                return False
            self.books[jl.jlong(sid)] = (0, 0)
            self.books[jl.jneg(sid)] = (0, 0)
            return True
        if sid < 0 or jl.jlong(2 * sid) in self.books:
            return False
        self.books[jl.jlong(2 * sid)] = (0, 0)
        self.books[jl.jlong(2 * sid + 1)] = (0, 0)
        return True

    def _remove_symbol(self, sid: int) -> bool:
        """removeSymbol (KProcessor.java:193-198). Java compat: inverted
        return (Q3) and the Q4 hang for non-empty books. Fixed: wipe both
        sides with margin refunds, delete the books, True on success."""
        if self.java:
            if self._remove_all_orders_java(jl.jlong(sid)) or self._remove_all_orders_java(
                jl.jneg(sid)
            ):
                return False
            self.books.pop(jl.jlong(sid), None)
            self.books.pop(jl.jneg(sid), None)
            return True
        s = abs(sid)
        k_buy, k_sell = jl.jlong(2 * s), jl.jlong(2 * s + 1)
        if k_buy not in self.books:
            return False
        self._wipe_book_fixed(k_buy)
        self._wipe_book_fixed(k_sell)
        del self.books[k_buy]
        del self.books[k_sell]
        return True

    def _remove_all_orders_java(self, book_key: int) -> bool:
        """removeAllOrders (KProcessor.java:335-357), java semantics: Q4 —
        the loop calls getWithBitSet where getWithBitUnset is needed, so a
        non-empty book never terminates. Only an empty or absent book
        returns; we raise ReferenceHang for the divergent path."""
        book = self.books.get(book_key)
        if book is None:
            return False
        if _book_min_price(book) != -1:
            raise ReferenceHang(
                f"removeAllOrders(key={book_key}) on a non-empty book: the "
                "reference loops forever re-refunding the min-price bucket "
                "(KProcessor.java:341-353 with the Q4 set-instead-of-unset bug)")
        return True

    def _wipe_book_fixed(self, book_key: int) -> None:
        """Fixed-mode book wipe: release margin for every resting order on
        this side (what removeAllOrders was meant to do)."""
        book = self.books.get(book_key)
        if book is None:
            return
        price = _book_min_price(book)
        while price != -1:
            bucket_key = self._bucket_key(book_key, price)
            bucket = self.buckets.pop(bucket_key, None)
            if bucket is None:
                raise ReferenceCrash("NPE: bitmap bit set but bucket missing")
            ptr: Optional[int] = bucket[0]
            while ptr is not None:
                rec = self.orders.pop(ptr, None)
                if rec is None:
                    raise ReferenceCrash("NPE: linked order missing in wipe")
                self._post_remove_adjustments(rec)
                ptr = rec.next
            book = _with_bit_unset(book, price)
            price = _book_min_price(book)
        self.books[book_key] = book

    # ------------------------------------------------------------------
    # settlement (KProcessor.java:148-165)

    def _payout(self, order: OrderMsg) -> bool:
        """payout (KProcessor.java:148-165): remove the symbol, then credit
        `amount * order.size` per matching position and delete it. In java
        compat, removeSymbol's inversion (Q3) means this only proceeds for
        symbols whose books don't exist. Fixed mode: sid >= 0 = YES
        resolution (credit longs `amount * size`), sid < 0 = NO resolution
        (positions deleted uncredited)."""
        if not self._remove_symbol(order.sid):
            return False
        match_sid = jl.jlong(order.sid) if self.java else jl.jlong(abs(order.sid))
        credit = self.java or order.sid >= 0
        to_remove = []
        for key, val in self.positions.items():
            k_aid, k_sid = key
            if jl.jlong(k_sid) == match_sid:
                if credit:
                    amount, _avail = val
                    bal = self.balances.get(k_aid)
                    if bal is None:
                        raise ReferenceCrash(
                            "NPE: payout credits account with no balance")
                    self.balances[k_aid] = jl.jadd(bal, jl.jmul(amount, order.size))
                to_remove.append(key)
        for key in to_remove:
            del self.positions[key]
        return True

    # ------------------------------------------------------------------
    # risk / margin engine (KProcessor.java:167-182, 325-333)

    def _check_balance(self, order: OrderMsg) -> bool:
        """checkBalance (KProcessor.java:167-182): margin reservation with
        netting against the opposite 'available' position. Buys reserve
        `price` per unit, sells reserve `price - 100` (i.e. debit
        `100 - price`); `adj` nets the new exposure against available
        opposite holdings so closing trades need no fresh margin."""
        aid = order.aid
        bal = self.balances.get(aid)
        if bal is None:
            return False
        is_buy = order.action == op.BUY
        size = jl.jint(order.size * (1 if is_buy else -1))
        pos = self.positions.get((aid, order.sid))
        available = pos[1] if pos is not None else 0
        # `-size` is Java int negation (wraps for INT_MIN) promoted to long
        neg_size = jl.jint(-size)
        if is_buy:
            adj = max(min(available, 0), neg_size)
        else:
            adj = min(max(available, 0), neg_size)
        # the margin unit `price - 100` is computed in 32-bit int before
        # promotion to long for the multiply (KProcessor.java:176)
        risk = jl.jmul(jl.jadd(size, adj),
                       jl.jint(order.price) if is_buy else jl.jint(order.price - 100))
        if bal < risk:
            return False
        self.balances[aid] = jl.jadd(bal, -risk)
        if adj != 0:
            # adj != 0 with no position is reachable for negative sizes
            # (available=0, -size > 0): the JVM NPEs at
            # getPositionAmount(null) (KProcessor.java:179-180) AFTER the
            # balance debit above persisted
            if pos is None:
                raise ReferenceCrash(
                    "NPE: checkBalance adj-write with no position")
            self.positions[(aid, order.sid)] = (pos[0], jl.jadd(available, -adj))
        return True

    def _post_remove_adjustments(self, rec: _StoredOrder) -> None:
        """postRemoveAdjustments (KProcessor.java:325-333): mirror of
        checkBalance — release the reserved margin, re-blocking any netted
        position 'available'. Java compat replicates Q11: the adj-write
        targets the VALUE UUID as key (KProcessor.java:332)."""
        is_buy = rec.action == op.BUY
        size = jl.jint(rec.size * (1 if is_buy else -1))
        pos = self.positions.get((rec.aid, rec.sid))
        blocked = (pos[0] - pos[1]) if pos is not None else 0
        neg_size = jl.jint(-size)  # Java int negation, as in checkBalance
        if is_buy:
            adj = max(min(blocked, 0), neg_size)
        else:
            adj = min(max(blocked, 0), neg_size)
        bal = self.balances.get(rec.aid)
        if bal is None:
            raise ReferenceCrash("NPE: margin release for account with no balance")
        self.balances[rec.aid] = jl.jadd(
            bal, jl.jmul(jl.jadd(size, adj),
                         jl.jint(rec.price) if is_buy else jl.jint(rec.price - 100)))
        if adj != 0:
            # same NPE shape as checkBalance: adj != 0 with pos None
            # (negative-size rec) dies at getPositionAmount(null)
            # (KProcessor.java:332) after the balance credit persisted
            if pos is None:
                raise ReferenceCrash(
                    "NPE: postRemoveAdjustments adj-write with no position")
            target = pos if self.java else (rec.aid, rec.sid)  # Q11
            self.positions[target] = (pos[0], jl.jadd(pos[1], adj))

    # ------------------------------------------------------------------
    # order entry (KProcessor.java:200-223)

    def _add_order(self, order: OrderMsg) -> bool:
        """addOrder (KProcessor.java:200-223): book existence -> margin
        check -> match; any unfilled remainder rests FIFO at its price
        bucket (new bucket + bitmap bit, or append to the list tail —
        mutating the echoed order's `prev`, Q9)."""
        if not self.java:
            # fixed-mode validation: the reference accepts any int price /
            # size, producing the Q2/Q7 pathologies; we bound the domain.
            if not (0 <= order.price < 126) or order.size <= 0:
                return False
        is_buy = order.action == op.BUY
        bkey = self._order_book_key(order.sid, is_buy)
        book = self.books.get(bkey)
        if book is None or not self._check_balance(order):
            return False
        if self._try_match(order):
            return True
        book = self.books[bkey]
        oid, price = order.oid, order.price
        bucket_key = self._bucket_key(bkey, price)
        if not _check_bit(book, price):
            self.buckets[bucket_key] = (oid, oid)
            self.books[bkey] = _with_bit_set(book, price)
        else:
            bucket = self.buckets.get(bucket_key)
            if bucket is None:
                raise ReferenceCrash("NPE: bitmap bit set but bucket missing")
            first_ptr, last_ptr = bucket
            curr_last = self.orders.get(last_ptr)
            if curr_last is None:
                raise ReferenceCrash("NPE: bucket tail order missing")
            curr_last = curr_last.copy()
            curr_last.next = oid
            order.prev = curr_last.oid
            self.orders[last_ptr] = curr_last
            self.buckets[bucket_key] = (first_ptr, oid)
        self.orders[oid] = _StoredOrder(
            order.action, order.oid, order.aid, order.sid,
            order.price, order.size, order.next, order.prev)
        return True

    # ------------------------------------------------------------------
    # matcher hot loop (KProcessor.java:225-263)

    def _try_match(self, taker: OrderMsg) -> bool:
        """tryMatch (KProcessor.java:225-263) — the hot crossing loop.

        Walks the best opposite price bucket's FIFO list, trading
        min(sizes) at the maker's price. Faithful to Q2 in java mode: the
        while guard parses as
        `(size > 0 && takerIsBuy) ? (maker <= p) : (maker >= p)`, so sell
        takers skip the size guard (one extra zero-size trade after a full
        fill when the next maker still crosses) and zero-size buy takers
        evaluate the sell-side comparison."""
        taker_is_buy = taker.action == op.BUY
        limit = taker.price
        opp_key = self._order_book_key(taker.sid, not taker_is_buy)
        bitmap = self.books.get(opp_key)
        if bitmap is None:
            raise ReferenceCrash("NPE: opposite book missing in tryMatch")
        price_bit = _book_min_price(bitmap) if taker_is_buy else _book_max_price(bitmap)
        if price_bit == -1:
            return False
        bucket_key = self._bucket_key(opp_key, price_bit)
        bucket = self.buckets.get(bucket_key)
        if bucket is None:
            raise ReferenceCrash(
                "NPE: best-price bucket missing (Q7 float max-scan overshoot)")
        maker_ptr = bucket[0]
        maker = self.orders.get(maker_ptr)
        if maker is None:
            raise ReferenceCrash("NPE: bucket head order missing")
        maker = maker.copy()
        while self._cross_guard(taker, maker, taker_is_buy, limit):
            trade_size = min(taker.size, maker.size)
            maker.size = jl.jint(maker.size - trade_size)
            taker.size = jl.jint(taker.size - trade_size)
            self._execute_trade(taker, maker, trade_size, taker_is_buy)
            if maker.size != 0:
                break
            # store.delete is a no-op on missing keys (RocksDB semantics,
            # KProcessor.java:243,245) — hence pop(..., None), not del
            self.orders.pop(maker.oid, None)
            if maker.next is None:
                self.buckets.pop(bucket_key, None)
                bitmap = _with_bit_unset(bitmap, maker.price)
                self.books[opp_key] = bitmap
                price_bit = (
                    _book_min_price(bitmap) if taker_is_buy else _book_max_price(bitmap)
                )
                if price_bit == -1:
                    return taker.size == 0
                bucket_key = self._bucket_key(opp_key, price_bit)
                bucket = self.buckets.get(bucket_key)
                if bucket is None:
                    raise ReferenceCrash(
                        "NPE: best-price bucket missing (Q7 overshoot)")
                maker_ptr = bucket[0]
            else:
                maker_ptr = maker.next
            maker = self.orders.get(maker_ptr)
            if maker is None:
                raise ReferenceCrash("NPE: next maker order missing")
            maker = maker.copy()
        # Post-loop bucket-head writeback (KProcessor.java:259-261): also
        # reached with no trade done, harmlessly rewriting identical state.
        self.buckets[bucket_key] = (maker_ptr, bucket[1])
        maker.prev = None
        self.orders[maker_ptr] = maker
        return taker.size == 0

    def _cross_guard(
        self, taker: OrderMsg, maker: _StoredOrder, taker_is_buy: bool, limit: int
    ) -> bool:
        """The while condition of KProcessor.java:237. Java compat keeps
        the Q2 precedence bug verbatim; fixed mode applies the intended
        `size > 0 && (crossing)` guard."""
        if self.java:
            if taker.size > 0 and taker_is_buy:
                return maker.price <= limit
            return maker.price >= limit
        if taker.size <= 0:
            return False
        return maker.price <= limit if taker_is_buy else maker.price >= limit

    # ------------------------------------------------------------------
    # trade execution / settlement (KProcessor.java:265-287)

    def _execute_trade(
        self, taker: OrderMsg, maker: _StoredOrder, trade_size: int, taker_is_buy: bool
    ) -> None:
        """executeTrade (KProcessor.java:265-274): maker fill at price 0,
        taker fill at the price improvement; maker event forwarded first."""
        maker_fill = OrderMsg(
            op.SOLD if taker_is_buy else op.BOUGHT,
            maker.oid, maker.aid, maker.sid, 0, trade_size)
        taker_fill = OrderMsg(
            op.BOUGHT if taker_is_buy else op.SOLD,
            taker.oid, taker.aid, taker.sid,
            jl.jint(taker.price - maker.price), trade_size)
        self._fill_order(maker_fill)
        self._fill_order(taker_fill)
        self._out.append(OutRecord("OUT", maker_fill))
        self._out.append(OutRecord("OUT", taker_fill))

    def _fill_order(self, fill: OrderMsg) -> None:
        """fillOrder (KProcessor.java:276-287): apply signed size to the
        (aid, sid) position — note delete-at-zero discards `available` —
        and credit `size * price` to the balance.

        Java compat replicates Q11: the else branch's delete/update target
        the VALUE UUID as the store key (KProcessor.java:283-284), so the
        real (aid, sid) entry keeps its first-fill value forever and the
        update lands on a garbage key (amount, available) — which may
        collide with a real (aid, sid) pair."""
        size = jl.jint(fill.size * (1 if fill.action == op.BOUGHT else -1))
        key = (fill.aid, fill.sid)
        pos = self.positions.get(key)
        if pos is None:
            self.positions[key] = (size, size)
        else:
            amount, avail = pos
            new_amount = jl.jadd(amount, size)
            target = pos if self.java else key  # Q11
            if new_amount == 0:
                self.positions.pop(target, None)
            else:
                self.positions[target] = (new_amount, jl.jadd(avail, size))
        bal = self.balances.get(fill.aid)
        if bal is None:
            raise ReferenceCrash("NPE: fill credits account with no balance")
        # `size * order.price` is int*int — wraps at int32 BEFORE the long
        # promotion of the balance add (KProcessor.java:286)
        self.balances[fill.aid] = jl.jadd(bal, jl.jint(size * fill.price))

    # ------------------------------------------------------------------
    # cancel path (KProcessor.java:289-323)

    def _remove_order(self, oid: int, aid: int) -> bool:
        """removeOrder (KProcessor.java:289-323): ownership check, 4-case
        doubly-linked unlink, then margin release."""
        rec = self.orders.get(oid)
        if rec is None or rec.aid != aid:
            return False
        rec = rec.copy()
        is_buy = rec.action == op.BUY
        bkey = self._order_book_key(rec.sid, is_buy)
        price = rec.price
        book = self.books.get(bkey)
        bucket_key = self._bucket_key(bkey, price)
        bucket = self.buckets.get(bucket_key)
        prev_ptr, next_ptr = rec.prev, rec.next
        if prev_ptr is None and next_ptr is None:
            if book is None:
                raise ReferenceCrash("NPE: book missing in removeOrder")
            self.buckets.pop(bucket_key, None)  # store.delete: no-op if absent
            self.books[bkey] = _with_bit_unset(book, price)
        elif prev_ptr is None:
            self.buckets[bucket_key] = (next_ptr, bucket[1])
            nxt = self.orders[next_ptr].copy()
            nxt.prev = None
            self.orders[next_ptr] = nxt
        elif next_ptr is None:
            self.buckets[bucket_key] = (bucket[0], prev_ptr)
            prv = self.orders[prev_ptr].copy()
            prv.next = None
            self.orders[prev_ptr] = prv
        else:
            prv = self.orders[prev_ptr].copy()
            nxt = self.orders[next_ptr].copy()
            prv.next = next_ptr
            nxt.prev = prev_ptr
            self.orders[prev_ptr] = prv
            self.orders[next_ptr] = nxt
        self.orders.pop(oid, None)  # store.delete: no-op if absent
        self._post_remove_adjustments(rec)
        return True

    # ------------------------------------------------------------------
    # state export / adoption (fixed mode): the shared audit/xray shape

    def export_state(self) -> dict:
        """The cross-engine state shape the auditor checks against and
        the seq/lane sessions export (seqsession._canon_to_export):
        balances, position tuples, resting orders with an `is_buy` tag,
        and the existing-symbol set. Fixed mode only — java-mode keys
        (signed sids, Q11 garbage position keys) have no canonical
        projection."""
        if self.java:
            raise ValueError("export_state is a fixed-mode projection")
        return {
            "balances": dict(self.balances),
            "positions": dict(self.positions),
            "orders": {oid: {"aid": r.aid, "sid": r.sid,
                             "price": r.price, "size": r.size,
                             "is_buy": r.action == op.BUY}
                       for oid, r in self.orders.items()},
            "books": {k // 2: True for k in self.books if k % 2 == 0},
        }

    @classmethod
    def from_export(cls, state: dict,
                    book_slots: Optional[int] = None,
                    max_fills: Optional[int] = None) -> "OracleEngine":
        """Adopt an exported state dict (fixed mode): rebuild the book
        bitmaps, price buckets and FIFO linked lists from the flat
        resting-order set. FIFO order within a price bucket is restored
        by ascending oid — exact for monotonically-minted oid streams
        (every workload generator here), and exactly what audit.py's
        seed() assumes for the same export."""
        eng = cls("fixed", book_slots=book_slots, max_fills=max_fills)
        eng.balances = {int(a): int(v)
                        for a, v in state.get("balances", {}).items()}
        eng.positions = {(int(a), int(s)): (int(amt), int(av))
                         for (a, s), (amt, av)
                         in state.get("positions", {}).items()}
        for sid in state.get("books", {}):
            eng._add_symbol(int(sid))
        for oid in sorted(state.get("orders", {})):
            o = state["orders"][oid]
            is_buy = bool(o["is_buy"])
            sid = int(o["sid"])
            bkey = eng._order_book_key(sid, is_buy)
            if bkey not in eng.books:    # resting order implies books
                eng.books[jl.jlong(2 * sid)] = (0, 0)
                eng.books[jl.jlong(2 * sid + 1)] = (0, 0)
            price = int(o["price"])
            bucket_key = eng._bucket_key(bkey, price)
            rec = _StoredOrder(op.BUY if is_buy else op.SELL, int(oid),
                               int(o["aid"]), sid, price, int(o["size"]))
            book = eng.books[bkey]
            if not _check_bit(book, price):
                eng.buckets[bucket_key] = (rec.oid, rec.oid)
                eng.books[bkey] = _with_bit_set(book, price)
            else:
                first_ptr, last_ptr = eng.buckets[bucket_key]
                tail = eng.orders[last_ptr].copy()
                tail.next = rec.oid
                rec.prev = tail.oid
                eng.orders[last_ptr] = tail
                eng.buckets[bucket_key] = (first_ptr, rec.oid)
            eng.orders[rec.oid] = rec
        return eng

    def book_levels(self, sid: int) -> dict:
        """Read-only ladder view of one symbol (fixed mode): per-side
        [(price, [(oid, aid, size), ...FIFO...])], best-first."""
        if self.java:
            raise ValueError("book_levels is a fixed-mode view")
        out: dict = {"sid": int(sid), "exists": False,
                     "buys": [], "sells": []}
        for side_name, side in (("buys", 0), ("sells", 1)):
            bkey = jl.jlong(2 * sid + side)
            book = self.books.get(bkey)
            if book is None:
                continue
            out["exists"] = True
            levels = []
            for price in range(126):
                if not _check_bit(book, price):
                    continue
                bucket = self.buckets.get(self._bucket_key(bkey, price))
                if bucket is None:
                    continue
                rows, ptr = [], bucket[0]
                while ptr is not None:
                    rec = self.orders[ptr]
                    rows.append((rec.oid, rec.aid, rec.size))
                    ptr = rec.next
                levels.append((price, rows))
            # best-first: highest bid, lowest ask
            out[side_name] = (list(reversed(levels)) if side == 0
                              else levels)
        return out
