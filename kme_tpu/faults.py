"""Process-wide, seed-deterministic fault-injection registry (kme-chaos).

The reference inherits its fault story from Kafka Streams (partition
reassignment + changelog restore); our replacement is the
kme-supervise -> checkpoint/resume -> journal/audit stack. This module
is the thing that ATTACKS that stack on purpose: named injection points
threaded through the broker/TCP transport, checkpoint save, the journal
writer and the serve loop fire faults according to a declarative,
seeded schedule, so a chaos run (bridge/chaos.py) is exactly
reproducible from its spec string.

Activation: set ``KME_FAULTS`` to a spec, e.g.

    KME_FAULTS="seed=42;broker.fetch:n=2;ckpt.torn:n=1:after=1;serve.kill:at=180"

Spec grammar — ';'-separated clauses. ``seed=N`` seeds every rule's RNG
(default 0). Every other clause is ``<point>[:key=value]...`` with

    p=F      fire probability per eligible hit (default 1.0)
    n=K      max fires for this rule (default 1; 0 = unlimited)
    after=K  skip the first K eligible hits (per process)
    at=N     offset gate: fire only once the call-site offset >= N
             (kill/stall points pass the service input offset)
    frac=F   for *.torn points: keep this fraction of the file
             (default 0.5)
    ms=K     magnitude in milliseconds for the net.*/clock.* points
             (partition window, added delivery delay, wall skew;
             default 50)

Known injection points (the call sites document themselves; grep for
``faults.``):

    broker.produce   InProcessBroker.produce raises BrokerError
    broker.fetch     InProcessBroker.fetch raises BrokerError
    tcp.partial      TCP handler writes half a reply, then drops the
                     connection (client sees a poisoned stream)
    tcp.disconnect   TCP handler drops the connection without replying
    ckpt.torn        truncate the just-renamed snapshot file
    ckpt.bitflip     flip one deterministic bit in the snapshot file
    journal.torn     write half a journal record, fsync, SIGKILL self
                     (a crash mid-journal-append)
    serve.kill       SIGKILL the serve process at an input offset
    serve.stuck      freeze the serve loop (tick stops, heartbeat
                     thread lives) at an input offset
    lease.steal      split-brain drill: another incarnation steals the
                     leader lease (next epoch + broker fence) right
                     before a checkpoint — the current leader must
                     detect it and die fenced, never write
    standby.lag      stall the hot-standby follower mid-tail (the
                     promotion path must absorb the catch-up)
    net.partition    sim transport: sever the front->group link for
                     `ms` virtual milliseconds (deliveries queue FIFO
                     and flush on heal — never drop)
    net.delay        sim transport: add `ms` virtual milliseconds to
                     one delivery (the whole link shifts behind it;
                     per-link FIFO order is preserved, like TCP)
    net.reorder      sim transport: re-send an EARLIER stamped record
                     after newer ones (an out-of-order duplicate
                     produce — the broker's idempotence watermark must
                     swallow it)
    clock.skew       sim: step one actor's wall clock by `ms` (stamps
                     shift; monotonic intervals don't, like NTP)

Cross-process accounting: under a supervisor, a restarted child re-reads
the same KME_FAULTS — an ``n``-limited rule must not refire every
incarnation. Set ``KME_FAULTS_STATE`` to a directory and each rule
persists its fire count there (one small file per rule), making ``n``
global across restarts. ``bridge/chaos.py`` always sets it.

No kme_tpu imports here (call sites raise their own exception types);
when KME_FAULTS is unset every ``should()`` is a cheap None check.
"""

from __future__ import annotations

import os
import random
import signal
import sys
import threading
from typing import List, Optional

ENV_SPEC = "KME_FAULTS"
ENV_STATE = "KME_FAULTS_STATE"

_POINTS = ("broker.produce", "broker.fetch", "tcp.partial",
           "tcp.disconnect", "ckpt.torn", "ckpt.bitflip", "journal.torn",
           "serve.kill", "serve.stuck", "lease.steal", "standby.lag",
           "net.partition", "net.delay", "net.reorder", "clock.skew")


class FaultSpecError(ValueError):
    """Malformed KME_FAULTS spec (surfaced loudly, never ignored)."""


class Rule:
    __slots__ = ("idx", "point", "p", "n", "after", "at", "frac", "ms",
                 "hits", "fires", "rng")

    def __init__(self, idx: int, point: str, seed: int, p: float = 1.0,
                 n: int = 1, after: int = 0, at: Optional[int] = None,
                 frac: float = 0.5, ms: int = 50) -> None:
        self.idx = idx
        self.point = point
        self.p = p
        self.n = n
        self.after = after
        self.at = at
        self.frac = frac
        self.ms = ms
        self.hits = 0           # eligible call-site visits (per process)
        self.fires = 0          # fires (per process)
        # one independent deterministic stream per rule: stable across
        # restarts and insensitive to other rules' draw order
        self.rng = random.Random((seed, idx, point).__repr__())

    def describe(self) -> str:
        bits = [self.point]
        if self.p < 1.0:
            bits.append(f"p={self.p}")
        bits.append(f"n={self.n}")
        if self.after:
            bits.append(f"after={self.after}")
        if self.at is not None:
            bits.append(f"at={self.at}")
        if self.ms != 50:
            bits.append(f"ms={self.ms}")
        return ":".join(bits)


class FaultPlan:
    """A parsed spec + its per-rule state (see module docstring)."""

    def __init__(self, spec: str, state_dir: Optional[str] = None) -> None:
        self.spec = spec
        self.state_dir = state_dir
        self.seed = 0
        self.rules: List[Rule] = []
        self._lock = threading.Lock()
        clauses = [c.strip() for c in spec.split(";") if c.strip()]
        pending = []
        for clause in clauses:
            if clause.startswith("seed="):
                self.seed = int(clause[5:])
                continue
            fields = clause.split(":")
            point, kv = fields[0], fields[1:]
            if point not in _POINTS:
                raise FaultSpecError(
                    f"unknown fault point {point!r} (known: "
                    f"{', '.join(_POINTS)})")
            kwargs = {}
            for f in kv:
                k, sep, v = f.partition("=")
                if not sep:
                    raise FaultSpecError(f"bad fault field {f!r} in "
                                         f"{clause!r} (want key=value)")
                if k in ("n", "after", "at", "ms"):
                    kwargs[k] = int(v)
                elif k in ("p", "frac"):
                    kwargs[k] = float(v)
                else:
                    raise FaultSpecError(
                        f"unknown fault field {k!r} in {clause!r}")
            pending.append((point, kwargs))
        for idx, (point, kwargs) in enumerate(pending):
            self.rules.append(Rule(idx, point, self.seed, **kwargs))
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)

    # -- cross-process fire accounting ---------------------------------

    def _state_path(self, rule: Rule) -> str:
        return os.path.join(self.state_dir,
                            f"rule{rule.idx}.{rule.point}.fired")

    def _persisted_fires(self, rule: Rule) -> int:
        if not self.state_dir:
            return 0
        try:
            with open(self._state_path(rule)) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def _record_fire(self, rule: Rule, total: int) -> None:
        if not self.state_dir:
            return
        tmp = self._state_path(rule) + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(total))
        os.replace(tmp, self._state_path(rule))

    # -- the decision --------------------------------------------------

    def fire(self, point: str, offset: Optional[int] = None
             ) -> Optional[Rule]:
        """Decide whether `point` fires at this call site. Returns the
        rule that fired (for torn/bitflip parameters) or None."""
        with self._lock:
            for rule in self.rules:
                if rule.point != point:
                    continue
                if rule.at is not None and (offset is None
                                            or offset < rule.at):
                    continue
                rule.hits += 1
                if rule.hits <= rule.after:
                    continue
                # persisted count wins under a state dir (cross-process
                # n); the in-process count otherwise
                total = (self._persisted_fires(rule) if self.state_dir
                         else rule.fires)
                if rule.n > 0 and total >= rule.n:
                    continue
                if rule.p < 1.0 and rule.rng.random() >= rule.p:
                    continue
                rule.fires += 1
                if self.state_dir:
                    self._record_fire(rule, total + 1)
                print(f"kme-faults: injected {point} "
                      f"(rule {rule.idx}, fire {total + 1})",
                      file=sys.stderr)
                return rule
        return None

    def fired_total(self) -> int:
        """Fires observed by THIS process (telemetry gauge)."""
        with self._lock:
            return sum(r.fires for r in self.rules)


# ---------------------------------------------------------------------------
# module-level plan (lazily loaded from the environment)

_plan: Optional[FaultPlan] = None
_loaded = False
_load_lock = threading.Lock()


def _get_plan() -> Optional[FaultPlan]:
    global _plan, _loaded
    if not _loaded:
        with _load_lock:
            if not _loaded:
                spec = os.environ.get(ENV_SPEC)
                if spec:
                    _plan = FaultPlan(spec, os.environ.get(ENV_STATE))
                _loaded = True
    return _plan


def configure(spec: Optional[str],
              state_dir: Optional[str] = None) -> Optional[FaultPlan]:
    """Install a plan explicitly (tests / embedding); None clears it."""
    global _plan, _loaded
    with _load_lock:
        _plan = FaultPlan(spec, state_dir) if spec else None
        _loaded = True
    return _plan


def clear() -> None:
    """Drop the installed plan and return to lazy env loading."""
    global _plan, _loaded
    with _load_lock:
        _plan = None
        _loaded = False


def active() -> bool:
    return _get_plan() is not None


def should(point: str, offset: Optional[int] = None) -> bool:
    """True iff `point` fires now (counts the fire)."""
    plan = _get_plan()
    return plan is not None and plan.fire(point, offset) is not None


def fire(point: str, offset: Optional[int] = None) -> Optional[Rule]:
    """Like ``should`` but returns the fired Rule, so parameterized
    call sites (the sim transport's ``ms`` windows, ``frac`` damage)
    can read the rule's knobs."""
    plan = _get_plan()
    return plan.fire(point, offset) if plan is not None else None


def points() -> tuple:
    """The known injection-point names (docs / schedule generators)."""
    return _POINTS


def fired_total() -> int:
    plan = _get_plan()
    return plan.fired_total() if plan is not None else 0


# -- call-site helpers ------------------------------------------------------


def damage_file(point: str, path: str,
                offset: Optional[int] = None) -> bool:
    """Post-write corruption: `*.torn` truncates `path` to the rule's
    `frac`; `*.bitflip` flips one deterministic bit. Returns True when
    damage was done (call sites never need to branch on it)."""
    plan = _get_plan()
    rule = plan.fire(point, offset) if plan is not None else None
    if rule is None:
        return False
    size = os.path.getsize(path)
    if size <= 0:
        return False
    if point.endswith(".torn"):
        with open(path, "r+b") as f:
            f.truncate(max(1, int(size * rule.frac)))
    else:  # bitflip
        pos = rule.rng.randrange(size)
        bit = rule.rng.randrange(8)
        with open(path, "r+b") as f:
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([b[0] ^ (1 << bit)]))
    return True


def kill_now(point: str, offset: Optional[int] = None) -> None:
    """SIGKILL this process if `point` fires — the no-cleanup crash
    (atexit, finally blocks and buffered writes all die with it)."""
    if should(point, offset):
        os.kill(os.getpid(), signal.SIGKILL)
