"""Workload generation — a seeded port of the reference's e2e driver
(/root/reference/exchange_test.js).

The reference drives the engine with an unseeded Math.random() stream, so
its exact event sequence is irreproducible; this port keeps the exact
*distribution* and sequencing semantics but is deterministic under a seed
(the parity strategy of SURVEY.md §4: golden traces come from replaying
one seeded stream through both the oracle and the TPU engine).

Faithful details:
  - seeding preamble: per account CREATE_BALANCE + TRANSFER of
    N(50000, 25000) (exchange_test.js:23-28, amounts are price-units*100),
    then `i < numSymbols/2+1` ADD_SYMBOLs — note the float loop bound
    creates 3 symbols for numSymbols=3 but only 3 for numSymbols=4 as
    well, leaving high sids unadded (exchange_test.js:29-32)
  - event mix per mille (exchange_test.js:106-117): 1 add-symbol,
    1 payout, 2 transfer N(0, 12500), 332 buy, 332 sell, ~334 cancel
  - prices and sizes are floor(N(50, 10)) — occasionally zero or negative
    (the Q2 trigger)
  - payouts are sent with action=4 (CANCEL) — the reference harness's
    opcode bug, Q5 (exchange_test.js:78 `createOrder(4, ...)`); pass
    payout_opcode_bug=False to emit the real PAYOUT opcode (200)
  - cancels pick a uniformly random previously-submitted oid and remove
    it from the pool whether or not the cancel succeeds
    (exchange_test.js:97-104); an empty pool yields the oid=0 cancel
  - oids are uniform in [0, 2^53) (exchange_test.js:82,88)
"""

from __future__ import annotations

import bisect
import math
import random
from typing import (Callable, Iterator, List, NamedTuple, Sequence,
                    Tuple)

from kme_tpu import opcodes as op
from kme_tpu.wire import OrderMsg


class WorkloadGen:
    """Deterministic re-implementation of exchange_test.js's generator."""

    def __init__(
        self,
        num_accounts: int = 10,
        num_symbols: int = 3,
        rake: int = 3,
        seed: int = 0,
        payout_opcode_bug: bool = True,
        validate: bool = False,
    ) -> None:
        self.num_accounts = num_accounts
        self.num_symbols = num_symbols
        self.rake = rake
        self.rng = random.Random(seed)
        self.payout_opcode_bug = payout_opcode_bug
        # validate=True clamps prices/sizes into the fixed-mode domain
        # (price 0..125, size >= 1) for clean-semantics workloads.
        self.validate = validate
        self.open_orders: dict[int, int] = {}  # oid -> aid (exchange_test.js:21)
        # sorted oid pool kept in lockstep with open_orders: cancels
        # select by SORTED position, and re-sorting the whole pool per
        # cancel is O(n^2 log n) over a long stream (the 400k soak spent
        # >20 min of host CPU there). bisect keeps the identical order
        # at O(n) memmove per op — the generated streams are UNCHANGED.
        self._pool: list[int] = []

    # -- primitive distributions (exchange_test.js:48-61) --

    def _random_normal(self) -> float:
        u = 0.0
        v = 0.0
        while u == 0.0:
            u = self.rng.random()
        while v == 0.0:
            v = self.rng.random()
        return math.sqrt(-2.0 * math.log(u)) * math.cos(2.0 * math.pi * v)

    def _uniform(self, rng_range: int) -> int:
        return math.floor(self.rng.random() * rng_range)

    def _normal_param(self, mean: float, std: float) -> int:
        return math.floor(self._random_normal() * std + mean)

    def _clamp_price(self, p: int) -> int:
        return min(125, max(0, p)) if self.validate else p

    def _clamp_size(self, s: int) -> int:
        return max(1, s) if self.validate else s

    # -- message constructors (exchange_test.js:63-104) --

    def create_account(self, aid: int) -> OrderMsg:
        return OrderMsg(action=op.CREATE_BALANCE, aid=aid)

    def create_symbol(self, sid: int) -> OrderMsg:
        return OrderMsg(action=op.ADD_SYMBOL, sid=sid)

    def create_transfer(self, aid: int, amount: int) -> OrderMsg:
        return OrderMsg(action=op.TRANSFER, aid=aid, size=amount)

    def create_payout(self, sid: int, success: bool) -> OrderMsg:
        action = op.CANCEL if self.payout_opcode_bug else op.PAYOUT
        return OrderMsg(
            action=action, sid=sid * (1 if success else -1),
            size=100 - self.rake)

    def create_buy(self, aid: int, sid: int, price: int, size: int) -> OrderMsg:
        oid = math.floor(self.rng.random() * (2 ** 53 - 1))
        if oid not in self.open_orders:
            bisect.insort(self._pool, oid)
        self.open_orders[oid] = aid
        return OrderMsg(action=op.BUY, oid=oid, aid=aid, sid=sid,
                        price=self._clamp_price(price), size=self._clamp_size(size))

    def create_sell(self, aid: int, sid: int, price: int, size: int) -> OrderMsg:
        oid = math.floor(self.rng.random() * (2 ** 53 - 1))
        if oid not in self.open_orders:
            bisect.insort(self._pool, oid)
        self.open_orders[oid] = aid
        return OrderMsg(action=op.SELL, oid=oid, aid=aid, sid=sid,
                        price=self._clamp_price(price), size=self._clamp_size(size))

    def create_cancel(self) -> OrderMsg:
        if not self.open_orders:
            return OrderMsg(action=op.CANCEL)
        # stable pool ordering under seed (identical to sorting the
        # dict keys per call — _pool IS that sorted sequence)
        i = math.floor(self.rng.random() * len(self._pool))
        oid = self._pool.pop(i)
        aid = self.open_orders.pop(oid)
        return OrderMsg(action=op.CANCEL, oid=oid, aid=aid)

    # -- event stream (exchange_test.js:4-37, 106-117) --

    def preamble(self) -> List[OrderMsg]:
        msgs: List[OrderMsg] = []
        for aid in range(self.num_accounts):
            msgs.append(self.create_account(aid))
            msgs.append(self.create_transfer(
                aid, self._normal_param(500 * 100, 250 * 100)))
        i = 0
        while i < self.num_symbols / 2 + 1:  # float bound, exchange_test.js:29
            msgs.append(self.create_symbol(i))
            i += 1
        return msgs

    def gen_event(self) -> OrderMsg:
        e = self._uniform(1000)
        if e == 0:
            return self.create_symbol(self._uniform(self.num_symbols))
        if e == 1:
            return self.create_payout(
                self._uniform(self.num_symbols), self._uniform(2) == 0)
        if e in (2, 3):
            return self.create_transfer(
                self._uniform(self.num_accounts), self._normal_param(0, 125 * 100))
        if 3 < e <= 335:
            return self.create_buy(
                self._uniform(self.num_accounts), self._uniform(self.num_symbols),
                self._normal_param(50, 10), self._normal_param(50, 10))
        if 335 < e <= 667:
            return self.create_sell(
                self._uniform(self.num_accounts), self._uniform(self.num_symbols),
                self._normal_param(50, 10), self._normal_param(50, 10))
        return self.create_cancel()

    def stream(self, num_events: int, include_preamble: bool = True
               ) -> Iterator[OrderMsg]:
        if include_preamble:
            yield from self.preamble()
        for _ in range(num_events):
            yield self.gen_event()


def harness_stream(num_events: int = 100_000, seed: int = 0,
                   num_accounts: int = 10, num_symbols: int = 3,
                   rake: int = 3, payout_opcode_bug: bool = True,
                   validate: bool = False) -> List[OrderMsg]:
    """The full reference harness workload: preamble + num_events random
    events (exchange_test.js:23-36 with the default knobs :18-20)."""
    gen = WorkloadGen(num_accounts, num_symbols, rake, seed,
                      payout_opcode_bug, validate)
    return list(gen.stream(num_events))


def zipf_symbol_stream(num_events: int, num_symbols: int, num_accounts: int,
                       seed: int = 0, zipf_a: float = 1.2,
                       deposit: int = 10_000_000,
                       payout_per_mille: int = 0) -> List[OrderMsg]:
    """Scale workload for the BASELINE.md throughput configs: Zipf-skewed
    symbol arrival over many symbols/accounts, valid-domain prices/sizes.
    payout_per_mille > 0 mixes in real PAYOUT barriers (each immediately
    followed by a re-ADD of the settled symbol so its lane stays live)."""
    gen = WorkloadGen(num_accounts, num_symbols, seed=seed, validate=True,
                      payout_opcode_bug=False)
    msgs: List[OrderMsg] = []
    for aid in range(num_accounts):
        msgs.append(gen.create_account(aid))
        msgs.append(gen.create_transfer(aid, deposit))
    for sid in range(num_symbols):
        msgs.append(gen.create_symbol(sid))
    # Zipf ranks over symbols, uniform accounts
    weights = [1.0 / (r + 1) ** zipf_a for r in range(num_symbols)]
    total = sum(weights)
    cdf = []
    acc = 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    import bisect
    for _ in range(num_events):
        u = gen.rng.random()
        sid = bisect.bisect_left(cdf, u)
        aid = gen._uniform(num_accounts)
        e = gen._uniform(1000)
        if e < payout_per_mille:
            msgs.append(gen.create_payout(sid, gen.rng.random() < 0.5))
            msgs.append(gen.create_symbol(sid))
        elif e < 450:
            msgs.append(gen.create_buy(aid, sid, gen._normal_param(50, 10),
                                       gen._normal_param(50, 10)))
        elif e < 900:
            msgs.append(gen.create_sell(aid, sid, gen._normal_param(50, 10),
                                        gen._normal_param(50, 10)))
        else:
            msgs.append(gen.create_cancel())
    return msgs


def zipf_hot_stream(num_events: int, num_symbols: int, num_accounts: int,
                    seed: int = 0, hot_frac: float = 0.7,
                    zipf_a: float = 1.2,
                    deposit: int = 10_000_000) -> List[OrderMsg]:
    """Adversarial profile for static sharding: ONE hot book. Symbol 0
    takes `hot_frac` of all events outright; the remainder is
    Zipf-distributed over symbols 1..n-1, so there is a distinctly WARM
    second-ranked book — the shape that defeats `lane % shards`
    placement twice over (the hot symbol saturates its shard AND the
    static hash co-locates the warm book with it, which an elastic
    planner migrates away). Seed-deterministic like every profile here
    (same stream for the same arguments — asserted in
    tests/test_workload.py)."""
    if num_symbols < 2:
        raise ValueError("zipf-hot needs >= 2 symbols (hot + cold set)")
    gen = WorkloadGen(num_accounts, num_symbols, seed=seed, validate=True,
                      payout_opcode_bug=False)
    msgs: List[OrderMsg] = []
    for aid in range(num_accounts):
        msgs.append(gen.create_account(aid))
        msgs.append(gen.create_transfer(aid, deposit))
    for sid in range(num_symbols):
        msgs.append(gen.create_symbol(sid))
    cold = num_symbols - 1
    weights = [1.0 / (r + 1) ** zipf_a for r in range(cold)]
    total = sum(weights)
    cdf = []
    acc = 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    for _ in range(num_events):
        if gen.rng.random() < hot_frac:
            sid = 0
        else:
            sid = 1 + bisect.bisect_left(cdf, gen.rng.random())
        aid = gen._uniform(num_accounts)
        e = gen._uniform(1000)
        if e < 450:
            msgs.append(gen.create_buy(aid, sid, gen._normal_param(50, 10),
                                       gen._normal_param(50, 10)))
        elif e < 900:
            msgs.append(gen.create_sell(aid, sid, gen._normal_param(50, 10),
                                        gen._normal_param(50, 10)))
        else:
            msgs.append(gen.create_cancel())
    return msgs


def payout_storm_stream(num_events: int, num_symbols: int,
                        num_accounts: int, seed: int = 0,
                        storms: int = 3,
                        deposit: int = 10_000_000) -> List[OrderMsg]:
    """Mass-settlement burst profile: steady Zipf trading punctuated by
    `storms` evenly-spaced bursts in which EVERY symbol is paid out
    (real PAYOUT opcode) and immediately re-ADDed. Each payout is a
    barrier window in the mesh planner, so the profile stresses the
    flush/rebind path and collapses then rebuilds every book at once.
    Seed-deterministic."""
    if storms < 1:
        raise ValueError("payout-storm needs storms >= 1")
    gen = WorkloadGen(num_accounts, num_symbols, seed=seed, validate=True,
                      payout_opcode_bug=False)
    msgs: List[OrderMsg] = []
    for aid in range(num_accounts):
        msgs.append(gen.create_account(aid))
        msgs.append(gen.create_transfer(aid, deposit))
    for sid in range(num_symbols):
        msgs.append(gen.create_symbol(sid))
    weights = [1.0 / (r + 1) ** 1.2 for r in range(num_symbols)]
    total = sum(weights)
    cdf = []
    acc = 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    storm_at = {max(1, (i + 1) * num_events // (storms + 1))
                for i in range(storms)}
    for k in range(num_events):
        if k in storm_at:
            for sid in range(num_symbols):
                msgs.append(gen.create_payout(sid,
                                              gen.rng.random() < 0.5))
                msgs.append(gen.create_symbol(sid))
            continue
        sid = bisect.bisect_left(cdf, gen.rng.random())
        aid = gen._uniform(num_accounts)
        e = gen._uniform(1000)
        if e < 450:
            msgs.append(gen.create_buy(aid, sid, gen._normal_param(50, 10),
                                       gen._normal_param(50, 10)))
        elif e < 900:
            msgs.append(gen.create_sell(aid, sid, gen._normal_param(50, 10),
                                        gen._normal_param(50, 10)))
        else:
            msgs.append(gen.create_cancel())
    return msgs


def cancel_heavy_stream(num_events: int, num_symbols: int, num_accounts: int,
                        seed: int = 0, cancel_ratio: float = 0.8,
                        deposit: int = 10_000_000) -> List[OrderMsg]:
    """BASELINE.md's bursty cancel/replace config: attempts a cancel with
    probability cancel_ratio whenever the open-order pool is non-empty.
    Steady-state cancels are structurally bounded near 50% of events (each
    cancel consumes one prior resting submit), matching the reference
    harness's own cancel-vs-submit equilibrium (exchange_test.js:106-117)."""
    gen = WorkloadGen(num_accounts, num_symbols, seed=seed, validate=True)
    msgs: List[OrderMsg] = []
    for aid in range(num_accounts):
        msgs.append(gen.create_account(aid))
        msgs.append(gen.create_transfer(aid, deposit))
    for sid in range(num_symbols):
        msgs.append(gen.create_symbol(sid))
    for _ in range(num_events):
        if gen.rng.random() < cancel_ratio and gen.open_orders:
            msgs.append(gen.create_cancel())
        else:
            aid = gen._uniform(num_accounts)
            sid = gen._uniform(num_symbols)
            if gen.rng.random() < 0.5:
                msgs.append(gen.create_buy(aid, sid, gen._normal_param(50, 10),
                                           gen._normal_param(50, 10)))
            else:
                msgs.append(gen.create_sell(aid, sid, gen._normal_param(50, 10),
                                            gen._normal_param(50, 10)))
    return msgs


def cross_account_stream(num_events: int, num_symbols: int,
                         num_accounts: int, ngroups: int,
                         seed: int = 0, cross_frac: float = 0.5,
                         zipf_a: float = 1.2,
                         deposit: int = 10_000_000) -> List[OrderMsg]:
    """Transfer-path sizing profile for the multi-leader topology
    (bridge/front.py): Zipf-skewed symbol arrival where a configurable
    fraction of orders is FORCED onto a non-home account — an account
    whose home group (rendezvous hash of aid) differs from the order's
    symbol group — so every such order costs the front door a
    reserve->settle transfer pair. cross_frac=1.0 is the degenerate
    worst case (100% cross-shard, the bench tail). Seed-deterministic;
    with ngroups=1 there are no non-home accounts and the stream
    degenerates to plain Zipf traffic."""
    from kme_tpu.bridge.front import account_group, symbol_group

    gen = WorkloadGen(num_accounts, num_symbols, seed=seed, validate=True,
                      payout_opcode_bug=False)
    msgs: List[OrderMsg] = []
    for aid in range(num_accounts):
        msgs.append(gen.create_account(aid))
        msgs.append(gen.create_transfer(aid, deposit))
    for sid in range(num_symbols):
        msgs.append(gen.create_symbol(sid))
    # account pools keyed by home group: same[g] lives on g, cross[g]
    # anywhere else (empty pools fall back to the full range)
    same = {g: [] for g in range(ngroups)}
    cross = {g: [] for g in range(ngroups)}
    for aid in range(num_accounts):
        h = account_group(aid, ngroups)
        for g in range(ngroups):
            (same if g == h else cross)[g].append(aid)
    weights = [1.0 / (r + 1) ** zipf_a for r in range(num_symbols)]
    total = sum(weights)
    cdf = []
    acc = 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    for _ in range(num_events):
        sid = bisect.bisect_left(cdf, gen.rng.random())
        g = symbol_group(sid, ngroups)
        pool = cross[g] if gen.rng.random() < cross_frac else same[g]
        aid = (pool[gen._uniform(len(pool))] if pool
               else gen._uniform(num_accounts))
        e = gen._uniform(1000)
        if e < 450:
            msgs.append(gen.create_buy(aid, sid, gen._normal_param(50, 10),
                                       gen._normal_param(50, 10)))
        elif e < 900:
            msgs.append(gen.create_sell(aid, sid, gen._normal_param(50, 10),
                                        gen._normal_param(50, 10)))
        else:
            msgs.append(gen.create_cancel())
    return msgs


# ---------------------------------------------------------------------------
# Adversarial storm suite (ROADMAP item 4): five named profiles that model
# how prediction markets actually die — at event boundaries, not in the zipf
# steady state. Every profile is seed-deterministic (same arguments, same
# stream — tests/test_workload.py) and exposes exact BURST WINDOWS: message
# index ranges [lo, hi) a producer should offer at `mult` times the base
# pacing, which is what turns a stored stream into an arrival-rate storm
# (wire messages carry no timestamps, so rate lives in the producer).
# kme-chaos paces with these windows; the overload controller's
# deterministic simulation (bridge/broker.py simulate_overload) replays the
# same windows for the gated shed_frac metrics.


def _zipf_cdf(n: int, a: float = 1.2) -> List[float]:
    weights = [1.0 / (r + 1) ** a for r in range(n)]
    total = sum(weights)
    cdf: List[float] = []
    acc = 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    return cdf


def _storm_preamble(gen: WorkloadGen, num_accounts: int, num_symbols: int,
                    deposit: int) -> List[OrderMsg]:
    """Flat funding preamble: 2*accounts + symbols messages, so burst
    windows can be computed exactly from the profile arguments."""
    msgs: List[OrderMsg] = []
    for aid in range(num_accounts):
        msgs.append(gen.create_account(aid))
        msgs.append(gen.create_transfer(aid, deposit))
    for sid in range(num_symbols):
        msgs.append(gen.create_symbol(sid))
    return msgs


def _preamble_len(num_accounts: int, num_symbols: int) -> int:
    return 2 * num_accounts + num_symbols


def _burst_ranges(num_events: int, bursts: int,
                  frac: float) -> List[Tuple[int, int]]:
    """`bursts` evenly-spaced event-index ranges, each ~frac of the
    stream (the same arithmetic shape as payout_storm_stream's
    storm_at, so window placement is deterministic)."""
    width = max(1, int(num_events * frac))
    out: List[Tuple[int, int]] = []
    for i in range(bursts):
        c = (i + 1) * num_events // (bursts + 1)
        lo = max(0, c - width // 2)
        out.append((lo, min(num_events, lo + width)))
    return out


def payout_storm_wide_stream(num_events: int, num_symbols: int,
                             num_accounts: int, seed: int = 0,
                             deposit: int = 10_000_000) -> List[OrderMsg]:
    """The event boundary itself: steady Zipf trading until ONE contiguous
    burst settles the ENTIRE symbol space (real PAYOUT per symbol, each
    immediately re-ADDed). At full scale that is ~1k symbols' worth of
    barrier ops arriving back-to-back — the all-at-once settlement shape
    KProcessor.java:148-165 implies but the reference harness never
    generates. One message per steady event, so the storm block sits at
    exactly preamble + num_events//2."""
    gen = WorkloadGen(num_accounts, num_symbols, seed=seed, validate=True,
                      payout_opcode_bug=False)
    msgs = _storm_preamble(gen, num_accounts, num_symbols, deposit)
    cdf = _zipf_cdf(num_symbols)
    storm_k = max(1, num_events // 2)
    for k in range(num_events):
        if k == storm_k:
            for sid in range(num_symbols):
                msgs.append(gen.create_payout(sid, gen.rng.random() < 0.5))
                msgs.append(gen.create_symbol(sid))
        sid = bisect.bisect_left(cdf, gen.rng.random())
        aid = gen._uniform(num_accounts)
        e = gen._uniform(1000)
        if e < 450:
            msgs.append(gen.create_buy(aid, sid, gen._normal_param(50, 10),
                                       gen._normal_param(50, 10)))
        elif e < 900:
            msgs.append(gen.create_sell(aid, sid, gen._normal_param(50, 10),
                                        gen._normal_param(50, 10)))
        else:
            msgs.append(gen.create_cancel())
    return msgs


def flash_crowd_stream(num_events: int, num_symbols: int,
                       num_accounts: int, seed: int = 0,
                       bursts: int = 3, burst_frac: float = 0.08,
                       hot_frac: float = 0.9,
                       deposit: int = 10_000_000) -> List[OrderMsg]:
    """Flash crowd: a breaking-news spike. Outside the burst windows the
    stream is ordinary Zipf trading; inside them everyone piles onto
    symbol 0 (probability hot_frac), the order mix collapses to pure
    buy/sell (nobody cancels during a rush), and the flow comes from a
    small flooder clique (num_accounts//8 accounts) — the per-account
    fairness adversary. The producer offers these windows at ~100x
    pacing (storm_windows), which is what makes it a rate storm."""
    gen = WorkloadGen(num_accounts, num_symbols, seed=seed, validate=True,
                      payout_opcode_bug=False)
    msgs = _storm_preamble(gen, num_accounts, num_symbols, deposit)
    cdf = _zipf_cdf(num_symbols)
    ranges = _burst_ranges(num_events, bursts, burst_frac)
    flooders = max(1, num_accounts // 8)
    for k in range(num_events):
        burst = any(lo <= k < hi for lo, hi in ranges)
        if burst:
            sid = (0 if gen.rng.random() < hot_frac
                   else bisect.bisect_left(cdf, gen.rng.random()))
            aid = gen._uniform(flooders)
            if gen.rng.random() < 0.5:
                msgs.append(gen.create_buy(aid, sid,
                                           gen._normal_param(50, 10),
                                           gen._normal_param(50, 10)))
            else:
                msgs.append(gen.create_sell(aid, sid,
                                            gen._normal_param(50, 10),
                                            gen._normal_param(50, 10)))
            continue
        sid = bisect.bisect_left(cdf, gen.rng.random())
        aid = gen._uniform(num_accounts)
        e = gen._uniform(1000)
        if e < 450:
            msgs.append(gen.create_buy(aid, sid, gen._normal_param(50, 10),
                                       gen._normal_param(50, 10)))
        elif e < 900:
            msgs.append(gen.create_sell(aid, sid, gen._normal_param(50, 10),
                                        gen._normal_param(50, 10)))
        else:
            msgs.append(gen.create_cancel())
    return msgs


def cancel_storm_stream(num_events: int, num_symbols: int,
                        num_accounts: int, seed: int = 0,
                        cancel_ratio: float = 0.75,
                        bogus_frac: float = 0.85,
                        deposit: int = 10_000_000) -> List[OrderMsg]:
    """Cancel blizzard (HFT quote-stuffing shape): ~3/4 of events are
    cancels, and most of those target oids that were never submitted —
    driving the engine's rej_cancel ratio to ~10x the reference
    harness's steady state (~7k/105k in BENCH_r05). The remaining
    events are fresh buy/sell flow, so cancels and new orders arrive
    interleaved — the stream the priority-aware shedder must split
    (cancels drain the book: admit; new orders grow it: shed)."""
    gen = WorkloadGen(num_accounts, num_symbols, seed=seed, validate=True,
                      payout_opcode_bug=False)
    msgs = _storm_preamble(gen, num_accounts, num_symbols, deposit)
    for _ in range(num_events):
        if gen.rng.random() < cancel_ratio:
            if gen.rng.random() < bogus_frac or not gen.open_orders:
                # a cancel for an oid nobody submitted: always rej_cancel
                msgs.append(OrderMsg(
                    action=op.CANCEL,
                    oid=math.floor(gen.rng.random() * (2 ** 53 - 1)),
                    aid=gen._uniform(num_accounts)))
            else:
                msgs.append(gen.create_cancel())
            continue
        aid = gen._uniform(num_accounts)
        sid = gen._uniform(num_symbols)
        if gen.rng.random() < 0.5:
            msgs.append(gen.create_buy(aid, sid, gen._normal_param(50, 10),
                                       gen._normal_param(50, 10)))
        else:
            msgs.append(gen.create_sell(aid, sid, gen._normal_param(50, 10),
                                        gen._normal_param(50, 10)))
    return msgs


def hot_book_stream(num_events: int, num_symbols: int,
                    num_accounts: int, seed: int = 0,
                    hot_frac: float = 0.97,
                    deposit: int = 10_000_000) -> List[OrderMsg]:
    """One-symbol pathology: hot_frac of ALL flow lands on symbol 0 with
    a tight price band (N(50, 3) — nearly every arrival crosses), and
    cancels are rare so the book only deepens. Unlike zipf-hot there is
    no warm cold-set for a rebalancer to migrate: a single book takes
    the whole storm, which no symbol-sharding layout can split — the
    overload controller is the only defense left."""
    if num_symbols < 2:
        raise ValueError("hot-book needs >= 2 symbols (hot + background)")
    gen = WorkloadGen(num_accounts, num_symbols, seed=seed, validate=True,
                      payout_opcode_bug=False)
    msgs = _storm_preamble(gen, num_accounts, num_symbols, deposit)
    for _ in range(num_events):
        sid = (0 if gen.rng.random() < hot_frac
               else 1 + gen._uniform(num_symbols - 1))
        aid = gen._uniform(num_accounts)
        e = gen._uniform(1000)
        if e < 475:
            msgs.append(gen.create_buy(aid, sid, gen._normal_param(50, 3),
                                       gen._normal_param(50, 10)))
        elif e < 950:
            msgs.append(gen.create_sell(aid, sid, gen._normal_param(50, 3),
                                        gen._normal_param(50, 10)))
        else:
            msgs.append(gen.create_cancel())
    return msgs


def liquidation_cascade_stream(num_events: int, num_symbols: int,
                               num_accounts: int, seed: int = 0,
                               cascades: int = 2,
                               deposit: int = 40_000) -> List[OrderMsg]:
    """Balance-exhaustion cascade: accounts are funded thinly (~16
    orders' margin), the mix is buy-heavy so margin locks up fast, and
    at each cascade point EVERY symbol is settled long-side (PAYOUT
    success=True, then re-ADDed) while orders are still resting — the
    mass-liquidation-against-open-interest interaction. Losers come out
    of each cascade with exhausted balances, so the post-cascade flow
    turns into a rej_risk wave. One message per steady event: cascade
    block c sits at exactly preamble + (c+1)*num_events//(cascades+1)
    + 2*num_symbols*c."""
    if cascades < 1:
        raise ValueError("liquidation-cascade needs cascades >= 1")
    gen = WorkloadGen(num_accounts, num_symbols, seed=seed, validate=True,
                      payout_opcode_bug=False)
    msgs = _storm_preamble(gen, num_accounts, num_symbols, deposit)
    cdf = _zipf_cdf(num_symbols)
    cascade_at = {max(1, (i + 1) * num_events // (cascades + 1))
                  for i in range(cascades)}
    for k in range(num_events):
        if k in cascade_at:
            for sid in range(num_symbols):
                msgs.append(gen.create_payout(sid, True))
                msgs.append(gen.create_symbol(sid))
        sid = bisect.bisect_left(cdf, gen.rng.random())
        aid = gen._uniform(num_accounts)
        e = gen._uniform(1000)
        if e < 650:
            msgs.append(gen.create_buy(aid, sid, gen._normal_param(50, 10),
                                       gen._normal_param(50, 10)))
        elif e < 900:
            msgs.append(gen.create_sell(aid, sid, gen._normal_param(50, 10),
                                        gen._normal_param(50, 10)))
        else:
            msgs.append(gen.create_cancel())
    return msgs


class StormProfile(NamedTuple):
    """Registry row: generator + full-scale defaults + burst windows.

    windows(num_events, num_symbols, num_accounts) returns absolute
    message-index ranges [(lo, hi, mult), ...]: offer messages in
    [lo, hi) at mult x the base pacing."""

    name: str
    summary: str
    symbols: int
    accounts: int
    fn: Callable[..., List[OrderMsg]]
    windows: Callable[[int, int, int], List[Tuple[int, int, int]]]


def _w_payout_wide(ev: int, sy: int, ac: int) -> List[Tuple[int, int, int]]:
    lo = _preamble_len(ac, sy) + max(1, ev // 2)
    return [(lo, lo + 2 * sy, 100)]


def _w_flash_crowd(ev: int, sy: int, ac: int) -> List[Tuple[int, int, int]]:
    pre = _preamble_len(ac, sy)
    return [(pre + lo, pre + hi, 100)
            for lo, hi in _burst_ranges(ev, 3, 0.08)]


def _w_cancel_storm(ev: int, sy: int, ac: int) -> List[Tuple[int, int, int]]:
    pre = _preamble_len(ac, sy)
    return [(pre + lo, pre + hi, 20)
            for lo, hi in _burst_ranges(ev, 2, 0.10)]


def _w_hot_book(ev: int, sy: int, ac: int) -> List[Tuple[int, int, int]]:
    pre = _preamble_len(ac, sy)
    return [(pre + lo, pre + hi, 10)
            for lo, hi in _burst_ranges(ev, 1, 0.20)]


def _w_cascade(ev: int, sy: int, ac: int) -> List[Tuple[int, int, int]]:
    pre = _preamble_len(ac, sy)
    out = []
    for c in range(2):
        lo = pre + max(1, (c + 1) * ev // 3) + 2 * sy * c
        out.append((lo, lo + 2 * sy + max(1, ev // 20), 50))
    return out


STORM_PROFILES = {
    "payout-storm-wide": StormProfile(
        "payout-storm-wide",
        "settle the entire symbol space (~1k symbols) in one contiguous "
        "PAYOUT+re-ADD burst mid-stream",
        1000, 64, payout_storm_wide_stream, _w_payout_wide),
    "flash-crowd": StormProfile(
        "flash-crowd",
        "100x-rate burst windows where a small flooder clique piles "
        "onto one symbol (per-account fairness adversary)",
        64, 64, flash_crowd_stream, _w_flash_crowd),
    "cancel-storm": StormProfile(
        "cancel-storm",
        "~75% cancels, mostly for never-submitted oids: rej_cancel at "
        "~10x the reference harness ratio, interleaved with fresh flow",
        16, 32, cancel_storm_stream, _w_cancel_storm),
    "hot-book": StormProfile(
        "hot-book",
        "97% of flow on ONE tight-priced symbol — the pathology no "
        "symbol-sharding layout can split",
        8, 32, hot_book_stream, _w_hot_book),
    "liquidation-cascade": StormProfile(
        "liquidation-cascade",
        "thin funding + buy-heavy flow, then mass long-side settlement "
        "against open interest: a rej_risk exhaustion wave",
        32, 48, liquidation_cascade_stream, _w_cascade),
}


def storm_stream(name: str, num_events: int, *, num_symbols: int = None,
                 num_accounts: int = None, seed: int = 0) -> List[OrderMsg]:
    """Generate a named storm profile (registry defaults unless the
    caller scales symbols/accounts down, e.g. for CI)."""
    p = STORM_PROFILES[name]
    return p.fn(num_events,
                p.symbols if num_symbols is None else num_symbols,
                p.accounts if num_accounts is None else num_accounts,
                seed=seed)


def storm_windows(name: str, num_events: int, num_symbols: int = None,
                  num_accounts: int = None) -> List[Tuple[int, int, int]]:
    """Burst windows for a named profile at the given scale: absolute
    message-index ranges [(lo, hi, mult), ...]."""
    p = STORM_PROFILES[name]
    return p.windows(num_events,
                     p.symbols if num_symbols is None else num_symbols,
                     p.accounts if num_accounts is None else num_accounts)


def spliced_stream(num_events: int, seed: int = 0,
                   splices: Sequence[Tuple[int, str, int]] = (),
                   num_accounts: int = 10,
                   num_symbols: int = 3,
                   prefund_cash: int = 0) -> List[OrderMsg]:
    """Generative scenario composition (kme-sim, kme_tpu/sim/): the
    reference harness baseline with named storm bursts spliced in at
    stream positions. `splices` is [(at, profile, n), ...] — insert an
    `n`-event `profile` burst (STORM_PROFILES) before baseline position
    `at`. Bursts keep their registry symbol/account spaces, so a spliced
    storm brings its own preamble and collides with the baseline's id
    space only where the registry says it does; everything stays a pure
    function of (num_events, seed, splices), which is what lets a
    shrunk fault schedule regenerate its input byte-identically.

    `prefund_cash` > 0 prepends a CREATE_BALANCE + TRANSFER(cash) pair
    for every account the composed stream can touch (baseline space ∪
    spliced profiles' registry spaces). Grouped serving's parity
    contract requires the funded envelope — the front's shadow-cash
    margin bound is a conservative LOWER bound that never models
    releases, so a depleted account can see a cross-shard grant fall
    short and the group engine reject what the single oracle accepts
    (`transfer_shortfall_total`; test_front pins shortfall == 0 for
    exactly this reason). The deposits ride IN the stream, seen
    identically by the oracle and the cluster."""
    base = harness_stream(num_events, seed=seed,
                          num_accounts=num_accounts,
                          num_symbols=num_symbols)
    # apply back-to-front so earlier positions stay valid
    for at, name, n in sorted(splices, key=lambda s: s[0], reverse=True):
        burst = storm_stream(name, n, seed=seed ^ 0x5EED)
        at = max(0, min(len(base), int(at)))
        base[at:at] = burst
    if prefund_cash > 0:
        space = max([num_accounts]
                    + [STORM_PROFILES[name].accounts
                       for _, name, _ in splices])
        base[0:0] = [m for aid in range(space)
                     for m in (OrderMsg(action=op.CREATE_BALANCE,
                                        aid=aid),
                               OrderMsg(action=op.TRANSFER, aid=aid,
                                        size=int(prefund_cash)))]
    return base
