"""kme_tpu.analysis — repo-native static analysis (`kme-lint`).

The repo's hardest invariants — replay determinism of the
``(epoch, out_seq)`` stamp stream, byte-exact oracle parity, and a
sync-free pipelined hot loop — are enforced dynamically by tests and
chaos drills, which catch violations only after they ship. This package
checks the same invariants *statically*, with three rule families over
the project's own AST:

  KME-H0xx  hot-path lints: host syncs and blocking I/O inside the
            pipelined submit window (rules.HOT_SCOPES)
  KME-D0xx  determinism lints: wall clock / randomness in
            replay-affecting paths (rules.REPLAY_SCOPES)
  KME-T0xx  tracer lints: Python branches on traced values and
            width-unstable dtypes in engine/ and ops/
  KME-L0xx  lock discipline: statically extracted lock-order cycles and
            attributes mutated from multiple threads without a common
            lock (lockgraph.py), backed by the KME_LOCKCHECK=1 runtime
            recorder (lockcheck.py)

Rule IDs are stable; a checked-in baseline (LINT_BASELINE.json at the
repo root) grandfathers existing findings, and ``kme-lint --gate``
exits nonzero only on NEW ones. Fingerprints hash the rule, file,
enclosing scope and normalized source line — not line numbers — so
unrelated edits above a finding do not invalidate the baseline.

Analysis is additive: nothing here changes runtime behavior
(COMPAT.md). The sanitizer leg (scripts/build_native.py --sanitize)
covers the native layer the AST cannot see.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str        # stable rule id, e.g. "KME-H001"
    path: str        # repo-relative, forward slashes
    line: int        # 1-based
    col: int
    scope: str       # "Class.method", "function", or "<module>"
    message: str
    snippet: str     # stripped source line the finding anchors to

    @property
    def fingerprint(self) -> str:
        """Line-shift-stable identity: rule + file + scope + the
        normalized source line. Duplicate snippets in one scope share a
        fingerprint; the baseline stores per-fingerprint counts so a
        NEW duplicate of a grandfathered line still gates."""
        norm = " ".join(self.snippet.split())
        raw = f"{self.rule}|{self.path}|{self.scope}|{norm}"
        return hashlib.sha256(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope}] {self.message}\n    {self.snippet}")


def repo_root(start: Optional[str] = None) -> str:
    """The repo root: nearest ancestor of `start` (default: this
    package) holding pyproject.toml, else the package's parent."""
    here = start or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    d = os.path.abspath(here)
    while True:
        if os.path.exists(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
        d = parent


BASELINE_NAME = "LINT_BASELINE.json"


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> {rule, path, scope, count, note?}. Missing file
    means an empty baseline (everything is new)."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != 1:
        raise ValueError(f"unknown baseline version in {path}: "
                         f"{data.get('version')!r}")
    return data.get("findings", {})


def save_baseline(path: str, findings: List[Finding],
                  notes: Optional[Dict[str, str]] = None) -> None:
    """Write the baseline for the given findings, preserving any
    `note` strings already attached to surviving fingerprints."""
    old = {}
    try:
        old = load_baseline(path)
    except (OSError, ValueError):
        pass
    table: Dict[str, dict] = {}
    for f in findings:
        fp = f.fingerprint
        ent = table.setdefault(fp, {
            "rule": f.rule, "path": f.path, "scope": f.scope,
            "snippet": " ".join(f.snippet.split()), "count": 0})
        ent["count"] += 1
        note = (notes or {}).get(fp) or old.get(fp, {}).get("note")
        if note:
            ent["note"] = note
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"version": 1, "findings": table}, f, indent=1,
                  sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def split_new(findings: List[Finding],
              baseline: Dict[str, dict]):
    """Partition findings into (new, grandfathered) against the
    per-fingerprint counts in the baseline."""
    budget = {fp: ent.get("count", 1) for fp, ent in baseline.items()}
    new, known = [], []
    for f in findings:
        fp = f.fingerprint
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            known.append(f)
        else:
            new.append(f)
    return new, known
