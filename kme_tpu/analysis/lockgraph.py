"""Static lock-discipline analysis (KME-L001 / KME-L002).

Extracts a lock model from the threaded modules without importing
them:

- **Lock sites**: ``self.X = threading.Lock()/RLock()`` inside a class
  (identity ``file::Class.X``) and module-level ``X = threading.Lock()``
  (identity ``file::X``). ``threading.Condition(self._lock)`` aliases
  the condition attribute to the wrapped lock — acquiring the condition
  IS acquiring the lock.

- **Acquisition graph**: within each function, ``with self.X:`` nests
  define edges A -> B (B acquired while A held). Calls made while
  holding A propagate one level: A gains edges to every lock the callee
  acquires directly (self-method and module-function calls). A cycle in
  this graph is a potential deadlock (KME-L001).

- **Thread attribution**: methods passed to ``threading.Thread(
  target=...)`` (including closures that call back into ``self``), and
  ``run`` on ``threading.Thread`` subclasses, execute off the main
  thread. The reachable set closes over self-method calls. An attribute
  stored both from thread-reachable code and from main-thread code,
  with no lock common to every store, is a potential race (KME-L002).
  Stores in ``__init__`` are construction-time (happens-before the
  thread start) and don't count. "Locks held at a store" includes
  caller-held locks when EVERY caller of the enclosing method holds
  them (a guaranteed-held fixpoint), so private helpers called under a
  lock are not false positives.

The runtime half (lockcheck.py, ``KME_LOCKCHECK=1``) validates the
same discipline against real acquisition orders during tier-1.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from kme_tpu.analysis import Finding

# The threaded surface: every module that creates a Lock/Condition or
# spawns a Thread. kme-lint re-derives L-family findings over exactly
# this set, so adding a threaded module means adding it here.
THREADED_MODULES = (
    "kme_tpu/telemetry/journal.py",
    "kme_tpu/telemetry/registry.py",
    "kme_tpu/telemetry/trace.py",
    "kme_tpu/telemetry/audit.py",
    "kme_tpu/telemetry/httpd.py",
    "kme_tpu/bridge/broker.py",
    "kme_tpu/bridge/service.py",
    "kme_tpu/bridge/tcp.py",
    "kme_tpu/bridge/chaos.py",
    "kme_tpu/faults.py",
)

_LOCK_CTORS = {"Lock", "RLock"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _FuncModel:
    """Per-function lock facts."""

    def __init__(self, qualname: str, node: ast.AST,
                 relpath: str) -> None:
        self.qualname = qualname          # "Class.method" or "func"
        self.node = node
        self.relpath = relpath
        self.direct: Set[str] = set()     # locks acquired in the body
        # (held_locks, lock) at each with-entry, for edge witnesses
        self.acquires: List[Tuple[Tuple[str, ...], str, int]] = []
        # calls made while holding locks: (held, callee_name, lineno);
        # callee_name is "self.M" or a bare module-level name
        self.calls: List[Tuple[Tuple[str, ...], str, int]] = []
        # attribute stores: attr -> list of (held_locks, lineno)
        self.stores: Dict[str, List[Tuple[Tuple[str, ...], int]]] = {}


class _ModuleModel:
    def __init__(self, relpath: str) -> None:
        self.relpath = relpath
        # lock id -> creation lineno
        self.locks: Dict[str, int] = {}
        # alias id -> canonical lock id (Condition wrapping)
        self.aliases: Dict[str, str] = {}
        self.funcs: Dict[str, _FuncModel] = {}   # qualname -> model
        # class -> thread-entry method names (directly identified)
        self.thread_entries: Dict[str, Set[str]] = {}
        self.classes: Set[str] = set()
        self.thread_subclasses: Set[str] = set()


class _Extractor(ast.NodeVisitor):
    def __init__(self, relpath: str) -> None:
        self.m = _ModuleModel(relpath)
        self._cls: Optional[str] = None
        self._fn: Optional[_FuncModel] = None
        self._held: List[str] = []

    # -- identity helpers ----------------------------------------------

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        """Canonical lock id for an acquisition expression, if the
        expression names a known lock (or alias) of this module."""
        name = _dotted(expr)
        if name is None:
            return None
        if name.startswith("self."):
            if self._cls is None:
                return None
            key = f"{self.m.relpath}::{self._cls}.{name[5:]}"
        else:
            key = f"{self.m.relpath}::{name}"
        key = self.m.aliases.get(key, key)
        return key if key in self.m.locks else None

    def _target_key(self, tgt: ast.AST) -> Optional[str]:
        name = _dotted(tgt)
        if name is None:
            return None
        if name.startswith("self.") and self._cls is not None:
            return f"{self.m.relpath}::{self._cls}.{name[5:]}"
        if "." not in name and self._fn is None:
            return f"{self.m.relpath}::{name}"
        return None

    # -- structure ------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev = self._cls
        self._cls = node.name
        self.m.classes.add(node.name)
        for base in node.bases:
            if (_dotted(base) or "").endswith("Thread"):
                self.m.thread_subclasses.add(node.name)
        self.generic_visit(node)
        self._cls = prev

    def _enter_fn(self, node) -> None:
        if self._fn is not None:
            # nested function: record as ClassOrOuter.outer.<name> so
            # closures passed to Thread(target=...) resolve
            qual = f"{self._fn.qualname}.{node.name}"
        elif self._cls is not None:
            qual = f"{self._cls}.{node.name}"
        else:
            qual = node.name
        prev_fn, prev_held = self._fn, self._held
        self._fn = _FuncModel(qual, node, self.m.relpath)
        self._held = []
        self.m.funcs[qual] = self._fn
        self.generic_visit(node)
        self._fn, self._held = prev_fn, prev_held

    visit_FunctionDef = _enter_fn
    visit_AsyncFunctionDef = _enter_fn

    # -- lock creation / aliasing ---------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        val = node.value
        if isinstance(val, ast.Call):
            ctor = _dotted(val.func) or ""
            tail = ctor.rsplit(".", 1)[-1]
            for tgt in node.targets:
                key = self._target_key(tgt)
                if key is None:
                    continue
                if tail in _LOCK_CTORS and (
                        ctor.startswith("threading.")
                        or ctor in _LOCK_CTORS):
                    self.m.locks[key] = node.lineno
                elif tail == "Condition":
                    if val.args:
                        wrapped = self._lock_id(val.args[0])
                        if wrapped is not None:
                            self.m.aliases[key] = wrapped
                            continue
                    # Condition() owns a fresh RLock
                    self.m.locks[key] = node.lineno
        self._record_stores(node.targets, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_stores([node.target], node.lineno)
        self.generic_visit(node)

    def _record_stores(self, targets, lineno: int) -> None:
        if self._fn is None:
            return
        for tgt in targets:
            for sub in ast.walk(tgt):
                name = _dotted(sub)
                if name and name.startswith("self.") \
                        and "." not in name[5:]:
                    self._fn.stores.setdefault(name[5:], []).append(
                        (tuple(self._held), lineno))

    # -- acquisition + calls --------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            lock = self._lock_id(item.context_expr)
            if lock is not None and self._fn is not None:
                self._fn.direct.add(lock)
                self._fn.acquires.append(
                    (tuple(self._held), lock, node.lineno))
                self._held.append(lock)
                acquired.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for lock in reversed(acquired):
            self._held.remove(lock)
        # with-items' own expressions (rare nested calls)
        for item in node.items:
            self.visit(item.context_expr)

    def visit_Call(self, node: ast.Call) -> None:
        if self._fn is not None:
            callee = _dotted(node.func)
            if callee is not None:
                if callee.startswith("self.") and "." not in callee[5:]:
                    self._fn.calls.append(
                        (tuple(self._held), f"self.{callee[5:]}",
                         node.lineno))
                elif "." not in callee:
                    self._fn.calls.append(
                        (tuple(self._held), callee, node.lineno))
            # thread entries: threading.Thread(target=X)
            ctor = _dotted(node.func) or ""
            if ctor.rsplit(".", 1)[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        self._mark_entry(kw.value)
        self.generic_visit(node)

    def _mark_entry(self, target: ast.AST) -> None:
        name = _dotted(target)
        if name is None or self._cls is None:
            return
        ent = self.m.thread_entries.setdefault(self._cls, set())
        if name.startswith("self."):
            ent.add(name[5:])
        else:
            # a closure defined in this function: qualname prefix match
            if self._fn is not None:
                ent.add(f"{self._fn.qualname}.{name}".split(".", 1)[1]
                        if self._cls and self._fn.qualname.startswith(
                            self._cls + ".")
                        else name)


def _resolve_callee(m: _ModuleModel, caller: _FuncModel,
                    callee: str) -> Optional[_FuncModel]:
    if callee.startswith("self."):
        cls = caller.qualname.split(".", 1)[0]
        return m.funcs.get(f"{cls}.{callee[5:]}")
    return m.funcs.get(callee)


def _guaranteed_held(m: _ModuleModel) -> Dict[str, Set[str]]:
    """For each function: locks held at EVERY call site (propagated
    through the intra-module call graph). Functions never called inside
    the module (API entry points) guarantee nothing."""
    callers: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
    for fn in m.funcs.values():
        for held, callee, _ in fn.calls:
            tgt = _resolve_callee(m, fn, callee)
            if tgt is not None:
                callers.setdefault(tgt.qualname, []).append(
                    (fn.qualname, held))
    guaranteed: Dict[str, Set[str]] = {q: set() for q in m.funcs}
    for _ in range(4):                    # small fixpoint
        changed = False
        for q, sites in callers.items():
            if not sites:
                continue
            agg: Optional[Set[str]] = None
            for caller_q, held in sites:
                eff = set(held) | guaranteed.get(caller_q, set())
                agg = eff if agg is None else (agg & eff)
            agg = agg or set()
            if agg != guaranteed[q]:
                guaranteed[q] = agg
                changed = True
        if not changed:
            break
    return guaranteed


def _construction_only(m: _ModuleModel, reach: Set[str]) -> Set[str]:
    """Methods whose every in-module caller chain roots at __init__
    (and that no thread reaches): they run before any thread that the
    constructor starts, so their stores are happens-before-ordered."""
    callers: Dict[str, Set[str]] = {}
    for fn in m.funcs.values():
        for _, callee, _ in fn.calls:
            tgt = _resolve_callee(m, fn, callee)
            if tgt is not None:
                callers.setdefault(tgt.qualname, set()).add(
                    fn.qualname)
    out: Set[str] = set()
    for _ in range(4):
        changed = False
        for q in m.funcs:
            if q in out or q in reach:
                continue
            cs = callers.get(q)
            if cs and all(
                    c.split(".")[-1] == "__init__" or c in out
                    for c in cs):
                out.add(q)
                changed = True
        if not changed:
            break
    return out


def _edges(models: List[_ModuleModel]):
    """(A, B, witness) edges: B acquired (or acquired by a callee)
    while A held."""
    out = []
    for m in models:
        for fn in m.funcs.values():
            for held, lock, lineno in fn.acquires:
                for h in held:
                    if h != lock:
                        out.append((h, lock, (m.relpath, lineno,
                                              fn.qualname)))
            for held, callee, lineno in fn.calls:
                if not held:
                    continue
                tgt = _resolve_callee(m, fn, callee)
                if tgt is None:
                    continue
                for lock in sorted(tgt.direct):
                    for h in held:
                        if h != lock:
                            out.append((h, lock, (m.relpath, lineno,
                                                  fn.qualname)))
    return out


def _find_cycles(edges) -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b, _ in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles, seen = [], set()

    def dfs(node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_path:
                cyc = path[path.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen:
                    seen.add(key)
                    cycles.append(cyc)
            elif (node, nxt) not in visited_edges:
                visited_edges.add((node, nxt))
                on_path.add(nxt)
                dfs(nxt, path + [nxt], on_path)
                on_path.discard(nxt)

    visited_edges: Set[Tuple[str, str]] = set()
    for start in sorted(graph):
        dfs(start, [start], {start})
    return cycles


def _thread_reachable(m: _ModuleModel) -> Set[str]:
    """Qualnames of functions that can run off the main thread."""
    entries: Set[str] = set()
    for cls, names in m.thread_entries.items():
        for n in names:
            for q in m.funcs:
                if q == f"{cls}.{n}" or q.startswith(f"{cls}.{n}."):
                    entries.add(q)
                # closures: "Class.method.closure" where the Thread
                # call named just the closure
                if q.endswith(f".{n}") and q.startswith(cls + "."):
                    entries.add(q)
    for cls in m.thread_subclasses:
        if f"{cls}.run" in m.funcs:
            entries.add(f"{cls}.run")
    # close over self-method calls (and closure method calls on any
    # receiver — over-approximate: `state._write_heartbeat()` in a
    # beater closure reaches the method)
    reach = set(entries)
    for _ in range(6):
        new = set()
        for q in reach:
            fn = m.funcs.get(q)
            if fn is None:
                continue
            cls = q.split(".", 1)[0]
            for _, callee, _ in fn.calls:
                if callee.startswith("self."):
                    tq = f"{cls}.{callee[5:]}"
                    if tq in m.funcs:
                        new.add(tq)
        for fn in m.funcs.values():
            # closures textually inside a reachable function
            for q in reach:
                if fn.qualname.startswith(q + "."):
                    new.add(fn.qualname)
        if new <= reach:
            break
        reach |= new
    # method calls on arbitrary receivers from reachable closures
    extra = set()
    for q in reach:
        fn = m.funcs.get(q)
        if fn is None:
            continue
        node = fn.node
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = _dotted(sub.func) or ""
                if "." in name:
                    meth = name.rsplit(".", 1)[-1]
                    for cls in m.classes:
                        tq = f"{cls}.{meth}"
                        if tq in m.funcs:
                            extra.add(tq)
    reach |= extra
    return reach


def analyze_modules(root: str,
                    modules=THREADED_MODULES) -> List[Finding]:
    models = []
    for rel in modules:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            continue
        with open(path, encoding="utf-8") as f:
            src = f.read()
        ex = _Extractor(rel)
        ex.visit(ast.parse(src, filename=rel))
        models.append(ex.m)
    findings: List[Finding] = []
    edges = _edges(models)
    src_lines: Dict[str, List[str]] = {}

    def line_of(rel, lineno):
        if rel not in src_lines:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                src_lines[rel] = f.read().splitlines()
        lines = src_lines[rel]
        return lines[lineno - 1].strip() if 0 < lineno <= len(lines) \
            else ""

    for cyc in _find_cycles(edges):
        a, b = cyc[0], cyc[1]
        wit = next(w for x, y, w in edges if x == a and y == b)
        rel, lineno, qual = wit
        findings.append(Finding(
            rule="KME-L001", path=rel, line=lineno, col=0, scope=qual,
            message=("lock-order cycle: "
                     + " -> ".join(c.split("::")[-1] for c in cyc)),
            snippet=line_of(rel, lineno)))
    for m in models:
        reach = _thread_reachable(m)
        if not reach:
            continue
        guaranteed = _guaranteed_held(m)
        ctor_only = _construction_only(m, reach)
        # attr -> [(qualname, held+guaranteed, lineno, threaded?)]
        per_attr: Dict[Tuple[str, str], List] = {}
        for fn in m.funcs.values():
            cls = fn.qualname.split(".", 1)[0]
            if cls not in m.classes:
                continue
            meth = fn.qualname.split(".")[-1]
            if meth == "__init__" or fn.qualname in ctor_only:
                continue        # happens-before thread start
            for attr, stores in fn.stores.items():
                for held, lineno in stores:
                    eff = set(held) | guaranteed.get(fn.qualname,
                                                     set())
                    per_attr.setdefault((cls, attr), []).append(
                        (fn.qualname, eff, lineno,
                         fn.qualname in reach))
        for (cls, attr), stores in sorted(per_attr.items()):
            threaded = [s for s in stores if s[3]]
            mainside = [s for s in stores if not s[3]]
            if not threaded or not mainside:
                continue
            common = set.intersection(*(s[1] for s in stores))
            if common:
                continue
            q, _, lineno, _ = threaded[0]
            others = sorted({f"{s[0]}:{s[2]}" for s in mainside})
            findings.append(Finding(
                rule="KME-L002", path=m.relpath, line=lineno, col=0,
                scope=q,
                message=(f"'self.{attr}' stored on a worker thread "
                         f"here and on the main thread at "
                         f"{', '.join(others[:3])} with no common "
                         f"lock"),
                snippet=line_of(m.relpath, lineno)))
    return findings
