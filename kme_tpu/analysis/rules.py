"""AST rule families for kme-lint (hot-path, determinism, tracer).

Every rule carries a stable ID (the baseline and the gate key on it)
and is scoped: hot-path rules fire only inside the pipelined submit
window (HOT_SCOPES), determinism rules only inside replay-affecting
functions (REPLAY_SCOPES), tracer rules only under engine/ and ops/
(the jit/Pallas surface). Scopes are named per file so a refactor that
moves a function out of the hot window stops linting it — the rule
follows the architecture, not the text.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from kme_tpu.analysis import Finding

# -- rule registry ----------------------------------------------------------

RULES: Dict[str, str] = {
    "KME-H001": "host sync (block_until_ready / device_get / "
                "np.asarray on device values / .item()) inside the "
                "pipelined submit window",
    "KME-H002": "blocking I/O (sleep, print, open, fsync, flush, "
                "subprocess) inside the pipelined submit window",
    "KME-D001": "wall clock (time.time/time_ns, datetime.now) in a "
                "replay-affecting path",
    "KME-D002": "nondeterminism source (random, np.random, uuid, "
                "os.urandom, secrets) in a replay-affecting path",
    "KME-T001": "Python-level branch on a traced value (if/while/assert "
                "over a jnp/lax expression) in engine/ or ops/",
    "KME-T002": "implicit dtype — array creation without dtype= (drifts "
                "to float64/int64 under x64) in engine/ or ops/",
    "KME-T003": "width-unstable dtype (dtype=int/float, astype(int/"
                "float), float64) in engine/ or ops/",
    "KME-L001": "lock-order cycle in the static acquisition graph",
    "KME-L002": "attribute mutated from multiple threads without a "
                "common lock",
    "KME-C001": "direct wall-clock/sleep call (time.time/monotonic/"
                "sleep/time_ns) in a clock-seamed sim-reachable "
                "function — go through the injected bridge/clock.py "
                "seam",
    "KME-E001": "wall clock / RNG in an event-identity path "
                "(telemetry/events.py) — event KEYS (src, seq, kind, "
                "detail) must be replay-deterministic bytes; only the "
                "advisory ts stamp may ride a clock, and only through "
                "the injected seam",
}

# -- scope tables -----------------------------------------------------------
#
# Hot scopes: the submit half of the double-buffered pipeline — between
# a batch's fetch and its device dispatch, any host sync or blocking
# I/O serializes the pipeline and shows up as measured_overlap_frac
# collapse. Collect-side functions (_collect_one, collect,
# _fetch_outputs) legitimately sync and are NOT listed.
HOT_SCOPES: Dict[str, Set[str]] = {
    "kme_tpu/bridge/service.py": {"_step_pipelined", "_parse_batch"},
    "kme_tpu/runtime/seqsession.py": {"submit", "_plan"},
    "kme_tpu/native/sched.py": {"plan_batch", "apply_placement",
                                "slice_windows"},
    # the mesh planner + elastic placement decision run per batch on
    # the host between dispatches; the MIGRATION executors
    # (_migrate/_maybe_rebalance) legitimately sync the state pytree
    # and are NOT listed, like the collect-side functions above.
    # Async dispatch (r14) adds the submit-side windows: the dispatch
    # planner, the per-shard stage+submit step, and the dependency
    # patcher all sit between queue pop and device dispatch — a host
    # sync there re-serializes the per-chip streams. The collect
    # barrier (_collect_merge/_dispatch_async walls) legitimately
    # syncs and is NOT listed.
    "kme_tpu/parallel/seqmesh.py": {"plan_windows", "plan_rebalance",
                                    "plan_dispatch",
                                    "_stage_and_dispatch",
                                    "_patch_shard"},
    # the front door's merge loop sits on the serving path of EVERY
    # group's consumer — a blocking call here stalls the global feed;
    # accept_frames is the binary front door itself (one C call per
    # batch — any blocking attr here re-taxes every ingress frame)
    "kme_tpu/bridge/front.py": {"merge_records", "merge_streams",
                                "accept_frames"},
    # the binary produce path batches its durable write into ONE
    # flush via _flush_log_lines (deliberately un-scoped: it is the
    # sanctioned batched exit point) — per-record blocking I/O
    # reappearing inside the loop is exactly the JSON-ingress tax
    # this path exists to remove
    "kme_tpu/bridge/broker.py": {"produce_frames"},
}

# Replay scopes: functions whose outputs must be bit-identical when a
# crash-resume replays the MatchIn tail — journal replay/derivation,
# checkpoint restore, and (epoch, out_seq) stamp regeneration. A wall
# clock or RNG here diverges the replay from the original run and the
# broker dedups the wrong records.
REPLAY_SCOPES: Dict[str, Set[str]] = {
    "kme_tpu/telemetry/journal.py": {
        "_resume_tail", "rewind_to_offset", "oracle_events",
        "batch_events", "canonical_lines", "iter_events",
        "read_events"},
    "kme_tpu/bridge/broker.py": {"_load_topic"},
    # the binary frame decoder feeds the broker's stored values (and
    # therefore the durable log + oracle replay): it must re-decode a
    # replayed buffer to bit-identical records, so no clock/RNG may
    # leak into the walk
    "kme_tpu/wire.py": {"decode_frame", "decode_frames",
                        "_check_frame_header"},
    "kme_tpu/bridge/service.py": {"_init_exactly_once", "_try_resume",
                                  # cross-shard transfer routing: the
                                  # MatchOut/Xfer split and the stamp
                                  # assignment must regenerate
                                  # identically on crash-replay
                                  "_produce_out", "_produce_xfer"},
    # the split IS the transfer regeneration path: a crash-replay
    # re-runs route_line over the MatchIn prefix and must emit the
    # byte-identical injected legs (same grants, same xids)
    "kme_tpu/bridge/front.py": {"route_line", "split",
                                "make_internal_transfer",
                                "make_internal_create"},
    "kme_tpu/runtime/checkpoint.py": {
        "load_session", "load_seq_session", "load_native",
        "load_oracle", "snapshot_extra", "oldest_retained_offset"},
    # the elastic placement decision must be RNG-free: a migration is
    # replayed as part of the batch sequence, and a random tie-break
    # would put lanes on different shards across original vs resumed
    # runs (harmless for MatchOut bytes, but it diverges the per-shard
    # telemetry and the planner's window stream — keep it deterministic)
    "kme_tpu/parallel/seqmesh.py": {"plan_rebalance"},
}

# Trace-identity scopes (KME-D00x, same determinism rules): trace ids
# are REPLAY-DERIVED identity — a crash-replay must re-mint the exact
# same id for the same order, and a post-mortem stitch re-derives them
# offline. A wall clock or RNG in any of these functions breaks the
# waterfall join silently (ids stop matching across replay segments),
# so the lint holds the line the tests can't see. Merged into
# replay_fns per file by _RuleVisitor.
TRACE_SCOPES: Dict[str, Set[str]] = {
    "kme_tpu/telemetry/dtrace.py": {
        "_tid_mix", "trace_id", "local_tid", "child_tid",
        "client_trace_id", "route_map", "collect_group_spans",
        "_spans_from_lat", "stitch"},
}

# Feed scopes (KME-D00x, same determinism rules): the market-data
# read path's REPLAY-PURITY surface (ISSUE 13). Book-delta derivation
# must be a pure function of the MatchOut stream — any two derivers at
# the same (group, out_seq) watermark must emit byte-identical frames,
# which is the entire failover story for the feed tier (a promoted
# deriver regenerates the dead one's frames exactly). A wall clock or
# RNG anywhere in the derivation, the frame codec, or the snapshot
# save/restore forks the frame stream silently. Merged into replay_fns
# per file by _RuleVisitor, like TRACE_SCOPES.
# X-ray scopes (KME-D00x, same determinism rules): time-travel
# materialization and live watchpoints (ISSUE 17). A watchpoint must
# be a pure function of (predicate, ledger-at-barrier): the SAME seeded
# run must produce the SAME hit set, and an offline `kme-xray eval` at
# the captured offset must re-fire — a wall clock or RNG anywhere in
# predicate parsing/evaluation or in the snapshot+replay walk forks
# live hits from their own repro commands. Merged into replay_fns per
# file by _RuleVisitor, like TRACE_SCOPES.
XRAY_SCOPES: Dict[str, Set[str]] = {
    "kme_tpu/telemetry/xray.py": {
        # offset-addressed materialization: anchor choice + replay
        "oldest_materializable", "_fetch_records", "_parse_replay",
        "_engine_from_snapshot", "materialize", "resolve_trace",
        # predicate grammar + evaluation (live AND offline paths)
        "parse_watch", "_cmp", "measure", "eval_predicate",
        "measure_engine", "eval_engine", "book_summary",
        # barrier-side observation (everything but the capture write)
        "seed", "observe_lines", "observe_events", "_repro_line",
        # bisection state projection + comparison
        "_journal_batches", "_batch_end_off", "_canon",
        "shadow_canon", "engine_canon", "state_diff",
        # cluster-cut accounting
        "_open_margin"},
}

FEED_SCOPES: Dict[str, Set[str]] = {
    "kme_tpu/feed/frames.py": {
        "_envelope", "encode_delta", "encode_tob", "encode_depth",
        "encode_snap_begin", "encode_snap_end", "encode_resync",
        "_check_feed_header", "decode_feed", "decode_feed_frames",
        "feed_frame_length"},
    "kme_tpu/feed/derive.py": {
        # BookState + canonical comparators
        "set_level", "get_level", "tob", "depth", "sids",
        "canonical_books", "books_from_oracle",
        # FeedDeriver: emission + mutation + snapshot state
        "_next_seq", "_frame", "_emit_delta", "_emit_tob",
        "_emit_depth", "_level_add", "_drop_resting", "_apply_out",
        "on_record", "on_line", "state", "from_state",
        # BookBuilder: the subscriber-side replay of the frame stream
        "_seq_ok", "_apply_image", "apply", "apply_buffer"},
    # the durable snapshot payload and the wire handover must restore /
    # serve bit-identically (file naming is offset-based, never
    # clock-based; frame seqs come from the deriver, never minted here)
    "kme_tpu/feed/snapshot.py": {
        "feed_snapshot_path", "_state_digest", "_load_one",
        "snapshot_frames"},
}

# Clock-seam scopes (KME-C001, ISSUE 19): functions the deterministic
# whole-cluster simulator (kme_tpu/sim/) reaches while it owns time.
# Each listed function received an injectable clock (bridge/clock.py)
# and must keep every wait/stamp/interval read on that seam: one direct
# ``time.sleep`` in a retry loop turns a reproducible seed into a
# wall-clock race, and a direct ``time.time_ns`` admission stamp forks
# the virtual-time latency attribution. ``perf_counter`` is deliberately
# NOT flagged — host profiling durations are observability, not
# behavior, and stay on the real clock by design (PROFILER_SCOPES
# below documents the same boundary for the profiling plane).
# ``run`` (service) is deliberately NOT listed: its serve.stuck /
# stall-drill branches block the real process on purpose, and the sim
# drives ``step()`` directly.
CLOCK_SCOPES: Dict[str, Set[str]] = {
    "kme_tpu/bridge/service.py": {
        "step", "_step_pipelined", "_process_batch", "_produce_retry",
        "_publish_batch", "_write_heartbeat"},
    "kme_tpu/bridge/broker.py": {"produce", "fetch"},
    "kme_tpu/bridge/replica.py": {"fetch", "run", "_write_heartbeat",
                                  "_promote"},
    "kme_tpu/bridge/tcp.py": {"_ats_for"},
}

# Event-identity scopes (KME-E001, ISSUE 20): the control-plane
# flight recorder's replay-determinism surface. A merged timeline is
# digested byte-for-byte (the sim's seventh verdict) and deduped on
# (src, seq) — so everything that BUILDS event identity (make/encode/
# order/dedup/digest) and everything that assigns the durable seq
# cursor (emit + the open/rescan paths) must be clock- and RNG-free.
# The one sanctioned clock touch is the ADVISORY ts stamp, and it must
# flow through the injected ``clock`` seam; the default-clock fallback
# in ``EventLog.__init__`` is the single grandfathered finding (the
# seam has to bottom out somewhere), held in LINT_BASELINE.json so any
# NEW wall read or RNG in these functions still gates. Unlike the
# D-family this rule also flags bare REFERENCES (``x = time.time``):
# smuggling the function object past the seam is the failure mode the
# injectable-clock design invites.
EVENTS_SCOPES: Dict[str, Set[str]] = {
    "kme_tpu/telemetry/events.py": {
        "make_event", "event_line", "order_key", "sort_events",
        "dedup_events", "merge_events", "timeline_digest",
        "emit", "__init__", "_open_live", "_seed_seq_from_rotated"},
}

# Profiler scopes (ISSUE 16): the continuous-profiling plane is
# DELIBERATELY outside every table above, and this entry documents the
# boundary so the exemption is a reviewed decision rather than an
# accident of omission.
#
#  - telemetry/tsdb.py appends, fsyncs, and rotates ON PURPOSE — it is
#    the durable history store, called only from the 1 Hz heartbeat
#    thread (serve/standby/feed) or a one-shot CLI exit path, never
#    from the submit half of the pipeline. Listing it in HOT_SCOPES
#    would flag its whole reason to exist.
#  - telemetry/profiler.py reads wall clocks and sleeps ON PURPOSE —
#    the sampler thread's time.sleep cadence and the capture files'
#    timestamps are the measurement, not state. Nothing here feeds
#    replay: TSDB samples are observability output, dedup'd by
#    sample_seq, and never re-derived on crash-resume, so REPLAY
#    determinism rules don't apply.
#
# The sanctioned coupling points back into scoped code are narrow and
# already covered: service._publish_batch / _write_heartbeat run on
# the telemetry thread (not HOT), and the TSDB append in the serve
# loop is fenced behind `self.tsdb is not None`. If a profiler call
# ever migrates into a HOT_SCOPES function, the existing hot-scope
# lint catches it at the call site — no profiler-side rule needed.
PROFILER_SCOPES: Dict[str, Set[str]] = {
    "kme_tpu/telemetry/tsdb.py": set(),
    "kme_tpu/telemetry/profiler.py": set(),
}

# Tracer scopes: whole directories — everything under them runs (or is
# staged to run) under jit/vmap/scan/pallas_call.
TRACED_DIRS = ("kme_tpu/engine/", "kme_tpu/ops/")

_HOST_SYNC_ATTRS = {"block_until_ready", "device_get", "item"}
_HOST_SYNC_NP = {"asarray", "array", "copy"}
_BLOCKING_CALLS = {
    ("time", "sleep"), ("os", "fsync"), ("os", "fdatasync"),
    ("subprocess", "run"), ("subprocess", "check_output"),
    ("subprocess", "Popen"), ("subprocess", "call"),
}
_BLOCKING_METHOD_ATTRS = {"write", "flush", "fsync", "sendall",
                          "recv", "readline"}
_WALLCLOCK = {("time", "time"), ("time", "time_ns"),
              ("time", "clock_gettime"), ("datetime", "now"),
              ("datetime", "utcnow"), ("datetime", "today")}
# the clock-seam family adds the interval/wait primitives the replay
# rule doesn't care about, and tolerates the repo's import aliases
# (``import time as _t`` / ``as _time``) — an alias must not launder a
# wall read past the seam
_CLOCK_HEADS = {"time", "_time", "_t"}
_CLOCK_TAILS = {"time", "time_ns", "clock_gettime", "monotonic",
                "monotonic_ns", "sleep"}
_RANDOM_MODULES = {"random", "secrets", "uuid"}
_IMPLICIT_CTORS = {"zeros", "ones", "empty", "full", "arange",
                   "linspace", "array", "asarray", "fromiter"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _RuleVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str, src_lines: List[str]) -> None:
        self.relpath = relpath
        self.lines = src_lines
        self.findings: List[Finding] = []
        self._scope: List[str] = []
        self.hot_fns = HOT_SCOPES.get(relpath, set())
        self.replay_fns = (REPLAY_SCOPES.get(relpath, set())
                           | TRACE_SCOPES.get(relpath, set())
                           | FEED_SCOPES.get(relpath, set())
                           | XRAY_SCOPES.get(relpath, set()))
        self.clock_fns = CLOCK_SCOPES.get(relpath, set())
        self.events_fns = EVENTS_SCOPES.get(relpath, set())
        self.traced = relpath.startswith(TRACED_DIRS)

    # -- bookkeeping ----------------------------------------------------

    def _scope_name(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def _in(self, table: Set[str]) -> bool:
        return any(name in table for name in self._scope)

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        self.findings.append(Finding(
            rule=rule, path=self.relpath, line=line,
            col=getattr(node, "col_offset", 0),
            scope=self._scope_name(), message=message,
            snippet=snippet))

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    def _visit_fn(self, node) -> None:
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- H/D families (call-shaped) -------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func) or ""
        head, _, tail = dotted.partition(".")
        if self._in(self.hot_fns):
            self._check_hot_call(node, dotted, head, tail)
        if self._in(self.replay_fns):
            self._check_replay_call(node, dotted, head, tail)
        if self._in(self.clock_fns):
            self._check_clock_call(node, dotted, head, tail)
        if self._in(self.events_fns):
            self._check_events_call(node, dotted, head, tail)
        if self.traced:
            self._visit_traced_call(node)
        self.generic_visit(node)

    def _check_hot_call(self, node, dotted, head, tail) -> None:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _HOST_SYNC_ATTRS:
            self._emit("KME-H001", node,
                       f"'{node.func.attr}()' forces a host/device "
                       f"sync inside the submit window")
            return
        if head in ("np", "numpy", "jnp") and tail in _HOST_SYNC_NP:
            self._emit("KME-H001", node,
                       f"'{dotted}()' materializes on host inside the "
                       f"submit window (device values block here)")
            return
        if dotted in ("jax.device_get",):
            self._emit("KME-H001", node,
                       "'jax.device_get()' inside the submit window")
            return
        if (head, tail) in _BLOCKING_CALLS or head == "subprocess":
            self._emit("KME-H002", node,
                       f"blocking call '{dotted}()' inside the submit "
                       f"window")
            return
        if dotted in ("print", "open", "input"):
            self._emit("KME-H002", node,
                       f"blocking I/O '{dotted}()' inside the submit "
                       f"window")
            return
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _BLOCKING_METHOD_ATTRS:
            self._emit("KME-H002", node,
                       f"blocking I/O method '.{node.func.attr}()' "
                       f"inside the submit window")

    def _check_replay_call(self, node, dotted, head, tail) -> None:
        if (head, tail) in _WALLCLOCK or dotted in (
                "datetime.datetime.now", "datetime.datetime.utcnow"):
            self._emit("KME-D001", node,
                       f"wall clock '{dotted}()' in a replay-affecting "
                       f"path (replay would diverge from the original "
                       f"run)")
            return
        if head in _RANDOM_MODULES or dotted.startswith(
                ("np.random", "numpy.random", "os.urandom")):
            self._emit("KME-D002", node,
                       f"nondeterminism source '{dotted}()' in a "
                       f"replay-affecting path")

    def _events_offender(self, dotted: str) -> Optional[str]:
        """The KME-E001 predicate, shared by the call and the bare-
        reference checks: a wall-clock or RNG dotted name."""
        head, _, tail = dotted.partition(".")
        if head in _CLOCK_HEADS and tail in _CLOCK_TAILS:
            return "wall clock"
        if dotted in ("datetime.datetime.now", "datetime.now",
                      "datetime.datetime.utcnow", "datetime.utcnow"):
            return "wall clock"
        if head in _RANDOM_MODULES or dotted.startswith(
                ("np.random", "numpy.random")) or dotted == "os.urandom":
            return "nondeterminism source"
        return None

    def _check_events_call(self, node, dotted, head, tail) -> None:
        kind = self._events_offender(dotted)
        if kind:
            self._emit("KME-E001", node,
                       f"{kind} '{dotted}()' in an event-identity "
                       f"path — event keys must replay "
                       f"byte-identically; stamp advisory ts through "
                       f"the injected clock seam")

    def _check_clock_call(self, node, dotted, head, tail) -> None:
        if head in _CLOCK_HEADS and tail in _CLOCK_TAILS:
            self._emit("KME-C001", node,
                       f"direct '{dotted}()' in a clock-seamed "
                       f"function — the simulator owns time here; use "
                       f"the injected clock (bridge/clock.py)")
        elif dotted in ("datetime.datetime.now",
                        "datetime.datetime.utcnow"):
            self._emit("KME-C001", node,
                       f"direct '{dotted}()' in a clock-seamed "
                       f"function — use the injected clock "
                       f"(bridge/clock.py)")

    # -- T family (engine/ops only) -------------------------------------

    def _test_is_traced(self, test: ast.AST) -> Optional[str]:
        """A jnp./lax./jax.-built expression used as a Python bool —
        under trace this raises ConcretizationTypeError (or silently
        constant-folds under np). Returns the offending dotted call."""
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                dotted = _dotted(sub.func) or ""
                head = dotted.split(".", 1)[0]
                if head in ("jnp", "lax") or dotted.startswith(
                        ("jax.numpy", "jax.lax")):
                    return dotted
        return None

    def _check_branch(self, node, test) -> None:
        if not self.traced:
            return
        dotted = self._test_is_traced(test)
        if dotted:
            kind = type(node).__name__.lower()
            self._emit("KME-T001", node,
                       f"Python-level {kind} on traced expression "
                       f"'{dotted}(...)' — use lax.cond/jnp.where "
                       f"(this either breaks under jit or silently "
                       f"constant-folds)")

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, node.test)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_branch(node, node.test)
        self.generic_visit(node)

    def _has_float_literal(self, node: ast.Call) -> bool:
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(a):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, float):
                    return True
        return False

    def _check_dtype_value(self, node: ast.AST, where: ast.AST) -> None:
        """dtype=int / dtype=float / dtype=np.float64 etc."""
        if isinstance(node, ast.Name) and node.id in ("int", "float",
                                                      "bool"):
            if node.id != "bool":
                self._emit("KME-T003", where,
                           f"width-unstable dtype '{node.id}' (int64/"
                           f"float64 under x64, int32 on some hosts) — "
                           f"name the width explicitly")
            return
        dotted = _dotted(node) or ""
        if dotted.endswith(("float64", "double", "intp", "int_",
                            "longlong")):
            self._emit("KME-T003", where,
                       f"'{dotted}' in device code — engine arrays are "
                       f"int32 (int64 only for money/oid paths, which "
                       f"spell jnp.int64 via the _I64 alias)")

    @staticmethod
    def _is_fresh_numeric(node: ast.AST) -> bool:
        """True when the expression builds fresh numeric data whose
        width the ctor's default dtype decides: int/float literals
        (not bool), unary minus on them, and list/tuple nests of
        them."""
        if isinstance(node, ast.Constant):
            return type(node.value) in (int, float)
        if isinstance(node, ast.UnaryOp) \
                and isinstance(node.op, (ast.USub, ast.UAdd)):
            return _RuleVisitor._is_fresh_numeric(node.operand)
        if isinstance(node, (ast.List, ast.Tuple)):
            return bool(node.elts) and all(
                _RuleVisitor._is_fresh_numeric(e) for e in node.elts)
        return False

    def _visit_traced_call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func) or ""
        head, _, tail = dotted.partition(".")
        # T002: jnp/np array constructors with no dtype= — the result
        # width depends on the x64 flag and the platform
        if head in ("np", "numpy", "jnp") and tail in _IMPLICIT_CTORS:
            has_dtype = any(kw.arg == "dtype" for kw in node.keywords)
            # the dtype rides positionally for most ctors: 2nd arg of
            # zeros/ones/empty/fromiter/array/asarray/arange(stop, dt),
            # 3rd of full(shape, fill, dt)
            if not has_dtype and tail in ("zeros", "ones", "empty",
                                          "fromiter", "array",
                                          "asarray") \
                    and len(node.args) >= 2:
                has_dtype = True
            if not has_dtype and tail == "full" and len(node.args) >= 3:
                has_dtype = True
            # array/asarray of an existing array is dtype-PRESERVING —
            # only fresh data (int/float literals, possibly nested in
            # lists/tuples) picks up the drifting default width
            if not has_dtype and tail in ("array", "asarray"):
                if not (node.args
                        and self._is_fresh_numeric(node.args[0])):
                    has_dtype = True
            if not has_dtype:
                self._emit("KME-T002", node,
                           f"'{dotted}()' without dtype= — defaults "
                           f"drift (float64/int64 under x64); pin the "
                           f"width")
        # T003: explicit width-unstable dtypes
        for kw in node.keywords:
            if kw.arg == "dtype":
                self._check_dtype_value(kw.value, node)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype" and node.args:
            self._check_dtype_value(node.args[0], node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.traced:
            dotted = _dotted(node) or ""
            if dotted in ("jnp.float64", "np.float64", "numpy.float64",
                          "jnp.double", "np.double"):
                self._emit("KME-T003", node,
                           f"'{dotted}' reference in device code "
                           f"(implicit float64 surface)")
        if self._in(self.events_fns):
            # KME-E001 flags bare references too: `clock or time.time`
            # hands the wall clock past the injected seam without a
            # single call-shaped node
            dotted = _dotted(node) or ""
            kind = self._events_offender(dotted)
            if kind:
                self._emit("KME-E001", node,
                           f"{kind} '{dotted}' referenced in an "
                           f"event-identity path — inject it through "
                           f"the clock seam instead")
        self.generic_visit(node)


def analyze_file(relpath: str, source: str) -> List[Finding]:
    """Run the H/D/T rule families over one file. L-family findings
    come from lockgraph.analyze_modules (cross-file)."""
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError as e:
        return [Finding(rule="KME-E000", path=relpath,
                        line=e.lineno or 1, col=e.offset or 0,
                        scope="<module>",
                        message=f"syntax error: {e.msg}", snippet="")]
    v = _RuleVisitor(relpath, source.splitlines())
    v.visit(tree)
    # one finding per (rule, line): the dtype checks can fire twice on
    # one expression (kw value + attribute walk)
    seen, out = set(), []
    for f in v.findings:
        key = (f.rule, f.line)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out
