"""`kme-lint` — run the repo-native rules (and ruff, when present).

Exit codes: 0 clean (or all findings grandfathered with --gate);
1 new findings in --gate mode, or any findings without --gate when
--strict is given; 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
from typing import List

from kme_tpu.analysis import (BASELINE_NAME, Finding, load_baseline,
                              repo_root, save_baseline, split_new)
from kme_tpu.analysis import lockgraph, rules


def _rule_rel(abspath: str, root: str) -> str:
    """The path the rule scope tables key on: repo-relative when the
    file is inside the repo, else the path from its last `kme_tpu/`
    component (so fixtures in a tmpdir still hit the right scopes)."""
    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
    if not rel.startswith(".."):
        return rel
    ap = abspath.replace(os.sep, "/")
    idx = ap.rfind("/kme_tpu/")
    return ap[idx + 1:] if idx >= 0 else ap.lstrip("/")


def _iter_py_files(root: str, paths: List[str]):
    """Yield (abspath, rule-path) for .py files under kme_tpu/ (or the
    explicit paths given)."""
    if paths:
        for p in paths:
            ap = os.path.abspath(p)
            if os.path.isdir(ap):
                for dirpath, dirnames, filenames in os.walk(ap):
                    dirnames[:] = [d for d in dirnames
                                   if d not in ("_build", "__pycache__")]
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            full = os.path.join(dirpath, fn)
                            yield full, _rule_rel(full, root)
            elif ap.endswith(".py"):
                yield ap, _rule_rel(ap, root)
        return
    pkg = os.path.join(root, "kme_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames
                       if d not in ("_build", "__pycache__")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                full = os.path.join(dirpath, fn)
                yield full, _rule_rel(full, root)


def run_rules(root: str, paths: List[str]) -> List[Finding]:
    findings: List[Finding] = []
    for full, rel in _iter_py_files(root, paths):
        try:
            with open(full, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            findings.append(Finding(
                rule="KME-E000", path=rel, line=0, col=0,
                scope="<io>", message=str(e), snippet=""))
            continue
        findings.extend(rules.analyze_file(rel, src))
    # lock-discipline rules always run over the full threaded surface:
    # the graph is only meaningful whole
    if not paths:
        findings.extend(lockgraph.analyze_modules(root))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def run_ruff(root: str) -> int:
    """Run ruff over kme_tpu/ if installed; returns its exit code, or
    0 with a note when unavailable (the CI lint job installs it)."""
    exe = shutil.which("ruff")
    if exe is None:
        print("kme-lint: ruff not installed; skipping generic lint "
              "(CI runs it)", file=sys.stderr)
        return 0
    proc = subprocess.run([exe, "check", "kme_tpu"], cwd=root)
    return proc.returncode


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="kme-lint",
        description="Repo-native static analysis for kme_tpu.")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: kme_tpu/; "
                         "lock rules only run on the default set)")
    ap.add_argument("--gate", action="store_true",
                    help="fail only on findings not in the baseline")
    ap.add_argument("--strict", action="store_true",
                    help="fail on ANY finding, ignoring the baseline")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline path (default: <root>/"
                         f"{BASELINE_NAME})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current findings as the baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON on stdout")
    ap.add_argument("--report", default=None,
                    help="also write the report to this file")
    ap.add_argument("--no-ruff", action="store_true",
                    help="skip the ruff pass")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, desc in sorted(rules.RULES.items()):
            print(f"{rid}  {desc}")
        return 0

    root = repo_root()
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    findings = run_rules(root, args.paths)

    if args.write_baseline:
        save_baseline(baseline_path, findings)
        print(f"kme-lint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    baseline = {}
    if args.gate and not args.strict:
        try:
            baseline = load_baseline(baseline_path)
        except ValueError as e:
            print(f"kme-lint: {e}", file=sys.stderr)
            return 2
    new, known = split_new(findings, baseline)
    shown = new if (args.gate and not args.strict) else findings

    lines = [f.render() for f in shown]
    if args.as_json:
        out = json.dumps(
            [{**f.__dict__, "fingerprint": f.fingerprint}
             for f in shown], indent=1)
        print(out)
    else:
        for ln in lines:
            print(ln)
    summary = (f"kme-lint: {len(findings)} finding(s)"
               + (f", {len(known)} grandfathered, {len(new)} new"
                  if args.gate and not args.strict else ""))
    print(summary)
    if args.report:
        with open(args.report, "w") as f:
            f.write("\n".join(lines + [summary]) + "\n")

    rc = 0
    if not args.no_ruff and not args.paths:
        rc = run_ruff(root)
    if args.strict and findings:
        return 1
    if args.gate and new:
        return 1
    return rc if rc == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
