"""Runtime lock-order recorder (``KME_LOCKCHECK=1``).

The static lock graph (lockgraph.py) over-approximates: it can't see
locks passed across modules or orders that only materialize under real
scheduling. This module validates the same discipline dynamically.
When installed (via ``kme_tpu/__init__`` on ``KME_LOCKCHECK=1``), it
replaces ``threading.Lock``/``threading.RLock`` with factories that
return tracking proxies. Each proxy is named by its creation site
(``file.py:line``); a thread-local stack records what each thread
holds, and every acquisition with locks already held contributes
(held -> acquired) edges to a global order graph. An **inversion** —
both (A, B) and (B, A) observed, A != B — is a potential deadlock: two
threads can each take their first lock and block on the other's.

The tier-1 suite runs with this active when ``KME_LOCKCHECK=1``; a
session-scoped fixture in tests/conftest.py calls ``assert_clean()``
at teardown, so any inversion introduced by new code fails CI.

Proxies intentionally do NOT expose ``_release_save`` /
``_acquire_restore`` / ``_is_owned``: ``threading.Condition`` probes
for those and, finding none, falls back to plain ``acquire``/
``release`` on the proxy — which we track. ``wait()`` therefore
correctly pops the lock from the held stack while waiting.

Zero overhead when not installed; tracking is a dict update per
contested acquisition when it is. Never enable in production.
"""

from __future__ import annotations

import _thread
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_real_lock = _thread.allocate_lock        # pre-patch factory
_state_lock = _thread.allocate_lock()     # guards the tables below
_edges: Dict[Tuple[str, str], Tuple[str, str]] = {}   # (a,b) -> stacks
_sites: Dict[str, int] = {}               # creation site -> count
_installed = False
_orig_lock = None
_orig_rlock = None
_tls = threading.local()


def _held() -> List[str]:
    try:
        return _tls.stack
    except AttributeError:
        _tls.stack = []
        return _tls.stack


def _creation_site() -> str:
    f = sys._getframe(2)
    # walk out of this module and the threading module
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(("lockcheck.py", "threading.py")):
            break
        f = f.f_back
    if f is None:
        return "<unknown>"
    rel = os.path.basename(os.path.dirname(f.f_code.co_filename))
    name = os.path.basename(f.f_code.co_filename)
    return f"{rel}/{name}:{f.f_lineno}"


class _TrackedLock:
    """Wraps a raw lock; records acquisition order by creation site."""

    __slots__ = ("_lk", "_name", "_reentrant", "_owner", "_depth")

    def __init__(self, reentrant: bool = False,
                 name: Optional[str] = None) -> None:
        self._lk = _real_lock()
        self._reentrant = reentrant
        self._owner: Optional[int] = None
        self._depth = 0
        if name is None:
            site = _creation_site()
            with _state_lock:
                n = _sites.get(site, 0)
                _sites[site] = n + 1
            name = site if n == 0 else f"{site}#{n}"
        self._name = name

    # -- the tracked core ----------------------------------------------

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        me = _thread.get_ident()
        if self._reentrant and self._owner == me:
            self._depth += 1
            return True
        if timeout == -1:
            got = self._lk.acquire(blocking)
        else:
            got = self._lk.acquire(blocking, timeout)
        if got:
            self._owner = me
            self._depth = 1
            stack = _held()
            if stack:
                snap = " -> ".join(stack + [self._name])
                with _state_lock:
                    for h in stack:
                        if h != self._name:
                            _edges.setdefault(
                                (h, self._name),
                                (snap, threading.current_thread().name))
            stack.append(self._name)
        return got

    def release(self) -> None:
        me = _thread.get_ident()
        if self._reentrant:
            if self._owner != me:
                raise RuntimeError(
                    "cannot release un-acquired lock")
            self._depth -= 1
            if self._depth:
                return
        self._owner = None
        stack = _held()
        if self._name in stack:
            # remove the innermost occurrence
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == self._name:
                    del stack[i]
                    break
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def _is_owned(self) -> bool:
        # threading.Condition probes for this by name. Without it, the
        # fallback does acquire(False)/release — which REENTERS a
        # reentrant proxy the caller already owns and concludes
        # not-owned, making Condition.wait() raise spuriously.
        # (_release_save/_acquire_restore stay intentionally absent so
        # Condition falls back to plain acquire/release, which we
        # track.)
        if self._reentrant:
            return self._owner == _thread.get_ident()
        return self._lk.locked()

    def __repr__(self) -> str:
        kind = "RLock" if self._reentrant else "Lock"
        return f"<Tracked{kind} {self._name}>"


def _make_lock():
    return _TrackedLock(reentrant=False)


def _make_rlock():
    return _TrackedLock(reentrant=True)


def install() -> None:
    """Patch ``threading.Lock``/``RLock``. Locks created BEFORE this
    runs are untracked, so call it before importing modules that
    allocate locks at import or construction time."""
    global _installed, _orig_lock, _orig_rlock
    if _installed:
        return
    _orig_lock = threading.Lock
    _orig_rlock = threading.RLock
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    _installed = True
    import atexit
    atexit.register(_atexit_report)


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _orig_lock
    threading.RLock = _orig_rlock
    _installed = False


def enabled() -> bool:
    return _installed


def reset() -> None:
    with _state_lock:
        _edges.clear()


def edges() -> Set[Tuple[str, str]]:
    with _state_lock:
        return set(_edges)


def inversions() -> List[Tuple[str, str, str, str]]:
    """(lock_a, lock_b, witness_ab, witness_ba) for every pair
    observed in both orders."""
    with _state_lock:
        snap = dict(_edges)
    out = []
    for (a, b), (wit_ab, _) in snap.items():
        if a < b and (b, a) in snap:
            out.append((a, b, wit_ab, snap[(b, a)][0]))
    return out


def report() -> str:
    inv = inversions()
    lines = [f"lockcheck: {len(edges())} distinct acquisition edges, "
             f"{len(inv)} inversion(s)"]
    for a, b, wab, wba in inv:
        lines.append(f"  INVERSION between {a} and {b}")
        lines.append(f"    order 1: {wab}")
        lines.append(f"    order 2: {wba}")
    return "\n".join(lines)


def assert_clean() -> None:
    inv = inversions()
    if inv:
        raise AssertionError("lock-order inversions observed:\n"
                             + report())


def _atexit_report() -> None:
    if inversions():
        print(report(), file=sys.stderr)
