"""Device-side primitive ops: java-exact int64 bit twiddling and dense
associative tables. Everything here is jit-/vmap-safe (static shapes, no
data-dependent Python control flow)."""
