"""Dense associative tables — the device equivalent of the reference's
RocksDB KeyValueStores (KProcessor.java:30-49).

The reference's five stores are hash maps behind JNI; on TPU the same
get/put/delete contract is a masked vector compare over a fixed-capacity
slot array: lookup is one `==` broadcast + argmax (VPU-friendly, O(1)
depth), insert picks the first free slot, delete clears the used bit.
Fixed capacity is the one semantic difference — overflow is reported via
a sticky flag the host checks per batch (SURVEY.md §7 H2: overflow policy
is explicit, not silent).

Keys are int64 (single or pair — the reference's UUID keys are two longs).
Slot 0 is a real slot; "not found" is the separate `found` boolean, so
callers must gate every gather/scatter on it.
"""

from __future__ import annotations

import kme_tpu._jaxsetup  # noqa: F401
import jax.numpy as jnp


def find(keys, used, k):
    """Index of the used slot holding key `k` -> (idx:int32, found:bool).

    Keys are unique among used slots (put_idx never duplicates), so argmax
    over the hit mask is THE slot.
    """
    hit = jnp.logical_and(used, keys == k)
    return jnp.argmax(hit).astype(jnp.int32), jnp.any(hit)


def find2(keys_a, keys_b, used, ka, kb):
    """Pair-key lookup (UUID-keyed stores: positions (aid,sid),
    KProcessor.java:418-425)."""
    hit = jnp.logical_and(used, jnp.logical_and(keys_a == ka, keys_b == kb))
    return jnp.argmax(hit).astype(jnp.int32), jnp.any(hit)


def alloc(used):
    """First free slot -> (idx:int32, ok:bool). ok=False means the table
    is full (capacity overflow — host-visible error)."""
    free = jnp.logical_not(used)
    return jnp.argmax(free).astype(jnp.int32), jnp.any(free)


def put_idx(keys, used, k):
    """Slot to write key `k` into: the existing slot if present, else a
    fresh one -> (idx:int32, ok:bool). Mirrors map.put upsert semantics."""
    idx, found = find(keys, used, k)
    fresh, ok = alloc(used)
    return jnp.where(found, idx, fresh), jnp.logical_or(found, ok)


def put2_idx(keys_a, keys_b, used, ka, kb):
    """Pair-key upsert slot -> (idx:int32, ok:bool)."""
    idx, found = find2(keys_a, keys_b, used, ka, kb)
    fresh, ok = alloc(used)
    return jnp.where(found, idx, fresh), jnp.logical_or(found, ok)


def delete_at(used, idx, present):
    """Clear slot `idx` when `present`; no-op otherwise. The slot's other
    columns may be left stale — `used` alone defines liveness."""
    return used.at[idx].set(jnp.logical_and(used[idx], jnp.logical_not(present)))
