"""Java-exact 64-bit bit ops and price-bitmap scans, on device.

The reference packs each book side's 126 price levels into two Java longs
(UUID msb/lsb, split at price 63 — KProcessor.java:391-394) and finds the
best price with double-precision log10 math (KProcessor.java:371-377,
quirk Q7). The oracle (kme_tpu/oracle/javalong.py) reproduces the float
formulas verbatim; here the same *semantics* are reproduced with exact
integer ops, which is both faster on TPU (no float64 emulation) and safe
against libm differences between XLA and the JVM:

- For the min-scan the float formula is exact on every reachable input
  (single-set-bit values; proven by tests/test_javalong.py), so an integer
  lowest-set-bit is identical.
- For the max-scan the float formula *overshoots by one* on dense values
  near the top of a 2^t..2^(t+1) range (the reference then NPEs on the
  missing bucket — oracle's ReferenceCrash). The exact overshoot frontier
  is precomputed per top-bit position with the host's math.log10 (the same
  IEEE-754 doubles the oracle uses), so the device returns bit-identical
  scan results including the overshot ones.
- Negative/zero inputs follow Java's (int) casts of NaN/-Infinity
  (0 / Integer.MIN_VALUE), as in javalong._java_int_of_log_ratio.

All shifts mask the count to 6 bits like Java long shifts.
"""

from __future__ import annotations

import kme_tpu._jaxsetup  # noqa: F401  (jax_enable_x64)
import jax
import jax.numpy as jnp
import numpy as np

from kme_tpu.oracle.javalong import _java_int_of_log_ratio as _host_int_log_ratio

INT32_MIN = -(1 << 31)
_I64 = jnp.int64


def _compute_overshoot_thresholds() -> np.ndarray:
    """For each top-bit t in 0..62, the smallest v in [2^t, 2^(t+1)) whose
    float last-set-bit scan returns t+1 instead of t; -1 if none.

    int(log10(v)/log10(2)) is monotone non-decreasing in v (log10 is
    monotone and IEEE double log10 preserves that), so binary search finds
    the exact frontier; the boundary is verified exhaustively ±64 around
    the found threshold.
    """
    thresholds = np.full(63, -1, dtype=np.int64)
    for t in range(63):
        lo, hi = 1 << t, (1 << (t + 1)) - 1
        if _host_int_log_ratio(hi) <= t:
            continue  # never overshoots in this range
        # first v with ratio >= t+1
        a, b = lo, hi
        while a < b:
            mid = (a + b) // 2
            if _host_int_log_ratio(mid) > t:
                b = mid
            else:
                a = mid + 1
        thr = a
        for v in range(max(lo, thr - 64), min(hi, thr + 64) + 1):
            expect = t + 1 if v >= thr else t
            assert _host_int_log_ratio(v) == expect, (t, v)
        thresholds[t] = thr
    return thresholds


# 63-entry table, computed once at import (63 binary searches, ~µs each).
# Kept as numpy: jnp indexing constant-folds it under jit, and importing
# this module stays free of JAX backend initialization.
_OVERSHOOT = _compute_overshoot_thresholds()


def jshl1(k):
    """Java `1L << k`: count masked to 6 bits; 1<<63 wraps negative."""
    return jnp.left_shift(jnp.asarray(1, _I64), jnp.bitwise_and(k, 63).astype(_I64))


def jget_bit(n, k):
    """KProcessor.java:406-408 — `1L == ((n >> k) & 1L)`, arithmetic shift."""
    shifted = jnp.right_shift(n.astype(_I64), jnp.bitwise_and(k, 63).astype(_I64))
    return jnp.bitwise_and(shifted, 1) == 1


def jset_bit(n, k):
    """KProcessor.java:410-412 — `n | (1L << k)`."""
    return jnp.bitwise_or(n.astype(_I64), jshl1(k))


def junset_bit(n, k):
    """KProcessor.java:414-416 — `n & ~(1L << k)`."""
    return jnp.bitwise_and(n.astype(_I64), jnp.bitwise_not(jshl1(k)))


def top_bit(v):
    """floor(log2(v)) for v > 0 (int64), via smear + popcount."""
    v = v.astype(_I64)
    for s in (1, 2, 4, 8, 16, 32):
        v = jnp.bitwise_or(v, jnp.right_shift(v, s))
    return (jax.lax.population_count(v) - 1).astype(jnp.int32)


def first_set_bit_pos(n):
    """javalong.first_set_bit_pos_float, exactly (KProcessor.java:371-373).

    v = n & -n is a single set bit; the float formula is exact there
    (test_javalong), so the answer is popcount(v-1). Java cast quirks:
    v < 0 (bit 63) -> 0, n == 0 -> Integer.MIN_VALUE.
    """
    n = n.astype(_I64)
    v = jnp.bitwise_and(n, -n)  # int64 two's-complement wrap == jand(n, jneg(n))
    pos = jax.lax.population_count(v - 1).astype(jnp.int32)
    out = jnp.where(v < 0, jnp.int32(0), pos)
    return jnp.where(n == 0, jnp.int32(INT32_MIN), out)


def last_set_bit_pos(n):
    """javalong.last_set_bit_pos_float, exactly (KProcessor.java:375-377),
    including the Q7 overshoot (returns top+1 past the per-top-bit float
    frontier — the caller's bucket lookup then misses, as on the JVM)."""
    n = n.astype(_I64)
    t = top_bit(jnp.where(n > 0, n, jnp.asarray(1, _I64)))
    thr = jnp.asarray(_OVERSHOOT, _I64)[jnp.clip(t, 0, 62)]
    over = jnp.logical_and(thr >= 0, n >= thr)
    pos = t + over.astype(jnp.int32)
    out = jnp.where(n < 0, jnp.int32(0), pos)
    return jnp.where(n == 0, jnp.int32(INT32_MIN), out)


# ---------------------------------------------------------------------------
# Book bitmap helpers (msb carries prices 63..125 at offset price-63,
# lsb carries 0..62; bit 63 of lsb unused in the valid domain — Q8 — but
# reachable via negative prices, which the shift masking handles like Java).

def book_min_price(msb, lsb):
    """getMinPriceBucketPointer (KProcessor.java:359-363)."""
    empty = jnp.logical_and(lsb == 0, msb == 0)
    from_msb = first_set_bit_pos(msb) + 63
    from_lsb = first_set_bit_pos(lsb)
    return jnp.where(empty, jnp.int32(-1),
                     jnp.where(lsb == 0, from_msb, from_lsb))


def book_max_price(msb, lsb):
    """getMaxPriceBucketPointer (KProcessor.java:365-369)."""
    empty = jnp.logical_and(lsb == 0, msb == 0)
    from_lsb = last_set_bit_pos(lsb)
    from_msb = last_set_bit_pos(msb) + 63
    return jnp.where(empty, jnp.int32(-1),
                     jnp.where(msb == 0, from_lsb, from_msb))


def book_check_bit(msb, lsb, price):
    """checkBit (KProcessor.java:391-394): split at price < 63."""
    return jnp.where(price < 63, jget_bit(lsb, price), jget_bit(msb, price - 63))


def book_with_bit_set(msb, lsb, price):
    """getWithBitSet (KProcessor.java:396-399) -> (msb, lsb)."""
    lo = price < 63
    new_lsb = jnp.where(lo, jset_bit(lsb, price), lsb)
    new_msb = jnp.where(lo, msb, jset_bit(msb, price - 63))
    return new_msb, new_lsb


def book_with_bit_unset(msb, lsb, price):
    """getWithBitUnset (KProcessor.java:401-404) -> (msb, lsb)."""
    lo = price < 63
    new_lsb = jnp.where(lo, junset_bit(lsb, price), lsb)
    new_msb = jnp.where(lo, msb, junset_bit(msb, price - 63))
    return new_msb, new_lsb


def bucket_key(book_key, price):
    """getBucketPointer (KProcessor.java:379-381): (key << 8) | (long)price
    with Java wrap; a negative price sign-extends and floods the high bits,
    exactly as on the JVM."""
    shifted = jnp.left_shift(book_key.astype(_I64), 8)
    return jnp.bitwise_or(shifted, price.astype(_I64))
