"""Pallas row-DMA: in-place per-lane row updates of big HBM state.

The lane engine's position state is (lanes x accounts) — ~16MB at the
bench shapes — but each scan step touches only the W active lanes' rows.
XLA:TPU scatter rewrites the WHOLE array per step (~1us/MB — measured
~24us/step at S=1024, A=2048, the dominant term of the round-3 step
profile, artifacts/profile_r03_summary.md). These kernels replace that
with true in-place row updates:

  gather_lane_rows:  DMA the W rows into a small (W, SUB, 128) block.
  scatter_lane_rows: DMA updated rows back, aliased in place
                     (input_output_aliases), skipping the scrap lane.

Measured on the v5e chip (scripts/exp_pallas_rowdma.py): 2.7us/step for
a full gather+update+scatter round vs 24.1us for the flat scatter —
including the s64 join/split (below) and the one-hot block update.

Backend constraints that shaped the design (all hit on the real chip):
- the X64-rewrite pass refuses s64 pallas_call operands, so everything
  crossing the kernel boundary is int32; 64-bit state is stored as
  PLANAR lo/hi int32 halves and joined to real s64 only on the small
  (W, A) blocks (join64/split64) where XLA's x64 emulation handles it;
- Mosaic memref indices must be 32-bit (np.int32 everywhere);
- a 2D VMEM ref cannot be sliced to one sublane row, so rows are shaped
  (SUB, 128) tiles and the state array is (S, SUB, 128).

On CPU (the test backend) the same kernels run under
``interpret=True`` — the kernel logic itself is what the parity suite
exercises, not a shadow implementation.
"""

from __future__ import annotations

import numpy as np

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LN = 128  # minor (lane) dim of every row tile


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _i32(x) -> np.int32:
    return np.int32(x)


def row_shape(width: int) -> tuple:
    """(SUB, LN) tile shape for a row of `width` int32 elements."""
    if width % LN != 0:
        raise ValueError(f"row width {width} must be a multiple of {LN}")
    return width // LN, LN


def join64(lo: jax.Array, hi: jax.Array) -> jax.Array:
    """Reassemble s64 from planar int32 halves (small blocks only)."""
    return (lo.astype(jnp.int64) & 0xFFFFFFFF) | (hi.astype(jnp.int64) << 32)


def split64(v: jax.Array) -> tuple:
    """s64 -> (lo, hi) int32 halves."""
    return (v & 0xFFFFFFFF).astype(jnp.int32), (v >> 32).astype(jnp.int32)


def pack64_np(flat64: np.ndarray, lanes: int) -> np.ndarray:
    """Host-side: (lanes, A) or (lanes*A,) s64 -> (lanes, SUB, LN)
    planar i32 [lo | hi] rows (checkpoint restore, state import). THE
    one definition of the planar layout on the host side — keep the
    device kernels, this packer and unpack64_np in lockstep."""
    v = np.asarray(flat64, np.int64).reshape(lanes, -1)
    lo = (v & 0xFFFFFFFF).astype(np.uint32).astype(np.int32)
    hi = (v >> 32).astype(np.int32)
    return np.concatenate([lo, hi], axis=1).reshape(
        (lanes,) + row_shape(2 * v.shape[1]))


def unpack64_np(rows: np.ndarray, lanes: int) -> np.ndarray:
    """Inverse of pack64_np: planar i32 rows -> (lanes, A) s64."""
    v = np.asarray(rows, np.int32).reshape(lanes, -1)
    A = v.shape[1] // 2
    return ((v[:, :A].astype(np.int64) & 0xFFFFFFFF)
            | (v[:, A:].astype(np.int64) << 32))


def join_rows(rows: jax.Array) -> jax.Array:
    """(W, SUB, LN) planar i32 rows -> (W, A) s64 block."""
    W = rows.shape[0]
    v = rows.reshape(W, -1)
    A = v.shape[1] // 2
    return join64(v[:, :A], v[:, A:])


def split_rows(blk: jax.Array) -> jax.Array:
    """(W, A) s64 block -> (W, SUB, LN) planar i32 rows."""
    W, A = blk.shape
    lo, hi = split64(blk)
    return jnp.concatenate([lo, hi], axis=1).reshape(
        (W,) + row_shape(2 * A))


def _gather_kernel(W):
    def kernel(lanes_ref, flat_ref, out_ref, sem):
        for w in range(W):
            pltpu.make_async_copy(
                flat_ref.at[lanes_ref[_i32(w)]],
                out_ref.at[_i32(w)], sem.at[_i32(w)]).start()
        for w in range(W):
            pltpu.make_async_copy(
                flat_ref.at[lanes_ref[_i32(w)]],
                out_ref.at[_i32(w)], sem.at[_i32(w)]).wait()

    return kernel


def _scatter_kernel(W, skip_lane):
    def kernel(lanes_ref, flat_ref, rows_ref, out_ref, sem):
        # out_ref aliases flat_ref in place. The scrap lane (padding
        # slots; may repeat within a step) is skipped outright — real
        # lanes are distinct by the scheduler's one-message-per-lane
        # step invariant, so every started DMA has a private target.
        for w in range(W):
            @pl.when(lanes_ref[_i32(w)] != _i32(skip_lane))
            def _():
                pltpu.make_async_copy(
                    rows_ref.at[_i32(w)],
                    out_ref.at[lanes_ref[_i32(w)]],
                    sem.at[_i32(w)]).start()
        for w in range(W):
            @pl.when(lanes_ref[_i32(w)] != _i32(skip_lane))
            def _():
                pltpu.make_async_copy(
                    rows_ref.at[_i32(w)],
                    out_ref.at[lanes_ref[_i32(w)]],
                    sem.at[_i32(w)]).wait()

    return kernel


def gather_lane_rows(flat: jax.Array, lanes: jax.Array) -> jax.Array:
    """flat: (S, SUB, LN) i32 in HBM; lanes: (W,) i32 -> (W, SUB, LN)."""
    S, SUB, ln = flat.shape
    (W,) = lanes.shape
    return pl.pallas_call(
        _gather_kernel(W),
        out_shape=jax.ShapeDtypeStruct((W, SUB, ln), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.SemaphoreType.DMA((W,))],
        interpret=_interpret(),
    )(lanes.astype(jnp.int32), flat)


def scatter_lane_rows(flat: jax.Array, lanes: jax.Array,
                      rows: jax.Array, skip_lane: int) -> jax.Array:
    """Write rows back into flat at `lanes`, IN PLACE (aliased); rows of
    `skip_lane` are dropped. Returns the updated flat array."""
    S, SUB, ln = flat.shape
    (W,) = lanes.shape
    return pl.pallas_call(
        _scatter_kernel(W, skip_lane),
        out_shape=jax.ShapeDtypeStruct(flat.shape, jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((W,))],
        input_output_aliases={1: 0},
        interpret=_interpret(),
    )(lanes.astype(jnp.int32), flat, rows.astype(jnp.int32))
