"""Small shared host-side utilities."""

from __future__ import annotations


def async_prefetch(values) -> None:
    """Start device->host copies for every array in `values` without
    blocking — np.asarray afterwards finds the bytes already in flight.
    Non-arrays (or older jax without the API) are skipped."""
    for v in values:
        try:
            v.copy_to_host_async()
        except AttributeError:
            pass


def pow2_bucket(n: int, lo: int = 64) -> int:
    """Round up to a power-of-two bucket (bounds XLA recompiles for
    shape-dependent host-side slicing/padding)."""
    b = lo
    while b < n:
        b *= 2
    return b
