"""Serial device replica of the reference matching engine.

This is build-plan step 2 (SURVEY.md §7): the whole of
`KProcessor.MatchingEngine` (/root/reference/src/main/java/KProcessor.java:63-445)
as ONE jitted `lax.scan` over a micro-batch of wire messages, processing
strictly in arrival order (the reference's single-StreamThread semantics,
SURVEY.md §2.3) with every store replaced by a dense associative table on
device (ops/tables.py) and every bitmap/bucket codec replaced by the
java-exact integer ops (ops/bits.py).

Semantics contract: for any message stream whose price/size fields fit in
int32 and ids in int64 (the Jackson-parseable envelope — out-of-range
values kill the reference's deserializer), the output stream equals
`kme_tpu.oracle.OracleEngine` byte for byte, in both compat modes,
including the quirk ledger Q1..Q11 (SURVEY.md §2.5). Paths where the
reference *dies* (NPE crashes, the Q4 infinite loop) surface as a sticky
per-batch error code at the offending message index instead of an
exception; the host wrapper truncates there and raises, mirroring the
oracle's ReferenceCrash/ReferenceHang.

Capacity is the one new degree of freedom (H2/H3): tables are fixed-size
and each message can emit at most `max_events` fills; exhaustion raises a
distinct error code (the reference's stores/lists are unbounded).

Design notes (TPU-first):
- No data-dependent Python control flow: dispatch is `lax.switch` over
  dense op codes, the match loop is `lax.while_loop` bounded by the fill
  buffer, stores are O(1)-depth masked vector compares (VPU work).
- The scan carries the full store pytree; buffers are donated by the
  host wrapper so state stays device-resident across batches.
- All arithmetic is int32/int64 with Java wrap semantics (hardware
  two's-complement — no float in the engine path).


ROLE (round 5): this engine is NOT a serving path. Java-mode serving
runs on the seq kernel (engine/seq.py compat='java', ~100x faster) or
the native C++ engine; this replica's remaining job is CROSS-EVIDENCE —
a third, structurally independent implementation of the quirk-exact
semantics that the test suite pins against the oracle, so a bug in the
seq kernel's java mode and a matching bug in the oracle cannot hide
each other.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

from kme_tpu import opcodes as op
from kme_tpu.ops import bits, tables
from kme_tpu.wire import OrderMsg, OutRecord

_I64 = jnp.int64
_I32 = jnp.int32

# Padding marker for partial batches (explicit `pad` lane flag, so every
# int32 wire action stays representable): no state change, no output.
NOP_PAD = -(1 << 31)  # conventional action value for pad lanes (flag rules)

# Error codes (sticky per batch; 0 = ok)
ERR_OK = 0
ERR_HANG = 1          # Q4 removeAllOrders infinite loop (ReferenceHang)
ERR_CRASH = 2         # reference NPE / death (ReferenceCrash / KeyError)
ERR_TABLE_FULL = 3    # store capacity exhausted (device-only envelope)
ERR_EVENTS_FULL = 4   # fill buffer exhausted (device-only envelope)

_ERR_NAMES = {
    ERR_HANG: "reference-hang (Q4 removeAllOrders loop)",
    ERR_CRASH: "reference-crash (NPE path)",
    ERR_TABLE_FULL: "device store capacity exhausted",
    ERR_EVENTS_FULL: "device fill-event buffer exhausted",
}


class DeviceParityError(RuntimeError):
    """Raised by the host wrapper when the device engine flags an error.

    `index` is the position (within the process_batch call) of the message
    on which the reference would have died or the device ran out of
    capacity; records for earlier messages are still valid and were
    emitted."""

    def __init__(self, code: int, index: int,
                 records: Optional[List[List["OutRecord"]]] = None) -> None:
        self.code = int(code)
        self.index = int(index)
        self.records = records or []  # per-message records before death
        super().__init__(
            f"device engine error at message {index}: "
            f"{_ERR_NAMES.get(self.code, self.code)}")


@dataclasses.dataclass(frozen=True)
class ParityCaps:
    """Static table capacities (one XLA program per distinct value)."""

    balances: int = 64        # AB — accounts
    positions: int = 4096     # PB — (aid,sid) pairs incl. Q11 garbage keys
    books: int = 64           # BB — 2 per symbol
    buckets: int = 1024       # KB — occupied price levels
    orders: int = 8192        # OB — resting orders
    max_events: int = 64      # E — fill events per message (2/trade)
    batch: int = 256          # T — scan steps per device dispatch


def make_state(caps: ParityCaps) -> Dict[str, jax.Array]:
    """Fresh empty store pytree (the reference's five empty stores)."""
    def z(n, dt):
        return jnp.zeros((n,), dt)

    return {
        "bal_key": z(caps.balances, _I64),
        "bal_val": z(caps.balances, _I64),
        "bal_used": z(caps.balances, bool),
        # positions: key UUID(aid, sid) -> value UUID(amount, available)
        # (KProcessor.java:418-444); Q11 garbage keys live here too.
        "pos_ka": z(caps.positions, _I64),
        "pos_ks": z(caps.positions, _I64),
        "pos_amt": z(caps.positions, _I64),
        "pos_avail": z(caps.positions, _I64),
        "pos_used": z(caps.positions, bool),
        # books: signed-sid key -> 126-bit bitmap in (msb, lsb)
        "book_key": z(caps.books, _I64),
        "book_msb": z(caps.books, _I64),
        "book_lsb": z(caps.books, _I64),
        "book_used": z(caps.books, bool),
        # buckets: (book_key<<8)|price -> (first oid, last oid)
        "bkt_key": z(caps.buckets, _I64),
        "bkt_first": z(caps.buckets, _I64),
        "bkt_last": z(caps.buckets, _I64),
        "bkt_used": z(caps.buckets, bool),
        # orders: oid -> Order record (intrusive doubly-linked list via
        # next/prev + nullability flags, KProcessor.java:448-475)
        "ord_oid": z(caps.orders, _I64),
        "ord_action": z(caps.orders, _I32),
        "ord_aid": z(caps.orders, _I64),
        "ord_sid": z(caps.orders, _I64),
        "ord_price": z(caps.orders, _I32),
        "ord_size": z(caps.orders, _I32),
        "ord_next": z(caps.orders, _I64),
        "ord_next_has": z(caps.orders, bool),
        "ord_prev": z(caps.orders, _I64),
        "ord_prev_has": z(caps.orders, bool),
        "ord_used": z(caps.orders, bool),
        # sticky error, carried ACROSS batches so pipelined dispatches
        # (several batches queued before any fetch) stay frozen after a
        # reference-death point exactly like per-batch dispatch would
        "err": jnp.zeros((), _I32),
    }


# ---------------------------------------------------------------------------
# small store helpers. Every mutator threads (state, err); err is sticky
# and mutators become no-ops once err != 0 (the oracle raises immediately;
# keeping later writes out preserves "state at death" comparability).

def _guard(err, new_err_cond, code):
    return jnp.where((err == ERR_OK) & new_err_cond, jnp.int32(code), err)


def _sel(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def _bal_get(st, aid):
    idx, found = tables.find(st["bal_key"], st["bal_used"], aid)
    return st["bal_val"][idx], found


def _bal_put(st, err, aid, val):
    idx, ok = tables.put_idx(st["bal_key"], st["bal_used"], aid)
    err = _guard(err, ~ok, ERR_TABLE_FULL)
    do = err == ERR_OK
    st = dict(st)
    st["bal_key"] = jnp.where(do, st["bal_key"].at[idx].set(aid), st["bal_key"])
    st["bal_val"] = jnp.where(do, st["bal_val"].at[idx].set(val), st["bal_val"])
    st["bal_used"] = jnp.where(do, st["bal_used"].at[idx].set(True), st["bal_used"])
    return st, err


def _pos_get(st, ka, ks):
    idx, found = tables.find2(st["pos_ka"], st["pos_ks"], st["pos_used"], ka, ks)
    return st["pos_amt"][idx], st["pos_avail"][idx], found


def _pos_put(st, err, ka, ks, amt, avail):
    idx, ok = tables.put2_idx(st["pos_ka"], st["pos_ks"], st["pos_used"], ka, ks)
    err = _guard(err, ~ok, ERR_TABLE_FULL)
    do = err == ERR_OK
    st = dict(st)
    for name, v in (("pos_ka", ka), ("pos_ks", ks), ("pos_amt", amt),
                    ("pos_avail", avail)):
        st[name] = jnp.where(do, st[name].at[idx].set(v), st[name])
    st["pos_used"] = jnp.where(do, st["pos_used"].at[idx].set(True), st["pos_used"])
    return st, err


def _pos_del(st, err, ka, ks):
    """positions.delete(key): no-op when absent (RocksDB delete semantics,
    KProcessor.java:283 — the oracle's dict .pop(key, None))."""
    idx, found = tables.find2(st["pos_ka"], st["pos_ks"], st["pos_used"], ka, ks)
    st = dict(st)
    st["pos_used"] = jnp.where(
        err == ERR_OK, tables.delete_at(st["pos_used"], idx, found), st["pos_used"])
    return st


def _book_get(st, key):
    idx, found = tables.find(st["book_key"], st["book_used"], key)
    return st["book_msb"][idx], st["book_lsb"][idx], found


def _book_put(st, err, key, msb, lsb):
    idx, ok = tables.put_idx(st["book_key"], st["book_used"], key)
    err = _guard(err, ~ok, ERR_TABLE_FULL)
    do = err == ERR_OK
    st = dict(st)
    for name, v in (("book_key", key), ("book_msb", msb), ("book_lsb", lsb)):
        st[name] = jnp.where(do, st[name].at[idx].set(v), st[name])
    st["book_used"] = jnp.where(do, st["book_used"].at[idx].set(True), st["book_used"])
    return st, err


def _book_del(st, err, key):
    idx, found = tables.find(st["book_key"], st["book_used"], key)
    st = dict(st)
    st["book_used"] = jnp.where(
        err == ERR_OK, tables.delete_at(st["book_used"], idx, found), st["book_used"])
    return st


def _bkt_get(st, key):
    idx, found = tables.find(st["bkt_key"], st["bkt_used"], key)
    return st["bkt_first"][idx], st["bkt_last"][idx], found


def _bkt_put(st, err, key, first, last):
    idx, ok = tables.put_idx(st["bkt_key"], st["bkt_used"], key)
    err = _guard(err, ~ok, ERR_TABLE_FULL)
    do = err == ERR_OK
    st = dict(st)
    for name, v in (("bkt_key", key), ("bkt_first", first), ("bkt_last", last)):
        st[name] = jnp.where(do, st[name].at[idx].set(v), st[name])
    st["bkt_used"] = jnp.where(do, st["bkt_used"].at[idx].set(True), st["bkt_used"])
    return st, err


def _bkt_del(st, err, key):
    idx, found = tables.find(st["bkt_key"], st["bkt_used"], key)
    st = dict(st)
    st["bkt_used"] = jnp.where(
        err == ERR_OK, tables.delete_at(st["bkt_used"], idx, found), st["bkt_used"])
    return st


_ORD_FIELDS = ("ord_oid", "ord_action", "ord_aid", "ord_sid", "ord_price",
               "ord_size", "ord_next", "ord_next_has", "ord_prev",
               "ord_prev_has")


def _ord_get(st, oid):
    """-> (record dict, found). Record values are gathered at the hit slot
    (slot 0 garbage when not found — callers gate on `found`)."""
    idx, found = tables.find(st["ord_oid"], st["ord_used"], oid)
    rec = {f: st[f][idx] for f in _ORD_FIELDS}
    return rec, found


def _ord_put(st, err, rec):
    idx, ok = tables.put_idx(st["ord_oid"], st["ord_used"], rec["ord_oid"])
    err = _guard(err, ~ok, ERR_TABLE_FULL)
    do = err == ERR_OK
    st = dict(st)
    for f in _ORD_FIELDS:
        st[f] = jnp.where(do, st[f].at[idx].set(rec[f]), st[f])
    st["ord_used"] = jnp.where(do, st["ord_used"].at[idx].set(True), st["ord_used"])
    return st, err


def _ord_del(st, err, oid):
    idx, found = tables.find(st["ord_oid"], st["ord_used"], oid)
    st = dict(st)
    st["ord_used"] = jnp.where(
        err == ERR_OK, tables.delete_at(st["ord_used"], idx, found), st["ord_used"])
    return st


def _order_rec(action, oid, aid, sid, price, size, nxt, nxt_has, prv, prv_has):
    return {
        "ord_oid": oid.astype(_I64), "ord_action": action.astype(_I32),
        "ord_aid": aid.astype(_I64), "ord_sid": sid.astype(_I64),
        "ord_price": price.astype(_I32), "ord_size": size.astype(_I32),
        "ord_next": nxt.astype(_I64), "ord_next_has": nxt_has,
        "ord_prev": prv.astype(_I64), "ord_prev_has": prv_has,
    }


# ---------------------------------------------------------------------------
# key codecs (oracle._order_book_key / _bucket_key)

def _order_book_key(sid, is_buy, java: bool):
    """Java: sid * (+1|-1) with long wrap (Q1: -0 == 0 merges sid=0's
    sides, KProcessor.java:201/227/292). Fixed: 2*sid + side."""
    sid = sid.astype(_I64)
    if java:
        return jnp.where(is_buy, sid, -sid)
    return 2 * sid + jnp.where(is_buy, 0, 1).astype(_I64)


# ---------------------------------------------------------------------------
# handlers — each: (st, err, msg, outbuf) -> (st, err, result, echo, outbuf)
# msg is a dict of scalars; echo is (size, prev, prev_has) mutations.
# outbuf is (events (E,6) i64, n i32).

def _echo_of(msg):
    return {"size": msg["size"], "prev": msg["prev"], "prev_has": msg["prev_has"]}


def _h_create_balance(st, err, msg, outbuf, java):
    """createBalance (KProcessor.java:131-138): idempotent create at 0."""
    _, found = _bal_get(st, msg["aid"])
    st2, err2 = _bal_put(st, err, msg["aid"], jnp.asarray(0, _I64))
    st = _sel(~found, st2, st)
    err = jnp.where(~found, err2, err)
    return st, err, ~found, _echo_of(msg), outbuf


def _h_transfer(st, err, msg, outbuf, java):
    """transfer (KProcessor.java:140-146): balance += size, withdrawal
    guarded by `balance < -size`."""
    bal, found = _bal_get(st, msg["aid"])
    size = msg["size"].astype(_I64)
    # `-order.size` is Java int negation: wraps at int32 before promotion
    neg_size = (-msg["size"]).astype(_I64)
    ok = found & ~(bal < neg_size)
    st2, err2 = _bal_put(st, err, msg["aid"], bal + size)
    st = _sel(ok, st2, st)
    err = jnp.where(ok, err2, err)
    return st, err, ok, _echo_of(msg), outbuf


def _h_add_symbol(st, err, msg, outbuf, java):
    """addSymbol (KProcessor.java:184-191): empty books at ±sid (java) or
    2*sid+side (fixed; sid < 0 rejected)."""
    sid = msg["sid"].astype(_I64)
    zero = jnp.asarray(0, _I64)
    if java:
        k1, k2 = sid, -sid
        _, _, exists = _book_get(st, k1)
        ok = ~exists
    else:
        k1, k2 = 2 * sid, 2 * sid + 1
        _, _, exists = _book_get(st, k1)
        ok = (sid >= 0) & ~exists
    st2, err2 = _book_put(st, err, k1, zero, zero)
    st2, err2 = _book_put(st2, err2, k2, zero, zero)
    st = _sel(ok, st2, st)
    err = jnp.where(ok, err2, err)
    return st, err, ok, _echo_of(msg), outbuf


def _check_balance(st, err, aid, sid, price, is_buy, size_in, java):
    """checkBalance (KProcessor.java:167-182): margin reserve with netting
    against the opposite 'available' position -> (st, err, ok)."""
    bal, found = _bal_get(st, aid)
    size32 = jnp.where(is_buy, size_in, -size_in).astype(_I32)
    size = size32.astype(_I64)
    # `-size` is Java int negation: wraps at int32 before promotion
    neg_size = (-size32).astype(_I64)
    amt, avail, pos_found = _pos_get(st, aid.astype(_I64), sid.astype(_I64))
    avail = jnp.where(pos_found, avail, 0)
    adj = jnp.where(is_buy,
                    jnp.maximum(jnp.minimum(avail, 0), neg_size),
                    jnp.minimum(jnp.maximum(avail, 0), neg_size))
    unit = jnp.where(is_buy, price, price - 100).astype(_I64)
    risk = (size + adj) * unit
    ok = found & ~(bal < risk)
    st2, err2 = _bal_put(st, err, aid, bal - risk)
    adj_write = ok & (adj != 0)
    # adj != 0 with no position (negative size): getPositionAmount(null)
    # NPE (KProcessor.java:179-180) — after the balance debit persisted
    err2 = _guard(err2, adj_write & ~pos_found, ERR_CRASH)
    # adj-write uses the REAL key (3-arg setPosition, KProcessor.java:179)
    st3, err3 = _pos_put(st2, err2, aid.astype(_I64), sid.astype(_I64),
                         amt, avail - adj)
    st2 = _sel(adj_write, st3, st2)
    err2 = jnp.where(adj_write, err3, err2)
    st = _sel(ok, st2, st)
    err = jnp.where(ok, err2, err)
    return st, err, ok


def _post_remove_adjustments(st, err, rec, java):
    """postRemoveAdjustments (KProcessor.java:325-333): margin release;
    Q11 in java mode — the adj-write keys by the position VALUE."""
    is_buy = rec["ord_action"] == op.BUY
    size32 = jnp.where(is_buy, rec["ord_size"], -rec["ord_size"]).astype(_I32)
    size = size32.astype(_I64)
    neg_size = (-size32).astype(_I64)  # Java int negation (wraps at int32)
    aid, sid = rec["ord_aid"], rec["ord_sid"]
    amt, avail, pos_found = _pos_get(st, aid, sid)
    blocked = jnp.where(pos_found, amt - avail, 0)
    adj = jnp.where(is_buy,
                    jnp.maximum(jnp.minimum(blocked, 0), neg_size),
                    jnp.minimum(jnp.maximum(blocked, 0), neg_size))
    bal, found = _bal_get(st, aid)
    err = _guard(err, ~found, ERR_CRASH)  # NPE: release with no balance
    unit = jnp.where(is_buy, rec["ord_price"], rec["ord_price"] - 100).astype(_I64)
    st, err = _bal_put(st, err, aid, bal + (size + adj) * unit)
    adj_write = adj != 0
    # adj != 0 with no position (negative-size rec): the JVM NPEs at
    # getPositionAmount(null) (KProcessor.java:332) after the credit above
    err = _guard(err, adj_write & ~pos_found, ERR_CRASH)
    tka = jnp.where(jnp.asarray(java), amt, aid)    # Q11 target
    tks = jnp.where(jnp.asarray(java), avail, sid)
    st2, err2 = _pos_put(st, err, tka, tks, amt, avail + adj)
    st = _sel(adj_write, st2, st)
    err = jnp.where(adj_write, err2, err)
    return st, err


def _fill_order(st, err, outbuf, action, oid, aid, sid, price, size, java,
                max_events):
    """fillOrder (KProcessor.java:276-287) + the event forward
    (KProcessor.java:272-273). Q11 in java mode: update/delete of an
    existing position keys by the VALUE pair."""
    events, n = outbuf
    err = _guard(err, n >= max_events, ERR_EVENTS_FULL)
    row = jnp.stack([action.astype(_I64), oid.astype(_I64), aid.astype(_I64),
                     sid.astype(_I64), price.astype(_I64), size.astype(_I64)])
    do = err == ERR_OK
    events = jnp.where(do, events.at[jnp.clip(n, 0, max_events - 1)].set(row),
                       events)
    n = jnp.where(do, n + 1, n)

    signed32 = jnp.where(action == op.BOUGHT, size, -size).astype(_I32)
    signed = signed32.astype(_I64)
    ka, ks = aid.astype(_I64), sid.astype(_I64)
    amt, avail, found = _pos_get(st, ka, ks)
    # create path
    st_new, err_new = _pos_put(st, err, ka, ks, signed, signed)
    # update/delete path (java: garbage target = old value pair)
    new_amt = amt + signed
    tka = jnp.where(jnp.asarray(java), amt, ka)
    tks = jnp.where(jnp.asarray(java), avail, ks)
    st_del = _pos_del(st, err, tka, tks)
    st_upd, err_upd = _pos_put(st, err, tka, tks, new_amt, avail + signed)
    st_old = _sel(new_amt == 0, st_del, st_upd)
    err_old = jnp.where(new_amt == 0, err, err_upd)
    st = _sel(found, st_old, st_new)
    err = jnp.where(found, err_old, err_new)

    bal, bfound = _bal_get(st, aid)
    err = _guard(err, ~bfound, ERR_CRASH)  # NPE: fill with no balance
    # `size * order.price` is int*int — int32 wrap before the long add
    # (KProcessor.java:286)
    credit = (signed32 * price.astype(_I32)).astype(_I64)
    st, err = _bal_put(st, err, aid, bal + credit)
    return st, err, (events, n)


def _execute_trade(st, err, outbuf, taker, maker, trade_size, taker_is_buy,
                   java, max_events):
    """executeTrade (KProcessor.java:265-274): maker fill at price 0 first,
    taker fill at the price improvement second."""
    maker_action = jnp.where(taker_is_buy, op.SOLD, op.BOUGHT).astype(_I32)
    taker_action = jnp.where(taker_is_buy, op.BOUGHT, op.SOLD).astype(_I32)
    improvement = (taker["price"] - maker["ord_price"]).astype(_I32)
    st, err, outbuf = _fill_order(
        st, err, outbuf, maker_action, maker["ord_oid"], maker["ord_aid"],
        maker["ord_sid"], jnp.asarray(0, _I32), trade_size, java, max_events)
    st, err, outbuf = _fill_order(
        st, err, outbuf, taker_action, taker["oid"], taker["aid"],
        taker["sid"], improvement, trade_size, java, max_events)
    return st, err, outbuf


def _try_match(st, err, msg, outbuf, taker_size, java, max_events):
    """tryMatch (KProcessor.java:225-263) as a bounded lax.while_loop.

    Returns (st, err, outbuf, matched:bool, taker_size). The Q2 guard
    precedence is replicated in java mode. Loop is bounded by the fill
    buffer: each iteration emits 2 events or exits."""
    taker_is_buy = msg["action"] == op.BUY
    limit = msg["price"]
    opp_key = _order_book_key(msg["sid"], ~taker_is_buy, java)
    msb, lsb, book_found = _book_get(st, opp_key)
    err = _guard(err, ~book_found, ERR_CRASH)  # NPE: opposite book missing

    price_bit = jnp.where(taker_is_buy, bits.book_min_price(msb, lsb),
                          bits.book_max_price(msb, lsb))
    empty = price_bit == -1

    bkey = bits.bucket_key(opp_key, price_bit)
    bfirst, blast, bfound = _bkt_get(st, bkey)
    err = _guard(err, ~empty & ~bfound, ERR_CRASH)  # Q7 overshoot NPE
    maker, mfound = _ord_get(st, bfirst)
    err = _guard(err, ~empty & bfound & ~mfound, ERR_CRASH)

    def cross_guard(tsize, maker_rec):
        mp = maker_rec["ord_price"]
        if java:  # Q2: (size>0 && isBuy) ? (mp <= limit) : (mp >= limit)
            return jnp.where((tsize > 0) & taker_is_buy, mp <= limit, mp >= limit)
        return (tsize > 0) & jnp.where(taker_is_buy, mp <= limit, mp >= limit)

    def cond(c):
        return (c["err"] == ERR_OK) & ~c["done"]

    def body(c):
        st, err, outbuf = c["st"], c["err"], c["outbuf"]
        maker = c["maker"]
        tsize = c["tsize"]
        guard = cross_guard(tsize, maker)

        # --- trade at maker price (KProcessor.java:238-241)
        trade_size = jnp.minimum(tsize, maker["ord_size"])
        maker_sz = (maker["ord_size"] - trade_size).astype(_I32)
        tsize_new = (tsize - trade_size).astype(_I32)
        taker_view = {"oid": c["taker_oid"], "aid": c["taker_aid"],
                      "sid": c["taker_sid"], "price": c["taker_price"]}
        maker_traded = dict(maker)
        maker_traded["ord_size"] = maker_sz
        st_t, err_t, outbuf_t = _execute_trade(
            st, err, outbuf, taker_view, maker_traded, trade_size,
            taker_is_buy, java, max_events)

        # exhausted maker? delete and advance (KProcessor.java:242-257)
        exhausted = maker_sz == 0
        st_d = _ord_del(st_t, err_t, maker["ord_oid"])

        # advance within bucket or to next price level
        has_next = maker["ord_next_has"]
        # next-level path: delete bucket, clear bit, rescan
        st_nl = _bkt_del(st_d, err_t, c["bkey"])
        nmsb, nlsb = bits.book_with_bit_unset(c["msb"], c["lsb"],
                                              maker["ord_price"])
        st_nl, err_nl = _book_put(st_nl, err_t, opp_key, nmsb, nlsb)
        nprice = jnp.where(taker_is_buy, bits.book_min_price(nmsb, nlsb),
                           bits.book_max_price(nmsb, nlsb))
        book_empty = nprice == -1
        nbkey = bits.bucket_key(opp_key, nprice)
        nbfirst, nblast, nbfound = _bkt_get(st_nl, nbkey)
        err_nl = _guard(err_nl, ~book_empty & ~nbfound, ERR_CRASH)

        # merge: next_ptr/bucket depending on path
        adv_ptr = jnp.where(has_next, maker["ord_next"], nbfirst)
        new_bkey = jnp.where(has_next, c["bkey"], nbkey)
        new_blast = jnp.where(has_next, c["blast"], nblast)
        new_msb = jnp.where(has_next, c["msb"], nmsb)
        new_lsb = jnp.where(has_next, c["lsb"], nlsb)
        st_adv = _sel(has_next, st_d, st_nl)
        err_adv = jnp.where(has_next, err_t, err_nl)

        nmaker, nmfound = _ord_get(st_adv, adv_ptr)
        fetch_ok = has_next | ~book_empty
        err_adv = _guard(err_adv, fetch_ok & ~nmfound, ERR_CRASH)

        # --- compose iteration outcome
        # 1. guard false -> done, exit with writeback (state untouched)
        # 2. traded, maker not exhausted -> done, writeback, maker mutated
        # 3. exhausted, book empty after level clear -> done, NO writeback
        # 4. advanced -> continue
        cont = guard & exhausted & fetch_ok
        done = ~cont
        no_wb = guard & exhausted & ~has_next & book_empty
        out = {}
        out["st"] = _sel(guard, _sel(exhausted, st_adv, st_t), st)
        out["err"] = jnp.where(guard, jnp.where(exhausted, err_adv, err_t), err)
        out["outbuf"] = jax.tree.map(
            lambda a, b: jnp.where(guard, a, b), outbuf_t, outbuf)
        out["tsize"] = jnp.where(guard, tsize_new, tsize)
        out["maker"] = _sel(guard, _sel(exhausted, nmaker, maker_traded), maker)
        out["maker_ptr"] = jnp.where(guard & exhausted, adv_ptr,
                                     c["maker_ptr"])
        out["bkey"] = jnp.where(guard & exhausted, new_bkey, c["bkey"])
        out["blast"] = jnp.where(guard & exhausted, new_blast, c["blast"])
        out["msb"] = jnp.where(guard & exhausted, new_msb, c["msb"])
        out["lsb"] = jnp.where(guard & exhausted, new_lsb, c["lsb"])
        out["done"] = done
        out["writeback"] = jnp.where(done, ~no_wb & (c["err"] == ERR_OK)
                                     & (out["err"] == ERR_OK), c["writeback"])
        out["taker_oid"] = c["taker_oid"]
        out["taker_aid"] = c["taker_aid"]
        out["taker_sid"] = c["taker_sid"]
        out["taker_price"] = c["taker_price"]
        return out

    carry = {
        "st": st, "err": err, "outbuf": outbuf, "tsize": taker_size,
        "maker": maker, "maker_ptr": bfirst, "bkey": bkey, "blast": blast,
        "msb": msb, "lsb": lsb, "done": empty | (err != ERR_OK),
        # When the book is non-empty the loop runs; a guard-false first
        # iteration still does the post-loop writeback (KProcessor.java:259-261).
        "writeback": ~empty & (err == ERR_OK),
        "taker_oid": msg["oid"].astype(_I64), "taker_aid": msg["aid"].astype(_I64),
        "taker_sid": msg["sid"].astype(_I64),
        "taker_price": msg["price"].astype(_I32),
    }
    c = jax.lax.while_loop(cond, body, carry)

    st, err, outbuf = c["st"], c["err"], c["outbuf"]
    # post-loop writeback: buckets[bkey] = (maker_ptr, blast); maker.prev
    # = null; orders[maker_ptr] = maker (KProcessor.java:259-261)
    wb = c["writeback"] & (err == ERR_OK)
    st_wb, err_wb = _bkt_put(st, err, c["bkey"], c["maker_ptr"], c["blast"])
    rec = dict(c["maker"])
    rec["ord_prev"] = jnp.asarray(0, _I64)
    rec["ord_prev_has"] = jnp.asarray(False)
    st_wb, err_wb = _ord_put(st_wb, err_wb, rec)
    st = _sel(wb, st_wb, st)
    err = jnp.where(wb, err_wb, err)
    # the empty-book early exit returns False unconditionally
    # (KProcessor.java:232-235), even for a zero-size taker
    matched = ~empty & (c["tsize"] == 0)
    return st, err, outbuf, matched, c["tsize"]


def _h_trade(st, err, msg, outbuf, java, max_events):
    """addOrder (KProcessor.java:200-223)."""
    is_buy = msg["action"] == op.BUY
    bkey = _order_book_key(msg["sid"], is_buy, java)
    _, _, book_found = _book_get(st, bkey)

    if java:
        valid = jnp.asarray(True)
    else:
        valid = (msg["price"] >= 0) & (msg["price"] < 126) & (msg["size"] > 0)

    st_cb, err_cb, bal_ok = _check_balance(
        st, err, msg["aid"], msg["sid"], msg["price"], is_buy, msg["size"], java)
    pre_ok = valid & book_found & bal_ok
    st = _sel(valid & book_found, st_cb, st)
    err = jnp.where(valid & book_found, err_cb, err)

    st_m, err_m, outbuf_m, matched, residual = _try_match(
        st, err, msg, outbuf, msg["size"].astype(_I32), java, max_events)
    st = _sel(pre_ok, st_m, st)
    err = jnp.where(pre_ok, err_m, err)
    outbuf = jax.tree.map(lambda a, b: jnp.where(pre_ok, a, b), outbuf_m, outbuf)
    residual = jnp.where(pre_ok, residual, msg["size"].astype(_I32))

    # rest the remainder (KProcessor.java:205-222)
    rest = pre_ok & ~matched
    msb, lsb, _ = _book_get(st, bkey)  # reload: tryMatch may have mutated it
    bit_set = bits.book_check_bit(msb, lsb, msg["price"])
    bkt_key = bits.bucket_key(bkey, msg["price"])
    oid64 = msg["oid"].astype(_I64)

    # fresh bucket path: bucket=(oid,oid), set bitmap bit
    st_f, err_f = _bkt_put(st, err, bkt_key, oid64, oid64)
    smsb, slsb = bits.book_with_bit_set(msb, lsb, msg["price"])
    st_f, err_f = _book_put(st_f, err_f, bkey, smsb, slsb)
    prev_f, prev_has_f = msg["prev"], msg["prev_has"]

    # append path: link onto tail (mutates echoed prev — Q9)
    bfirst, blast, bfound = _bkt_get(st, bkt_key)
    err_a = _guard(err, ~bfound, ERR_CRASH)  # NPE: bit set, bucket missing
    tail, tail_found = _ord_get(st, blast)
    err_a = _guard(err_a, bfound & ~tail_found, ERR_CRASH)
    tail_upd = dict(tail)
    tail_upd["ord_next"] = oid64
    tail_upd["ord_next_has"] = jnp.asarray(True)
    st_a, err_a = _ord_put(st, err_a, tail_upd)
    st_a, err_a = _bkt_put(st_a, err_a, bkt_key, bfirst, oid64)
    prev_a, prev_has_a = tail["ord_oid"], jnp.asarray(True)

    st_r = _sel(bit_set, st_a, st_f)
    err_r = jnp.where(bit_set, err_a, err_f)
    prev_out = jnp.where(bit_set, prev_a, prev_f)
    prev_has_out = jnp.where(bit_set, prev_has_a, prev_has_f)
    rec = _order_rec(msg["action"], oid64, msg["aid"], msg["sid"],
                     msg["price"], residual, msg["next"], msg["next_has"],
                     prev_out, prev_has_out)
    st_r, err_r = _ord_put(st_r, err_r, rec)

    st = _sel(rest, st_r, st)
    err = jnp.where(rest, err_r, err)
    echo = {"size": residual.astype(_I32),
            "prev": jnp.where(rest, prev_out, msg["prev"]),
            "prev_has": jnp.where(rest, prev_has_out, msg["prev_has"])}
    return st, err, pre_ok, echo, outbuf


def _h_cancel(st, err, msg, outbuf, java):
    """removeOrder (KProcessor.java:289-323): ownership check, 4-case
    doubly-linked unlink, margin release."""
    rec, found = _ord_get(st, msg["oid"].astype(_I64))
    ok = found & (rec["ord_aid"] == msg["aid"].astype(_I64))

    is_buy = rec["ord_action"] == op.BUY
    bkey = _order_book_key(rec["ord_sid"], is_buy, java)
    price = rec["ord_price"]
    msb, lsb, book_found = _book_get(st, bkey)
    bkt_key = bits.bucket_key(bkey, price)
    bfirst, blast, bkt_found = _bkt_get(st, bkt_key)
    has_prev, has_next = rec["ord_prev_has"], rec["ord_next_has"]

    # case only: delete bucket, clear bit (NPE if book missing)
    err_only = _guard(err, ~book_found, ERR_CRASH)
    st_only = _bkt_del(st, err_only, bkt_key)
    umsb, ulsb = bits.book_with_bit_unset(msb, lsb, price)
    st_only, err_only = _book_put(st_only, err_only, bkey, umsb, ulsb)

    # case head: bucket first = next; next.prev = null (NPE if bucket/next missing)
    err_head = _guard(err, ~bkt_found, ERR_CRASH)
    st_head, err_head = _bkt_put(st, err_head, bkt_key, rec["ord_next"], blast)
    nxt, nxt_found = _ord_get(st, rec["ord_next"])
    err_head = _guard(err_head, ~nxt_found, ERR_CRASH)
    nxt_upd = dict(nxt)
    nxt_upd["ord_prev"] = jnp.asarray(0, _I64)
    nxt_upd["ord_prev_has"] = jnp.asarray(False)
    st_head, err_head = _ord_put(st_head, err_head, nxt_upd)

    # case tail: bucket last = prev; prev.next = null
    err_tail = _guard(err, ~bkt_found, ERR_CRASH)
    st_tail, err_tail = _bkt_put(st, err_tail, bkt_key, bfirst, rec["ord_prev"])
    prv, prv_found = _ord_get(st, rec["ord_prev"])
    err_tail = _guard(err_tail, ~prv_found, ERR_CRASH)
    prv_upd = dict(prv)
    prv_upd["ord_next"] = jnp.asarray(0, _I64)
    prv_upd["ord_next_has"] = jnp.asarray(False)
    st_tail, err_tail = _ord_put(st_tail, err_tail, prv_upd)

    # case middle: prev.next = next; next.prev = prev
    prv2, prv2_found = _ord_get(st, rec["ord_prev"])
    nxt2, nxt2_found = _ord_get(st, rec["ord_next"])
    err_mid = _guard(err, ~prv2_found | ~nxt2_found, ERR_CRASH)
    prv2_upd = dict(prv2)
    prv2_upd["ord_next"] = rec["ord_next"]
    prv2_upd["ord_next_has"] = jnp.asarray(True)
    nxt2_upd = dict(nxt2)
    nxt2_upd["ord_prev"] = rec["ord_prev"]
    nxt2_upd["ord_prev_has"] = jnp.asarray(True)
    st_mid, err_mid = _ord_put(st, err_mid, prv2_upd)
    st_mid, err_mid = _ord_put(st_mid, err_mid, nxt2_upd)

    st_u = _sel(has_prev,
                _sel(has_next, st_mid, st_tail),
                _sel(has_next, st_head, st_only))
    err_u = jnp.where(has_prev,
                      jnp.where(has_next, err_mid, err_tail),
                      jnp.where(has_next, err_head, err_only))

    st_u = _ord_del(st_u, err_u, msg["oid"].astype(_I64))
    st_u, err_u = _post_remove_adjustments(st_u, err_u, rec, java)

    st = _sel(ok, st_u, st)
    err = jnp.where(ok, err_u, err)
    return st, err, ok, _echo_of(msg), outbuf


def _remove_all_orders_java(st, err, book_key):
    """removeAllOrders java semantics (KProcessor.java:335-357, Q4): any
    non-empty book loops forever -> ERR_HANG. Returns (err, exists)."""
    msb, lsb, found = _book_get(st, book_key)
    nonempty = bits.book_min_price(msb, lsb) != -1
    err = _guard(err, found & nonempty, ERR_HANG)
    return err, found


def _wipe_book_fixed(st, err, book_key, java, max_iters):
    """Fixed-mode book wipe (oracle._wipe_book_fixed): pop every bucket,
    release margin for every resting order, clear the bitmap."""
    msb, lsb, found = _book_get(st, book_key)

    def cond(c):
        return (c["err"] == ERR_OK) & ~c["done"]

    def body(c):
        st, err = c["st"], c["err"]
        # fetch level head if not walking a list
        price = jnp.where(c["walking"], c["price"],
                          bits.book_min_price(c["msb"], c["lsb"]))
        level_done = ~c["walking"] & (price == -1)

        bkey = bits.bucket_key(book_key, price)
        bfirst, _, bfound = _bkt_get(st, bkey)
        # entering a level: pop bucket (oracle .pop raises when missing)
        entering = ~c["walking"] & ~level_done
        err_e = _guard(err, entering & ~bfound, ERR_CRASH)
        st_e = _sel(entering, _bkt_del(st, err_e, bkey), st)

        ptr = jnp.where(c["walking"], c["ptr"], bfirst)
        rec, rfound = _ord_get(st_e, ptr)
        act = ~level_done
        err_e = _guard(err_e, act & ~rfound, ERR_CRASH)
        st_o = _ord_del(st_e, err_e, ptr)
        st_o, err_o = _post_remove_adjustments(st_o, err_e, rec, java)
        st_n = _sel(act, st_o, st_e)
        err_n = jnp.where(act, err_o, err_e)

        walking_next = act & rec["ord_next_has"]
        # level finished: clear bit
        level_end = act & ~rec["ord_next_has"]
        nmsb, nlsb = bits.book_with_bit_unset(c["msb"], c["lsb"], price)
        out = {
            "st": st_n, "err": err_n,
            "msb": jnp.where(level_end, nmsb, c["msb"]),
            "lsb": jnp.where(level_end, nlsb, c["lsb"]),
            "walking": walking_next,
            "ptr": jnp.where(walking_next, rec["ord_next"], 0).astype(_I64),
            "price": price.astype(_I32),
            "done": level_done,
            "iters": c["iters"] + 1,
        }
        out["err"] = _guard(out["err"], out["iters"] >= max_iters, ERR_CRASH)
        return out

    # carry constants derived from traced inputs so the loop types stay
    # consistent under shard_map's varying-axis tracking
    zi64 = book_key.astype(_I64) * 0
    zi32 = zi64.astype(_I32)
    carry = {"st": st, "err": err, "msb": msb, "lsb": lsb,
             "walking": zi32 != 0, "ptr": zi64,
             "price": zi32 - 1, "done": ~found,
             "iters": zi32}
    c = jax.lax.while_loop(cond, body, carry)
    st, err = c["st"], c["err"]
    st2, err2 = _book_put(st, err, book_key, c["msb"], c["lsb"])
    st = _sel(found, st2, st)
    err = jnp.where(found, err2, err)
    return st, err


def _h_remove_symbol(st, err, msg, outbuf, java, max_iters):
    """removeSymbol (KProcessor.java:193-198). Java: Q3 inverted return +
    Q4 hang; short-circuit `or` replicated. Fixed: wipe + delete, True."""
    sid = msg["sid"].astype(_I64)
    if java:
        err1, exists1 = _remove_all_orders_java(st, err, sid)
        # short-circuit: -sid side only evaluated when +sid side absent
        # (KProcessor.java:194 `if (a || b)`) — its hang can't fire then
        err2, exists2 = _remove_all_orders_java(st, err1, -sid)
        err_sc = jnp.where(exists1, err1, err2)
        ok = ~exists1 & ~exists2
        st_p = _book_del(st, err_sc, sid)
        st_p = _book_del(st_p, err_sc, -sid)
        st = _sel(ok, st_p, st)
        return st, err_sc, ok, _echo_of(msg), outbuf
    s = jnp.abs(sid)
    k1, k2 = 2 * s, 2 * s + 1
    _, _, found = _book_get(st, k1)
    st_w, err_w = _wipe_book_fixed(st, err, k1, java, max_iters)
    st_w, err_w = _wipe_book_fixed(st_w, err_w, k2, java, max_iters)
    st_w = _book_del(st_w, err_w, k1)
    st_w = _book_del(st_w, err_w, k2)
    st = _sel(found, st_w, st)
    err = jnp.where(found, err_w, err)
    return st, err, found, _echo_of(msg), outbuf


def _h_payout(st, err, msg, outbuf, java, max_iters):
    """payout (KProcessor.java:148-165): removeSymbol, then credit
    `amount * order.size` per matching position and delete it (vectorized
    over the positions table — order-insensitive since mod-2^64 adds
    commute). Java: Q3 makes this reachable only for missing books; Q5/Q6
    the result is discarded by the dispatcher. Fixed: sid>=0 YES credits
    longs, sid<0 NO deletes uncredited."""
    st, err, removed, _, outbuf = _h_remove_symbol(
        st, err, msg, outbuf, java, max_iters)

    sid = msg["sid"].astype(_I64)
    match_sid = sid if java else jnp.abs(sid)
    credit = jnp.asarray(True) if java else sid >= 0

    pmask = st["pos_used"] & (st["pos_ks"] == match_sid)
    # per-balance-slot credit: sum over matching positions owned by that key
    owner = st["pos_ka"][:, None] == st["bal_key"][None, :]
    hit = pmask[:, None] & owner & st["bal_used"][None, :]
    credit_amt = jnp.sum(
        jnp.where(hit, st["pos_amt"][:, None] * msg["size"].astype(_I64), 0),
        axis=0)
    orphan = pmask & ~jnp.any(hit, axis=1)  # NPE: position w/o balance
    do = removed & (err == ERR_OK)
    err = _guard(err, do & credit & jnp.any(orphan), ERR_CRASH)
    apply = do & credit & (err == ERR_OK)
    st = dict(st)
    st["bal_val"] = jnp.where(apply, st["bal_val"] + credit_amt, st["bal_val"])
    st["pos_used"] = jnp.where(do & (err == ERR_OK),
                               st["pos_used"] & ~pmask, st["pos_used"])
    return st, err, removed, _echo_of(msg), outbuf


# ---------------------------------------------------------------------------
# dispatch + scan

def _dense_op(action, pad):
    """Wire action -> dense branch index. 0 = pad/no-op (explicit flag)."""
    table = [
        (op.ADD_SYMBOL, 1), (op.REMOVE_SYMBOL, 2), (op.BUY, 3), (op.SELL, 3),
        (op.CANCEL, 4), (op.CREATE_BALANCE, 5), (op.TRANSFER, 6),
        (op.PAYOUT, 7),
    ]
    out = jnp.asarray(8, _I32)  # unknown -> reject
    for wire, dense in table:
        out = jnp.where(action == wire, jnp.asarray(dense, _I32), out)
    return jnp.where(pad, jnp.asarray(0, _I32), out)


@functools.lru_cache(maxsize=None)
def build_step_fn(caps: ParityCaps, compat: str):
    """Build the PURE batch step: (state, msgs) -> (state, outputs).

    Jit-free so it can be embedded in shard_map/vmap contexts; use
    build_step() for the compiled host-callable with buffer donation.

    msgs: dict of (T,)-arrays. outputs: dict of per-message results
    (result, action_out, size_out, prev_out, prev_has_out, events,
    n_events, err)."""
    java = compat == "java"
    E = caps.max_events
    max_iters = caps.orders + 130

    def one_message(st, err, msg):
        # buffers derived from the traced message so shard_map's
        # varying-axis types stay consistent through loops/branches
        zv32 = (msg["action"] * 0).astype(_I32)
        outbuf = (jnp.zeros((E, 6), _I64) + zv32.astype(_I64), zv32)

        def b_pad(a):
            st, err, msg, outbuf = a
            return st, err, msg["pad"] | True, _echo_of(msg), outbuf

        def b_add_symbol(a):
            return _h_add_symbol(*a, java)

        def b_remove_symbol(a):
            st, err, msg, outbuf = a
            return _h_remove_symbol(st, err, msg, outbuf, java, max_iters)

        def b_trade(a):
            st, err, msg, outbuf = a
            return _h_trade(st, err, msg, outbuf, java, E)

        def b_cancel(a):
            return _h_cancel(*a, java)

        def b_create_balance(a):
            return _h_create_balance(*a, java)

        def b_transfer(a):
            return _h_transfer(*a, java)

        def b_payout(a):
            st, err, msg, outbuf = a
            st, err, r, echo, outbuf = _h_payout(st, err, msg, outbuf, java,
                                                 max_iters)
            # Q5/Q6: java discards payout's result (KProcessor.java:113-115)
            return st, err, ((r & False) if java else r), echo, outbuf

        def b_unknown(a):
            st, err, msg, outbuf = a
            return st, err, msg["pad"] & False, _echo_of(msg), outbuf

        branches = [b_pad, b_add_symbol, b_remove_symbol, b_trade, b_cancel,
                    b_create_balance, b_transfer, b_payout, b_unknown]
        dense = _dense_op(msg["action"], msg["pad"])
        st, err, result, echo, outbuf = jax.lax.switch(
            dense, branches, (st, err, msg, outbuf))
        is_pad = dense == 0
        # REJECT rewrite (KProcessor.java:123)
        action_out = jnp.where(result, msg["action"], jnp.asarray(op.REJECT, _I32))
        return st, err, {
            "result": result & ~is_pad,
            "pad": is_pad,
            "action_out": jnp.where(is_pad, msg["action"], action_out),
            "size_out": echo["size"].astype(_I32),
            "prev_out": echo["prev"].astype(_I64),
            "prev_has_out": echo["prev_has"],
            "events": outbuf[0],
            "n_events": outbuf[1],
            "err": err,
        }

    def scan_body(carry, msg):
        st, err = carry
        # sticky error: freeze all processing after the first failure
        st2, err2, out = one_message(st, err, msg)
        frozen = err != ERR_OK
        st = _sel(frozen, st, st2)
        err = jnp.where(frozen, err, err2)
        out = jax.tree.map(lambda x: jnp.where(frozen, jnp.zeros_like(x), x), out)
        out["err"] = err
        return (st, err), out

    def step(state, msgs):
        state = dict(state)
        err0 = state.pop("err")
        (state, err), outs = jax.lax.scan(scan_body, (state, err0), msgs)
        state["err"] = err

        # Device-side event compaction: the (T, E, 6) event grid is >95%
        # padding; pack the used rows into one (T*E, 6) buffer so the
        # host fetches only the used prefix (the same compact-I/O design
        # as the lanes fill log — transfers, not FLOPs, bound the e2e).
        T = outs["n_events"].shape[0]
        nev = outs["n_events"]
        offs = jnp.cumsum(nev) - nev
        eidx = jnp.arange(E, dtype=_I32)[None, :]
        mask = eidx < nev[:, None]
        pos = jnp.where(mask, offs[:, None] + eidx, T * E).astype(_I32)
        packed = jnp.zeros((T * E + 1, 6), _I64)
        packed = packed.at[pos.reshape(-1)].set(
            outs.pop("events").reshape(T * E, 6))[:T * E]
        outs["ev_total"] = jnp.sum(nev)
        return state, outs, packed

    return step


@functools.lru_cache(maxsize=None)
def build_step(caps: ParityCaps, compat: str):
    """Compiled batch step with state-buffer donation; cached per
    (caps, compat) so every ParityEngine with the same shape shares one
    XLA program."""
    return jax.jit(build_step_fn(caps, compat), donate_argnums=(0,))


# ---------------------------------------------------------------------------
# host wrapper

def _msgs_to_arrays(msgs: Sequence[OrderMsg], batch: int) -> Dict[str, np.ndarray]:
    from kme_tpu.oracle import javalong as jl

    T = batch
    arr = {
        "action": np.full(T, NOP_PAD, np.int32),
        "pad": np.ones(T, bool),
        "oid": np.zeros(T, np.int64), "aid": np.zeros(T, np.int64),
        "sid": np.zeros(T, np.int64), "price": np.zeros(T, np.int32),
        "size": np.zeros(T, np.int32),
        "next": np.zeros(T, np.int64), "next_has": np.zeros(T, bool),
        "prev": np.zeros(T, np.int64), "prev_has": np.zeros(T, bool),
    }
    for i, m in enumerate(msgs):
        arr["pad"][i] = False
        arr["action"][i] = jl.jint(m.action)
        arr["oid"][i] = jl.jlong(m.oid)
        arr["aid"][i] = jl.jlong(m.aid)
        arr["sid"][i] = jl.jlong(m.sid)
        arr["price"][i] = jl.jint(m.price)
        arr["size"][i] = jl.jint(m.size)
        if m.next is not None:
            arr["next"][i] = jl.jlong(m.next)
            arr["next_has"][i] = True
        if m.prev is not None:
            arr["prev"][i] = jl.jlong(m.prev)
            arr["prev_has"][i] = True
    return arr


class ParityEngine:
    """Host wrapper: the drop-in device-backed equivalent of OracleEngine.

    process()/process_batch() return the same OutRecord stream the oracle
    produces (IN echo, fills, OUT echo per message —
    KProcessor.java:97, 272-273, 124). On a reference-death path it
    raises DeviceParityError after emitting the records of every message
    before the death point."""

    def __init__(self, compat: str = "java",
                 caps: Optional[ParityCaps] = None) -> None:
        if compat not in ("java", "fixed"):
            raise ValueError(compat)
        self.compat = compat
        self.caps = caps or ParityCaps()
        self.state = make_state(self.caps)
        self._step = build_step(self.caps, compat)

    def process(self, msg: OrderMsg) -> List[OutRecord]:
        return self.process_batch([msg])[0]

    def process_batch(self, msgs: Sequence[OrderMsg]) -> List[List[OutRecord]]:
        """Process messages strictly in order; returns per-message record
        lists.

        Pipelined I/O: batches are dispatched up to a bounded window
        ahead of the fetch (state chains on device via donation; the
        sticky error in the state keeps post-death batches frozen),
        device->host copies start asynchronously, and records are built
        from bulk host lists — transfers and reconstruction overlap
        device compute instead of serializing with it. The packed event
        log is fetched as a power-of-two-bucketed used-prefix slice
        (bounded recompiles, only used rows cross the wire)."""
        from kme_tpu.utils import async_prefetch, pow2_bucket

        WINDOW = 8  # dispatch lookahead (bounds device-resident outputs)
        pending = []
        out: List[List[OutRecord]] = []

        def fetch_one(rec) -> None:
            lo, chunk, outs, packed = rec
            h = {k: np.asarray(v) for k, v in outs.items()}
            tot = int(h["ev_total"])
            if tot:
                sl = packed[:pow2_bucket(tot, lo=256)]
                async_prefetch([sl])
                ev = np.asarray(sl)[:tot].tolist()
            else:
                ev = []
            recs, bad = self._records_batch(chunk, h, ev)
            out.extend(recs)
            if bad is not None:
                raise DeviceParityError(int(h["err"][bad]), lo + bad, out)

        for lo in range(0, len(msgs), self.caps.batch):
            chunk = list(msgs[lo:lo + self.caps.batch])
            arrs = _msgs_to_arrays(chunk, self.caps.batch)
            self.state, outs, packed = self._step(self.state, arrs)
            async_prefetch(outs.values())
            pending.append((lo, chunk, outs, packed))
            if len(pending) > WINDOW:
                fetch_one(pending.pop(0))
        for rec in pending:
            fetch_one(rec)
        return out

    @staticmethod
    def _records_batch(chunk, h, ev_rows):
        """Bulk per-batch record construction from host lists. Returns
        (records, first_error_index_or_None)."""
        errs = h["err"].tolist()
        n_events = h["n_events"].tolist()
        action_out = h["action_out"].tolist()
        size_out = h["size_out"].tolist()
        prev_out = h["prev_out"].tolist()
        prev_has = h["prev_has_out"].tolist()
        out = []
        off = 0
        for i, m in enumerate(chunk):
            if errs[i] != ERR_OK:
                return out, i
            recs = [OutRecord("IN", m.copy())]
            for e in range(n_events[i]):
                a, oid, aid, sid, price, size = ev_rows[off + e]
                recs.append(OutRecord("OUT", OrderMsg(
                    action=a, oid=oid, aid=aid, sid=sid, price=price,
                    size=size)))
            off += n_events[i]
            echo = m.copy()
            echo.action = action_out[i]
            echo.size = size_out[i]
            echo.prev = prev_out[i] if prev_has[i] else None
            recs.append(OutRecord("OUT", echo))
            out.append(recs)
        return out, None

    # -- state export for deep-equality tests ---------------------------------

    def export_state(self) -> Dict[str, dict]:
        """Host-side dict view of the five stores, directly comparable to
        the oracle's dicts."""
        s = jax.tree.map(np.asarray, self.state)
        balances = {int(k): int(v) for k, v, u in
                    zip(s["bal_key"], s["bal_val"], s["bal_used"]) if u}
        positions = {}
        for ka, ks, amt, av, u in zip(s["pos_ka"], s["pos_ks"], s["pos_amt"],
                                      s["pos_avail"], s["pos_used"]):
            if u:
                positions[(int(ka), int(ks))] = (int(amt), int(av))
        books = {}
        for k, msb, lsb, u in zip(s["book_key"], s["book_msb"], s["book_lsb"],
                                  s["book_used"]):
            if u:
                books[int(k)] = (int(msb), int(lsb))
        buckets = {}
        for k, f, l, u in zip(s["bkt_key"], s["bkt_first"], s["bkt_last"],
                              s["bkt_used"]):
            if u:
                buckets[int(k)] = (int(f), int(l))
        orders = {}
        for i in range(len(s["ord_oid"])):
            if s["ord_used"][i]:
                orders[int(s["ord_oid"][i])] = {
                    "action": int(s["ord_action"][i]),
                    "aid": int(s["ord_aid"][i]), "sid": int(s["ord_sid"][i]),
                    "price": int(s["ord_price"][i]),
                    "size": int(s["ord_size"][i]),
                    "next": int(s["ord_next"][i]) if s["ord_next_has"][i] else None,
                    "prev": int(s["ord_prev"][i]) if s["ord_prev_has"][i] else None,
                }
        return {"balances": balances, "positions": positions, "books": books,
                "buckets": buckets, "orders": orders}
