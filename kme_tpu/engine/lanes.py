"""Throughput engine: vmapped per-symbol order-book lanes.

This is the TPU-first redesign of the matching core (SURVEY.md §7 design
stance): the reference's KV-stores + intrusive linked lists
(KProcessor.java:30-49, 448-475) dissolve into dense per-lane arrays, and
the per-message match loop (KProcessor.java:237-258) becomes a
sort + prefix-sum *sweep* — no data-dependent loop, constant work per
step, everything vectorized over S symbol lanes.

Semantics: compat='fixed' exactly (the corrected reference semantics the
scalar oracle defines — kme_tpu/oracle/engine.py docstring). Java-quirk
parity is the serial parity engine's job; this engine is the performance
path. The one observable java-era behavior kept is the Q9 prev-echo leak
(appending to a non-empty price bucket stamps the bucket tail's oid into
the echoed order), which `compat=fixed` preserves.

Exact-parallelism model (SURVEY.md §7 H1). A key structural fact of the
reference: maker fills carry price 0 (KProcessor.java:268-271), so
`fillOrder` credits `size * 0 == 0` to maker balances — balances are
mutated ONLY by their own account's messages (margin reserve/release,
taker credit, transfers) plus the rare PAYOUT. Therefore a parallel step
that (a) keeps per-symbol arrival order within its lane, (b) never
schedules two messages from the same account, and (c) isolates
PAYOUT/REMOVE_SYMBOL as barrier steps, is *bit-exact* with serial replay.
The host sequencer (kme_tpu/runtime/sequencer.py) enforces (a)-(c).

Data layout per lane (S = lanes, N = slots/side, A = dense accounts):
- book slots (S, 2, N): oid i64, aid-index i32, price i32, size i32,
  seqno i32 (FIFO arrival stamp), used bool. Price-time priority is the
  scalar key `price * 2^32 + seqno` (ask side; bid side uses 125-price),
  so "best maker" is one masked argsort — the bitmap+bucket+linked-list
  machinery (KProcessor.java:359-416) has no equivalent here.
- positions (S, A): amount i64, available i64, used bool — dense by
  (lane, account), so maker-position scatter needs no associative probe.
- balances (A,) + used (A,): replicated across shards; per-step deltas
  are scattered densely and (under shard_map) psum-merged — disjointness
  is guaranteed by the scheduler, so the merge is exact.

Fills are emitted as compact per-step arrays (maker oid/aid/price + fill
size, in priority order); the host reconstructs the byte-exact
IN/fill/OUT record stream (maker event before taker event per trade,
KProcessor.java:265-274).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp


_I64 = jnp.int64
_I32 = jnp.int32

# dense lane op codes (host-side sequencer packs these)
L_NOP = 0
L_BUY = 1
L_SELL = 2
L_CANCEL = 3
L_CREATE = 4
L_TRANSFER = 5
L_ADD_SYMBOL = 6

# lane error codes (sticky, per batch). Book/fill CAPACITY overflow is
# NOT an error: it is a per-message REJECT (the H2/H3 envelope policy —
# the offending order is refused as a unit, surfaced as an OUT REJECT in
# the wire stream, and the batch continues). Only the host-side fill-log
# sizing knob remains a sticky error, since it is a session buffer bound,
# not an engine-semantics bound.
LERR_OK = 0
LERR_FILLBUF_FULL = 3  # session fill log exhausted (fill_buffer knob)

# on-device metrics counters (state["metrics"], int64, accumulated in
# the scan carry and psum-merged under sharding — SURVEY.md §5's
# replacement for the reference's untouched JMX metrics)
MET_MSGS = 0            # device-executed messages (non-NOP)
MET_TRADES_OK = 1       # accepted BUY/SELL
MET_FILLS = 2           # fill events (maker count)
MET_CONTRACTS = 3       # contracts traded (sum of fill sizes)
MET_REJ_CAPACITY = 4    # H2/H3 envelope rejects
MET_REJ_RISK = 5        # margin/validation rejects
MET_RESTED = 6          # orders appended to a book
MET_CANCELS_OK = 7
MET_REJ_CANCEL = 8
MET_TRANSFERS_OK = 9
MET_REJ_OTHER = 10      # failed create/transfer/add_symbol
MET_BARRIERS = 11       # payout/remove settles executed
N_METRICS = 12

METRIC_NAMES = ("msgs", "trades_ok", "fills", "contracts", "rej_capacity",
                "rej_risk", "rested", "cancels_ok", "rej_cancel",
                "transfers_ok", "rej_other", "barriers")

# on-device distribution histograms (state["hist"]): power-of-two
# buckets accumulated next to the metrics counters and fetched in the
# same device transfer (no extra round-trips). Bucket index for value
# v is #{k in 0..14 : v >= 2^k}: v <= 0 -> bucket 0, v == 1 -> 1,
# v in [2^(i-1), 2^i) -> i, v >= 2^14 -> 15.
HIST_FILLS = 0        # makers swept per ACCEPTED trade (0 = pure rest)
HIST_DEPTH = 1        # resting orders (both sides) in the touched book
#                       after each accepted trade/cancel
HIST_OCCUPANCY = 2    # non-NOP messages per dispatch unit (scan step /
#                       seq kernel call); empty units are unobserved
N_HIST = 3
N_HIST_BUCKETS = 16

HIST_NAMES = ("fills_per_order", "book_depth", "batch_occupancy")

_HIST_THRESH = tuple(1 << k for k in range(N_HIST_BUCKETS - 1))


def hist_bucket(v):
    """Power-of-two bucket index (vectorized, any int shape)."""
    thr = jnp.asarray(_HIST_THRESH, _I32)
    return jnp.sum(v[..., None] >= thr, axis=-1).astype(_I32)


@dataclasses.dataclass(frozen=True)
class LaneConfig:
    """Static shapes; one XLA program per distinct value."""

    lanes: int = 8            # S — symbols (sharded axis)
    slots: int = 128          # N — resting orders per book side
    accounts: int = 256      # A — dense account capacity
    max_fills: int = 16       # E — makers swept per taker (H3 bound)
    steps: int = 64           # T bucket granularity of a dispatch window
    window: int = 1024        # max scan steps per dispatch (HBM bound)
    fill_buffer: int = 1 << 20  # device fill ring capacity (H3 envelope)
    # width > 0 enables ACTIVE-LANE COMPACTION: each scan step computes
    # at width W (the at-most-W lanes the scheduler placed in the step)
    # instead of full S — book rows are gathered/scattered by lane id
    # and position ops use flat lane*A+acc indices, so per-step work is
    # O(W·N + W·E) instead of O(S·(N+A)). Profiled on v5e: the full-
    # width step spends >85% of its time on (S,2E)->(S,A) scatters and
    # (S,N) gathers for lanes that are pure padding. The LAST device
    # lane is reserved as the padding scrap row (LaneSession sizes the
    # device state to lanes+1). Single-device only; the sharded path
    # ignores width.
    width: int = 0            # W — max active lanes per scan step
    # scan-body unroll factor: amortizes XLA loop overhead and lets the
    # compiler fuse across adjacent steps; shapes are unchanged
    unroll: int = 1
    # pos_dma (compact mode only): positions live as PLANAR lo/hi int32
    # rows (S, 2A/128, 128) updated IN PLACE by Pallas row-DMA kernels
    # (ops/rowdma.py) instead of flat (S*A,) int64 arrays rewritten
    # whole by XLA scatter (~24us/step at the bench shapes vs ~2.7us
    # for the DMA round trip — measured, scripts/exp_pallas_rowdma.py).
    # Requires accounts % 64 == 0 (128-lane row tiles). LaneSession
    # enables it automatically; snapshots stay canonical (flat s64).
    pos_dma: bool = False


def _fill_slack(cfg: LaneConfig) -> int:
    """Slack columns past the fill-log overflow watermark (see the
    fillbuf note in make_lane_state). Compact mode's block append can
    write up to one full (M*E,) window block starting at the watermark;
    M is bucketed to a power of two over at most window*width slots."""
    if cfg.width <= 0:
        return 1
    from kme_tpu.utils import pow2_bucket

    return pow2_bucket(cfg.window * cfg.width) * cfg.max_fills


def make_lane_state(cfg: LaneConfig):
    S, N, A = cfg.lanes, cfg.slots, cfg.accounts
    if cfg.pos_dma:
        from kme_tpu.ops import rowdma

        sub, ln = rowdma.row_shape(2 * A)
        pos = {"pos_amt": jnp.zeros((S, sub, ln), _I32),
               "pos_avail": jnp.zeros((S, sub, ln), _I32)}
    else:
        pos = {"pos_amt": jnp.zeros((S * A,), _I64),
               "pos_avail": jnp.zeros((S * A,), _I64)}
    return {
        "slot_oid": jnp.zeros((S, 2, N), _I64),
        "slot_aid": jnp.zeros((S, 2, N), _I32),
        "slot_price": jnp.zeros((S, 2, N), _I32),
        "slot_size": jnp.zeros((S, 2, N), _I32),
        "slot_seq": jnp.zeros((S, 2, N), _I32),
        "slot_used": jnp.zeros((S, 2, N), bool),
        "seq": jnp.zeros((S,), _I32),
        "book_exists": jnp.zeros((S,), bool),
        # positions (non-pos_dma): kept FLAT (S*A,) — lane-major, index
        # lane*A+acc.
        # A 2-D (S, A) layout costs a physical re-tiling copy per scan
        # step on TPU for the reshape to flat scatter indices (profiled:
        # ~100us/step in reshape copies + un-aliased scatters); flat
        # arrays scatter with far less traffic, though XLA:TPU scatter
        # still rewrites the array (~1us/MB — the dominant per-step HBM
        # term, see the bench's modeled_hbm_gbps model). A per-lane (S, P)
        # associative table was evaluated and rejected: hot-symbol
        # holder counts approach A on skewed workloads, so P cannot
        # shrink below O(A) without spuriously capacity-rejecting them.
        # There is no `used` flag: in fixed mode a position exists iff
        # amt != 0 (delete-at-zero, KProcessor.java:281-284 corrected),
        # and the engine maintains avail == 0 whenever amt == 0.
        **pos,
        "bal": jnp.zeros((A,), _I64),
        "bal_used": jnp.zeros((A,), bool),
        "err": jnp.zeros((), _I32),
        # compact mode keeps the counters as a TUPLE of scalars: the
        # (12,) array form costs a serialized 12-way concatenate per
        # scan step (~8us/step profiled, x64 pairs); scalar carries are
        # free. Snapshots canonicalize to the (12,) array either way.
        "metrics": (tuple(jnp.zeros((), _I64) for _ in range(N_METRICS))
                    if cfg.width > 0 else jnp.zeros((N_METRICS,), _I64)),
        # distribution histograms (HIST_NAMES rows): same tuple-vs-array
        # split as the counters; rows stay replicated under sharding
        # (psum-merged deltas), canonicalized to (N_HIST, B) in snapshots
        "hist": (tuple(jnp.zeros((N_HIST_BUCKETS,), _I64)
                       for _ in range(N_HIST))
                 if cfg.width > 0
                 else jnp.zeros((N_HIST, N_HIST_BUCKETS), _I64)),
        # persistent fill log: rows oid/aid/price/size; filloff = next
        # free position. Only the used prefix ever crosses to the host
        # (ONE sliced fetch per batch — the tunneled-TPU I/O design, see
        # chunk_compaction). Compact mode appends whole sorted (M*E,)
        # blocks with one dynamic_update_slice, so the log carries a
        # full block of slack past the overflow watermark; the
        # full-width path's per-entry scatter needs one clamp slot.
        "fillbuf": jnp.zeros((4, cfg.fill_buffer + _fill_slack(cfg)), _I64),
        "filloff": jnp.zeros((1,), _I64),
    }


def _priority_key(side, price, seqno):
    """Scalar price-time key, ascending = better maker. side is the
    MAKER side: 1 (asks) -> low price first; 0 (bids) -> high first."""
    p = jnp.where(side == 1, price, 125 - price).astype(_I64)
    return (p << 32) | seqno.astype(_I64)


_ROW_KEYS = ("slot_oid", "slot_aid", "slot_price", "slot_size",
             "slot_seq", "slot_used")


@functools.lru_cache(maxsize=None)
def build_lane_step(cfg: LaneConfig, axis_name: Optional[str] = None):
    """The pure scan-step batch function: (state, batch) -> (state, outs).

    batch: dict of (T, X) arrays (act, oid, aid, price, size) where X is
    the step width — S in full-width mode, cfg.width under active-lane
    compaction, which adds a (T, X) "lane" array mapping each step slot
    to its device lane (padding slots carry the scrap lane S-1 with
    act=NOP, so their writes are identity by construction).
    outs per (t, slot): ok, residual, append prev info, fill arrays,
    plus the sticky error code.
    When axis_name is set the balance-delta merge is psum'd over that
    mesh axis (shard_map embedding; full-width only)."""
    S, N, A, E = cfg.lanes, cfg.slots, cfg.accounts, cfg.max_fills
    compact = cfg.width > 0
    X = cfg.width if compact else S
    assert not (compact and axis_name), \
        "active-lane compaction is single-device only"
    assert not (cfg.pos_dma and not compact), \
        "pos_dma requires active-lane compaction"
    if cfg.pos_dma:
        from kme_tpu.ops import rowdma

    # TPU-friendly indexed access: multi-dim advanced indexing like
    # a[lane_ids, side, idx] lowers to a generic (slow, ~ms) gather /
    # scatter; take_along_axis / one-hot selects lower to vectorized VPU
    # work (~20µs at S=1024). Measured on v5e — use ONLY these forms in
    # the per-step path.
    def _ta1(a, idx):
        """a: (X, K), idx: (X,) -> (X,) — batched axis-1 gather."""
        return jnp.take_along_axis(a, idx[:, None].astype(_I32), axis=1)[:, 0]

    def _pa1(a, idx, vals):
        """a: (X, K), idx: (X,) -> a with a[x, idx[x]] = vals[x]."""
        return jnp.put_along_axis(a, idx[:, None].astype(_I32),
                                  vals[:, None].astype(a.dtype), axis=1,
                                  inplace=False)

    def one_step(st, msg):
        act, oid, aid = msg["act"], msg["oid"], msg["aid"]
        price, size = msg["price"], msg["size"]

        if compact:
            lanes = msg["lane"].astype(_I32)        # (W,) device lanes
            sl = {k: st[k][lanes] for k in _ROW_KEYS}   # (W, 2, N) rows
            seq_v = st["seq"][lanes]
            be_v = st["book_exists"][lanes]
        else:
            lanes = jnp.arange(S, dtype=_I32)
            sl = {k: st[k] for k in _ROW_KEYS}
            seq_v = st["seq"]
            be_v = st["book_exists"]

        if cfg.pos_dma:
            # row-DMA the W active lanes' position rows into small
            # (X, A) s64 blocks; every read/write below is block-local
            # (each step slot owns its lane row — scheduler invariant),
            # and the updated rows DMA back IN PLACE at the end of the
            # step. The 16MB flat arrays are never scattered.
            pa_f = rowdma.join_rows(
                rowdma.gather_lane_rows(st["pos_amt"], lanes))
            pv_f = rowdma.join_rows(
                rowdma.gather_lane_rows(st["pos_avail"], lanes))

            def pos_read(blk, accs):                # accs: (X,) | (X, K)
                i = (accs if accs.ndim == 2 else accs[:, None]).astype(_I32)
                v = jnp.take_along_axis(blk, i, axis=1)
                return v if accs.ndim == 2 else v[:, 0]

            acc_iota = jnp.arange(A, dtype=_I32)

            def pos_write(blk, accs, vals):
                # one-hot masked merge, NOT scatter: XLA:TPU serializes
                # scatter updates (~11us for a (W,2E)->(W,A) put_along,
                # profiled), while the (X, K, A) one-hot reduction is
                # pure vectorized VPU work. Duplicate accounts within a
                # row carry IDENTICAL values by construction (the engine
                # computes each account's final value for every entry),
                # so a max-select over contributors is exact.
                i = (accs if accs.ndim == 2 else accs[:, None]).astype(_I32)
                v = (vals if vals.ndim == 2 else vals[:, None]).astype(blk.dtype)
                oh = i[:, :, None] == acc_iota                  # (X, K, A)
                hit = jnp.any(oh, axis=1)                       # (X, A)
                BOT = jnp.asarray(-(1 << 62), blk.dtype)
                merged = jnp.max(jnp.where(oh, v[:, :, None], BOT), axis=1)
                return jnp.where(hit, merged, blk)
        else:
            # positions via flat lane*A+acc indices — the state arrays
            # are flat (make_lane_state); XLA scatter rewrites the whole
            # array per step (the pos_dma path avoids this)
            pbase = lanes * A                       # (X,) int32; S*A < 2^31
            pa_f = st["pos_amt"]
            pv_f = st["pos_avail"]

            def pos_read(arr_f, accs):              # accs: (X,) | (X, K)
                idx = pbase[:, None] + accs if accs.ndim == 2 else pbase + accs
                return arr_f[idx]

            def pos_write(arr_f, accs, vals):
                idx = pbase[:, None] + accs if accs.ndim == 2 else pbase + accs
                return arr_f.at[idx].set(vals.astype(arr_f.dtype))

        is_trade = (act == L_BUY) | (act == L_SELL)
        is_buy = act == L_BUY
        side = jnp.where(is_buy, 0, 1).astype(_I32)     # own (rest) side
        opp = (1 - side).astype(_I32)
        opp_is0 = (opp == 0)[:, None]                   # (X, 1) side select
        side_oh = (side[:, None] == jnp.arange(2, dtype=_I32))[:, :, None]
        opp_oh = (opp[:, None] == jnp.arange(2, dtype=_I32))[:, :, None]

        def pick_side(a, is0):
            return jnp.where(is0, a[:, 0], a[:, 1])

        def set_side(a, oh, new):
            """a: (X,2,N); oh: (X,2,1) one-hot; new: (X,N) side image."""
            return jnp.where(oh, new[:, None, :], a)

        bal_g = st["bal"][aid]              # (X,) pre-step actor balances
        bal_ok = st["bal_used"][aid]

        # ------------------------------------------------- CREATE_BALANCE
        create_ok = (act == L_CREATE) & ~bal_ok

        # ------------------------------------------------------- TRANSFER
        size64 = size.astype(_I64)
        # `-order.size` is Java int negation: wraps at int32 (INT_MIN stays
        # INT_MIN) before the long comparison — mirrors oracle._transfer
        neg_size64 = (-size).astype(_I64)
        transfer_ok = (act == L_TRANSFER) & bal_ok & ~(bal_g < neg_size64)

        # ----------------------------------------------------- ADD_SYMBOL
        addsym_ok = (act == L_ADD_SYMBOL) & ~be_v
        book_exists = be_v | addsym_ok

        # ------------------------------------------------- TRADE: margin
        # checkBalance (KProcessor.java:167-182), fixed-domain: price in
        # [0,126), size > 0 (validated), so no int32 wrap can occur.
        valid = (price >= 0) & (price < 126) & (size > 0)
        signed = jnp.where(is_buy, size, -size).astype(_I32)
        signed64 = signed.astype(_I64)
        p_avail = pos_read(pv_f, aid)  # == 0 when no position exists
        adj = jnp.where(is_buy,
                        jnp.maximum(jnp.minimum(p_avail, 0), -signed64),
                        jnp.minimum(jnp.maximum(p_avail, 0), -signed64))
        unit = jnp.where(is_buy, price, price - 100).astype(_I64)
        risk = (signed64 + adj) * unit
        trade_ok = is_trade & valid & be_v & bal_ok & ~(bal_g < risk)

        # -------------------------------------------------- TRADE: sweep
        # the match loop (KProcessor.java:237-258) as ONE multi-operand
        # lax.sort + prefix sum over the opposite side's slots. Profiled
        # on v5e: the sort network is ~30us at (1024, 128) while argsort
        # + per-payload take_along gathers cost ~9ms/step — payloads must
        # ride the sort, and the inverse permutation is a second sort
        # keyed on the slot index, never a gather.
        g = lambda a: pick_side(a, opp_is0)            # (X, N) opp side
        m_used = g(sl["slot_used"])
        m_price, m_size = g(sl["slot_price"]), g(sl["slot_size"])
        m_oid, m_aid, m_seq = g(sl["slot_oid"]), g(sl["slot_aid"]), g(sl["slot_seq"])
        crossing = m_used & jnp.where(
            is_buy[:, None], m_price <= price[:, None], m_price >= price[:, None])
        crossing = crossing & trade_ok[:, None]
        key = _priority_key(opp[:, None], m_price, m_seq)
        BIG = jnp.asarray((1 << 62), _I64)
        masked_key = jnp.where(crossing, key, BIG)
        slot_ids = jnp.broadcast_to(jnp.arange(N, dtype=_I32), (X, N))
        (_, cross_s, sz_raw_s, oid_s, aid_s, price_s, slot_s) = jax.lax.sort(
            (masked_key, crossing, m_size, m_oid, m_aid, m_price, slot_ids),
            num_keys=1, dimension=1)                   # (X, N) best-first
        sz_sorted = jnp.where(cross_s, sz_raw_s, 0)
        prefix = jnp.cumsum(sz_sorted, axis=1) - sz_sorted   # exclusive
        z = jnp.where(trade_ok, size, 0)[:, None]
        fill_sorted = jnp.clip(z - prefix, 0, sz_sorted)
        filled_total = jnp.sum(fill_sorted, axis=1).astype(_I32)
        residual = (size - jnp.where(trade_ok, filled_total, 0)).astype(_I32)
        nfill = jnp.sum(fill_sorted > 0, axis=1).astype(_I32)
        overflow_fills = nfill > E

        # ------------------------- capacity envelope (SURVEY.md §7 H2/H3)
        # A message that would overflow its book side (no free resting
        # slot for the residual) or sweep more makers than max_fills is
        # rejected AS A UNIT — no fills, no state change, OUT REJECT on
        # the wire — mirrored exactly by the oracle's capacity envelope.
        # Per-message policy; the batch continues (no sticky poison).
        side_is0 = (side == 0)[:, None]
        own = lambda a: pick_side(a, side_is0)
        o_used_pre = own(sl["slot_used"])
        free_idx = jnp.argmax(~o_used_pre, axis=1).astype(_I32)
        have_free = jnp.any(~o_used_pre, axis=1)
        rest_want = trade_ok & (residual > 0)
        overflow_book = rest_want & ~have_free
        cap_reject = trade_ok & (overflow_book | overflow_fills)
        trade_acc = trade_ok & ~cap_reject

        # margin netting blocks part of the opposite position (:179) —
        # applied only for accepted messages
        adj_write = trade_acc & (adj != 0)
        pv_f = pos_write(pv_f, aid,
                         pos_read(pv_f, aid)
                         + jnp.where(adj_write, -adj, 0))

        # write back maker sizes via the inverse permutation: a second
        # sort keyed on the carried slot index (slot_s is a permutation
        # of 0..N-1 per lane, so this restores slot order exactly)
        _, new_sz_s = jax.lax.sort(
            (slot_s, (sz_raw_s - fill_sorted).astype(_I32)),
            num_keys=1, dimension=1)
        new_m_size = new_sz_s
        new_m_used = m_used & (new_m_size > 0)
        slot_size = set_side(sl["slot_size"], opp_oh,
                             jnp.where(trade_acc[:, None], new_m_size, m_size))
        slot_used = set_side(sl["slot_used"], opp_oh,
                             jnp.where(trade_acc[:, None], new_m_used, m_used))

        # compact per-trade outputs (priority order), truncated at E.
        # E > N is legal (a sweep can cross at most N makers): the [:E]
        # slice clamps at N, so pad the tail back out to E.
        def cap_e(a):
            a = a[:, :E]
            if a.shape[1] < E:
                a = jnp.pad(a, ((0, 0), (0, E - a.shape[1])))
            return a

        fo_oid = cap_e(oid_s)
        fo_aid = cap_e(aid_s)
        fo_price = cap_e(price_s)
        fo_fill = cap_e(fill_sorted).astype(_I32)

        # ---------------------------------- TRADE: position updates
        # Exact closed-form replay of the per-trade fill sequence (maker
        # fill then taker fill per trade, KProcessor.java:272-273),
        # including delete-at-zero/recreate semantics. Key identity:
        # create(s) == update from (0,0), and a delete only ever happens
        # when the running amount IS zero — so the running amount is the
        # plain prefix sum, and `available` restarts from zero after the
        # account's LAST zero-crossing within the sweep:
        #   amt_final  = amt0 + sum(fills)
        #   avail_fin  = sum(fills after last zero prefix)   if any zero
        #              = avail0 + sum(fills)                 otherwise
        # This replaces a 2E-deep sequential loop with a few (S,2E,2E)
        # masked reductions — pure VPU work, no serialization. (Masked
        # where+sum rather than int64 einsum: an s64 dot_general hits
        # XLA:TPU's unimplemented X64-rewrite path and fails to compile.)
        twoE = 2 * E
        idx2 = jnp.arange(twoE, dtype=_I32)
        # interleave maker/taker entries [m0, t0, m1, t1, ...] via
        # stack+reshape — a pure relayout; the earlier strided
        # .at[:, 0::2].set form lowered to serialized scatters
        # (~1.4us each, profiled)
        def interleave(m, t):
            return jnp.stack([m, t], axis=-1).reshape(X, twoE)

        acc = interleave(fo_aid, jnp.broadcast_to(aid[:, None], (X, E)))
        m_sgn = jnp.where(is_buy[:, None], -fo_fill, fo_fill).astype(_I64)
        t_sgn = jnp.where(is_buy[:, None], fo_fill, -fo_fill).astype(_I64)
        sgn = interleave(m_sgn, t_sgn)
        fv = (fo_fill > 0) & trade_acc[:, None]
        fvalid = interleave(fv, fv)
        a0 = pos_read(pa_f, acc)   # 0 when no position exists
        v0 = pos_read(pv_f, acc)
        # eq[s, i, j]: entry i is a VALID contributor to entry j's account.
        # Only the contributor side is validity-gated: every entry j —
        # valid or not — then computes its account's exact final value, so
        # ALL duplicate scatter targets carry identical values and the
        # plain put_along below is deterministic with no dummy column.
        # (Profiled: the old pad-concat + slice around a (S, A+1) scatter
        # copied the 16MB position arrays twice and cost ~2ms per call.)
        eq = ((acc[:, :, None] == acc[:, None, :])
              & fvalid[:, :, None])                          # (S, i, j)
        le = idx2[:, None] <= idx2[None, :]
        sgn_b = sgn[:, :, None]                              # (S, i, 1)
        prefix = a0 + jnp.sum(jnp.where(eq & le[None], sgn_b, 0), axis=1)
        zero = fvalid & (prefix == 0)
        # per entry j: index of its account's last zero prefix (-1 if none)
        jlast = jnp.max(
            jnp.where(zero[:, :, None] & eq, idx2[None, :, None], -1), axis=1)
        after = eq & (idx2[None, :, None] > jlast[:, None, :])
        avail_sum = jnp.sum(jnp.where(after, sgn_b, 0), axis=1)
        total = jnp.sum(jnp.where(eq, sgn_b, 0), axis=1)
        anyzero = jnp.any(zero[:, :, None] & eq, axis=1)
        amt_fin = a0 + total
        avail_fin = jnp.where(anyzero, avail_sum, v0 + total)
        used_fin = amt_fin != 0
        # untouched accounts land on identity writes (amt_fin = a0 etc.),
        # so no masking is needed: scatter values directly. Deleted
        # positions (amt_fin == 0) write avail = 0 — the no-used-flag
        # invariant.
        pa_f = pos_write(pa_f, acc, amt_fin)
        pv_f = pos_write(pv_f, acc, jnp.where(used_fin, avail_fin, 0))

        # taker balance credit: sum of fill * improvement (maker credit is
        # size * 0 == 0 — the structural fact the scheduler relies on).
        # Each per-fill product is Java int*int — wraps at int32 BEFORE
        # the long balance add (KProcessor.java:286, oracle._fill_order)
        improve = (jnp.where(trade_acc[:, None], price[:, None], 0)
                   - fo_price).astype(_I32)
        signed_credit = jnp.where(is_buy[:, None], fo_fill, -fo_fill).astype(_I32)
        credit = jnp.sum((signed_credit * improve).astype(_I64), axis=1)

        # ------------------------------------------------- TRADE: rest
        # (free slot existence already established by the capacity
        # envelope: trade_acc & rest_want implies have_free)
        # Q9 prev-echo: tail of my price bucket = max seqno among used
        # same-price slots on my side
        o_price, o_seq_ = own(sl["slot_price"]), own(sl["slot_seq"])
        same_level = o_used_pre & (o_price == price[:, None])
        bucket_nonempty = jnp.any(same_level, axis=1)
        tail_idx = jnp.argmax(
            jnp.where(same_level, o_seq_, -1), axis=1).astype(_I32)
        tail_oid = _ta1(own(sl["slot_oid"]), tail_idx)

        do_rest = rest_want & trade_acc
        seqno = seq_v
        # one-hot write of the rested order into (lane, side, free_idx)
        slot_oh = (free_idx[:, None] == jnp.arange(N, dtype=_I32))[:, None, :]
        wr = side_oh & slot_oh & do_rest[:, None, None]      # (X, 2, N)
        slot_oid = jnp.where(wr, oid[:, None, None], sl["slot_oid"])
        slot_aid = jnp.where(wr, aid[:, None, None], sl["slot_aid"])
        slot_price = jnp.where(wr, price[:, None, None], sl["slot_price"])
        slot_size = jnp.where(wr, residual[:, None, None], slot_size)
        slot_seq = jnp.where(wr, seqno[:, None, None], sl["slot_seq"])
        slot_used = slot_used | wr
        seq = seqno + do_rest.astype(_I32)

        # --------------------------------------------------------- CANCEL
        # removeOrder (KProcessor.java:289-323): slot lookup by oid +
        # ownership, then margin release (postRemoveAdjustments :325-333)
        is_cancel = act == L_CANCEL
        hit = sl["slot_used"] & (sl["slot_oid"] == oid[:, None, None])
        hit_flat = hit.reshape(X, 2 * N)
        hit_any = jnp.any(hit_flat, axis=1)
        hit_idx = jnp.argmax(hit_flat, axis=1).astype(_I32)
        h_side = hit_idx // N
        c_aid = _ta1(sl["slot_aid"].reshape(X, 2 * N), hit_idx)
        c_price = _ta1(sl["slot_price"].reshape(X, 2 * N), hit_idx)
        c_size = _ta1(sl["slot_size"].reshape(X, 2 * N), hit_idx)
        cancel_ok = is_cancel & hit_any & (c_aid == aid)
        clear = ((hit_idx[:, None] == jnp.arange(2 * N, dtype=_I32))
                 & cancel_ok[:, None]).reshape(X, 2, N)
        slot_used = slot_used & ~clear
        # margin release
        c_isbuy = h_side == 0
        c_signed = jnp.where(c_isbuy, c_size, -c_size).astype(_I64)
        cp_amt = pos_read(pa_f, aid)
        cp_avail_raw = pos_read(pv_f, aid)
        # amt == avail == 0 when no position exists, so blocked == 0
        blocked = cp_amt - cp_avail_raw
        c_adj = jnp.where(c_isbuy,
                          jnp.maximum(jnp.minimum(blocked, 0), -c_signed),
                          jnp.minimum(jnp.maximum(blocked, 0), -c_signed))
        c_unit = jnp.where(c_isbuy, c_price, c_price - 100).astype(_I64)
        c_release = (c_signed + c_adj) * c_unit
        c_adj_write = cancel_ok & (c_adj != 0)
        pv_f = pos_write(pv_f, aid,
                         cp_avail_raw + jnp.where(c_adj_write, c_adj, 0))

        # ------------------------------------------- balance delta merge
        delta = (jnp.where(transfer_ok, size64, 0)
                 + jnp.where(trade_acc, -risk + credit, 0)
                 + jnp.where(cancel_ok, c_release, 0))
        dense_delta = jnp.zeros((A,), _I64).at[aid].add(delta)
        dense_create = jnp.zeros((A,), bool).at[aid].max(create_ok)
        if axis_name is not None:
            dense_delta = jax.lax.psum(dense_delta, axis_name)
            dense_create = jax.lax.psum(
                dense_create.astype(_I32), axis_name) > 0
        bal = st["bal"] + dense_delta
        bal_used = st["bal_used"] | dense_create

        err = st["err"]
        if axis_name is not None:
            # any shard's sticky error becomes globally visible (and the
            # replicated err stays identical across shards)
            err = jax.lax.pmax(err, axis_name)

        # ------------------------------------------------ metrics delta
        cnt = lambda m: jnp.sum(m.astype(_I64))
        met = (
            cnt(act != L_NOP),                                 # MSGS
            cnt(trade_acc),                                    # TRADES_OK
            jnp.sum(jnp.where(trade_acc, nfill, 0).astype(_I64)),
            jnp.sum(jnp.where(trade_acc, filled_total, 0).astype(_I64)),
            cnt(cap_reject),                                   # REJ_CAPACITY
            cnt(is_trade & ~trade_ok),                         # REJ_RISK
            cnt(do_rest),                                      # RESTED
            cnt(cancel_ok),                                    # CANCELS_OK
            cnt(is_cancel & ~cancel_ok),                       # REJ_CANCEL
            cnt(transfer_ok),                                  # TRANSFERS_OK
            cnt(((act == L_CREATE) & ~create_ok)
                | ((act == L_TRANSFER) & ~transfer_ok)
                | ((act == L_ADD_SYMBOL) & ~addsym_ok)),       # REJ_OTHER
            jnp.zeros((), _I64),                               # BARRIERS
        )
        if compact:
            # scalar-tuple carry: no per-step (12,) concatenate
            metrics = tuple(m + d for m, d in zip(st["metrics"], met))
        else:
            met = jnp.stack(met)
            if axis_name is not None:
                met = jax.lax.psum(met, axis_name)
            metrics = st["metrics"] + met

        # ---------------------------------------------- histogram deltas
        # one-hot scatter-adds into the power-of-two bucket rows. Depth
        # observes the touched book AFTER the message (final slot_used,
        # cancel clear included); padding/scrap rows carry act=NOP so
        # trade_acc/cancel_ok exclude them by construction.
        obs_depth = trade_acc | cancel_ok
        depth = jnp.sum(slot_used.reshape(X, 2 * N).astype(_I32), axis=1)
        d_fills = (jnp.zeros((N_HIST_BUCKETS,), _I64)
                   .at[hist_bucket(nfill)].add(trade_acc.astype(_I64)))
        d_depth = (jnp.zeros((N_HIST_BUCKETS,), _I64)
                   .at[hist_bucket(depth)].add(obs_depth.astype(_I64)))
        occ = jnp.sum((act != L_NOP).astype(_I32))
        if axis_name is not None:
            # shard-invariance: merge the per-shard fills/depth deltas;
            # occupancy counts the GLOBAL step population, so psum the
            # count BEFORE bucketing — the resulting row is identical
            # on every shard and needs no merge of its own
            d_fills = jax.lax.psum(d_fills, axis_name)
            d_depth = jax.lax.psum(d_depth, axis_name)
            occ = jax.lax.psum(occ, axis_name)
        d_occ = (jnp.zeros((N_HIST_BUCKETS,), _I64)
                 .at[hist_bucket(occ)].add((occ > 0).astype(_I64)))
        if compact:
            hist = tuple(h + d for h, d in
                         zip(st["hist"], (d_fills, d_depth, d_occ)))
        else:
            hist = st["hist"] + jnp.stack((d_fills, d_depth, d_occ))

        ok = jnp.where(
            is_trade, trade_acc,
            jnp.where(is_cancel, cancel_ok,
                      jnp.where(act == L_CREATE, create_ok,
                                jnp.where(act == L_TRANSFER, transfer_ok,
                                          jnp.where(act == L_ADD_SYMBOL,
                                                    addsym_ok, act == L_NOP)))))

        new_rows = {
            "slot_oid": slot_oid, "slot_aid": slot_aid,
            "slot_price": slot_price, "slot_size": slot_size,
            "slot_seq": slot_seq, "slot_used": slot_used,
        }
        if compact:
            # Scatter the W updated rows back into the full device state.
            # Duplicate indices only occur on the scrap lane (padding,
            # act=NOP), whose computed rows are bitwise identity — so the
            # duplicate-index scatter is deterministic by construction.
            new_st = dict(st)
            for k, v in new_rows.items():
                new_st[k] = st[k].at[lanes].set(v)
            new_st["seq"] = st["seq"].at[lanes].set(seq)
            new_st["book_exists"] = st["book_exists"].at[lanes].set(book_exists)
            if cfg.pos_dma:
                # DMA the updated (X, A) blocks back in place (the
                # kernel itself skips scrap-lane rows)
                new_st["pos_amt"] = rowdma.scatter_lane_rows(
                    st["pos_amt"], lanes, rowdma.split_rows(pa_f), S - 1)
                new_st["pos_avail"] = rowdma.scatter_lane_rows(
                    st["pos_avail"], lanes, rowdma.split_rows(pv_f), S - 1)
            else:
                new_st["pos_amt"] = pa_f
                new_st["pos_avail"] = pv_f
            new_st.update(bal=bal, bal_used=bal_used, err=err,
                          metrics=metrics, hist=hist)
        else:
            new_st = {
                **new_rows,
                "seq": seq, "book_exists": book_exists,
                "pos_amt": pa_f, "pos_avail": pv_f,
                "bal": bal, "bal_used": bal_used, "err": err,
                "metrics": metrics, "hist": hist,
                "fillbuf": st["fillbuf"], "filloff": st["filloff"],
            }
        outs = {
            "ok": ok,
            "residual": jnp.where(trade_acc, residual, size).astype(_I32),
            "append": bucket_nonempty & do_rest,
            "prev_oid": tail_oid,
            "nfill": jnp.where(trade_acc, nfill, 0),
            "cap_reject": cap_reject,
            "fill_oid": fo_oid, "fill_aid": fo_aid,
            "fill_price": fo_price, "fill_size": fo_fill,
            "err": err,
        }
        return new_st, outs

    def step(state, batch):
        return jax.lax.scan(one_step, state, batch, unroll=cfg.unroll)

    return step


# ---------------------------------------------------------------------------
# compact-I/O chunk: the serving-path wrapper around the scan


def chunk_compaction(cfg: LaneConfig, T: int, M: int, step):
    """Wrap a (state, (T,S) batch) scan `step` with device-side input
    scatter and output compaction.

    Motivation: host<->device traffic, not FLOPs, bounds serving
    throughput (the driver's TPU is reached through a tunnel measured at
    ~10-20 MB/s with ~126 ms round trips; even on local PCIe the dense
    (T,S,E) fill grids are >95% padding). Nothing O(T*S) crosses the
    boundary: inputs arrive as (M,) message vectors with (t, lane)
    schedule coordinates and are scattered to the grid on device, and
    outputs return as per-message (M,) vectors. Fills are appended to
    the PERSISTENT state fill log (state["fillbuf"], in cb order — the
    session packs cb sorted by (t, lane) so the order is deterministic);
    the host fetches the used prefix once per batch. Overflowing the log
    sets the sticky LERR_FILLBUF_FULL error (H3 envelope knob
    `fill_buffer`).

    The sharded path wraps the same chunk around the shard_map'd step
    (parallel/mesh.py): GSPMD gathers each window's compact fills over
    the mesh and the append lands identically on every shard's
    replicated log.

    Under active-lane compaction (cfg.width > 0) the scan grid is
    (T, W) message slots instead of (T, S) lanes: cb carries a "slot"
    coordinate (position within the step, assigned by the scheduler's
    width cap) and the per-step batch includes the (T, W) lane map.
    Padding slots point at the scrap lane S-1 with act=NOP, so their
    row writes are bitwise identity.

    t >= T marks padding entries."""
    S, E = cfg.lanes, cfg.max_fills
    FB = cfg.fill_buffer
    compact = cfg.width > 0
    X = cfg.width if compact else S
    assert not compact or M * E <= _fill_slack(cfg), (
        f"chunk M={M} x max_fills={E} exceeds the fill-log slack "
        f"{_fill_slack(cfg)} — the block append could clamp backward and "
        f"corrupt earlier fills without tripping the sticky error")

    def chunk(state, cb):
        valid = cb["t"] < T
        col = cb["slot"] if compact else cb["lane"]
        flat = jnp.where(valid, cb["t"] * X + col, T * X).astype(_I32)

        def grid(v, dt, fill=0):
            z = jnp.full((T * X + 1,), fill, dt)
            return z.at[flat].set(v.astype(dt))[:T * X].reshape(T, X)

        batch = {
            "act": grid(cb["act"], _I32), "oid": grid(cb["oid"], _I64),
            "aid": grid(cb["aid"], _I32), "price": grid(cb["price"], _I32),
            "size": grid(cb["size"], _I32),
        }
        if compact:
            batch["lane"] = grid(cb["lane"], _I32, fill=S - 1)
        state, outs = step(state, batch)

        gflat = jnp.minimum(flat, T * X - 1)

        def pick(a):  # (T, X, ...) -> (M, ...) per-message gather
            return a.reshape((T * X,) + a.shape[2:])[gflat]

        nfill = jnp.where(valid, pick(outs["nfill"]), 0)
        total = jnp.sum(nfill)
        fo, fa = pick(outs["fill_oid"]), pick(outs["fill_aid"])
        fp, fs = pick(outs["fill_price"]), pick(outs["fill_size"])

        state = dict(state)
        # append to the persistent fill log at the running offset
        base = state["filloff"][0]
        offs = base + (jnp.cumsum(nfill) - nfill).astype(_I64)
        eidx = jnp.arange(E, dtype=_I64)[None, :]
        mask = eidx < nfill[:, None].astype(_I64)
        new_off = base + total.astype(_I64)
        if compact:
            # Stream-compact the (M, E) fill grid with ONE multi-operand
            # sort — valid entries keyed by their window-relative log
            # position (already unique and in (t, lane, e) order),
            # padding keyed past the end — then append the packed block
            # with a single in-place dynamic_update_slice. The previous
            # per-entry scatter serialized on TPU (~4.7ms per window at
            # M=4096, profiled); the sort + contiguous DUS is ~2 orders
            # cheaper. DUS clamps the start when the log overflows; the
            # sticky error below fires before the host ever reads fills.
            rel = offs[:, None] - base + eidx              # (M, E)
            key = jnp.where(mask, rel, M * E).astype(_I32).reshape(-1)
            _, so, sa, sp, ss = jax.lax.sort(
                (key, fo.astype(_I64).reshape(-1),
                 fa.astype(_I64).reshape(-1), fp.astype(_I64).reshape(-1),
                 fs.astype(_I64).reshape(-1)), num_keys=1)
            blk = jnp.stack([so, sa, sp, ss])              # (4, M*E)
            buf = jax.lax.dynamic_update_slice(
                state["fillbuf"], blk, (jnp.zeros((), _I64), base))
        else:
            pos = jnp.where(mask, jnp.minimum(offs[:, None] + eidx, FB), FB)
            pos = pos.astype(_I32).reshape(-1)
            buf = state["fillbuf"]
            for c, arr in enumerate((fo, fa, fp, fs)):
                buf = buf.at[c].set(
                    buf[c].at[pos].set(arr.astype(_I64).reshape(-1)))
        err = state["err"]
        err = jnp.where((err == LERR_OK) & (new_off > FB),
                        jnp.asarray(LERR_FILLBUF_FULL, _I32), err)
        state["fillbuf"] = buf
        state["filloff"] = jnp.full((1,), 0, _I64) + new_off
        state["err"] = err
        # ALL per-message outputs ride ONE (8, M) i64 array — a single
        # device->host transfer per window (each separate np.asarray
        # costs a tunnel round trip, ~8ms profiled). Rows 6/7 broadcast
        # the err/total scalars.
        packed = jnp.stack([
            jnp.where(valid, pick(outs["ok"]), False).astype(_I64),
            pick(outs["residual"]).astype(_I64),
            jnp.where(valid, pick(outs["append"]), False).astype(_I64),
            pick(outs["prev_oid"]),
            jnp.where(valid, pick(outs["cap_reject"]), False).astype(_I64),
            nfill.astype(_I64),
            jnp.full((M,), 0, _I64) + err.astype(_I64),
            jnp.full((M,), 0, _I64) + total.astype(_I64),
        ])
        return state, {"packed": packed}

    return chunk


@functools.lru_cache(maxsize=None)
def build_lane_chunk(cfg: LaneConfig, T: int, M: int):
    """Single-device compact-I/O chunk fn, jitted with state donation and
    cached per static shape — sessions share compiled executables."""
    return jax.jit(chunk_compaction(cfg, T, M, build_lane_step(cfg)),
                   donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def build_gauges(cfg: LaneConfig):
    """Jitted point-in-time gauges over the lane state (book depth,
    open orders, live books/accounts/positions) — the state-derived half
    of the observability surface; counters live in state['metrics']."""
    def gauges(state):
        used = state["slot_used"]
        depth = jnp.sum(used.astype(_I32), axis=2)     # (S, 2)
        pa = state["pos_amt"]
        if cfg.pos_dma:  # planar lo/hi rows: live iff either half != 0
            v = pa.reshape(pa.shape[0], 2, -1)
            live = (v[:, 0] != 0) | (v[:, 1] != 0)
        else:
            live = pa != 0
        return {
            "open_orders": jnp.sum(used.astype(_I64)),
            "books": jnp.sum(state["book_exists"].astype(_I64)),
            "accounts": jnp.sum(state["bal_used"].astype(_I64)),
            "positions": jnp.sum(live.astype(_I64)),
            "max_book_depth": jnp.max(depth).astype(_I64),
        }

    return jax.jit(gauges)


@functools.lru_cache(maxsize=None)
def build_fill_reset(cfg: LaneConfig):
    """Tiny jitted op: rewind the fill log (the host consumed it)."""
    def reset(state):
        state = dict(state)
        state["filloff"] = jnp.zeros((1,), _I64)
        return state

    return jax.jit(reset, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# barrier ops (rare; invoked by the host between scan dispatches)

@functools.lru_cache(maxsize=None)
def build_barrier_ops(cfg: LaneConfig, axis_name: Optional[str] = None):
    """payout/remove_symbol as standalone jitted-able fns over ONE lane.

    Both wipe the lane's book with per-order margin release in the
    reference's wipe order — min price level first, FIFO within level,
    buy side then sell side (oracle._wipe_book_fixed) — which is
    sequential per account (each release changes `available`, feeding the
    next release's netting), hence the fori_loop over slots in wipe
    order. PAYOUT then credits `amount * size` per holder (YES) or just
    deletes positions (NO) — exchange_test.js:76-79 intent, oracle
    `_payout` fixed mode."""
    S, N, A = cfg.lanes, cfg.slots, cfg.accounts
    lane_ids = jnp.arange(S, dtype=_I32)

    def _pos_row(st, key, lane):
        """One lane's positions as an (A,) s64 row, either layout."""
        if cfg.pos_dma:
            from kme_tpu.ops import rowdma

            r = jax.lax.dynamic_index_in_dim(
                st[key], lane, 0, keepdims=False).reshape(2 * A)
            return rowdma.join64(r[:A], r[A:])
        return jax.lax.dynamic_slice_in_dim(st[key], lane * A, A)

    def _pos_row_set(st_arr, lane, row64):
        """Write an (A,) s64 row back at `lane`, either layout."""
        if cfg.pos_dma:
            from kme_tpu.ops import rowdma

            lo, hi = rowdma.split64(row64)
            packed = jnp.concatenate([lo, hi]).reshape(st_arr.shape[1:])
            return st_arr.at[lane].set(packed)
        return jax.lax.dynamic_update_slice_in_dim(
            st_arr, row64.astype(st_arr.dtype), lane * A, 0)

    def wipe_lane(st, lane, do):
        """Release margin for every resting order of `lane`, clear slots.
        `do` gates the whole operation."""
        sl = lambda k: st[k][lane]                      # (2, N)
        used = sl("slot_used")
        price = sl("slot_price")
        seqno = sl("slot_seq")
        # wipe order: side-major (buy side first), then (price, seqno) —
        # the reference's wipe sequence (oracle._wipe_book_fixed). The
        # side tag (1<<44) dominates the (price<<32 | seq) key range.
        key = (jnp.repeat(jnp.arange(2, dtype=_I64)[:, None] * (1 << 44), N, 1)
               + (price.astype(_I64) << 32) + seqno.astype(_I64))
        key = jnp.where(used, key, jnp.asarray(1 << 62, _I64))
        order = jnp.argsort(key.reshape(2 * N))
        n_used = jnp.sum(used)

        def body(i, carry):
            pos_amt, pos_avail, bal_delta = carry
            flat = order[i]
            s_side = flat // N
            s_slot = flat % N
            active = do & (i < n_used)
            a = st["slot_aid"][lane, s_side, s_slot]
            pr = st["slot_price"][lane, s_side, s_slot]
            sz = st["slot_size"][lane, s_side, s_slot]
            isbuy = s_side == 0
            signed = jnp.where(isbuy, sz, -sz).astype(_I64)
            amt = pos_amt[a]
            avail = pos_avail[a]        # 0 when no position exists
            blocked = amt - avail
            adj = jnp.where(isbuy,
                            jnp.maximum(jnp.minimum(blocked, 0), -signed),
                            jnp.minimum(jnp.maximum(blocked, 0), -signed))
            unit = jnp.where(isbuy, pr, pr - 100).astype(_I64)
            release = (signed + adj) * unit
            pos_avail = pos_avail.at[a].add(jnp.where(active & (adj != 0), adj, 0))
            bal_delta = bal_delta.at[a].add(jnp.where(active, release, 0))
            return pos_amt, pos_avail, bal_delta

        # zero delta derived from lane-sharded state so its varying-axis
        # type matches the loop body's output under shard_map
        zv64 = (st["seq"][0] * 0).astype(_I64)
        carry = (_pos_row(st, "pos_amt", lane),
                 _pos_row(st, "pos_avail", lane),
                 jnp.zeros((A,), _I64) + zv64)
        pos_amt_l, pos_avail_l, bal_delta = jax.lax.fori_loop(
            0, 2 * N, body, carry)
        return pos_amt_l, pos_avail_l, bal_delta

    def settle(state, lane, credit_size, mode):
        """mode: 0 = REMOVE_SYMBOL, 1 = PAYOUT YES, 2 = PAYOUT NO.

        Returns (state, ok). Under shard_map, `lane` is the LOCAL lane
        index on the owning shard; other shards call with do=False via
        lane=-1."""
        do = (lane >= 0) & state["book_exists"][jnp.maximum(lane, 0)]
        lane_c = jnp.maximum(lane, 0)
        pos_amt_l, pos_avail_l, bal_delta = wipe_lane(state, lane_c, do)
        st = dict(state)

        def upd_pos(key, new_row):
            cur = _pos_row(st, key, lane_c)
            return _pos_row_set(st[key], lane_c,
                                jnp.where(do, new_row, cur))

        st["pos_amt"] = upd_pos("pos_amt", pos_amt_l)
        st["pos_avail"] = upd_pos("pos_avail", pos_avail_l)
        st["slot_used"] = st["slot_used"].at[lane_c].set(
            jnp.where(do, False, st["slot_used"][lane_c]))
        st["book_exists"] = st["book_exists"].at[lane_c].set(
            jnp.where(do, False, st["book_exists"][lane_c]))

        # payout credit/delete over the lane's positions (a holder is any
        # account with amt != 0 — the no-used-flag invariant)
        is_payout = mode > 0
        credit = (mode == 1)
        pm = jnp.where(do & is_payout, True, False)
        amts = _pos_row(st, "pos_amt", lane_c)
        pay = jnp.where(pm & credit,
                        amts * credit_size.astype(_I64), 0)
        bal_delta = bal_delta + pay

        def clear_pos(key):
            cur = _pos_row(st, key, lane_c)
            return _pos_row_set(st[key], lane_c, jnp.where(pm, 0, cur))

        st["pos_amt"] = clear_pos("pos_amt")
        st["pos_avail"] = clear_pos("pos_avail")

        if axis_name is not None:
            bal_delta = jax.lax.psum(bal_delta, axis_name)
            do_any = jax.lax.psum(do.astype(_I32), axis_name) > 0
        else:
            do_any = do
        st["bal"] = st["bal"] + bal_delta
        if cfg.width > 0:  # scalar-tuple metrics carry (compact mode)
            mets = list(st["metrics"])
            mets[MET_BARRIERS] = mets[MET_BARRIERS] + do_any.astype(_I64)
            st["metrics"] = tuple(mets)
        else:
            st["metrics"] = st["metrics"].at[MET_BARRIERS].add(
                do_any.astype(_I64))
        return st, do_any

    return settle
