"""Device engines.

- `parity`: the serial-in-time device replica of the reference engine —
  one message at a time under `lax.scan`, dense associative stores,
  byte-exact vs the scalar oracle in both compat modes. The parity judge
  for everything faster.
- `lanes` (throughput engine): vmapped per-symbol order books, fixed-mode
  semantics, sharded over the symbol mesh axis.
"""
