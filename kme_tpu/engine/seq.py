"""Sequential Pallas mega-kernel engine (round 4).

The round-3 profile showed the vectorized sweep engine's scan step is
OP-COUNT-bound: ~185 XLA ops/step at ~0.25us launch overhead each, with
occupancy capped at ~4.4 msgs/step by hot-lane serialization under the
conflict-free scheduler (one message per lane per step). This engine
removes both limits at once: ONE Pallas kernel processes a micro-batch
of B messages STRICTLY SEQUENTIALLY — the reference's own execution
model (KProcessor.java:95-126, single StreamThread) — with the entire
engine state VMEM-resident for the duration of the call. Sequential
execution inside the kernel IS serial replay, so no scheduling
constraints exist at all: same-account runs, hot-symbol bursts and the
10-account stock harness (exchange_test.js:18) run at full speed
(SURVEY.md §7 H1 dissolves).

Measured basis (scripts/exp_seqkernel.py, v5e chip): a bare sequential
sweep body runs at ~64ns/msg — two orders of magnitude under the sweep
engine's per-step floor.

Semantics: compat='fixed' exactly, mirroring engine/lanes.py (which the
oracle pins byte-exact) including the capacity envelope (slots /
max_fills per-message rejects), Q9 prev-echo, Java int32/int64 wrap
arithmetic, and barrier settles (payout/remove wipe order: buy side
first, (price, seq) within a side — oracle._wipe_book_fixed).

Data layout (everything int32 — the Mosaic kernel boundary refuses
s64; 64-bit balance/position values live as planar lo/hi i32 pairs and
are recombined only in scalar emulation helpers inside the kernel):

- book planes (2*S*NR, 128), row = lane*2*NR + side*NR + r, side 0 =
  buy, N = NR*128 slots/side: oid lo/hi, aid, price, size, seq.
  A slot is occupied iff size > 0 (no used flag).
- positions: an open-addressing HASH TABLE of (CAP,) entries in
  (CAP/128, 128) planes [key, amt lo/hi, avail lo/hi]; key =
  lane*A + acc + 1 (0 = empty). Entries are NEVER deleted — a live
  position has amt != 0 (the delete-at-zero invariant the lanes engine
  already uses), so lookups need no tombstones; probing is
  tile-granular linear (scan 128-wide rows from the home tile until
  key or an empty slot appears). The dense (S, A) alternative is 33MB
  — VMEM is ~16MB/core, the hash is ~2.6MB at CAP=2^17.
- balances (A/128, 128) lo/hi/used planes.
- per-lane seq counters and book-exists flags as (ceil(S/128), 128)
  planes.

Mosaic constraints that shaped the code (all hit on the real chip,
see scripts/exp_seqkernel.py): jax_enable_x64 poisons fori_loop
induction vars / weak int literals / scalar jnp.sum with i64 that the
lowering cannot convert (use fori32 + np.int32 literals + min/max
reductions only); i1-vector selects do not legalize (select on i32,
compare once); with input_output_aliases the OUTPUT VMEM ref starts
initialized with the input's bytes and state must be read AND written
through it.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kme_tpu.engine.lanes import (  # noqa: F401 (re-exported act codes)
    L_NOP, L_BUY, L_SELL, L_CANCEL, L_CREATE, L_TRANSFER, L_ADD_SYMBOL,
    LERR_OK, LERR_FILLBUF_FULL, METRIC_NAMES, N_METRICS,
    MET_MSGS, MET_TRADES_OK, MET_FILLS, MET_CONTRACTS, MET_REJ_CAPACITY,
    MET_REJ_RISK, MET_RESTED, MET_CANCELS_OK, MET_REJ_CANCEL,
    MET_TRANSFERS_OK, MET_REJ_OTHER, MET_BARRIERS,
    HIST_NAMES, HIST_FILLS, HIST_DEPTH, HIST_OCCUPANCY,
    N_HIST, N_HIST_BUCKETS,
)

# scalar-row histogram window: lanes [HIST_LANE0, HIST_LANE0 + 3*16) of
# output row 0 carry the PER-CALL power-of-two histogram deltas (fills,
# depth, occupancy — HIST_NAMES order). They are accumulated in a VMEM
# scratch row already pre-offset to these lanes, so the epilogue merge
# is one masked where (no lane rotate). Lanes 2..13 hold the 12 metric
# deltas, so the window starts right after them.
HIST_LANE0 = 2 + N_METRICS

# barrier acts (device-executed, unlike the lanes engine where barriers
# are separate settle calls): mode mapping matches barrier_ops.settle
L_PAYOUT_YES = 7
L_PAYOUT_NO = 8
L_REMOVE_SYMBOL = 9

LERR_HASH_FULL = 4   # position hash exhausted (pos_cap knob)
LERR_JAVA_DOMAIN = 5   # java mode: price/size outside the device domain
LERR_JAVA_CAP = 6      # java mode: slots/max_fills device bound exceeded
                       # (the reference's stores are unbounded; hitting
                       # a static capacity is fatal, never a REJECT)

I32 = jnp.int32
_i = np.int32
MIN32 = _i(-(1 << 31))
BIG = _i(1 << 30)
LN = 128

_STATE_KEYS = ("bo_lo", "bo_hi", "ba", "bp", "bs", "bq",
               "seqc", "bex", "bal_lo", "bal_hi", "bal_u",
               "hk", "ha_lo", "ha_hi", "hv_lo", "hv_hi", "dep", "err")

# java mode: Q11 positions are keyed by 128-bit pairs — real keys
# (aid, sid), garbage keys (amount, available) — with true deletion
# (delete-at-zero pops arbitrary keys), so the hash carries four key
# planes + an explicit state plane (0 empty / 1 live / 2 tombstone),
# plus raw-id lookup tables (dense idx -> Java-long aid, lane -> sid)
# the maker-fill path needs to BUILD keys from device-resident ids.
_STATE_KEYS_JAVA = (
    "bo_lo", "bo_hi", "ba", "bp", "bs", "bq",
    "seqc", "bex", "bal_lo", "bal_hi", "bal_u",
    "hka_lo", "hka_hi", "hkb_lo", "hkb_hi", "hstate",
    "ha_lo", "ha_hi", "hv_lo", "hv_hi",
    "araw_lo", "araw_hi", "sraw_lo", "sraw_hi", "err")


def state_keys(cfg: SeqConfig):
    return _STATE_KEYS_JAVA if cfg.compat == "java" else _STATE_KEYS

AMASK = _i((1 << 30) - 1)   # java: ba plane packs aidx | is_buy << 30


@dataclasses.dataclass(frozen=True)
class SeqConfig:
    """Static shapes; one Mosaic program per distinct value."""

    lanes: int = 1024          # S symbols
    slots: int = 128           # N resting orders per side (mult of 128)
    accounts: int = 2048       # A dense account capacity (mult of 128)
    max_fills: int = 16        # E makers swept per taker (H3 envelope)
    batch: int = 4096          # B messages per kernel call (mult of 128)
    pos_cap: int = 1 << 17     # position hash capacity (pow2 mult of 128)
    fill_cap: int = 1 << 15    # fill entries per call (mult of 128)
    probe_max: int = 64        # max hash tiles probed before HASH_FULL
    # compat='java' replicates the reference quirk-for-quirk ON DEVICE
    # (Q1 merged sid-0 book, Q2 ghost trades, Q9, Q11 value-as-key
    # positions with a 128-bit-key tombstoned hash) for the stock wire
    # surface: CREATE/TRANSFER/ADD_SYMBOL(sid>=0)/BUY/SELL/CANCEL with
    # in-domain prices/sizes. Barriers and negative-sid symbols (dead or
    # broken paths in the reference -- Q3-Q6) are routed to the native
    # engine instead; out-of-domain fields trip a sticky error. fixed
    # mode is the performance/envelope path; java mode is the
    # quirk-exact-parity-on-TPU path (COMPAT.md).
    compat: str = "fixed"
    # hbm_books: book planes live in HBM (pl.ANY) and the kernel keeps
    # ONE lane's rows in a VMEM scratch cache, flushed/loaded on lane
    # switch. VMEM cannot hold deep books (slots=8192 at S=1024 is
    # ~400MB across the planes); the Zipf hot lane needs thousands of
    # resting slots for the envelope to stop rejecting flow the
    # reference (unbounded lists, KProcessor.java:200-223) accepts.
    # Lane locality makes switches cheap, and HBM bandwidth (~800GB/s)
    # dwarfs the ~64KB/plane moved per switch.
    hbm_books: bool = False

    def __post_init__(self):
        if self.compat not in ("fixed", "java"):
            raise ValueError(f"unknown compat {self.compat!r}")
        assert self.slots % LN == 0 and self.slots >= LN
        assert self.accounts % LN == 0
        assert self.batch % LN == 0
        assert self.pos_cap % LN == 0 and (
            self.pos_cap & (self.pos_cap - 1)) == 0
        assert self.fill_cap % LN == 0
        assert self.max_fills <= LN
        assert self.lanes * self.accounts + self.accounts < (1 << 31), \
            "hash keys must fit int32"

    @property
    def nr(self):
        return self.slots // LN

    @property
    def srows(self):
        return -(-self.lanes // LN)

    @property
    def arows(self):
        return self.accounts // LN

    @property
    def caprows(self):
        return self.pos_cap // LN


def make_seq_state(cfg: SeqConfig):
    S, NR = cfg.lanes, cfg.nr
    z = lambda r: jnp.zeros((r, LN), I32)
    common = {
        "bo_lo": z(2 * S * NR), "bo_hi": z(2 * S * NR), "ba": z(2 * S * NR),
        "bp": z(2 * S * NR), "bs": z(2 * S * NR), "bq": z(2 * S * NR),
        "seqc": z(cfg.srows), "bex": z(cfg.srows),
        "bal_lo": z(cfg.arows), "bal_hi": z(cfg.arows), "bal_u": z(cfg.arows),
        "err": z(1),
    }
    if cfg.compat == "java":
        common.update({
            "hka_lo": z(cfg.caprows), "hka_hi": z(cfg.caprows),
            "hkb_lo": z(cfg.caprows), "hkb_hi": z(cfg.caprows),
            "hstate": z(cfg.caprows),
            "ha_lo": z(cfg.caprows), "ha_hi": z(cfg.caprows),
            "hv_lo": z(cfg.caprows), "hv_hi": z(cfg.caprows),
            "araw_lo": z(cfg.arows), "araw_hi": z(cfg.arows),
            "sraw_lo": z(cfg.srows), "sraw_hi": z(cfg.srows),
        })
    else:
        common.update({
            "hk": z(cfg.caprows),
            "ha_lo": z(cfg.caprows), "ha_hi": z(cfg.caprows),
            "hv_lo": z(cfg.caprows), "hv_hi": z(cfg.caprows),
            # per-lane occupied-slot count (both sides), maintained
            # incrementally for the book-depth histogram: a both-plane
            # reduction per message would dwarf the message cost
            "dep": z(cfg.srows),
        })
    return common


# ---------------------------------------------------------------------------
# output plane layout (host unpack in unpack_out)

def out_rows(cfg: SeqConfig):
    """Output plane rows: [0] scalars (err, fill_total, metric deltas);
    [1, 1+5BR) per-message regions (flags/residual/nfill/prev lo/hi);
    [1+5BR, ...) fills in GROUPS of 5 rows per 128 entries (oid lo/hi,
    aid, price, size) so the used prefix is ONE contiguous row slice —
    the host fetches header + exactly ceil(fill_total/128) groups."""
    BR, FR = cfg.batch // LN, cfg.fill_cap // LN
    return 1 + 5 * BR + 5 * FR


def hdr_rows(cfg: SeqConfig):
    return 1 + 5 * (cfg.batch // LN)


# ---------------------------------------------------------------------------
# kernel-side helpers (scalar i64 emulation on i32 pairs etc.)

def _fori32(n, body, init):
    """while_loop with an np.int32 counter (see module docstring)."""
    def cond(c):
        return c[0] < _i(n)

    def step(c):
        i, carry = c
        return i + _i(1), body(i, carry)

    return jax.lax.while_loop(cond, step, (_i(0), init))[1]


def _u_lt(a, b):
    return (a ^ MIN32) < (b ^ MIN32)


def _add64(alo, ahi, blo, bhi):
    rlo = alo + blo
    carry = _u_lt(rlo, alo).astype(I32)
    return rlo, ahi + bhi + carry


def _sx(v):
    """sign-extend i32 scalar to an (lo, hi) pair."""
    return v, v >> _i(31)


def _neg64(lo, hi):
    return -lo, ~hi + (lo == _i(0)).astype(I32)


def _lt64(alo, ahi, blo, bhi):
    return (ahi < bhi) | ((ahi == bhi) & _u_lt(alo, blo))


def _sel64(c, a, b):
    return jnp.where(c, a[0], b[0]), jnp.where(c, a[1], b[1])


def _min64(a, b):
    return _sel64(_lt64(*a, *b), a, b)


def _max64(a, b):
    return _sel64(_lt64(*a, *b), b, a)


def _muls64(a, b):
    """Exact i64 product of i32 `a` and SMALL i32 `b` (|b| <= ~2^14):
    16-bit split keeps every partial in i32 range."""
    t1 = (a & _i(0xFFFF)) * b            # [0, 2^16) * b
    t2 = (a >> _i(16)) * b               # [-2^15, 2^15) * b
    return _add64(t2 << _i(16), t2 >> _i(16), *_sx(t1))


def _mul64(alo, ahi, blo, bhi):
    """Full 64x64 -> 64 wrap product (Java long multiply) via 8-bit
    limbs — every limb product < 2^16 and limb accumulators stay far
    inside i32. Only the rare payout credit path uses this."""
    M = _i(0xFF)
    a = [(alo >> _i(8 * k)) & M for k in range(4)] + \
        [(ahi >> _i(8 * k)) & M for k in range(4)]
    b = [(blo >> _i(8 * k)) & M for k in range(4)] + \
        [(bhi >> _i(8 * k)) & M for k in range(4)]
    limbs = []
    carry = _i(0)
    for k in range(8):
        acc = carry
        for i2 in range(k + 1):
            acc = acc + a[i2] * b[k - i2]
        limbs.append(acc & M)
        carry = acc >> _i(8)
    lo = limbs[0] | (limbs[1] << _i(8)) | (limbs[2] << _i(16)) \
        | (limbs[3] << _i(24))
    hi = limbs[4] | (limbs[5] << _i(8)) | (limbs[6] << _i(16)) \
        | (limbs[7] << _i(24))
    return lo, hi


# ---------------------------------------------------------------------------
# the kernel

@functools.lru_cache(maxsize=None)
def build_seq_step(cfg: SeqConfig):
    """Returns the jitted (state, msgs) -> (state, out_plane) callable.

    msgs: dict of (B,) int32 arrays act/oid_lo/oid_hi/aid/price/size/
    lane (host router output; padding entries carry act = L_NOP).
    out_plane: (out_rows, 128) int32 — see unpack_out.
    """
    S, NR, E, B = cfg.lanes, cfg.nr, cfg.max_fills, cfg.batch
    A, CAPR, FB = cfg.accounts, cfg.caprows, cfg.fill_cap
    BR, FR = B // LN, FB // LN
    NROWS = out_rows(cfg)
    PROBE = min(cfg.probe_max, CAPR)
    CAPMASK = _i(cfg.pos_cap - 1)

    HBM = cfg.hbm_books
    JAVA = cfg.compat == "java"
    KEYS = state_keys(cfg)
    NSMEM = 12 if JAVA else 7
    BOOK_KEYS = ("bo_lo", "bo_hi", "ba", "bp", "bs", "bq")

    def kernel(*args):
        # args: NSMEM message arrays, then aliased state ins, state outs
        # + out plane, then scratch: an SMEM scalar row (cross-section
        # results — the heavy sections run under pl.when branches so
        # non-trade messages skip the trade machinery entirely), then
        # (hbm_books) 6 VMEM scratch planes + a DMA semaphore array.
        (act_s, oidlo_s, oidhi_s, aid_s, price_s, size_s,
         lane_s) = args[:7]
        if JAVA:
            aidrlo_s, aidrhi_s, sidrlo_s, sidrhi_s, flags_s = args[7:12]
        refs = args[NSMEM:]
        nst = len(KEYS)
        outs = refs[nst:]
        st = dict(zip(KEYS, outs[:nst]))
        out = outs[nst]
        sm = refs[nst + nst + 1]
        vr = refs[nst + nst + 2]
        if HBM:
            scr = dict(zip(BOOK_KEYS, refs[nst + nst + 3:nst + nst + 9]))
            dsem = refs[nst + nst + 9]

        ci = jax.lax.broadcasted_iota(I32, (1, LN), 1)
        # flat slot index over an (NR, 128) side block
        fi = (jax.lax.broadcasted_iota(I32, (NR, LN), 0) * _i(LN)
              + jax.lax.broadcasted_iota(I32, (NR, LN), 1))

        def pick(row, l):
            """exact scalar extract from a (1,128) row at lane l."""
            return MIN32 ^ jnp.max(
                jnp.where(ci == l, row ^ MIN32, MIN32))

        def pick2(blk, f):
            """extract from an (NR,128) block at flat index f."""
            return MIN32 ^ jnp.max(
                jnp.where(fi == f, blk ^ MIN32, MIN32))

        def put(ref, r, l, v):
            row = ref[pl.ds(r, 1), :]
            ref[pl.ds(r, 1), :] = jnp.where(ci == l, v, row)

        def rget(ref, r, l):
            return pick(ref[pl.ds(r, 1), :], l)

        def set_err(code):
            r0 = st["err"][0:1, :]
            st["err"][0:1, :] = jnp.where(
                (ci == _i(0)) & (r0 == _i(LERR_OK)), code, r0)

        def hbucket(v):
            """power-of-two bucket index of scalar v (lanes.hist_bucket
            semantics): #{k in 0..14 : v >= 2^k}."""
            b = _i(0)
            for k2 in range(N_HIST_BUCKETS - 1):
                b = b + (v >= _i(1 << k2)).astype(I32)
            return b

        def hist_obs(cond, lane0, v):
            """bump the scratch histogram row (pre-offset scalar-row
            lanes) at bucket(v) of the histogram starting at lane0."""
            @pl.when(cond)
            def _():
                hr = vr[NR + 2:NR + 3, :]
                vr[NR + 2:NR + 3, :] = hr + (
                    ci == _i(lane0) + hbucket(v)).astype(I32)

        # -------- balances (row r = acc >> 7, lane l = acc & 127)
        def bal_get(acc):
            r, l = acc >> _i(7), acc & _i(127)
            return rget(st["bal_lo"], r, l), rget(st["bal_hi"], r, l)

        def bal_add(acc, dlo, dhi):
            r, l = acc >> _i(7), acc & _i(127)
            lo, hi = rget(st["bal_lo"], r, l), rget(st["bal_hi"], r, l)
            nlo, nhi = _add64(lo, hi, dlo, dhi)
            put(st["bal_lo"], r, l, nlo)
            put(st["bal_hi"], r, l, nhi)

        # -------- position hash ---------------------------------------
        def h_home(key):
            # Fibonacci hash, tile-granular
            return ((key * _i(-1640531527)) >> _i(7)) & (CAPMASK >> _i(7))

        def h_find(key):
            """-> (flat entry index or -1, err_flag). Scans tiles from
            the home tile until the key or an empty slot appears. The
            FIRST tile probes straight-line (the enforced <=50% load
            factor makes one tile the overwhelmingly common case —
            and merely entering a while_loop costs ~0.9us on this
            Mosaic, scripts/exp_loopbody.py); the loop is entered only
            when tile 0 is full with no hit."""
            t0 = h_home(key)
            krow = st["hk"][pl.ds(t0, 1), :]
            hit = krow == key
            hidx = jnp.min(jnp.where(hit, ci, BIG))
            empty = jnp.min(jnp.where(krow == _i(0), ci, BIG))
            found = hidx < BIG
            stop0 = found | (empty < BIG) | (_i(1) >= _i(PROBE))
            sm[14] = jnp.where(found, t0 * _i(LN) + hidx, _i(-1))
            sm[15] = ((~found) & (_i(1) >= _i(PROBE))).astype(I32)

            @pl.when(~stop0)
            def _():
                def body(c):
                    t, probes, res, done = c
                    kr = st["hk"][pl.ds(t, 1), :]
                    ht = kr == key
                    hx = jnp.min(jnp.where(ht, ci, BIG))
                    em = jnp.min(jnp.where(kr == _i(0), ci, BIG))
                    fnd = hx < BIG
                    stop = (fnd | (em < BIG)
                            | (probes + _i(1) >= _i(PROBE)))
                    res = jnp.where(fnd, t * _i(LN) + hx, res)
                    return ((t + _i(1)) & (CAPMASK >> _i(7)),
                            probes + _i(1), res, stop)

                _, probes, res, _ = jax.lax.while_loop(
                    lambda c: ~c[3], body,
                    ((t0 + _i(1)) & (CAPMASK >> _i(7)), _i(1),
                     _i(-1), False))
                sm[14] = res
                sm[15] = ((res < _i(0))
                          & (probes >= _i(PROBE))).astype(I32)

            return sm[14], sm[15] != _i(0)

        def h_claim(key):
            """find-or-insert -> (flat index, err_flag). First tile
            straight-line, loop only on a full missless tile 0 (see
            h_find)."""
            t0 = h_home(key)
            krow = st["hk"][pl.ds(t0, 1), :]
            hit = krow == key
            hidx = jnp.min(jnp.where(hit, ci, BIG))
            empty = jnp.min(jnp.where(krow == _i(0), ci, BIG))
            found = hidx < BIG
            can_ins = ~found & (empty < BIG)
            res0 = jnp.where(found, t0 * _i(LN) + hidx, _i(-1))
            res0 = jnp.where(can_ins, t0 * _i(LN) + empty, res0)
            sm[14] = res0

            @pl.when(can_ins)
            def _():
                put(st["hk"], t0, empty, key)

            stop0 = found | can_ins | (_i(1) >= _i(PROBE))

            @pl.when(~stop0)
            def _():
                def body(c):
                    t, probes, res, done = c
                    kr = st["hk"][pl.ds(t, 1), :]
                    ht = kr == key
                    hx = jnp.min(jnp.where(ht, ci, BIG))
                    em = jnp.min(jnp.where(kr == _i(0), ci, BIG))
                    fnd = hx < BIG
                    ins = ~fnd & (em < BIG)
                    res = jnp.where(fnd, t * _i(LN) + hx, res)
                    res = jnp.where(ins, t * _i(LN) + em, res)

                    @pl.when(ins)
                    def _():
                        put(st["hk"], t, em, key)

                    stop = fnd | ins | (probes + _i(1) >= _i(PROBE))
                    return ((t + _i(1)) & (CAPMASK >> _i(7)),
                            probes + _i(1), res, stop)

                _, probes, res, _ = jax.lax.while_loop(
                    lambda c: ~c[3], body,
                    ((t0 + _i(1)) & (CAPMASK >> _i(7)), _i(1),
                     _i(-1), False))
                sm[14] = res

            resv = sm[14]
            return resv, resv < _i(0)

        def pos_key(lane, acc):
            return lane * _i(A) + acc + _i(1)

        def pos_get(lane, acc):
            """-> (amt lo, hi, avail lo, hi); zeros when absent."""
            e, _err = h_find(pos_key(lane, acc))
            r, l = e >> _i(7), e & _i(127)
            there = e >= _i(0)
            rr = jnp.where(there, r, _i(0))
            z = _i(0)
            alo = jnp.where(there, rget(st["ha_lo"], rr, l), z)
            ahi = jnp.where(there, rget(st["ha_hi"], rr, l), z)
            vlo = jnp.where(there, rget(st["hv_lo"], rr, l), z)
            vhi = jnp.where(there, rget(st["hv_hi"], rr, l), z)
            return alo, ahi, vlo, vhi

        def pos_set(lane, acc, alo, ahi, vlo, vhi):
            """write a position (claiming a slot if new) -> err_flag."""
            e, err = h_claim(pos_key(lane, acc))
            r, l = jnp.where(e >= _i(0), e >> _i(7), _i(0)), e & _i(127)

            @pl.when(e >= _i(0))
            def _():
                put(st["ha_lo"], r, l, alo)
                put(st["ha_hi"], r, l, ahi)
                put(st["hv_lo"], r, l, vlo)
                put(st["hv_hi"], r, l, vhi)

            return err

        def fill_one(lane, acc, sgn_fill):
            """fillOrder's position half (KProcessor.java:276-287),
            fixed mode: create == update-from-(0,0); delete-at-zero
            writes (0,0). sgn_fill: signed i32 size. -> err_flag."""
            alo, ahi, vlo, vhi = pos_get(lane, acc)
            nalo, nahi = _add64(alo, ahi, *_sx(sgn_fill))
            nvlo, nvhi = _add64(vlo, vhi, *_sx(sgn_fill))
            dead = (nalo == _i(0)) & (nahi == _i(0))
            z = _i(0)
            return pos_set(lane, acc,
                           nalo, nahi,
                           jnp.where(dead, z, nvlo),
                           jnp.where(dead, z, nvhi))

        # -------- java (Q11) position hash: 128-bit keys, tombstones --
        if JAVA:
            def jhome(kal, kah, kbl, kbh):
                h = (kal * _i(-1640531527) ^ kah * _i(-2048144789)
                     ^ kbl * _i(-1028477387) ^ kbh * _i(69069))
                return (h >> _i(7)) & (CAPMASK >> _i(7))

            def _jtile(t, kal, kah, kbl, kbh):
                """probe one tile -> (hidx, empty) lane minima."""
                srow = st["hstate"][pl.ds(t, 1), :]
                live = srow == _i(1)
                eq = (live
                      & (st["hka_lo"][pl.ds(t, 1), :] == kal)
                      & (st["hka_hi"][pl.ds(t, 1), :] == kah)
                      & (st["hkb_lo"][pl.ds(t, 1), :] == kbl)
                      & (st["hkb_hi"][pl.ds(t, 1), :] == kbh))
                hidx = jnp.min(jnp.where(eq, ci, BIG))
                empty = jnp.min(jnp.where(srow == _i(0), ci, BIG))
                return hidx, empty

            def jfind(kal, kah, kbl, kbh):
                """-> (flat entry or -1, err). Tombstones are passed
                over; an EMPTY slot ends the probe. First tile probes
                straight-line (while_loop entry costs ~0.9us on this
                Mosaic — see h_find)."""
                t0 = jhome(kal, kah, kbl, kbh)
                hidx, empty = _jtile(t0, kal, kah, kbl, kbh)
                found = hidx < BIG
                stop0 = found | (empty < BIG) | (_i(1) >= _i(PROBE))
                sm[14] = jnp.where(found, t0 * _i(LN) + hidx, _i(-1))
                sm[15] = ((~found) & (_i(1) >= _i(PROBE))).astype(I32)

                @pl.when(~stop0)
                def _():
                    def body(c):
                        t, probes, res, done = c
                        hx, em = _jtile(t, kal, kah, kbl, kbh)
                        fnd = hx < BIG
                        stop = (fnd | (em < BIG)
                                | (probes + _i(1) >= _i(PROBE)))
                        res = jnp.where(fnd, t * _i(LN) + hx, res)
                        return ((t + _i(1)) & (CAPMASK >> _i(7)),
                                probes + _i(1), res, stop)

                    _, probes, res, _ = jax.lax.while_loop(
                        lambda c: ~c[3], body,
                        ((t0 + _i(1)) & (CAPMASK >> _i(7)), _i(1),
                         _i(-1), False))
                    sm[14] = res
                    sm[15] = ((res < _i(0))
                              & (probes >= _i(PROBE))).astype(I32)

                return sm[14], sm[15] != _i(0)

            def jslot_for_insert(kal, kah, kbl, kbh):
                """-> (flat slot, found_live, err): the live match if it
                exists, else the first reusable (tombstone/empty) slot
                seen on the probe path. First tile straight-line (see
                jfind)."""
                t0 = jhome(kal, kah, kbl, kbh)
                srow = st["hstate"][pl.ds(t0, 1), :]
                hidx, empty = _jtile(t0, kal, kah, kbl, kbh)
                free = jnp.min(jnp.where(srow != _i(1), ci, BIG))
                found0 = hidx < BIG
                reuse0 = jnp.where(free < BIG, t0 * _i(LN) + free,
                                   _i(-1))
                res0 = jnp.where(found0, t0 * _i(LN) + hidx, _i(-1))
                stop0 = (found0 | (empty < BIG)
                         | (_i(1) >= _i(PROBE)))
                sm[13] = res0
                sm[14] = reuse0

                @pl.when(~stop0)
                def _():
                    def body(c):
                        t, probes, res, reuse, done = c
                        sr = st["hstate"][pl.ds(t, 1), :]
                        hx, em = _jtile(t, kal, kah, kbl, kbh)
                        fr = jnp.min(jnp.where(sr != _i(1), ci, BIG))
                        fnd = hx < BIG
                        reuse = jnp.where((reuse < _i(0)) & (fr < BIG),
                                          t * _i(LN) + fr, reuse)
                        res = jnp.where(fnd, t * _i(LN) + hx, res)
                        stop = (fnd | (em < BIG)
                                | (probes + _i(1) >= _i(PROBE)))
                        return ((t + _i(1)) & (CAPMASK >> _i(7)),
                                probes + _i(1), res, reuse, stop)

                    _, probes, res, reuse, _ = jax.lax.while_loop(
                        lambda c: ~c[4], body,
                        ((t0 + _i(1)) & (CAPMASK >> _i(7)), _i(1),
                         res0, reuse0, False))
                    sm[13] = res
                    sm[14] = reuse

                resv = sm[13]
                reusev = sm[14]
                found = resv >= _i(0)
                slot = jnp.where(found, resv, reusev)
                return slot, found, slot < _i(0)

            def jvals(e):
                r, l = e >> _i(7), e & _i(127)
                rr = jnp.where(e >= _i(0), r, _i(0))
                there = e >= _i(0)
                z = _i(0)
                return (jnp.where(there, rget(st["ha_lo"], rr, l), z),
                        jnp.where(there, rget(st["ha_hi"], rr, l), z),
                        jnp.where(there, rget(st["hv_lo"], rr, l), z),
                        jnp.where(there, rget(st["hv_hi"], rr, l), z))

            def jwrite(e, kal, kah, kbl, kbh, alo, ahi, vlo, vhi):
                r, l = e >> _i(7), e & _i(127)

                @pl.when(e >= _i(0))
                def _():
                    put(st["hstate"], r, l, _i(1))
                    put(st["hka_lo"], r, l, kal)
                    put(st["hka_hi"], r, l, kah)
                    put(st["hkb_lo"], r, l, kbl)
                    put(st["hkb_hi"], r, l, kbh)
                    put(st["ha_lo"], r, l, alo)
                    put(st["ha_hi"], r, l, ahi)
                    put(st["hv_lo"], r, l, vlo)
                    put(st["hv_hi"], r, l, vhi)

            def jdelete(e):
                r, l = e >> _i(7), e & _i(127)

                @pl.when(e >= _i(0))
                def _():
                    put(st["hstate"], r, l, _i(2))   # tombstone

            def jfill_one(alo, ahi, slo, shi, sgn_fill):
                """fillOrder java (Q11, KProcessor.java:276-287): first
                fill creates the real (aid, sid) entry; later fills
                read the real entry but write/delete the VALUE-as-key
                (amount, available) target. -> err flag."""
                e, err0 = jfind(alo, ahi, slo, shi)
                amt_lo, amt_hi, av_lo, av_hi = jvals(e)
                absent = e < _i(0)
                nalo, nahi = _add64(amt_lo, amt_hi, *_sx(sgn_fill))
                nvlo, nvhi = _add64(av_lo, av_hi, *_sx(sgn_fill))
                err = err0

                @pl.when(absent & ~err0)
                def _():
                    s2, _f, e2 = jslot_for_insert(alo, ahi, slo, shi)
                    jwrite(s2, alo, ahi, slo, shi,
                           sgn_fill, sgn_fill >> _i(31),
                           sgn_fill, sgn_fill >> _i(31))

                    @pl.when(e2)
                    def _():
                        set_err(_i(LERR_HASH_FULL))

                @pl.when(~absent)
                def _():
                    # target key = the OLD value (amount, available)
                    dead = (nalo == _i(0)) & (nahi == _i(0))

                    @pl.when(dead)
                    def _():
                        t_e, _te = jfind(amt_lo, amt_hi, av_lo, av_hi)
                        jdelete(t_e)   # pop(target, None): no-op absent

                    @pl.when(~dead)
                    def _():
                        s2, _f, e2 = jslot_for_insert(
                            amt_lo, amt_hi, av_lo, av_hi)
                        jwrite(s2, amt_lo, amt_hi, av_lo, av_hi,
                               nalo, nahi, nvlo, nvhi)

                        @pl.when(e2)
                        def _():
                            set_err(_i(LERR_HASH_FULL))

                return err

            def araw_of(acc):
                r, l = acc >> _i(7), acc & _i(127)
                return (rget(st["araw_lo"], r, l),
                        rget(st["araw_hi"], r, l))

            def sraw_of(lane):
                r, l = lane >> _i(7), lane & _i(127)
                return (rget(st["sraw_lo"], r, l),
                        rget(st["sraw_hi"], r, l))

        # -------- book row access -------------------------------------
        # Under hbm_books the CURRENT lane's rows live in the VMEM
        # scratch cache (lane arg ignored; the switch logic in `one`
        # guarantees the cache holds the message's lane before any
        # book-touching path runs).
        def side_base(lane, side):
            return lane * _i(2 * NR) + side * _i(NR)

        def _rows(start, n):
            """Static slice for constant starts (pl.ds rejects numpy
            scalars), dynamic pl.ds for traced ones."""
            if isinstance(start, (int, np.integer)):
                return slice(int(start), int(start) + n)
            return pl.ds(start, n)

        def side_blk(key, lane, side):
            if HBM:
                return scr[key][_rows(side * _i(NR), NR), :]
            return st[key][_rows(side_base(lane, side), NR), :]

        def side_put(key, lane, side, blk):
            if HBM:
                scr[key][_rows(side * _i(NR), NR), :] = blk
            else:
                st[key][_rows(side_base(lane, side), NR), :] = blk

        def slot_write(key, lane, side, f, v):
            blk = side_blk(key, lane, side)
            side_put(key, lane, side, jnp.where(fi == f, v, blk))

        def books_flush(cur):
            """scratch -> HBM rows of lane `cur` (all 6 planes)."""
            for k_, key in enumerate(BOOK_KEYS):
                pltpu.make_async_copy(
                    scr[key], st[key].at[pl.ds(cur * _i(2 * NR), 2 * NR)],
                    dsem.at[_i(k_)]).start()
            for k_, key in enumerate(BOOK_KEYS):
                pltpu.make_async_copy(
                    scr[key], st[key].at[pl.ds(cur * _i(2 * NR), 2 * NR)],
                    dsem.at[_i(k_)]).wait()

        def books_load(lane):
            """HBM rows of `lane` -> scratch (all 6 planes)."""
            for k_, key in enumerate(BOOK_KEYS):
                pltpu.make_async_copy(
                    st[key].at[pl.ds(lane * _i(2 * NR), 2 * NR)],
                    scr[key], dsem.at[_i(k_)]).start()
            for k_, key in enumerate(BOOK_KEYS):
                pltpu.make_async_copy(
                    st[key].at[pl.ds(lane * _i(2 * NR), 2 * NR)],
                    scr[key], dsem.at[_i(k_)]).wait()

        # -------- margin release shared by cancel + wipe --------------
        def release_margin(lane, acc, o_isbuy, o_price, o_size):
            """postRemoveAdjustments (KProcessor.java:325-333): returns
            the balance credit and applies the avail adjustment."""
            signed = jnp.where(o_isbuy, o_size, -o_size)
            alo, ahi, vlo, vhi = pos_get(lane, acc)
            blo, bhi = _add64(alo, ahi, *_neg64(vlo, vhi))  # blocked
            z64 = (_i(0), _i(0))
            nsg = _neg64(*_sx(signed))
            adjlo, adjhi = _sel64(
                o_isbuy,
                _max64(_min64((blo, bhi), z64), nsg),
                _min64(_max64((blo, bhi), z64), nsg))
            unit = jnp.where(o_isbuy, o_price, o_price - _i(100))
            rel_lo, rel_hi = _muls64(signed + adjlo, unit)
            adj_nz = (adjlo != _i(0)) | (adjhi != _i(0))

            err = _i(0)

            @pl.when(adj_nz)
            def _():
                nvlo, nvhi = _add64(vlo, vhi, adjlo, adjhi)
                e = pos_set(lane, acc, alo, ahi, nvlo, nvhi)
                # adj_nz requires an existing position (amt != 0 or
                # avail != 0 implies the entry exists), so pos_set can
                # only fail if the hash itself is broken — fold into
                # the sticky error anyway via the out-of-band plane
                @pl.when(e)
                def _():
                    set_err(_i(LERR_HASH_FULL))

            return rel_lo, rel_hi

        # -------- output row helpers ----------------------------------
        def out_put(region_row, m, v):
            r = region_row + (m >> _i(7))
            put(out, r, m & _i(127), v)

        def fill_put(field, p, v):
            # group layout: 5 consecutive rows per 128 fill entries
            r = _i(1 + 5 * BR) + (p >> _i(7)) * _i(5) + _i(field)
            put(out, r, p & _i(127), v)

        # ==============================================================
        def one(m, carry):
            (fill_total, cur_lane, met) = carry
            act = act_s[m]
            lane = lane_s[m]
            acc = aid_s[m]
            limit = price_s[m]
            size = size_s[m]
            t_oidlo = oidlo_s[m]
            t_oidhi = oidhi_s[m]

            is_trade = (act == _i(L_BUY)) | (act == _i(L_SELL))
            is_buy = act == _i(L_BUY)
            is_cancel = act == _i(L_CANCEL)
            is_barrier = ((act == _i(L_PAYOUT_YES))
                          | (act == _i(L_PAYOUT_NO))
                          | (act == _i(L_REMOVE_SYMBOL)))
            side = jnp.where(is_buy, _i(0), _i(1))
            opp = _i(1) - side
            # sgn: buy -> +1 (low ask first), sell -> -1 (high bid first)
            sgn = jnp.where(is_buy, _i(1), _i(-1))
            if JAVA:
                # Q1: sid=0's buy and sell books share one key (-0 == 0)
                # — both directions rest into and sweep side 0
                merged = (flags_s[m] & _i(1)) != _i(0)
                side = jnp.where(merged, _i(0), side)
                opp = jnp.where(merged, _i(0), opp)
                a_rlo, a_rhi = aidrlo_s[m], aidrhi_s[m]
                s_rlo, s_rhi = sidrlo_s[m], sidrhi_s[m]

            if HBM:
                needs_books = is_trade | is_cancel | is_barrier
                do_switch = needs_books & (lane != cur_lane)

                @pl.when(do_switch & (cur_lane >= _i(0)))
                def _():
                    books_flush(cur_lane)

                @pl.when(do_switch)
                def _():
                    books_load(lane)

                cur_lane = jnp.where(do_switch, lane, cur_lane)

            lr, ll = lane >> _i(7), lane & _i(127)
            bex_v = rget(st["bex"], lr, ll) != _i(0)

            if JAVA:
                # raw-id tables: every actor-ful message refreshes its
                # dense->raw binding (idempotent); ADD_SYMBOL binds the
                # lane's sid (trades gate on book_exists, so fills only
                # ever read bound lanes)
                has_actor = (is_trade | is_cancel | (act == _i(L_CREATE))
                             | (act == _i(L_TRANSFER)))

                @pl.when(has_actor)
                def _():
                    ar, al = acc >> _i(7), acc & _i(127)
                    put(st["araw_lo"], ar, al, a_rlo)
                    put(st["araw_hi"], ar, al, a_rhi)

                @pl.when(act == _i(L_ADD_SYMBOL))
                def _():
                    put(st["sraw_lo"], lr, ll, s_rlo)
                    put(st["sraw_hi"], lr, ll, s_rhi)

            blo, bhi = bal_get(acc)
            bal_ok = rget(st["bal_u"], acc >> _i(7), acc & _i(127)) != _i(0)

            # ---------------- CREATE / TRANSFER / ADD_SYMBOL ----------
            create_ok = (act == _i(L_CREATE)) & ~bal_ok
            neg_sz = -size  # Java int negation (wraps at INT_MIN)
            transfer_ok = ((act == _i(L_TRANSFER)) & bal_ok
                           & ~_lt64(blo, bhi, *_sx(neg_sz)))
            addsym_ok = (act == _i(L_ADD_SYMBOL)) & ~bex_v

            @pl.when(create_ok)
            def _():
                put(st["bal_u"], acc >> _i(7), acc & _i(127), _i(1))

            @pl.when(transfer_ok)
            def _():
                bal_add(acc, *_sx(size))

            @pl.when(addsym_ok)
            def _():
                put(st["bex"], lr, ll, _i(1))

            # ---------------- cross-section scalar defaults -----------
            # sm: 0 trade_ok, 1 trade_acc, 2 cap_reject, 3 append,
            #     4 residual echo, 5 nfill, 6/7 tail prev lo/hi,
            #     8 do_rest, 9 cancel_ok, 10 emptied-maker count (dep
            #     plane decrement). The heavy sections below run
            #     under pl.when(act) branches (a NOP/CREATE message
            #     must not pay for hash probes or book reductions) and
            #     publish their scalar results here for the epilogue.
            sm[0] = _i(0)
            sm[1] = _i(0)
            sm[2] = _i(0)
            sm[3] = _i(0)
            sm[4] = size
            sm[5] = _i(0)
            sm[6] = _i(0)
            sm[7] = _i(0)
            sm[8] = _i(0)
            sm[9] = _i(0)
            sm[10] = _i(0)

            # ================ TRADE section (pl.when-gated) ===========
            @pl.when(is_trade)
            def _trade_section():
                # -------- margin (checkBalance) -----------------------
                valid = ((limit >= _i(0)) & (limit < _i(126))
                         & (size > _i(0)))
                signed = jnp.where(is_buy, size, -size)
                if JAVA:
                    # the reference runs UNVALIDATED fields (no valid
                    # gate); out-of-domain values would corrupt the
                    # dense book layout, so they are a fatal
                    # device-envelope error
                    @pl.when(~valid)
                    def _():
                        set_err(_i(LERR_JAVA_DOMAIN))
                    e_actor, aerr = jfind(a_rlo, a_rhi, s_rlo, s_rhi)
                    palo, pahi, pvlo, pvhi = jvals(e_actor)
                else:
                    palo, pahi, pvlo, pvhi = pos_get(lane, acc)
                z64 = (_i(0), _i(0))
                nsg = _neg64(*_sx(signed))
                adjlo, adjhi = _sel64(
                    is_buy,
                    _max64(_min64((pvlo, pvhi), z64), nsg),
                    _min64(_max64((pvlo, pvhi), z64), nsg))
                unit = jnp.where(is_buy, limit, limit - _i(100))
                risk_lo, risk_hi = _muls64(signed + adjlo, unit)
                gates = bex_v & bal_ok if JAVA \
                    else (valid & bex_v & bal_ok)
                trade_ok = gates & ~_lt64(blo, bhi, risk_lo, risk_hi)

                # -------- phase 1: non-mutating sweep -----------------
                op_blk = side_blk("bp", lane, opp)
                os_blk = side_blk("bs", lane, opp)
                oq_blk = side_blk("bq", lane, opp)

                # working state lives in the vr scratch (rows 0..NR-1:
                # opp-side sizes, row NR: fill slots, row NR+1: fill
                # sizes): vector while-carries cost ~2us/iteration on
                # Mosaic (measured, scripts/exp_devpath.py round 5);
                # scratch rows + scalar-only carries make an iteration
                # tens of ns
                want = jnp.where(trade_ok, size, _i(0))

                # init UNCONDITIONALLY per trade message: the post-loop
                # reads (wsize at the Q2 ghost probe, the merged-book
                # w_blk select) run for every trade, including a
                # balance-rejected one (want == 0) — gating this on
                # `want > 0` would let those reads see the PREVIOUS
                # message's stale scratch rows
                vr[0:NR, :] = os_blk
                z = jnp.zeros((1, LN), I32)
                vr[NR:NR + 1, :] = z
                vr[NR + 1:NR + 2, :] = z

                def sweep(c):
                    # SELF-CONTAINED body: every vector it touches is a
                    # ref load or a recomputed iota — closure-captured
                    # vector VALUES become per-iteration loop inputs in
                    # Mosaic and cost ~2us/iteration (measured)
                    remaining, e, ovf, emptied, nempt, done = c
                    fi2 = (jax.lax.broadcasted_iota(I32, (NR, LN), 0)
                           * _i(LN)
                           + jax.lax.broadcasted_iota(I32, (NR, LN), 1))
                    ci2 = jax.lax.broadcasted_iota(I32, (1, LN), 1)
                    p_blk = side_blk("bp", lane, opp)
                    q_blk = side_blk("bq", lane, opp)
                    wsize = vr[0:NR, :]
                    cross = (wsize > _i(0)) & (
                        (p_blk - limit) * sgn <= _i(0))
                    pstar = jnp.min(jnp.where(cross, p_blk * sgn, BIG))
                    anyc = (pstar < BIG) & (remaining > _i(0))
                    at = cross & (p_blk * sgn == pstar)
                    sstar = jnp.min(jnp.where(at, q_blk, BIG))
                    at2 = at & (q_blk == sstar)
                    flat = jnp.min(jnp.where(at2, fi2, BIG))
                    have = MIN32 ^ jnp.max(
                        jnp.where(fi2 == flat, wsize ^ MIN32, MIN32))
                    fill = jnp.minimum(remaining, have)
                    exceed = anyc & (e >= _i(E))
                    take = anyc & ~exceed

                    @pl.when(take)
                    def _():
                        vr[0:NR, :] = jnp.where(fi2 == flat, wsize - fill,
                                                wsize)
                        fsr = vr[NR:NR + 1, :]
                        vr[NR:NR + 1, :] = jnp.where(ci2 == e, flat, fsr)
                        ffr = vr[NR + 1:NR + 2, :]
                        vr[NR + 1:NR + 2, :] = jnp.where(ci2 == e, fill, ffr)

                    remaining = remaining - jnp.where(take, fill, _i(0))
                    e = e + jnp.where(take, _i(1), _i(0))
                    ovf = ovf | exceed
                    # did the LAST executed trade exhaust its maker exactly?
                    # (the Q2 ghost-trade precondition: the reference loop
                    # re-evaluates its guard only after a maker empties)
                    emptied = jnp.where(take, have - fill == _i(0), emptied)
                    # emptied-maker COUNT: the dep plane's trade decrement
                    nempt = nempt + (take
                                     & (have - fill == _i(0))).astype(I32)
                    done = (~anyc) | exceed | (remaining == _i(0))
                    return remaining, e, ovf, emptied, nempt, done

                (residual_t, nfill, ovf_fills, last_emptied, nempt, _d) = \
                    jax.lax.while_loop(lambda c: ~c[5], sweep,
                                       (want, _i(0), False, False, _i(0),
                                        want == _i(0)))
                wsize = vr[0:NR, :]
                if JAVA:
                    # Q2 (KProcessor.java:237 precedence): with the taker
                    # exhausted, the guard parses to `maker.price >= limit`
                    # regardless of direction — when the last fill emptied
                    # its maker and the NEXT best maker satisfies it, ONE
                    # zero-size trade emits before `maker.size != 0` breaks
                    live_g = wsize > _i(0)
                    gbest = jnp.min(jnp.where(live_g, op_blk * sgn, BIG))
                    g_at = live_g & (op_blk * sgn == gbest)
                    g_ss = jnp.min(jnp.where(g_at, oq_blk, BIG))
                    g_at2 = g_at & (oq_blk == g_ss)
                    gflat = jnp.min(jnp.where(g_at2, fi, BIG))
                    gfc = jnp.where(gbest < BIG, gflat, _i(0))
                    g_price = pick2(op_blk, gfc)
                    ghost = (trade_ok & (residual_t == _i(0)) & last_emptied
                             & (gbest < BIG) & (g_price >= limit))
                    ghost_ok = ghost & (nfill < _i(E))

                    @pl.when(ghost & (nfill >= _i(E)))
                    def _():
                        set_err(_i(LERR_JAVA_CAP))

                    fsr = vr[NR:NR + 1, :]
                    vr[NR:NR + 1, :] = jnp.where(ghost_ok & (ci == nfill),
                                                 gfc, fsr)
                    ffr = vr[NR + 1:NR + 2, :]
                    vr[NR + 1:NR + 2, :] = jnp.where(
                        ghost_ok & (ci == nfill), _i(0), ffr)
                    nfill = nfill + ghost_ok.astype(I32)

                # ---------------- capacity envelope + Q9 ------------------
                w_blk = side_blk("bs", lane, side)      # own side sizes
                if JAVA:
                    # merged (Q1) books: the sweep just consumed from the
                    # SAME side the residual rests on — the free-slot
                    # search and the Q9 bucket tail must see POST-sweep
                    # sizes (the reference's bitmap bit is unset when the
                    # bucket empties mid-sweep, so the rest creates a NEW
                    # bucket with prev = null)
                    w_blk = jnp.where(is_trade & merged, wsize, w_blk)
                wp_blk = side_blk("bp", lane, side)
                wq_blk = side_blk("bq", lane, side)
                free_flat = jnp.min(jnp.where(w_blk == _i(0), fi, BIG))
                have_free = free_flat < BIG
                rest_want = trade_ok & (residual_t > _i(0))
                ovf_book = rest_want & ~have_free
                if JAVA:
                    # unbounded reference stores: hitting a device capacity
                    # is FATAL (sticky error), never a per-message REJECT
                    @pl.when(trade_ok & (ovf_fills | ovf_book))
                    def _():
                        set_err(_i(LERR_JAVA_CAP))

                    cap_reject = is_trade & False
                    trade_acc = trade_ok
                else:
                    cap_reject = trade_ok & (ovf_fills | ovf_book)
                    trade_acc = trade_ok & ~cap_reject
                do_rest = rest_want & trade_acc & have_free

                same_level = (w_blk > _i(0)) & (wp_blk == limit)
                bucket_nonempty = jnp.max(
                    jnp.where(same_level, _i(1), _i(0))) == _i(1)
                smax = jnp.max(jnp.where(same_level, wq_blk, _i(-1)))
                tail_at = same_level & (wq_blk == smax)
                tail_flat = jnp.min(jnp.where(tail_at, fi, BIG))
                tfc = jnp.where(bucket_nonempty, tail_flat, _i(0))
                tail_lo = pick2(side_blk("bo_lo", lane, side), tfc)
                tail_hi = pick2(side_blk("bo_hi", lane, side), tfc)
                append = bucket_nonempty & do_rest

                # ---------------- TRADE phase 2: apply --------------------
                @pl.when(trade_acc)
                def _():
                    # checkBalance debit + adj-write (before the fills, the
                    # reference's order — final state is order-invariant
                    # but the position write must precede fill updates of
                    # the SAME key)
                    bal_add(acc, *_neg64(risk_lo, risk_hi))
                    adj_nz = (adjlo != _i(0)) | (adjhi != _i(0))

                    @pl.when(adj_nz)
                    def _():
                        nvlo, nvhi = _add64(pvlo, pvhi, *_neg64(adjlo, adjhi))
                        if JAVA:
                            # 3-arg setPosition: the REAL key keeps its
                            # amount, only `available` moves
                            # (KProcessor.java:179, exempt from Q11)
                            jwrite(e_actor, a_rlo, a_rhi, s_rlo, s_rhi,
                                   palo, pahi, nvlo, nvhi)
                        else:
                            e = pos_set(lane, acc, palo, pahi, nvlo, nvhi)

                            @pl.when(e)
                            def _():
                                set_err(_i(LERR_HASH_FULL))

                    # maker size writeback (size==0 deletes the slot)
                    side_put("bs", lane, opp, wsize)

                    def apply_fill(e2, _c):
                        # self-contained: blocks load inside (captured
                        # vectors become per-iteration loop inputs)
                        oa_blk = side_blk("ba", lane, opp)
                        olo_blk = side_blk("bo_lo", lane, opp)
                        ohi_blk = side_blk("bo_hi", lane, opp)
                        mp_blk = side_blk("bp", lane, opp)
                        flat = pick(vr[NR:NR + 1, :], e2)
                        fill = pick(vr[NR + 1:NR + 2, :], e2)
                        maid_raw_plane = pick2(oa_blk, flat)
                        maid = (maid_raw_plane & AMASK) if JAVA \
                            else maid_raw_plane
                        mprice = pick2(mp_blk, flat)
                        p = fill_total + e2
                        pc = jnp.minimum(p, _i(FB - 1))

                        @pl.when(p < _i(FB))
                        def _():
                            fill_put(0, pc, pick2(olo_blk, flat))
                            fill_put(1, pc, pick2(ohi_blk, flat))
                            fill_put(2, pc, maid)
                            fill_put(3, pc, mprice)
                            fill_put(4, pc, fill)

                        # maker fill then taker fill (executeTrade order)
                        msz = jnp.where(is_buy, -fill, fill)
                        tsz = jnp.where(is_buy, fill, -fill)
                        if JAVA:
                            mr, ml = maid >> _i(7), maid & _i(127)
                            m_rlo = rget(st["araw_lo"], mr, ml)
                            m_rhi = rget(st["araw_hi"], mr, ml)
                            me = jfill_one(m_rlo, m_rhi, s_rlo, s_rhi, msz)
                            te = jfill_one(a_rlo, a_rhi, s_rlo, s_rhi, tsz)
                        else:
                            me = fill_one(lane, maid, msz)
                            te = fill_one(lane, acc, tsz)
                        # taker credit: int*int wraps at i32 before the
                        # long add (KProcessor.java:286); maker credit is 0
                        bal_add(acc, *_sx(tsz * (limit - mprice)))

                        @pl.when(me | te)
                        def _():
                            set_err(_i(LERR_HASH_FULL))

                        return _c

                    # peeled: fill 0 straight-line, loop only for 2+
                    @pl.when(nfill > _i(0))
                    def _():
                        apply_fill(_i(0), _i(0))

                    @pl.when(nfill > _i(1))
                    def _():
                        jax.lax.while_loop(
                            lambda c: c[0] < nfill,
                            lambda c: (c[0] + _i(1),
                                       apply_fill(c[0], c[1])),
                            (_i(1), _i(0)))

                    @pl.when(fill_total + nfill > _i(FB))
                    def _():
                        set_err(_i(LERR_FILLBUF_FULL))

                    # rest the residual
                    @pl.when(do_rest)
                    def _():
                        seqv = rget(st["seqc"], lr, ll)
                        slot_write("bo_lo", lane, side, free_flat, t_oidlo)
                        slot_write("bo_hi", lane, side, free_flat, t_oidhi)
                        ba_val = (acc | (is_buy.astype(I32) << _i(30))) \
                            if JAVA else acc
                        slot_write("ba", lane, side, free_flat, ba_val)
                        slot_write("bp", lane, side, free_flat, limit)
                        slot_write("bs", lane, side, free_flat, residual_t)
                        slot_write("bq", lane, side, free_flat, seqv)
                        put(st["seqc"], lr, ll, seqv + _i(1))

                # publish section results for the epilogue
                sm[0] = trade_ok.astype(I32)
                sm[1] = trade_acc.astype(I32)
                sm[2] = cap_reject.astype(I32)
                sm[3] = append.astype(I32)
                sm[4] = jnp.where(trade_acc, residual_t, size)
                sm[5] = jnp.where(trade_acc, nfill, _i(0))
                sm[6] = tail_lo
                sm[7] = tail_hi
                sm[8] = do_rest.astype(I32)
                sm[10] = jnp.where(trade_acc, nempt, _i(0))

            # ---------------- CANCEL ----------------------------------
            # (pl.when-gated: only cancels pay for the
            # both-sides oid search)
            @pl.when(is_cancel)
            def _cancel_section():
                # search both sides for the oid among occupied slots
                b0 = side_blk("bo_lo", lane, _i(0))
                b0h = side_blk("bo_hi", lane, _i(0))
                s0 = side_blk("bs", lane, _i(0))
                b1 = side_blk("bo_lo", lane, _i(1))
                b1h = side_blk("bo_hi", lane, _i(1))
                s1 = side_blk("bs", lane, _i(1))
                hit0 = (s0 > _i(0)) & (b0 == t_oidlo) & (b0h == t_oidhi)
                hit1 = (s1 > _i(0)) & (b1 == t_oidlo) & (b1h == t_oidhi)
                f0 = jnp.min(jnp.where(hit0, fi, BIG))
                f1 = jnp.min(jnp.where(hit1, fi, BIG))
                c_side = jnp.where(f0 < BIG, _i(0), _i(1))
                c_flat = jnp.where(f0 < BIG, f0, f1)
                hit_any = is_cancel & (c_flat < BIG)
                cfc = jnp.where(hit_any, c_flat, _i(0))
                c_ba = pick2(side_blk("ba", lane, c_side), cfc)
                c_aid = (c_ba & AMASK) if JAVA else c_ba
                # merged (Q1) books hold both directions in side 0, so java
                # reads the order's direction from the ba tag bit
                c_isbuy = ((c_ba >> _i(30)) & _i(1)) == _i(1) if JAVA \
                    else c_side == _i(0)
                c_price = pick2(side_blk("bp", lane, c_side), cfc)
                c_size = pick2(side_blk("bs", lane, c_side), cfc)
                cancel_ok = hit_any & (c_aid == acc)

                @pl.when(cancel_ok)
                def _():
                    slot_write("bs", lane, c_side, c_flat, _i(0))
                    if JAVA:
                        # postRemoveAdjustments is Q11-CORRUPTED too
                        # (KProcessor.java:332, 2-arg setPosition): the
                        # adj-write lands on the VALUE-as-key target, the
                        # real (aid, sid) entry stays untouched
                        e_c, _ce = jfind(a_rlo, a_rhi, s_rlo, s_rhi)
                        calo, cahi, cvlo, cvhi = jvals(e_c)
                        cblo, cbhi = _add64(calo, cahi, *_neg64(cvlo, cvhi))
                        csigned = jnp.where(c_isbuy, c_size, -c_size)
                        cz = (_i(0), _i(0))
                        cns = _neg64(*_sx(csigned))
                        cjlo, cjhi = _sel64(
                            c_isbuy,
                            _max64(_min64((cblo, cbhi), cz), cns),
                            _min64(_max64((cblo, cbhi), cz), cns))
                        cunit = jnp.where(c_isbuy, c_price,
                                          c_price - _i(100))
                        rlo, rhi = _muls64(csigned + cjlo, cunit)
                        c_nz = (cjlo != _i(0)) | (cjhi != _i(0))

                        @pl.when(c_nz)
                        def _():
                            nvlo, nvhi = _add64(cvlo, cvhi, cjlo, cjhi)
                            s2, _f2, ce2 = jslot_for_insert(
                                calo, cahi, cvlo, cvhi)
                            jwrite(s2, calo, cahi, cvlo, cvhi,
                                   calo, cahi, nvlo, nvhi)

                            @pl.when(ce2)
                            def _():
                                set_err(_i(LERR_HASH_FULL))
                    else:
                        rlo, rhi = release_margin(lane, acc, c_isbuy,
                                                  c_price, c_size)
                    bal_add(acc, rlo, rhi)

                sm[9] = cancel_ok.astype(I32)

            # ---------------- BARRIERS (payout / remove) --------------
            barrier_do = is_barrier & bex_v if not JAVA \
                else is_barrier & False

            @pl.when(barrier_do)
            def _():
                if JAVA:
                    return  # the java router never routes barriers
                # wipe both sides with margin release, buy side first,
                # (price, seq) order within a side (_wipe_book_fixed)
                def wipe_side(wside):
                    pb = side_blk("bp", lane, wside)
                    qb = side_blk("bq", lane, wside)
                    ab = side_blk("ba", lane, wside)

                    def w_body(c):
                        _k, done = c
                        sb = side_blk("bs", lane, wside)
                        used = sb > _i(0)
                        pmin = jnp.min(jnp.where(used, pb, BIG))
                        anyu = pmin < BIG

                        pm = jnp.where(anyu, pmin, _i(0))
                        at = used & (pb == pm)
                        smin = jnp.min(jnp.where(at, qb, BIG))
                        at2 = at & (qb == smin)
                        flat = jnp.min(jnp.where(at2, fi, BIG))
                        fc = jnp.where(anyu, flat, _i(0))

                        @pl.when(anyu)
                        def _():
                            o_aid = pick2(ab, fc)
                            o_price = pick2(pb, fc)
                            o_size = pick2(sb, fc)
                            slot_write("bs", lane, wside, fc, _i(0))
                            rlo, rhi = release_margin(
                                lane, o_aid, wside == _i(0),
                                o_price, o_size)
                            bal_add(o_aid, rlo, rhi)

                        return _k + _i(1), ~anyu

                    jax.lax.while_loop(lambda c: ~c[1], w_body,
                                       (_i(0), False))

                wipe_side(_i(0))
                wipe_side(_i(1))
                put(st["bex"], lr, ll, _i(0))

                # payout: credit (YES) / just delete (NO) the lane's
                # positions — hash scan; entries keep their keys, a
                # zeroed amt/avail IS deletion (the absence invariant)
                is_payout = act != _i(L_REMOVE_SYMBOL)
                do_credit = act == _i(L_PAYOUT_YES)

                @pl.when(is_payout)
                def _():
                    klo = lane * _i(A) + _i(1)

                    def scan_row(tr, _c):
                        krow = st["hk"][pl.ds(tr, 1), :]
                        mine = (krow >= klo) & (krow < klo + _i(A))
                        arow_lo = st["ha_lo"][pl.ds(tr, 1), :]
                        arow_hi = st["ha_hi"][pl.ds(tr, 1), :]
                        live = mine & ((arow_lo != _i(0))
                                       | (arow_hi != _i(0)))

                        @pl.when(do_credit
                                 & (jnp.max(jnp.where(live, _i(1), _i(0)))
                                    == _i(1)))
                        def _():
                            def credit_one(c):
                                rem, done = c
                                l2 = jnp.min(jnp.where(
                                    rem > _i(0), ci, BIG))
                                anyl = l2 < BIG
                                lc = jnp.where(anyl, l2, _i(0))

                                @pl.when(anyl)
                                def _():
                                    a2lo = pick(arow_lo, lc)
                                    a2hi = pick(arow_hi, lc)
                                    acc2 = pick(krow, lc) - klo
                                    plo, phi = _mul64(a2lo, a2hi,
                                                      *_sx(size))
                                    bal_add(acc2, plo, phi)

                                rem = jnp.where(ci == lc, _i(0), rem)
                                return rem, ~anyl

                            jax.lax.while_loop(
                                lambda c: ~c[1], credit_one,
                                (jnp.where(live, _i(1), _i(0)), False))

                        # delete: zero amt + avail where mine
                        st["ha_lo"][pl.ds(tr, 1), :] = jnp.where(
                            mine, _i(0), arow_lo)
                        st["ha_hi"][pl.ds(tr, 1), :] = jnp.where(
                            mine, _i(0), arow_hi)
                        vr_lo = st["hv_lo"][pl.ds(tr, 1), :]
                        vr_hi = st["hv_hi"][pl.ds(tr, 1), :]
                        st["hv_lo"][pl.ds(tr, 1), :] = jnp.where(
                            mine, _i(0), vr_lo)
                        st["hv_hi"][pl.ds(tr, 1), :] = jnp.where(
                            mine, _i(0), vr_hi)
                        return _c

                    _fori32(CAPR, scan_row, _i(0))

            # ---------------- outputs + metrics -----------------------
            t_ok = sm[0] != _i(0)
            t_acc = sm[1] != _i(0)
            capr = sm[2] != _i(0)
            appnd = sm[3]
            resid_v = sm[4]
            nf = sm[5]
            c_ok = sm[9] != _i(0)

            # ------------- dep plane + distribution histograms --------
            # fills-per-order: one observation per ACCEPTED trade
            hist_obs(t_acc, HIST_LANE0, nf)
            if not JAVA:
                # per-lane occupied-slot count: +rested -emptied on an
                # accepted trade, -1 on a cancel, wiped by a barrier;
                # the post-message value feeds the book-depth histogram
                @pl.when(t_acc | c_ok | barrier_do)
                def _():
                    dval = rget(st["dep"], lr, ll)
                    newd = jnp.where(
                        barrier_do, _i(0),
                        dval + sm[8] - sm[10] - c_ok.astype(I32))
                    put(st["dep"], lr, ll, newd)
                    hist_obs(t_acc | c_ok,
                             HIST_LANE0 + N_HIST_BUCKETS, newd)

            ok = jnp.where(
                is_trade, t_acc,
                jnp.where(is_cancel, c_ok,
                          jnp.where(act == _i(L_CREATE), create_ok,
                                    jnp.where(act == _i(L_TRANSFER),
                                              transfer_ok,
                                              jnp.where(
                                                  act == _i(L_ADD_SYMBOL),
                                                  addsym_ok,
                                                  jnp.where(
                                                      is_barrier,
                                                      barrier_do,
                                                      act == _i(L_NOP)))))))
            flags = (ok.astype(I32) | (capr.astype(I32) << _i(1))
                     | (appnd << _i(2)))
            out_put(_i(1), m, flags)
            out_put(_i(1 + BR), m, resid_v)
            out_put(_i(1 + 2 * BR), m, nf)
            out_put(_i(1 + 3 * BR), m, sm[6])
            out_put(_i(1 + 4 * BR), m, sm[7])

            filled = jnp.where(t_acc, size - resid_v, _i(0))
            cnt = lambda c: c.astype(I32)
            met = (
                met[0] + cnt(act != _i(L_NOP)),
                met[1] + cnt(t_acc),
                met[2] + nf,
                met[3] + filled,
                met[4] + cnt(capr),
                met[5] + cnt(is_trade & ~t_ok),
                met[6] + sm[8],
                met[7] + cnt(c_ok),
                met[8] + cnt(is_cancel & ~c_ok),
                met[9] + cnt(transfer_ok),
                met[10] + cnt(((act == _i(L_CREATE)) & ~create_ok)
                              | ((act == _i(L_TRANSFER)) & ~transfer_ok)
                              | ((act == _i(L_ADD_SYMBOL)) & ~addsym_ok)),
                met[11] + cnt(barrier_do),
            )
            fill_total2 = fill_total + nf
            return (fill_total2, cur_lane, met)

        # per-call histogram deltas accumulate in the scratch row,
        # pre-offset to their final scalar-row lanes
        vr[NR + 2:NR + 3, :] = jnp.zeros((1, LN), I32)
        met0 = tuple(_i(0) for _ in range(N_METRICS))
        fill_total, cur_lane, met = _fori32(
            B, one, (_i(0), _i(-1), met0))
        if HBM:
            @pl.when(cur_lane >= _i(0))
            def _():
                books_flush(cur_lane)

        # batch occupancy: ONE observation per non-empty kernel call
        # (met[0] = this call's non-NOP message count)
        hist_obs(met[0] > _i(0), HIST_LANE0 + 2 * N_HIST_BUCKETS, met[0])

        # scalar row: lane0 err, lane1 fill_total, lanes 2.. metrics,
        # lanes HIST_LANE0.. the histogram deltas (already in place in
        # the scratch row)
        errv = pick(st["err"][0:1, :], _i(0))
        scal = jnp.where(ci == _i(0), errv, _i(0))
        scal = jnp.where(ci == _i(1), fill_total, scal)
        for k in range(N_METRICS):
            scal = jnp.where(ci == _i(2 + k), met[k], scal)
        hr = vr[NR + 2:NR + 3, :]
        scal = jnp.where(
            (ci >= _i(HIST_LANE0))
            & (ci < _i(HIST_LANE0 + N_HIST * N_HIST_BUCKETS)), hr, scal)
        out[0:1, :] = scal

    nstate = len(KEYS)
    MSG_FIELDS = ("act", "oid_lo", "oid_hi", "aid", "price", "size",
                  "lane") + (("aidr_lo", "aidr_hi", "sidr_lo",
                              "sidr_hi", "flags") if JAVA else ())

    def _spec(key):
        if cfg.hbm_books and key in BOOK_KEYS:
            return pl.BlockSpec(memory_space=pl.ANY)
        return pl.BlockSpec(memory_space=pltpu.VMEM)

    scratches = [pltpu.SMEM((16,), I32),
                 pltpu.VMEM((NR + 3, LN), I32)] \
        + ([pltpu.VMEM((2 * NR, LN), I32)] * 6
           + [pltpu.SemaphoreType.DMA((6,))] if cfg.hbm_books else [])

    def raw_call(state, msgs):
        outs = pl.pallas_call(
            kernel,
            out_shape=tuple(
                [jax.ShapeDtypeStruct(state[k].shape, I32)
                 for k in KEYS]
                + [jax.ShapeDtypeStruct((NROWS, LN), I32)]),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * NSMEM
            + [_spec(k) for k in KEYS],
            out_specs=tuple([_spec(k) for k in KEYS]
                            + [pl.BlockSpec(memory_space=pltpu.VMEM)]),
            input_output_aliases={NSMEM + k: k for k in range(nstate)},
            scratch_shapes=scratches,
            interpret=jax.default_backend() != "tpu",
        )(*[msgs[f] for f in MSG_FIELDS],
          *[state[k] for k in KEYS])
        new_state = dict(zip(KEYS, outs[:nstate]))
        return new_state, outs[nstate]

    # NOTE: jit-level donation composes badly with the pallas-level
    # input_output_aliases (the donated state buffers get clobbered and
    # the aliased outputs read zeros — observed under interpret); the
    # aliasing alone keeps the in-kernel copy semantics, at the cost of
    # one XLA copy of the state per call (~10MB, ~12us on v5e).
    return jax.jit(raw_call), raw_call


@functools.lru_cache(maxsize=None)
def build_seq_scan(cfg: SeqConfig, k: int):
    """ONE jitted dispatch for k chunks: lax.scan threads the state
    through k kernel invocations and stacks the k output planes on
    device. On the tunneled driver every separate dispatch/fetch costs
    ~a round trip (~100-150ms blocked), so a 100k-message stream runs
    as one scan call + two sliced fetches instead of ~26 of each."""
    _, raw_call = build_seq_step(cfg)

    def call_scan(state, stacked):
        def body(st, ms):
            st2, outp = raw_call(st, ms)
            return st2, outp

        return jax.lax.scan(body, state, stacked, length=k)

    return jax.jit(call_scan)


def step_cost_analysis(cfg: SeqConfig, k: int = 4):
    """Compiled-scan cost model for the profiler's device plane
    (telemetry/profiler.py): lower + compile a k-chunk NOP batch and
    read XLA's `cost_analysis()` — flops and bytes touched per
    dispatch, normalized to {"flops", "bytes_accessed"}. The lowering
    hits the same jit cache the serving path warms, so calling this on
    a live session costs one metadata read, not a recompile. Returns
    None when the backend exposes no cost model (never raises — the
    profiler degrades, the engine does not)."""
    try:
        state = make_seq_state(cfg)
        cols = {name: np.zeros(cfg.batch, np.int64)
                for name in ("act", "aid", "price", "size", "lane",
                             "oid", "aid_raw", "sid_raw", "flags")}
        one = pack_msgs(cfg, cols, 0)
        stacked = {name: np.broadcast_to(
            v, (k,) + v.shape).copy() for name, v in one.items()}
        compiled = build_seq_scan(cfg, k).lower(state, stacked).compile()
        ca = compiled.cost_analysis()
    except Exception:   # noqa: BLE001 — cost probe only, never fatal
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    nbytes = ca.get("bytes accessed", ca.get("bytes_accessed"))
    out = {}
    if isinstance(flops, (int, float)) and flops > 0:
        out["flops"] = float(flops)
    if isinstance(nbytes, (int, float)) and nbytes > 0:
        out["bytes_accessed"] = float(nbytes)
    return out or None


# ---------------------------------------------------------------------------
# host-side packing / unpacking

def pack_msgs(cfg: SeqConfig, cols: dict, n: int) -> dict:
    """Columnar router output (numpy, length n <= batch) -> padded
    (B,) i32 input dict. Padding entries are NOPs.

    Single-chunk convenience for tests and __graft_entry__; the serving
    path packs ALL chunks at once in SeqSession._plan (vectorized twin
    of this layout — keep the two in sync)."""
    B = cfg.batch

    def split64(name, src64):
        v = np.zeros(B, np.int64)
        v[:n] = src64[:n]
        return {f"{name}_lo": (v & 0xFFFFFFFF).astype(np.uint32)
                .astype(np.int32),
                f"{name}_hi": (v >> 32).astype(np.int32)}

    out = {}
    for k in ("act", "aid", "price", "size", "lane"):
        a = np.zeros(B, np.int32)
        a[:n] = cols[k][:n]
        out[k] = a
    out.update(split64("oid", cols["oid"]))
    if cfg.compat == "java":
        out.update(split64("aidr", cols["aid_raw"]))
        out.update(split64("sidr", cols["sid_raw"]))
        fl = np.zeros(B, np.int32)
        fl[:n] = cols["flags"][:n]
        out["flags"] = fl
    return out


def unpack_hdr(cfg: SeqConfig, hdr: np.ndarray, n: int) -> dict:
    """Header slice (hdr_rows, 128) -> per-message host dict + scalars."""
    B = cfg.batch
    BR = B // LN
    flat = hdr.reshape(-1)
    scal = flat[:LN]
    base = LN
    flags = flat[base:base + B][:n]
    res = {
        "ok": (flags & 1) != 0,
        "cap_reject": (flags & 2) != 0,
        "append": (flags & 4) != 0,
        "residual": flat[base + BR * LN:base + BR * LN + B][:n],
        "nfill": flat[base + 2 * BR * LN:base + 2 * BR * LN + B][:n],
        "prev_oid": ((flat[base + 3 * BR * LN:base + 3 * BR * LN + B][:n]
                      .astype(np.int64) & 0xFFFFFFFF)
                     | (flat[base + 4 * BR * LN:base + 4 * BR * LN + B][:n]
                        .astype(np.int64) << 32)),
        "err": int(scal[0]),
        "fill_total": int(scal[1]),
        "metrics": scal[2:2 + N_METRICS].astype(np.int64),
        "hist": scal[HIST_LANE0:HIST_LANE0 + N_HIST * N_HIST_BUCKETS]
        .astype(np.int64).reshape(N_HIST, N_HIST_BUCKETS),
    }
    return res


def unpack_fills(groups: np.ndarray, ftot: int) -> np.ndarray:
    """Fill group rows (5g, 128) -> (4, ftot) [oid, aid, price, size]."""
    if ftot == 0:
        return np.zeros((4, 0), np.int64)
    g = groups.reshape(-1, 5, LN)
    per = np.transpose(g, (1, 0, 2)).reshape(5, -1)
    f_oid = ((per[0, :ftot].astype(np.int64) & 0xFFFFFFFF)
             | (per[1, :ftot].astype(np.int64) << 32))
    return np.stack([f_oid,
                     per[2, :ftot].astype(np.int64),
                     per[3, :ftot].astype(np.int64),
                     per[4, :ftot].astype(np.int64)])


def unpack_out(cfg: SeqConfig, plane: np.ndarray, n: int) -> dict:
    """Whole-plane unpack (tests / single-shot paths)."""
    HR = hdr_rows(cfg)
    res = unpack_hdr(cfg, plane[:HR], n)
    ftot = res["fill_total"]
    groups = plane[HR:HR + 5 * (-(-max(ftot, 1) // LN))]
    res["fills"] = unpack_fills(groups, ftot)
    return res


def export_java(cfg: SeqConfig, state) -> dict:
    """Host view of a JAVA-mode state: positions keyed by the 128-bit
    (ka, kb) pairs exactly as the java oracle's dict (real keys
    (aid, sid) AND Q11 garbage keys (amount, available)); orders carry
    the direction tag; seq/book planes as in fixed mode."""
    assert cfg.compat == "java"
    S, N, NR = cfg.lanes, cfg.slots, cfg.nr
    h = {k: np.asarray(state[k]) for k in state_keys(cfg)}

    def planes2slot(lo, hi=None):
        v = lo.reshape(S, 2, NR * LN)[:, :, :N]
        if hi is None:
            return v
        return ((v.astype(np.int64) & 0xFFFFFFFF)
                | (hi.reshape(S, 2, NR * LN)[:, :, :N].astype(np.int64)
                   << 32))

    def j64(lo, hi):
        return ((lo.astype(np.int64) & 0xFFFFFFFF)
                | (hi.astype(np.int64) << 32))

    live = h["hstate"].reshape(-1) == 1
    ka = j64(h["hka_lo"].reshape(-1), h["hka_hi"].reshape(-1))[live]
    kb = j64(h["hkb_lo"].reshape(-1), h["hkb_hi"].reshape(-1))[live]
    amt = j64(h["ha_lo"].reshape(-1), h["ha_hi"].reshape(-1))[live]
    av = j64(h["hv_lo"].reshape(-1), h["hv_hi"].reshape(-1))[live]
    positions = {(int(a), int(b)): (int(x), int(y))
                 for a, b, x, y in zip(ka, kb, amt, av)}
    A = cfg.accounts
    bal = j64(h["bal_lo"].reshape(-1)[:A], h["bal_hi"].reshape(-1)[:A])
    return {
        "positions": positions,
        "bal": bal,
        "bal_used": h["bal_u"].reshape(-1)[:A] != 0,
        "slot_oid": planes2slot(h["bo_lo"], h["bo_hi"]),
        "slot_ba": planes2slot(h["ba"]).astype(np.int64),
        "slot_price": planes2slot(h["bp"]).astype(np.int32),
        "slot_size": planes2slot(h["bs"]).astype(np.int32),
        "book_exists": h["bex"].reshape(-1)[:S] != 0,
        "err": np.int32(h["err"].reshape(-1)[0]),
    }


# ---------------------------------------------------------------------------
# canonical (lanes-style) state import/export for checkpoint parity

def export_canonical(cfg: SeqConfig, state) -> dict:
    """Device planes -> the canonical snapshot layout the lanes engine
    checkpoints use (slot_* (S,2,N) i64/i32/bool, flat positions s64,
    bal s64) so snapshots restore across engines. Fixed mode only:
    java-mode state has its OWN canonical form (128-bit position keys,
    direction-tagged merged books) in runtime/javasnap.py."""
    if cfg.compat != "fixed":
        raise ValueError(
            "java-mode state has no fixed-layout canonical export — "
            "snapshot via runtime/javasnap.export_seqjava")
    S, N, A, NR = cfg.lanes, cfg.slots, cfg.accounts, cfg.nr
    h = {k: np.asarray(state[k]) for k in _STATE_KEYS}

    def planes2slot(lo, hi=None):
        v = lo.reshape(S, 2, NR * LN)[:, :, :N]
        if hi is None:
            return v
        return ((v.astype(np.int64) & 0xFFFFFFFF)
                | (hi.reshape(S, 2, NR * LN)[:, :, :N].astype(np.int64)
                   << 32))

    slot_size = planes2slot(h["bs"]).astype(np.int32)
    used = slot_size > 0
    pos_amt = np.zeros(S * A, np.int64)
    pos_avail = np.zeros(S * A, np.int64)
    hk = h["hk"].reshape(-1)
    live = hk != 0
    keys = hk[live] - 1
    amt = ((h["ha_lo"].reshape(-1)[live].astype(np.int64) & 0xFFFFFFFF)
           | (h["ha_hi"].reshape(-1)[live].astype(np.int64) << 32))
    avail = ((h["hv_lo"].reshape(-1)[live].astype(np.int64) & 0xFFFFFFFF)
             | (h["hv_hi"].reshape(-1)[live].astype(np.int64) << 32))
    pos_amt[keys] = amt
    pos_avail[keys] = avail
    seqc = h["seqc"].reshape(-1)[:S].astype(np.int32)
    bal = ((h["bal_lo"].reshape(-1)[:A].astype(np.int64) & 0xFFFFFFFF)
           | (h["bal_hi"].reshape(-1)[:A].astype(np.int64) << 32))
    return {
        "slot_oid": planes2slot(h["bo_lo"], h["bo_hi"]),
        "slot_aid": planes2slot(h["ba"]).astype(np.int32),
        "slot_price": planes2slot(h["bp"]).astype(np.int32),
        "slot_size": slot_size,
        "slot_seq": planes2slot(h["bq"]).astype(np.int32),
        "slot_used": used,
        "seq": seqc,
        "book_exists": h["bex"].reshape(-1)[:S] != 0,
        "pos_amt": pos_amt,
        "pos_avail": pos_avail,
        "bal": bal,
        "bal_used": h["bal_u"].reshape(-1)[:A] != 0,
        "err": np.int32(h["err"].reshape(-1)[0]),
        "metrics": None,  # counters are host-accumulated in SeqSession
    }


# the replicated balance planes (account a -> row a>>7, lane a&127):
# the only cross-shard-coupled state the seqmesh async dispatcher
# forwards point-to-point and select-merges at barriers
BAL_KEYS = ("bal_lo", "bal_hi", "bal_u")


def select_balances(planes_by_shard, sel) -> dict:
    """Merge per-shard copies of the replicated balance planes by
    per-account OWNER SELECTION: sel[a] names the shard whose copy of
    account a is authoritative. Exact by construction — under the
    seqmesh window invariant an account's balance only ever advances on
    the shard it is currently bound to, so a select needs no arithmetic
    merge (and trivially preserves Java-long wrap).

    planes_by_shard: per-shard dicts of BAL_KEYS -> (arows, 128) i32.
    sel: (arows*128,) int shard index per flat account slot.
    Returns merged (arows, 128) planes."""
    stacked = {k: np.stack([p[k] for p in planes_by_shard])
               for k in BAL_KEYS}
    arows, lanes = stacked[BAL_KEYS[0]].shape[1:]
    idx = sel.reshape(arows, lanes)
    r = np.arange(arows, dtype=np.int64)[:, None]
    c = np.arange(lanes, dtype=np.int64)[None, :]
    return {k: stacked[k][idx, r, c] for k in BAL_KEYS}


def import_canonical(cfg: SeqConfig, canon: dict):
    """Inverse of export_canonical (numpy -> device plane dict). The
    snapshot's slot depth and account capacity may be SMALLER than the
    config's (elastic restore into deeper books / wider account space —
    position hash keys are recomputed with the new stride); shrinking
    either is a state migration, not a restore, and raises."""
    S, N, A, NR = cfg.lanes, cfg.slots, cfg.accounts, cfg.nr
    S0 = np.asarray(canon["slot_oid"]).shape[0]
    if S0 != S:
        raise ValueError(
            f"snapshot has {S0} lanes, cfg.lanes={S} — lane-count "
            f"changes need a state migration, not a restore")
    N0 = np.asarray(canon["slot_oid"]).shape[2]
    if N0 > N:
        raise ValueError(
            f"snapshot books are {N0} slots deep; cfg.slots={N} cannot "
            f"hold them — restore into slots >= {N0}")
    A0 = np.asarray(canon["pos_amt"]).reshape(-1).size // S
    if A0 > A:
        raise ValueError(
            f"snapshot has {A0} account slots; cfg.accounts={A} cannot "
            f"hold them — restore into accounts >= {A0}")

    def slot2planes(v, split=False):
        full = np.zeros((S, 2, NR * LN), np.int64)
        full[:, :, :N0] = np.asarray(v).reshape(S, 2, N0)
        flat = full.reshape(2 * S * NR, LN)
        if split:
            lo = (flat & 0xFFFFFFFF).astype(np.uint32).astype(np.int32)
            hi = (flat >> 32).astype(np.int32)
            return lo, hi
        return flat.astype(np.int32)

    lo, hi = slot2planes(canon["slot_oid"], split=True)
    used = np.asarray(canon["slot_used"])
    sizes = np.where(used, np.asarray(canon["slot_size"]), 0)

    def padplane(v, rows):
        a = np.zeros(rows * LN, np.int32)
        a[:len(v)] = v
        return a.reshape(rows, LN)

    pos_amt = np.asarray(canon["pos_amt"]).reshape(S, A0)
    pos_avail = np.asarray(canon["pos_avail"]).reshape(S, A0)
    live2 = np.nonzero(pos_amt != 0)
    # re-key (lane, acc) with the CONFIG's stride (A may exceed A0)
    live = live2[0].astype(np.int64) * A + live2[1].astype(np.int64)
    pos_amt = {int(k): int(pos_amt[l, a])
               for k, l, a in zip(live, live2[0], live2[1])}
    pos_avail = {int(k): int(pos_avail[l, a])
                 for k, l, a in zip(live, live2[0], live2[1])}
    if len(live) > cfg.pos_cap // 2:
        raise ValueError(
            f"{len(live)} live positions exceed half the hash capacity "
            f"{cfg.pos_cap} — raise pos_cap")
    hk = np.zeros(cfg.pos_cap, np.int32)
    halo = np.zeros(cfg.pos_cap, np.int32)
    hahi = np.zeros(cfg.pos_cap, np.int32)
    hvlo = np.zeros(cfg.pos_cap, np.int32)
    hvhi = np.zeros(cfg.pos_cap, np.int32)
    capr = cfg.caprows
    tilemask = capr - 1
    # the kernel's h_find/h_claim stop after min(probe_max, capr) tiles;
    # an entry the import places beyond that bound would be silently
    # INVISIBLE to the device (pos_get returns zeros), so the host probe
    # is bounded identically and overflow is a loud error
    probe_lim = min(cfg.probe_max, capr)
    for k in live:
        key = int(k) + 1
        # home tile = the kernel's Fibonacci hash (h_home) in int32 wrap
        # arithmetic: ((key * -1640531527) >> 7) & tilemask
        h = (key * -1640531527) & 0xFFFFFFFF
        if h >= 1 << 31:
            h -= 1 << 32
        t = (h >> 7) & tilemask
        placed = False
        for p in range(probe_lim):
            base = ((t + p) & tilemask) * LN
            row = hk[base:base + LN]
            empt = np.nonzero(row == 0)[0]
            if len(empt):
                j = base + empt[0]
                def _lo(v):
                    lo = int(v) & 0xFFFFFFFF
                    return np.int32(lo - (1 << 32) if lo >= (1 << 31)
                                    else lo)

                hk[j] = np.int32(key)
                halo[j] = _lo(pos_amt[int(k)])
                hahi[j] = np.int32(int(pos_amt[int(k)]) >> 32)
                hvlo[j] = _lo(pos_avail[int(k)])
                hvhi[j] = np.int32(int(pos_avail[int(k)]) >> 32)
                placed = True
                break
        if not placed:
            raise ValueError(
                "position hash import overflow: entry unreachable within "
                "probe_max tiles — raise pos_cap or probe_max")

    bal = np.asarray(canon["bal"]).reshape(-1)
    return {
        "bo_lo": jnp.asarray(lo), "bo_hi": jnp.asarray(hi),
        "ba": jnp.asarray(slot2planes(canon["slot_aid"])),
        "bp": jnp.asarray(slot2planes(canon["slot_price"])),
        "bs": jnp.asarray(slot2planes(sizes)),
        "bq": jnp.asarray(slot2planes(canon["slot_seq"])),
        "seqc": jnp.asarray(padplane(np.asarray(canon["seq"]), cfg.srows)),
        "bex": jnp.asarray(padplane(
            np.asarray(canon["book_exists"]).astype(np.int32), cfg.srows)),
        "bal_lo": jnp.asarray(padplane(
            (bal & 0xFFFFFFFF).astype(np.uint32).astype(np.int32),
            cfg.arows)),
        "bal_hi": jnp.asarray(padplane((bal >> 32).astype(np.int32),
                                       cfg.arows)),
        "bal_u": jnp.asarray(padplane(
            np.asarray(canon["bal_used"]).astype(np.int32), cfg.arows)),
        "hk": jnp.asarray(hk.reshape(capr, LN)),
        "ha_lo": jnp.asarray(halo.reshape(capr, LN)),
        "ha_hi": jnp.asarray(hahi.reshape(capr, LN)),
        "hv_lo": jnp.asarray(hvlo.reshape(capr, LN)),
        "hv_hi": jnp.asarray(hvhi.reshape(capr, LN)),
        # dep is derived state (occupied slots per lane, both sides) —
        # recomputed here so canonical snapshots stay engine-agnostic
        "dep": jnp.asarray(padplane(
            (sizes.reshape(S, -1) > 0).sum(axis=1).astype(np.int32),
            cfg.srows)),
        "err": jnp.asarray(padplane(
            np.array([int(canon.get("err", 0))], np.int32), 1)),
    }
