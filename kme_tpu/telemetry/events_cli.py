"""kme-events: merge per-process control-plane event logs into one
causally-ordered cluster timeline — filter it, follow it live, explain
one event from the metrics history, or render it into the trace viewer.

Sources are event-log files or state-root directories (discovered
recursively: every ``events-*.jsonl`` writer plus merged
``events.jsonl`` artifacts, rotated segments included). The merge is
the pure events.py pipeline: first-wins dedup on (source, event_seq),
then offset-anchored causal order with walltime fallback.

``--why SRC:SEQ`` answers "what changed around this decision": it
takes the event's timestamp, summarizes the TSDB metrics history
(``--store``) over the windows before and after it with the same
``window_summary`` machinery kme-prof's regression attribution uses,
and prints the biggest deltas — counters as rate deltas, gauges as
mean shifts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional, Set, Tuple

from kme_tpu.telemetry import events as ev_mod


_fmt_event = ev_mod.format_event


def _passes(ev: dict, args) -> bool:
    if args.source and args.source not in str(ev.get("src", "")):
        return False
    if args.kind and args.kind not in str(ev.get("kind", "")):
        return False
    if args.severity and ev.get("sev") != args.severity:
        return False
    if args.group is not None and int(ev.get("g", -1)) != args.group:
        return False
    ts = int(ev.get("ts", 0)) / 1e6
    if args.since is not None and ts < args.since:
        return False
    if args.until is not None and ts > args.until:
        return False
    return True


def _merged(paths: List[str]) -> List[dict]:
    return ev_mod.merge_logs(paths)


def _find_event(timeline: List[dict], ref: str) -> Optional[dict]:
    """Resolve ``--why`` refs: "SRC:SEQ" (exact identity) or a bare
    kind substring (first match, causal order)."""
    if ":" in ref:
        src, _, seq_s = ref.rpartition(":")
        try:
            seq = int(seq_s)
        except ValueError:
            seq = None
        if seq is not None:
            for ev in timeline:
                if ev.get("src") == src and int(ev.get("seq", -1)) == seq:
                    return ev
    for ev in timeline:
        if ref in str(ev.get("kind", "")):
            return ev
    return None


def _why(ev: dict, store: str, window_s: float, top: int,
         out=None) -> int:
    from kme_tpu.telemetry.tsdb import window_summary

    out = out if out is not None else sys.stdout

    ts = int(ev.get("ts", 0))
    w = int(window_s * 1e6)
    before = window_summary(store, t0_us=ts - w, t1_us=ts)
    after = window_summary(store, t0_us=ts, t1_us=ts + w)
    rows: List[Tuple[float, str, float, float]] = []
    for name in sorted(set(before) | set(after)):
        b = before.get(name, 0.0)
        a = after.get(name, 0.0)
        if b == a:
            continue
        denom = max(abs(b), 1e-12)
        rows.append((abs(a - b) / denom, name, b, a))
    rows.sort(reverse=True)
    print(f"why {ev.get('src')}#{ev.get('seq')} {ev.get('kind')} "
          f"@ {ts / 1e6:.6f} (±{window_s:g}s window, store {store})",
          file=out)
    if not rows:
        print("  no metric moved across the window", file=out)
        return 0
    for rel, name, b, a in rows[:top]:
        print(f"  {name}: {b:g} -> {a:g}  ({a - b:+g}, "
              f"{rel:+.1%} rel)", file=out)
    return 0


def _follow(paths: List[str], args, out=None) -> int:
    out = out if out is not None else sys.stdout
    seen: Set[Tuple[str, int]] = set()
    try:
        while True:
            fresh = []
            for ev in _merged(paths):
                key = (str(ev.get("src", "")), int(ev.get("seq", -1)))
                if key in seen:
                    continue
                seen.add(key)
                if _passes(ev, args):
                    fresh.append(ev)
            for ev in fresh:
                print(json.dumps(ev, sort_keys=True) if args.json
                      else _fmt_event(ev), file=out)
            out.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kme-events",
                                description=__doc__)
    p.add_argument("sources", nargs="*", default=None,
                   metavar="PATH",
                   help="event-log files or state-root directories "
                        "(default: current directory)")
    p.add_argument("--source", default=None,
                   help="only events whose src contains this")
    p.add_argument("--kind", default=None,
                   help="only events whose kind contains this")
    p.add_argument("--severity", default=None,
                   choices=list(ev_mod.SEVERITIES))
    p.add_argument("--group", type=int, default=None,
                   help="only events anchored to this group")
    p.add_argument("--since", type=float, default=None,
                   metavar="EPOCH_S")
    p.add_argument("--until", type=float, default=None,
                   metavar="EPOCH_S")
    p.add_argument("--tail", type=int, default=None, metavar="N",
                   help="only the last N matching events")
    p.add_argument("--json", action="store_true",
                   help="JSONL output instead of human lines")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="also write the merged (unfiltered) timeline "
                        "as a canonical events.jsonl artifact")
    p.add_argument("--chrome-out", default=None, metavar="PATH",
                   help="write the filtered timeline as Chrome "
                        "trace-events (control-plane spans in the "
                        "same viewer as the data-plane traces)")
    p.add_argument("--follow", action="store_true",
                   help="poll the sources and stream new events")
    p.add_argument("--interval", type=float, default=0.5,
                   help="--follow poll cadence seconds")
    p.add_argument("--why", default=None, metavar="SRC:SEQ|KIND",
                   help="explain one event: TSDB metric deltas over "
                        "the windows before/after it")
    p.add_argument("--store", default=None, metavar="DIR",
                   help="TSDB store directory for --why")
    p.add_argument("--window", type=float, default=5.0,
                   help="--why window half-width, seconds")
    p.add_argument("--top", type=int, default=12,
                   help="--why: how many deltas to print")
    args = p.parse_args(argv)
    paths = args.sources or ["."]

    if args.follow:
        return _follow(paths, args)

    timeline = _merged(paths)
    if args.out:
        ev_mod.write_merged(timeline, args.out)

    if args.why is not None:
        if not args.store:
            p.error("--why needs --store (TSDB directory)")
        target = _find_event(timeline, args.why)
        if target is None:
            print(f"kme-events: no event matches {args.why!r}",
                  file=sys.stderr)
            return 2
        return _why(target, args.store, args.window, args.top)

    picked = [ev for ev in timeline if _passes(ev, args)]
    if args.tail is not None:
        picked = picked[-max(0, args.tail):]
    if args.chrome_out:
        with open(args.chrome_out, "w") as f:
            json.dump({"traceEvents": ev_mod.to_chrome(picked),
                       "displayTimeUnit": "ms"}, f)
    for ev in picked:
        print(json.dumps(ev, sort_keys=True) if args.json
              else _fmt_event(ev))
    if not args.json:
        print(f"kme-events: {len(picked)}/{len(timeline)} events "
              f"from {len(paths)} source(s)", file=sys.stderr)
    return 0


if __name__ == "__main__":          # pragma: no cover
    raise SystemExit(main())
