"""Time-travel state inspection, divergence bisection and live
watchpoints (kme-xray).

The engine is a deterministic state machine over a durable input log
(the SMR framing): any historical state is `nearest retained snapshot
<= target offset` + `replay of the MatchIn tail` — so "what was
account 7's balance at offset 90_000" is a query, not an archaeology
project. Three tools share that primitive:

* **materialize(log_dir, at, ...)** — offset-addressed state. Anchors
  on the nearest snapshot (any kind: .pkl oracle snapshots restore the
  exact engine; .npz canonical snapshots restore into a SeqSession and
  are adopted by `OracleEngine.from_export`), replays the durable
  MatchIn log forward through the Python oracle with the service's
  exact drop policy, and answers point queries (`balance`, `book`,
  `order`) — optionally entered through a Dapper-style trace id
  (`resolve_trace`, scanning the deterministic dtrace id space).

* **bisect(journal, log_dir, ...)** — first-divergent-batch search.
  The journal is the engine's *claimed* history; the oracle replay of
  the input log is the *truth*. When they disagree (an audit violation,
  a KME_AUDIT_TAMPER drill, a real engine bug), binary-search the
  batch boundary where canonical state projections first differ:
  O(log N) oracle replays, each anchored on the nearest checkpoint at
  or below the current known-good watermark (so checkpoints written
  *after* a real divergence can never mask it). Emits a minimized
  repro in the audit.py format plus the exact field-level diff;
  `replay_bisect_repro` re-derives the same diff offline.

* **WatchEngine** — live watchpoints. A tiny deterministic predicate
  grammar (`balance[AID]<0`, `position[AID,SID]>X`, `depth[SID]>=N`,
  `spread[SID]==0`) evaluated at batch barriers against an
  InvariantAuditor shadow ledger fed from the batch's own output
  lines. Pure functions of exported state — no clock, no RNG
  (kme-lint's WATCH_SCOPES enforces it) — so two seeded runs fire
  identical (offset, predicate) hit sets. Hits write bounded
  TriggerCapture-compatible `capture_NNN.json` files carrying the
  offset, the batch's trace exemplars and the `kme-xray` one-liner
  that reproduces the hit offline. Watchpoints never gate admission
  and never touch MatchOut bytes (COMPAT.md).

Cluster mode (`cluster_cut`) materializes every group of a multi-group
run at a consistent cut — per-group local offsets derived by re-running
the front's deterministic router over the merged input prefix — and
checks global cash conservation (balances + open-order margin, with the
router's unconsumed `pending_reserve` residuals reported) byte-for-byte
against the single-leader oracle at the same merge watermark.
"""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

_J = dict(sort_keys=True, separators=(",", ":"))


class XrayError(ValueError):
    """Unmaterializable request — target outside the replay window,
    missing durable log, malformed predicate. The message names the
    actionable fix (e.g. the oldest materializable offset)."""


# ---------------------------------------------------------------------------
# offset-addressed state materialization


def oldest_materializable(ckpt_dir: Optional[str]) -> int:
    """The replay-window floor: with retained snapshots, the oldest
    snapshot offset (the journal's retention guard prunes rotated
    segments below it, so nothing older can be cross-checked); with no
    snapshots at all, 0 — the durable log replays from the start."""
    if not ckpt_dir:
        return 0
    from kme_tpu.runtime import checkpoint as ck

    off = ck.oldest_retained_offset(ckpt_dir)
    return 0 if off is None else int(off)


def _fetch_records(log_dir: str, topic: str, start: int, end: int):
    """Records [start, end) from a durable broker log directory."""
    from kme_tpu.bridge.broker import BrokerError, InProcessBroker

    if not os.path.isdir(log_dir):
        raise XrayError(f"no durable broker log directory: {log_dir}")
    br = InProcessBroker(persist_dir=log_dir)
    try:
        have = br.end_offset(topic)
    except BrokerError:
        raise XrayError(
            f"topic {topic!r} has no durable log under {log_dir}")
    if end > have:
        raise XrayError(
            f"durable log for {topic!r} ends at offset {have}; cannot "
            f"materialize offset {end}")
    out, off = [], start
    while off < end:
        recs = br.fetch(topic, off, max_records=min(4096, end - off))
        if not recs:
            break
        out.extend(recs)
        off = recs[-1].offset + 1
    return out


def _parse_replay(value: str):
    """The service's drop policy (bridge/service.py _parse): malformed
    or out-of-int32 records never reach the engine — None here."""
    from kme_tpu.wire import parse_order

    try:
        m = parse_order(value)
        if not (-2**31 <= m.price < 2**31 and -2**31 <= m.size < 2**31):
            return None
        return m
    except ValueError:
        return None


def _engine_from_snapshot(path: str, book_slots: Optional[int],
                          max_fills: Optional[int]):
    """One snapshot file -> a fixed-mode OracleEngine holding its state.
    .pkl restores the exact pickled engine (envelope included); .npz
    restores the canonical form into a SeqSession and adopts its export
    (envelope defaults to the snapshot's own cfg)."""
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.runtime import checkpoint as ck

    if path.endswith(".pkl"):
        eng = ck.load_oracle_file(path)
        if getattr(eng, "java", False):
            raise XrayError(
                "java-mode oracle snapshot: xray materializes fixed-mode "
                "state only")
        return eng
    if path.endswith(".npz"):
        ses = ck.restore_seq_snapshot(path, None)
        if ses.cfg.compat != "fixed":
            raise XrayError(
                "java-mode snapshot: xray materializes fixed-mode state "
                "only")
        return OracleEngine.from_export(
            ses.export_state(),
            book_slots=(book_slots if book_slots is not None
                        else ses.cfg.slots),
            max_fills=(max_fills if max_fills is not None
                       else ses.cfg.max_fills))
    raise XrayError(
        f"snapshot kind of {os.path.basename(path)} is not anchorable "
        f"here (native .nat dumps need the native engine library)")


def materialize(log_dir: str, at: Optional[int], topic: str = "MatchIn",
                ckpt_dir: Optional[str] = None,
                allow_cold: bool = False,
                max_anchor: Optional[int] = None,
                book_slots: Optional[int] = None,
                max_fills: Optional[int] = None):
    """State at input offset `at` (exclusive: all records with offset
    < at applied — the checkpoint offset convention; None = log end).
    Returns (OracleEngine, anchor_offset, replayed_count).

    Replay-window policy: when `ckpt_dir` holds snapshots, targets
    below `oldest_materializable` raise XrayError naming the floor —
    the journal retention guard has already released history below the
    oldest snapshot, so nothing there can be cross-checked.
    `allow_cold=True` overrides (bisect probes and cluster cuts replay
    from offset 0 off the never-pruned broker log). `max_anchor` caps
    the anchor offset (bisect: only checkpoints at or below the
    known-good watermark are trusted)."""
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.runtime import checkpoint as ck

    if at is None:
        if not os.path.isdir(log_dir):
            raise XrayError(
                f"no durable broker log directory: {log_dir}")
        at = InProcessBroker(persist_dir=log_dir).end_offset(topic)
    at = int(at)
    if at < 0:
        raise XrayError("target offset must be >= 0")
    engine, anchor_off = None, 0
    if ckpt_dir:
        snaps = ck.all_snapshots(ckpt_dir)
        if snaps:
            oldest = oldest_materializable(ckpt_dir)
            if at < oldest and not allow_cold:
                raise XrayError(
                    f"offset {at} predates the replay window: the oldest "
                    f"materializable offset is {oldest} (snapshots below "
                    f"it were pruned — raise --checkpoint-keep / "
                    f"KME_CKPT_KEEP and the journal rotate_keep to retain "
                    f"deeper history)")
        bound = at if max_anchor is None else min(at, int(max_anchor))
        for off, path in snaps:      # newest first, all kinds
            if off > bound:
                continue
            try:
                engine = _engine_from_snapshot(path, book_slots,
                                               max_fills)
                anchor_off = off
                break
            except Exception as e:   # corrupt/foreign: older anchor
                print(f"kme-xray: skipping snapshot {path}: {e}",
                      file=sys.stderr)
    if engine is None:
        kw = {}
        if book_slots is not None:
            kw = {"book_slots": book_slots,
                  "max_fills": max_fills or 16}
        engine = OracleEngine("fixed", **kw)
        anchor_off = 0
    replayed = 0
    for rec in _fetch_records(log_dir, topic, anchor_off, at):
        msg = _parse_replay(rec.value)
        if msg is None:
            continue
        engine.process(msg)
        replayed += 1
    return engine, anchor_off, replayed


def resolve_trace(tid, log_dir: str, topic: str = "MatchIn",
                  ngroups: int = 1) -> Optional[int]:
    """Trace id -> input offset. The dtrace ids are splitmix64 mixes
    (NOT invertible), so resolution scans the offset space recomputing
    them: group-local ids (`local_tid`) need only the log length;
    order-identity ids (`trace_id(off, aid, oid)`) re-parse the line at
    each offset. Returns the first matching offset or None."""
    from kme_tpu.telemetry import dtrace

    if isinstance(tid, str):
        tid = int(tid, 0)
    tid = int(tid)
    from kme_tpu.bridge.broker import BrokerError, InProcessBroker

    br = InProcessBroker(persist_dir=log_dir)
    try:
        end = br.end_offset(topic)
    except BrokerError:
        raise XrayError(
            f"topic {topic!r} has no durable log under {log_dir}")
    for off in range(end):
        for g in range(max(1, ngroups)):
            if dtrace.local_tid(g, off) == tid:
                return off
    off = 0
    while off < end:
        for rec in br.fetch(topic, off, max_records=4096):
            m = _parse_replay(rec.value)
            if m is not None and dtrace.trace_id(
                    rec.offset, m.aid, m.oid) == tid:
                return rec.offset
            off = rec.offset + 1
    return None


# ---------------------------------------------------------------------------
# watchpoint predicate grammar (pure: no clock, no RNG — lint-enforced)

_PRED_RE = re.compile(
    r"^\s*(balance|position|depth|spread)\s*\[\s*(-?\d+)\s*"
    r"(?:,\s*(-?\d+)\s*)?\]\s*(<=|>=|==|!=|<|>)\s*(-?\d+)\s*$")

_GRAMMAR = ("balance[AID] | position[AID,SID] | depth[SID] | "
            "spread[SID], compared with < <= > >= == != to an integer")


class Watchpoint:
    """One parsed predicate: kind, index tuple, comparator, rhs."""

    __slots__ = ("expr", "kind", "a", "b", "op", "rhs")

    def __init__(self, expr: str, kind: str, a: int, b: Optional[int],
                 op: str, rhs: int) -> None:
        self.expr, self.kind, self.a, self.b = expr, kind, a, b
        self.op, self.rhs = op, rhs


def parse_watch(expr: str) -> Watchpoint:
    m = _PRED_RE.match(expr)
    if not m:
        raise XrayError(
            f"unparseable watch predicate {expr!r}; grammar: {_GRAMMAR}")
    kind, a, b, cmp_op, rhs = m.groups()
    if kind == "position" and b is None:
        raise XrayError(
            f"watch predicate {expr!r}: position takes [AID,SID]")
    if kind != "position" and b is not None:
        raise XrayError(
            f"watch predicate {expr!r}: {kind} takes a single index")
    return Watchpoint(expr.strip(), kind, int(a),
                      int(b) if b is not None else None,
                      cmp_op, int(rhs))


def _cmp(op_s: str, lhs: int, rhs: int) -> bool:
    if op_s == "<":
        return lhs < rhs
    if op_s == "<=":
        return lhs <= rhs
    if op_s == ">":
        return lhs > rhs
    if op_s == ">=":
        return lhs >= rhs
    if op_s == "==":
        return lhs == rhs
    return lhs != rhs


def measure(pred: Watchpoint, ledger) -> Optional[int]:
    """Evaluate a predicate's left-hand side against an
    InvariantAuditor-shaped shadow ledger. None = unmeasurable
    (unknown account; one-sided or absent book for spread) — the
    predicate does not fire."""
    if pred.kind == "balance":
        return ledger.balances.get(pred.a)
    if pred.kind == "position":
        pos = ledger.positions.get((pred.a, pred.b))
        return pos[0] if pos is not None else 0
    book = ledger.books.get(pred.a)
    if pred.kind == "depth":
        if book is None:
            return 0
        return sum(len(oids) for side in book for oids in side.values())
    if book is None:
        return None
    bids = [px for px, oids in book[0].items() if oids]
    asks = [px for px, oids in book[1].items() if oids]
    if not bids or not asks:
        return None
    return min(asks) - max(bids)


def eval_predicate(pred: Watchpoint, ledger
                   ) -> Tuple[bool, Optional[int]]:
    val = measure(pred, ledger)
    if val is None:
        return False, None
    return _cmp(pred.op, val, pred.rhs), val


def measure_engine(pred: Watchpoint, engine) -> Optional[int]:
    """Same measurement over a materialized OracleEngine (the offline
    `kme-xray eval` path)."""
    if pred.kind == "balance":
        return engine.balances.get(pred.a)
    if pred.kind == "position":
        pos = engine.positions.get((pred.a, pred.b))
        return pos[0] if pos is not None else 0
    lv = engine.book_levels(pred.a)
    if pred.kind == "depth":
        return sum(len(rows) for _px, rows in lv["buys"] + lv["sells"])
    if not lv["buys"] or not lv["sells"]:
        return None
    return lv["sells"][0][0] - lv["buys"][0][0]


def eval_engine(pred: Watchpoint, engine) -> Tuple[bool, Optional[int]]:
    """eval_predicate over a materialized engine instead of a shadow
    ledger — the `kme-xray eval` path."""
    val = measure_engine(pred, engine)
    if val is None:
        return False, None
    return _cmp(pred.op, val, pred.rhs), val


def book_summary(engine, sid: int) -> dict:
    """JSON-safe ladder view of one symbol plus the derived depth and
    spread the watchpoint grammar measures."""
    lv = engine.book_levels(sid)
    buys = [[int(px), [[int(o), int(a), int(s)] for o, a, s in rows]]
            for px, rows in lv["buys"]]
    sells = [[int(px), [[int(o), int(a), int(s)] for o, a, s in rows]]
             for px, rows in lv["sells"]]
    depth = sum(len(rows) for _px, rows in buys + sells)
    spread = (sells[0][0] - buys[0][0]) if buys and sells else None
    return {"sid": int(sid), "exists": bool(lv["exists"]),
            "buys": buys, "sells": sells,
            "depth": depth, "spread": spread}


class WatchEngine:
    """Armed watchpoints + the shadow ledger they read.

    Fed at batch barriers (bridge/service.py) either inline from the
    batch's output line groups or as a journal observer sharing the
    already-derived lifecycle events. Edge-triggered: a predicate fires
    when it transitions false->true and re-arms when it goes false
    again, so hit sets are bounded and deterministic. Firing writes a
    TriggerCapture-compatible capture_NNN.json (same reader:
    `kme-prof --captures`)."""

    def __init__(self, exprs: Sequence[str],
                 out_dir: Optional[str] = None, registry=None,
                 max_captures: int = 16,
                 repro: Optional[dict] = None) -> None:
        from kme_tpu.telemetry.audit import InvariantAuditor

        self.preds = [parse_watch(e) for e in exprs]
        self._shadow = InvariantAuditor()
        self._armed = [True] * len(self.preds)
        # (batch-end input offset, predicate expr, measured value)
        self.hits: List[Tuple[int, str, int]] = []
        self.out_dir = out_dir
        self.max_captures = int(max_captures)
        self.capture_paths: List[str] = []
        self._next_capture = 0
        self._repro = dict(repro or {})
        self._counter = None
        if registry is not None:
            self._counter = registry.counter(
                "watch_hits_total",
                help="watchpoint predicates transitioned to true")

    def seed(self, state: dict) -> None:
        """Adopt an engine export on resume, like the auditor does."""
        self._shadow.seed(state)

    def observe_lines(self, lines_per_msg, reasons=None, offsets=None,
                      drops=(), exemplars=None) -> List[tuple]:
        from kme_tpu.telemetry.journal import batch_events

        evs = batch_events(lines_per_msg, reasons=reasons,
                           offsets=offsets, drops=drops)
        return self.observe_events(evs, exemplars=exemplars)

    def observe_engine(self, engine, off: int,
                       exemplars=None) -> List[tuple]:
        """One batch barrier read DIRECTLY off the live OracleEngine —
        the zero-derivation path bridge/service.py uses when the
        serving engine is itself the deterministic truth (no lifecycle
        re-parse, no shadow ledger; the 3% always-on budget). Hit sets
        are identical to the event-fed path: both read the same state
        machine at the same barrier."""
        fired: List[tuple] = []
        for i, pred in enumerate(self.preds):
            hit, val = eval_engine(pred, engine)
            if hit and self._armed[i]:
                self._armed[i] = False
                rec = (off, pred.expr, val)
                self.hits.append(rec)
                fired.append(rec)
            elif not hit:
                self._armed[i] = True
        if fired and self._counter is not None:
            self._counter.inc(len(fired))
        for rec in fired:
            self._write_capture(rec[0], rec[1], rec[2], exemplars)
        return fired

    def observe_events(self, events: List[dict],
                       exemplars=None) -> List[tuple]:
        """One batch barrier: apply the lifecycle deltas, evaluate every
        armed predicate, record edge-triggered hits. Pure function of
        the event stream — the capture write is observability on the
        side and never feeds back into the decision."""
        sh = self._shadow
        if events:
            sh.observe(events)
            # the shadow is a ledger here, not a judge — its violation
            # log is the auditor's job and must not grow unbounded
            sh.violations.clear()
        off = -1
        for ev in events:
            o = ev.get("off", -1)
            if o > off:
                off = o
        fired: List[tuple] = []
        for i, pred in enumerate(self.preds):
            hit, val = eval_predicate(pred, sh)
            if hit and self._armed[i]:
                self._armed[i] = False
                rec = (off, pred.expr, val)
                self.hits.append(rec)
                fired.append(rec)
            elif not hit:
                self._armed[i] = True
        if fired and self._counter is not None:
            self._counter.inc(len(fired))
        for rec in fired:
            self._write_capture(rec[0], rec[1], rec[2], exemplars)
        return fired

    # -- capture emission (TriggerCapture-compatible doc + naming) -----

    def _repro_line(self, off: int, expr: str) -> Optional[str]:
        log_dir = self._repro.get("log_dir")
        if not log_dir:
            return None
        cmd = f"kme-xray eval '{expr}' --at {off + 1} --log-dir {log_dir}"
        topic = self._repro.get("topic")
        if topic and topic != "MatchIn":
            cmd += f" --topic {topic}"
        ckd = self._repro.get("checkpoint_dir")
        if ckd:
            cmd += f" --checkpoint-dir {ckd}"
        return cmd

    def _write_capture(self, off: int, expr: str, val: int,
                       exemplars) -> Optional[str]:
        if self.out_dir is None or len(
                self.capture_paths) >= self.max_captures:
            return None
        import time

        try:
            os.makedirs(self.out_dir, exist_ok=True)
            n = self._next_capture
            while True:   # share the namespace with TriggerCapture
                path = os.path.join(self.out_dir,
                                    f"capture_{n:03d}.json")
                if not os.path.exists(path):
                    break
                n += 1
            doc = {"time": time.time(), "trigger": "watchpoint",
                   "predicate": expr, "offset": off, "value": val,
                   "exemplars": [dict(e) for e in (exemplars or [])],
                   "repro": self._repro_line(off, expr),
                   "resolve_with": ("kme-prof --captures DIR to list; "
                                    "run the 'repro' line to "
                                    "re-materialize the hit offline")}
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, path)
            self._next_capture = n + 1
            self.capture_paths.append(path)
            return path
        except OSError:      # disk trouble must never stall the barrier
            return None


# ---------------------------------------------------------------------------
# divergence bisection

_TIMING_EVENTS = ("win", "lat", "span")


def _journal_batches(events: List[dict]) -> List[Tuple[int, List[dict]]]:
    """[(batch_id, lifecycle events), ...] in stream order."""
    out: List[Tuple[int, List[dict]]] = []
    cur = None
    for ev in events:
        if ev.get("e") in _TIMING_EVENTS:
            continue
        b = ev.get("b", -1)
        if cur is None or b != cur:
            out.append((b, []))
            cur = b
        out[-1][1].append(ev)
    return out


def _batch_end_off(evs: List[dict]) -> int:
    off = -1
    for ev in evs:
        o = ev.get("off", -1)
        if o > off:
            off = o
    return off


def _canon(balances, positions, orders, books) -> dict:
    """Canonical-codec projection of a ledger: the JSON-stable shape
    both bisect sides are diffed in. Orders normalize to the auditor's
    [aid, sid, is_buy, price, size] rows; books to the sorted sid set
    (FIFO order inside a bucket is not part of the projection — audit's
    check_engine draws the same line)."""
    return {
        "balances": {str(a): int(v) for a, v in balances.items()},
        "positions": {f"{a}:{s}": [int(x) for x in v]
                      for (a, s), v in positions.items()},
        "orders": {str(o): [int(v[0]), int(v[1]), bool(v[2]),
                            int(v[3]), int(v[4])]
                   for o, v in orders.items()},
        "books": sorted(int(s) for s in books),
    }


def shadow_canon(aud) -> dict:
    return _canon(aud.balances, aud.positions, aud.orders, aud.books)


def engine_canon(engine) -> dict:
    ex = engine.export_state()
    orders = {o: [v["aid"], v["sid"], v["is_buy"], v["price"],
                  v["size"]] for o, v in ex["orders"].items()}
    return _canon(ex["balances"], ex["positions"], orders, ex["books"])


def state_diff(want: dict, got: dict) -> Dict[str, str]:
    """Field-level diff between two canonical projections (want =
    oracle truth, got = journal shadow)."""
    from kme_tpu.telemetry.audit import _dict_diff

    out: Dict[str, str] = {}
    for store in ("balances", "positions", "orders"):
        if want.get(store) != got.get(store):
            out[store] = _dict_diff(want.get(store, {}),
                                    got.get(store, {}), limit=8)
    if want.get("books") != got.get("books"):
        out["books"] = (f"oracle={want.get('books')} "
                        f"journal={got.get('books')}")
    return out


def bisect(journal_path: str, log_dir: str, topic: str = "MatchIn",
           ckpt_dir: Optional[str] = None,
           lo: Optional[int] = None, hi: Optional[int] = None,
           hi_batch: Optional[int] = None,
           book_slots: Optional[int] = None,
           max_fills: Optional[int] = None,
           repro_dir: Optional[str] = None) -> dict:
    """Binary-search the first batch where the journal's claimed state
    diverges from the oracle replay of the durable input.

    `lo`/`hi` bound the search window in input offsets (lo known-good,
    hi known- or suspected-bad); `hi_batch` names the upper bound by
    journal batch id instead (what audit repro dumps carry). Each probe
    is ONE oracle replay, anchored on the nearest checkpoint at or
    below the known-good watermark — total replays <=
    ceil(log2(window_batches)) + 1, asserted by the CI drill."""
    from kme_tpu.telemetry.audit import InvariantAuditor
    from kme_tpu.telemetry.journal import read_events

    events = read_events(journal_path)
    batches = _journal_batches(events)
    if not batches:
        raise XrayError(f"journal {journal_path} holds no batches")

    ends = [_batch_end_off(evs) for _b, evs in batches]
    hi_i = len(batches) - 1
    if hi_batch is not None:
        hi_i = next((i for i, (b, _e) in enumerate(batches)
                     if b == int(hi_batch)), None)
        if hi_i is None:
            raise XrayError(
                f"batch {hi_batch} is not in journal {journal_path}")
    elif hi is not None:
        hi_i = max((i for i, e in enumerate(ends) if e < int(hi)),
                   default=len(batches) - 1)
    lo_i = -1
    if lo is not None:
        lo_i = max((i for i, e in enumerate(ends) if e < int(lo)),
                   default=-1)
    if lo_i >= hi_i:
        raise XrayError(f"empty bisect window: lo batch index {lo_i} "
                        f">= hi batch index {hi_i}")

    def shadow_at(i: int) -> dict:
        aud = InvariantAuditor()
        for k in range(i + 1):
            aud.observe(batches[k][1])
            aud.violations.clear()
        return shadow_canon(aud)

    replays = 0

    def oracle_at(i: int, good_i: int) -> dict:
        nonlocal replays
        end = ends[i] + 1 if i >= 0 else 0
        good_off = ends[good_i] + 1 if good_i >= 0 else 0
        eng, _anchor, _n = materialize(
            log_dir, end, topic=topic, ckpt_dir=ckpt_dir,
            allow_cold=True, max_anchor=good_off,
            book_slots=book_slots, max_fills=max_fills)
        replays += 1
        return engine_canon(eng)

    span = hi_i - lo_i
    want_hi = oracle_at(hi_i, lo_i)
    got_hi = shadow_at(hi_i)
    result = {"journal": journal_path, "log_dir": log_dir,
              "topic": topic, "n_batches": len(batches),
              "window_batches": span}
    if want_hi == got_hi:
        result.update(divergent=False, replays=replays)
        return result
    div_want, div_got = want_hi, got_hi
    while hi_i - lo_i > 1:
        mid = (lo_i + hi_i) // 2
        want_m = oracle_at(mid, lo_i)
        got_m = shadow_at(mid)
        if want_m == got_m:
            lo_i = mid
        else:
            hi_i, div_want, div_got = mid, want_m, got_m

    b, evs = batches[hi_i]
    first_off = min((ev.get("off", -1) for ev in evs
                     if ev.get("off", -1) >= 0), default=-1)
    diff = state_diff(div_want, div_got)
    result.update(
        divergent=True, batch=b, batch_index=hi_i,
        first_divergent_offset=first_off, end_offset=ends[hi_i],
        replays=replays, diff=diff)

    # minimized repro in the audit.py dump format, replayable offline
    pre_aud = InvariantAuditor()
    for k in range(hi_i):
        pre_aud.observe(batches[k][1])
        pre_aud.violations.clear()
    inputs = None
    try:
        inputs = [r.value for r in _fetch_records(
            log_dir, topic, max(0, first_off), ends[hi_i] + 1)]
    except XrayError:
        pass
    doc = {
        "violations": [{"kind": "bisect_divergence",
                        "detail": "; ".join(
                            f"{k}: {v}" for k, v in sorted(diff.items())),
                        "batch": b, "seq": -1}],
        "batch": b, "pre_state": pre_aud._snapshot(),
        "events": evs, "inputs": inputs, "checkpoint_ref": ckpt_dir,
        "oracle_state": div_want, "shadow_state": div_got,
        "diff": diff,
        "xray": (f"kme-xray --bisect --journal {journal_path} "
                 f"--log-dir {log_dir} --hi-batch {b}"
                 + (f" --checkpoint-dir {ckpt_dir}" if ckpt_dir else "")),
    }
    out_dir = repro_dir or os.path.dirname(os.path.abspath(journal_path))
    path = os.path.join(out_dir, f"xray_bisect_b{b}.json")
    try:
        os.makedirs(out_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f, **_J)
        result["repro"] = path
    except OSError:
        result["repro"] = None
    return result


def replay_bisect_repro(path: str) -> dict:
    """Offline repro replay: seed the journal shadow from the dumped
    pre-batch state, re-apply the dumped events, re-derive the diff
    against the dumped oracle state. `match` is True when it equals the
    dumped diff — the bisect verdict reproduces from the dump alone."""
    from kme_tpu.telemetry.audit import auditor_from_pre

    with open(path) as f:
        doc = json.load(f)
    aud = auditor_from_pre(doc["pre_state"])
    aud.observe(doc["events"])
    aud.violations.clear()
    got = shadow_canon(aud)
    diff = state_diff(doc["oracle_state"], got)
    return {"batch": doc["batch"], "diff": diff,
            "match": diff == doc.get("diff")}


# ---------------------------------------------------------------------------
# cluster mode: consistent cut + global cash conservation


def _open_margin(engine) -> int:
    """Worst-case notional margin of resting orders (fixed mode: buys
    reserve price per unit, sells 100 - price). Position netting can
    make the actual escrow smaller, but the quantity is computed
    identically on both sides of the conservation check from resting
    order sets that byte-match — so agreement is exact."""
    from kme_tpu import opcodes as op

    total = 0
    for rec in engine.orders.values():
        if rec.action == op.BUY:
            total += rec.size * rec.price
        else:
            total += rec.size * (100 - rec.price)
    return int(total)


def cluster_cut(state_root: str, at: Optional[int] = None,
                input_path: Optional[str] = None,
                prefund: int = 8, transfers: bool = True,
                book_slots: Optional[int] = None,
                max_fills: Optional[int] = None) -> dict:
    """Materialize every group of a multi-group run at a consistent
    cut and check global cash conservation against the single-leader
    oracle at the same merge watermark.

    The cut: `at` is a merged-input offset (default: the whole input).
    Re-running the front's deterministic GroupRouter over the input
    prefix yields each group's local substream length — exactly the
    per-group MatchIn.g{k} offsets the live front had produced when its
    merge watermark stood at `at` (both transfer legs of every grant
    ride the same input line, so the cut never splits a transfer).

    Conservation: sum of group balances must equal the single-leader
    oracle's balance sum byte-for-byte, and likewise with open-order
    margin added back (internal transfer pairs net to zero; the
    router's unconsumed pending_reserve residuals are plain balance at
    the granted group and are reported per (aid, group))."""
    from kme_tpu.bridge.front import GroupRouter
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.telemetry import dtrace

    groups = dtrace.discover_groups(state_root)
    if not groups:
        raise XrayError(f"no group*/ directories under {state_root}")
    n = max(k for k, _d in groups) + 1
    in_path = input_path or os.path.join(state_root, "front.in")
    if not os.path.exists(in_path):
        raise XrayError(
            f"no merged input log at {in_path} (pass --input)")
    with open(in_path) as f:
        lines = [ln.rstrip("\n") for ln in f if ln.strip()]
    watermark = len(lines) if at is None else min(int(at), len(lines))

    router = GroupRouter(n, transfers=transfers, prefund=prefund)
    per = router.split(lines[:watermark])
    cuts = [len(p) for p in per]

    kw = {}
    if book_slots is not None:
        kw = {"book_slots": book_slots, "max_fills": max_fills or 16}
    single = OracleEngine("fixed", **kw)
    for ln in lines[:watermark]:
        msg = _parse_replay(ln)
        if msg is not None:
            single.process(msg)
    single_cash = int(sum(single.balances.values()))
    single_margin = _open_margin(single)

    report: dict = {"state_root": state_root, "watermark": watermark,
                    "groups": {}, "cuts": cuts}
    cluster_cash = cluster_margin = 0
    for k, gdir in groups:
        ckd = (os.path.join(gdir, "state")
               if os.path.isdir(os.path.join(gdir, "state")) else gdir)
        log_dir = os.path.join(ckd, "broker-log")
        eng, anchor, replayed = materialize(
            log_dir, cuts[k] if k < len(cuts) else 0,
            topic=f"MatchIn.g{k}", ckpt_dir=ckd, allow_cold=True,
            book_slots=book_slots, max_fills=max_fills)
        cash = int(sum(eng.balances.values()))
        margin = _open_margin(eng)
        cluster_cash += cash
        cluster_margin += margin
        report["groups"][str(k)] = {
            "cut": cuts[k] if k < len(cuts) else 0, "cash": cash,
            "open_margin": margin, "accounts": len(eng.balances),
            "resting_orders": len(eng.orders), "anchor": anchor,
            "replayed": replayed}

    pending = {f"{aid}:g{g}": int(v)
               for (aid, g), v in sorted(router.reserve.items()) if v}
    cluster_view = {"cash": cluster_cash, "open_margin": cluster_margin,
                    "gross": cluster_cash + cluster_margin}
    single_view = {"cash": single_cash, "open_margin": single_margin,
                   "gross": single_cash + single_margin}
    report.update(
        cluster=cluster_view, single_leader=single_view,
        pending_reserve=pending,
        pending_reserve_total=int(sum(router.reserve.values())),
        transfer_shortfalls=router.counters[
            "transfer_shortfall_total"],
        conserved=(json.dumps(cluster_view, **_J)
                   == json.dumps(single_view, **_J)),
        delta=cluster_view["gross"] - single_view["gross"])
    return report
