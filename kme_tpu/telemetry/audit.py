"""Continuous invariant auditing over the flight-recorder journal.

The InvariantAuditor is a SHADOW LEDGER: it subscribes to the journal
(Journal.observers) and replays each batch's lifecycle deltas — accept
margin reservations, fills, cancels, payouts, transfers — using the
reference engine's exact fixed-mode arithmetic (oracle/javalong int32/
int64 wrap semantics), without re-running the matching loop. Against
that shadow it checks, continuously and in-process:

per-event guards (each journaled event must have been legal):
  - margin_overdraw     accept with balance < required risk
  - transfer_overdraw   transfer past the balance guard
  - create_dup          create for an existing account
  - addsym_dup          add_symbol for an existing book
  - accept_no_book      trade accepted on a nonexistent book
  - fill_unknown_maker  fill against a maker not resting in the shadow
  - fill_price_mismatch fill price != the maker's resting price
  - fill_overfill       fill size exceeds maker size or taker residual
  - fill_no_taker       fill with no in-flight accepted taker
  - rest_mismatch       rested size != the taker's unfilled residual
  - unfilled_residual   taker finished with residual but never rested
  - cancel_unknown      cancel-ok for an order the shadow doesn't hold
  - payout_no_book      payout/remove_symbol on a nonexistent book

per-batch conservation invariants:
  - position_conservation  per symbol, position amounts sum to zero
    (every fill credits a long and debits a short symmetrically)
  - escrow_negative        net external inflow (transfers + payout
    settlements) minus the sum of balances must stay >= 0: open-order
    margin lives in this escrow, so a negative value means the engine
    credited money it never collected. The check self-disables once a
    sell above price 100 is accepted — the reference margin formula
    `(size+adj)*(price-100)` legally mints credit there.

at checkpoint cadence (`check_engine`):
  - state_mismatch  the shadow's balances/positions/orders/books
    deep-compared against the engine's `export_state()`
  - hist_mismatch   the shadow's fills_per_order histogram (exact
    mirror: one observation per accepted trade, value = fill pairs)
    and the book_depth observation COUNT (one observation per accepted
    trade or successful cancel; the per-lane depth values depend on
    router placement, so only the count is checked) against the
    device histograms, net of the seed baseline

On violation the auditor increments the `audit_violations` counter,
invokes `on_violation` (kme-serve marks the heartbeat degraded), and
writes a minimized repro dump: the offending batch's events + input
lines, the pre-batch shadow state, and a checkpoint reference —
`replay_repro()` (or `kme-trace --replay-repro`) re-applies the dump
offline and must reproduce the same violations.

Test hook: set `auditor.tamper` to a callable(events)->events to
corrupt the delta stream before replay (deliberate violation
injection); kme-serve wires KME_AUDIT_TAMPER=fill_qty to a canned
first-fill +1 corruption for end-to-end tests.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from kme_tpu import opcodes as op
from kme_tpu.oracle import javalong as jl
from kme_tpu.telemetry.registry import N_BUCKETS, bucket_index

_J = dict(sort_keys=True, separators=(",", ":"))


class Violation(dict):
    """{kind, detail, batch, seq} — a dict so it JSON-serializes into
    repro dumps untouched."""

    def __init__(self, kind: str, detail: str, batch: int = -1,
                 seq: int = -1) -> None:
        super().__init__(kind=kind, detail=detail, batch=batch, seq=seq)

    def __str__(self) -> str:  # pragma: no cover - repr convenience
        return f"[{self['kind']}] b={self['batch']} {self['detail']}"


class InvariantAuditor:
    """Shadow-ledger replay of journal deltas + conservation checks.

    Subscribe with `journal.observers.append(auditor.observe)`; in the
    journal's async mode the replay then runs on the writer thread, off
    the serving hot path. All auditor state is guarded by one lock so
    `check_engine` may be called from the checkpoint path concurrently.
    """

    def __init__(self, registry=None, repro_dir: Optional[str] = None,
                 on_violation: Optional[Callable] = None,
                 max_dumps: int = 8,
                 checkpoint_ref: Optional[str] = None,
                 journal_ref: Optional[str] = None,
                 log_ref: Optional[str] = None) -> None:
        self.balances: Dict[int, int] = {}
        # (aid, sid) -> (amount, available)
        self.positions: Dict[Tuple[int, int], Tuple[int, int]] = {}
        # oid -> [aid, sid, is_buy, price, size]
        self.orders: Dict[int, list] = {}
        # sid -> ({price: [oid FIFO]} buys, {price: [oid FIFO]} sells)
        self.books: Dict[int, Tuple[dict, dict]] = {}
        self.inflow = 0
        self.violations: List[Violation] = []
        self.batches = 0
        self.dumps: List[str] = []
        self.tamper: Optional[Callable] = None
        self.repro_dir = repro_dir
        self.checkpoint_ref = checkpoint_ref
        self.journal_ref = journal_ref
        self.log_ref = log_ref
        self.max_dumps = max_dumps
        self.on_violation = on_violation
        self._unbounded_credit = False
        self._pending: Optional[dict] = None
        self._fills_hist = [0] * N_BUCKETS
        self._depth_obs = 0
        self._hist_base: Optional[dict] = None
        self._lock = threading.Lock()
        self._counter = None
        self._batch_counter = None
        if registry is not None:
            self._counter = registry.counter(
                "audit_violations",
                help="conservation-invariant violations detected")
            self._batch_counter = registry.counter(
                "audit_batches", help="batches audited")

    # ------------------------------------------------------------------
    # journal observer entry point

    def observe(self, events: List[dict], lines=None) -> None:
        """Replay one journaled batch and run the per-batch checks.
        Signature matches Journal observer fan-out (events, lines)."""
        if self.tamper is not None:
            events = self.tamper(events)
        with self._lock:
            batch = next((ev.get("b", -1) for ev in events), -1)
            pre = self._snapshot() if self.repro_dir else None
            found: List[Violation] = []
            for ev in events:
                self._apply(ev, found)
            self._finalize_pending(found)
            self._batch_checks(found, batch)
            self.batches += 1
            if self._batch_counter is not None:
                self._batch_counter.inc()
            if not found:
                return
            self.violations.extend(found)
            if self._counter is not None:
                self._counter.inc(len(found))
            dump = None
            if pre is not None and len(self.dumps) < self.max_dumps:
                dump = self._write_repro(found, batch, pre, events,
                                         lines)
        if self.on_violation is not None:
            self.on_violation(found, dump)

    # ------------------------------------------------------------------
    # event replay (exact fixed-mode arithmetic; see oracle/engine.py)

    def _apply(self, ev: dict, out: List[Violation]) -> None:
        e = ev["e"]
        if e in ("win", "lat", "drop", "reject"):
            return      # timing/terminal records — no ledger effect
        if e == "submit":
            self._finalize_pending(out)
            return
        b, seq = ev.get("b", -1), ev.get("seq", -1)

        def bad(kind, detail):
            out.append(Violation(kind, detail, b, seq))

        aid, sid = ev.get("aid", 0), ev.get("sid", 0)
        qty, px = ev.get("qty", 0), ev.get("px", 0)
        if e == "create":
            if aid in self.balances:
                bad("create_dup", f"aid={aid} already exists")
            else:
                self.balances[aid] = 0
        elif e == "transfer":
            bal = self.balances.get(aid)
            if bal is None or bal < jl.jint(-qty):
                bad("transfer_overdraw",
                    f"aid={aid} bal={bal} transfer={qty}")
            self.balances[aid] = jl.jadd(bal or 0, qty)
            self.inflow += qty
        elif e == "add_symbol":
            if sid in self.books:
                bad("addsym_dup", f"sid={sid}")
            else:
                self.books[sid] = ({}, {})
        elif e == "accept":
            self._accept(ev, bad)
        elif e == "fill":
            self._fill(ev, bad)
        elif e == "rest":
            self._rest(ev, bad)
        elif e == "cancel":
            self._cancel(ev, bad)
        elif e in ("payout", "remove_symbol"):
            self._settle(ev, e == "payout", bad)

    def _accept(self, ev, bad) -> None:
        aid, sid = ev["aid"], ev["sid"]
        qty, px = ev["qty"], ev["px"]
        is_buy = ev["act"] == op.BUY
        if sid not in self.books:
            bad("accept_no_book", f"oid={ev['oid']} sid={sid}")
        # checkBalance (KProcessor.java:167-182) in fixed mode
        sz = jl.jint(qty if is_buy else -qty)
        pos = self.positions.get((aid, sid))
        avail = pos[1] if pos is not None else 0
        neg = jl.jint(-sz)
        adj = (max(min(avail, 0), neg) if is_buy
               else min(max(avail, 0), neg))
        risk = jl.jmul(jl.jadd(sz, adj),
                       jl.jint(px) if is_buy else jl.jint(px - 100))
        bal = self.balances.get(aid)
        if bal is None or bal < risk:
            bad("margin_overdraw",
                f"oid={ev['oid']} aid={aid} bal={bal} risk={risk}")
        self.balances[aid] = jl.jadd(bal or 0, -risk)
        if not is_buy and px > 100:
            self._unbounded_credit = True   # negative risk is legal here
        if adj != 0 and pos is not None:
            self.positions[(aid, sid)] = (pos[0], jl.jadd(avail, -adj))
        self._pending = {"oid": ev["oid"], "aid": aid, "sid": sid,
                         "is_buy": is_buy, "px": px, "rem": qty,
                         "nf": 0, "rested": False}

    def _fill(self, ev, bad) -> None:
        oid, aid = ev["oid"], ev["aid"]
        moid, maid = ev["moid"], ev["maid"]
        sid, qty, px = ev["sid"], ev["qty"], ev["px"]
        taker_bought = ev["act"] == op.BOUGHT
        rec = self.orders.get(moid)
        if rec is None or rec[0] != maid:
            bad("fill_unknown_maker", f"moid={moid} maid={maid}")
        else:
            if rec[3] != px:
                bad("fill_price_mismatch",
                    f"moid={moid} resting px={rec[3]} fill px={px}")
            rec[4] -= qty
            if rec[4] < 0:
                bad("fill_overfill",
                    f"moid={moid} overfilled by {-rec[4]}")
            if rec[4] <= 0:
                self._unrest(moid, rec)
        p = self._pending
        if p is not None and p["oid"] == oid:
            limit = p["px"]
            p["rem"] -= qty
            p["nf"] += 1
            if p["rem"] < 0:
                bad("fill_overfill",
                    f"taker oid={oid} overfilled by {-p['rem']}")
        else:
            bad("fill_no_taker", f"oid={oid} has no in-flight accept")
            limit = px
        # fillOrder x2 (KProcessor.java:276-287): maker at price 0
        # first, taker at the price improvement
        self._fill_apply(maid, sid, not taker_bought, qty, 0, bad)
        self._fill_apply(aid, sid, taker_bought, qty,
                         jl.jint(limit - px), bad)

    def _fill_apply(self, aid, sid, bought, size, price, bad) -> None:
        sz = jl.jint(size if bought else -size)
        key = (aid, sid)
        pos = self.positions.get(key)
        if pos is None:
            self.positions[key] = (sz, sz)
        else:
            na = jl.jadd(pos[0], sz)
            if na == 0:
                # delete-at-zero discards `available` (reference quirk)
                self.positions.pop(key, None)
            else:
                self.positions[key] = (na, jl.jadd(pos[1], sz))
        bal = self.balances.get(aid)
        if bal is None:
            bad("fill_no_balance", f"aid={aid} filled with no balance")
            bal = 0
        self.balances[aid] = jl.jadd(bal, jl.jint(sz * price))

    def _rest(self, ev, bad) -> None:
        p = self._pending
        oid, qty = ev["oid"], ev["qty"]
        if p is None or p["oid"] != oid:
            bad("rest_mismatch", f"oid={oid} rested without accept")
            return
        if p["rem"] != qty:
            bad("rest_mismatch",
                f"oid={oid} residual={p['rem']} rested={qty}")
        p["rested"] = True
        side = self.books.setdefault(p["sid"], ({}, {}))[
            0 if p["is_buy"] else 1]
        side.setdefault(p["px"], []).append(oid)
        self.orders[oid] = [p["aid"], p["sid"], p["is_buy"], p["px"],
                            qty]

    def _cancel(self, ev, bad) -> None:
        oid, aid = ev["oid"], ev["aid"]
        rec = self.orders.get(oid)
        if rec is None or rec[0] != aid:
            bad("cancel_unknown", f"oid={oid} aid={aid}")
            return
        self._unrest(oid, rec)
        self._release(rec, bad)
        self._depth_obs += 1

    def _settle(self, ev, credit, bad) -> None:
        """payout / remove_symbol: wipe both book sides min-price-first
        FIFO with margin release (the fixed-mode removeAllOrders), then
        for a YES payout credit `amount * size` per position."""
        sid = ev["sid"]
        s = abs(sid)
        book = self.books.pop(s, None)
        if book is None:
            bad("payout_no_book", f"sid={sid}")
            return
        for side in book:
            for px in sorted(side):
                for oid in side[px]:
                    rec = self.orders.pop(oid, None)
                    if rec is not None:
                        self._release(rec, bad)
        if credit and ev["sid"] >= 0:
            qty = ev["qty"]
            for key in [k for k in self.positions if k[1] == s]:
                amt, _avail = self.positions.pop(key)
                bal = self.balances.get(key[0])
                if bal is None:
                    bad("fill_no_balance",
                        f"payout credits aid={key[0]} with no balance")
                    bal = 0
                pay = jl.jmul(amt, qty)
                self.balances[key[0]] = jl.jadd(bal, pay)
                # settlement is external funding for escrow purposes
                self.inflow += pay
        else:
            for key in [k for k in self.positions if k[1] == s]:
                del self.positions[key]

    def _release(self, rec, bad) -> None:
        """postRemoveAdjustments (KProcessor.java:325-333), fixed."""
        aid, sid, is_buy, price, size = rec
        sz = jl.jint(size if is_buy else -size)
        pos = self.positions.get((aid, sid))
        blocked = (pos[0] - pos[1]) if pos is not None else 0
        neg = jl.jint(-sz)
        adj = (max(min(blocked, 0), neg) if is_buy
               else min(max(blocked, 0), neg))
        bal = self.balances.get(aid)
        if bal is None:
            bad("fill_no_balance",
                f"margin release for aid={aid} with no balance")
            bal = 0
        unit = jl.jint(price) if is_buy else jl.jint(price - 100)
        self.balances[aid] = jl.jadd(
            bal, jl.jmul(jl.jadd(sz, adj), unit))
        if adj != 0 and pos is not None:
            self.positions[(aid, sid)] = (pos[0], jl.jadd(pos[1], adj))

    def _unrest(self, oid, rec) -> None:
        self.orders.pop(oid, None)
        book = self.books.get(rec[1])
        if book is None:
            return
        bucket = book[0 if rec[2] else 1].get(rec[3])
        if bucket and oid in bucket:
            bucket.remove(oid)
            if not bucket:
                del book[0 if rec[2] else 1][rec[3]]

    def _finalize_pending(self, out: List[Violation]) -> None:
        p, self._pending = self._pending, None
        if p is None:
            return
        if p["rem"] > 0 and not p["rested"]:
            out.append(Violation(
                "unfilled_residual",
                f"oid={p['oid']} residual={p['rem']} never rested"))
        # device histogram mirror: fills_per_order observes nf per
        # accepted trade; book_depth observes once per accepted trade
        self._fills_hist[bucket_index(p["nf"])] += 1
        self._depth_obs += 1

    # ------------------------------------------------------------------
    # per-batch conservation checks

    def _batch_checks(self, out: List[Violation], batch: int) -> None:
        sums: Dict[int, int] = {}
        for (aid, sid), (amt, _a) in self.positions.items():
            sums[sid] = sums.get(sid, 0) + amt
        for sid, total in sums.items():
            if total != 0:
                out.append(Violation(
                    "position_conservation",
                    f"sid={sid} position amounts sum to {total}",
                    batch))
        if not self._unbounded_credit:
            escrow = self.inflow - sum(self.balances.values())
            if escrow < 0:
                out.append(Violation(
                    "escrow_negative",
                    f"balances exceed external inflow by {-escrow}",
                    batch))

    # ------------------------------------------------------------------
    # engine cross-checks (checkpoint cadence)

    def check_engine(self, state: dict,
                     histograms: Optional[dict] = None
                     ) -> List[Violation]:
        """Deep-compare the shadow against the engine's export_state()
        (and optionally its histograms() net of the seed baseline).
        Returns (and records) any mismatches as violations."""
        with self._lock:
            found: List[Violation] = []

            def bad(kind, detail):
                found.append(Violation(kind, detail, self.batches))

            if state.get("balances") != self.balances:
                d = _dict_diff(state.get("balances", {}), self.balances)
                bad("state_mismatch", f"balances differ: {d}")
            eng_pos = {k: tuple(v)
                       for k, v in state.get("positions", {}).items()}
            if eng_pos != self.positions:
                d = _dict_diff(eng_pos, self.positions)
                bad("state_mismatch", f"positions differ: {d}")
            eng_ord = {o: (v["aid"], v["sid"], v["is_buy"], v["price"],
                           v["size"])
                       for o, v in state.get("orders", {}).items()}
            shd_ord = {o: tuple(v) for o, v in self.orders.items()}
            if eng_ord != shd_ord:
                d = _dict_diff(eng_ord, shd_ord)
                bad("state_mismatch", f"orders differ: {d}")
            eng_books = set(state.get("books", {}))
            if eng_books != set(self.books):
                bad("state_mismatch",
                    f"books differ: engine={sorted(eng_books)} "
                    f"shadow={sorted(self.books)}")
            if histograms is not None:
                base = self._hist_base or {}
                fills = [a - b for a, b in zip(
                    histograms.get("fills_per_order",
                                   [0] * N_BUCKETS),
                    base.get("fills_per_order", [0] * N_BUCKETS))]
                if fills != self._fills_hist:
                    bad("hist_mismatch",
                        f"fills_per_order device={fills} "
                        f"shadow={self._fills_hist}")
                if "book_depth" in histograms:
                    dev = (sum(histograms["book_depth"])
                           - sum(base.get("book_depth", [])))
                    if dev != self._depth_obs:
                        bad("hist_mismatch",
                            f"book_depth observations device={dev} "
                            f"shadow={self._depth_obs}")
            if found:
                self.violations.extend(found)
                if self._counter is not None:
                    self._counter.inc(len(found))
        if found and self.on_violation is not None:
            self.on_violation(found, None)
        return found

    # ------------------------------------------------------------------
    # seeding (resume) + snapshots + repro dumps

    def seed(self, state: dict,
             histograms: Optional[dict] = None) -> None:
        """Adopt an engine export as the shadow's starting point (a
        resumed service audits forward from the checkpoint). Book FIFO
        order within a price bucket is reconstructed by ascending oid —
        an approximation of arrival order that only matters for margin
        release ordering during wipes. The escrow baseline resets so
        the invariant tracks post-seed flow only."""
        with self._lock:
            self.balances = dict(state.get("balances", {}))
            self.positions = {k: tuple(v) for k, v in
                              state.get("positions", {}).items()}
            self.orders = {o: [v["aid"], v["sid"], v["is_buy"],
                               v["price"], v["size"]]
                           for o, v in state.get("orders", {}).items()}
            self.books = {sid: ({}, {})
                          for sid in state.get("books", {})}
            for oid in sorted(self.orders):
                aid, sid, is_buy, px, size = self.orders[oid]
                book = self.books.setdefault(sid, ({}, {}))
                book[0 if is_buy else 1].setdefault(px, []).append(oid)
            self.inflow = sum(self.balances.values())
            self._hist_base = ({k: list(v)
                                for k, v in histograms.items()}
                               if histograms else None)
            self._fills_hist = [0] * N_BUCKETS
            self._depth_obs = 0
            self._pending = None

    def _snapshot(self) -> dict:
        return {
            "balances": dict(self.balances),
            "positions": {f"{a}:{s}": list(v)
                          for (a, s), v in self.positions.items()},
            "orders": {str(o): list(v)
                       for o, v in self.orders.items()},
            "books": sorted(self.books),
            "inflow": self.inflow,
            "unbounded_credit": self._unbounded_credit,
        }

    def _write_repro(self, found, batch, pre, events, lines
                     ) -> Optional[str]:
        try:
            os.makedirs(self.repro_dir, exist_ok=True)
            path = os.path.join(self.repro_dir,
                                f"audit_repro_b{batch}.json")
            doc = {"violations": found, "batch": batch,
                   "pre_state": pre, "events": events,
                   "inputs": ([ln for grp in lines for ln in grp]
                              if lines else None),
                   "checkpoint_ref": self.checkpoint_ref,
                   "xray": self._xray_cmd(batch)}
            with open(path, "w") as f:
                json.dump(doc, f, **_J)
            self.dumps.append(path)
            return path
        except OSError:  # pragma: no cover - disk-full etc.
            return None

    def _xray_cmd(self, batch: int) -> Optional[str]:
        """The ready-to-run `kme-xray --bisect` line for the violating
        window — pasted from the repro dump, it binary-searches the
        journal-vs-oracle divergence that tripped this auditor."""
        if not (self.journal_ref and self.log_ref):
            return None
        cmd = (f"kme-xray --bisect --journal {self.journal_ref} "
               f"--log-dir {self.log_ref} --hi-batch {batch}")
        if self.checkpoint_ref:
            cmd += f" --checkpoint-dir {self.checkpoint_ref}"
        return cmd


def _dict_diff(a: dict, b: dict, limit: int = 4) -> str:
    keys = [k for k in set(a) | set(b) if a.get(k) != b.get(k)]
    parts = [f"{k}: engine={a.get(k)} shadow={b.get(k)}"
             for k in sorted(keys, key=str)[:limit]]
    more = len(keys) - limit
    return "; ".join(parts) + (f"; +{more} more" if more > 0 else "")


def load_repro(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def auditor_from_pre(pre: dict) -> "InvariantAuditor":
    """Fresh auditor seeded from a repro dump's `pre_state` snapshot
    (the _snapshot wire shape). Shared by replay_repro and the xray
    bisect repro replayer."""
    aud = InvariantAuditor()
    aud.balances = {int(k): v for k, v in pre["balances"].items()}
    aud.positions = {(int(a), int(s)): tuple(v)
                     for ks, v in pre["positions"].items()
                     for a, s in [ks.split(":")]}
    aud.orders = {int(o): list(v) for o, v in pre["orders"].items()}
    aud.books = {sid: ({}, {}) for sid in pre["books"]}
    for oid in sorted(aud.orders):
        aid, sid, is_buy, px, size = aud.orders[oid]
        book = aud.books.setdefault(sid, ({}, {}))
        book[0 if is_buy else 1].setdefault(px, []).append(oid)
    aud.inflow = pre["inflow"]
    aud._unbounded_credit = pre.get("unbounded_credit", False)
    return aud


def replay_repro(path: str) -> List[Violation]:
    """Offline replay of a repro dump: seed a fresh auditor with the
    dumped pre-batch shadow state, re-apply the dumped events, return
    the violations found — which must cover the dumped ones."""
    doc = load_repro(path)
    aud = auditor_from_pre(doc["pre_state"])
    aud.observe(doc["events"])
    return aud.violations
