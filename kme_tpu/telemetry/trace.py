"""Phase timing spans + Chrome trace-event export.

PhaseTimer replaces the per-session `time.perf_counter()` blocks that
were triplicated across runtime/session.py, runtime/seqsession.py, and
parallel/seqmesh.py. Its `totals` dict IS the session's `phases`
attribute (same object, assigned once), and — unlike the old code —
totals ACCUMULATE across batches; callers snapshot/reset explicitly.

When a TraceRecorder is installed (module-global via install(), as
`kme-serve --trace-out` and `bench --trace-out` do), every phase span
is also emitted as a Chrome trace event; save() writes the standard
{"traceEvents": [...]} JSON that chrome://tracing / Perfetto load
directly.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager


class TraceRecorder:
    """Collects Chrome trace-event "X" (complete) events.

    Timestamps are microseconds relative to recorder creation; `tid`
    groups events into named rows (one per session/component)."""

    def __init__(self):
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._events = []
        self._tids: dict = {}

    def _tid(self, track: str) -> int:
        t = self._tids.get(track)
        if t is None:
            t = len(self._tids)
            self._tids[track] = t
        return t

    def add(self, name: str, start_s: float, dur_s: float,
            track: str = "main", args: dict | None = None) -> None:
        ev = {
            "name": name,
            "ph": "X",
            "ts": (start_s - self._t0) * 1e6,
            "dur": dur_s * 1e6,
            "pid": os.getpid(),
        }
        with self._lock:
            ev["tid"] = self._tid(track)
            if args:
                ev["args"] = args
            self._events.append(ev)

    def flow(self, name: str, phase: str, flow_id: int,
             track: str = "main", at_s: float | None = None) -> None:
        """Chrome trace FLOW event: ph "s" starts arrow `flow_id`, ph
        "f" finishes it — the renderer draws a causality arrow from the
        span enclosing the start to the span enclosing the finish
        (bp="e": bind to the enclosing slice). Links an order batch's
        submit/engine span to its produce span across tracks."""
        if phase not in ("s", "f"):
            raise ValueError(f"flow phase must be 's' or 'f', "
                             f"got {phase!r}")
        t = at_s if at_s is not None else time.perf_counter()
        ev = {
            "name": name,
            "ph": phase,
            "cat": "flow",
            "id": int(flow_id),
            "ts": (t - self._t0) * 1e6,
            "pid": os.getpid(),
        }
        if phase == "f":
            ev["bp"] = "e"
        with self._lock:
            ev["tid"] = self._tid(track)
            self._events.append(ev)

    def instant(self, name: str, track: str = "main",
                args: dict | None = None) -> None:
        ev = {
            "name": name,
            "ph": "i",
            "ts": (time.perf_counter() - self._t0) * 1e6,
            "pid": os.getpid(),
            "s": "t",
        }
        with self._lock:
            ev["tid"] = self._tid(track)
            if args:
                ev["args"] = args
            self._events.append(ev)

    def trace_events(self) -> list:
        with self._lock:
            meta = [
                {"name": "thread_name", "ph": "M", "pid": os.getpid(),
                 "tid": tid, "args": {"name": track}}
                for track, tid in self._tids.items()
            ]
            return meta + list(self._events)

    def save(self, path: str) -> None:
        doc = {"traceEvents": self.trace_events(),
               "displayTimeUnit": "ms"}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)


# module-global recorder: CLI entry points install one so every
# PhaseTimer in the process emits trace events without plumbing
_tracer: TraceRecorder | None = None


def install(recorder: TraceRecorder | None) -> None:
    global _tracer
    _tracer = recorder


def get_tracer() -> TraceRecorder | None:
    return _tracer


class PhaseTimer:
    """Accumulating span timer.

    `totals` maps phase name -> cumulative seconds across every span
    since the last reset(). Sessions expose it directly as
    `self.phases`."""

    def __init__(self, track: str = "main"):
        self.totals: dict = {}
        self.track = track

    @contextmanager
    def phase(self, name: str, **args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            tr = _tracer
            if tr is not None:
                tr.add(name, t0, dt, track=self.track,
                       args=args or None)

    def add(self, name: str, seconds: float) -> None:
        """Fold an externally-timed duration into the totals."""
        self.totals[name] = self.totals.get(name, 0.0) + seconds

    def reset(self) -> None:
        self.totals.clear()
