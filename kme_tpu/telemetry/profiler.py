"""Always-on continuous profiling — host, device, and trigger planes.

Following the Google-Wide Profiling discipline (Ren et al., IEEE Micro
2010; PAPERS.md), profiling here is not a tool you attach when things
are already broken: it runs continuously at negligible overhead, its
output is retained (the TSDB, telemetry/tsdb.py), and regressions are
answered from history instead of reproduced under a debugger.

Three planes:

1. HOST — `StageProfiler`, a sampling wall-clock profiler. A daemon
   thread samples every live Python stack ~200x/s and attributes each
   sample to one of the serving-pipeline stage scopes the kme-lint
   scope tables already name (parse / plan / dispatch / collect /
   produce — analysis/rules.py HOT_SCOPES); everything else is `other`.
   Per-stage sample fractions publish as `prof_stage_frac_<stage>`
   gauges, so they ride the heartbeat into the TSDB and kme-prof can
   diff them across windows.

2. DEVICE — `device_plane()` wraps the compiled scan step's
   `cost_analysis()` (flops + bytes touched per batch) and a measured
   H2D bandwidth probe, and folds in the session's live
   `h2d_overlap_frac` / `h2d_stage_s` advisories (PR 14). The result is
   a per-backend transfer-vs-compute JSON artifact
   (`write_transfer_artifact`) — the measured ratio the ROADMAP item-4
   autotuner consumes. CPU CI records a real CPU ratio today; a future
   TPU run overwrites ONLY its own backend key in place.

3. TRIGGER — `TriggerCapture`. SLO burn (slo.py's degradation reason)
   or a p99 exemplar past a threshold auto-records a bounded capture:
   the installed Chrome-trace recorder's current window plus the
   exemplar trace ids, written as `capture_NNN.json`. The span ids are
   the same deterministic `tid`s the journal records, so a capture
   links straight into `kme-trace` waterfalls. Cooldown + max-capture
   bounds keep a sustained burn from turning the profiler into the
   incident.

The profiler reads wall clocks by design — it measures the serve loop,
it never participates in replay/recovery. That legitimacy is recorded
in the analysis scope tables (analysis/rules.py PROFILER_SCOPES), not
grandfathered.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, Optional

# stage attribution tables: function names (f_code.co_name) that mark a
# sample as belonging to a serving-pipeline stage. These mirror the
# HOT_SCOPES entries in analysis/rules.py — the same functions the
# lint rules police for blocking I/O are the ones wall time is
# attributed to.
STAGE_FUNCS: Dict[str, tuple] = {
    "parse": ("_parse_batch", "_parse", "parse_order", "decode_frames"),
    "plan": ("_plan", "plan_batch", "pack_msgs", "route_line"),
    "dispatch": ("submit", "_stage_and_dispatch", "dispatch",
                 "build_seq_scan", "call_scan"),
    "collect": ("collect", "_collect_one", "_fetch_outputs", "_run",
                "_drain_pipeline"),
    "produce": ("_produce_out", "_produce_buffer", "_produce_xfer",
                "produce_batch", "produce_frames", "record_batch"),
}

PROF_STAGES = tuple(STAGE_FUNCS) + ("other",)

_FUNC_TO_STAGE = {fn: stage
                  for stage, fns in STAGE_FUNCS.items() for fn in fns}


class StageProfiler:
    """Sampling host profiler attributing wall time to pipeline stages.

    A daemon thread walks `sys._current_frames()` every `interval_s`
    seconds; each thread's stack is attributed to the INNERMOST frame
    whose function name appears in STAGE_FUNCS (idle/unrelated stacks
    are ignored entirely, so fractions describe time spent inside the
    serving pipeline). Registry publication is cheap gauges only — the
    profiler never touches device state or takes foreign locks."""

    def __init__(self, registry=None, interval_s: float = 0.005):
        self.registry = registry
        self.interval_s = max(0.001, float(interval_s))
        self.samples: Dict[str, int] = {s: 0 for s in PROF_STAGES}
        self.total = 0              # samples that hit ANY stage scope
        self.wall_samples = 0       # sampler wakeups
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._own_ident: Optional[int] = None

    # -- sampling -------------------------------------------------------

    def _classify(self, frame) -> Optional[str]:
        while frame is not None:
            stage = _FUNC_TO_STAGE.get(frame.f_code.co_name)
            if stage is not None:
                return stage
            frame = frame.f_back
        return None

    def sample_once(self) -> None:
        self.wall_samples += 1
        frames = sys._current_frames()
        for ident, frame in frames.items():
            if ident == self._own_ident:
                continue
            stage = self._classify(frame)
            if stage is not None:
                self.samples[stage] += 1
                self.total += 1

    def _loop(self) -> None:
        self._own_ident = threading.get_ident()
        n = 0
        while not self._stop.wait(self.interval_s):
            self.sample_once()
            n += 1
            if self.registry is not None and n % 64 == 0:
                self.publish(self.registry)

    def start(self) -> "StageProfiler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="kme-prof-sampler", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self.registry is not None:
            self.publish(self.registry)

    # -- reporting ------------------------------------------------------

    def stage_fractions(self) -> Dict[str, float]:
        """{stage: fraction of in-pipeline samples} (0.0 when quiet)."""
        t = self.total
        return {s: (self.samples[s] / t if t else 0.0)
                for s in PROF_STAGES if s != "other"}

    def publish(self, registry) -> None:
        registry.gauge(
            "prof_samples_total",
            "host profiler samples attributed to a pipeline stage"
        ).set(self.total)
        registry.gauge(
            "prof_wall_samples_total",
            "host profiler sampler wakeups").set(self.wall_samples)
        for stage, frac in self.stage_fractions().items():
            registry.gauge(
                f"prof_stage_frac_{stage}",
                f"fraction of in-pipeline wall samples in the "
                f"{stage} stage").set(round(frac, 4))


# -- device plane -----------------------------------------------------------


H2D_PROBE_BYTES = 8 << 20


def _measure_h2d_bytes_per_s(probe_bytes: int = H2D_PROBE_BYTES,
                             repeats: int = 3) -> Optional[float]:
    """Measured host->device copy bandwidth (best of `repeats`)."""
    try:
        import jax
        import numpy as np
    except ImportError:
        return None
    buf = np.zeros(probe_bytes // 4, dtype=np.int32)
    best = None
    try:
        for _ in range(repeats):
            t0 = time.perf_counter()
            dev = jax.device_put(buf)
            dev.block_until_ready()
            dt = time.perf_counter() - t0
            if dt > 0 and (best is None or dt < best):
                best = dt
    except Exception:       # noqa: BLE001 — probe only, never fatal
        return None
    return probe_bytes / best if best else None


def device_plane(session=None, cfg=None, k: int = 4) -> dict:
    """Transfer-vs-compute characterization for the current backend.

    Uses the compiled scan step's `cost_analysis()` (flops + bytes per
    k-chunk batch; engine/seq.py `step_cost_analysis`) plus a measured
    H2D bandwidth probe. When a live SeqSession is given, its measured
    `h2d_overlap_frac` / `h2d_stage_s` advisories (PR 14) fold in, so
    the artifact reflects the run, not just the machine."""
    try:
        import jax

        backend = jax.default_backend()
    except ImportError:
        backend = "none"
    doc: dict = {"backend": backend, "probe_bytes": H2D_PROBE_BYTES}
    cost = None
    if cfg is None and session is not None:
        cfg = getattr(session, "cfg", None)
    if cfg is not None:
        from kme_tpu.engine.seq import step_cost_analysis

        cost = step_cost_analysis(cfg, k)
    if cost:
        doc["flops_per_batch"] = cost.get("flops")
        doc["bytes_per_batch"] = cost.get("bytes_accessed")
        if cost.get("flops") and cost.get("bytes_accessed"):
            doc["flops_per_byte"] = round(
                cost["flops"] / cost["bytes_accessed"], 4)
    h2d = _measure_h2d_bytes_per_s()
    if h2d:
        doc["h2d_bytes_per_s"] = round(h2d, 1)
        if doc.get("bytes_per_batch"):
            # the autotuner's ratio: seconds moving one batch's bytes
            # over the wire vs (roofline) seconds computing on them
            xfer_s = doc["bytes_per_batch"] / h2d
            doc["transfer_s_per_batch"] = round(xfer_s, 9)
    if session is not None:
        ov = getattr(session, "h2d_overlap_frac", None)
        if ov:
            doc["h2d_overlap_frac"] = ov
        phases = getattr(session, "phases", None) or {}
        stage_s = phases.get("stage_s")
        if stage_s:
            doc["h2d_stage_s"] = round(stage_s, 6)
        disp = phases.get("dispatch_s", 0.0) + phases.get("fetch_s", 0.0)
        if stage_s and disp:
            doc["transfer_compute_ratio"] = round(stage_s / disp, 4)
    return doc


def write_transfer_artifact(path: str, plane: dict) -> dict:
    """Merge one backend's device plane into the per-backend artifact
    IN PLACE: `{backend: {...}}` keyed by backend name, other backends'
    recorded ratios untouched (CPU CI writes "cpu" today; a TPU run
    later overwrites only "tpu"). Returns the full document."""
    doc = {}
    try:
        with open(path) as f:
            loaded = json.load(f)
        if isinstance(loaded, dict):
            doc = loaded
    except (OSError, ValueError):
        pass
    entry = dict(plane)
    backend = entry.pop("backend", "unknown")
    entry["recorded_at"] = time.time()
    doc[backend] = entry
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return doc


def read_transfer_artifact(path: str) -> dict:
    """The per-backend artifact, `{backend: plane}` (ROADMAP item-4
    autotuner input). Raises on a missing/undecodable file — consumers
    must know the ratio is absent, not silently assume one."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: transfer artifact must be a dict")
    return doc


# -- trigger-based capture --------------------------------------------------


class TriggerCapture:
    """Bounded auto-capture on SLO burn or a slow p99 exemplar.

    `maybe_fire(reason, exemplars)` is called from the serve loop's
    rate-limited publish path. When armed (cooldown elapsed, budget
    left) and either `reason` is set or an exemplar's `e2e_us` exceeds
    `p99_us`, one capture lands in `out_dir`:

    - `capture_NNN.json` — trigger metadata plus the exemplar list;
      each exemplar's deterministic `tid` resolves through
      `kme-trace --cluster --order AID:OID` to a full waterfall;
    - the process-global Chrome-trace recorder's events at capture
      time (when one is installed via --trace-out) — the bounded
      "what was the engine doing" window;
    - a `jax.profiler` device trace under `capture_NNN.jaxprof/` when
      the runtime supports it (best-effort, never fatal).
    """

    def __init__(self, out_dir: str, p99_us: Optional[int] = None,
                 cooldown_s: float = 30.0, max_captures: int = 4,
                 jax_window_s: float = 0.0, registry=None):
        self.out_dir = out_dir
        self.p99_us = p99_us
        self.cooldown_s = float(cooldown_s)
        self.max_captures = int(max_captures)
        self.jax_window_s = float(jax_window_s)
        self.registry = registry
        self.captures = 0
        self._last_fire = -float("inf")

    def _why(self, reason, exemplars) -> Optional[dict]:
        if reason:
            return {"trigger": "slo_burn", "reason": reason}
        if self.p99_us is not None:
            for ex in exemplars or ():
                if int(ex.get("e2e_us", 0)) > self.p99_us:
                    return {"trigger": "p99_exemplar",
                            "threshold_us": self.p99_us,
                            "e2e_us": int(ex["e2e_us"])}
        return None

    def maybe_fire(self, reason: Optional[str], exemplars) -> Optional[str]:
        """Returns the capture path when one fired, else None."""
        if self.captures >= self.max_captures:
            return None
        now = time.monotonic()
        if now - self._last_fire < self.cooldown_s:
            return None
        why = self._why(reason, exemplars)
        if why is None:
            return None
        self._last_fire = now
        self.captures += 1
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir,
                            f"capture_{self.captures:03d}.json")
        doc = {"time": time.time(), **why,
               "exemplars": [dict(ex) for ex in (exemplars or ())],
               # tid is the journal's span key: kme-trace joins it
               "resolve_with": "kme-trace --order AID:OID "
                               "(or --cluster for grouped runs)"}
        from kme_tpu.telemetry.trace import get_tracer

        tracer = get_tracer()
        if tracer is not None:
            doc["trace_events"] = tracer.trace_events()
        if self.jax_window_s > 0:
            jdir = path[:-5] + ".jaxprof"
            if self._jax_capture(jdir, self.jax_window_s):
                doc["jax_profile_dir"] = jdir
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        if self.registry is not None:
            self.registry.gauge(
                "prof_captures_total",
                "trigger-fired profile captures").set(self.captures)
        return path

    @staticmethod
    def _jax_capture(out_dir: str, window_s: float) -> bool:
        try:
            import jax

            jax.profiler.start_trace(out_dir)
            time.sleep(window_s)
            jax.profiler.stop_trace()
            return True
        except Exception:   # noqa: BLE001 — capture is best-effort
            return False


# ---------------------------------------------------------------------------
# capture reader (kme-prof --captures): TriggerCapture and xray
# watchpoint captures share the capture_NNN.json namespace and doc shape


def list_captures(dir_path: str) -> list:
    """capture_NNN.json paths in a capture directory, index order."""
    import re

    pat = re.compile(r"^capture_(\d+)\.json$")
    try:
        names = os.listdir(dir_path)
    except OSError:
        return []
    out = []
    for n in names:
        m = pat.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(dir_path, n)))
    return [p for _i, p in sorted(out)]


def format_capture(path: str) -> str:
    """One capture doc as human-readable lines."""
    with open(path) as f:
        doc = json.load(f)
    when = time.strftime("%Y-%m-%d %H:%M:%S",
                         time.localtime(doc.get("time", 0)))
    trig = doc.get("trigger", "?")
    head = f"{os.path.basename(path)}  {when}  trigger={trig}"
    if trig == "watchpoint":
        head += (f"  predicate={doc.get('predicate')!r}"
                 f"  offset={doc.get('offset')}"
                 f"  value={doc.get('value')}")
    elif trig == "slo_burn":
        head += f"  reason={doc.get('reason')}"
    elif trig == "p99_exemplar":
        head += (f"  e2e_us={doc.get('e2e_us')}"
                 f"  threshold_us={doc.get('threshold_us')}")
    lines = [head]
    for ex in doc.get("exemplars") or []:
        lines.append(
            f"  exemplar off={ex.get('off')} oid={ex.get('oid')} "
            f"aid={ex.get('aid')} e2e_us={ex.get('e2e_us')} "
            f"tid={ex.get('tid')}")
    if doc.get("trace_events") is not None:
        lines.append(f"  trace events: {len(doc['trace_events'])}")
    if doc.get("jax_profile_dir"):
        lines.append(f"  jax profile: {doc['jax_profile_dir']}")
    if doc.get("repro"):
        lines.append(f"  repro: {doc['repro']}")
    if doc.get("resolve_with"):
        lines.append(f"  resolve: {doc['resolve_with']}")
    return "\n".join(lines)
