"""Service-level objectives over the live latency surface.

An SLO here is "p99 of stage S stays under T ms" plus an optional
throughput floor, evaluated against the same LatencyHistograms the
/metrics scrape reads. Rather than alert on a single slow scrape, the
evaluator tracks an ERROR BUDGET: every observation slower than the
target is a bad event, the budget says what fraction of events may be
bad (e.g. 0.001 = 99.9 % must meet the target), and the BURN RATE is
how fast the budget is being consumed (bad_fraction / budget — burn 1.0
means the budget exactly runs out over the window; sustained burn > 1
means the objective will be missed).

The service calls `evaluate()` once per publish interval; the returned
reason string (or None) feeds the heartbeat `degraded` field the
supervisor already watches, so an SLO breach surfaces through the same
channel as an audit violation — no new control plane.

Everything is computed from counter DELTAS between evaluations, so a
startup spike ages out instead of poisoning the objective forever.
"""

from __future__ import annotations

import time

from kme_tpu.telemetry.registry import Registry

# stages the serving pipeline stamps (service.py); "e2e" spans broker
# admission -> produce visible
STAGES = ("ingress", "plan", "device", "produce", "e2e", "consume")


class SLO:
    """One latency objective (+ optional throughput floor).

    Parameters
    ----------
    registry : the Registry holding the stage LatencyHistograms
    stage : which `lat_<stage>` histogram to watch (see STAGES)
    p99_ms : latency target — an observation over this is a bad event
    budget : allowed bad-event fraction (0.001 == "99.9 % under target")
    min_ops : minimum observations per window before judging (a quiet
        service is not a degraded service)
    min_records_per_s : optional throughput floor, measured from the
        `service_records` counter
    window_s : evaluation smoothing window; burn rate is computed over
        deltas at least this old
    """

    def __init__(self, registry: Registry, stage: str = "e2e",
                 p99_ms: float = 50.0, budget: float = 0.001,
                 min_ops: int = 100, min_records_per_s: float = 0.0,
                 window_s: float = 5.0, clock=time.monotonic):
        if stage not in STAGES:
            raise ValueError(f"unknown SLO stage {stage!r}; "
                             f"expected one of {STAGES}")
        self.registry = registry
        self.stage = stage
        self.p99_ms = float(p99_ms)
        self.budget = max(1e-9, float(budget))
        self.min_ops = int(min_ops)
        self.min_records_per_s = float(min_records_per_s)
        self.window_s = float(window_s)
        self._clock = clock
        # previous window edge: (t, total_count, bad_count, records)
        self._prev = None
        self.last_reason = None

    # -- current raw readings ------------------------------------------

    def _readings(self):
        hist = self.registry.latency(f"lat_{self.stage}")
        bad = hist.count_over(self.p99_ms * 1e-3)
        total = hist.count
        recs = self.registry.counter("service_records").value
        return total, bad, recs

    def evaluate(self) -> str | None:
        """Advance the window and return a degradation reason, or None.

        Also publishes `slo_burn_rate`, `slo_bad_events_total`,
        `slo_window_ops`, and `slo_ok` into the registry so the SLO
        state is scrapeable alongside the latencies it judges."""
        now = self._clock()
        total, bad, recs = self._readings()
        reg = self.registry
        reg.counter("slo_bad_events_total",
                    "observations over the SLO latency target").set(bad)
        if self._prev is None:
            self._prev = (now, total, bad, recs)
            reg.gauge("slo_ok", "1 while the SLO holds").set(1)
            return None
        t0, total0, bad0, recs0 = self._prev
        dt = now - t0
        if dt < self.window_s:
            return self.last_reason
        d_total = total - total0
        d_bad = bad - bad0
        d_recs = recs - recs0
        self._prev = (now, total, bad, recs)

        reason = None
        if d_total >= self.min_ops:
            bad_frac = d_bad / d_total
            burn = bad_frac / self.budget
            reg.gauge("slo_burn_rate",
                      "error-budget burn rate (1.0 = budget exactly "
                      "consumed over the window)").set(round(burn, 3))
            if burn > 1.0:
                reason = (f"slo burn {burn:.1f}x: "
                          f"{self.stage} p99>{self.p99_ms}ms for "
                          f"{bad_frac:.2%} of {d_total} ops "
                          f"(budget {self.budget:.2%})")
        reg.gauge("slo_window_ops",
                  "latency observations in the last SLO window").set(d_total)
        if (reason is None and self.min_records_per_s > 0
                and d_recs / dt < self.min_records_per_s and d_recs >= 0):
            reason = (f"slo throughput {d_recs / dt:.0f} rec/s below "
                      f"floor {self.min_records_per_s:.0f}")
        reg.gauge("slo_ok", "1 while the SLO holds").set(
            0 if reason else 1)
        self.last_reason = reason
        return reason

    def describe(self) -> dict:
        return {"stage": self.stage, "p99_ms": self.p99_ms,
                "budget": self.budget, "min_ops": self.min_ops,
                "min_records_per_s": self.min_records_per_s,
                "window_s": self.window_s}
