"""Unified telemetry: metric registry, phase tracing, live HTTP surface.

- registry: Counter/Gauge/Histogram + Prometheus text + JSON export
- trace: PhaseTimer spans + Chrome trace-event recording
- httpd: stdlib /metrics endpoint over a Registry
"""

from kme_tpu.telemetry.registry import (  # noqa: F401
    BUCKET_LE,
    N_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    bucket_index,
)
from kme_tpu.telemetry.trace import (  # noqa: F401
    PhaseTimer,
    TraceRecorder,
    get_tracer,
    install,
)
from kme_tpu.telemetry.httpd import start_metrics_server  # noqa: F401
