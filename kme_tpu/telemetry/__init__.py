"""Unified telemetry: metric registry, phase tracing, live HTTP surface,
order-lifecycle flight recorder, and continuous invariant auditing.

- registry: Counter/Gauge/Histogram/LatencyHistogram + Prometheus text
  + JSON export
- trace: PhaseTimer spans + Chrome trace-event recording (incl. flow
  arrows)
- httpd: stdlib /metrics endpoint over a Registry
- journal: append-only lifecycle journal (jsonl/binary) + readers
- audit: shadow-ledger invariant auditor over the journal
- slo: error-budget objectives over the live latency histograms
- top: the kme-top live operations dashboard
- tsdb: on-disk metrics history (fixed-width binary segments)
- profiler: continuous host/device profiling + trigger captures
- events: control-plane flight recorder (durable cluster event
  timeline) + the kme-events merge/query pipeline
"""

from kme_tpu.telemetry.registry import (  # noqa: F401
    BUCKET_LE,
    LAT_BOUNDS,
    LAT_N_BUCKETS,
    N_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    LatencyHistogram,
    Registry,
    bucket_index,
)
from kme_tpu.telemetry.trace import (  # noqa: F401
    PhaseTimer,
    TraceRecorder,
    get_tracer,
    install,
)
from kme_tpu.telemetry.httpd import start_metrics_server  # noqa: F401
from kme_tpu.telemetry.journal import (  # noqa: F401
    Journal,
    batch_events,
    canonical_events,
    canonical_lines,
    iter_events,
    measured_overlap_s,
    oracle_events,
    read_events,
)
from kme_tpu.telemetry.audit import (  # noqa: F401
    InvariantAuditor,
    Violation,
    replay_repro,
)
from kme_tpu.telemetry.slo import SLO  # noqa: F401
from kme_tpu.telemetry.tsdb import (  # noqa: F401
    TSDB,
    flatten_snapshot,
    read_samples,
    window_summary,
)
from kme_tpu.telemetry.events import (  # noqa: F401
    EventLog,
    merge_events,
    merge_logs,
    open_log,
    read_log,
    timeline_digest,
)
from kme_tpu.telemetry.profiler import (  # noqa: F401
    StageProfiler,
    TriggerCapture,
    device_plane,
    read_transfer_artifact,
    write_transfer_artifact,
)
