"""Cluster-wide distributed tracing: deterministic per-order waterfalls
across front, groups, transfer legs and merge (kme-trace --cluster), and
the aggregated cluster SLO plane (kme-agg).

Dapper's model (Sigelman et al. 2010 — PAPERS.md) is a tree of spans
joined by a trace id that is MINTED at the edge and CARRIED through
every hop. This repo grafts that model onto its replay-exact identity
discipline instead of carrying ids end to end:

- **Identity, not clocks.** A trace id is a pure splitmix64 mix of the
  order's durable identity — (input-stream offset, aid, oid) — never a
  wall clock or RNG draw (`kme-lint` KME-D001/D002 enforce this scope).
  A crash-replay that regenerates the same input prefix regenerates the
  SAME trace ids, so a waterfall stitched post-mortem is identical
  before and after a failover.

- **Two id spaces, one join.** The front's global id is
  `trace_id(off, aid, oid)` over the GLOBAL input offset. A serving
  group only knows its LOCAL broker offset, so its spans carry
  `local_tid(group, local_off)`. The stitcher re-runs the deterministic
  `GroupRouter` over the front input (route_map) to rebuild the global
  off -> (group, local index) map — including the injected transfer
  legs, whose emission order fixes their kinds (home debit =
  xfer_reserve, symbol credit = xfer_settle) — and joins the two spaces
  offline. Parent/child linkage is therefore a STITCH-time product;
  services never need the global id (their spans set ptid=0).

- **Carried ids are advisory.** The 80-byte FLAG_TID wire frame, the
  TCP "tid" produce key and Record.tid let a CLIENT thread its own
  correlation id through the stack (kme-loadgen stamps
  `client_trace_id`). Those ids are transport metadata: they do not
  survive a broker-log reload and are never used as the stitch key.

Span sources, per group directory (chaos/supervise layout
`<state-root>/group{k}/state/`):

- "span" journal events (kme-serve --trace-spans): ingress/plan/device/
  produce with real stage bounds;
- "lat" journal events as a fallback — the same stage durations, spans
  synthesized here;
- front_accept/route (+ merge) spans are synthesized by the stitcher
  when no front trace journal recorded them: the split and the merge
  are deterministic functions, not runtime hops, so their spans mark
  positions, zero-width (`synthetic: true`).

Failover replay segments are deduplicated by the durable key
(group, local_off, kind) — first occurrence wins — mirroring how the
broker dedups (epoch, out_seq). A promoted standby CONTINUES an order's
spans (a gap during the outage), it never forks a second waterfall.

The SLO plane (aggregate/kme-agg) merges per-group /metrics.json
snapshots: latency histograms are summed at the raw LAT_BOUNDS bucket
level, so cluster quantiles are EXACT merged quantiles, never a
quantile-of-quantiles estimate. p99 exemplars (registry exemplars, the
service's slowest recent orders) resolve back to waterfalls via
`kme-trace --order AID:OID`.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from kme_tpu import opcodes as op
from kme_tpu.bridge.front import (GroupRouter, _mix64, is_internal_line)
from kme_tpu.telemetry.journal import SPAN_KINDS, read_events
from kme_tpu.telemetry.registry import LAT_N_BUCKETS, LatencyHistogram
from kme_tpu.wire import parse_order

# distinct salts keep the three id spaces (global trace, group-local
# span join key, client-carried correlation) from colliding
TRACE_SALT = 0x44545243      # "DTRC"
LOCAL_SALT = 0x4C4F434C      # "LOCL"
CLIENT_SALT = 0x434C4E54     # "CLNT"
_MASK63 = (1 << 63) - 1      # ids stay positive int64 (journal packs <q)


def _tid_mix(salt: int, a: int, b: int, c: int) -> int:
    """Three-word splitmix64 combine, folded to a positive nonzero
    int64 (0 is the wire's "no trace id"). Pure: no clock, no RNG —
    the whole point is that a crash-replay re-derives the same id."""
    z = _mix64(salt ^ _mix64(a & ((1 << 64) - 1)))
    z = _mix64(z ^ _mix64(b & ((1 << 64) - 1)))
    z = _mix64(z ^ _mix64(c & ((1 << 64) - 1)))
    z &= _MASK63
    return z or 1


def trace_id(off: int, aid: int, oid: int) -> int:
    """The order's GLOBAL trace id: minted from its durable identity in
    the front's input stream (global offset + aid + oid)."""
    return _tid_mix(TRACE_SALT, off, aid, oid)


def local_tid(group: int, off: int) -> int:
    """A serving group's span join key: (group ordinal, group-local
    broker offset). This is what `--trace-spans` journals; the stitcher
    maps it back to the global trace via route_map."""
    return _tid_mix(LOCAL_SALT, group, off, 0)


def child_tid(parent: int, leg: int) -> int:
    """Deterministic child id for the leg-th front-injected line of a
    traced order (transfer legs, balance broadcasts)."""
    return _tid_mix(TRACE_SALT, parent, leg, 1)


def client_trace_id(seq: int, aid: int, oid: int) -> int:
    """The ADVISORY id a client stamps into the 80-byte FLAG_TID frame
    (or the TCP "tid" produce key): minted from the client's own stable
    identity (its out_seq counter + the order fields), so reconnects
    and retries re-stamp the same id."""
    return _tid_mix(CLIENT_SALT, seq, aid, oid)


def _mix64_np(z):
    """Vectorized splitmix64 finalizer over a numpy uint64 array —
    bit-identical to front._mix64 (uint64 arithmetic wraps mod 2^64
    exactly like the scalar's explicit masking)."""
    import numpy as np

    z = z + np.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def client_trace_ids(seq0: int, aids, oids) -> List[int]:
    """client_trace_id over a whole batch (seq0, seq0+1, ...),
    vectorized: the binary send path mints thousands of ids per batch
    and the scalar's six Python splitmix rounds per record would
    dominate the ingress cost. Bit-identical to the scalar."""
    import numpy as np

    n = len(aids)
    seqs = np.arange(seq0, seq0 + n, dtype=np.int64).astype(np.uint64)
    a = np.asarray(aids, dtype=np.int64).astype(np.uint64)
    b = np.asarray(oids, dtype=np.int64).astype(np.uint64)
    z = _mix64_np(np.uint64(CLIENT_SALT) ^ _mix64_np(seqs))
    z = _mix64_np(z ^ _mix64_np(a))
    z = _mix64_np(z ^ _mix64_np(b))
    out = (z & np.uint64(_MASK63)).astype(np.int64)
    out[out == 0] = 1
    return out.tolist()


# ---------------------------------------------------------------------------
# route map: global input -> (group, local index) + injected legs


def route_map(lines: Sequence[str], ngroups: int,
              transfers: bool = True, prefund: int = 8
              ) -> Tuple[List[dict], GroupRouter]:
    """Re-run the deterministic front split and record, for every input
    line, WHERE its rows landed: the primary row's (group, local index)
    and every injected leg's (group, local index, kind, child tid).

    Leg kinds follow route_line's emission order, which is part of the
    durable stream contract: a cross-shard BUY/SELL injects the home
    group's debit leg first (xfer_reserve) then the symbol group's
    credit leg (xfer_settle); CREATE_BALANCE broadcasts are "route"
    legs. Returns (entries, router) — entries[k] may be None for a
    malformed line (dropped before routing, like the service does)."""
    router = GroupRouter(ngroups, transfers=transfers, prefund=prefund)
    li = [0] * max(1, ngroups)
    entries: List[Optional[dict]] = []
    for off, line in enumerate(lines):
        try:
            routed = router.route_line(line)
            m = parse_order(line)
        except ValueError:
            entries.append(None)
            continue
        tid = trace_id(off, m.aid, m.oid)
        legs: List[dict] = []
        prim: Optional[Tuple[int, int]] = None
        for g, ln in routed:
            idx = li[g]
            li[g] += 1
            if is_internal_line(ln):
                if m.action in (op.BUY, op.SELL):
                    kind = ("xfer_reserve" if not legs
                            else "xfer_settle")
                else:
                    kind = "route"
                legs.append({"g": g, "li": idx, "kind": kind,
                             "tid": child_tid(tid, len(legs) + 1)})
            else:
                prim = (g, idx)
        assert prim is not None, "input line carries the internal marker"
        entries.append({"off": off, "tid": tid, "aid": m.aid,
                        "oid": m.oid, "act": m.action,
                        "g": prim[0], "li": prim[1], "legs": legs})
    return entries, router


# ---------------------------------------------------------------------------
# span collection (journal readers + lat fallback + replay dedup)

_STAGES = ("ingress", "plan", "device", "produce")


def _spans_from_lat(ev: dict, group: int) -> List[dict]:
    """Synthesize the four service-stage spans from one "lat" event:
    same stage numbers, absolute bounds anchored at the event's commit
    stamp (ts == produce-visible for the batch)."""
    off = ev.get("off", -1)
    e2e = int(ev.get("e2e_us", 0))
    t_arr = int(ev.get("ts", 0)) - e2e
    tid = local_tid(group, off)
    bounds = []
    t = t_arr
    for k, dur in (("ingress", ev.get("in_us", 0)),
                   ("plan", ev.get("plan_us", 0)),
                   ("device", ev.get("dev_us", 0)),
                   ("produce", ev.get("prod_us", 0))):
        d = max(0, int(dur))
        bounds.append({"e": "span", "kind": k, "g": group, "off": off,
                       "oid": ev.get("oid", 0), "tid": tid, "ptid": 0,
                       "t0": t, "t1": t + d, "aid": 0, "li": -1,
                       "seq": ev.get("seq", 0)})
        t += d
    return bounds


def collect_group_spans(events: Iterable[dict], group: int
                        ) -> Dict[Tuple[int, str], dict]:
    """One group's journal events -> {(local_off, kind): span}, replay
    segments deduplicated (first occurrence by journal order wins — the
    same convention the broker applies to (epoch, out_seq) stamps).
    Prefers real "span" events; synthesizes from "lat" only for
    (off, stage) pairs no span event covered."""
    spans: Dict[Tuple[int, str], dict] = {}
    lat_fallback: Dict[Tuple[int, str], dict] = {}
    for ev in events:
        e = ev.get("e")
        if e == "span":
            key = (ev.get("off", -1), ev.get("kind"))
            if key not in spans:
                spans[key] = dict(ev, g=group)
        elif e == "lat":
            for sp in _spans_from_lat(ev, group):
                key = (sp["off"], sp["kind"])
                if key not in lat_fallback:
                    lat_fallback[key] = sp
    for key, sp in lat_fallback.items():
        if key not in spans:
            spans[key] = sp
    return spans


def _find_journal(gdir: str) -> Optional[str]:
    for rel in ("state/journal.bin", "state/journal.jsonl",
                "journal.bin", "journal.jsonl"):
        p = os.path.join(gdir, rel)
        if os.path.exists(p):
            return p
    return None


def discover_groups(state_root: str) -> List[Tuple[int, str]]:
    """[(k, groupdir)] for every `group{k}` child of a chaos/cluster run
    directory, ordered by k."""
    out = []
    try:
        names = os.listdir(state_root)
    except OSError:
        return []
    for name in names:
        if name.startswith("group") and name[5:].isdigit():
            p = os.path.join(state_root, name)
            if os.path.isdir(p):
                out.append((int(name[5:]), p))
    return sorted(out)


# ---------------------------------------------------------------------------
# stitching


def stitch(lines: Sequence[str],
           group_events: Dict[int, List[dict]],
           ngroups: int, transfers: bool = True, prefund: int = 8,
           front_events: Optional[List[dict]] = None) -> dict:
    """Merge per-group journals into per-order cluster waterfalls.

    Returns {"orders": [...], "admitted": n, "stitched": n,
    "groups": n, "counters": router counters}. An order is ADMITTED
    when its primary group journaled any span for its row, and STITCHED
    when the full service pipeline (ingress..produce) plus every
    injected transfer leg resolved. orders[k]["spans"] is waterfall
    order; synthesized positional spans (front_accept/route/merge — the
    split and merge are deterministic functions, not runtime hops)
    carry `synthetic: True` and zero width."""
    entries, router = route_map(lines, ngroups, transfers=transfers,
                                prefund=prefund)
    by_group: Dict[int, Dict[Tuple[int, str], dict]] = {
        g: collect_group_spans(evs, g)
        for g, evs in group_events.items()}
    front_idx: Dict[Tuple[int, str], dict] = {}
    for ev in front_events or ():
        if ev.get("e") == "span":
            key = (ev.get("off", -1), ev.get("kind"))
            front_idx.setdefault(key, ev)

    orders: List[dict] = []
    admitted = stitched = 0
    for ent in entries:
        if ent is None:
            continue
        g, li = ent["g"], ent["li"]
        gspans = by_group.get(g, {})
        stages = {k: gspans.get((li, k)) for k in _STAGES}
        if not any(stages.values()):
            continue        # never reached its group (not admitted)
        admitted += 1
        spans: List[dict] = []

        def _positional(kind, t, tid, ptid, grp):
            real = front_idx.get((ent["off"], kind))
            if real is not None:
                return dict(real, tid=tid, ptid=ptid, g=grp)
            return {"kind": kind, "g": grp, "off": ent["off"],
                    "oid": ent["oid"], "tid": tid, "ptid": ptid,
                    "t0": t, "t1": t, "synthetic": True}

        legs_ok = True
        for leg in ent["legs"]:
            lspans = by_group.get(leg["g"], {})
            lst = [lspans.get((leg["li"], k)) for k in _STAGES]
            present = [s for s in lst if s]
            if not present:
                legs_ok = False
                continue
            spans.append({"kind": leg["kind"], "g": leg["g"],
                          "off": ent["off"], "oid": ent["oid"],
                          "tid": leg["tid"], "ptid": ent["tid"],
                          "li": leg["li"],
                          "t0": min(s["t0"] for s in present),
                          "t1": max(s["t1"] for s in present)})
        complete = all(stages.values())
        for k in _STAGES:
            s = stages[k]
            if s is None:
                continue
            spans.append({"kind": k, "g": g, "off": ent["off"],
                          "oid": ent["oid"], "tid": ent["tid"],
                          "ptid": ent["tid"], "li": li,
                          "t0": s["t0"], "t1": s["t1"]})
        # order extent covers the legs too: independent groups run on
        # their own wall clocks, so a leg can land outside the primary
        # pipeline's window — the renderer must scale to the full span
        t_in = min(sp["t0"] for sp in spans)
        t_out = max(sp["t1"] for sp in spans)
        spans.insert(0, _positional("route", t_in, ent["tid"],
                                    ent["tid"], -1))
        spans.insert(0, _positional("front_accept", t_in, ent["tid"],
                                    0, -1))
        spans.append(_positional("merge", t_out, ent["tid"],
                                 ent["tid"], -1))
        if complete and legs_ok:
            stitched += 1
        # the group-LOCAL join keys (what exemplars/journals carry —
        # the service never sees the global front offset)
        ltids = [local_tid(g, li)] + [local_tid(lg["g"], lg["li"])
                                      for lg in ent["legs"]]
        orders.append({"off": ent["off"], "tid": ent["tid"],
                       "aid": ent["aid"], "oid": ent["oid"],
                       "g": g, "li": li, "legs": ent["legs"],
                       "ltids": ltids,
                       "complete": complete and legs_ok,
                       "t0": t_in, "t1": t_out, "spans": spans})
    return {"groups": ngroups, "admitted": admitted,
            "stitched": stitched, "orders": orders,
            "counters": dict(router.counters)}


def stitch_state_root(state_root: str, input_path: Optional[str] = None,
                      transfers: bool = True, prefund: int = 8) -> dict:
    """Stitch a chaos/cluster run directory: `group{k}/` children hold
    each group's journal (chaos layout `group{k}/state/journal.bin`);
    the front's input stream is `front.in` at the root (or
    `input_path`). Groups whose journal is missing contribute no spans
    — their orders simply count as not admitted."""
    groups = discover_groups(state_root)
    if not groups:
        raise FileNotFoundError(
            f"no group*/ directories under {state_root}")
    if input_path is None:
        input_path = os.path.join(state_root, "front.in")
    with open(input_path) as f:
        lines = [ln.strip() for ln in f if ln.strip()]
    group_events: Dict[int, List[dict]] = {}
    for k, gdir in groups:
        jp = _find_journal(gdir)
        if jp is not None:
            group_events[k] = [ev for ev in read_events(jp)
                               if ev.get("e") in ("span", "lat")]
    ngroups = max(k for k, _ in groups) + 1
    front_jp = os.path.join(state_root, "front.trace")
    front_events = (list(read_events(front_jp))
                    if os.path.exists(front_jp) else None)
    doc = stitch(lines, group_events, ngroups, transfers=transfers,
                 prefund=prefund, front_events=front_events)
    doc["state_root"] = state_root
    return doc


def find_order(doc: dict, spec: str) -> Optional[dict]:
    """Resolve `--order AID:OID` (or a bare trace id) against a
    stitched doc."""
    if ":" in spec:
        aid_s, _, oid_s = spec.partition(":")
        aid, oid = int(aid_s), int(oid_s)
        for o in doc["orders"]:
            if o["aid"] == aid and o["oid"] == oid:
                return o
        return None
    tid = int(spec, 0)
    for o in doc["orders"]:
        if o["tid"] == tid or o["off"] == tid:
            return o
    # exemplars carry the group-LOCAL span join key (the service never
    # sees the global front offset) — resolve those too
    for o in doc["orders"]:
        if tid in o.get("ltids", ()):
            return o
    return None


# ---------------------------------------------------------------------------
# rendering: per-order text waterfall + Chrome trace


def waterfall_text(order: dict, width: int = 48) -> str:
    """One order's cluster waterfall as aligned text: span rows with
    group, absolute offsets and a proportional bar."""
    t0, t1 = order["t0"], max(order["t1"], order["t0"] + 1)
    span_total = t1 - t0
    lines = [f"order aid={order['aid']} oid={order['oid']} "
             f"off={order['off']} tid=0x{order['tid']:016x} "
             f"group=g{order['g']} "
             f"{'complete' if order['complete'] else 'PARTIAL'} "
             f"e2e={span_total}us"]
    for sp in order["spans"]:
        rel0 = max(0, sp["t0"] - t0)
        dur = max(0, sp["t1"] - sp["t0"])
        a = min(width - 1, int(width * rel0 / span_total))
        b = min(width, max(a + 1, int(width * (rel0 + dur)
                                      / span_total)))
        bar = " " * a + "#" * (b - a) + " " * (width - b)
        where = f"g{sp['g']}" if sp.get("g", -1) >= 0 else "--"
        tag = " (syn)" if sp.get("synthetic") else ""
        lines.append(f"  {sp['kind']:>12} {where:>3} |{bar}| "
                     f"+{rel0:>8}us {dur:>8}us{tag}")
    return "\n".join(lines)


def chrome_trace_doc(doc: dict) -> dict:
    """Chrome trace-event JSON ({"traceEvents": [...]}, chrome://tracing
    / Perfetto): one process row per group (front/merge on pid 0), one
    "X" slice per span, flow arrows (s/f, bp:"e") threading each
    order's spans across groups so the cross-shard hops draw as
    arrows."""
    evs: List[dict] = []
    meta_done = set()

    def _meta(pid, name):
        if pid not in meta_done:
            meta_done.add(pid)
            evs.append({"ph": "M", "pid": pid, "tid": 0,
                        "name": "process_name",
                        "args": {"name": name}})

    _meta(0, "front/merge")
    for o in doc["orders"]:
        flow_id = f"0x{o['tid']:x}"
        prev_pid = None
        for sp in o["spans"]:
            g = sp.get("g", -1)
            pid = 0 if g < 0 else g + 1
            if pid:
                _meta(pid, f"group{g}")
            ts = sp["t0"]
            dur = max(1, sp["t1"] - sp["t0"])
            evs.append({"ph": "X", "pid": pid, "tid": o["off"],
                        "ts": ts, "dur": dur, "name": sp["kind"],
                        "cat": "kme",
                        "args": {"tid": f"0x{sp['tid']:x}",
                                 "ptid": f"0x{sp.get('ptid', 0):x}",
                                 "oid": o["oid"], "aid": o["aid"],
                                 "off": o["off"]}})
            if prev_pid is not None and pid != prev_pid:
                evs.append({"ph": "s", "pid": prev_pid,
                            "tid": o["off"], "ts": ts, "cat": "flow",
                            "name": "hop", "id": flow_id})
                evs.append({"ph": "f", "pid": pid, "tid": o["off"],
                            "ts": ts, "cat": "flow", "name": "hop",
                            "id": flow_id, "bp": "e"})
            prev_pid = pid
    return {"traceEvents": evs, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# cluster aggregation (kme-agg): the SLO plane


def merge_latencies(snaps: Sequence[Tuple[str, dict]]) -> dict:
    """Sum per-source latency histograms at the raw bucket level and
    recompute quantiles from the MERGED counts — exact, because every
    LatencyHistogram shares the fixed LAT_BOUNDS layout (the snapshot's
    "buckets" key, registry.py)."""
    merged: Dict[str, List[int]] = {}
    for _name, snap in snaps:
        for lname, lat in (snap.get("latencies") or {}).items():
            counts = lat.get("buckets")
            if not counts or len(counts) != LAT_N_BUCKETS:
                continue
            acc = merged.setdefault(lname, [0] * LAT_N_BUCKETS)
            for i, c in enumerate(counts):
                acc[i] += int(c)
    out = {}
    for lname, counts in merged.items():
        total = sum(counts)
        out[lname] = {
            "count": total,
            "p50_ms": round(LatencyHistogram._quantile_from(
                counts, total, 0.5) * 1e3, 3),
            "p90_ms": round(LatencyHistogram._quantile_from(
                counts, total, 0.9) * 1e3, 3),
            "p99_ms": round(LatencyHistogram._quantile_from(
                counts, total, 0.99) * 1e3, 3),
            "p999_ms": round(LatencyHistogram._quantile_from(
                counts, total, 0.999) * 1e3, 3),
            "buckets": counts,
        }
    return out


def _burn_rate(counts: Sequence[int], threshold_s: float,
               budget: float) -> Optional[float]:
    """SLO burn rate from merged buckets: (bad fraction) / (error
    budget). >1.0 burns the budget faster than the SLO allows. Bucket-
    conservative like LatencyHistogram.count_over."""
    import bisect

    from kme_tpu.telemetry.registry import LAT_BOUNDS

    total = sum(counts)
    if total <= 0 or budget <= 0:
        return None
    i = bisect.bisect_left(LAT_BOUNDS, threshold_s)
    bad = sum(counts[i + 1:])
    return round((bad / total) / budget, 4)


def aggregate(snaps: Sequence[Tuple[str, dict]],
              slo_ms: Optional[float] = None,
              slo_target: float = 0.999,
              stale: Optional[dict] = None) -> dict:
    """The cluster SLO plane from N scraped /metrics.json snapshots
    (front + every group). Returns:

    - "e2e": merged cluster end-to-end latency (lat_e2e — front
      admission stamp to produce-visible; the merge itself is a
      deterministic sort, so produce-visible IS merge-visible),
      plus every other merged latency family;
    - "slo": global burn rate against (slo_ms, slo_target) when given;
    - "per_group": one row per source — e2e p99, input lag, overload
      state, shed count, imbalance gauges — degraded rows ("up": False)
      for sources that could not be scraped; rows named in `stale`
      (source -> {"age_s", "intervals", "sample_seq"}) additionally
      carry "stale": True — scraped fine, but the heartbeat's
      sample_seq/mtime has not advanced within 3 write intervals, so
      the numbers describe a frozen writer, not the present;
    - "exemplars": the slowest-order exemplars across all sources,
      worst first (each resolves to a waterfall via
      `kme-trace --order AID:OID`)."""
    lat = merge_latencies([(n, s) for n, s in snaps if s])
    doc: dict = {"sources": len(snaps), "latencies": lat,
                 "e2e": lat.get("lat_e2e")}
    if slo_ms is not None and "lat_e2e" in lat:
        doc["slo"] = {
            "threshold_ms": slo_ms, "target": slo_target,
            "burn_rate": _burn_rate(lat["lat_e2e"]["buckets"],
                                    slo_ms * 1e-3, 1.0 - slo_target)}
    rows = []
    exemplars: List[dict] = []
    for name, snap in snaps:
        if not snap:
            rows.append({"source": name, "up": False})
            continue
        g = snap.get("gauges") or {}
        c = snap.get("counters") or {}
        lats = snap.get("latencies") or {}
        row = {"source": name, "up": True,
               "e2e_p99_ms": (lats.get("lat_e2e") or {}).get("p99_ms"),
               "orders": (lats.get("lat_e2e") or {}).get("count", 0),
               "overload_state": g.get("overload_state"),
               "shed": g.get("overload_rejects", 0)}
        if stale and name in stale:
            row["stale"] = True
            row["hb_age_s"] = stale[name].get("age_s")
            row["hb_intervals"] = stale[name].get("intervals")
            row["hb_sample_seq"] = stale[name].get("sample_seq")
            if stale[name].get("events_frozen"):
                # the control-plane event recorder wedged while the
                # heartbeat kept advancing (events_lag_bytes > 0):
                # the timeline describes the past, flag it loudly
                row["events_frozen"] = True
                row["events_lag_bytes"] = stale[name].get(
                    "events_lag_bytes")
        for k, v in g.items():
            if k.startswith("group") and (k.endswith("_lag")
                                          or k.endswith("_imbalance")):
                row[k] = v
        for k in ("cross_shard_transfers_total",
                  "transfer_shortfall_total"):
            if k in c:
                row[k] = c[k]
        if "feed_subscribers" in g:
            # feed-tier source (kme-feed heartbeat): fan-out health
            # rides the same per-source row; extras render generically
            delivered = c.get("feed_delivered_total", 0)
            dropped = c.get("feed_conflated_frames_total", 0)
            offered = delivered + dropped
            row["feed_subs"] = g["feed_subscribers"]
            row["feed_delivered"] = delivered
            row["feed_conflation"] = (round(dropped / offered, 4)
                                      if offered else 0.0)
            fl = lats.get("feed_lag") or {}
            if fl:
                row["feed_lag_p50_ms"] = fl.get("p50_ms")
                row["feed_lag_p99_ms"] = fl.get("p99_ms")
        rows.append(row)
        for ex in snap.get("exemplars") or ():
            exemplars.append(dict(ex, source=name))
    exemplars.sort(key=lambda e: -int(e.get("e2e_us", 0)))
    doc["per_group"] = rows
    doc["exemplars"] = exemplars[:16]
    return doc


def load_snapshots(paths: Sequence[str]) -> List[Tuple[str, dict]]:
    """(name, snapshot) per path; unreadable/undecodable sources come
    back as (name, None) so the aggregate renders a degraded row
    instead of dying."""
    out: List[Tuple[str, dict]] = []
    for p in paths:
        try:
            with open(p) as f:
                out.append((p, json.load(f)))
        except (OSError, ValueError):
            out.append((p, None))
    return out


def render_agg(doc: dict) -> str:
    """kme-agg's human view: cluster quantiles, SLO burn, the per-group
    table, and resolvable exemplars."""
    lines = [f"cluster: {doc['sources']} sources"]
    e2e = doc.get("e2e")
    if e2e:
        lines.append(
            f"  e2e (front admission -> merge visible), "
            f"{e2e['count']} orders: p50={e2e['p50_ms']}ms "
            f"p90={e2e['p90_ms']}ms p99={e2e['p99_ms']}ms "
            f"p999={e2e['p999_ms']}ms")
    slo = doc.get("slo")
    if slo:
        br = slo.get("burn_rate")
        lines.append(
            f"  SLO {slo['threshold_ms']}ms @ {slo['target']:.3%}: "
            f"burn rate {br if br is not None else 'n/a'}"
            f"{'  ** BURNING **' if br is not None and br > 1 else ''}")
    lines.append("  per-group:")
    for row in doc.get("per_group", ()):
        if not row.get("up"):
            lines.append(f"    {row['source']}: DEGRADED (unreachable)")
            continue
        extras = " ".join(
            f"{k}={row[k]}" for k in sorted(row)
            if k not in ("source", "up", "e2e_p99_ms", "orders",
                         "stale", "hb_age_s", "hb_intervals",
                         "hb_sample_seq", "events_frozen",
                         "events_lag_bytes"))
        mark = ""
        if row.get("stale"):
            bits = []
            if row.get("events_frozen"):
                bits.append(f"event log frozen "
                            f"({row.get('events_lag_bytes', 0)}B "
                            f"unflushed)")
            if row.get("hb_age_s") is not None:
                bits.append(f"heartbeat {row['hb_age_s']:.1f}s old "
                            f"({row.get('hb_intervals', 0):.1f} "
                            f"intervals)")
            if row.get("hb_sample_seq") is not None:
                bits.append(f"sample_seq frozen at "
                            f"{row['hb_sample_seq']}")
            mark = f" ** STALE ({', '.join(bits) or 'frozen'}) **"
        lines.append(f"    {row['source']}: orders={row['orders']} "
                     f"e2e_p99={row['e2e_p99_ms']}ms {extras}{mark}")
    ex = doc.get("exemplars") or ()
    if ex:
        lines.append("  slowest orders (kme-trace --order AID:OID):")
        for e in ex[:8]:
            lines.append(
                f"    {e.get('e2e_us', 0):>9}us aid={e.get('aid')} "
                f"oid={e.get('oid')} g={e.get('g')} off={e.get('off')} "
                f"tid=0x{int(e.get('tid', 0)):x} [{e.get('source')}]")
    return "\n".join(lines)


__all__ = [
    "SPAN_KINDS", "trace_id", "local_tid", "child_tid",
    "client_trace_id", "client_trace_ids", "route_map", "collect_group_spans", "stitch",
    "stitch_state_root", "discover_groups", "find_order",
    "waterfall_text", "chrome_trace_doc", "merge_latencies",
    "aggregate", "load_snapshots", "render_agg",
]
