"""kme-top: live operations dashboard for a serving pair.

One terminal view over the surfaces the serving stack already exposes —
nothing here adds instrumentation, it only reads:

- the LEADER's /metrics.json (kme-serve --metrics-port) or heartbeat
  file (--health-file; the heartbeat embeds the same registry snapshot)
- the STANDBY's /metrics.json (kme-standby --metrics-port) or its
  heartbeat file
- the SUPERVISOR's state mirror (<checkpoint-dir>/supervisor.json)

Shown: input throughput (rate computed between refreshes), per-stage
latency quantiles (ingress/plan/device/produce/e2e/consume — the
attribution pipeline in bridge/service.py), leader epoch and offset,
SLO state, per-shard occupancy/imbalance/migrations when the leader is
a sharded mesh session (device_shard{N} + shard_imbalance,
parallel/seqmesh.py), replica application lag, and the supervisor's
restart history. `--once` prints a single plain-text frame (scriptable; the
smoke test uses it); the default is a curses loop that redraws every
--interval seconds and quits on `q`.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Optional

STAGES = ("ingress", "plan", "device", "produce", "e2e", "consume")


# -- collection --------------------------------------------------------


def scrape(source: Optional[str], timeout: float = 1.0) -> dict:
    """Read one node's state from a URL or a heartbeat file.

    Returns {"source", "ok", "error"?, "hb"?, "metrics"} — `hb` is the
    heartbeat dict when the source was a heartbeat file (or a metrics
    surface that happens to embed one); `metrics` is always the
    registry-snapshot shape ({counters, gauges, histograms,
    latencies}), possibly empty."""
    if not source:
        return {"source": None, "ok": False, "metrics": {}}
    out: dict = {"source": source, "ok": False, "metrics": {}}
    try:
        if source.startswith(("http://", "https://")):
            from urllib.request import urlopen

            url = source
            if not url.rstrip("/").endswith("metrics.json"):
                url = url.rstrip("/") + "/metrics.json"
            with urlopen(url, timeout=timeout) as resp:
                doc = json.loads(resp.read().decode())
        else:
            with open(source) as f:
                doc = json.load(f)
    except Exception as e:
        out["error"] = str(e)
        return out
    out["ok"] = True
    if "counters" in doc or "latencies" in doc:
        out["metrics"] = doc          # bare registry snapshot
    else:
        out["hb"] = doc               # heartbeat with embedded metrics
        out["metrics"] = doc.get("metrics") or {}
    return out


def read_supervisor(path: Optional[str]) -> Optional[dict]:
    if not path:
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def discover_endpoints(state_root: str) -> dict:
    """Endpoint discovery for a state directory — the ONE place the
    conventional file names live (kme-agg and the --cluster view share
    it; the single-pair names used to be hardcoded in main()).

    A plain checkpoint dir yields the leader/standby/supervisor trio;
    a multi-leader run dir (chaos layout: `group{k}/state/...`) also
    yields one row per group. Paths are returned whether or not the
    files exist yet — scrape() degrades unreachable sources instead of
    dying."""
    import os

    eps: dict = {
        "leader": os.path.join(state_root, "serve.health"),
        "standby": os.path.join(state_root, "standby.health"),
        "supervisor": os.path.join(state_root, "supervisor.json"),
        "feed": os.path.join(state_root, "feed.health"),
        "groups": [],
    }
    try:
        names = sorted(os.listdir(state_root))
    except OSError:
        names = []
    for name in names:
        if name.startswith("group") and name[5:].isdigit():
            st = os.path.join(state_root, name, "state")
            eps["groups"].append({
                "k": int(name[5:]),
                "health": os.path.join(st, "serve.health"),
                "supervisor": os.path.join(st, "supervisor.json"),
                "feed": os.path.join(st, "feed.health"),
            })
    return eps


def collect(leader: Optional[str], standby: Optional[str],
            supervisor: Optional[str], now: Optional[float] = None,
            feed: Optional[str] = None) -> dict:
    return {"t": time.monotonic() if now is None else now,
            "leader": scrape(leader), "standby": scrape(standby),
            "supervisor": read_supervisor(supervisor),
            "feed": scrape(feed)}


def collect_cluster(groups, now: Optional[float] = None) -> dict:
    """One scrape sweep over a discovered group list — every row goes
    through the same scrape() path as the single-pair view."""
    rows = []
    for g in groups:
        rows.append({"k": g["k"], "node": scrape(g.get("health")),
                     "supervisor": read_supervisor(
                         g.get("supervisor")),
                     "feed": scrape(g.get("feed"))})
    return {"t": time.monotonic() if now is None else now,
            "rows": rows}


# -- derivation --------------------------------------------------------


def _counter(node: dict, name: str):
    return node.get("metrics", {}).get("counters", {}).get(name)


def _gauge(node: dict, name: str):
    return node.get("metrics", {}).get("gauges", {}).get(name)


def build_view(cur: dict, prev: Optional[dict] = None) -> dict:
    """Fold two collections into the render model: point-in-time state
    plus rates derived from the deltas between them."""
    view = dict(cur)
    rate = None
    if prev is not None:
        dt = cur["t"] - prev["t"]
        a = _counter(prev["leader"], "service_records")
        b = _counter(cur["leader"], "service_records")
        if dt > 0 and a is not None and b is not None and b >= a:
            rate = (b - a) / dt
    view["records_per_s"] = rate
    lead = cur["leader"]
    stby = cur["standby"]
    lag = _gauge(stby, "replica_lag_records")
    if lag is None:
        hb = stby.get("hb") or {}
        applied, lead_off = hb.get("applied"), hb.get("leader_offset")
        if applied is not None and lead_off is not None:
            lag = max(0, lead_off - applied)
    view["replica_lag"] = lag
    hb = lead.get("hb") or {}
    view["degraded"] = hb.get("degraded")
    view["epoch"] = hb.get("epoch", _gauge(lead, "leader_epoch"))
    view["offset"] = hb.get("offset", _gauge(lead, "service_offset"))
    return view


# -- rendering ---------------------------------------------------------

_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(vals, width: int = 24) -> str:
    """Render a value series as a unicode sparkline, newest right.
    Longer series keep the newest `width` points; constant (or empty)
    series render flat."""
    vals = [float(v) for v in vals][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_BLOCKS[0] * len(vals)
    n = len(_SPARK_BLOCKS) - 1
    return "".join(_SPARK_BLOCKS[round((v - lo) / (hi - lo) * n)]
                   for v in vals)


# curated kme-top history columns: what an operator wants at a glance.
# Monotonic series (counters and histogram .count sub-series) plot
# their per-sample deltas — a rate shape — instead of an ever-rising
# ramp that always renders as the same diagonal.
HISTORY_NAMES = ("service_records", "lat_e2e.p99_ms",
                 "lat_device.p99_ms", "lat_produce.p99_ms",
                 "prof_stage_frac_plan", "prof_stage_frac_dispatch",
                 "prof_stage_frac_produce", "pipeline_depth")


def history_lines(store: str, source: str = "serve",
                  names=HISTORY_NAMES, width: int = 24,
                  indent: str = "  ") -> list:
    """Sparkline rows from the on-disk TSDB (kme-serve --tsdb) — the
    dashboard's look-back columns. Series absent from the store are
    skipped; an unreadable store degrades to a note, never a crash."""
    from kme_tpu.telemetry import tsdb as _tsdb

    try:
        series = _tsdb.query(store, names, source=source)
    except (OSError, ValueError) as e:
        return [f"{indent}history unavailable: {e}"]
    lines = []
    for name in names:
        pts = series.get(name) or []
        if len(pts) < 2:
            continue
        vals = [v for _ts, v in pts]
        if _tsdb._is_monotonic_name(name):
            vals = [b - a for a, b in zip(vals, vals[1:])]
        lines.append(f"{indent}{name:<26s} {sparkline(vals, width)} "
                     f"{_fmt(vals[-1], 3)}")
    if lines:
        lines.insert(0, f"{indent[:-2]}history  (oldest -> newest, "
                        f"source={source})")
    return lines


def _fmt(v, nd=1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.{nd}f}"
    return f"{v:,}"


def feed_lines(node: dict, indent: str = "") -> list:
    """The feed-tier rows (kme-feed fan-out metrics) for one scraped
    node — shared by the single-pair and --cluster frames. Conflation
    rate = frames dropped into conflated-TOB mode over frames offered
    to subscriber queues (delivered + dropped)."""
    delivered = _counter(node, "feed_delivered_total") or 0
    dropped = _counter(node, "feed_conflated_frames_total") or 0
    offered = delivered + dropped
    rate = (dropped / offered) if offered else 0.0
    lat = (node.get("metrics", {}).get("latencies", {})
           .get("feed_lag") or {})
    lines = [
        f"{indent}feed     subs="
        f"{_fmt(_gauge(node, 'feed_subscribers'), 0)} "
        f"group={_fmt(_gauge(node, 'feed_group'), 0)} "
        f"offset={_fmt(_gauge(node, 'feed_offset'), 0)} "
        f"frames={_fmt(_counter(node, 'feed_frames_total'), 0)} "
        f"delivered={_fmt(delivered, 0)}",
        f"{indent}  conflation rate={rate:.1%} "
        f"cycles={_fmt(_counter(node, 'feed_conflations_total'), 0)} "
        f"resyncs={_fmt(_counter(node, 'feed_resyncs_total'), 0)} "
        f"snapshots="
        f"{_fmt(_counter(node, 'feed_snapshots_served_total'), 0)} "
        f"disconnects="
        f"{_fmt(_counter(node, 'feed_disconnects_total'), 0)}",
        f"{indent}  feed_lag p50={_fmt(lat.get('p50_ms'), 3)}ms "
        f"p99={_fmt(lat.get('p99_ms'), 3)}ms "
        f"({_fmt(lat.get('count'), 0)} obs)",
    ]
    return lines


def event_lines(state_root: str, limit: int = 6,
                indent: str = "") -> list:
    """Recent-events pane: the tail of the merged control-plane
    timeline (telemetry/events.py) under a state root — restarts,
    promotions, fences, autoscale proposals — one line each. Empty
    when no writer has an event log yet."""
    try:
        from kme_tpu.telemetry import events as cpevents

        merged = cpevents.merge_logs([state_root])
    except Exception:
        return []
    if not merged:
        return []
    lines = [f"{indent}events   (last {min(limit, len(merged))} of "
             f"{len(merged)} — kme-events for the full timeline):"]
    for ev in merged[-limit:]:
        lines.append(f"{indent}  {cpevents.format_event(ev)}")
    return lines


def render(view: dict, width: int = 78) -> list:
    """The dashboard frame as plain lines (shared by the curses loop
    and --once; pure so the smoke test can assert on it)."""
    lead, stby = view["leader"], view["standby"]
    sup = view.get("supervisor")
    bar = "=" * width
    lines = [f"kme-top  {time.strftime('%H:%M:%S')}", bar]

    rate = view.get("records_per_s")
    lines.append(
        f"leader   epoch={_fmt(view.get('epoch'))} "
        f"offset={_fmt(view.get('offset'))} "
        f"records={_fmt(_counter(lead, 'service_records'))} "
        f"rate={_fmt(rate) + '/s' if rate is not None else '-'}")
    if not lead["ok"]:
        lines.append(f"  leader source unreachable: "
                     f"{lead.get('error', 'no source')}")
    deg = view.get("degraded")
    slo_ok = _gauge(lead, "slo_ok")
    burn = _gauge(lead, "slo_burn_rate")
    if deg:
        lines.append(f"  DEGRADED: {deg}")
    if slo_ok is not None:
        lines.append(
            f"  slo={'OK' if slo_ok else 'BREACH'}"
            + (f" burn={_fmt(burn, 2)}x" if burn is not None else ""))
    if _gauge(lead, "pipeline_warning"):
        lines.append("  pipeline_warning: speedup < 1.0 "
                     "(see measured_overlap_s)")

    # degradation row (adaptive overload controller, kme-serve
    # --overload-high-lag): only rendered when the controller is
    # active — overload_state is absent on a binary-max_lag or
    # unbounded-ingress leader
    ostate = _gauge(lead, "overload_state")
    if ostate is not None:
        names = ("normal", "shedding", "draining")
        sname = (names[int(ostate)] if 0 <= int(ostate) < 3
                 else f"?{ostate}")
        adm = [_gauge(lead, f"admitted_by_class{c}") or 0
               for c in range(3)]
        shd = [_gauge(lead, f"shed_by_class{c}") or 0
               for c in range(3)]
        offered = sum(adm) + sum(shd)
        frac = (sum(shd) / offered) if offered else 0.0
        lines.append(
            f"  overload state={sname.upper() if ostate else sname} "
            f"shed={_fmt(sum(shd), 0)} ({frac:.1%}) "
            f"backoff={_fmt(_gauge(lead, 'overload_backoff_ms'), 0)}ms "
            f"transitions="
            f"{_fmt(_gauge(lead, 'overload_transitions'), 0)} "
            f"fairness_sheds="
            f"{_fmt(_gauge(lead, 'overload_fairness_sheds'), 0)}")
        lines.append(
            f"  {'class':<16s}{'admitted':>10s}{'shed':>10s}")
        for c, label in enumerate(("drain (cxl/pay)", "admin",
                                   "new orders")):
            lines.append(f"  {label:<16s}{_fmt(adm[c], 0):>10s}"
                         f"{_fmt(shd[c], 0):>10s}")

    # wire row (binary front door, kme-serve + produce_frames): only
    # rendered when the leader publishes the binary-adoption gauge —
    # absent on pre-binary leaders
    wfrac = _gauge(lead, "wire_binary_frac")
    if wfrac is not None:
        lines.append(
            f"  wire binary={wfrac:.1%} "
            f"parse={_fmt(_gauge(lead, 'parse_ns_per_msg'), 0)}ns/msg")

    lats = lead.get("metrics", {}).get("latencies", {})
    rows = [(s, lats.get(f"lat_{s}")) for s in STAGES]
    if any(v for _s, v in rows):
        lines.append("")
        lines.append(f"  {'stage':<9s}{'count':>10s}{'p50 ms':>10s}"
                     f"{'p99 ms':>10s}{'p999 ms':>10s}")
        for s, v in rows:
            if not v:
                continue
            lines.append(
                f"  {s:<9s}{_fmt(v.get('count'), 0):>10s}"
                f"{_fmt(v.get('p50_ms'), 3):>10s}"
                f"{_fmt(v.get('p99_ms'), 3):>10s}"
                f"{_fmt(v.get('p999_ms'), 3):>10s}")

    # per-shard straggler attribution (SeqMeshSession telemetry):
    # occupancy + migration gauges and the occupancy-weighted
    # device_shard{N} latency summaries
    nshards = _gauge(lead, "shard_count")
    if nshards:
        lines.append("")
        head = (
            f"  shards={_fmt(nshards, 0)} "
            f"imbalance={_fmt(_gauge(lead, 'shard_imbalance'), 3)} "
            f"migrations="
            f"{_fmt(_counter(lead, 'shard_migrations_total'), 0)} "
            f"rebalances="
            f"{_fmt(_counter(lead, 'shard_rebalances_total'), 0)}")
        # per-chip timing gauges only exist under async dispatch (r14);
        # their absence means a lockstep mesh — no stall column, and
        # the histograms fall back to occupancy-weighted splits
        stall = _gauge(lead, "chip_stall_frac")
        if stall is not None:
            head += f" stall={stall:.1%}"
        lines.append(head)
        has_stall = any(
            _gauge(lead, f"shard{s}_stall_frac") is not None
            for s in range(int(nshards)))
        lines.append(f"  {'shard':<9s}{'occupancy':>10s}{'p50 ms':>12s}"
                     f"{'p99 ms':>12s}"
                     + (f"{'stall%':>9s}" if has_stall else ""))
        for s in range(int(nshards)):
            v = lats.get(f"device_shard{s}") or {}
            row = (
                f"  {s:<9d}"
                f"{_fmt(_gauge(lead, f'shard{s}_occupancy'), 0):>10s} "
                f"{_fmt(v.get('p50_ms'), 3):>11s} "
                f"{_fmt(v.get('p99_ms'), 3):>11s}")
            if has_stall:
                sf = _gauge(lead, f"shard{s}_stall_frac")
                row += (f" {sf * 100:>7.1f}%" if sf is not None
                        else f" {'-':>8s}")
            lines.append(row)

    # multi-leader shard group (bridge/front.py scale-out): the
    # leader's place in the group universe, its input lag, and the
    # cross-shard transfer traffic with the reserve->settle RTT
    ngroups = _gauge(lead, "group_count")
    if ngroups and ngroups > 1:
        gid = _gauge(lead, "group_id")
        lag = (_gauge(lead, f"group{int(gid)}_lag")
               if gid is not None else None)
        lines.append("")
        lines.append(
            f"  group={_fmt(gid, 0)}/{_fmt(ngroups, 0)} "
            f"lag={_fmt(lag, 0)} "
            f"xfers="
            f"{_fmt(_gauge(lead, 'cross_shard_transfers_total'), 0)} "
            f"volume="
            f"{_fmt(_gauge(lead, 'cross_shard_transfer_volume'), 0)} "
            f"broadcasts="
            f"{_fmt(_gauge(lead, 'balance_broadcasts_total'), 0)}")
        rtt = lats.get("transfer_rtt")
        if rtt:
            lines.append(
                f"  transfer_rtt  count={_fmt(rtt.get('count'), 0)} "
                f"p50={_fmt(rtt.get('p50_ms'), 3)}ms "
                f"p99={_fmt(rtt.get('p99_ms'), 3)}ms")

    # feed-tier row (kme-feed fan-out, --state-root feed.health): only
    # rendered when the feed gauges are present — absent on runs with
    # no market-data tier
    feedn = view.get("feed") or {}
    if _gauge(feedn, "feed_subscribers") is not None:
        lines.append("")
        lines.extend(feed_lines(feedn))

    lines.append("")
    if stby.get("source"):
        hb = stby.get("hb") or {}
        lines.append(
            f"standby  applied={_fmt(hb.get('applied', _gauge(stby, 'replica_applied_offset')))} "
            f"lag={_fmt(view.get('replica_lag'))} "
            f"out_seq={_fmt(hb.get('out_seq'))} "
            f"discarded={_fmt(hb.get('discarded'))}")
        if not stby["ok"]:
            lines.append(f"  standby source unreachable: "
                         f"{stby.get('error', '?')}")
    else:
        lines.append("standby  (none)")

    hist = view.get("history")
    if hist:
        lines.append("")
        lines.extend(hist)

    if sup is not None:
        lines.append(
            f"superv   restarts={_fmt(sup.get('restarts_total'))} "
            f"budget={_fmt(sup.get('budget_used'))}/"
            f"{_fmt(sup.get('max_restarts'))} "
            f"standby_restarts={_fmt(sup.get('standby_restarts'))}")
        for rec in (sup.get("recoveries") or [])[-3:]:
            if isinstance(rec, dict):
                lines.append("  recovery: " + " ".join(
                    f"{k}={rec[k]}" for k in sorted(rec)))
    evs = view.get("events")
    if evs:
        lines.append("")
        lines.extend(evs)
    lines.append(bar)
    return lines


def render_cluster(cur: dict, prev: Optional[dict] = None,
                   width: int = 78) -> list:
    """Multi-leader frame: one row per shard group (rate from the
    previous sweep's counters), DEGRADED rows for groups whose health
    surface is unreachable instead of a crash or a silent hole."""
    bar = "=" * width
    lines = [f"kme-top --cluster  {time.strftime('%H:%M:%S')}", bar,
             f"  {'group':<7s}{'epoch':>6s}{'offset':>10s}"
             f"{'rate/s':>10s}{'e2e p99':>10s}{'lag':>8s}"
             f"{'shed':>8s}{'restarts':>9s}"]
    prev_rows = {r["k"]: r for r in (prev or {}).get("rows", ())}
    dt = (cur["t"] - prev["t"]) if prev else 0.0
    up = 0
    for row in cur["rows"]:
        k, node = row["k"], row["node"]
        if not node["ok"]:
            lines.append(f"  g{k:<6d} DEGRADED (unreachable: "
                         f"{node.get('error', 'no source')})")
            continue
        up += 1
        hb = node.get("hb") or {}
        rate = None
        p = prev_rows.get(k)
        if p is not None and p["node"]["ok"] and dt > 0:
            a = _counter(p["node"], "service_records")
            b = _counter(node, "service_records")
            if a is not None and b is not None and b >= a:
                rate = (b - a) / dt
        lats = node.get("metrics", {}).get("latencies", {})
        p99 = (lats.get("lat_e2e") or {}).get("p99_ms")
        lag = _gauge(node, f"group{k}_lag")
        shed = _gauge(node, "overload_rejects")
        sup = row.get("supervisor") or {}
        lines.append(
            f"  g{k:<6d}"
            f"{_fmt(hb.get('epoch', _gauge(node, 'leader_epoch')), 0):>6s}"
            f"{_fmt(hb.get('offset', _gauge(node, 'service_offset')), 0):>10s}"
            f"{_fmt(rate, 0):>10s}"
            f"{_fmt(p99, 3):>10s}"
            f"{_fmt(lag, 0):>8s}"
            f"{_fmt(shed, 0):>8s}"
            f"{_fmt(sup.get('restarts_total'), 0):>9s}")
    # feed tier, one block per group that publishes the feed gauges
    feed_rows = [(row["k"], row.get("feed") or {}) for row in cur["rows"]
                 if _gauge(row.get("feed") or {}, "feed_subscribers")
                 is not None]
    if feed_rows:
        lines.append("  feed tier:")
        for k, node in feed_rows:
            for ln in feed_lines(node, indent="  "):
                lines.append(ln.replace("feed     ", f"g{k} feed  ", 1))
    lines.append(bar)
    lines.append(f"  {up}/{len(cur['rows'])} groups up")
    return lines


# -- entry point -------------------------------------------------------


def _curses_loop(args) -> int:
    import curses

    def loop(scr):
        curses.curs_set(0)
        scr.nodelay(True)
        prev = None
        while True:
            cur = collect(args.leader, args.standby, args.supervisor,
                          feed=args.feed)
            view = build_view(cur, prev)
            if args.tsdb:
                view["history"] = history_lines(args.tsdb)
            if args.state_root:
                view["events"] = event_lines(args.state_root)
            prev = cur
            scr.erase()
            maxy, maxx = scr.getmaxyx()
            for i, ln in enumerate(render(view, width=min(maxx - 1, 100))):
                if i >= maxy - 1:
                    break
                scr.addnstr(i, 0, ln, maxx - 1)
            scr.refresh()
            t_end = time.monotonic() + args.interval
            while time.monotonic() < t_end:
                ch = scr.getch()
                if ch in (ord("q"), ord("Q")):
                    return 0
                time.sleep(0.05)

    return curses.wrapper(loop) or 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kme-top", description=__doc__)
    p.add_argument("--leader", default=None, metavar="URL|PATH",
                   help="leader metrics URL (http://host:port, the "
                        "/metrics.json path is appended) or heartbeat "
                        "file (serve.health)")
    p.add_argument("--standby", default=None, metavar="URL|PATH",
                   help="standby metrics URL or heartbeat file "
                        "(standby.health)")
    p.add_argument("--supervisor", default=None, metavar="PATH",
                   help="supervisor state mirror "
                        "(<checkpoint-dir>/supervisor.json)")
    p.add_argument("--feed", default=None, metavar="URL|PATH",
                   help="feed-tier metrics URL or heartbeat file "
                        "(kme-feed --state-root writes feed.health); "
                        "the feed section renders iff its gauges are "
                        "present")
    p.add_argument("--state-root", default=None, metavar="DIR",
                   help="convenience: a checkpoint dir (or a multi-"
                        "leader run dir with group{k}/ children); "
                        "fills in --leader/--standby/--supervisor via "
                        "discover_endpoints")
    p.add_argument("--tsdb", default=None, metavar="DIR",
                   help="on-disk metrics history (kme-serve --tsdb): "
                        "adds sparkline look-back columns to the "
                        "leader frame")
    p.add_argument("--cluster", action="store_true",
                   help="multi-leader view: one row per discovered "
                        "shard group under --state-root (degraded "
                        "rows for unreachable groups)")
    p.add_argument("--interval", type=float, default=1.0,
                   metavar="SECS")
    p.add_argument("--once", action="store_true",
                   help="print one plain-text frame and exit (after a "
                        "second sample --interval later for rates)")
    p.add_argument("--no-rate-sample", action="store_true",
                   help="with --once: single sample, no rate")
    args = p.parse_args(argv)
    eps = None
    if args.state_root:
        eps = discover_endpoints(args.state_root)
        args.leader = args.leader or eps["leader"]
        args.standby = args.standby or eps["standby"]
        args.supervisor = args.supervisor or eps["supervisor"]
        args.feed = args.feed or eps["feed"]
    if args.cluster:
        if eps is None or not eps["groups"]:
            p.error("--cluster needs --state-root pointing at a run "
                    "dir with group{k}/ children")
        prev = None
        if args.once and not args.no_rate_sample:
            prev = collect_cluster(eps["groups"])
            time.sleep(min(args.interval, 1.0))
        if args.once:
            for ln in render_cluster(collect_cluster(eps["groups"]),
                                     prev):
                print(ln)
            for ln in event_lines(args.state_root):
                print(ln)
            return 0
        try:
            while True:
                cur = collect_cluster(eps["groups"])
                for ln in render_cluster(cur, prev):
                    print(ln)
                for ln in event_lines(args.state_root):
                    print(ln)
                prev = cur
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
    if not (args.leader or args.standby or args.supervisor):
        p.error("nothing to watch: give --leader/--standby/"
                "--supervisor or --state-root")
    if args.once:
        prev = None
        if not args.no_rate_sample:
            prev = collect(args.leader, args.standby, args.supervisor,
                           feed=args.feed)
            time.sleep(min(args.interval, 1.0))
        cur = collect(args.leader, args.standby, args.supervisor,
                      feed=args.feed)
        view = build_view(cur, prev)
        if args.tsdb:
            view["history"] = history_lines(args.tsdb)
        if args.state_root:
            view["events"] = event_lines(args.state_root)
        for ln in render(view):
            print(ln)
        return 0
    try:
        return _curses_loop(args)
    except Exception as e:
        # no tty / TERM unset (CI): degrade to a plain-text loop
        print(f"kme-top: curses unavailable ({e}); plain loop "
              f"(ctrl-c to quit)", file=sys.stderr)
        prev = None
        try:
            while True:
                cur = collect(args.leader, args.standby,
                              args.supervisor, feed=args.feed)
                view = build_view(cur, prev)
                if args.tsdb:
                    view["history"] = history_lines(args.tsdb)
                if args.state_root:
                    view["events"] = event_lines(args.state_root)
                for ln in render(view):
                    print(ln)
                prev = cur
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
