"""Metric registry: Counter / Gauge / Histogram with Prometheus text
exposition and JSON export.

One Registry instance is owned by each session (LaneSession,
SeqSession, SeqMeshSession) and shared with the serving layer —
`MatchService` publishes its per-batch counters into the same registry
the engine projects its on-device counters into, so a single
`/metrics` scrape (telemetry/httpd.py) sees both.

Histograms use the engine's power-of-two bucket layout (16 buckets,
engine/lanes.py): bucket 0 holds values <= 0, bucket i (1..14) holds
values in [2^(i-1), 2^i - 1], bucket 15 holds values >= 2^14. The
Prometheus exposition therefore uses cumulative upper bounds
le="0","1","3","7",...,"16383","+Inf". Device-filled histograms carry
no true sum (the kernel only accumulates bucket counts); `sum` is
exact only for host-side `observe()` use.
"""

from __future__ import annotations

import bisect
import json
import threading

N_BUCKETS = 16

# upper bound of bucket i: 0 for i=0, 2^i - 1 for 1..14, +Inf for 15
BUCKET_LE = tuple(
    ["0"] + [str((1 << i) - 1) for i in range(1, N_BUCKETS - 1)] + ["+Inf"])


def bucket_index(v: int) -> int:
    """Host-side mirror of the kernel bucketing: #{k in 0..14 : v >= 2^k}."""
    b = 0
    for k in range(N_BUCKETS - 1):
        if v >= (1 << k):
            b += 1
    return b


class Counter:
    """Monotonic counter. Sessions project absolute on-device totals via
    set(); host-side producers use inc()."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def inc(self, delta: int = 1) -> None:
        self.value += delta

    def set(self, value: int) -> None:
        self.value = int(value)


class Gauge:
    """Point-in-time value (may go down)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def set(self, value) -> None:
        self.value = value

    def inc(self, delta=1) -> None:
        self.value += delta


class Histogram:
    """Power-of-two bucket histogram (engine layout, N_BUCKETS buckets).

    Two fill modes: host-side observe(v) (tracks an exact sum), or
    set_buckets(counts) projecting device-accumulated bucket counts
    (sum stays whatever was last set via set_sum, default 0)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.buckets = [0] * N_BUCKETS
        self.sum = 0

    def observe(self, value: int) -> None:
        self.buckets[bucket_index(value)] += 1
        self.sum += value

    def set_buckets(self, counts) -> None:
        counts = [int(c) for c in counts]
        if len(counts) != N_BUCKETS:
            raise ValueError(
                f"{self.name}: expected {N_BUCKETS} buckets, "
                f"got {len(counts)}")
        self.buckets = counts

    def set_sum(self, value) -> None:
        self.sum = value

    @property
    def count(self) -> int:
        return sum(self.buckets)


# -- streaming latency quantiles --------------------------------------------
#
# Fixed log-spaced buckets: 1 µs doubling up to ~67 s, one overflow
# bucket. 27 boundaries + overflow = 28 counts; a full histogram is a
# few hundred bytes, so every stage of the serving pipeline can afford
# one that is ALWAYS on (the bench's sort-all-samples percentiles need
# the whole sample vector; this needs O(1) memory and O(1) observe).

LAT_N_BUCKETS = 28
LAT_BOUNDS = tuple(1e-6 * (1 << i) for i in range(LAT_N_BUCKETS - 1))


class LatencyHistogram:
    """Streaming quantile estimator over log-spaced duration buckets.

    Values are SECONDS. `observe(v, n)` records the same duration for n
    orders at once — batch-granular stages (plan, device, produce)
    charge the batch's wall time to every order in it, so the quantiles
    reflect per-order experience, not per-batch. Callers must pass
    intended-start-based durations (arrival stamps, not dequeue times)
    to stay coordinated-omission-safe.

    Thread-safe: observe() and the snapshot/quantile readers take the
    instance lock, so an HTTP scrape mid-batch sees a consistent
    (count, sum, buckets) triple."""

    kind = "latency"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._counts = [0] * LAT_N_BUCKETS
        self._count = 0
        self._sum = 0.0

    def observe(self, seconds: float, n: int = 1) -> None:
        if n <= 0:
            return
        i = bisect.bisect_left(LAT_BOUNDS, seconds)
        with self._lock:
            self._counts[i] += n
            self._count += n
            self._sum += seconds * n

    # -- readers (each takes one consistent view under the lock) -------

    def state(self) -> tuple:
        """(count, sum, bucket-counts copy) — one atomic view."""
        with self._lock:
            return self._count, self._sum, list(self._counts)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @staticmethod
    def _quantile_from(counts, total, q: float) -> float:
        if total <= 0:
            return 0.0
        target = q * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = 0.0 if i == 0 else LAT_BOUNDS[i - 1]
                hi = (LAT_BOUNDS[i] if i < len(LAT_BOUNDS)
                      else 2 * LAT_BOUNDS[-1])
                frac = (target - cum) / c
                return lo + (hi - lo) * frac
            cum += c
        return 2 * LAT_BOUNDS[-1]

    def quantile(self, q: float) -> float:
        count, _s, counts = self.state()
        return self._quantile_from(counts, count, q)

    def quantiles(self) -> dict:
        """{0.5: s, 0.9: s, 0.99: s, 0.999: s} from ONE atomic view."""
        count, _s, counts = self.state()
        return {q: self._quantile_from(counts, count, q)
                for q in (0.5, 0.9, 0.99, 0.999)}

    def count_over(self, threshold_s: float) -> int:
        """Observations in buckets wholly above `threshold_s` — the
        SLO module's bad-event counter (bucket-conservative: the
        threshold's own bucket counts as good)."""
        i = bisect.bisect_left(LAT_BOUNDS, threshold_s)
        with self._lock:
            return sum(self._counts[i + 1:])


def _sanitize(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        ok = ch.isalpha() or ch == "_" or ch == ":" or (ch.isdigit() and i)
        out.append(ch if ok else "_")
    return "".join(out)


class Registry:
    """Thread-safe metric registry.

    Writers (the session main thread, MatchService.step) mutate under
    the lock via counter()/gauge()/histogram() handles; readers (the
    heartbeat thread, the /metrics HTTP handler) take consistent
    snapshots via prometheus_text()/to_json()/snapshot()."""

    def __init__(self, namespace: str = ""):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._metrics: dict = {}  # insertion-ordered
        # p99 exemplars: slowest recent orders as {tid, off, oid, aid,
        # e2e_us} dicts (deterministic trace ids — telemetry/dtrace.py)
        # so a cluster-level quantile outlier resolves to a waterfall
        self._exemplars: list = []

    def _get(self, cls, name: str, help: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(Histogram, name, help)

    def latency(self, name: str, help: str = "") -> LatencyHistogram:
        return self._get(LatencyHistogram, name, help)

    def set_exemplars(self, exemplars) -> None:
        """Replace the slow-order exemplar list exported in snapshot()
        (bounded upstream; the registry stores what it is given)."""
        with self._lock:
            self._exemplars = list(exemplars)

    def exemplars(self) -> list:
        with self._lock:
            return list(self._exemplars)

    # -- bulk publication (the session metrics()/histograms() projection)

    def publish_counters(self, counters: dict) -> None:
        for k, v in counters.items():
            self.counter(k).set(v)

    def publish_gauges(self, gauges: dict) -> None:
        for k, v in gauges.items():
            self.gauge(k).set(v)

    def publish_histograms(self, hists: dict) -> None:
        for k, buckets in hists.items():
            self.histogram(k).set_buckets(buckets)

    # -- export

    def _qualified(self, name: str) -> str:
        base = _sanitize(name)
        return f"{self.namespace}_{base}" if self.namespace else base

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            items = list(self._metrics.items())
        lines = []
        for name, m in items:
            q = self._qualified(name)
            if m.help:
                lines.append(f"# HELP {q} {m.help}")
            # latency histograms expose as Prometheus summaries
            # (pre-computed quantiles, no bucket series)
            lines.append(f"# TYPE {q} "
                         f"{'summary' if m.kind == 'latency' else m.kind}")
            if m.kind == "histogram":
                cum = 0
                for le, c in zip(BUCKET_LE, m.buckets):
                    cum += c
                    lines.append(f'{q}_bucket{{le="{le}"}} {cum}')
                lines.append(f"{q}_sum {m.sum}")
                lines.append(f"{q}_count {cum}")
            elif m.kind == "latency":
                # summary exposition: one atomic state() view feeds
                # every quantile line plus sum/count
                count, total, counts = m.state()
                for qq in (0.5, 0.9, 0.99, 0.999):
                    v = m._quantile_from(counts, count, qq)
                    lines.append(f'{q}{{quantile="{qq}"}} {v:.6g}')
                lines.append(f"{q}_sum {total:.6g}")
                lines.append(f"{q}_count {count}")
            else:
                lines.append(f"{q} {m.value}")
        return "\n".join(lines) + "\n"

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def snapshot(self) -> dict:
        """Plain-dict view: {"counters": {...}, "gauges": {...},
        "histograms": {name: {"buckets", "sum", "count"}}}."""
        with self._lock:
            out = {"counters": {}, "gauges": {}, "histograms": {},
                   "latencies": {}}
            if self._exemplars:
                out["exemplars"] = list(self._exemplars)
            for name, m in self._metrics.items():
                if m.kind == "counter":
                    out["counters"][name] = m.value
                elif m.kind == "gauge":
                    out["gauges"][name] = m.value
                elif m.kind == "latency":
                    count, total, counts = m.state()
                    out["latencies"][name] = {
                        "count": count,
                        "sum_s": round(total, 6),
                        "p50_ms": round(m._quantile_from(
                            counts, count, 0.5) * 1e3, 3),
                        "p90_ms": round(m._quantile_from(
                            counts, count, 0.9) * 1e3, 3),
                        "p99_ms": round(m._quantile_from(
                            counts, count, 0.99) * 1e3, 3),
                        "p999_ms": round(m._quantile_from(
                            counts, count, 0.999) * 1e3, 3),
                        # raw bucket counts (LAT_BOUNDS layout): the
                        # cluster aggregator (kme-agg) sums these across
                        # scrapes, so merged quantiles are EXACT — not a
                        # quantile-of-quantiles estimate
                        "buckets": counts,
                    }
                else:
                    out["histograms"][name] = {
                        "buckets": list(m.buckets),
                        "sum": m.sum,
                        "count": m.count,
                    }
            return out
