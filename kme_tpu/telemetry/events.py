"""Control-plane flight recorder: durable, crash-safe event timeline.

The data plane already answers "what happened to order X" (journal,
traces, TSDB); this module answers "what did the CLUSTER decide" —
supervisor restarts and promotions, lease grants/steals/fences,
autoscale observations and proposals, reshard phases with their walls,
overload-controller transitions, feed resyncs. Every control-plane
seam appends typed events to a per-process ``EventLog``; ``kme-events``
merges the logs into one causally-ordered cluster timeline.

One record per line, canonical compact JSON (sorted keys), with a
small fixed schema (absent optional keys mean not-applicable):

  src     writer identity ("supervisor", "reshard", "serve.g0", ...)
  seq     per-source monotonic event sequence — the replay-dedup key,
          mirroring tsdb's ``sample_seq`` and the broker's
          ``(epoch, out_seq)`` discipline: a crash-resumed writer that
          re-emits an already-committed event is dropped on append,
          and the merge reader drops it again (first wins)
  kind    dotted event name ("supervisor.restart", "reshard.fence")
  sev     "info" | "warn" | "error"
  ts      wall clock, microseconds — ADVISORY ONLY. Timestamps come
          from the writer's injected clock and never participate in
          identity or (where an offset anchor exists) ordering.
  g       group ordinal anchor (absent = not group-scoped)
  epoch   lease epoch anchor
  off     input-stream offset anchor — the replay position this
          decision is causally tied to; within one group, offsets
          order the timeline even when wall clocks skew
  tid     optional trace-id link into the per-order waterfalls
  detail  free-form structured payload (phase walls, fingerprints...)

Durability mirrors journal.py/tsdb.py: append-only JSONL with
logrotate-style rotation (``path -> path.1 -> ...``), a sha256 JSON
sidecar written per rotated segment, digest-verified pruning beyond
``retain``, and torn-tail recovery on open (a crash mid-append leaves
a partial final line; the next open truncates it and re-derives the
seq cursor from the surviving tail, seeding from rotated segments when
the live file is empty).

Determinism contract (lint-enforced via EVENTS_SCOPES): the pure
key/ordering/merge functions below — ``order_key``, ``sort_events``,
``dedup_events``, ``merge_events``, ``timeline_digest``,
``event_line`` — never read wall clock or RNG. Writers that need
replay-stable identity (the reshard coordinator across a SIGKILL
re-run) pass an explicit ``seq`` derived from durable state (the
journal phase ordinal), so the re-run's duplicate emission deduplicates
instead of double-counting.

Event emission is always-on but can be globally disabled with
``KME_EVENTS=0`` (the MatchOut byte-parity escape hatch the prof suite
exercises); a disabled log swallows emissions without touching disk.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

SEVERITIES = ("info", "warn", "error")

# conventional file names: one live log per writer in its state dir —
# ``events-<source>.jsonl`` — plus the bare ``events.jsonl`` name used
# for MERGED artifacts (chaos reports, sim repro kits). Discovery
# accepts both so a merged artifact can itself be re-merged/queried.
PREFIX = "events-"
SUFFIX = ".jsonl"
MERGED_NAME = "events.jsonl"


def enabled() -> bool:
    """Global emission gate: KME_EVENTS=0 turns the recorder off (the
    byte-parity escape hatch); anything else leaves it on."""
    return os.environ.get("KME_EVENTS", "1") != "0"


def log_path(state_dir: str, source: str) -> str:
    """The conventional live-log path for one writer."""
    safe = source.replace("/", "_").replace(os.sep, "_")
    return os.path.join(state_dir, f"{PREFIX}{safe}{SUFFIX}")


# ---------------------------------------------------------------------------
# pure schema / ordering / merge functions (EVENTS_SCOPES: no wall
# clock, no RNG — replay-law code)


def make_event(source: str, seq: int, kind: str, ts_us: int,
               severity: str = "info", group: Optional[int] = None,
               epoch: Optional[int] = None, offset: Optional[int] = None,
               tid: Optional[int] = None,
               detail: Optional[dict] = None) -> dict:
    """One schema-complete event dict. ``ts_us`` is caller-supplied
    (the writer's injected clock) so this stays a pure function."""
    if severity not in SEVERITIES:
        raise ValueError(f"unknown severity {severity!r}")
    ev: dict = {"src": str(source), "seq": int(seq), "kind": str(kind),
                "sev": severity, "ts": int(ts_us)}
    if group is not None and int(group) >= 0:
        ev["g"] = int(group)
    if epoch is not None and int(epoch) >= 0:
        ev["epoch"] = int(epoch)
    if offset is not None and int(offset) >= 0:
        ev["off"] = int(offset)
    if tid:
        ev["tid"] = int(tid)
    if detail:
        ev["detail"] = dict(detail)
    return ev


def event_line(ev: dict) -> str:
    """The canonical on-disk form (and the digest input): compact JSON,
    sorted keys, one line."""
    return json.dumps(ev, sort_keys=True, separators=(",", ":"))


def format_event(ev: dict) -> str:
    """One human line per event (kme-events, the kme-top/kme-agg
    recent-events pane)."""
    ts = int(ev.get("ts", 0)) / 1e6
    bits = [f"{ts:.6f}", f"{ev.get('sev', 'info'):5s}",
            f"{ev.get('src', '?')}#{ev.get('seq', -1)}",
            str(ev.get("kind", "?"))]
    for k in ("g", "epoch", "off", "tid"):
        if k in ev:
            bits.append(f"{k}={ev[k]}")
    det = ev.get("detail")
    if det:
        bits.append(" ".join(f"{k}={det[k]}" for k in sorted(det)))
    return "  ".join(bits)


def order_key(ev: dict) -> tuple:
    """Walltime interleave key (ts, src, seq): the FALLBACK order.
    ``sort_events`` then lets offset anchors override it within each
    group — see there."""
    return (int(ev.get("ts", 0)), str(ev.get("src", "")),
            int(ev.get("seq", 0)))


def sort_events(events: Sequence[dict]) -> List[dict]:
    """Causal order for a merged timeline.

    Pass 1 interleaves everything by the advisory walltime (stable,
    deterministic: ties break on (src, seq)). Pass 2 enforces the
    anchors: within each group, the events that carry an input-stream
    offset are re-ordered by (off, src, seq) IN PLACE of the slots
    they already occupy — replay position beats wall clock inside one
    group's history (skewed clocks cannot reorder it), while
    unanchored events and cross-group interleave keep their walltime
    positions. Pure function of its input."""
    out = sorted(events, key=order_key)
    by_group: Dict[int, List[int]] = {}
    for i, ev in enumerate(out):
        if int(ev.get("g", -1)) >= 0 and int(ev.get("off", -1)) >= 0:
            by_group.setdefault(int(ev["g"]), []).append(i)
    for slots in by_group.values():
        anchored = sorted((out[i] for i in slots),
                          key=lambda e: (int(e["off"]), str(e["src"]),
                                         int(e["seq"])))
        for i, ev in zip(slots, anchored):
            out[i] = ev
    return out


def dedup_events(events: Iterable[dict]) -> List[dict]:
    """First-wins dedup on the (src, seq) identity — the reader-side
    half of the replay-dedup discipline (a torn-then-resumed writer,
    or the same log merged twice, collapses to one timeline).

    A (src, seq) collision between two DIFFERENT records is not a
    replay — it is two distinct writers that happen to share a source
    name (e.g. ``serve.g0`` in two reshard generations merged into one
    timeline). Those are kept: only byte-identical duplicates drop."""
    seen: Dict[Tuple[str, int], List[str]] = {}
    out: List[dict] = []
    for ev in events:
        key = (str(ev.get("src", "")), int(ev.get("seq", -1)))
        line = event_line(ev)
        lines = seen.setdefault(key, [])
        if line in lines:
            continue
        lines.append(line)
        out.append(ev)
    return out


def merge_events(streams: Iterable[Iterable[dict]]) -> List[dict]:
    """N per-process event iterables -> one deduped, causally ordered
    timeline."""
    flat: List[dict] = []
    for stream in streams:
        flat.extend(stream)
    return sort_events(dedup_events(flat))


def timeline_digest(events: Sequence[dict]) -> str:
    """sha256 over the canonical lines of an (ordered) timeline — the
    byte-determinism verdict substrate for the sim."""
    h = hashlib.sha256()
    for ev in events:
        h.update(event_line(ev).encode("utf-8"))
        h.update(b"\n")
    return h.hexdigest()


# ---------------------------------------------------------------------------
# readers


def iter_log(path: str) -> Iterator[dict]:
    """Stream one segment's events in append order; a torn trailing
    line (crash mid-append) is skipped, matching the writer's resume
    behavior. Unparseable interior lines are skipped too (a reader
    must not die on one bad record)."""
    try:
        fh = open(path, "rb")
    except OSError:
        return
    with fh:
        for ln in fh:
            if not ln.endswith(b"\n"):
                return          # torn tail
            ln = ln.strip()
            if not ln:
                continue
            try:
                yield json.loads(ln)
            except ValueError:
                continue


def read_log(path: str, include_rotated: bool = True) -> List[dict]:
    """All of one writer's events, oldest first (rotated segments
    ``path.N`` N-descending first, then the live file)."""
    paths: List[str] = []
    if include_rotated:
        n = 1
        while os.path.exists(f"{path}.{n}"):
            n += 1
        paths = [f"{path}.{k}" for k in range(n - 1, 0, -1)]
    paths.append(path)
    out: List[dict] = []
    for p in paths:
        out.extend(iter_log(p))
    return out


def discover_logs(root: str) -> List[str]:
    """Every event-log live file under a state root: conventional
    ``events-*.jsonl`` writers plus merged ``events.jsonl`` artifacts.
    Rotated ``.N`` siblings ride along via read_log. Sorted for
    deterministic merge input order."""
    found: List[str] = []
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            if (name == MERGED_NAME
                    or (name.startswith(PREFIX)
                        and name.endswith(SUFFIX))):
                found.append(os.path.join(dirpath, name))
    return sorted(found)


def merge_logs(paths: Sequence[str]) -> List[dict]:
    """Merge per-process logs (files or state-root directories) into
    one timeline."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(discover_logs(p))
        else:
            files.append(p)
    return merge_events(read_log(f) for f in files)


def write_merged(events: Sequence[dict], path: str) -> None:
    """Write a merged timeline artifact (atomic replace)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for ev in events:
            f.write(event_line(ev) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# sidecar digests (same shape as tsdb.py's)


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_digest(path: str) -> None:
    doc = {"sha256": _sha256_file(path),
           "bytes": os.path.getsize(path)}
    tmp = path + ".sha256.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path + ".sha256")


def _verify_digest(path: str) -> Optional[bool]:
    """True/False verdict, None when no sidecar exists."""
    side = path + ".sha256"
    if not os.path.exists(side):
        return None
    try:
        with open(side) as f:
            doc = json.load(f)
        return (doc.get("bytes") == os.path.getsize(path)
                and doc.get("sha256") == _sha256_file(path))
    except (OSError, ValueError):
        return False


def verify_log(path: str) -> dict:
    """Offline integrity sweep over one writer's segments: per-segment
    sidecar verdicts plus a seq-gap scan across the whole history."""
    segs: List[str] = []
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    segs = [f"{path}.{k}" for k in range(n - 1, 0, -1)]
    report = {"segments": [], "events": 0, "seq_gaps": 0, "ok": True}
    last = -1
    for seg in segs:
        verdict = _verify_digest(seg)
        report["segments"].append({"path": seg, "digest_ok": verdict})
        if verdict is False:
            report["ok"] = False
    for ev in read_log(path):
        report["events"] += 1
        seq = int(ev.get("seq", -1))
        if last >= 0 and seq > last + 1:
            report["seq_gaps"] += 1
        if seq > last:
            last = seq
    return report


# ---------------------------------------------------------------------------
# writer


class EventLog:
    """Durable append-only control-plane event writer.

    ``clock`` is a zero-arg seconds-float callable (the writer's
    injected time source — a Supervisor's fake clock, a sim actor's
    virtual view); it stamps the ADVISORY ``ts`` field only. ``seq``
    defaults to the durable cursor + 1; writers with their own durable
    identity (reshard phases) pass it explicitly and rely on the
    dedup: an append at or below the committed high-water mark is
    dropped and counted, never written twice.

    ``enabled=False`` (or KME_EVENTS=0 at construction) makes every
    emit a no-op that touches no disk — the byte-parity off switch."""

    def __init__(self, path: str, source: str,
                 rotate_bytes: int = 1 << 20, retain: int = 8,
                 fsync: bool = True, clock=None,
                 enabled: Optional[bool] = None) -> None:
        self.path = path
        self.source = str(source)
        self.rotate_bytes = max(4096, int(rotate_bytes))
        self.retain = max(1, int(retain))
        self.fsync = fsync
        # the ONE sanctioned wall-clock touch in this module: where the
        # injected-clock seam bottoms out for writers nobody scripts.
        # Grandfathered under KME-E001 (LINT_BASELINE.json) so any new
        # clock/RNG reference in the identity paths still gates.
        self._clock = clock or time.time
        self.enabled = (globals()["enabled"]() if enabled is None
                        else bool(enabled))
        self.last_seq = -1
        self.dup_skipped = 0
        self.digest_mismatches = 0
        self.last_offset = 0        # committed bytes in the live file
        self.lag_bytes = 0          # written but not yet fsync'd
        self._f = None
        if self.enabled:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._open_live()

    # -- open / recovery ----------------------------------------------

    def _open_live(self) -> None:
        if os.path.exists(self.path) and os.path.getsize(self.path):
            with open(self.path, "r+b") as f:
                data = f.read()
                if not data.endswith(b"\n"):
                    # torn tail: a crash mid-append left a partial
                    # line — truncate to the last complete record
                    cut = data.rfind(b"\n") + 1
                    f.truncate(cut)
            for ev in iter_log(self.path):
                seq = int(ev.get("seq", -1))
                if seq > self.last_seq:
                    self.last_seq = seq
        if self.last_seq < 0:
            self._seed_seq_from_rotated()
        self._f = open(self.path, "ab")
        self.last_offset = self._f.tell()

    def _seed_seq_from_rotated(self) -> None:
        """Empty/fresh live file after a rotation boundary crash: the
        cursor must continue from the newest rotated segment or the
        dedup guarantee dies exactly when it matters."""
        if not os.path.exists(f"{self.path}.1"):
            return
        for ev in iter_log(f"{self.path}.1"):
            seq = int(ev.get("seq", -1))
            if seq > self.last_seq:
                self.last_seq = seq

    # -- append -------------------------------------------------------

    def emit(self, kind: str, severity: str = "info",
             group: Optional[int] = None, epoch: Optional[int] = None,
             offset: Optional[int] = None, tid: Optional[int] = None,
             seq: Optional[int] = None, ts_us: Optional[int] = None,
             **detail) -> bool:
        """Append one event. Returns False when disabled or when the
        (explicit) seq is at or below the committed high-water mark —
        the crash-resume no-op."""
        if not self.enabled or self._f is None:
            return False
        if seq is None:
            seq = self.last_seq + 1
        seq = int(seq)
        if seq <= self.last_seq:
            self.dup_skipped += 1
            return False
        if ts_us is None:
            ts_us = int(self._clock() * 1e6)
        ev = make_event(self.source, seq, kind, ts_us,
                        severity=severity, group=group, epoch=epoch,
                        offset=offset, tid=tid,
                        detail=detail or None)
        blob = (event_line(ev) + "\n").encode("utf-8")
        self.lag_bytes += len(blob)
        self._f.write(blob)
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
            self.lag_bytes = 0
        self.last_seq = seq
        # monotonic committed-bytes cursor (heartbeat
        # events_last_offset): rotation must not rewind it
        self.last_offset += len(blob)
        if self._f.tell() >= self.rotate_bytes:
            self._rotate()
        return True

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())
            self.lag_bytes = 0

    def close(self) -> None:
        if self._f is not None:
            self.flush()
            self._f.close()
            self._f = None

    # -- rotation -----------------------------------------------------

    def _rotate(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        for k in range(n, 0, -1):
            src = self.path if k == 1 else f"{self.path}.{k - 1}"
            dst = f"{self.path}.{k}"
            os.replace(src, dst)
            side = (self.path if k == 1
                    else f"{self.path}.{k - 1}") + ".sha256"
            if os.path.exists(side):
                os.replace(side, dst + ".sha256")
        _write_digest(f"{self.path}.1")
        self._prune()
        self._f = open(self.path, "ab")
        self.lag_bytes = 0

    def _prune(self) -> None:
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        for k in range(n - 1, self.retain, -1):
            seg = f"{self.path}.{k}"
            if _verify_digest(seg) is False:
                self.digest_mismatches += 1
            for p in (seg, seg + ".sha256"):
                try:
                    os.unlink(p)
                except OSError:
                    pass


def open_log(state_dir: str, source: str, clock=None,
             **kw) -> EventLog:
    """The conventional constructor: live log at
    ``<state_dir>/events-<source>.jsonl``."""
    return EventLog(log_path(state_dir, source), source,
                    clock=clock, **kw)


# ---------------------------------------------------------------------------
# chrome trace-event rendering (kme-events --chrome-out)


def to_chrome(events: Sequence[dict]) -> List[dict]:
    """Chrome trace-event dicts for an ordered timeline: one instant
    event per record (pid = source, tid = group), plus duration spans
    for matched ``*.begin`` / ``*.end`` kind pairs per (src, stem) —
    loadable into the same trace viewer the data-plane spans use."""
    out: List[dict] = []
    open_spans: Dict[Tuple[str, str], dict] = {}
    for ev in events:
        src = str(ev.get("src", "?"))
        kind = str(ev.get("kind", "?"))
        ts = int(ev.get("ts", 0))
        args = dict(ev.get("detail") or {})
        for k in ("g", "epoch", "off", "sev"):
            if k in ev:
                args[k] = ev[k]
        tidno = int(ev.get("g", -1)) + 1
        if kind.endswith(".begin"):
            open_spans[(src, kind[:-6])] = {"ts": ts, "args": args}
        elif kind.endswith(".end"):
            stem = kind[:-4]
            b = open_spans.pop((src, stem), None)
            if b is not None:
                out.append({"name": stem, "ph": "X", "ts": b["ts"],
                            "dur": max(0, ts - b["ts"]), "pid": src,
                            "tid": tidno, "args": args})
        out.append({"name": kind, "ph": "i", "ts": ts, "pid": src,
                    "tid": tidno, "s": "g", "args": args})
    return out
