"""Order-lifecycle flight recorder: structured, append-only journal.

Every input order's lifecycle — submit, accept/reject (with the engine's
rej_* reason code), rest, each fill (price/qty/counterparty), cancel,
transfer, payout — is derived from the byte-pinned wire line groups the
sessions already reconstruct, stamped with provenance (batch id,
intra-batch slot, engine sequence number, wall clock microseconds,
shard), and appended to a journal file in one of two framings:

- jsonl: one canonical compact JSON object per line (sorted keys) —
  greppable, streamable, the default.
- binary: fixed 96-byte records behind an 8-byte magic — 3-4x denser,
  O(1) tail scan on resume, same event dicts after decode.

The journal is an OBSERVABILITY artifact, not the source of truth (the
broker log is): the service's offset commit does not wait on journal
durability; `fsync="batch"` tightens the loss window to one batch when
the operator wants it.

Event dictionaries (canonical keys; absent keys mean not-applicable):

  e    event type: submit accept reject rest fill cancel create
       transfer payout add_symbol remove_symbol drop win lat span
  seq  engine-global event sequence number (monotonic, survives resume)
  ts   wall clock, microseconds since epoch
  b    batch id (monotonic per journal)
  i    intra-batch message slot (-1 for drop/win)
  off  input-stream offset of the originating record (-1 if standalone)
  sh   shard id
  act  wire action of the originating message (taker fill action for
       fill events)
  oid/aid/sid/px/qty   message fields; for fill events oid/aid are the
       TAKER's, moid/maid the resting MAKER's, px the maker's execution
       price and qty the traded contracts
  rej  reason code (wire.REJ_*) on reject/drop events
  kind/t0/t1   on win (pipeline window) events: "submit"|"collect" and
       the window bounds in integer microseconds
  in_us/plan_us/dev_us/prod_us/e2e_us   on lat (stage-attribution)
       events: per-order microseconds spent in broker ingress wait,
       batch plan, device dispatch+fetch, produce-visible, and the
       arrival->visible total (ingress is per-order from the broker
       arrival stamp; plan/device/produce are the enclosing batch's
       stage walls — every order in a batch shares them)
  kind/tid/ptid/t0/t1/g/li   on span (distributed-tracing) events:
       SPAN_KINDS stage name, deterministic trace id (+ parent trace
       id for XFER legs), wall-clock span bounds in microseconds,
       group ordinal and front-local row index (telemetry/dtrace.py)

`batch_events` is the single wire->events derivation; the oracle replay
(`oracle_events`) reuses it on the Python reference engine's output so a
journal can be verified byte-for-byte (canonical form) against an
independent replay of the same input stream — `kme-trace --verify`.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from kme_tpu import opcodes as op
from kme_tpu.wire import (REJ_MALFORMED, REJ_UNSPECIFIED, parse_order,
                          reason_for_reject)

ETYPES = ("submit", "accept", "reject", "rest", "fill", "cancel",
          "create", "transfer", "payout", "add_symbol", "remove_symbol",
          "drop", "win", "lat", "span")
_ETYPE_IDX = {n: i for i, n in enumerate(ETYPES)}

# distributed-tracing span kinds (telemetry/dtrace.py): the per-hop
# stages a cluster waterfall is stitched from. Order is the wire
# encoding (rej byte in the binary record) — append-only.
SPAN_KINDS = ("front_accept", "route", "ingress", "plan", "device",
              "produce", "xfer_reserve", "xfer_settle", "merge",
              "consume")
_SPAN_IDX = {n: i for i, n in enumerate(SPAN_KINDS)}

_ACT_EVENT = {
    op.CANCEL: "cancel",
    op.CREATE_BALANCE: "create",
    op.TRANSFER: "transfer",
    op.PAYOUT: "payout",
    op.ADD_SYMBOL: "add_symbol",
    op.REMOVE_SYMBOL: "remove_symbol",
}

MAGIC = b"KMEJRNL1"
# etype, rej, sh, pad | act, b, i | seq, ts, off, oid, aid, sid, px,
# qty, moid, maid
_REC = struct.Struct("<BBBBiii10q")
REC_SIZE = _REC.size            # 96 bytes

_WIN_KINDS = ("submit", "collect")


# ---------------------------------------------------------------------------
# wire lines -> lifecycle events


def batch_events(lines_per_msg: Sequence[Sequence[str]],
                 reasons: Optional[Sequence[int]] = None,
                 offsets: Optional[Sequence[int]] = None,
                 drops: Sequence[Tuple[int, int]] = ()) -> List[dict]:
    """One batch's wire line groups (per input message: the IN echo,
    then OUT fill pairs, then the OUT result echo) -> lifecycle event
    dicts WITHOUT provenance stamps (Journal.record_batch stamps seq/
    ts/b/sh). `reasons` are per-message wire.REJ_* codes (sessions'
    `last_reasons`); None falls back to the action heuristic. `offsets`
    are per-message input-stream offsets; None -> -1. `drops` lists
    (slot, offset) records dropped before the engine (malformed)."""
    evs: List[dict] = []
    for slot, off in drops:
        evs.append({"e": "drop", "i": slot, "off": off,
                    "rej": REJ_MALFORMED})
    for i, lines in enumerate(lines_per_msg):
        off = offsets[i] if offsets is not None else -1
        m = json.loads(lines[0].partition(" ")[2])
        act = m["action"]
        base = {"i": i, "off": off, "act": act, "oid": m["oid"],
                "aid": m["aid"], "sid": m["sid"], "px": m["price"],
                "qty": m["size"]}
        evs.append(dict(base, e="submit"))
        if len(lines) < 2:      # defensive: every message echoes a result
            continue
        res = json.loads(lines[-1].partition(" ")[2])
        if res["action"] == op.REJECT:
            rej = (int(reasons[i]) if reasons is not None
                   else reason_for_reject(act))
            if rej == 0:
                rej = REJ_UNSPECIFIED
            evs.append(dict(base, e="reject", rej=rej))
            continue
        if act in (op.BUY, op.SELL):
            # margin reservation precedes matching in the engine, so the
            # accept event precedes the fill events (the auditor replays
            # in event order)
            evs.append(dict(base, e="accept"))
            for k in range(1, len(lines) - 1, 2):
                mk = json.loads(lines[k].partition(" ")[2])
                tk = json.loads(lines[k + 1].partition(" ")[2])
                evs.append({"e": "fill", "i": i, "off": off,
                            "act": tk["action"], "oid": tk["oid"],
                            "aid": tk["aid"], "moid": mk["oid"],
                            "maid": mk["aid"], "sid": tk["sid"],
                            "px": m["price"] - tk["price"],
                            "qty": tk["size"]})
            if res["size"] > 0:
                evs.append(dict(base, e="rest", qty=res["size"]))
        else:
            evs.append(dict(base, e=_ACT_EVENT.get(act, "accept")))
    return evs


def canonical_events(events: Iterable[dict]) -> List[dict]:
    """Provenance-independent view for replay comparison: window and
    latency-stamp events dropped (both are recorder-local timing, not
    lifecycle); seq/ts/b/i/sh/rej stripped (batching, wall clock and
    reason granularity differ between recorders; the lifecycle payload
    and the input offset alignment must not). Events are stably
    ordered by input offset — batching also decides WHERE a drop
    record lands relative to whole messages (drops lead their batch),
    and two recorders with different batch sizes must still compare
    byte-for-byte."""
    out = []
    for ev in events:
        if ev.get("e") in ("win", "lat", "span"):
            continue
        out.append({k: v for k, v in ev.items()
                    if k not in ("seq", "ts", "b", "i", "sh", "rej")})
    out.sort(key=lambda ev: ev.get("off", -1))   # stable
    return out


def canonical_lines(events: Iterable[dict]) -> List[str]:
    return [json.dumps(ev, sort_keys=True, separators=(",", ":"))
            for ev in canonical_events(events)]


def oracle_events(input_lines: Iterable[str], compat: str = "fixed",
                  book_slots: Optional[int] = None,
                  max_fills: Optional[int] = None) -> List[dict]:
    """Independent replay: run the input stream through the Python
    reference replica (oracle/engine.py) and derive lifecycle events
    from ITS wire output — the judge for `kme-trace --verify` and the
    journal tests. Unparseable/out-of-envelope records become drop
    events, mirroring the service's drop policy."""
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.wire import dumps_order

    kw = {}
    if compat == "fixed" and book_slots is not None:
        kw = {"book_slots": book_slots, "max_fills": max_fills or 16}
    eng = OracleEngine(compat, **kw)
    groups: List[List[str]] = []
    offsets: List[int] = []
    drops: List[Tuple[int, int]] = []
    for off, ln in enumerate(input_lines):
        ln = ln.strip()
        if not ln:
            continue
        try:
            m = parse_order(ln)
            if not (-2**31 <= m.price < 2**31
                    and -2**31 <= m.size < 2**31):
                raise ValueError("price/size outside int32")
        except ValueError:
            drops.append((-1, off))
            continue
        recs = eng.process(m)
        groups.append([f"{r.key} {dumps_order(r.value)}" for r in recs])
        offsets.append(off)
    return batch_events(groups, offsets=offsets, drops=drops)


# ---------------------------------------------------------------------------
# binary framing


def _encode(ev: dict) -> bytes:
    e = _ETYPE_IDX[ev["e"]]
    if ev["e"] == "win":
        return _REC.pack(e, _WIN_KINDS.index(ev["kind"]),
                         ev.get("sh", 0), 0, 0, ev.get("b", -1), -1,
                         ev.get("seq", 0), ev.get("ts", 0), -1,
                         ev["t0"], ev["t1"], 0, 0, 0, 0, 0)
    if ev["e"] == "lat":
        # stage micro-durations ride the spare int64 slots (aid/sid/
        # px/qty/moid) — same 96-byte framing, no format version bump
        return _REC.pack(
            e, 0, ev.get("sh", 0), 0, 0, ev.get("b", -1), -1,
            ev.get("seq", 0), ev.get("ts", 0), ev.get("off", -1),
            ev.get("oid", 0), ev.get("in_us", 0), ev.get("plan_us", 0),
            ev.get("dev_us", 0), ev.get("prod_us", 0),
            ev.get("e2e_us", 0), 0)
    if ev["e"] == "span":
        # trace span: kind index in the rej byte, group in act, and the
        # spare q-slots carry tid/ptid/t0/t1/aid/li — same framing, no
        # version bump (mirrors the "lat" precedent above)
        return _REC.pack(
            e, _SPAN_IDX[ev["kind"]], ev.get("sh", 0), 0,
            ev.get("g", -1), ev.get("b", -1), -1, ev.get("seq", 0),
            ev.get("ts", 0), ev.get("off", -1), ev.get("oid", 0),
            ev.get("tid", 0), ev.get("ptid", 0), ev.get("t0", 0),
            ev.get("t1", 0), ev.get("aid", 0), ev.get("li", -1))
    return _REC.pack(
        e, ev.get("rej", 0), ev.get("sh", 0), 0, ev.get("act", 0),
        ev.get("b", 0), ev.get("i", -1), ev.get("seq", 0),
        ev.get("ts", 0), ev.get("off", -1), ev.get("oid", 0),
        ev.get("aid", 0), ev.get("sid", 0), ev.get("px", 0),
        ev.get("qty", 0), ev.get("moid", 0), ev.get("maid", 0))


def _decode(buf: bytes) -> dict:
    (e, rej, sh, _pad, act, b, i, seq, ts, off, oid, aid, sid, px, qty,
     moid, maid) = _REC.unpack(buf)
    name = ETYPES[e]
    ev = {"e": name, "seq": seq, "ts": ts, "b": b, "sh": sh}
    if name == "win":
        ev.update(kind=_WIN_KINDS[rej], t0=oid, t1=aid)
        return ev
    if name == "lat":
        ev.update(off=off, oid=oid, in_us=aid, plan_us=sid,
                  dev_us=px, prod_us=qty, e2e_us=moid)
        return ev
    if name == "span":
        ev.update(kind=SPAN_KINDS[rej], g=act, off=off, oid=oid,
                  tid=aid, ptid=sid, t0=px, t1=qty, aid=moid, li=maid)
        return ev
    ev.update(i=i, off=off)
    if name == "drop":
        ev["rej"] = rej
        return ev
    ev.update(act=act, oid=oid, aid=aid, sid=sid, px=px, qty=qty)
    if name == "fill":
        ev.update(moid=moid, maid=maid)
    if name == "reject":
        ev["rej"] = rej
    return ev


# ---------------------------------------------------------------------------
# readers


def iter_events(path: str) -> Iterator[dict]:
    """Stream one journal file's events (format auto-detected). A torn
    trailing record (crash mid-write) is ignored, matching the writer's
    resume behavior."""
    with open(path, "rb") as f:
        head = f.read(len(MAGIC))
        if head == MAGIC:
            while True:
                rec = f.read(REC_SIZE)
                if len(rec) < REC_SIZE:
                    return
                yield _decode(rec)
        f.seek(0)
        for ln in f:
            if not ln.endswith(b"\n"):
                return          # torn tail
            ln = ln.strip()
            if ln:
                yield json.loads(ln)


def read_events(path: str, include_rotated: bool = True) -> List[dict]:
    """All events, oldest first. With include_rotated, rotated
    predecessors (`<path>.N`, N descending = oldest first) are read
    before the live file."""
    paths = []
    if include_rotated:
        n = 1
        while os.path.exists(f"{path}.{n}"):
            n += 1
        paths = [f"{path}.{k}" for k in range(n - 1, 0, -1)]
    paths.append(path)
    out: List[dict] = []
    for p in paths:
        if os.path.exists(p):
            out.extend(iter_events(p))
    return out


# ---------------------------------------------------------------------------
# writer


class Journal:
    """Append-only lifecycle journal with rotation, fsync policy, tail
    resume and an optional background writer thread.

    fmt: "jsonl" | "binary" | None (None = by extension: .bin/.kmej ->
    binary). fsync: "off" (OS buffering; flushed on close) or "batch"
    (fsync after every record_batch — bounds loss to one batch).
    rotate_bytes: start a new file once the live one exceeds this
    (logrotate-style shift: path -> path.1 -> path.2 ...). resume: scan
    the existing file's tail and continue seq/batch numbering
    monotonically (a torn binary tail is truncated; a torn jsonl line
    is dropped). async_write: derive + encode + write on a FIFO worker
    thread so the serving hot path only enqueues (flush() drains).

    Observers (`observers.append(fn)`) are called as fn(events,
    lines_per_msg) after each batch commits — the invariant auditor
    subscribes here and thus runs on the writer thread in async mode.
    """

    def __init__(self, path: str, fmt: Optional[str] = None,
                 rotate_bytes: Optional[int] = None,
                 fsync: str = "off", shard: int = 0,
                 resume: bool = True, async_write: bool = False,
                 clock=None, rotate_keep: Optional[int] = None,
                 retention_guard=None) -> None:
        if fmt is None:
            fmt = ("binary" if path.endswith((".bin", ".kmej"))
                   else "jsonl")
        if fmt not in ("jsonl", "binary"):
            raise ValueError(f"unknown journal format {fmt!r}")
        if fsync not in ("off", "batch"):
            raise ValueError(f"unknown fsync policy {fsync!r}")
        self.path = path
        self.fmt = fmt
        self.rotate_bytes = rotate_bytes
        # bound how many rotated segments are retained (None = keep
        # all, the historical behavior). retention_guard, when set, is
        # a zero-arg callable returning the oldest input offset a
        # restore could still need (the oldest retained snapshot's
        # offset, runtime/checkpoint.oldest_retained_offset) — a
        # segment containing any event at or past that offset is NEVER
        # pruned, whatever rotate_keep says: a standby restoring the
        # oldest snapshot must still replay the journal to the tip.
        self.rotate_keep = rotate_keep
        self.retention_guard = retention_guard
        self.fsync = fsync
        self.shard = shard
        self.observers: List = []
        self._clock = clock or (lambda: __import__("time").time_ns()
                                // 1000)
        self._seq = 0
        self._batch = 0
        self._lock = threading.Lock()
        # writer-lag instrumentation (heartbeat gauges): payload bytes
        # enqueued but not yet committed (a wedged async worker shows
        # up here long before the disk fills), and the highest input
        # offset a committed event carried
        self._lag_lock = threading.Lock()
        self._pending_bytes = 0
        self.last_offset = -1
        if resume and os.path.exists(path) and os.path.getsize(path):
            self._resume_tail()
        self._f = open(path, "ab")
        if self.fmt == "binary" and self._f.tell() == 0:
            self._f.write(MAGIC)
        self._q = None
        self._worker = None
        if async_write:
            import queue

            self._q = queue.Queue()
            self._worker = threading.Thread(target=self._drain,
                                            daemon=True)
            self._worker.start()

    # -- resume ---------------------------------------------------------

    def _resume_tail(self) -> None:
        size = os.path.getsize(self.path)
        with open(self.path, "r+b") as f:
            head = f.read(len(MAGIC))
            if head == MAGIC:
                body = size - len(MAGIC)
                torn = body % REC_SIZE
                if torn:
                    f.truncate(size - torn)
                    body -= torn
                if body:
                    f.seek(len(MAGIC) + body - REC_SIZE)
                    last = _decode(f.read(REC_SIZE))
                    self._seq = last["seq"] + 1
                    self._batch = last["b"] + 1
                return
            # jsonl: drop a torn final line, read the last complete one
            f.seek(0)
            data = f.read()
            if not data.endswith(b"\n"):
                cut = data.rfind(b"\n") + 1
                f.truncate(cut)
                data = data[:cut]
            lines = data.splitlines()
            if lines:
                last = json.loads(lines[-1])
                self._seq = last.get("seq", -1) + 1
                self._batch = last.get("b", -1) + 1

    # -- hot-path API ---------------------------------------------------

    def record_batch(self, lines_per_msg, reasons=None, offsets=None,
                     drops=()) -> None:
        """Journal one processed batch. In async mode this only
        enqueues; derivation, encoding, the write and the observer
        fan-out all happen on the worker thread in FIFO order (so seq
        and batch numbering stay deterministic)."""
        job = ("batch", lines_per_msg, reasons, offsets, tuple(drops))
        # payload estimate for lag_bytes: the wire lines dominate the
        # encoded size in either framing
        est = sum(len(ln) + 1 for lines in lines_per_msg
                  for ln in lines)
        self._submit(job, est)

    def record_window(self, kind: str, t0: float, t1: float,
                      batch: Optional[int] = None) -> None:
        """Record one pipeline overlap window (submit or collect):
        [t0, t1] seconds on any monotonic clock, stored as integer
        microseconds. `batch` tags the pipeline batch index."""
        job = ("win", kind, int(t0 * 1e6), int(t1 * 1e6),
               -1 if batch is None else batch)
        self._submit(job, REC_SIZE)

    def record_latency(self, entries: Sequence[dict],
                       batch: Optional[int] = None) -> None:
        """Append per-order stage-attribution stamps ("lat" events).
        Each entry carries off/oid plus in_us/plan_us/dev_us/prod_us/
        e2e_us microsecond durations (see module docstring). Dropped
        from the canonical form, so `kme-trace --verify` still
        byte-agrees with the oracle replay."""
        job = ("lat", tuple(dict(e) for e in entries),
               -1 if batch is None else batch)
        self._submit(job, REC_SIZE * len(entries))

    def record_spans(self, entries: Sequence[dict],
                     batch: Optional[int] = None) -> None:
        """Append distributed-tracing "span" events (kind/off/oid/aid/
        tid/ptid/t0/t1/g/li — see SPAN_KINDS and telemetry/dtrace.py).
        Like "lat", spans are excluded from the canonical form: the
        lifecycle stream `kme-trace --verify` replays is untouched."""
        job = ("span", tuple(dict(e) for e in entries),
               -1 if batch is None else batch)
        self._submit(job, REC_SIZE * len(entries))

    def append_events(self, events: List[dict]) -> None:
        """Stamp + append pre-derived events (one batch's worth)."""
        job = ("events", events)
        self._submit(job, REC_SIZE * len(events))

    # -- worker / commit ------------------------------------------------

    def _submit(self, job, est: int) -> None:
        with self._lag_lock:
            self._pending_bytes += est
        if self._q is not None:
            self._q.put((job, est))
        else:
            self._commit_job(job, est)

    def _commit_job(self, job, est: int) -> None:
        try:
            self._commit(job)
        finally:
            with self._lag_lock:
                self._pending_bytes -= est

    @property
    def lag_bytes(self) -> int:
        """Estimated payload bytes enqueued but not yet written."""
        with self._lag_lock:
            return self._pending_bytes

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            job, est = item
            try:
                self._commit_job(job, est)
            except Exception as e:  # pragma: no cover - defensive
                import sys

                print(f"kme journal: write failed ({e})",
                      file=sys.stderr)

    def _commit(self, job) -> None:
        with self._lock:
            ts = self._clock()
            lines = None
            if job[0] == "batch":
                _, lines, reasons, offsets, drops = job
                events = batch_events(lines, reasons, offsets, drops)
                b = self._batch
                self._batch += 1
            elif job[0] == "win":
                _, kind, t0, t1, b = job
                events = [{"e": "win", "kind": kind, "t0": t0,
                           "t1": t1}]
            elif job[0] == "lat":
                _, entries, b = job
                events = [dict(ev, e="lat") for ev in entries]
            elif job[0] == "span":
                _, entries, b = job
                events = [dict(ev, e="span") for ev in entries]
            else:
                _, events = job
                b = self._batch
                self._batch += 1
            for ev in events:
                ev.setdefault("b", b)
                ev["seq"] = self._seq
                self._seq += 1
                ev["ts"] = ts
                ev["sh"] = self.shard
            self._write(events)
            for ev in events:
                off = ev.get("off", -1)
                if off is not None and off > self.last_offset:
                    self.last_offset = off
        for obs in self.observers:
            obs(events, lines)

    def _write(self, events: List[dict]) -> None:
        if self.fmt == "binary":
            blob = b"".join(_encode(ev) for ev in events)
        else:
            blob = "".join(
                json.dumps(ev, sort_keys=True,
                           separators=(",", ":")) + "\n"
                for ev in events).encode()
        from kme_tpu import faults

        if faults.should("journal.torn"):
            # kme-chaos: crash mid-append — half the batch's bytes reach
            # the file, then the process dies with no cleanup. The next
            # incarnation's _resume_tail must truncate/drop the torn
            # record (appending after it would corrupt the interior).
            import signal as _sig

            self._f.write(blob[:max(1, len(blob) // 2)])
            self._f.flush()
            os.fsync(self._f.fileno())
            os.kill(os.getpid(), _sig.SIGKILL)
        self._f.write(blob)
        if self.fsync == "batch":
            self._f.flush()
            os.fsync(self._f.fileno())
        if self.rotate_bytes and self._f.tell() >= self.rotate_bytes:
            self._rotate()

    def _rotate(self) -> None:
        self._f.flush()
        self._f.close()
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        for k in range(n, 0, -1):
            src = self.path if k == 1 else f"{self.path}.{k - 1}"
            os.replace(src, f"{self.path}.{k}")
        self._prune_rotated()
        self._f = open(self.path, "ab")
        if self.fmt == "binary":
            self._f.write(MAGIC)

    def _prune_rotated(self) -> None:
        """Unlink rotated segments beyond `rotate_keep`, oldest (largest
        .N) first, but never one the retention guard still needs — and
        stop at the first still-needed segment, since everything newer
        is needed too. A guard that errors or reports no snapshot keeps
        everything (fail safe: losing disk to journals beats losing the
        ability to replay)."""
        if not self.rotate_keep:
            return
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        if n - 1 <= self.rotate_keep:
            return
        guard = None
        if self.retention_guard is not None:
            try:
                guard = self.retention_guard()
            except Exception:
                return
            if guard is None:
                return      # no snapshot yet: every event may replay
        for k in range(n - 1, self.rotate_keep, -1):
            seg = f"{self.path}.{k}"
            if guard is not None:
                try:
                    newest = max((int(ev.get("off", -1))
                                  for ev in iter_events(seg)), default=-1)
                except (OSError, ValueError, TypeError):
                    return
                if newest >= guard:
                    return
            try:
                os.unlink(seg)
            except OSError:
                return

    # -- lifecycle ------------------------------------------------------

    def flush(self) -> None:
        """Drain the async queue (if any) and flush OS buffers."""
        if self._q is not None:
            # the worker holds _lock while committing, so empty queue +
            # an acquired lock below means the last job has landed
            import time

            while not self._q.empty():
                time.sleep(0.002)
        with self._lock:
            self._f.flush()

    def close(self) -> None:
        if self._q is not None and self._worker is not None:
            self.flush()
            self._q.put(None)
            self._worker.join(timeout=5)
            self._q = None
        with self._lock:
            self._f.flush()
            self._f.close()

    @property
    def next_seq(self) -> int:
        return self._seq

    # -- at-least-once resume dedup ------------------------------------

    def rewind_to_offset(self, offset: int) -> None:
        """Drop journaled events whose input offset is >= `offset` (the
        resume point): the service replays the MatchIn tail from the
        snapshot offset (at-least-once), and without this the replayed
        batches would journal twice. Standalone events (off == -1:
        windows, drops of unoffsetted records) are kept. Rewrites the
        live file atomically; rotated files are assumed older than any
        replayable tail (rotation cadence >> checkpoint cadence)."""
        if not os.path.exists(self.path):
            return
        with self._lock:
            self._f.flush()     # buffered appends must be on disk first
            kept = [ev for ev in iter_events(self.path)
                    if ev.get("off", -1) < offset]
            self._f.close()
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                if self.fmt == "binary":
                    f.write(MAGIC)
                    f.write(b"".join(_encode(ev) for ev in kept))
                else:
                    f.write("".join(
                        json.dumps(ev, sort_keys=True,
                                   separators=(",", ":")) + "\n"
                        for ev in kept).encode())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            if kept:
                self._seq = max(ev["seq"] for ev in kept) + 1
                self._batch = max(ev.get("b", -1) for ev in kept) + 1
                self.last_offset = max(ev.get("off", -1) for ev in kept)
            else:
                self._seq = self._batch = 0
                self.last_offset = -1
            self._f = open(self.path, "ab")


# ---------------------------------------------------------------------------
# pipeline overlap measurement (bench satellite: BENCH_r05 reported
# pipeline_speedup 0.93 from two-size differencing; this measures the
# actual submit/collect overlap from recorded windows instead)


def measured_overlap_s(windows: Iterable[Tuple[str, int, float, float]]
                       ) -> float:
    """Measured host/device overlap from (kind, batch, t0, t1) windows:
    the time collect (host fetch+recon of batch N) spent while another
    batch was submitted-but-not-collected (its device execution span is
    bounded by [submit_end, collect_start]). This is the wall time the
    pipeline actually hid, as opposed to the t_serial/t_pipe ratio
    which also carries run-to-run tunnel variance."""
    subs: Dict[int, Tuple[float, float]] = {}
    cols: Dict[int, Tuple[float, float]] = {}
    for kind, b, t0, t1 in windows:
        (subs if kind == "submit" else cols)[b] = (t0, t1)
    inflight = {b: (subs[b][1], cols[b][0])
                for b in subs if b in cols and cols[b][0] > subs[b][1]}
    total = 0.0
    for b, (c0, c1) in cols.items():
        cover = 0.0
        for b2, (s1, k0) in inflight.items():
            if b2 != b:
                cover += max(0.0, min(c1, k0) - max(c0, s1))
        total += min(cover, c1 - c0)
    return total


# ---------------------------------------------------------------------------
# lifecycle reconstruction (kme-trace)


def order_lifecycle(events: Iterable[dict], oid: int) -> List[dict]:
    """Every event touching order `oid` — as taker (oid) or as resting
    maker (moid) — in journal order."""
    return [ev for ev in events
            if ev.get("oid") == oid or ev.get("moid") == oid]


def account_history(events: Iterable[dict], aid: int) -> List[dict]:
    """Every event touching account `aid` (incl. maker-side fills)."""
    return [ev for ev in events
            if ev.get("aid") == aid or ev.get("maid") == aid]


def lifecycle_summary(events: List[dict], oid: int) -> dict:
    """Terminal state of one order from its lifecycle events."""
    sub = next((e for e in events if e["e"] == "submit"
                and e.get("oid") == oid), None)
    filled = sum(e["qty"] for e in events if e["e"] == "fill"
                 and (e.get("oid") == oid or e.get("moid") == oid))
    rested = next((e["qty"] for e in events if e["e"] == "rest"
                   and e.get("oid") == oid), None)
    state = "unknown"
    if any(e["e"] == "reject" and e.get("oid") == oid
           and e.get("act") in (op.BUY, op.SELL) for e in events):
        # a rejected CANCEL (act=4) says nothing about the order itself
        state = "rejected"
    elif any(e["e"] == "cancel" and e.get("oid") == oid
             for e in events):
        state = "cancelled"
    elif sub is not None and sub.get("act") in (op.BUY, op.SELL):
        taker_fill = sum(e["qty"] for e in events if e["e"] == "fill"
                         and e.get("oid") == oid)
        maker_fill = sum(e["qty"] for e in events if e["e"] == "fill"
                         and e.get("moid") == oid)
        if rested is not None:
            state = ("resting" if maker_fill < rested
                     else "filled")
        else:
            state = ("filled" if sub["qty"] == taker_fill
                     else "accepted")
    elif sub is not None:
        state = "done"
    return {"oid": oid, "state": state, "filled": filled,
            "rested": rested,
            "events": len(events)}
