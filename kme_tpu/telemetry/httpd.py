"""Tiny stdlib HTTP surface for a Registry.

`kme-serve --metrics-port N` starts this; GET /metrics returns
Prometheus text exposition (0.0.4), GET /metrics.json the JSON
snapshot. The handler only reads registry snapshots (taken under the
registry lock) — it never touches device arrays, so it is safe beside
a main thread that donates buffers into jitted steps.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def start_metrics_server(registry, port: int, host: str = "0.0.0.0"):
    """Serve `registry` on (host, port) from a daemon thread.

    Returns the ThreadingHTTPServer (port=0 picks a free port —
    read it back from server.server_address; call shutdown() to stop).
    """

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API name)
            path = self.path.split("?", 1)[0]
            try:
                if path in ("/metrics", "/"):
                    body = registry.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/metrics.json":
                    body = registry.to_json().encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
            except Exception as e:   # a broken metric must not 200-empty
                self.send_error(500, explain=str(e))
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # scrapes are not news
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    server.daemon_threads = True
    thread = threading.Thread(
        target=server.serve_forever, name="kme-metrics-http", daemon=True)
    thread.start()
    return server
