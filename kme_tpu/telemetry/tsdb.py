"""On-disk metrics time-series store — the cluster's memory.

Every observability surface built before this module is point-in-time:
a /metrics scrape, a heartbeat JSON, a kme-top frame all evaporate the
moment they are read. The TSDB keeps a durable history instead: each
service appends one flattened Registry snapshot per heartbeat into an
append-only file of FIXED-WIDTH binary records, on the same framing
discipline as the lifecycle journal (telemetry/journal.py):

- a magic header per segment, fixed 64-byte records after it — a torn
  tail after a crash is `(size - len(MAGIC)) % REC_SIZE` bytes that the
  next open truncates away (never a resync scan);
- logrotate-style rotation (`path -> path.1 -> path.2 ...`) once the
  live segment exceeds `rotate_bytes`, with a `<segment>.sha256` digest
  sidecar written when a segment is finalized;
- retention pruning beyond `retain` rotated segments, oldest first,
  verifying the recorded digest on the way out (a mismatch is counted
  and reported — evidence of on-disk corruption — but the segment is
  still pruned: retention is a space bound, not an audit);
- an fsync policy (`off` = OS buffering, `batch` = fsync after every
  appended snapshot).

Records come in two kinds. NAME records intern a metric name to a
32-bit id once per segment (so 48-byte names never repeat per sample);
SAMPLE records carry `(name_id, sample_seq, ts_us, value)`. Every
segment is self-contained: rotation resets the intern table, so a
reader never needs a sibling segment to resolve names.

Replay dedup mirrors the broker's `(epoch, out_seq)` discipline: every
appended snapshot carries a monotonic `sample_seq`. The store remembers
the highest sequence it has committed (rescanned from the tail on
open), and `append_snapshot` drops any snapshot at or below it — so a
service that crash-resumes from a checkpoint and replays heartbeats it
already wrote cannot double-count history. Writers without a durable
cursor of their own (standby, feed, clients) seed from `last_seq + 1`.

Layout: one store directory holds one live segment per SOURCE
(`<source>.kmet`), so a serve leader, its standby, the feed tier and
load-generating clients can share a directory without write contention.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

MAGIC = b"KMETSDB1"
REC_SIZE = 64

# kind(u8) pad flags(u16) name_id(u32) sample_seq(u64) + payload
_NAME = struct.Struct("<BxHIQ48s")           # payload: utf-8 name
_SAMP = struct.Struct("<BxHIQqd32x")         # payload: ts_us, value
assert _NAME.size == REC_SIZE and _SAMP.size == REC_SIZE

KIND_NAME = 1
KIND_SAMPLE = 2

NAME_MAX = 48
SUFFIX = ".kmet"


def _clip_name(name: str) -> str:
    """Deterministic 48-byte interning key: long names keep a prefix
    plus a short content hash so two distinct long names never
    collide after clipping (and re-clipping is stable across runs)."""
    raw = name.encode("utf-8")
    if len(raw) <= NAME_MAX:
        return name
    tag = hashlib.sha256(raw).hexdigest()[:8]
    head = raw[:NAME_MAX - 9].decode("utf-8", "ignore")
    return f"{head}~{tag}"


def flatten_snapshot(snap: dict) -> List[Tuple[str, float]]:
    """Registry.snapshot() -> flat numeric (name, value) series.

    Counters and numeric gauges pass through under their own names;
    latency families explode into the sub-series kme-prof diffs
    (`lat_e2e.p99_ms` etc.); plain histograms keep count and sum. The
    bucket vectors stay out — the TSDB answers "what moved", the live
    snapshot answers "what is the exact distribution right now"."""
    out: List[Tuple[str, float]] = []
    for name, v in (snap.get("counters") or {}).items():
        if isinstance(v, (int, float)):
            out.append((name, float(v)))
    for name, v in (snap.get("gauges") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out.append((name, float(v)))
    for name, lat in (snap.get("latencies") or {}).items():
        if not isinstance(lat, dict):
            continue
        for sub in ("count", "sum_s", "p50_ms", "p90_ms", "p99_ms",
                    "p999_ms"):
            v = lat.get(sub)
            if isinstance(v, (int, float)):
                out.append((f"{name}.{sub}", float(v)))
    for name, h in (snap.get("histograms") or {}).items():
        if isinstance(h, dict):
            for sub in ("count", "sum"):
                v = h.get(sub)
                if isinstance(v, (int, float)):
                    out.append((f"{name}.{sub}", float(v)))
    return out


class TSDB:
    """Append-only per-source metrics history in `directory`.

    Parameters
    ----------
    directory : the shared store root (created if missing)
    source : which service this writer is (`serve`, `standby`, `feed`,
        `front`, `loadgen`, `consume`, ...) — names the segment file
    rotate_bytes : rotate the live segment past this size (default 4 MiB)
    retain : rotated segments kept per source (default 8)
    fsync : "off" | "batch" — batch fsyncs after every snapshot
    """

    def __init__(self, directory: str, source: str = "serve",
                 rotate_bytes: int = 4 << 20, retain: int = 8,
                 fsync: str = "off") -> None:
        if fsync not in ("off", "batch"):
            raise ValueError(f"unknown fsync policy {fsync!r}")
        if any(ch in source for ch in "/\\"):
            raise ValueError(f"source {source!r} must be a bare name")
        self.directory = directory
        self.source = source
        self.rotate_bytes = max(REC_SIZE * 4, int(rotate_bytes))
        self.retain = max(1, int(retain))
        self.fsync = fsync
        self.path = os.path.join(directory, source + SUFFIX)
        self.last_seq = -1          # highest committed sample_seq
        self.dup_skipped = 0        # snapshots dropped by the dedup
        self.digest_mismatches = 0  # pruned segments failing sha256
        self._names: Dict[str, int] = {}   # live-segment intern table
        self._torn_bytes = 0
        os.makedirs(directory, exist_ok=True)
        self._fh = self._open_live()

    # -- segment lifecycle ---------------------------------------------

    def _open_live(self):
        """Open (or adopt) the live segment: verify the magic, truncate
        a torn tail to the last whole record, and rebuild the intern
        table + dedup cursor from the surviving records."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            size = -1
        if size < len(MAGIC):
            if size >= 0:
                # shorter than the magic: unrecoverable stub from a
                # crash inside the header write — start the segment over
                os.unlink(self.path)
            fh = open(self.path, "ab")
            fh.write(MAGIC)
            fh.flush()
            self._seed_seq_from_rotated()
            return fh
        with open(self.path, "rb") as rd:
            head = rd.read(len(MAGIC))
            if head != MAGIC:
                raise ValueError(
                    f"{self.path}: bad magic {head!r} — not a TSDB "
                    f"segment")
            body = size - len(MAGIC)
            torn = body % REC_SIZE
            for _off, kind, name_id, seq, payload in _iter_records(rd):
                if kind == KIND_NAME:
                    nm = payload[0]
                    self._names[nm] = name_id
                elif kind == KIND_SAMPLE:
                    self.last_seq = max(self.last_seq, seq)
        if torn:
            self._torn_bytes = torn
            with open(self.path, "r+b") as t:
                t.truncate(size - torn)
        if self.last_seq < 0:
            self._seed_seq_from_rotated()
        return open(self.path, "ab")

    def _seed_seq_from_rotated(self) -> None:
        """A fresh/empty live segment right after rotation must not
        reset the dedup cursor — adopt the newest rotated segment's
        high-water mark."""
        newest = self.path + ".1"
        if not os.path.exists(newest):
            return
        try:
            for _ts, seq, _name, _v in iter_samples(newest):
                self.last_seq = max(self.last_seq, seq)
        except (OSError, ValueError):
            pass

    def _rotate(self) -> None:
        """path -> path.1 -> path.2 ... then finalize the shifted-out
        segment with a sha256 sidecar and prune beyond `retain`."""
        self._fh.close()
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        for i in range(n, 1, -1):
            os.replace(f"{self.path}.{i - 1}", f"{self.path}.{i}")
            side = f"{self.path}.{i - 1}.sha256"
            if os.path.exists(side):
                os.replace(side, f"{self.path}.{i}.sha256")
        os.replace(self.path, f"{self.path}.1")
        _write_digest(f"{self.path}.1")
        self._prune()
        self._names = {}      # segments are self-contained
        fh = open(self.path, "ab")
        fh.write(MAGIC)
        fh.flush()
        self._fh = fh

    def _prune(self) -> None:
        """Unlink rotated segments beyond `retain`, oldest (highest .N)
        first, verifying the recorded digest on the way out."""
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        for i in range(n - 1, self.retain, -1):
            seg = f"{self.path}.{i}"
            if not _verify_digest(seg):
                self.digest_mismatches += 1
            for p in (seg, seg + ".sha256"):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    # -- writing --------------------------------------------------------

    def _intern(self, name: str) -> int:
        nid = self._names.get(name)
        if nid is None:
            nid = len(self._names) + 1
            self._names[name] = nid
            self._fh.write(_NAME.pack(KIND_NAME, 0, nid, 0,
                                      name.encode("utf-8")))
        return nid

    def append_snapshot(self, snap: dict, sample_seq: int,
                        ts_us: Optional[int] = None) -> bool:
        """Append one flattened Registry snapshot under `sample_seq`.

        Returns False (and counts `dup_skipped`) when the sequence is
        at or below the committed high-water mark — the crash-resume
        replay dedup. The whole snapshot commits or none of it does
        from the reader's point of view: a torn write truncates away on
        the next open, and `last_seq` only advances after the OS
        accepted every record."""
        seq = int(sample_seq)
        if seq <= self.last_seq:
            self.dup_skipped += 1
            return False
        if ts_us is None:
            ts_us = time.time_ns() // 1000
        for name, value in flatten_snapshot(snap):
            nid = self._intern(_clip_name(name))
            self._fh.write(_SAMP.pack(KIND_SAMPLE, 0, nid, seq,
                                      int(ts_us), float(value)))
        self._fh.flush()
        if self.fsync == "batch":
            os.fsync(self._fh.fileno())
        self.last_seq = seq
        if self._fh.tell() >= self.rotate_bytes:
            self._rotate()
        return True

    def append_values(self, values: dict, sample_seq: int,
                      ts_us: Optional[int] = None) -> bool:
        """Append a plain {name: number} dict (client-side writers that
        have no Registry) under the same dedup discipline."""
        return self.append_snapshot(
            {"gauges": {k: v for k, v in values.items()
                        if isinstance(v, (int, float))
                        and not isinstance(v, bool)}},
            sample_seq, ts_us=ts_us)

    def next_seq(self) -> int:
        """The next unused sample_seq — writers without their own
        durable cursor (standby/feed/clients) call this per sample."""
        return self.last_seq + 1

    def flush(self) -> None:
        self._fh.flush()

    def close(self) -> None:
        try:
            self._fh.flush()
            self._fh.close()
        except (OSError, ValueError):
            pass

    def segments(self) -> List[str]:
        """Readable segment paths, oldest first (live file last)."""
        return _segments(self.path)


# -- readers ----------------------------------------------------------------


def _iter_records(fh) -> Iterator[tuple]:
    """(offset, kind, name_id, sample_seq, payload) per whole record;
    a torn tail (short read) ends the iteration silently."""
    off = fh.tell()
    while True:
        buf = fh.read(REC_SIZE)
        if len(buf) < REC_SIZE:
            return
        kind = buf[0]
        if kind == KIND_NAME:
            k, _fl, nid, seq, raw = _NAME.unpack(buf)
            name = raw.rstrip(b"\x00").decode("utf-8", "replace")
            yield off, k, nid, seq, (name,)
        elif kind == KIND_SAMPLE:
            k, _fl, nid, seq, ts_us, value = _SAMP.unpack(buf)
            yield off, k, nid, seq, (ts_us, value)
        # unknown kinds skip (additive forward-compat)
        off += REC_SIZE


def iter_samples(path: str) -> Iterator[Tuple[int, int, str, float]]:
    """(ts_us, sample_seq, name, value) from ONE segment file, in
    append order, resolving the segment's own intern table."""
    names: Dict[int, str] = {}
    with open(path, "rb") as fh:
        if fh.read(len(MAGIC)) != MAGIC:
            raise ValueError(f"{path}: not a TSDB segment")
        for _off, kind, nid, seq, payload in _iter_records(fh):
            if kind == KIND_NAME:
                names[nid] = payload[0]
            elif kind == KIND_SAMPLE:
                ts_us, value = payload
                yield ts_us, seq, names.get(nid, f"#{nid}"), value


def _segments(live_path: str) -> List[str]:
    segs = []
    n = 1
    while os.path.exists(f"{live_path}.{n}"):
        segs.append(f"{live_path}.{n}")
        n += 1
    segs.reverse()              # oldest (highest .N) first
    if os.path.exists(live_path):
        segs.append(live_path)
    return segs


def read_samples(store: str, source: Optional[str] = None,
                 ) -> Iterator[Tuple[str, int, int, str, float]]:
    """(source, ts_us, sample_seq, name, value) across a store
    directory (every source, or one), rotated segments first. Accepts
    a bare segment path too."""
    if os.path.isfile(store):
        src = os.path.basename(store).split(SUFFIX)[0]
        for ts, seq, name, v in iter_samples(store):
            yield src, ts, seq, name, v
        return
    try:
        entries = sorted(os.listdir(store))
    except OSError:
        return
    for ent in entries:
        if not ent.endswith(SUFFIX):
            continue
        src = ent[:-len(SUFFIX)]
        if source is not None and src != source:
            continue
        for seg in _segments(os.path.join(store, ent)):
            try:
                for ts, seq, name, v in iter_samples(seg):
                    yield src, ts, seq, name, v
            except (OSError, ValueError):
                continue    # unreadable sibling never hides the rest


def query(store: str, names: Optional[Sequence[str]] = None,
          source: Optional[str] = None, t0_us: Optional[int] = None,
          t1_us: Optional[int] = None) -> Dict[str, List[Tuple[int, float]]]:
    """{name: [(ts_us, value), ...]} filtered by source/name/window.
    Duplicate (seq, name) points (pre-dedup history from old stores)
    keep the first occurrence."""
    want = set(names) if names else None
    out: Dict[str, List[Tuple[int, float]]] = {}
    seen = set()
    for src, ts, seq, name, v in read_samples(store, source=source):
        if want is not None and name not in want:
            continue
        if t0_us is not None and ts < t0_us:
            continue
        if t1_us is not None and ts > t1_us:
            continue
        key = (src, seq, name)
        if key in seen:
            continue
        seen.add(key)
        out.setdefault(name, []).append((ts, v))
    for series in out.values():
        series.sort(key=lambda p: p[0])
    return out


def window_summary(store: str, t0_us: Optional[int] = None,
                   t1_us: Optional[int] = None,
                   source: Optional[str] = None) -> Dict[str, float]:
    """{name: representative value} over a window — the diff substrate.

    Monotonic series (counters, `.count`/`.sum*` sub-series) summarize
    as their in-window DELTA (last - first) so two windows compare as
    rates; everything else (gauges, quantile series) as the mean."""
    series = query(store, source=source, t0_us=t0_us, t1_us=t1_us)
    out: Dict[str, float] = {}
    for name, pts in series.items():
        vals = [v for _t, v in pts]
        if not vals:
            continue
        if _is_monotonic_name(name):
            out[name] = vals[-1] - vals[0] if len(vals) > 1 else vals[0]
        else:
            out[name] = sum(vals) / len(vals)
    return out


def _is_monotonic_name(name: str) -> bool:
    return (name.endswith("_total") or name.endswith(".count")
            or name.endswith(".sum") or name.endswith(".sum_s")
            or name.startswith("service_"))


# -- digest sidecars --------------------------------------------------------


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_digest(seg: str) -> None:
    doc = {"segment": os.path.basename(seg),
           "sha256": _sha256_file(seg),
           "bytes": os.path.getsize(seg)}
    tmp = seg + ".sha256.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, seg + ".sha256")


def _verify_digest(seg: str) -> bool:
    """True when the sidecar digest matches (or no sidecar exists —
    pre-digest segments are not treated as corrupt)."""
    side = seg + ".sha256"
    try:
        with open(side) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return True
    try:
        return _sha256_file(seg) == doc.get("sha256")
    except OSError:
        return False


def verify_store(store: str) -> dict:
    """Digest audit across every finalized segment in a store dir:
    {"segments": n, "verified": n_ok, "mismatched": [paths]}."""
    mismatched = []
    n = 0
    try:
        entries = sorted(os.listdir(store))
    except OSError:
        entries = []
    for ent in entries:
        if SUFFIX + "." not in ent or ent.endswith(".sha256"):
            continue
        seg = os.path.join(store, ent)
        n += 1
        if not _verify_digest(seg):
            mismatched.append(seg)
    return {"segments": n, "verified": n - len(mismatched),
            "mismatched": mismatched}
