"""Parallelism: mesh construction and the shard_map-sharded engine step.

The reference scales by Kafka partition rebalancing (SURVEY.md §2.3);
here the symbol axis is sharded over a jax.sharding.Mesh, account state
is replicated with exact psum delta-merges, and collectives ride ICI.
"""
