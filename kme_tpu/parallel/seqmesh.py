"""Multi-chip SEQ fleet: a symbol-sharded set of sequential mega-kernels
under ONE shard_map, bit-exact vs single-chip serial replay.

The flagship seq engine (kme_tpu/engine/seq.py) is strictly serial on
one chip. Scale-out follows the reference's partition model (the topic
is partitioned and Streams instances split partitions — topic.js:18,
KProcessor.java:59-60), TPU-first: lanes (books, positions, seq
counters) are SHARDED over the 'symbol' mesh axis — each device runs
its own seq kernel over its own message subsequence — and balances are
REPLICATED with exact psum delta-merges at window boundaries.

Why this is bit-exact (the window invariant): within one window every
ACCOUNT's messages live on a single shard, so an account's balance
evolves exactly as in serial replay (balance writes are always to the
acting account: taker debit/credit, transfer, cancel release; maker
fills credit price 0 and touch only lane-local position state). The
host planner (plan_windows) closes a window whenever a message's
account is already bound to a different shard, whenever a shard's
window capacity fills, and around barriers (PAYOUT/REMOVE credit many
accounts, so each runs alone in its own window). At a window boundary
each shard contributes an int64 balance delta with at most one nonzero
contributor per account — psum is exact, including Java-long wrap.

The sticky error plane is pmax-merged (any shard's envelope error
surfaces globally; WHICH error wins when several shards fail in one
window is unspecified, unlike the serial engine's first-error rule —
the error path aborts the stream either way).

ELASTIC PLACEMENT (this round): lanes are no longer pinned to shards
by the static `global_lane // S_local` layout. A placement table
(`_perm`: global lane -> global slot; shard = slot // S_local) starts
as the identity — byte-identical to the old static layout — and a
per-lane load EWMA drives BETWEEN-BATCH migrations of hot lanes to
underloaded shards (plan_rebalance decides, _migrate permutes the
sharded lane axis of the state pytree through the engine's canonical
codec). Correctness is placement-INDEPENDENT: the engine is a
deterministic state machine, so any symbol->shard assignment that
preserves the global application order and the per-window
account-disjointness invariant above yields byte-identical MatchOut —
which is what lets the planner rebalance aggressively and the tests
gate on oracle parity WITH migrations observed
(tests/test_shard_elastic.py, kme-bench --suite shards).

PER-CHIP ASYNC DISPATCH (this round): the shard_map scan above is
LOCKSTEP — one dispatch, every shard waits for the slowest shard at
every window boundary, and per-chip walls are unmeasurable from the
host. `dispatch="async"` (the default wherever every mesh device is
locally addressable) breaks that: each shard gets its OWN submission
queue of window segments, dispatched as independent per-device scan
calls that drain at the shard's own rate. The global psum barrier is
replaced by the minimal dependency set the window invariant implies:
when an account's messages move from shard A to shard B between
windows, B's queue takes a point-to-point dependency on A — the host
fetches A's (tiny) balance planes as of that window and patches ONLY
the moved accounts into B's planes with an on-device scatter; all
other shards run ahead untouched. Barriers (PAYOUT/REMOVE credit many
accounts) and the batch-end collect are the only FULL merges: the
host selects each account's balance from the shard that last bound it
(tracked exactly by the planner), pushes the merged planes to every
shard, and output order is re-established at collect from the same
placements list the lockstep path uses — so MatchOut stays byte-exact
vs the single-chip oracle in both modes. Lockstep remains available
(`dispatch="lockstep"`) and byte-identical to the pre-async behavior;
multi-process meshes (tests/test_multihost.py) fall back to lockstep
automatically because per-device queues need locally addressable
devices.

One semantic note: the sticky error plane is per-shard in async mode
(no per-window pmax), so after an envelope error the OTHER shards keep
executing their queued windows instead of no-opping. The first errored
(window, shard) cell in collect order raises the same LaneEngineError
either way, and the error path aborts the stream, so the divergence is
unobservable through the session surface.

Executed evidence: tests/test_seqmesh.py (bit-exact at shards 1/2/8 on
a virtual mesh vs the scalar oracle and the single-chip SeqSession),
tests/test_async_dispatch.py (async-vs-lockstep byte parity under
migrations, payout storms, mid-stream checkpoints; stall-schedule
determinism), tests/test_multihost.py (the same program SPMD across
two OS processes), and __graft_entry__.dryrun_multichip (the driver's
multichip artifact).
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional

import numpy as np

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kme_tpu.engine import seq as SQ
from kme_tpu.native import sched as native_sched
from kme_tpu.parallel.mesh import AXIS, build_mesh
from kme_tpu.runtime.seqsession import SeqSession, make_seq_router
from kme_tpu.telemetry import PhaseTimer, Registry
from kme_tpu.utils import pow2_bucket

# per-shard per-window message capacity (windows close earlier on
# account conflicts; 128 keeps the padded input planes small)
WINDOW_CAP = 128

# rebalance when the hottest shard's EWMA load exceeds the mean by
# this factor; migrating costs a full canonical round-trip of the lane
# state, so the trigger is deliberately above measurement noise
REBALANCE_THRESHOLD = 1.25
# per-batch decay of the per-lane load estimate
LOAD_EWMA_ALPHA = 0.5
# matchable messages (BUY/SELL) sweep makers; everything else is O(1)
MATCH_WORK_WEIGHT = 2.0

# wall-feed (async only, opt-in): EWMA decay and clip for the measured
# per-shard cost rate that scales the rebalancer's lane weights
WALL_RATE_ALPHA = 0.5
WALL_RATE_MIN, WALL_RATE_MAX = 0.5, 2.0

# communication costs for the dispatch-schedule simulation, in the
# same work units as the per-message weights. The lockstep scan pays a
# full cross-shard collective EVERY window (balance psum + sticky-err
# pmax + output all_gather are baked into its scan body); async pays
# the full merge only at barriers and batch-end collect, plus one
# point-to-point fetch+scatter per dependency patch. Modeling that
# asymmetry is what makes chip_stall_frac reflect the schedules'
# actual communication structure, not just their compute.
MERGE_COST_WEIGHT = 0.5   # collective cost per participating shard
# host-side cost of one point-to-point dep fetch + scatter enqueue.
# Deliberately below one message unit: the dominant real cost of a
# patch — waiting for the source shard's earlier windows — is modeled
# separately via the prev[src] wait; this term only covers the host's
# drain/materialize + scatter enqueue of a few KB of balance planes
PATCH_COST = 0.25

_MSG_FIELDS = ("act", "aid", "price", "size", "lane",
               "oid_lo", "oid_hi")


@jax.jit
def _scatter_balances(lo, hi, u, rows, cls, vlo, vhi, vu):
    """On-device patch of forwarded account balances into a shard's
    replicated planes. Callers pad the index/value arrays by REPEATING
    the last entry, so duplicate scatter indices always carry identical
    values and the scatter is order-independent (deterministic)."""
    return (lo.at[rows, cls].set(vlo),
            hi.at[rows, cls].set(vhi),
            u.at[rows, cls].set(vu))


def make_mesh_state(local_cfg: SQ.SeqConfig, shards: int) -> dict:
    """Global state pytree: per-shard seq states stacked on the leading
    row axis for the sharded keys; balances/err replicated."""
    local = SQ.make_seq_state(local_cfg)
    out = {}
    for k, v in local.items():
        if k in ("bal_lo", "bal_hi", "bal_u", "err"):
            out[k] = v
        else:
            out[k] = jnp.tile(v, (shards, 1))
    return out


def state_specs(local_cfg: SQ.SeqConfig) -> dict:
    specs = {}
    for k in SQ.state_keys(local_cfg):
        if k in ("bal_lo", "bal_hi", "bal_u", "err"):
            specs[k] = P()
        else:
            specs[k] = P(AXIS)
    return specs


def _i64(lo, hi):
    return ((lo.astype(jnp.int64) & 0xFFFFFFFF)
            | (hi.astype(jnp.int64) << 32))


def _split64(v):
    lo = v & 0xFFFFFFFF
    lo = jnp.where(lo >= 1 << 31, lo - (1 << 32), lo).astype(jnp.int32)
    return lo, (v >> 32).astype(jnp.int32)


def _shard_map(fn, mesh, in_specs, out_specs):
    """shard_map with varying-mesh-axes checking off: the body contains
    a pallas_call, whose out_shapes carry no vma annotation."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:  # pragma: no cover - older jax fallback
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except TypeError:  # older jax spells the flag check_rep
        try:
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
        except TypeError:  # pragma: no cover - jax without either flag
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs)


@functools.lru_cache(maxsize=None)
def build_seq_mesh_scan(local_cfg: SQ.SeqConfig, shards: int, K: int):
    """Jitted (state, wins) -> (state, out_planes): a lax.scan over K
    account-disjoint windows inside ONE shard_map. Each window: the
    per-shard seq kernel runs its local sub-batch, then balance deltas
    psum-merge (exact — see module docstring) and the sticky error
    pmax-merges."""
    mesh = build_mesh(shards)
    _, raw_call = SQ.build_seq_step(local_cfg)

    def body(state, win):
        start_lo = state["bal_lo"]
        start_hi = state["bal_hi"]
        start_u = state["bal_u"]
        st2, outp = raw_call(state, win)
        old = _i64(start_lo, start_hi)
        delta = _i64(st2["bal_lo"], st2["bal_hi"]) - old
        merged = old + jax.lax.psum(delta, AXIS)
        mlo, mhi = _split64(merged)
        mu = start_u + jax.lax.psum(st2["bal_u"] - start_u, AXIS)
        err = jax.lax.pmax(st2["err"], AXIS)
        st2 = dict(st2, bal_lo=mlo, bal_hi=mhi, bal_u=mu, err=err)
        # REPLICATE the window's out planes (all_gather over ICI/DCN):
        # under multi-process meshes the host can only fetch
        # fully-addressable arrays (tests/test_multihost.py)
        return st2, jax.lax.all_gather(outp, AXIS)

    def run(state, wins):
        return jax.lax.scan(body, state, wins, length=K)

    specs = state_specs(local_cfg)
    win_specs = {f: P(None, AXIS) for f in _MSG_FIELDS}
    # NO jit-level donation: it composes badly with the kernel's
    # input_output_aliases (clobbered aliased outputs — the documented
    # hazard in build_seq_step's NOTE), at the cost of one state copy
    # per dispatch.
    sharded = _shard_map(run, mesh, (specs, win_specs),
                         (specs, P()))
    return jax.jit(sharded)   # outs: (K, shards, NROWS, 128) replicated


def plan_rebalance(lane_load, perm, shards: int,
                   threshold: float = REBALANCE_THRESHOLD,
                   max_swaps: Optional[int] = None):
    """Pure placement decision: given the per-lane load EWMA and the
    current placement table, return a new table (or None for "stay").

    Greedy slot swaps between the hottest and coldest shard, accepted
    only while each swap STRICTLY reduces that pair's peak load, so the
    loop terminates and a balanced table is a fixed point. Fully
    deterministic (argmax/argmin first-index ties, no RNG) — the
    decision is replay-safe by construction, which kme-lint's KME-D002
    replay scope pins.
    """
    S = len(perm)
    Sl = S // shards
    total = float(lane_load.sum())
    if total <= 0.0:
        return None
    shard_loads = np.bincount(perm // Sl, weights=lane_load,
                              minlength=shards).astype(float)
    mean = total / shards
    if shard_loads.max() <= threshold * mean:
        return None
    new = perm.copy()
    budget = S if max_swaps is None else max_swaps
    swapped = False
    for _ in range(budget):
        h = int(shard_loads.argmax())
        c = int(shard_loads.argmin())
        if h == c:
            break
        # best single lane swap hot<->cold: minimize the pair's peak
        best = None
        for gh in range(S):
            if new[gh] // Sl != h:
                continue
            for gc in range(S):
                if new[gc] // Sl != c:
                    continue
                d = float(lane_load[gh]) - float(lane_load[gc])
                if d <= 0.0:
                    continue
                peak = max(shard_loads[h] - d, shard_loads[c] + d)
                if peak >= shard_loads[h]:
                    continue
                if best is None or peak < best[0]:
                    best = (peak, gh, gc, d)
        if best is None:
            break
        _, gh, gc, d = best
        new[gh], new[gc] = new[gc], new[gh]
        shard_loads[h] -= d
        shard_loads[c] += d
        swapped = True
    return new if swapped else None


class SeqMeshSession(SeqSession):
    """Sharded drop-in for SeqSession (fixed mode): same process /
    process_wire / process_wire_buffer surface, state sharded over a
    `shards`-device mesh.

    `dispatch` selects the mesh execution discipline:

    - "async" (default where available): per-shard submission queues —
      independent per-device scan segments with point-to-point balance
      forwarding and full merges only at barriers and batch-end collect
      (module docstring). Needs every mesh device locally addressable.
    - "lockstep": the original single-shard_map scan with per-window
      psum merges; byte-identical to the pre-async behavior.
    - "auto": async when capable, else lockstep (multi-process SPMD).

    Both modes produce byte-identical MatchOut. `wall_feed=True`
    (async only) feeds measured per-chip walls into the rebalancer's
    lane-load EWMA as a per-shard cost rate — placement changes, bytes
    don't (correctness is placement-independent, see ELASTIC above)."""

    # replicated state keys: migration must NOT permute these
    _REPL_KEYS = ("bal_lo", "bal_hi", "bal_u", "err")

    def __init__(self, cfg: SQ.SeqConfig, shards: int, *,
                 rebalance: bool = True,
                 rebalance_threshold: float = REBALANCE_THRESHOLD,
                 dispatch: str = "auto",
                 wall_feed: bool = False,
                 ) -> None:
        if cfg.compat != "fixed":
            raise ValueError(
                "sharded seq serving is fixed-mode only (java mode is "
                "single-chip by Q11's serial semantics, COMPAT.md)")
        if cfg.hbm_books:
            raise ValueError("seq mesh uses VMEM books per shard")
        if cfg.lanes % shards:
            raise ValueError(f"lanes {cfg.lanes} not divisible by "
                             f"{shards} shards")
        self.cfg = cfg
        self.shards = shards
        self.local_cfg = SQ.SeqConfig(
            lanes=cfg.lanes // shards, slots=cfg.slots,
            accounts=cfg.accounts, max_fills=cfg.max_fills,
            batch=WINDOW_CAP, pos_cap=cfg.pos_cap,
            fill_cap=cfg.fill_cap, probe_max=cfg.probe_max)
        self.S_local = cfg.lanes // shards
        self.state = make_mesh_state(self.local_cfg, shards)
        self.router = make_seq_router(cfg.lanes, cfg.accounts)
        self._metrics = np.zeros(SQ.N_METRICS, np.int64)
        self._hist = np.zeros((SQ.N_HIST, SQ.N_HIST_BUCKETS), np.int64)
        self._recon = None
        self.telemetry = Registry()
        self.timer = PhaseTimer(track="seqmesh")
        self.phases = self.timer.totals   # cumulative across batches
        self._use_native_wire = True
        self._ghint = 8
        # elastic placement: global lane -> global slot; shard of a
        # lane is perm[lane] // S_local, its kernel row perm[lane] %
        # S_local. Identity == the pre-elastic static layout.
        self.rebalance = rebalance
        self.rebalance_threshold = rebalance_threshold
        self._perm = np.arange(cfg.lanes, dtype=np.int64)
        self._lane_load = np.zeros(cfg.lanes, np.float64)
        # sticky account home: last GLOBAL LANE the account traded on
        # (tracked as a lane, not a shard, so homes follow migrations)
        self._acct_lane: Dict[int, int] = {}
        self._migrations = 0
        self._rebalances = 0
        self._occ_shard = np.zeros(shards, np.int64)
        self._hist_shard = np.zeros(
            (shards, SQ.N_HIST, SQ.N_HIST_BUCKETS), np.int64)
        # -- per-chip async dispatch --
        if dispatch not in ("auto", "async", "lockstep"):
            raise ValueError(f"unknown dispatch mode {dispatch!r}")
        capable = self._async_capable(shards)
        if dispatch == "auto":
            dispatch = "async" if capable else "lockstep"
        elif dispatch == "async" and not capable:
            raise ValueError(
                "async dispatch needs every mesh device locally "
                "addressable (single-process mesh); use "
                "dispatch='lockstep' or 'auto'")
        self.dispatch = dispatch
        self.wall_feed = wall_feed
        self._bal_shape = tuple(self.state["bal_lo"].shape)
        self._shard_rate = np.ones(shards, np.float64)
        self._shard_states: Optional[List[dict]] = None
        self._devices = None
        # deterministic stall schedule accumulators (plan_dispatch)
        self._sim_busy = np.zeros(shards, np.float64)
        self._sim_T_async = 0.0
        self._sim_T_lock = 0.0
        # measured per-chip walls + H2D overlap accounting
        self._msgs_total = 0
        self._async_wall_total = 0.0
        self._h2d_total_s = 0.0
        self._h2d_overlap_s = 0.0
        self._seg_inflight = 0
        self._t0_shard: List[Optional[float]] = [None] * shards
        if dispatch == "async":
            self._init_async_states()

    @staticmethod
    def _async_capable(shards: int) -> bool:
        """Per-shard queues dispatch to individual devices with
        jax.device_put, which needs every device addressable from this
        process — false under multi-process SPMD (test_multihost)."""
        try:
            return jax.process_count() == 1
        except Exception:  # pragma: no cover - defensive
            return False

    def _init_async_states(self) -> None:
        mesh = build_mesh(self.shards)
        self._devices = [d for d in np.asarray(mesh.devices).reshape(-1)]
        host = {k: np.asarray(v) for k, v in self.state.items()}
        self._split_state_async(host)
        self.state = None   # async truth lives in _shard_states

    def _split_state_async(self, host: dict) -> None:
        """Host stacked state dict -> per-shard device-committed local
        states (replicated planes copied to every shard)."""
        states = []
        for s in range(self.shards):
            loc = {k: (v if k in self._REPL_KEYS
                       else v.reshape(self.shards, -1, v.shape[-1])[s])
                   for k, v in host.items()}
            states.append(jax.device_put(loc, self._devices[s]))
        self._shard_states = states

    def _gather_state_async(self) -> dict:
        """Per-shard states -> host stacked dict (the lockstep layout).
        Only called at batch boundaries, where _collect_merge has left
        every shard's replicated planes identical — so the replicated
        keys legitimately come from shard 0."""
        parts = [jax.device_get(st) for st in self._shard_states]
        host = {}
        for k in parts[0]:
            if k in self._REPL_KEYS:
                host[k] = np.asarray(parts[0][k])
            else:
                host[k] = np.concatenate(
                    [np.asarray(p[k]) for p in parts], axis=0)
        return host

    # -- host planning -------------------------------------------------

    def plan_windows(self, cols):
        """Columnar routed messages -> (wins dict of (K, shards*Bw) i32,
        placements list of (window, shard, pos) per routed message,
        cnts (K, shards) int).

        A lane's shard and kernel row come from the elastic placement
        table (`_perm`, applied once per batch via
        native_sched.apply_placement), NOT the old static
        `lane // S_local` split. Laneless balance messages (CREATE/
        TRANSFER) follow the account's sticky home lane, which is the
        last lane it traded on — tracked as a LANE so a migration
        automatically re-pins the account to the lane's new shard and
        the balance-coupling window invariant survives rebalancing.

        The planner is host Python (per-message loop): fine for the
        dryrun/test scale this session targets; a measured multi-chip
        serving path would move it next to the C++ router
        (native/kme_router.cpp) like round 4 did for routing."""
        n = len(cols["act"])
        Bw = WINDOW_CAP
        acts = cols["act"]
        lanes = cols["lane"]
        aids = cols["aid"]
        _, shard_col, local_col = native_sched.apply_placement(
            self._perm, lanes, self.S_local)
        barrier = ((acts == SQ.L_PAYOUT_YES) | (acts == SQ.L_PAYOUT_NO)
                   | (acts == SQ.L_REMOVE_SYMBOL))
        laneful = ((acts == SQ.L_BUY) | (acts == SQ.L_SELL)
                   | (acts == SQ.L_CANCEL) | (acts == SQ.L_ADD_SYMBOL)
                   | barrier)
        # only balance-touching acts bind their account to a shard
        # (ADD_SYMBOL routes with aid=0 but never touches balances)
        binds = ((acts == SQ.L_BUY) | (acts == SQ.L_SELL)
                 | (acts == SQ.L_CANCEL) | (acts == SQ.L_CREATE)
                 | (acts == SQ.L_TRANSFER))
        windows: List[List[List[int]]] = []  # [w][s] -> routed indices
        placements = []
        bound: Dict[int, int] = {}
        cur = [[] for _ in range(self.shards)]

        def flush():
            nonlocal cur, bound
            if any(cur[s] for s in range(self.shards)):
                windows.append(cur)
            cur = [[] for _ in range(self.shards)]
            bound = {}

        for k in range(n):
            if barrier[k]:
                # barriers credit many accounts: run alone
                flush()
                cur[int(shard_col[k])].append(k)
                flush()
                continue
            a = int(aids[k])
            if laneful[k]:
                s = int(shard_col[k])
                if binds[k]:
                    self._acct_lane[a] = int(lanes[k])
            else:
                s = bound.get(a, self._home_shard(a))
            b = bound.get(a) if binds[k] else None
            if (b is not None and b != s) or len(cur[s]) >= Bw:
                flush()
            if binds[k]:
                bound[a] = s
            cur[s].append(k)
        flush()

        K = pow2_bucket(max(len(windows), 1), lo=1)
        wins = {f: np.zeros((K, self.shards, Bw), np.int32)
                for f in _MSG_FIELDS}
        cnts = np.zeros((K, self.shards), np.int32)
        for w, per in enumerate(windows):
            for s, idxs in enumerate(per):
                cnts[w, s] = len(idxs)
                for p, k in enumerate(idxs):
                    placements.append((k, w, s, p))
                    wins["act"][w, s, p] = cols["act"][k]
                    wins["aid"][w, s, p] = cols["aid"][k]
                    wins["price"][w, s, p] = cols["price"][k]
                    wins["size"][w, s, p] = cols["size"][k]
                    wins["lane"][w, s, p] = int(local_col[k])
                    oid = int(cols["oid"][k])
                    lo = oid & 0xFFFFFFFF
                    wins["oid_lo"][w, s, p] = np.int32(
                        lo - (1 << 32) if lo >= 1 << 31 else lo)
                    wins["oid_hi"][w, s, p] = np.int32(oid >> 32)
        wins = {f: v.reshape(K, self.shards * WINDOW_CAP)
                for f, v in wins.items()}
        placements.sort()
        return wins, placements, cnts, K

    # -- the SeqSession contract ---------------------------------------

    def _run(self, msgs):
        if self.dispatch == "async":
            return self._run_async(msgs)
        return self._run_lockstep(msgs)

    def _run_lockstep(self, msgs):
        from kme_tpu.runtime.session import LaneEngineError

        # migrations happen BETWEEN batches only: state is quiescent
        # here, so the permutation is a pure relabeling of lane rows
        self._maybe_rebalance()

        with self.timer.phase("plan_s"):
            cols, host_rejects = self.router.route(msgs)
            self._note_load(cols)
            wins, placements, cnts, K = self.plan_windows(cols)

        with self.timer.phase("dispatch_s"):
            t_disp = time.perf_counter()
            scan = build_seq_mesh_scan(self.local_cfg, self.shards, K)
            self.state, outs = scan(self.state, wins)
            jax.block_until_ready(self.state)
            disp_wall = time.perf_counter() - t_disp

        with self.timer.phase("fetch_s"):
            outs = np.asarray(outs)   # (K, shards, NROWS, 128)
            HR = SQ.hdr_rows(self.local_cfg)
            n = len(cols["act"])
            host = {k: np.zeros(n, dt) for k, dt in
                    (("ok", bool), ("cap_reject", bool),
                     ("append", bool), ("residual", np.int64),
                     ("nfill", np.int64), ("prev_oid", np.int64))}
            groups = {}
            mets = np.zeros(SQ.N_METRICS, np.int64)
            # batch_occupancy convention (documented + tested,
            # tests/test_shard_elastic.py): per-(window, shard) kernel
            # calls are the dispatch units here, so batch_occupancy
            # observes per-shard SUB-WINDOWS — one observation per
            # non-empty (w, s) cell, valued at that cell's message
            # count cnts[w, s], NOT one blended observation per host
            # batch like the single-chip session. The same counters
            # accumulate per shard into _hist_shard and surface as
            # batch_occupancy_shard{N} (histograms()); the cumulative
            # per-shard occupancy totals (_occ_shard) feed the
            # shard_imbalance gauge = max/mean per-shard occupancy.
            hists = np.zeros((SQ.N_HIST, SQ.N_HIST_BUCKETS), np.int64)
            for w in range(K):
                for s in range(self.shards):
                    cnt = int(cnts[w, s])
                    if not cnt:
                        continue
                    res = SQ.unpack_hdr(self.local_cfg,
                                        outs[w, s][:HR], cnt)
                    if res["err"] != SQ.LERR_OK:
                        raise LaneEngineError(res["err"])
                    ft = res["fill_total"]
                    gr = outs[w, s][HR:HR + 5 * (-(-max(ft, 1) // 128))]
                    groups[(w, s)] = (res, SQ.unpack_fills(gr, ft),
                                      np.concatenate(
                                          ([0], np.cumsum(res["nfill"]))))
                    mets += res["metrics"]
                    hists += res["hist"]
                    self._hist_shard[s] += res["hist"]
            self._metrics += mets
            self._hist += hists
            self._publish_shard_telemetry(
                disp_wall, cnts.sum(axis=0).astype(np.int64))
            fills_parts = []
            for k, w, s, p in placements:
                res, fills_ws, off = groups[(w, s)]
                for key in host:
                    host[key][k] = res[key][p]
                if res["nfill"][p]:
                    fills_parts.append(fills_ws[:, off[p]:off[p + 1]])
            fills = (np.concatenate(fills_parts, axis=1) if fills_parts
                     else np.zeros((4, 0), np.int64))
        return cols, host_rejects, host, fills

    # -- per-chip async dispatch ---------------------------------------

    def _run_async(self, msgs):
        self._maybe_rebalance()

        with self.timer.phase("plan_s"):
            cols, host_rejects = self.router.route(msgs)
            self._note_load(cols)
            wins, placements, cnts, K = self.plan_windows(cols)
            plan = self.plan_dispatch(cols, placements)

        with self.timer.phase("dispatch_s"):
            t_disp = time.perf_counter()
            out_map, walls = self._dispatch_async(wins, cnts, plan)
            disp_wall = time.perf_counter() - t_disp

        with self.timer.phase("fetch_s"):
            host, fills = self._unpack_outputs(
                cols, placements, cnts, K, out_map)
            occ = cnts.sum(axis=0).astype(np.int64)
            self._sim_busy += plan["busy"]
            self._sim_T_async += plan["T_async"]
            self._sim_T_lock += plan["T_lock"]
            self._msgs_total += int(occ.sum())
            if walls.size:
                self._async_wall_total += float(walls.max())
            if self.wall_feed:
                self._update_wall_rates(walls, plan["busy"])
            self._publish_shard_telemetry_async(walls, occ, disp_wall)
        return cols, host_rejects, host, fills

    def _owner_sel(self, loc: Dict[int, int],
                   base: Optional[int]) -> np.ndarray:
        """Per-account owner-shard selection table for a full merge:
        account a's authoritative balance copy lives on loc[a], else on
        `base` (the shard the last barrier ran on), else anywhere (all
        shards identical since the previous merge — pick 0)."""
        sel = np.zeros(self._bal_shape[0] * self._bal_shape[1],
                       np.int32)
        if base:
            sel[:] = base
        for a, s in loc.items():
            sel[a] = s
        return sel

    def plan_dispatch(self, cols, placements) -> dict:
        """Pure host planning for async dispatch (hot scope: no device
        syncs, no blocking I/O). One walk over the batch's windows in
        stream order derives:

        - `deps[(w, s)]`: the point-to-point dependency set — accounts
          bound to shard s in window w whose authoritative balance copy
          currently lives on another shard (the ONLY cross-shard waits
          the async schedule takes outside barriers);
        - `merge_sel[w]` / `final_sel`: owner-selection tables for the
          full merges at barrier windows and batch-end collect;
        - a deterministic stall schedule for BOTH dispatch modes, with
          per-message weighted costs (MATCH_WORK_WEIGHT, same as the
          rebalancer) plus communication terms (MERGE_COST_WEIGHT /
          PATCH_COST): async — per-shard clocks plus a host clock that
          blocks on the source shard (+ one patch cost) at each
          dependency fetch, a full-merge collective at barriers and
          batch-end only; lockstep — every window is a global barrier
          AND a full collective, so T += max-shard cost + S·merge per
          window. chip_stall_frac derives from this schedule, so the
          perfgate metric is replay-stable and backend-independent.
        """
        acts = cols["act"]
        aids = cols["aid"]
        S = self.shards
        W = placements[-1][1] + 1 if placements else 0
        barrier_acts = (SQ.L_PAYOUT_YES, SQ.L_PAYOUT_NO,
                        SQ.L_REMOVE_SYMBOL)
        bind_acts = (SQ.L_BUY, SQ.L_SELL, SQ.L_CANCEL, SQ.L_CREATE,
                     SQ.L_TRANSFER)
        cost = np.zeros((W, S))
        binds_w: List[List] = [[] for _ in range(W)]
        barriers: Dict[int, int] = {}
        for k, w, s, _ in placements:
            act = int(acts[k])
            if act in barrier_acts:
                barriers[w] = s
            cost[w, s] += (MATCH_WORK_WEIGHT
                           if act in (SQ.L_BUY, SQ.L_SELL) else 1.0)
            if act in bind_acts:
                binds_w[w].append((int(aids[k]), s))
        deps: Dict[tuple, list] = {}
        merge_sel: Dict[int, np.ndarray] = {}
        loc: Dict[int, int] = {}
        base: Optional[int] = None
        clock = np.zeros(S)
        busy = np.zeros(S)
        host_t = 0.0
        t_lock = 0.0
        m_full = MERGE_COST_WEIGHT * S   # one full-merge collective
        for w in range(W):
            # lockstep: barrier + collective (psum/pmax/all_gather in
            # the scan body) every window
            t_lock += float(cost[w].max()) + m_full
            bs = barriers.get(w)
            if bs is not None:
                # full merge: host waits for every shard, pays ONE
                # collective, then the barrier cell runs alone
                merge_sel[w] = self._owner_sel(loc, base)
                t = max(float(clock.max()), host_t) + m_full
                clock[:] = t
                host_t = t
                clock[bs] = t + float(cost[w, bs])
                busy[bs] += float(cost[w, bs])
                loc = {}
                base = bs
                continue
            cell_deps: Dict[tuple, Dict[int, int]] = {}
            for a, s in binds_w[w]:
                src = loc.get(a, base)
                if src is not None and src != s:
                    cell_deps.setdefault((w, s), {})[a] = src
            for key, d in cell_deps.items():
                deps[key] = sorted(d.items())
            # dependency fetches read the SOURCE shard as of window w-1
            # (the dispatcher patches before appending w to any queue),
            # so dep waits use the pre-window clocks: every cell starts
            # no later than the lockstep barrier max — T_async <= T_lock
            # by induction, strictly less whenever windows are imbalanced
            prev = clock.copy()
            for s in range(S):
                c = float(cost[w, s])
                if c <= 0.0:
                    continue
                dl = cell_deps.get((w, s))
                if dl:
                    for src in sorted(set(dl.values())):
                        # drain src, then one point-to-point
                        # fetch+scatter onto the destination
                        host_t = (max(host_t, float(prev[src]))
                                  + PATCH_COST)
                    start = max(float(prev[s]), host_t)
                else:
                    start = float(prev[s])
                clock[s] = start + c
                busy[s] += c
            for a, s in binds_w[w]:
                loc[a] = s
        return {
            "W": W, "deps": deps, "barriers": barriers,
            "merge_sel": merge_sel,
            "final_sel": self._owner_sel(loc, base),
            "busy": busy,
            # batch-end collect pays async's one deferred collective
            "T_async": ((max(float(clock.max()), host_t) + m_full)
                        if W else 0.0),
            "T_lock": t_lock,
        }

    def _stage_and_dispatch(self, s: int, seg: dict):
        """Enqueue one window segment on shard s's dispatch stream (hot
        scope: device_put is async, the jitted per-device scan returns
        futures — no host syncs here). H2D staging time is charged as
        overlapped when any earlier segment of this batch is still in
        flight: that is exactly the device-side double-buffering win —
        shard s's (or a peer's) compute hides the copy."""
        t0 = time.perf_counter()
        staged = jax.device_put(seg, self._devices[s])
        dt = time.perf_counter() - t0
        self._h2d_total_s += dt
        if self._seg_inflight:
            self._h2d_overlap_s += dt
        self._seg_inflight += 1
        if self._t0_shard[s] is None:
            self._t0_shard[s] = t0
        kseg = next(iter(staged.values())).shape[0]
        scan = SQ.build_seq_scan(self.local_cfg, kseg)
        st2, outs = scan(self._shard_states[s], staged)
        self._shard_states[s] = st2
        return outs

    def _patch_shard(self, s: int, rows, cls, vlo, vhi, vu) -> None:
        """Enqueue an on-device scatter of forwarded account balances
        into shard s's replicated planes (hot scope: no syncs). Arrays
        are padded by repeating the LAST entry — duplicate scatter
        indices with identical values stay deterministic — so the jit
        cache is bounded by pow2 bucket sizes."""
        n = rows.shape[0]
        npad = pow2_bucket(n, lo=8)

        def pad(a):
            out = np.empty(npad, a.dtype)
            out[:n] = a
            out[n:] = a[n - 1]
            return out

        dev = self._devices[s]
        args = [jax.device_put(pad(a), dev)
                for a in (rows, cls, vlo, vhi, vu)]
        st = self._shard_states[s]
        lo, hi, u = _scatter_balances(
            st["bal_lo"], st["bal_hi"], st["bal_u"], *args)
        self._shard_states[s] = dict(st, bal_lo=lo, bal_hi=hi, bal_u=u)

    def _collect_merge(self, sel: np.ndarray) -> None:
        """FULL merge barrier (watermark/checkpoint/produce boundary or
        barrier window): drain every shard, select each account's
        authoritative balance copy per `sel`, max-merge the sticky
        error, and push the merged replicated planes to every shard."""
        parts = []
        err = None
        for s in range(self.shards):
            st = self._shard_states[s]
            parts.append({k: np.asarray(st[k]) for k in SQ.BAL_KEYS})
            e = np.asarray(st["err"])
            err = e if err is None else np.maximum(err, e)
        merged = SQ.select_balances(parts, sel)
        merged["err"] = err
        for s in range(self.shards):
            put = jax.device_put(merged, self._devices[s])
            self._shard_states[s] = dict(self._shard_states[s], **put)
        self._seg_inflight = 0

    def _dispatch_async(self, wins, cnts, plan):
        """Walk the batch's windows in stream order, buffering each
        shard's windows into its own submission queue and flushing a
        queue only when forced: a dependency fetch (point-to-point — the
        host drains the SOURCE shard and patches just the moved accounts
        into the destination), a barrier (full merge), or batch end.
        Shards without dependencies run arbitrarily far ahead."""
        Bw = WINDOW_CAP
        S = self.shards
        pend: List[List[int]] = [[] for _ in range(S)]
        segs: List[List[tuple]] = [[] for _ in range(S)]
        fetched: Dict[int, tuple] = {}
        self._t0_shard = [None] * S
        self._seg_inflight = 0

        def flush(s):
            if not pend[s]:
                return
            win_idx, pend[s] = pend[s], []
            fetched.pop(s, None)
            seg = native_sched.slice_windows(wins, win_idx, s, S, Bw)
            segs[s].append((win_idx, self._stage_and_dispatch(s, seg)))

        def planes_of(src):
            # blocks the host until src's queue drains — THE
            # point-to-point wait. The cached fetch is only valid while
            # src has received no further windows: any pending (or
            # patched — see the explicit pops) work invalidates it.
            if pend[src] or src not in fetched:
                flush(src)
                st = self._shard_states[src]
                fetched[src] = tuple(np.asarray(st[k])
                                     for k in SQ.BAL_KEYS)
            return fetched[src]

        for w in range(plan["W"]):
            bs = plan["barriers"].get(w)
            if bs is not None:
                for s in range(S):
                    flush(s)
                self._collect_merge(plan["merge_sel"][w])
                fetched.clear()
                pend[bs].append(w)
                flush(bs)
                continue
            # pass 1 — dependency fetches + patches BEFORE window w is
            # appended to ANY queue: the source flush inside planes_of
            # therefore only covers windows <= w-1, matching the stall
            # schedule's prev-clock dep waits (and lockstep's timing
            # bound). Patch-then-append keeps the destination's on-device
            # scatter ordered after its own w-1 segment by data flow.
            for s in range(S):
                if not cnts[w, s]:
                    continue
                dl = plan["deps"].get((w, s))
                if not dl:
                    continue
                flush(s)
                by_src: Dict[int, list] = {}
                for a, src in dl:
                    by_src.setdefault(src, []).append(a)
                for src in sorted(by_src):
                    lo_p, hi_p, u_p = planes_of(src)
                    accs = np.fromiter(by_src[src], np.int64,
                                       len(by_src[src]))
                    rows = (accs >> 7).astype(np.int32)
                    cls_ = (accs & 127).astype(np.int32)
                    self._patch_shard(
                        s, rows, cls_, lo_p[rows, cls_],
                        hi_p[rows, cls_], u_p[rows, cls_])
                fetched.pop(s, None)
            # pass 2 — enqueue window w on every occupied shard
            for s in range(S):
                if cnts[w, s]:
                    pend[s].append(w)
        for s in range(S):
            flush(s)
        # drain + measure real per-chip walls (first submit -> done)
        walls = np.zeros(S, np.float64)
        for s in range(S):
            if segs[s]:
                jax.block_until_ready(segs[s][-1][1])
            if self._t0_shard[s] is not None:
                walls[s] = time.perf_counter() - self._t0_shard[s]
        self._collect_merge(plan["final_sel"])
        out_map = {}
        for s in range(S):
            for win_idx, outs in segs[s]:
                h = np.asarray(outs)   # (kseg, NROWS, 128)
                for i, w in enumerate(win_idx):
                    out_map[(w, s)] = h[i]
        return out_map, walls

    def _unpack_outputs(self, cols, placements, cnts, K, out_map):
        """Async collect: byte-identical to the lockstep fetch loop,
        reading per-(window, shard) output planes from `out_map` instead
        of the stacked all_gather array. Raises at the first errored
        cell in (w, s) order — the same error surface as lockstep
        (module docstring)."""
        from kme_tpu.runtime.session import LaneEngineError

        HR = SQ.hdr_rows(self.local_cfg)
        n = len(cols["act"])
        host = {k: np.zeros(n, dt) for k, dt in
                (("ok", bool), ("cap_reject", bool),
                 ("append", bool), ("residual", np.int64),
                 ("nfill", np.int64), ("prev_oid", np.int64))}
        groups = {}
        mets = np.zeros(SQ.N_METRICS, np.int64)
        hists = np.zeros((SQ.N_HIST, SQ.N_HIST_BUCKETS), np.int64)
        for w in range(K):
            for s in range(self.shards):
                cnt = int(cnts[w, s])
                if not cnt:
                    continue
                cell = out_map[(w, s)]
                res = SQ.unpack_hdr(self.local_cfg, cell[:HR], cnt)
                if res["err"] != SQ.LERR_OK:
                    raise LaneEngineError(res["err"])
                ft = res["fill_total"]
                gr = cell[HR:HR + 5 * (-(-max(ft, 1) // 128))]
                groups[(w, s)] = (res, SQ.unpack_fills(gr, ft),
                                  np.concatenate(
                                      ([0], np.cumsum(res["nfill"]))))
                mets += res["metrics"]
                hists += res["hist"]
                self._hist_shard[s] += res["hist"]
        self._metrics += mets
        self._hist += hists
        fills_parts = []
        for k, w, s, p in placements:
            res, fills_ws, off = groups[(w, s)]
            for key in host:
                host[key][k] = res[key][p]
            if res["nfill"][p]:
                fills_parts.append(fills_ws[:, off[p]:off[p + 1]])
        fills = (np.concatenate(fills_parts, axis=1) if fills_parts
                 else np.zeros((4, 0), np.int64))
        return host, fills

    def _update_wall_rates(self, walls, busy) -> None:
        """Fold measured per-chip walls into the per-shard cost-rate
        EWMA (wall_feed=True): a shard whose wall exceeds its planned
        busy share is genuinely slower (thermals, host contention), so
        its lanes weigh more in the rebalancer. Bytes are unaffected —
        placement only moves work, never changes MatchOut."""
        act = (busy > 0) & (walls > 0)
        if int(act.sum()) < 2:
            return
        r = ((walls[act] / walls[act].mean())
             / (busy[act] / busy[act].mean()))
        rate = np.ones(self.shards, np.float64)
        rate[act] = np.clip(r, WALL_RATE_MIN, WALL_RATE_MAX)
        self._shard_rate = np.clip(
            WALL_RATE_ALPHA * rate
            + (1.0 - WALL_RATE_ALPHA) * self._shard_rate,
            WALL_RATE_MIN, WALL_RATE_MAX)

    def _publish_shard_telemetry_async(self, walls, occ,
                                       disp_wall: float) -> None:
        """Async-mode telemetry: REAL measured per-chip walls feed the
        device_shard{N} histograms (replacing the lockstep
        occupancy-weighted split approximation), plus the deterministic
        stall-schedule gauges and the H2D overlap fraction."""
        self._occ_shard += occ
        reg = self.telemetry
        reg.gauge("shard_count", "mesh shard count").set(self.shards)
        reg.counter("shard_migrations_total",
                    "lane slots moved by elastic placement"
                    ).set(self._migrations)
        reg.counter("shard_rebalances_total",
                    "between-batch rebalance events"
                    ).set(self._rebalances)
        tot = int(self._occ_shard.sum())
        if tot:
            reg.gauge(
                "shard_imbalance",
                "max/mean per-shard cumulative occupancy").set(
                round(float(self._occ_shard.max())
                      * self.shards / tot, 4))
        for s in range(self.shards):
            reg.gauge(f"shard{s}_occupancy",
                      "cumulative messages executed on shard").set(
                int(self._occ_shard[s]))
            if int(occ[s]) and walls[s] > 0:
                reg.latency(
                    f"device_shard{s}",
                    "measured per-chip dispatch wall").observe(
                    float(walls[s]), n=int(occ[s]))
        if self._sim_T_async > 0:
            tot_busy = float(self._sim_busy.sum())
            reg.gauge(
                "chip_stall_frac",
                "stall fraction of the async dispatch schedule "
                "(deterministic, weighted-cost)").set(round(
                    1.0 - tot_busy / (self.shards * self._sim_T_async),
                    4))
            for s in range(self.shards):
                reg.gauge(
                    f"shard{s}_stall_frac",
                    "per-chip stall fraction (async schedule)").set(
                    round(1.0 - float(self._sim_busy[s])
                          / self._sim_T_async, 4))
        if self._sim_T_lock > 0:
            reg.gauge(
                "chip_stall_frac_lockstep",
                "stall fraction the lockstep schedule would incur on "
                "the same batches").set(round(
                    1.0 - float(self._sim_busy.sum())
                    / (self.shards * self._sim_T_lock), 4))
        if self._async_wall_total > 0:
            reg.gauge(
                "chip_msgs_per_sec",
                "messages per second of measured async dispatch wall"
                ).set(round(self._msgs_total / self._async_wall_total,
                            2))
        if self._h2d_total_s > 0:
            reg.gauge(
                "h2d_overlap_frac",
                "fraction of H2D staging time overlapped under "
                "in-flight device compute").set(
                round(self._h2d_overlap_s / self._h2d_total_s, 4))

    def stall_stats(self) -> dict:
        """Bench/report surface for the deterministic stall schedule."""
        tot_busy = float(self._sim_busy.sum())
        S = self.shards
        return {
            "chip_stall_frac": (
                round(1.0 - tot_busy / (S * self._sim_T_async), 4)
                if self._sim_T_async > 0 else 0.0),
            "chip_stall_frac_lockstep": (
                round(1.0 - tot_busy / (S * self._sim_T_lock), 4)
                if self._sim_T_lock > 0 else 0.0),
            "h2d_overlap_frac": (
                round(self._h2d_overlap_s / self._h2d_total_s, 4)
                if self._h2d_total_s > 0 else 0.0),
            "chip_msgs_per_sec": (
                round(self._msgs_total / self._async_wall_total, 2)
                if self._async_wall_total > 0 else 0.0),
        }

    # -- elastic placement ---------------------------------------------

    def _home_shard(self, a: int) -> int:
        """Shard for a laneless balance message: the account's sticky
        home lane's CURRENT shard under the placement table, falling
        back to the static hash for accounts that never traded."""
        g = self._acct_lane.get(a)
        if g is None:
            return a % self.shards
        return int(self._perm[g]) // self.S_local

    def _note_load(self, cols) -> None:
        """Fold this batch's routed messages into the per-lane load
        EWMA. Matchable messages (BUY/SELL) weigh more: a taker can
        sweep up to max_fills makers, everything else is O(1)."""
        acts = cols["act"]
        laneful = ((acts == SQ.L_BUY) | (acts == SQ.L_SELL)
                   | (acts == SQ.L_CANCEL) | (acts == SQ.L_ADD_SYMBOL)
                   | (acts == SQ.L_PAYOUT_YES)
                   | (acts == SQ.L_PAYOUT_NO)
                   | (acts == SQ.L_REMOVE_SYMBOL))
        w = np.where((acts == SQ.L_BUY) | (acts == SQ.L_SELL),
                     MATCH_WORK_WEIGHT, 1.0)
        batch = np.bincount(
            cols["lane"][laneful].astype(np.int64),
            weights=w[laneful], minlength=self.cfg.lanes)
        if self.wall_feed and self.dispatch == "async":
            # measured per-chip walls feed the rebalancer: scale each
            # lane's weight by its CURRENT shard's cost rate so lanes
            # on genuinely-slow chips look hotter than their raw count
            batch = batch * self._shard_rate[
                (self._perm // self.S_local).astype(np.int64)]
        self._lane_load = (LOAD_EWMA_ALPHA * batch
                           + (1.0 - LOAD_EWMA_ALPHA) * self._lane_load)

    def _maybe_rebalance(self) -> None:
        if not self.rebalance or self.shards == 1:
            return
        new = plan_rebalance(self._lane_load, self._perm, self.shards,
                             threshold=self.rebalance_threshold)
        if new is None:
            return
        with self.timer.phase("migrate_s"):
            moved = self._migrate(new)
        if moved:
            self._rebalances += 1
            self._migrations += moved

    def _migrate(self, new_perm) -> int:
        """Permute the sharded lane axis of the state pytree to the new
        placement. Lane state moves WHOLESALE through the engine's
        canonical codec (export_canonical / import_canonical per
        shard): books, per-lane seq counters, and the lane-keyed
        position hash are re-keyed for the destination shard's local
        lane stride, while the replicated balance planes are untouched
        — so the migrated mesh state replays byte-identically.
        Returns the number of lanes that changed slot."""
        old_perm = self._perm
        moved = int((new_perm != old_perm).sum())
        if not moved:
            return 0
        # async mode: rebalancing only runs between batches, where the
        # per-shard queues are drained and every shard's replicated
        # planes are identical — gather to the stacked lockstep layout,
        # permute through the canonical codec, split back out
        async_mode = (self.dispatch == "async"
                      and self._shard_states is not None)
        if async_mode:
            self.state = self._gather_state_async()
        Sl, A = self.S_local, self.local_cfg.accounts
        host = {k: np.asarray(v) for k, v in self.state.items()}
        canons = []
        for s in range(self.shards):
            loc = {k: (v if k in self._REPL_KEYS
                       else v.reshape(self.shards, -1, v.shape[-1])[s])
                   for k, v in host.items()}
            canons.append(SQ.export_canonical(self.local_cfg, loc))
        # inverse of the NEW table: which global lane lands in slot g
        inv_new = np.empty_like(new_perm)
        inv_new[new_perm] = np.arange(len(new_perm),
                                      dtype=new_perm.dtype)
        parts = []
        for s in range(self.shards):
            src = []   # (old_shard, old_row) feeding each local row
            for r in range(Sl):
                g = int(inv_new[s * Sl + r])
                o = int(old_perm[g])
                src.append((o // Sl, o % Sl))
            tgt = dict(canons[0])   # replicated planes from shard 0
            for key in ("slot_oid", "slot_aid", "slot_price",
                        "slot_size", "slot_seq", "slot_used"):
                tgt[key] = np.stack(
                    [canons[ss][key][rr] for ss, rr in src])
            tgt["seq"] = np.stack(
                [canons[ss]["seq"][rr] for ss, rr in src])
            tgt["book_exists"] = np.stack(
                [canons[ss]["book_exists"][rr] for ss, rr in src])
            for key in ("pos_amt", "pos_avail"):
                tgt[key] = np.stack(
                    [canons[ss][key].reshape(Sl, A)[rr]
                     for ss, rr in src]).reshape(-1)
            parts.append(SQ.import_canonical(self.local_cfg, tgt))
        state = {}
        for k in host:
            if k in self._REPL_KEYS:
                state[k] = parts[0][k]
            else:
                state[k] = jnp.concatenate(
                    [parts[s][k] for s in range(self.shards)], axis=0)
        if async_mode:
            self._split_state_async(
                {k: np.asarray(v) for k, v in state.items()})
            self.state = None
        else:
            self.state = state
        self._perm = new_perm
        return moved

    # -- per-shard telemetry -------------------------------------------

    def _publish_shard_telemetry(self, disp_wall: float, occ) -> None:
        """Per-shard straggler attribution. The mesh scan is lockstep
        (one shard_map dispatch), so per-chip walls are not separately
        measurable from the host — attribution charges each shard an
        occupancy-weighted share of the batch's dispatch wall, the
        psum-mergeable convention the on-device histogram counters
        already use. shard_imbalance = cumulative max/mean per-shard
        occupancy (1.0 == perfectly balanced)."""
        self._occ_shard += occ
        reg = self.telemetry
        reg.gauge("shard_count", "mesh shard count").set(self.shards)
        reg.counter("shard_migrations_total",
                    "lane slots moved by elastic placement"
                    ).set(self._migrations)
        reg.counter("shard_rebalances_total",
                    "between-batch rebalance events"
                    ).set(self._rebalances)
        tot = int(self._occ_shard.sum())
        if tot:
            reg.gauge(
                "shard_imbalance",
                "max/mean per-shard cumulative occupancy").set(
                round(float(self._occ_shard.max())
                      * self.shards / tot, 4))
        btot = int(occ.sum())
        for s in range(self.shards):
            reg.gauge(f"shard{s}_occupancy",
                      "cumulative messages executed on shard").set(
                int(self._occ_shard[s]))
            if btot and int(occ[s]):
                reg.latency(
                    f"device_shard{s}",
                    "occupancy-weighted device wall share").observe(
                    disp_wall * float(occ[s]) / btot, n=int(occ[s]))

    def shard_stats(self) -> dict:
        """Bench/report surface: per-shard occupancy + imbalance."""
        tot = int(self._occ_shard.sum())
        return {
            "shards": self.shards,
            "occupancy": self._occ_shard.tolist(),
            "imbalance": (round(float(self._occ_shard.max())
                                * self.shards / tot, 4)
                          if tot else 0.0),
            "migrations": self._migrations,
            "rebalances": self._rebalances,
        }

    # -- the SeqSession metric surface ---------------------------------

    def histograms(self) -> Dict[str, list]:
        out = {name: self._hist[i].tolist()
               for i, name in enumerate(SQ.HIST_NAMES)}
        for s in range(self.shards):
            for i, name in enumerate(SQ.HIST_NAMES):
                out[f"{name}_shard{s}"] = self._hist_shard[s][i].tolist()
        self.telemetry.publish_histograms(out)
        return out

    def metrics(self) -> Dict[str, int]:
        counters = dict(zip(SQ.METRIC_NAMES, self._metrics.tolist()))
        counters["shard_migrations"] = self._migrations
        counters["shard_rebalances"] = self._rebalances
        tot = int(self._occ_shard.sum())
        if tot:
            counters["shard_imbalance"] = round(
                float(self._occ_shard.max()) * self.shards / tot, 4)
        self._publish(counters)
        return counters

    def export_canonical_global(self) -> dict:
        """Stitch the per-shard canonical exports back into ONE
        global-cfg canonical dict through the inverse placement table.
        Every _run fully drains before returning (async submit queues
        never span host batches), so this is always a quiescent
        drain-to-barrier snapshot — a checkpoint landing between
        batches sees exactly the serial-session state."""
        Sl, A = self.S_local, self.local_cfg.accounts
        if self.dispatch == "async":
            host = self._gather_state_async()
        else:
            host = {k: np.asarray(v) for k, v in self.state.items()}
        canons = []
        for s in range(self.shards):
            loc = {k: (v if k in self._REPL_KEYS
                       else v.reshape(self.shards, -1, v.shape[-1])[s])
                   for k, v in host.items()}
            canons.append(SQ.export_canonical(self.local_cfg, loc))
        where = []   # global lane g -> (shard, local row)
        for g in range(self.cfg.lanes):
            slot = int(self._perm[g])
            where.append((slot // Sl, slot % Sl))
        gl = {}
        for key in ("slot_oid", "slot_aid", "slot_price", "slot_size",
                    "slot_seq", "slot_used"):
            gl[key] = np.stack([canons[ss][key][rr] for ss, rr in where])
        gl["seq"] = np.stack([canons[ss]["seq"][rr] for ss, rr in where])
        gl["book_exists"] = np.stack(
            [canons[ss]["book_exists"][rr] for ss, rr in where])
        for key in ("pos_amt", "pos_avail"):
            gl[key] = np.stack(
                [canons[ss][key].reshape(Sl, A)[rr]
                 for ss, rr in where]).reshape(-1)
        # replicated planes are identical across shards at batch
        # boundaries (psum merge / _collect_merge) — take shard 0
        gl["bal"] = canons[0]["bal"]
        gl["bal_used"] = canons[0]["bal_used"]
        gl["err"] = np.int32(max(int(c["err"]) for c in canons))
        gl["metrics"] = None
        return gl

    def export_state(self):
        """Oracle-comparable host dict view, both dispatch modes: the
        stitched global canon through SeqSession's shared mapping."""
        return self._canon_to_export(self.export_canonical_global())
