"""Multi-chip SEQ fleet: a symbol-sharded set of sequential mega-kernels
under ONE shard_map, bit-exact vs single-chip serial replay.

The flagship seq engine (kme_tpu/engine/seq.py) is strictly serial on
one chip. Scale-out follows the reference's partition model (the topic
is partitioned and Streams instances split partitions — topic.js:18,
KProcessor.java:59-60), TPU-first: lanes (books, positions, seq
counters) are SHARDED over the 'symbol' mesh axis — each device runs
its own seq kernel over its own message subsequence — and balances are
REPLICATED with exact psum delta-merges at window boundaries.

Why this is bit-exact (the window invariant): within one window every
ACCOUNT's messages live on a single shard, so an account's balance
evolves exactly as in serial replay (balance writes are always to the
acting account: taker debit/credit, transfer, cancel release; maker
fills credit price 0 and touch only lane-local position state). The
host planner (plan_windows) closes a window whenever a message's
account is already bound to a different shard, whenever a shard's
window capacity fills, and around barriers (PAYOUT/REMOVE credit many
accounts, so each runs alone in its own window). At a window boundary
each shard contributes an int64 balance delta with at most one nonzero
contributor per account — psum is exact, including Java-long wrap.

The sticky error plane is pmax-merged (any shard's envelope error
surfaces globally; WHICH error wins when several shards fail in one
window is unspecified, unlike the serial engine's first-error rule —
the error path aborts the stream either way).

ELASTIC PLACEMENT (this round): lanes are no longer pinned to shards
by the static `global_lane // S_local` layout. A placement table
(`_perm`: global lane -> global slot; shard = slot // S_local) starts
as the identity — byte-identical to the old static layout — and a
per-lane load EWMA drives BETWEEN-BATCH migrations of hot lanes to
underloaded shards (plan_rebalance decides, _migrate permutes the
sharded lane axis of the state pytree through the engine's canonical
codec). Correctness is placement-INDEPENDENT: the engine is a
deterministic state machine, so any symbol->shard assignment that
preserves the global application order and the per-window
account-disjointness invariant above yields byte-identical MatchOut —
which is what lets the planner rebalance aggressively and the tests
gate on oracle parity WITH migrations observed
(tests/test_shard_elastic.py, kme-bench --suite shards).

Executed evidence: tests/test_seqmesh.py (bit-exact at shards 1/2/8 on
a virtual mesh vs the scalar oracle and the single-chip SeqSession),
tests/test_multihost.py (the same program SPMD across two OS
processes), and __graft_entry__.dryrun_multichip (the driver's
multichip artifact).
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional

import numpy as np

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kme_tpu.engine import seq as SQ
from kme_tpu.native import sched as native_sched
from kme_tpu.parallel.mesh import AXIS, build_mesh
from kme_tpu.runtime.seqsession import SeqSession, make_seq_router
from kme_tpu.telemetry import PhaseTimer, Registry
from kme_tpu.utils import pow2_bucket

# per-shard per-window message capacity (windows close earlier on
# account conflicts; 128 keeps the padded input planes small)
WINDOW_CAP = 128

# rebalance when the hottest shard's EWMA load exceeds the mean by
# this factor; migrating costs a full canonical round-trip of the lane
# state, so the trigger is deliberately above measurement noise
REBALANCE_THRESHOLD = 1.25
# per-batch decay of the per-lane load estimate
LOAD_EWMA_ALPHA = 0.5
# matchable messages (BUY/SELL) sweep makers; everything else is O(1)
MATCH_WORK_WEIGHT = 2.0

_MSG_FIELDS = ("act", "aid", "price", "size", "lane",
               "oid_lo", "oid_hi")


def make_mesh_state(local_cfg: SQ.SeqConfig, shards: int) -> dict:
    """Global state pytree: per-shard seq states stacked on the leading
    row axis for the sharded keys; balances/err replicated."""
    local = SQ.make_seq_state(local_cfg)
    out = {}
    for k, v in local.items():
        if k in ("bal_lo", "bal_hi", "bal_u", "err"):
            out[k] = v
        else:
            out[k] = jnp.tile(v, (shards, 1))
    return out


def state_specs(local_cfg: SQ.SeqConfig) -> dict:
    specs = {}
    for k in SQ.state_keys(local_cfg):
        if k in ("bal_lo", "bal_hi", "bal_u", "err"):
            specs[k] = P()
        else:
            specs[k] = P(AXIS)
    return specs


def _i64(lo, hi):
    return ((lo.astype(jnp.int64) & 0xFFFFFFFF)
            | (hi.astype(jnp.int64) << 32))


def _split64(v):
    lo = v & 0xFFFFFFFF
    lo = jnp.where(lo >= 1 << 31, lo - (1 << 32), lo).astype(jnp.int32)
    return lo, (v >> 32).astype(jnp.int32)


def _shard_map(fn, mesh, in_specs, out_specs):
    """shard_map with varying-mesh-axes checking off: the body contains
    a pallas_call, whose out_shapes carry no vma annotation."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:  # pragma: no cover - older jax fallback
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except TypeError:  # older jax spells the flag check_rep
        try:
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
        except TypeError:  # pragma: no cover - jax without either flag
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs)


@functools.lru_cache(maxsize=None)
def build_seq_mesh_scan(local_cfg: SQ.SeqConfig, shards: int, K: int):
    """Jitted (state, wins) -> (state, out_planes): a lax.scan over K
    account-disjoint windows inside ONE shard_map. Each window: the
    per-shard seq kernel runs its local sub-batch, then balance deltas
    psum-merge (exact — see module docstring) and the sticky error
    pmax-merges."""
    mesh = build_mesh(shards)
    _, raw_call = SQ.build_seq_step(local_cfg)

    def body(state, win):
        start_lo = state["bal_lo"]
        start_hi = state["bal_hi"]
        start_u = state["bal_u"]
        st2, outp = raw_call(state, win)
        old = _i64(start_lo, start_hi)
        delta = _i64(st2["bal_lo"], st2["bal_hi"]) - old
        merged = old + jax.lax.psum(delta, AXIS)
        mlo, mhi = _split64(merged)
        mu = start_u + jax.lax.psum(st2["bal_u"] - start_u, AXIS)
        err = jax.lax.pmax(st2["err"], AXIS)
        st2 = dict(st2, bal_lo=mlo, bal_hi=mhi, bal_u=mu, err=err)
        # REPLICATE the window's out planes (all_gather over ICI/DCN):
        # under multi-process meshes the host can only fetch
        # fully-addressable arrays (tests/test_multihost.py)
        return st2, jax.lax.all_gather(outp, AXIS)

    def run(state, wins):
        return jax.lax.scan(body, state, wins, length=K)

    specs = state_specs(local_cfg)
    win_specs = {f: P(None, AXIS) for f in _MSG_FIELDS}
    # NO jit-level donation: it composes badly with the kernel's
    # input_output_aliases (clobbered aliased outputs — the documented
    # hazard in build_seq_step's NOTE), at the cost of one state copy
    # per dispatch.
    sharded = _shard_map(run, mesh, (specs, win_specs),
                         (specs, P()))
    return jax.jit(sharded)   # outs: (K, shards, NROWS, 128) replicated


def plan_rebalance(lane_load, perm, shards: int,
                   threshold: float = REBALANCE_THRESHOLD,
                   max_swaps: Optional[int] = None):
    """Pure placement decision: given the per-lane load EWMA and the
    current placement table, return a new table (or None for "stay").

    Greedy slot swaps between the hottest and coldest shard, accepted
    only while each swap STRICTLY reduces that pair's peak load, so the
    loop terminates and a balanced table is a fixed point. Fully
    deterministic (argmax/argmin first-index ties, no RNG) — the
    decision is replay-safe by construction, which kme-lint's KME-D002
    replay scope pins.
    """
    S = len(perm)
    Sl = S // shards
    total = float(lane_load.sum())
    if total <= 0.0:
        return None
    shard_loads = np.bincount(perm // Sl, weights=lane_load,
                              minlength=shards).astype(float)
    mean = total / shards
    if shard_loads.max() <= threshold * mean:
        return None
    new = perm.copy()
    budget = S if max_swaps is None else max_swaps
    swapped = False
    for _ in range(budget):
        h = int(shard_loads.argmax())
        c = int(shard_loads.argmin())
        if h == c:
            break
        # best single lane swap hot<->cold: minimize the pair's peak
        best = None
        for gh in range(S):
            if new[gh] // Sl != h:
                continue
            for gc in range(S):
                if new[gc] // Sl != c:
                    continue
                d = float(lane_load[gh]) - float(lane_load[gc])
                if d <= 0.0:
                    continue
                peak = max(shard_loads[h] - d, shard_loads[c] + d)
                if peak >= shard_loads[h]:
                    continue
                if best is None or peak < best[0]:
                    best = (peak, gh, gc, d)
        if best is None:
            break
        _, gh, gc, d = best
        new[gh], new[gc] = new[gc], new[gh]
        shard_loads[h] -= d
        shard_loads[c] += d
        swapped = True
    return new if swapped else None


class SeqMeshSession(SeqSession):
    """Sharded drop-in for SeqSession (fixed mode): same process /
    process_wire / process_wire_buffer surface, state sharded over a
    `shards`-device mesh. Durability/checkpointing rides the
    single-chip SeqSession or the lanes mesh — this session is the
    scale-out serving/validation path (export_state intentionally
    unsupported)."""

    # replicated state keys: migration must NOT permute these
    _REPL_KEYS = ("bal_lo", "bal_hi", "bal_u", "err")

    def __init__(self, cfg: SQ.SeqConfig, shards: int, *,
                 rebalance: bool = True,
                 rebalance_threshold: float = REBALANCE_THRESHOLD,
                 ) -> None:
        if cfg.compat != "fixed":
            raise ValueError(
                "sharded seq serving is fixed-mode only (java mode is "
                "single-chip by Q11's serial semantics, COMPAT.md)")
        if cfg.hbm_books:
            raise ValueError("seq mesh uses VMEM books per shard")
        if cfg.lanes % shards:
            raise ValueError(f"lanes {cfg.lanes} not divisible by "
                             f"{shards} shards")
        self.cfg = cfg
        self.shards = shards
        self.local_cfg = SQ.SeqConfig(
            lanes=cfg.lanes // shards, slots=cfg.slots,
            accounts=cfg.accounts, max_fills=cfg.max_fills,
            batch=WINDOW_CAP, pos_cap=cfg.pos_cap,
            fill_cap=cfg.fill_cap, probe_max=cfg.probe_max)
        self.S_local = cfg.lanes // shards
        self.state = make_mesh_state(self.local_cfg, shards)
        self.router = make_seq_router(cfg.lanes, cfg.accounts)
        self._metrics = np.zeros(SQ.N_METRICS, np.int64)
        self._hist = np.zeros((SQ.N_HIST, SQ.N_HIST_BUCKETS), np.int64)
        self._recon = None
        self.telemetry = Registry()
        self.timer = PhaseTimer(track="seqmesh")
        self.phases = self.timer.totals   # cumulative across batches
        self._use_native_wire = True
        self._ghint = 8
        # elastic placement: global lane -> global slot; shard of a
        # lane is perm[lane] // S_local, its kernel row perm[lane] %
        # S_local. Identity == the pre-elastic static layout.
        self.rebalance = rebalance
        self.rebalance_threshold = rebalance_threshold
        self._perm = np.arange(cfg.lanes, dtype=np.int64)
        self._lane_load = np.zeros(cfg.lanes, np.float64)
        # sticky account home: last GLOBAL LANE the account traded on
        # (tracked as a lane, not a shard, so homes follow migrations)
        self._acct_lane: Dict[int, int] = {}
        self._migrations = 0
        self._rebalances = 0
        self._occ_shard = np.zeros(shards, np.int64)
        self._hist_shard = np.zeros(
            (shards, SQ.N_HIST, SQ.N_HIST_BUCKETS), np.int64)

    # -- host planning -------------------------------------------------

    def plan_windows(self, cols):
        """Columnar routed messages -> (wins dict of (K, shards*Bw) i32,
        placements list of (window, shard, pos) per routed message,
        cnts (K, shards) int).

        A lane's shard and kernel row come from the elastic placement
        table (`_perm`, applied once per batch via
        native_sched.apply_placement), NOT the old static
        `lane // S_local` split. Laneless balance messages (CREATE/
        TRANSFER) follow the account's sticky home lane, which is the
        last lane it traded on — tracked as a LANE so a migration
        automatically re-pins the account to the lane's new shard and
        the balance-coupling window invariant survives rebalancing.

        The planner is host Python (per-message loop): fine for the
        dryrun/test scale this session targets; a measured multi-chip
        serving path would move it next to the C++ router
        (native/kme_router.cpp) like round 4 did for routing."""
        n = len(cols["act"])
        Bw = WINDOW_CAP
        acts = cols["act"]
        lanes = cols["lane"]
        aids = cols["aid"]
        _, shard_col, local_col = native_sched.apply_placement(
            self._perm, lanes, self.S_local)
        barrier = ((acts == SQ.L_PAYOUT_YES) | (acts == SQ.L_PAYOUT_NO)
                   | (acts == SQ.L_REMOVE_SYMBOL))
        laneful = ((acts == SQ.L_BUY) | (acts == SQ.L_SELL)
                   | (acts == SQ.L_CANCEL) | (acts == SQ.L_ADD_SYMBOL)
                   | barrier)
        # only balance-touching acts bind their account to a shard
        # (ADD_SYMBOL routes with aid=0 but never touches balances)
        binds = ((acts == SQ.L_BUY) | (acts == SQ.L_SELL)
                 | (acts == SQ.L_CANCEL) | (acts == SQ.L_CREATE)
                 | (acts == SQ.L_TRANSFER))
        windows: List[List[List[int]]] = []  # [w][s] -> routed indices
        placements = []
        bound: Dict[int, int] = {}
        cur = [[] for _ in range(self.shards)]

        def flush():
            nonlocal cur, bound
            if any(cur[s] for s in range(self.shards)):
                windows.append(cur)
            cur = [[] for _ in range(self.shards)]
            bound = {}

        for k in range(n):
            if barrier[k]:
                # barriers credit many accounts: run alone
                flush()
                cur[int(shard_col[k])].append(k)
                flush()
                continue
            a = int(aids[k])
            if laneful[k]:
                s = int(shard_col[k])
                if binds[k]:
                    self._acct_lane[a] = int(lanes[k])
            else:
                s = bound.get(a, self._home_shard(a))
            b = bound.get(a) if binds[k] else None
            if (b is not None and b != s) or len(cur[s]) >= Bw:
                flush()
            if binds[k]:
                bound[a] = s
            cur[s].append(k)
        flush()

        K = pow2_bucket(max(len(windows), 1), lo=1)
        wins = {f: np.zeros((K, self.shards, Bw), np.int32)
                for f in _MSG_FIELDS}
        cnts = np.zeros((K, self.shards), np.int32)
        for w, per in enumerate(windows):
            for s, idxs in enumerate(per):
                cnts[w, s] = len(idxs)
                for p, k in enumerate(idxs):
                    placements.append((k, w, s, p))
                    wins["act"][w, s, p] = cols["act"][k]
                    wins["aid"][w, s, p] = cols["aid"][k]
                    wins["price"][w, s, p] = cols["price"][k]
                    wins["size"][w, s, p] = cols["size"][k]
                    wins["lane"][w, s, p] = int(local_col[k])
                    oid = int(cols["oid"][k])
                    lo = oid & 0xFFFFFFFF
                    wins["oid_lo"][w, s, p] = np.int32(
                        lo - (1 << 32) if lo >= 1 << 31 else lo)
                    wins["oid_hi"][w, s, p] = np.int32(oid >> 32)
        wins = {f: v.reshape(K, self.shards * WINDOW_CAP)
                for f, v in wins.items()}
        placements.sort()
        return wins, placements, cnts, K

    # -- the SeqSession contract ---------------------------------------

    def _run(self, msgs):
        from kme_tpu.runtime.session import LaneEngineError

        # migrations happen BETWEEN batches only: state is quiescent
        # here, so the permutation is a pure relabeling of lane rows
        self._maybe_rebalance()

        with self.timer.phase("plan_s"):
            cols, host_rejects = self.router.route(msgs)
            self._note_load(cols)
            wins, placements, cnts, K = self.plan_windows(cols)

        with self.timer.phase("dispatch_s"):
            t_disp = time.perf_counter()
            scan = build_seq_mesh_scan(self.local_cfg, self.shards, K)
            self.state, outs = scan(self.state, wins)
            jax.block_until_ready(self.state)
            disp_wall = time.perf_counter() - t_disp

        with self.timer.phase("fetch_s"):
            outs = np.asarray(outs)   # (K, shards, NROWS, 128)
            HR = SQ.hdr_rows(self.local_cfg)
            n = len(cols["act"])
            host = {k: np.zeros(n, dt) for k, dt in
                    (("ok", bool), ("cap_reject", bool),
                     ("append", bool), ("residual", np.int64),
                     ("nfill", np.int64), ("prev_oid", np.int64))}
            groups = {}
            mets = np.zeros(SQ.N_METRICS, np.int64)
            # batch_occupancy convention (documented + tested,
            # tests/test_shard_elastic.py): per-(window, shard) kernel
            # calls are the dispatch units here, so batch_occupancy
            # observes per-shard SUB-WINDOWS — one observation per
            # non-empty (w, s) cell, valued at that cell's message
            # count cnts[w, s], NOT one blended observation per host
            # batch like the single-chip session. The same counters
            # accumulate per shard into _hist_shard and surface as
            # batch_occupancy_shard{N} (histograms()); the cumulative
            # per-shard occupancy totals (_occ_shard) feed the
            # shard_imbalance gauge = max/mean per-shard occupancy.
            hists = np.zeros((SQ.N_HIST, SQ.N_HIST_BUCKETS), np.int64)
            for w in range(K):
                for s in range(self.shards):
                    cnt = int(cnts[w, s])
                    if not cnt:
                        continue
                    res = SQ.unpack_hdr(self.local_cfg,
                                        outs[w, s][:HR], cnt)
                    if res["err"] != SQ.LERR_OK:
                        raise LaneEngineError(res["err"])
                    ft = res["fill_total"]
                    gr = outs[w, s][HR:HR + 5 * (-(-max(ft, 1) // 128))]
                    groups[(w, s)] = (res, SQ.unpack_fills(gr, ft),
                                      np.concatenate(
                                          ([0], np.cumsum(res["nfill"]))))
                    mets += res["metrics"]
                    hists += res["hist"]
                    self._hist_shard[s] += res["hist"]
            self._metrics += mets
            self._hist += hists
            self._publish_shard_telemetry(
                disp_wall, cnts.sum(axis=0).astype(np.int64))
            fills_parts = []
            for k, w, s, p in placements:
                res, fills_ws, off = groups[(w, s)]
                for key in host:
                    host[key][k] = res[key][p]
                if res["nfill"][p]:
                    fills_parts.append(fills_ws[:, off[p]:off[p + 1]])
            fills = (np.concatenate(fills_parts, axis=1) if fills_parts
                     else np.zeros((4, 0), np.int64))
        return cols, host_rejects, host, fills

    # -- elastic placement ---------------------------------------------

    def _home_shard(self, a: int) -> int:
        """Shard for a laneless balance message: the account's sticky
        home lane's CURRENT shard under the placement table, falling
        back to the static hash for accounts that never traded."""
        g = self._acct_lane.get(a)
        if g is None:
            return a % self.shards
        return int(self._perm[g]) // self.S_local

    def _note_load(self, cols) -> None:
        """Fold this batch's routed messages into the per-lane load
        EWMA. Matchable messages (BUY/SELL) weigh more: a taker can
        sweep up to max_fills makers, everything else is O(1)."""
        acts = cols["act"]
        laneful = ((acts == SQ.L_BUY) | (acts == SQ.L_SELL)
                   | (acts == SQ.L_CANCEL) | (acts == SQ.L_ADD_SYMBOL)
                   | (acts == SQ.L_PAYOUT_YES)
                   | (acts == SQ.L_PAYOUT_NO)
                   | (acts == SQ.L_REMOVE_SYMBOL))
        w = np.where((acts == SQ.L_BUY) | (acts == SQ.L_SELL),
                     MATCH_WORK_WEIGHT, 1.0)
        batch = np.bincount(
            cols["lane"][laneful].astype(np.int64),
            weights=w[laneful], minlength=self.cfg.lanes)
        self._lane_load = (LOAD_EWMA_ALPHA * batch
                           + (1.0 - LOAD_EWMA_ALPHA) * self._lane_load)

    def _maybe_rebalance(self) -> None:
        if not self.rebalance or self.shards == 1:
            return
        new = plan_rebalance(self._lane_load, self._perm, self.shards,
                             threshold=self.rebalance_threshold)
        if new is None:
            return
        with self.timer.phase("migrate_s"):
            moved = self._migrate(new)
        if moved:
            self._rebalances += 1
            self._migrations += moved

    def _migrate(self, new_perm) -> int:
        """Permute the sharded lane axis of the state pytree to the new
        placement. Lane state moves WHOLESALE through the engine's
        canonical codec (export_canonical / import_canonical per
        shard): books, per-lane seq counters, and the lane-keyed
        position hash are re-keyed for the destination shard's local
        lane stride, while the replicated balance planes are untouched
        — so the migrated mesh state replays byte-identically.
        Returns the number of lanes that changed slot."""
        old_perm = self._perm
        moved = int((new_perm != old_perm).sum())
        if not moved:
            return 0
        Sl, A = self.S_local, self.local_cfg.accounts
        host = {k: np.asarray(v) for k, v in self.state.items()}
        canons = []
        for s in range(self.shards):
            loc = {k: (v if k in self._REPL_KEYS
                       else v.reshape(self.shards, -1, v.shape[-1])[s])
                   for k, v in host.items()}
            canons.append(SQ.export_canonical(self.local_cfg, loc))
        # inverse of the NEW table: which global lane lands in slot g
        inv_new = np.empty_like(new_perm)
        inv_new[new_perm] = np.arange(len(new_perm),
                                      dtype=new_perm.dtype)
        parts = []
        for s in range(self.shards):
            src = []   # (old_shard, old_row) feeding each local row
            for r in range(Sl):
                g = int(inv_new[s * Sl + r])
                o = int(old_perm[g])
                src.append((o // Sl, o % Sl))
            tgt = dict(canons[0])   # replicated planes from shard 0
            for key in ("slot_oid", "slot_aid", "slot_price",
                        "slot_size", "slot_seq", "slot_used"):
                tgt[key] = np.stack(
                    [canons[ss][key][rr] for ss, rr in src])
            tgt["seq"] = np.stack(
                [canons[ss]["seq"][rr] for ss, rr in src])
            tgt["book_exists"] = np.stack(
                [canons[ss]["book_exists"][rr] for ss, rr in src])
            for key in ("pos_amt", "pos_avail"):
                tgt[key] = np.stack(
                    [canons[ss][key].reshape(Sl, A)[rr]
                     for ss, rr in src]).reshape(-1)
            parts.append(SQ.import_canonical(self.local_cfg, tgt))
        state = {}
        for k in host:
            if k in self._REPL_KEYS:
                state[k] = parts[0][k]
            else:
                state[k] = jnp.concatenate(
                    [parts[s][k] for s in range(self.shards)], axis=0)
        self.state = state
        self._perm = new_perm
        return moved

    # -- per-shard telemetry -------------------------------------------

    def _publish_shard_telemetry(self, disp_wall: float, occ) -> None:
        """Per-shard straggler attribution. The mesh scan is lockstep
        (one shard_map dispatch), so per-chip walls are not separately
        measurable from the host — attribution charges each shard an
        occupancy-weighted share of the batch's dispatch wall, the
        psum-mergeable convention the on-device histogram counters
        already use. shard_imbalance = cumulative max/mean per-shard
        occupancy (1.0 == perfectly balanced)."""
        self._occ_shard += occ
        reg = self.telemetry
        reg.gauge("shard_count", "mesh shard count").set(self.shards)
        reg.counter("shard_migrations_total",
                    "lane slots moved by elastic placement"
                    ).set(self._migrations)
        reg.counter("shard_rebalances_total",
                    "between-batch rebalance events"
                    ).set(self._rebalances)
        tot = int(self._occ_shard.sum())
        if tot:
            reg.gauge(
                "shard_imbalance",
                "max/mean per-shard cumulative occupancy").set(
                round(float(self._occ_shard.max())
                      * self.shards / tot, 4))
        btot = int(occ.sum())
        for s in range(self.shards):
            reg.gauge(f"shard{s}_occupancy",
                      "cumulative messages executed on shard").set(
                int(self._occ_shard[s]))
            if btot and int(occ[s]):
                reg.latency(
                    f"device_shard{s}",
                    "occupancy-weighted device wall share").observe(
                    disp_wall * float(occ[s]) / btot, n=int(occ[s]))

    def shard_stats(self) -> dict:
        """Bench/report surface: per-shard occupancy + imbalance."""
        tot = int(self._occ_shard.sum())
        return {
            "shards": self.shards,
            "occupancy": self._occ_shard.tolist(),
            "imbalance": (round(float(self._occ_shard.max())
                                * self.shards / tot, 4)
                          if tot else 0.0),
            "migrations": self._migrations,
            "rebalances": self._rebalances,
        }

    # -- the SeqSession metric surface ---------------------------------

    def histograms(self) -> Dict[str, list]:
        out = {name: self._hist[i].tolist()
               for i, name in enumerate(SQ.HIST_NAMES)}
        for s in range(self.shards):
            for i, name in enumerate(SQ.HIST_NAMES):
                out[f"{name}_shard{s}"] = self._hist_shard[s][i].tolist()
        self.telemetry.publish_histograms(out)
        return out

    def metrics(self) -> Dict[str, int]:
        counters = dict(zip(SQ.METRIC_NAMES, self._metrics.tolist()))
        counters["shard_migrations"] = self._migrations
        counters["shard_rebalances"] = self._rebalances
        tot = int(self._occ_shard.sum())
        if tot:
            counters["shard_imbalance"] = round(
                float(self._occ_shard.max()) * self.shards / tot, 4)
        self._publish(counters)
        return counters

    def export_state(self):
        raise NotImplementedError(
            "SeqMeshSession has no canonical export; durable serving "
            "rides the single-chip SeqSession (runtime/checkpoint.py)")
