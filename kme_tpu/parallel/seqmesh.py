"""Multi-chip SEQ fleet: a symbol-sharded set of sequential mega-kernels
under ONE shard_map, bit-exact vs single-chip serial replay.

The flagship seq engine (kme_tpu/engine/seq.py) is strictly serial on
one chip. Scale-out follows the reference's partition model (the topic
is partitioned and Streams instances split partitions — topic.js:18,
KProcessor.java:59-60), TPU-first: lanes (books, positions, seq
counters) are SHARDED over the 'symbol' mesh axis — each device runs
its own seq kernel over its own message subsequence — and balances are
REPLICATED with exact psum delta-merges at window boundaries.

Why this is bit-exact (the window invariant): within one window every
ACCOUNT's messages live on a single shard, so an account's balance
evolves exactly as in serial replay (balance writes are always to the
acting account: taker debit/credit, transfer, cancel release; maker
fills credit price 0 and touch only lane-local position state). The
host planner (plan_windows) closes a window whenever a message's
account is already bound to a different shard, whenever a shard's
window capacity fills, and around barriers (PAYOUT/REMOVE credit many
accounts, so each runs alone in its own window). At a window boundary
each shard contributes an int64 balance delta with at most one nonzero
contributor per account — psum is exact, including Java-long wrap.

The sticky error plane is pmax-merged (any shard's envelope error
surfaces globally; WHICH error wins when several shards fail in one
window is unspecified, unlike the serial engine's first-error rule —
the error path aborts the stream either way).

Executed evidence: tests/test_seqmesh.py (bit-exact at shards 1/2/8 on
a virtual mesh vs the scalar oracle and the single-chip SeqSession),
tests/test_multihost.py (the same program SPMD across two OS
processes), and __graft_entry__.dryrun_multichip (the driver's
multichip artifact).
"""

from __future__ import annotations

import functools
from typing import Dict, List

import numpy as np

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kme_tpu.engine import seq as SQ
from kme_tpu.parallel.mesh import AXIS, build_mesh
from kme_tpu.runtime.seqsession import SeqSession, make_seq_router
from kme_tpu.telemetry import PhaseTimer, Registry
from kme_tpu.utils import pow2_bucket

# per-shard per-window message capacity (windows close earlier on
# account conflicts; 128 keeps the padded input planes small)
WINDOW_CAP = 128

_MSG_FIELDS = ("act", "aid", "price", "size", "lane",
               "oid_lo", "oid_hi")


def make_mesh_state(local_cfg: SQ.SeqConfig, shards: int) -> dict:
    """Global state pytree: per-shard seq states stacked on the leading
    row axis for the sharded keys; balances/err replicated."""
    local = SQ.make_seq_state(local_cfg)
    out = {}
    for k, v in local.items():
        if k in ("bal_lo", "bal_hi", "bal_u", "err"):
            out[k] = v
        else:
            out[k] = jnp.tile(v, (shards, 1))
    return out


def state_specs(local_cfg: SQ.SeqConfig) -> dict:
    specs = {}
    for k in SQ.state_keys(local_cfg):
        if k in ("bal_lo", "bal_hi", "bal_u", "err"):
            specs[k] = P()
        else:
            specs[k] = P(AXIS)
    return specs


def _i64(lo, hi):
    return ((lo.astype(jnp.int64) & 0xFFFFFFFF)
            | (hi.astype(jnp.int64) << 32))


def _split64(v):
    lo = v & 0xFFFFFFFF
    lo = jnp.where(lo >= 1 << 31, lo - (1 << 32), lo).astype(jnp.int32)
    return lo, (v >> 32).astype(jnp.int32)


def _shard_map(fn, mesh, in_specs, out_specs):
    """shard_map with varying-mesh-axes checking off: the body contains
    a pallas_call, whose out_shapes carry no vma annotation."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:  # pragma: no cover - older jax fallback
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except TypeError:  # older jax spells the flag check_rep
        try:
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
        except TypeError:  # pragma: no cover - jax without either flag
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs)


@functools.lru_cache(maxsize=None)
def build_seq_mesh_scan(local_cfg: SQ.SeqConfig, shards: int, K: int):
    """Jitted (state, wins) -> (state, out_planes): a lax.scan over K
    account-disjoint windows inside ONE shard_map. Each window: the
    per-shard seq kernel runs its local sub-batch, then balance deltas
    psum-merge (exact — see module docstring) and the sticky error
    pmax-merges."""
    mesh = build_mesh(shards)
    _, raw_call = SQ.build_seq_step(local_cfg)

    def body(state, win):
        start_lo = state["bal_lo"]
        start_hi = state["bal_hi"]
        start_u = state["bal_u"]
        st2, outp = raw_call(state, win)
        old = _i64(start_lo, start_hi)
        delta = _i64(st2["bal_lo"], st2["bal_hi"]) - old
        merged = old + jax.lax.psum(delta, AXIS)
        mlo, mhi = _split64(merged)
        mu = start_u + jax.lax.psum(st2["bal_u"] - start_u, AXIS)
        err = jax.lax.pmax(st2["err"], AXIS)
        st2 = dict(st2, bal_lo=mlo, bal_hi=mhi, bal_u=mu, err=err)
        # REPLICATE the window's out planes (all_gather over ICI/DCN):
        # under multi-process meshes the host can only fetch
        # fully-addressable arrays (tests/test_multihost.py)
        return st2, jax.lax.all_gather(outp, AXIS)

    def run(state, wins):
        return jax.lax.scan(body, state, wins, length=K)

    specs = state_specs(local_cfg)
    win_specs = {f: P(None, AXIS) for f in _MSG_FIELDS}
    # NO jit-level donation: it composes badly with the kernel's
    # input_output_aliases (clobbered aliased outputs — the documented
    # hazard in build_seq_step's NOTE), at the cost of one state copy
    # per dispatch.
    sharded = _shard_map(run, mesh, (specs, win_specs),
                         (specs, P()))
    return jax.jit(sharded)   # outs: (K, shards, NROWS, 128) replicated


class SeqMeshSession(SeqSession):
    """Sharded drop-in for SeqSession (fixed mode): same process /
    process_wire / process_wire_buffer surface, state sharded over a
    `shards`-device mesh. Durability/checkpointing rides the
    single-chip SeqSession or the lanes mesh — this session is the
    scale-out serving/validation path (export_state intentionally
    unsupported)."""

    def __init__(self, cfg: SQ.SeqConfig, shards: int) -> None:
        if cfg.compat != "fixed":
            raise ValueError(
                "sharded seq serving is fixed-mode only (java mode is "
                "single-chip by Q11's serial semantics, COMPAT.md)")
        if cfg.hbm_books:
            raise ValueError("seq mesh uses VMEM books per shard")
        if cfg.lanes % shards:
            raise ValueError(f"lanes {cfg.lanes} not divisible by "
                             f"{shards} shards")
        self.cfg = cfg
        self.shards = shards
        self.local_cfg = SQ.SeqConfig(
            lanes=cfg.lanes // shards, slots=cfg.slots,
            accounts=cfg.accounts, max_fills=cfg.max_fills,
            batch=WINDOW_CAP, pos_cap=cfg.pos_cap,
            fill_cap=cfg.fill_cap, probe_max=cfg.probe_max)
        self.S_local = cfg.lanes // shards
        self.state = make_mesh_state(self.local_cfg, shards)
        self.router = make_seq_router(cfg.lanes, cfg.accounts)
        self._metrics = np.zeros(SQ.N_METRICS, np.int64)
        self._hist = np.zeros((SQ.N_HIST, SQ.N_HIST_BUCKETS), np.int64)
        self._recon = None
        self.telemetry = Registry()
        self.timer = PhaseTimer(track="seqmesh")
        self.phases = self.timer.totals   # cumulative across batches
        self._use_native_wire = True
        self._ghint = 8

    # -- host planning -------------------------------------------------

    def plan_windows(self, cols):
        """Columnar routed messages -> (wins dict of (K, shards*Bw) i32,
        placements list of (window, shard, pos) per routed message,
        cnts (K, shards) int).

        The planner is host Python (per-message loop): fine for the
        dryrun/test scale this session targets; a measured multi-chip
        serving path would move it next to the C++ router
        (native/kme_router.cpp) like round 4 did for routing."""
        n = len(cols["act"])
        Bw = WINDOW_CAP
        acts = cols["act"]
        lanes = cols["lane"]
        aids = cols["aid"]
        barrier = ((acts == SQ.L_PAYOUT_YES) | (acts == SQ.L_PAYOUT_NO)
                   | (acts == SQ.L_REMOVE_SYMBOL))
        laneful = ((acts == SQ.L_BUY) | (acts == SQ.L_SELL)
                   | (acts == SQ.L_CANCEL) | (acts == SQ.L_ADD_SYMBOL)
                   | barrier)
        # only balance-touching acts bind their account to a shard
        # (ADD_SYMBOL routes with aid=0 but never touches balances)
        binds = ((acts == SQ.L_BUY) | (acts == SQ.L_SELL)
                 | (acts == SQ.L_CANCEL) | (acts == SQ.L_CREATE)
                 | (acts == SQ.L_TRANSFER))
        windows: List[List[List[int]]] = []  # [w][s] -> routed indices
        placements = []
        bound: Dict[int, int] = {}
        cur = [[] for _ in range(self.shards)]

        def flush():
            nonlocal cur, bound
            if any(cur[s] for s in range(self.shards)):
                windows.append(cur)
            cur = [[] for _ in range(self.shards)]
            bound = {}

        for k in range(n):
            if barrier[k]:
                # barriers credit many accounts: run alone
                flush()
                s = int(lanes[k]) // self.S_local
                cur[s].append(k)
                flush()
                continue
            a = int(aids[k])
            if laneful[k]:
                s = int(lanes[k]) // self.S_local
            else:
                s = bound.get(a, a % self.shards)
            b = bound.get(a) if binds[k] else None
            if (b is not None and b != s) or len(cur[s]) >= Bw:
                flush()
            if binds[k]:
                bound[a] = s
            cur[s].append(k)
        flush()

        K = pow2_bucket(max(len(windows), 1), lo=1)
        wins = {f: np.zeros((K, self.shards, Bw), np.int32)
                for f in _MSG_FIELDS}
        cnts = np.zeros((K, self.shards), np.int32)
        for w, per in enumerate(windows):
            for s, idxs in enumerate(per):
                cnts[w, s] = len(idxs)
                for p, k in enumerate(idxs):
                    placements.append((k, w, s, p))
                    wins["act"][w, s, p] = cols["act"][k]
                    wins["aid"][w, s, p] = cols["aid"][k]
                    wins["price"][w, s, p] = cols["price"][k]
                    wins["size"][w, s, p] = cols["size"][k]
                    wins["lane"][w, s, p] = (int(cols["lane"][k])
                                             % self.S_local)
                    oid = int(cols["oid"][k])
                    lo = oid & 0xFFFFFFFF
                    wins["oid_lo"][w, s, p] = np.int32(
                        lo - (1 << 32) if lo >= 1 << 31 else lo)
                    wins["oid_hi"][w, s, p] = np.int32(oid >> 32)
        wins = {f: v.reshape(K, self.shards * WINDOW_CAP)
                for f, v in wins.items()}
        placements.sort()
        return wins, placements, cnts, K

    # -- the SeqSession contract ---------------------------------------

    def _run(self, msgs):
        from kme_tpu.runtime.session import LaneEngineError

        with self.timer.phase("plan_s"):
            cols, host_rejects = self.router.route(msgs)
            wins, placements, cnts, K = self.plan_windows(cols)

        with self.timer.phase("dispatch_s"):
            scan = build_seq_mesh_scan(self.local_cfg, self.shards, K)
            self.state, outs = scan(self.state, wins)
            jax.block_until_ready(self.state)

        with self.timer.phase("fetch_s"):
            outs = np.asarray(outs)   # (K, shards, NROWS, 128)
            HR = SQ.hdr_rows(self.local_cfg)
            n = len(cols["act"])
            host = {k: np.zeros(n, dt) for k, dt in
                    (("ok", bool), ("cap_reject", bool),
                     ("append", bool), ("residual", np.int64),
                     ("nfill", np.int64), ("prev_oid", np.int64))}
            groups = {}
            mets = np.zeros(SQ.N_METRICS, np.int64)
            # per-(window, shard) kernel calls are the dispatch units
            # here, so batch_occupancy observes per-shard sub-windows
            hists = np.zeros((SQ.N_HIST, SQ.N_HIST_BUCKETS), np.int64)
            for w in range(K):
                for s in range(self.shards):
                    cnt = int(cnts[w, s])
                    if not cnt:
                        continue
                    res = SQ.unpack_hdr(self.local_cfg,
                                        outs[w, s][:HR], cnt)
                    if res["err"] != SQ.LERR_OK:
                        raise LaneEngineError(res["err"])
                    ft = res["fill_total"]
                    gr = outs[w, s][HR:HR + 5 * (-(-max(ft, 1) // 128))]
                    groups[(w, s)] = (res, SQ.unpack_fills(gr, ft),
                                      np.concatenate(
                                          ([0], np.cumsum(res["nfill"]))))
                    mets += res["metrics"]
                    hists += res["hist"]
            self._metrics += mets
            self._hist += hists
            fills_parts = []
            for k, w, s, p in placements:
                res, fills_ws, off = groups[(w, s)]
                for key in host:
                    host[key][k] = res[key][p]
                if res["nfill"][p]:
                    fills_parts.append(fills_ws[:, off[p]:off[p + 1]])
            fills = (np.concatenate(fills_parts, axis=1) if fills_parts
                     else np.zeros((4, 0), np.int64))
        return cols, host_rejects, host, fills

    def metrics(self) -> Dict[str, int]:
        counters = dict(zip(SQ.METRIC_NAMES, self._metrics.tolist()))
        self._publish(counters)
        return counters

    def export_state(self):
        raise NotImplementedError(
            "SeqMeshSession has no canonical export; durable serving "
            "rides the single-chip SeqSession (runtime/checkpoint.py)")
