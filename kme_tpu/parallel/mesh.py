"""Sharded engine step: shard_map over the 'symbol' mesh axis.

Layout (SURVEY.md §2.3 "TPU-native equivalent", §7 step 6):
- lane state (books, positions, seq, flags): sharded on the leading
  symbol axis — each device owns S/n contiguous lanes;
- account state (balances): replicated; every step produces a dense
  (A,) delta on each shard which is psum-merged — exact because the
  scheduler guarantees per-step account disjointness (lanes.py
  docstring), so the sum has at most one non-zero contributor per slot;
- the sticky error code: pmax-merged so any shard's envelope error
  surfaces globally;
- barrier ops (payout/remove): the owning shard resolves the global
  lane to its local index, wipes/credits locally, and the balance
  delta rides the same psum.

The same step function works single-device (axis_name=None) — the
sharded build is a thin shard_map wrapper around engine/lanes.py.

Multi-host (DCN): the mesh is built from jax.devices(), so under
`jax.distributed.initialize()` the same code spans hosts — the symbol
axis lays contiguous lane blocks per process, keeping the per-step
balance/metric psum on ICI within a slice and crossing DCN only for the
rare barrier settles and the replicated (A,)-sized merges (the only
cross-shard traffic this design has; fills ride the GSPMD gather in
kme_tpu/engine/lanes.py chunk_compaction). EXECUTED EVIDENCE:
tests/test_multihost.py runs the sharded session SPMD across two OS
processes (4 virtual CPU devices each, one 8-way jax.distributed mesh)
and requires the wire stream bit-identical to a single-process run —
the reference analog of multiple Streams instances joining one group
(KProcessor.java:59-60).
"""

from __future__ import annotations

import functools

import numpy as np

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kme_tpu.engine import lanes as L

AXIS = "symbol"


def _shard_map(fn, mesh, in_specs, out_specs):
    sm = getattr(jax, "shard_map", None)
    if sm is None:  # pragma: no cover - older jax fallback
        from jax.experimental.shard_map import shard_map as sm
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


@functools.lru_cache(maxsize=None)
def build_mesh(shards: int) -> Mesh:
    """One Mesh per shard count per process — sessions share it, so the
    jitted sharded builders below cache across sessions exactly like the
    single-device build_lane_chunk lru_cache."""
    devs = jax.devices()
    if len(devs) < shards:
        raise ValueError(
            f"need {shards} devices for {shards} shards, have {len(devs)}")
    return Mesh(np.array(devs[:shards]), axis_names=(AXIS,))


def state_specs(state) -> dict:
    """PartitionSpec pytree for the lane state: lane-major arrays sharded
    on the symbol axis, account/global arrays replicated."""
    specs = {}
    for k, v in state.items():
        if k in ("bal", "bal_used", "err", "metrics", "hist", "fillbuf",
                 "filloff"):
            # the packed fill log is REPLICATED: the chunk wrapper runs
            # under GSPMD, which gathers each window's compact (M, E)
            # fills over the mesh before the append — so every shard
            # holds the identical log and the host fetches one slice
            specs[k] = P()
        else:
            specs[k] = P(AXIS)
    return specs


def build_sharded_step(cfg: L.LaneConfig, mesh: Mesh):
    """(state, batch) -> (state, outs), lanes sharded over `mesh`."""
    assert cfg.lanes % mesh.devices.size == 0, (cfg.lanes, mesh.devices.size)
    local_cfg = L.LaneConfig(
        lanes=cfg.lanes // mesh.devices.size, slots=cfg.slots,
        accounts=cfg.accounts, max_fills=cfg.max_fills, steps=cfg.steps)
    inner = L.build_lane_step(local_cfg, axis_name=AXIS)

    st_specs = state_specs(L.make_lane_state(cfg))
    batch_specs = {k: P(None, AXIS) for k in ("act", "oid", "aid", "price",
                                              "size")}
    out_specs = {
        "ok": P(None, AXIS), "residual": P(None, AXIS),
        "append": P(None, AXIS), "prev_oid": P(None, AXIS),
        "nfill": P(None, AXIS), "cap_reject": P(None, AXIS),
        "fill_oid": P(None, AXIS),
        "fill_aid": P(None, AXIS), "fill_price": P(None, AXIS),
        "fill_size": P(None, AXIS), "err": P(),
    }
    return _shard_map(inner, mesh, (st_specs, batch_specs),
                      (st_specs, out_specs))


def build_sharded_chunk(cfg: L.LaneConfig, mesh: Mesh, T: int, M: int):
    """Compact-I/O chunk (L.chunk_compaction) around the SHARDED scan:
    the (M,) message vectors stay replicated, the grid scatter and output
    compaction run under GSPMD (with_sharding_constraint pins the grids
    to the symbol axis), and the scan itself is the shard_map step.
    Fills ride the same packed device log as the single-device path:
    GSPMD gathers the per-window compact (M, E) fill outputs over the
    mesh (ICI all-gather of compact data, never dense grids) and the
    append lands identically on every shard's replicated log."""
    sstep = build_sharded_step(cfg, mesh)
    grid_sh = NamedSharding(mesh, P(None, AXIS))

    def pinned_step(state, batch):
        batch = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, grid_sh), batch)
        return sstep(state, batch)

    return L.chunk_compaction(cfg, T, M, pinned_step)


def build_sharded_settle(cfg: L.LaneConfig, mesh: Mesh):
    """(state, global_lane, credit_size, mode) -> (state, ok), sharded.

    The owning shard computes its local lane index; other shards pass
    lane=-1 (no-op) and contribute zero to the psum'd balance delta."""
    n = mesh.devices.size
    assert cfg.lanes % n == 0
    S_local = cfg.lanes // n
    local_cfg = L.LaneConfig(lanes=S_local, slots=cfg.slots,
                             accounts=cfg.accounts, max_fills=cfg.max_fills,
                             steps=cfg.steps)
    inner = L.build_barrier_ops(local_cfg, axis_name=AXIS)

    def settle(state, global_lane, credit_size, mode):
        shard = jax.lax.axis_index(AXIS).astype(jnp.int32)
        owner = global_lane // S_local == shard
        local = jnp.where(owner, global_lane % S_local, -1).astype(jnp.int32)
        return inner(state, local, credit_size, mode)

    st_specs = state_specs(L.make_lane_state(cfg))
    return _shard_map(settle, mesh, (st_specs, P(), P(), P()),
                      (st_specs, P()))


@functools.lru_cache(maxsize=None)
def build_sharded_chunk_jit(cfg: L.LaneConfig, shards: int, T: int, M: int):
    """Jitted sharded chunk with state donation, cached per static shape
    at MODULE level — sharded sessions share compiled executables."""
    mesh = build_mesh(shards)
    return jax.jit(build_sharded_chunk(cfg, mesh, T, M), donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def build_sharded_settle_jit(cfg: L.LaneConfig, shards: int):
    mesh = build_mesh(shards)
    return jax.jit(build_sharded_settle(cfg, mesh), donate_argnums=(0,))


def shard_state(state, mesh: Mesh):
    """Place a host-built state pytree onto the mesh with its specs."""
    specs = state_specs(state)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs,
        is_leaf=lambda x: not isinstance(x, dict))
