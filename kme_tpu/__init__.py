"""kme_tpu — TPU-native matching-engine framework.

A ground-up JAX/XLA/Pallas/pjit re-design of the capabilities of the
reference VD44/Kafka-Matching-Engine (a Kafka Streams limit-order-book
processor, /root/reference/src/main/java/KProcessor.java): prediction-market
style binary-outcome contracts, integer prices 0..125, margin `price` per
unit for buys and `100 - price` per unit for sells
(KProcessor.java:167-182), account ledgers, pre-trade risk checks,
price-time-priority matching, cancels, and symbol settlement.

Instead of one message at a time against five RocksDB stores, this framework
keeps the entire exchange state resident in dense device arrays (HBM),
processes conflict-free micro-batch steps with `lax.scan` (serial in time,
parallel across symbols via `vmap`), and shards the symbol axis over a TPU
mesh with `shard_map`, merging cross-shard account-balance deltas with exact
integer `psum` collectives over ICI.

Package layout:
  oracle/    quirk-faithful pure-Python replica of the reference semantics
             (the golden parity judge; compat='java' and compat='fixed')
  engine/    the device engines: parity.py (serial quirk-exact replica as
             one lax.scan) and lanes.py (the throughput engine: compacted
             per-symbol lanes, sort+prefix-sum matching, on-device
             metrics, packed fill log)
  ops/       exact bit/codec device utilities and associative tables
  parallel/  mesh construction, sharding specs, psum-merged collectives
  runtime/   host runtime: conflict-free scheduler (sequencer.py), the
             batching session with compact device I/O (session.py), and
             checkpoint/resume (checkpoint.py)
  bridge/    transport edge speaking the reference's Kafka wire contract:
             broker core with durable logs, TCP process boundary, and the
             MatchIn -> engine -> MatchOut service + CLIs
  wire/workload/opcodes/benchmarks/cli  byte-exact serde, seeded harness
             workloads, protocol constants, bench suite, entry points

Compatibility envelope and mode matrix: COMPAT.md at the repo root.
The top-level package is import-light: the pure-Python layers (wire,
oracle, workload) work without JAX. Device modules (engine/, ops/,
parallel/) import `kme_tpu._jaxsetup` which enables x64 once.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("KME_LOCKCHECK") == "1":
    # opt-in lock-order recorder: must patch threading.Lock/RLock
    # before any kme_tpu module allocates a lock, hence here at the
    # package root. See kme_tpu/analysis/lockcheck.py; tier-1 runs
    # with this set assert no inversions at session teardown.
    from kme_tpu.analysis import lockcheck as _lockcheck

    _lockcheck.install()

from kme_tpu import opcodes  # noqa: F401
