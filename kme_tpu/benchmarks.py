"""Benchmark suite (BASELINE.md matrix).

The reference publishes no numbers (BASELINE.md); its structural bound is
single-digit-thousands of orders/sec (serial awaited produce per order,
commit per record, JSON serde, RocksDB round-trips — BASELINE.md table).
`REFERENCE_BASELINE_OPS` pins the top of that band (5k orders/sec) as the
denominator for `vs_baseline`, documented here so the ratio is honest and
reproducible.

Headline metric: matched orders/sec through the vmapped lane engine
(device dispatch phase) across 1k symbols — the BASELINE.md "1k symbols ×
100k orders" row. Host planning/packing and record reconstruction are
timed separately (they pipeline with device work in the serving path and
are the C++ runtime's optimization target).
"""

from __future__ import annotations

import json
import sys
import time

REFERENCE_BASELINE_OPS = 5_000.0  # orders/sec, derived bound (BASELINE.md)


def bench_lane_engine(events: int = 100_000, symbols: int = 1024,
                      accounts: int = 2048, seed: int = 0,
                      zipf_a: float = 0.0, steps: int = 64,
                      slots: int = 64, max_fills: int = 16,
                      shards: int = 1) -> dict:
    """Lane-engine throughput: plan+pack (host), dispatch (device, timed
    as the headline), reconstruct (host). Fill parity is asserted on a
    prefix via the scalar oracle elsewhere (tests); here we count fills."""
    import jax

    from kme_tpu.engine.lanes import LaneConfig
    from kme_tpu.runtime.session import LaneSession
    from kme_tpu.workload import zipf_symbol_stream

    cfg = LaneConfig(lanes=symbols, slots=slots, accounts=accounts,
                     max_fills=max_fills, steps=steps)
    msgs = zipf_symbol_stream(events, num_symbols=symbols,
                              num_accounts=accounts, seed=seed,
                              zipf_a=zipf_a)
    ses = LaneSession(cfg, shards=shards)

    t0 = time.perf_counter()
    sched = ses.scheduler.plan(msgs)
    t_plan = time.perf_counter() - t0

    t0 = time.perf_counter()
    packed = [ses._pack_segment(sched, i) for i in range(len(sched.segment_steps))]
    t_pack = time.perf_counter() - t0

    # warmup compile on a zero batch of the same shape
    T = cfg.steps
    warm = {k: v[:T] * 0 for k, v in packed[0].items()}
    st, _ = ses._step(ses.state, warm)
    ses.state = st
    jax.block_until_ready(ses.state)

    t0 = time.perf_counter()
    chunks = [ses._run_segment(arrs) for arrs in packed]
    jax.block_until_ready(ses.state)
    t_disp = time.perf_counter() - t0

    # reconstruction (host): reuse session plumbing by replaying the
    # chunk outputs through the record builder
    t0 = time.perf_counter()
    fills = 0
    for segchunks in chunks:
        for ch in segchunks:
            fills += int(ch["nfill"].sum())
    t_recon = time.perf_counter() - t0

    n = len(msgs)
    steps_total = sum(sched.segment_steps)
    ops = n / t_disp
    return {
        "metric": "orders_per_sec_lane_engine",
        "value": round(ops, 1),
        "unit": "orders/s",
        "vs_baseline": round(ops / REFERENCE_BASELINE_OPS, 3),
        "detail": {
            "events": n, "symbols": symbols, "accounts": accounts,
            "zipf_a": zipf_a, "shards": shards,
            "dispatch_s": round(t_disp, 3), "plan_s": round(t_plan, 3),
            "pack_s": round(t_pack, 3), "recon_scan_s": round(t_recon, 3),
            "sched_steps": steps_total,
            "msgs_per_step": round(n / max(steps_total, 1), 1),
            "trades": fills,
            "backend": jax.devices()[0].platform,
            "baseline_assumption_ops": REFERENCE_BASELINE_OPS,
        },
    }


def bench_parity_engine(events: int = 4096, seed: int = 0, batch: int = 256,
                        compat: str = "java") -> dict:
    """Throughput of the serial device parity engine on the stock harness
    workload (the quirk-exact replica — correctness path, not the
    performance path)."""
    from kme_tpu.engine.parity import ParityCaps, ParityEngine
    from kme_tpu.workload import harness_stream

    caps = ParityCaps(balances=32, positions=8192, books=32, buckets=1024,
                      orders=16384, max_events=64, batch=batch)
    msgs = harness_stream(events, seed=seed)
    eng = ParityEngine(compat, caps)
    eng.process_batch(msgs[:batch])  # warmup: compile + first dispatch
    t0 = time.perf_counter()
    eng.process_batch(msgs[batch:])
    dt = time.perf_counter() - t0
    n = len(msgs) - batch
    ops = n / dt
    import jax
    return {
        "metric": "orders_per_sec_serial_parity",
        "value": round(ops, 1),
        "unit": "orders/s",
        "vs_baseline": round(ops / REFERENCE_BASELINE_OPS, 3),
        "detail": {
            "events": n, "seconds": round(dt, 3), "batch": batch,
            "compat": compat, "backend": jax.devices()[0].platform,
            "baseline_assumption_ops": REFERENCE_BASELINE_OPS,
        },
    }


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="kme-bench")
    p.add_argument("--suite", choices=("lanes", "parity"), default="lanes")
    p.add_argument("--events", type=int, default=None)
    p.add_argument("--symbols", type=int, default=1024)
    p.add_argument("--accounts", type=int, default=2048)
    p.add_argument("--zipf", type=float, default=0.0)
    p.add_argument("--shards", type=int, default=1)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--compat", choices=("java", "fixed"), default="java")
    args = p.parse_args(argv)
    if args.suite == "lanes":
        rec = bench_lane_engine(args.events or 100_000, args.symbols,
                                args.accounts, args.seed, args.zipf,
                                shards=args.shards)
    else:
        rec = bench_parity_engine(args.events or 4096, args.seed, args.batch,
                                  args.compat)
    out = {k: rec[k] for k in ("metric", "value", "unit", "vs_baseline")}
    print(json.dumps(out))
    print(json.dumps(rec["detail"]), file=sys.stderr)
    return 0
