"""Benchmark suite (BASELINE.md matrix).

The reference publishes no numbers (BASELINE.md); its structural bound is
single-digit-thousands of orders/sec (serial awaited produce per order,
commit per record, JSON serde, RocksDB round-trips — BASELINE.md table).
`REFERENCE_BASELINE_OPS` pins the top of that band (5k orders/sec) as the
denominator for `vs_baseline`. The environment has no JVM (no `java` on
PATH), so the reference cannot be measured here; the assumption and its
basis are documented in BASELINE.md and printed in the detail line.

Headline metric: END-TO-END orders/sec through the lane engine on the
BASELINE.md "1k symbols x 100k orders, Zipf-skewed" row — plan + pack +
device dispatch + output fetch + full record-stream reconstruction, with
fill parity vs the scalar oracle asserted on a prefix of the same stream
inside the run. Phase timings are reported in the detail line.
"""

from __future__ import annotations

import json
import sys
import time

REFERENCE_BASELINE_OPS = 5_000.0  # orders/sec, derived bound (BASELINE.md)

# Bench-default compaction width, tuned on the Zipf-1.2 headline config
# (hot-lane depth bounds the step count there, so narrow steps win; on
# un-skewed workloads wider steps amortize better — LaneSession's own
# default stays 16 for that reason).
DEFAULT_WIDTH = 4

# Latency-suite micro-batch size (one constant for the function, the
# CLI, and the BASELINE.md row).
DEFAULT_LATENCY_BATCH = 2048

# the five adversarial storm profiles (workload.STORM_PROFILES), usable
# as --workload names on the engine suites and driven deterministically
# end-to-end by --suite storms
STORM_WORKLOADS = ("payout-storm-wide", "flash-crowd", "cancel-storm",
                   "hot-book", "liquidation-cascade")


def _judge_wire(msgs, prefix: int, kw: dict):
    """The quirk-exact judge's wire stream for a message prefix: the
    native C++ replica when available (itself pinned byte+store-exact
    against the Python oracle by tests/test_native_oracle.py), else the
    Python oracle. A native-engine failure must SURFACE, not silently
    fall back — the judge's health is part of what the check verifies."""
    use_native = False
    try:
        from kme_tpu.native.oracle import NativeOracleEngine, native_available

        use_native = native_available()
    except ImportError:
        pass
    if use_native:
        judge = NativeOracleEngine("fixed", **kw)
        return judge.process_wire([m.copy() for m in msgs[:prefix]])
    from kme_tpu.oracle import OracleEngine

    print("bench: native judge unavailable; using the Python oracle",
          file=sys.stderr)
    ora = OracleEngine("fixed", **kw)
    return [[r.wire() for r in ora.process(msgs[i].copy())]
            for i in range(prefix)]


def _assert_parity_prefix(msgs, cfg, shards, prefix: int,
                          width: int) -> None:
    """Replay `prefix` messages through a throwaway session and the
    quirk-exact reference replica (with the matching capacity envelope);
    require byte-identical wire streams."""
    from kme_tpu.runtime.session import LaneSession

    ses = LaneSession(cfg, shards=shards, width=width)
    want = _judge_wire(msgs, prefix,
                       dict(book_slots=cfg.slots, max_fills=cfg.max_fills))
    got = ses.process_wire(msgs[:prefix])
    for i in range(prefix):
        assert got[i] == want[i], \
            f"bench parity prefix diverged at message {i}"


SEQ_DEFAULT_SLOTS = 8192   # deep books: the Zipf hot lane rests ~2k
                           # orders at 100k events; 8192 leaves the
                           # envelope a non-story (rej_capacity == 0)


def _wire_buffer(msgs) -> bytes:
    """The stream as newline-separated order JSON — the engine's real
    input boundary (the reference consumes JSON bytes from Kafka,
    KProcessor.java:96)."""
    from kme_tpu.wire import dumps_order

    return ("\n".join(dumps_order(m) for m in msgs)).encode()


def _device_path(cfg, batch, reps: int = 3) -> dict:
    """Transfer-free device-path time of ONE full-stream scan dispatch.

    Method (the axon tunnel forbids naive timing: block_until_ready has
    shown not-actually-blocking behavior, any output fetch costs a
    round trip, and post-fetch dispatches degrade ~10ms/call): AOT-
    compile the K-chunk scan and a 1-chunk scan, time each as
    [dispatch + tiny err-plane fetch barrier], and difference the
    minima — the tunnel constant cancels, leaving (K-1) chunks of pure
    device time. Scaled back to K chunks = the whole stream. This is
    how the r5 numbers were measured after the r4 device-path claims
    (6.5ms / "15-16M msg/s") turned out to be enqueue-only artifacts
    of the axon barrier behavior.
    """
    import time

    import jax
    import numpy as np

    from kme_tpu.engine import seq as SQ
    from kme_tpu.runtime.seqsession import SeqSession

    ses = SeqSession(cfg)
    cols, _hr, stacked, _cnts, K = ses._plan(batch)
    state0 = ses.state
    full_d = jax.device_put(stacked)
    scan_full = SQ.build_seq_scan(cfg, K)
    c_full = scan_full.lower(state0, full_d).compile()

    def timed(compiled, st, inp):
        t0 = time.perf_counter()
        st2, _out = compiled(st, inp)
        np.asarray(st2["err"])   # completion barrier (512B fetch)
        return time.perf_counter() - t0

    n = len(batch)
    if K == 1:
        timed(c_full, state0, full_d)   # warm
        t = min(timed(c_full, state0, full_d) for _ in range(reps))
        return {"device_path_s": round(t, 4),
                "device_path_msgs_per_sec": round(n / max(t, 1e-9), 1),
                "method": "single-chunk upper bound (incl. one tunnel "
                          "round trip)", "chunks": K}
    small_d = jax.device_put({f: v[:1] for f, v in stacked.items()})
    c_small = SQ.build_seq_scan(cfg, 1).lower(state0, small_d).compile()
    timed(c_full, state0, full_d)
    timed(c_small, state0, small_d)
    t_full = min(timed(c_full, state0, full_d) for _ in range(reps))
    t_small = min(timed(c_small, state0, small_d) for _ in range(reps))
    per_chunk = (t_full - t_small) / (K - 1)
    dev_s = max(per_chunk * K, 1e-9)
    return {"device_path_s": round(dev_s, 4),
            "device_path_msgs_per_sec": round(n / dev_s, 1),
            "method": "two-size scan differencing (tunnel constant "
                      "cancelled); covers all chunks incl. padding",
            "chunks": K}


def _judge_seq_full(msgs, cfg, compat: str):
    """The quirk-exact judge's FULL wire stream as one byte buffer
    (concatenated lines, the exact layout process_wire_buffer emits)."""
    if compat == "java":
        from kme_tpu.native.oracle import NativeOracleEngine, \
            native_available

        if native_available():
            judge = NativeOracleEngine("java")
            lines = judge.process_wire([m.copy() for m in msgs])
        else:
            from kme_tpu.oracle import OracleEngine

            print("bench: native judge unavailable; using the Python "
                  "oracle", file=sys.stderr)
            ora = OracleEngine("java")
            lines = [[r.wire() for r in ora.process(m.copy())]
                     for m in msgs]
    else:
        lines = _judge_wire(msgs, len(msgs),
                            dict(book_slots=cfg.slots,
                                 max_fills=cfg.max_fills))
    return "".join(ln for per in lines for ln in per).encode()


def _bench_seq_latency(symbols: int, accounts: int, seed: int,
                       zipf_a: float, events: int = 40_960,
                       batch: int = DEFAULT_LATENCY_BATCH) -> dict:
    """Streaming micro-batch latency on the seq engine, double-buffered
    (SURVEY.md §7 H5): batch N+1 DISPATCHES before batch N's outputs
    fetch/reconstruct (SeqSession.submit/collect), so device execution
    overlaps host recon. Reported per 2048-msg batch:

    - engine-side p50/p99 = per-batch host work (route+pack measured
      per batch, recon measured per batch) + the device time per batch
      (two-size scan differencing, an average — per-batch device
      variance is below the host jitter on this homogeneous mix);
      fetch is excluded as tunnel transport (see fetched_mb).
    - streamed_orders_per_sec: the pipelined wall-clock rate through
      the tunnel (RTT-bound here), with the serial rate alongside as
      the overlap evidence.
    """
    import time

    import jax
    import numpy as np

    from kme_tpu.engine import seq as SQ
    from kme_tpu.runtime.seqsession import SeqSession
    from kme_tpu.wire import WireBatch
    from kme_tpu.workload import zipf_symbol_stream

    msgs = zipf_symbol_stream(events, num_symbols=symbols,
                              num_accounts=accounts, seed=seed,
                              zipf_a=zipf_a)
    cfg = SQ.SeqConfig(lanes=symbols, slots=128, accounts=accounts,
                       max_fills=16, batch=batch)
    batches = [WireBatch.from_msgs(msgs[lo:lo + batch])
               for lo in range(0, len(msgs), batch)]

    # device time per batch: two-size differencing over the stream
    ses0 = SeqSession(cfg)
    cols, _hr, stacked, _c, K = ses0._plan(
        WireBatch.from_msgs(msgs))
    state0 = ses0.state
    full_d = jax.device_put(stacked)
    small_d = jax.device_put({f: v[:1] for f, v in stacked.items()})
    cK = SQ.build_seq_scan(cfg, K).lower(state0, full_d).compile()
    c1 = SQ.build_seq_scan(cfg, 1).lower(state0, small_d).compile()

    def timed(cc, inp):
        t0 = time.perf_counter()
        st, _o = cc(state0, inp)
        np.asarray(st["err"])
        return time.perf_counter() - t0

    timed(cK, full_d)
    timed(c1, small_d)
    # differencing noise can make the K-batch run time under the
    # 1-batch run on fast backends — clamp at 0 rather than report a
    # negative per-batch device time; K == 1 (events <= batch) leaves
    # nothing to difference
    if K > 1:
        dev_batch_s = max(0.0, (
            min(timed(cK, full_d) for _ in range(2))
            - min(timed(c1, small_d) for _ in range(2))) / (K - 1))
    else:
        dev_batch_s = min(timed(cK, full_d) for _ in range(2))

    def run(pipelined: bool):
        # drives the REAL serving surface (SeqSession.submit/collect —
        # the same calls kme-serve --pipeline makes); the session's
        # flight-recorder windows feed measured_overlap_s
        ses = SeqSession(cfg)
        walls, per_batch, pend = [], [], []

        def collect_one():
            nb2, t_sub, handle = pend.pop(0)
            p0 = dict(ses.phases)
            ses.collect(handle)
            p1 = ses.phases
            walls.append(time.perf_counter() - t_sub)
            per_batch[nb2]["fetch_ms"] = round(
                (p1.get("fetch_s", 0.0) - p0.get("fetch_s", 0.0)) * 1e3,
                3)
            per_batch[nb2]["recon_ms"] = round(
                (p1.get("recon_s", 0.0) - p0.get("recon_s", 0.0)) * 1e3,
                3)

        t_all = time.perf_counter()
        for nb, bt in enumerate(batches):
            t_sub = time.perf_counter()
            p0 = dict(ses.phases)
            handle = ses.submit(bt)
            p1 = ses.phases
            per_batch.append({
                "plan_ms": round((p1.get("plan_s", 0.0)
                                  - p0.get("plan_s", 0.0)) * 1e3, 3),
                "dispatch_ms": round(
                    (p1.get("dispatch_s", 0.0)
                     - p0.get("dispatch_s", 0.0)) * 1e3, 3)})
            pend.append((nb, t_sub, handle))
            while len(pend) > (1 if pipelined else 0):
                collect_one()
        while pend:
            collect_one()
        return time.perf_counter() - t_all, per_batch, walls, ses

    run(True)   # warm every shape (compile shared via lru caches)
    t_serial, _pb0, _w0, _ses0 = run(False)
    t_pipe, per_batch, walls, ses_pipe = run(True)

    from kme_tpu.telemetry.journal import measured_overlap_s

    windows = ses_pipe.windows
    overlap_s = measured_overlap_s(windows)
    collect_wall = sum(t1 - t0 for kind, _b, t0, t1 in windows
                      if kind == "collect")

    eng = sorted((pb["plan_ms"] + pb["recon_ms"]) * 1e-3 + dev_batch_s
                 for pb in per_batch)

    def pct(xs, p):
        import math

        return xs[max(0, min(len(xs) - 1, math.ceil(p * len(xs)) - 1))]

    ph = ses_pipe.phases
    res = {
        "batch": batch, "batches": len(batches), "events": len(msgs),
        "engine_side_p50_ms": round(pct(eng, 0.50) * 1e3, 2),
        "engine_side_p90_ms": round(pct(eng, 0.90) * 1e3, 2),
        "engine_side_p99_ms": round(pct(eng, 0.99) * 1e3, 2),
        "device_ms_per_batch": round(dev_batch_s * 1e3, 2),
        "tunnel_wall_p50_ms": round(
            pct(sorted(walls), 0.50) * 1e3, 1),
        "tunnel_wall_p99_ms": round(
            pct(sorted(walls), 0.99) * 1e3, 1),
        "streamed_orders_per_sec": round(len(msgs) / t_pipe, 1),
        "serial_orders_per_sec": round(len(msgs) / t_serial, 1),
        "pipeline_speedup": round(t_serial / t_pipe, 2),
        # measured from the recorded submit/collect windows: wall time
        # a collect actually ran while another batch was in flight on
        # device. The FRACTION is over the total collect wall — the
        # host-side work the pipeline exists to hide — so it converges
        # structurally to 1.0 under working double-buffering and is
        # gateable, unlike the t_serial/t_pipe ratio whose run-to-run
        # tunnel variance produced the spurious 0.93 in BENCH_r05
        "measured_overlap_s": round(overlap_s, 4),
        "collect_wall_s": round(collect_wall, 4),
        "measured_overlap_frac": round(
            overlap_s / max(collect_wall, 1e-9), 4),
        # cumulative phase walls of the pipelined run (mirrors the
        # java sub-dict's field names for artifact-diffing)
        "plan_s": round(ph.get("plan_s", 0.0), 4),
        "dispatch_s": round(ph.get("dispatch_s", 0.0), 4),
        "fetch_s": round(ph.get("fetch_s", 0.0), 4),
        "recon_s": round(ph.get("recon_s", 0.0), 4),
        "per_batch": per_batch,
        "method": "double-buffered submit/collect (the serving API); "
                  "engine-side = per-batch plan+recon (measured) + "
                  "device/batch (two-size differencing, averaged); "
                  "fetch = tunnel. pipeline_speedup ~1 through THIS "
                  "driver's tunnel (round trips serialize); "
                  "measured_overlap_frac = overlap / collect wall is "
                  "the gateable overlap evidence",
    }
    import jax as _jax
    res["backend"] = _jax.devices()[0].platform
    if res["measured_overlap_frac"] < 0.5:
        res["pipeline_warning"] = (
            f"measured_overlap_frac {res['measured_overlap_frac']} "
            "< 0.5 — less than half the collect wall was hidden under "
            "device execution; the double-buffer is not overlapping "
            "(host-bound batches or a serializing transport)")
        print(f"kme-bench: WARNING {res['pipeline_warning']}",
              file=sys.stderr)
    publish_pipeline_gauges(ses_pipe.telemetry, res)
    return res


def publish_pipeline_gauges(registry, detail: dict) -> None:
    """Pipeline health as LIVE gauges (the same registry a
    --metrics-port scrape or heartbeat snapshot reads). The warning
    travels as a numeric 0/1 gauge — Prometheus carries no strings —
    with the prose staying in the detail dict."""
    g = registry.gauge
    for k in ("pipeline_speedup", "device_ms_per_batch",
              "measured_overlap_frac", "local_s"):
        if k in detail:
            g(k).set(detail[k])
    g("pipeline_warning",
      "1 when measured_overlap_frac fell under 0.5 (the collect wall "
      "is not being hidden under device execution)").set(
        1 if detail.get("pipeline_warning") else 0)


def bench_pipeline(events: int = 40_960, symbols: int = 32,
                   accounts: int = 256, seed: int = 0,
                   zipf_a: float = 1.2, batch: int = 1024,
                   depth: int = 2) -> dict:
    """IN-PROCESS pipelined serving bench (no TCP, no broker): the
    serve hot path — bytes parse -> native plan+pack -> async dispatch
    under the previous batch's device step -> fetch -> native
    reconstruction — driven through SeqSession.submit/collect exactly
    as `kme-serve --pipeline` drives it, against the serial
    submit+collect-immediately loop over the SAME byte stream.

    Because no transport round trips serialize the loop, this is the
    suite where the double-buffer's wall-clock win is actually
    measurable (pipeline_speedup > 1) and where the host-path gate
    metrics are recorded: `local_s` (parse + plan + recon — the wall
    the host spends OFF the device) and `measured_overlap_frac`
    (fraction of the collect wall hidden under device execution).
    Output parity between the two runs is asserted byte-for-byte."""
    import time

    import jax

    from kme_tpu.engine import seq as SQ
    from kme_tpu.native import load_library
    from kme_tpu.runtime.seqsession import SeqSession
    from kme_tpu.wire import WireBatch, dumps_order
    from kme_tpu.workload import zipf_symbol_stream

    if load_library() is None:
        raise RuntimeError(
            "the pipeline suite needs the native host runtime "
            "(KME_NATIVE=0 or no toolchain?) — the buffer serving "
            "path under test is native-only")
    msgs = zipf_symbol_stream(events, num_symbols=symbols,
                              num_accounts=accounts, seed=seed,
                              zipf_a=zipf_a)
    slots = 128
    accounts_eff = -(-accounts // 128) * 128
    cfg = SQ.SeqConfig(lanes=symbols, slots=slots,
                       accounts=accounts_eff, max_fills=16,
                       batch=max(128, min(4096,
                                          1 << (batch - 1).bit_length())))
    # the serve loop's input: newline-framed wire bytes per batch
    bufs = []
    for lo in range(0, len(msgs), batch):
        bufs.append("\n".join(dumps_order(m)
                              for m in msgs[lo:lo + batch]).encode())

    def run(pipelined: bool):
        ses = SeqSession(cfg)
        parse_s = 0.0
        pend, outs, per_batch = [], [], []

        def collect_one():
            nb2, handle = pend.pop(0)
            p0 = dict(ses.phases)
            buf, _lo, _ml = ses.collect(handle)
            p1 = ses.phases
            outs.append(buf)
            per_batch[nb2]["fetch_ms"] = round(
                (p1.get("fetch_s", 0.0) - p0.get("fetch_s", 0.0)) * 1e3,
                3)
            per_batch[nb2]["recon_ms"] = round(
                (p1.get("recon_s", 0.0) - p0.get("recon_s", 0.0)) * 1e3,
                3)

        t_all = time.perf_counter()
        for nb, raw in enumerate(bufs):
            t0 = time.perf_counter()
            wb = WireBatch.parse_buffer(raw)
            tp = time.perf_counter() - t0
            parse_s += tp
            p0 = dict(ses.phases)
            handle = ses.submit(wb)
            p1 = ses.phases
            per_batch.append({
                "parse_ms": round(tp * 1e3, 3),
                "plan_ms": round((p1.get("plan_s", 0.0)
                                  - p0.get("plan_s", 0.0)) * 1e3, 3),
                "dispatch_ms": round(
                    (p1.get("dispatch_s", 0.0)
                     - p0.get("dispatch_s", 0.0)) * 1e3, 3)})
            pend.append((nb, handle))
            while len(pend) > (depth if pipelined else 0):
                collect_one()
        while pend:
            collect_one()
        return (time.perf_counter() - t_all, parse_s, per_batch,
                b"".join(outs), ses)

    run(True)   # warm every shape bucket (jit caches shared)
    # best-of-two per mode: the hideable host wall is a few percent of
    # the CPU device wall, so a single-run ratio flaps on scheduler
    # noise; the systematic win survives a min-of-2
    s_runs = [run(False) for _ in range(2)]
    t_serial = min(r[0] for r in s_runs)
    out_serial = s_runs[0][3]
    p_runs = [run(True) for _ in range(2)]
    t_pipe, parse_s, per_batch, out_pipe, ses = min(
        p_runs, key=lambda r: r[0])
    assert out_pipe == out_serial, (
        f"pipelined output diverged from serial "
        f"({len(out_pipe)} vs {len(out_serial)} bytes)")

    from kme_tpu.telemetry.journal import measured_overlap_s

    windows = ses.windows
    overlap_s = measured_overlap_s(windows)
    collect_wall = sum(t1 - t0 for kind, _b, t0, t1 in windows
                       if kind == "collect")
    ph = ses.phases
    n = len(msgs)
    local_s = (parse_s + ph.get("plan_s", 0.0) + ph.get("recon_s", 0.0))
    ops = n / t_pipe
    detail = {
        "engine": "seq (submit/collect, in-process)",
        "events": n, "symbols": symbols, "accounts": accounts_eff,
        "batch": batch, "depth": depth, "batches": len(bufs),
        "serial_wall_s": round(t_serial, 4),
        "pipelined_wall_s": round(t_pipe, 4),
        "pipelined_orders_per_sec": round(ops, 1),
        "serial_orders_per_sec": round(n / t_serial, 1),
        "pipeline_speedup": round(t_serial / t_pipe, 4),
        "measured_overlap_s": round(overlap_s, 4),
        "collect_wall_s": round(collect_wall, 4),
        "measured_overlap_frac": round(
            overlap_s / max(collect_wall, 1e-9), 4),
        # fraction of the H2D staging wall that ran while an earlier
        # batch was still in flight on the device (r14 double-buffer
        # surface; advisory-up in the gate — it rides wall clocks)
        "h2d_overlap_frac": ses.h2d_overlap_frac,
        # the host-path wall the native layer exists to shrink:
        # bytes->columns parse + route/pack plan + output recon
        "local_s": round(local_s, 4),
        "local_orders_per_sec": round(n / max(local_s, 1e-9), 1),
        "parse_s": round(parse_s, 4),
        "plan_s": round(ph.get("plan_s", 0.0), 4),
        "dispatch_s": round(ph.get("dispatch_s", 0.0), 4),
        "fetch_s": round(ph.get("fetch_s", 0.0), 4),
        "recon_s": round(ph.get("recon_s", 0.0), 4),
        "per_batch": per_batch,
        "out_mb": round(len(out_pipe) / 1e6, 2),
        "parity": "pipelined byte stream == serial byte stream",
        "backend": jax.devices()[0].platform,
        "method": "same byte stream through submit/collect twice: "
                  "serial (collect immediately) vs depth-N pipelined "
                  "(parse+plan+dispatch of batch N+1 under batch N's "
                  "device step); no transport in the loop",
    }
    if detail["measured_overlap_frac"] < 0.5:
        detail["pipeline_warning"] = (
            f"measured_overlap_frac {detail['measured_overlap_frac']} "
            "< 0.5 — less than half the collect wall was hidden under "
            "device execution")
        print(f"kme-bench: WARNING {detail['pipeline_warning']}",
              file=sys.stderr)
    if detail["h2d_overlap_frac"] < 0.5:
        detail["h2d_warning"] = (
            f"h2d_overlap_frac {detail['h2d_overlap_frac']} < 0.5 — "
            "most input staging ran with the device idle")
        print(f"kme-bench: WARNING {detail['h2d_warning']}",
              file=sys.stderr)
    publish_pipeline_gauges(ses.telemetry, detail)
    return {
        "metric": "pipelined_orders_per_sec",
        "value": round(ops, 1),
        "unit": "orders/s",
        "vs_baseline": round(ops / REFERENCE_BASELINE_OPS, 3),
        "detail": detail,
    }


def bench_seq_engine(events: int = 100_000, symbols: int = 1024,
                     accounts: int = 2048, seed: int = 0,
                     zipf_a: float = 1.2, slots: int = SEQ_DEFAULT_SLOTS,
                     max_fills: int = 16, batch: int = 4096,
                     workload: str = "zipf",
                     compat: str = "fixed",
                     with_java: bool = None,
                     journal_out: str = None,
                     audit: bool = False) -> dict:
    """End-to-end throughput of the SEQUENTIAL MEGA-KERNEL engine
    (kme_tpu/engine/seq.py) on the headline row, measured BYTES-IN to
    BYTES-OUT: native JSON parse -> columnar route + pack -> one scan
    dispatch -> one-round fetch -> native C++ wire reconstruction.
    Parity is asserted on the FULL stream: the timed run's output
    buffer must equal the quirk-exact replica's, byte for byte.

    Also measured and reported:
    - device_path: transfer-free device time of the full-stream scan
      (see _device_path; runs BEFORE any fetch poisons dispatch).
    - local_orders_per_sec: n / (parse + plan + recon + device_path) —
      the non-tunnel phases, i.e. the rate this host+chip pair would
      sustain with locally attached hardware (fetch excluded; its
      device->host traffic is reported as fetched_mb).
    """
    import os
    import time

    import jax

    from kme_tpu.engine import seq as SQ
    from kme_tpu.runtime.seqsession import SeqSession
    from kme_tpu.wire import WireBatch
    from kme_tpu.workload import cancel_heavy_stream, zipf_symbol_stream

    # books deeper than VMEM affords live in HBM behind the kernel's
    # per-lane scratch cache (SeqConfig.hbm_books)
    if compat == "java":
        # quirk-exact java mode ON the kernel: the STOCK harness shape
        # (10 accounts, 3 symbols, Q5 payouts-as-cancels, sid=0
        # trading); unbounded reference stores need deep device
        # capacity (max_fills rides one (1,128) row, E <= 128).
        # 8 lanes x 8192 slots FIT IN VMEM (no hbm lane switching).
        symbols, accounts = 8, 128
        max_fills = 128
        workload = "harness"
        # 8 lanes x 8192 slots fit in VMEM (no hbm lane switching);
        # user-requested deeper books fall back to the HBM cache
        eff_slots = max(slots, 8192)
        cfg = SQ.SeqConfig(lanes=symbols, slots=eff_slots,
                           accounts=accounts, max_fills=max_fills,
                           batch=batch, pos_cap=1 << 17,
                           probe_max=64, compat="java",
                           hbm_books=eff_slots > 8192)
    else:
        cfg = SQ.SeqConfig(lanes=symbols, slots=slots, accounts=accounts,
                           max_fills=max_fills, batch=batch,
                           hbm_books=slots > 512)
    if workload == "harness":
        from kme_tpu.workload import harness_stream

        msgs = harness_stream(events, seed=seed)
    elif workload == "cancel":
        msgs = cancel_heavy_stream(events, num_symbols=symbols,
                                   num_accounts=accounts, seed=seed)
    elif workload == "zipf-hot":
        from kme_tpu.workload import zipf_hot_stream

        msgs = zipf_hot_stream(events, num_symbols=symbols,
                               num_accounts=accounts, seed=seed)
    elif workload == "payout-storm":
        from kme_tpu.workload import payout_storm_stream

        msgs = payout_storm_stream(events, num_symbols=symbols,
                                   num_accounts=accounts, seed=seed)
    elif workload in STORM_WORKLOADS:
        from kme_tpu.workload import storm_stream

        msgs = storm_stream(workload, events, num_symbols=symbols,
                            num_accounts=accounts, seed=seed)
    else:
        msgs = zipf_symbol_stream(events, num_symbols=symbols,
                                  num_accounts=accounts, seed=seed,
                                  zipf_a=zipf_a)
    n = len(msgs)
    in_buf = _wire_buffer(msgs)
    batch0 = WireBatch.parse_buffer(in_buf)

    # transfer-free device path FIRST: any np.asarray fetch in the
    # process degrades subsequent dispatch timing (axon tunnel)
    dev = _device_path(cfg, batch0,
                       reps=int(os.environ.get("KME_BENCH_DEV_REPS",
                                               "3")))

    warm = SeqSession(cfg)          # warmup: compile + shapes
    native_ok = warm.process_wire_buffer(batch0) is not None
    if not native_ok:
        warm.process_wire(msgs)     # no native toolchain: warm this path
    # the driver's TPU tunnel has large run-to-run variance (fetch wall
    # 0.6s..3.5s observed on identical code); report the best of three
    # timed runs as steady-state and disclose every run's wall
    runs = []
    best = None
    for _rep in range(3):
        ses = SeqSession(cfg)
        ses._ghint = getattr(warm, "_ghint", ses._ghint)
        t0 = time.perf_counter()
        bt = WireBatch.parse_buffer(in_buf)
        t_parse = time.perf_counter() - t0
        if native_ok:
            r = ses.process_wire_buffer(bt)
            total = time.perf_counter() - t0
            out_buf, line_off, _ml = r
            n_records = len(line_off) - 1
            split = (line_off, _ml)
        else:
            records = ses.process_wire(bt)
            total = time.perf_counter() - t0
            out_buf = "".join(ln for per in records
                              for ln in per).encode()
            n_records = sum(len(x) for x in records)
            split = records
        runs.append(round(total, 3))
        if best is None or total < best[0]:
            best = (total, n_records, dict(ses.phases, parse_s=t_parse),
                    ses.metrics(), out_buf, split)
    total, n_records, ph, metrics, out_buf, split = best
    # FULL-STREAM parity: the timed run's byte stream vs the judge
    want_buf = _judge_seq_full(msgs, cfg, compat)
    assert out_buf == want_buf, (
        f"seq bench FULL-STREAM parity diverged "
        f"(got {len(out_buf)} bytes, want {len(want_buf)})")
    parity_checked = n
    ops = n / total
    local_s = (ph.get("parse_s", 0.0) + ph.get("plan_s", 0.0)
               + ph.get("recon_s", 0.0) + dev["device_path_s"])
    HR = SQ.hdr_rows(cfg)
    ghint = getattr(warm, "_ghint", 8)
    fetched_mb = (dev["chunks"] * (HR + 5 * ghint) * 128 * 4) / 1e6
    detail = {
        "engine": "seq (sequential Pallas mega-kernel)",
        "compat": compat,
        "events": n, "symbols": symbols, "accounts": accounts,
        "workload": workload, "zipf_a": zipf_a, "slots": slots,
        "max_fills": max_fills, "batch": batch,
        "parse_s": round(ph.get("parse_s", 0.0), 3),
        "plan_s": round(ph.get("plan_s", 0.0), 3),
        "dispatch_s": round(ph.get("dispatch_s", 0.0), 3),
        "fetch_s": round(ph.get("fetch_s", 0.0), 3),
        "recon_s": round(ph.get("recon_s", 0.0), 3),
        "total_s": round(total, 3),
        "all_run_walls_s": runs,
        # transfer-free device path, measured in-run (see _device_path
        # docstring). dispatch_s/fetch_s above are tunnel-bound.
        "device_path_s": dev["device_path_s"],
        "device_path_msgs_per_sec": dev["device_path_msgs_per_sec"],
        "device_path_method": dev["method"],
        # the non-tunnel rate: what this pipeline sustains without the
        # driver tunnel between host and chip (fetch excluded; the
        # fetch moves fetched_mb of output which costs ~1ms locally)
        "local_orders_per_sec": round(n / max(local_s, 1e-9), 1),
        "local_s": round(local_s, 4),
        "fetched_mb": round(fetched_mb, 2),
        "out_records": n_records,
        "out_mb": round(len(out_buf) / 1e6, 2),
        "accepted_orders_per_sec": round(
            (n - int(metrics.get("rej_capacity", 0))) / total, 1),
        "cap_rejects": int(metrics.get("rej_capacity", 0)),
        "parity_checked_msgs": parity_checked,
        "parity": "full-stream byte-exact vs native judge",
        "backend": jax.devices()[0].platform,
        "baseline_assumption_ops": REFERENCE_BASELINE_OPS,
        "vs_baseline_note": "vs_baseline divides by the ASSUMED 5k "
                            "orders/s reference bound (BASELINE.md) — "
                            "no measured JVM baseline exists in this "
                            "environment",
        "device_metrics": metrics,
    }
    if (journal_out is not None or audit) and compat == "fixed":
        # flight-recorder overhead row: journal + audit the BEST run's
        # byte stream POST-HOC (the timed runs stay untouched — the
        # parity assert above proves the stream is the engine's), and
        # report the cost as a fraction of the run wall, i.e. the
        # overhead kme-serve pays doing the same work inline per batch
        from kme_tpu.telemetry.audit import InvariantAuditor
        from kme_tpu.telemetry.journal import Journal, batch_events

        if native_ok:
            # native output is one flat buffer; line_off marks record
            # boundaries, ml counts records per input message
            line_off, ml = split
            text = out_buf.decode()
            lines = [text[line_off[k]:line_off[k + 1]]
                     for k in range(len(line_off) - 1)]
            per_msg, k = [], 0
            for c in ml:
                per_msg.append(lines[k:k + int(c)])
                k += int(c)
        else:
            per_msg = split
        jd = {"events": n}
        if journal_out is not None:
            t0 = time.perf_counter()
            j = Journal(journal_out)
            for lo in range(0, len(per_msg), batch):
                chunk = per_msg[lo:lo + batch]
                j.record_batch(chunk,
                               offsets=list(range(lo, lo + len(chunk))))
            j.close()
            journal_s = time.perf_counter() - t0
            jd.update({"path": journal_out,
                       "journal_s": round(journal_s, 3),
                       "journal_overhead_frac":
                           round(journal_s / total, 4)})
        if audit:
            aud = InvariantAuditor()
            t0 = time.perf_counter()
            for lo in range(0, len(per_msg), batch):
                aud.observe(batch_events(per_msg[lo:lo + batch]))
            audit_s = time.perf_counter() - t0
            jd.update({"audit_s": round(audit_s, 3),
                       "audit_overhead_frac": round(audit_s / total, 4),
                       "audit_violations": len(aud.violations)})
        detail["journal"] = jd
    if compat == "fixed" and n >= 50_000 and native_ok \
            and os.environ.get("KME_BENCH_LATENCY", "1") != "0":
        # the streaming-latency row (VERDICT r4 #6): engine-side
        # per-batch latency + double-buffered serving overlap, in the
        # same driver artifact
        detail["latency"] = _bench_seq_latency(symbols, accounts, seed,
                                               zipf_a)
    if with_java is None:
        with_java = (compat == "fixed"
                     and os.environ.get("KME_BENCH_JAVA", "1") != "0")
    if with_java:
        # the quirk-exact java lane as a sub-run so the driver artifact
        # carries BOTH headline rows (VERDICT r4: the java device-path
        # number must live in a driver-captured artifact)
        sub = bench_seq_engine(events=100_000, seed=seed, batch=batch,
                               compat="java", with_java=False)
        keep = ("events", "device_path_s", "device_path_msgs_per_sec",
                "local_orders_per_sec", "parse_s", "plan_s",
                "dispatch_s", "fetch_s", "recon_s", "total_s",
                "parity_checked_msgs", "cap_rejects", "out_records")
        detail["java"] = {k: sub["detail"][k] for k in keep}
        detail["java"]["orders_per_sec_e2e"] = sub["value"]
    return {
        "metric": ("orders_per_sec_java_exact_tpu" if compat == "java"
                   else "orders_per_sec_e2e"),
        "value": round(ops, 1),
        "unit": "orders/s",
        "vs_baseline": round(ops / REFERENCE_BASELINE_OPS, 3),
        "detail": detail,
    }


def bench_lane_engine(events: int = 100_000, symbols: int = 1024,
                      accounts: int = 2048, seed: int = 0,
                      zipf_a: float = 1.2, steps: int = 64,
                      slots: int = 128, max_fills: int = 16,
                      shards: int = 1, parity_prefix: int = 20000,
                      width: int = DEFAULT_WIDTH,
                      workload: str = "zipf", window: int = 1024,
                      profile_dir: str = None) -> dict:
    """End-to-end lane-engine throughput (see module docstring).
    workload: 'zipf' (the headline row) or 'cancel' (the bursty
    cancel/replace BASELINE.md row)."""
    import jax

    from kme_tpu.engine.lanes import LaneConfig
    from kme_tpu.runtime.session import LaneSession
    from kme_tpu.workload import cancel_heavy_stream, zipf_symbol_stream

    cfg = LaneConfig(lanes=symbols, slots=slots, accounts=accounts,
                     max_fills=max_fills, steps=steps, window=window)
    if workload == "cancel":
        msgs = cancel_heavy_stream(events, num_symbols=symbols,
                                   num_accounts=accounts, seed=seed)
    elif workload in STORM_WORKLOADS:
        from kme_tpu.workload import storm_stream

        msgs = storm_stream(workload, events, num_symbols=symbols,
                            num_accounts=accounts, seed=seed)
    else:
        msgs = zipf_symbol_stream(events, num_symbols=symbols,
                                  num_accounts=accounts, seed=seed,
                                  zipf_a=zipf_a)

    # correctness inside the bench: oracle parity on a stream prefix that
    # extends past the preamble into the trade mix
    preamble = 2 * accounts + symbols
    prefix = min(preamble + parity_prefix, len(msgs))
    _assert_parity_prefix(msgs, cfg, shards, prefix, width)

    # warmup run on a fresh session: compiles every (T, M) bucket the
    # timed run will hit (compiled executables are shared via the
    # module-level chunk cache)
    LaneSession(cfg, shards=shards, width=width).process(msgs)

    # timed run, phase by phase (sum = the honest end-to-end number)
    ses = LaneSession(cfg, shards=shards, width=width)
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    try:
        t0 = time.perf_counter()
        sched = ses.scheduler.plan(msgs)
        t_plan = time.perf_counter() - t0

        t0 = time.perf_counter()
        runs, barrier_ok = ses._dispatch(sched)   # pack + async dispatch
        jax.block_until_ready(ses.state)
        t_disp = time.perf_counter() - t0

        t0 = time.perf_counter()
        fills = ses._fetch(runs)
        t_fetch = time.perf_counter() - t0

        t0 = time.perf_counter()
        records = ses._reconstruct_wire(msgs, sched, runs, barrier_ok, fills)
        t_recon = time.perf_counter() - t0
    finally:
        if profile_dir:
            jax.profiler.stop_trace()

    n = len(msgs)
    total = t_plan + t_disp + t_fetch + t_recon
    # the serving number: one unphased process_wire call on a fresh
    # session — device compute, transfers and reconstruction overlap
    # naturally there, unlike the phase-separated sum above
    ses2 = LaneSession(cfg, shards=shards, width=width)
    t0 = time.perf_counter()
    ses2.process_wire(msgs)
    t_unphased = time.perf_counter() - t0
    metrics = ses.metrics()
    nfills = sum(int(r.host["nfill_total"]) for r in runs)
    # slice to the real placements: the M bucket is padded and padding
    # entries report ok=False
    cap_rejects = sum(int(r.host["cap_reject"][:len(r.idx)].sum())
                      for r in runs)
    rejects = sum(int((~r.host["ok"][:len(r.idx)]).sum())
                  for r in runs)
    n_records = sum(len(r) for r in records)
    steps_total = sum(sched.segment_steps)
    ops = n / total
    return {
        "metric": "orders_per_sec_e2e",
        "value": round(ops, 1),
        "unit": "orders/s",
        "vs_baseline": round(ops / REFERENCE_BASELINE_OPS, 3),
        "detail": {
            "events": n, "symbols": symbols, "accounts": accounts,
            "workload": workload,
            "zipf_a": zipf_a, "shards": shards, "slots": slots,
            "max_fills": max_fills, "width": width,
            "plan_s": round(t_plan, 3), "dispatch_s": round(t_disp, 3),
            "fetch_s": round(t_fetch, 3), "recon_s": round(t_recon, 3),
            "total_s": round(total, 3),
            "device_orders_per_sec": round(n / max(t_disp + t_fetch, 1e-9), 1),
            "unphased_orders_per_sec": round(n / max(t_unphased, 1e-9), 1),
            "sched_steps": steps_total,
            "msgs_per_step": round(n / max(steps_total, 1), 1),
            "trades": nfills, "out_records": n_records,
            "cap_rejects": cap_rejects, "rejects": rejects,
            "parity_checked_msgs": prefix,
            "backend": jax.devices()[0].platform,
            "baseline_assumption_ops": REFERENCE_BASELINE_OPS,
            # on-device counters (scan-carry accumulated) + gauges
            "device_metrics": metrics,
            # utilization: device-busy fraction of the e2e wall, and an
            # HBM-traffic estimate for the scan (dominant modeled terms:
            # the two position-array scatter copies r+w per step, plus
            # the gathered/scattered book rows) — integer workload, so
            # bandwidth-bound utilization is the honest analog of MFU
            "device_busy_frac": round((t_disp + t_fetch) / total, 3),
            "per_step_us": round(t_disp / max(steps_total, 1) * 1e6, 1),
            # MODELED, not measured: derived from the _est_step_bytes
            # bytes-per-step formula, like baseline_assumption_ops
            "modeled_hbm_gbps": round(
                _est_step_bytes(
                    symbols + (1 if shards == 1 and width > 0 else 0),
                    accounts, slots, max_fills,
                    width if shards == 1 and width > 0 else symbols)
                * steps_total / max(t_disp, 1e-9) / 1e9, 1),
        },
    }


def _est_step_bytes(S, A, N, E, W) -> int:
    """Modeled HBM bytes touched per scan step (see bench detail note):
    position traffic, 6 slot-row arrays gathered + scattered at width W,
    fill outputs. With pos_dma active (compact width and accounts % 64
    == 0 — mirrors LaneSession's enable rule) positions move as row DMAs
    (W rows x 2A i32, in+out, two arrays) instead of full-array scatter
    rewrites."""
    if W < S and (2 * A) % 128 == 0:  # pos_dma row DMA
        pos = 2 * 2 * W * 2 * A * 4
    else:  # full-array scatter rewrite, read+write, 8B each
        pos = 2 * 2 * 8 * S * A
    rows = 2 * 6 * W * 2 * N * 4
    fills = 4 * W * E * 8
    return pos + rows + fills


def bench_native_engine(events: int = 100_000, seed: int = 0,
                        batch: int = 8192, compat: str = "java") -> dict:
    """Quirk-exact throughput of the NATIVE C++ engine on the stock
    harness workload — the fast java-compat serving path (COMPAT.md:
    quirk-exact parallelism is impossible under Q11, so this host-native
    engine plays the role the reference's own JVM stack plays)."""
    from kme_tpu.native.oracle import NativeOracleEngine
    from kme_tpu.workload import harness_stream

    msgs = harness_stream(events, seed=seed)
    if len(msgs) <= batch:
        raise ValueError(
            f"events ({len(msgs)} incl. preamble) must exceed the warmup "
            f"batch ({batch}) — nothing would be timed")
    eng = NativeOracleEngine(compat)
    eng.process_wire(msgs[:batch])  # warmup (allocator, caches)
    t0 = time.perf_counter()
    nlines = 0
    for lo in range(batch, len(msgs), batch):
        out = eng.process_wire(msgs[lo:lo + batch])
        nlines += sum(len(x) for x in out)
    dt = time.perf_counter() - t0
    n = len(msgs) - batch
    ops = n / dt
    return {
        "metric": "orders_per_sec_native_quirk_exact",
        "value": round(ops, 1),
        "unit": "orders/s",
        "vs_baseline": round(ops / REFERENCE_BASELINE_OPS, 3),
        "detail": {
            "events": n, "seconds": round(dt, 3), "batch": batch,
            "compat": compat, "out_lines": nlines,
            "engine": "native C++ (kme_tpu/native/kme_oracle.cpp)",
            "baseline_assumption_ops": REFERENCE_BASELINE_OPS,
        },
    }


def bench_parity_engine(events: int = 4096, seed: int = 0, batch: int = 2048,
                        compat: str = "java") -> dict:
    """Throughput of the serial device parity engine on the stock harness
    workload (the quirk-exact replica — correctness path, not the
    performance path)."""
    from kme_tpu.engine.parity import ParityCaps, ParityEngine
    from kme_tpu.workload import harness_stream

    caps = ParityCaps(balances=32, positions=8192, books=32, buckets=1024,
                      orders=16384, max_events=64, batch=batch)
    msgs = harness_stream(events, seed=seed)
    eng = ParityEngine(compat, caps)
    eng.process_batch(msgs[:batch])  # warmup: compile + first dispatch
    t0 = time.perf_counter()
    eng.process_batch(msgs[batch:])
    dt = time.perf_counter() - t0
    n = len(msgs) - batch
    ops = n / dt
    import jax
    return {
        "metric": "orders_per_sec_serial_parity",
        "value": round(ops, 1),
        "unit": "orders/s",
        "vs_baseline": round(ops / REFERENCE_BASELINE_OPS, 3),
        "detail": {
            "events": n, "seconds": round(dt, 3), "batch": batch,
            "compat": compat, "backend": jax.devices()[0].platform,
            "baseline_assumption_ops": REFERENCE_BASELINE_OPS,
        },
    }


def bench_latency(events: int = 20_000, symbols: int = 1024,
                  accounts: int = 2048, seed: int = 0, zipf_a: float = 1.2,
                  slots: int = 128, max_fills: int = 16,
                  width: int = DEFAULT_WIDTH, shards: int = 1,
                  batch: int = DEFAULT_LATENCY_BATCH,
                  engine: str = "seq") -> dict:
    """Streaming latency (BASELINE.md p99 column): the stream is served
    in micro-batches of `batch` messages through process_wire; a
    message's fill latency is bounded by its batch's wall time, so the
    per-batch wall distribution IS the latency envelope. engine='seq'
    (default) serves each micro-batch as ONE kernel dispatch + one
    fetch round; 'sweep' is the round-3 lanes path.

    Caveat on this driver's numbers: the TPU sits behind a tunnel with
    ~100ms round trips — the measured floor is transport latency, not
    engine time (the same batches cost ~10ms of device+host work on
    locally attached hardware per the phase timings)."""
    import jax

    from kme_tpu.workload import zipf_symbol_stream

    msgs = zipf_symbol_stream(events, num_symbols=symbols,
                              num_accounts=accounts, seed=seed,
                              zipf_a=zipf_a)

    if engine == "seq":
        from kme_tpu.engine import seq as SQ
        from kme_tpu.runtime.seqsession import SeqSession

        # the seq kernel's plane layout needs 128-multiples; the
        # EFFECTIVE envelope is reported in the detail dict
        slots = -(-max(slots, 128) // 128) * 128
        accounts = -(-accounts // 128) * 128
        scfg = SQ.SeqConfig(
            lanes=symbols, slots=slots, accounts=accounts,
            max_fills=max_fills, hbm_books=slots > 512,
            batch=max(128, min(4096, 1 << (batch - 1).bit_length())))
        mk = lambda: SeqSession(scfg)
    else:
        from kme_tpu.engine.lanes import LaneConfig
        from kme_tpu.runtime.session import LaneSession

        cfg = LaneConfig(lanes=symbols, slots=slots, accounts=accounts,
                         max_fills=max_fills)
        mk = lambda: LaneSession(cfg, shards=shards, width=width)
    warm = mk()  # compile every shape bucket
    for lo in range(0, len(msgs), batch):
        warm.process_wire(msgs[lo:lo + batch])
    ses = mk()
    walls = []
    t_all = time.perf_counter()
    for lo in range(0, len(msgs), batch):
        t0 = time.perf_counter()
        ses.process_wire(msgs[lo:lo + batch])
        walls.append(time.perf_counter() - t0)
    t_all = time.perf_counter() - t_all
    walls.sort()

    def pct(p):
        # nearest-rank percentile; with few batches high percentiles
        # degenerate to the worst batch — `batches` is reported so the
        # sample size is visible
        import math

        return walls[max(0, min(len(walls) - 1,
                                math.ceil(p * len(walls)) - 1))]

    return {
        "metric": "p99_batch_latency_ms",
        "value": round(pct(0.99) * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round((len(msgs) / t_all) / REFERENCE_BASELINE_OPS, 3),
        "detail": {
            "events": len(msgs), "batch": batch, "engine": engine,
            "slots": slots,
            # topology flags only apply to the sweep engine; the seq
            # path is single-device with no compaction width
            "width": width if engine != "seq" else 0,
            "shards": shards if engine != "seq" else 1,
            "p50_ms": round(pct(0.50) * 1e3, 2),
            "p90_ms": round(pct(0.90) * 1e3, 2),
            "p99_ms": round(pct(0.99) * 1e3, 2),
            "max_ms": round(walls[-1] * 1e3, 2),
            "batches": len(walls),
            "streamed_orders_per_sec": round(len(msgs) / t_all, 1),
            "backend": jax.devices()[0].platform,
        },
    }


def bench_shards(events: int = 4000, symbols: int = 8,
                 accounts: int = 32, seed: int = 0,
                 workload: str = "zipf-hot",
                 shards_list=(1, 2, 4), slots: int = 128,
                 max_fills: int = 16, slice_size: int = 500,
                 dispatch: str = "auto") -> dict:
    """Elastic-sharding suite (`--suite shards`): the skewed workload
    through SeqMeshSession at every shard count, with byte parity
    asserted against the scalar fixed-mode oracle and MIGRATIONS
    REQUIRED at shards > 1 (the stream is fed in slices, because
    rebalancing happens between process_wire calls only — a single
    giant batch would never migrate). At the top shard count a
    rebalance=False control run records the static-hash placement's
    imbalance, so the report carries both `shard_imbalance` (elastic,
    perfgate-gated, down-is-better) and `shard_imbalance_static` (the
    adversary's score the elastic planner must beat).

    Per-chip async dispatch (r14) grows the suite three ways, all at
    the top shard count: a `--dispatch lockstep` control run re-asserts
    byte parity for the legacy mesh scan (the async-vs-lockstep parity
    leg CI runs), the report carries `chip_stall_frac` /
    `chip_stall_frac_lockstep` from the deterministic dispatch
    simulation (replay-stable — chip_stall_frac is perfgate-GATED
    down, and on zipf-hot async must strictly beat lockstep), and a
    wall_feed=True advisory run exercises the wall-fed rebalancer EWMA
    (parity asserted; its imbalance is reported but never gated — the
    fed walls are real clocks, so its placement drifts run to run).

    Runs on a CPU mesh when XLA_FLAGS=--xla_force_host_platform_
    device_count=N provides the virtual devices (the CI smoke) and
    unchanged on a real multi-chip mesh."""
    import jax

    from kme_tpu.engine import seq as SQ
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.parallel.seqmesh import SeqMeshSession
    from kme_tpu.workload import (payout_storm_stream, zipf_hot_stream,
                                  zipf_symbol_stream)

    need = max(shards_list)
    have = len(jax.devices())
    if have < need:
        raise RuntimeError(
            f"--suite shards needs {need} devices, found {have}: set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={need} "
            f"(before jax initializes) for a virtual CPU mesh")
    if workload == "zipf-hot":
        msgs = zipf_hot_stream(events, num_symbols=symbols,
                               num_accounts=accounts, seed=seed)
    elif workload == "payout-storm":
        msgs = payout_storm_stream(events, num_symbols=symbols,
                                   num_accounts=accounts, seed=seed)
    else:
        msgs = zipf_symbol_stream(events, num_symbols=symbols,
                                  num_accounts=accounts, seed=seed)
    oracle = OracleEngine("fixed", book_slots=slots,
                          max_fills=max_fills)
    want = [r.wire() for m in msgs for r in oracle.process(m.copy())]
    cfg = SQ.SeqConfig(lanes=symbols, slots=slots,
                       accounts=-(-max(accounts, 128) // 128) * 128,
                       max_fills=max_fills, pos_cap=1 << 10,
                       probe_max=8)

    def run(shards, rebalance, mode=dispatch, wall_feed=False):
        ses = SeqMeshSession(cfg, shards, rebalance=rebalance,
                             dispatch=mode, wall_feed=wall_feed)
        got = []
        t0 = time.perf_counter()
        for lo in range(0, len(msgs), slice_size):
            for per in ses.process_wire(msgs[lo:lo + slice_size]):
                got.extend(per)
        wall = time.perf_counter() - t0
        if got != want:
            raise AssertionError(
                f"shards={shards} rebalance={rebalance} "
                f"dispatch={ses.dispatch}: MatchOut diverged from the "
                f"single-chip oracle "
                f"({sum(a != b for a, b in zip(got, want))} lines + "
                f"{abs(len(got) - len(want))} length delta)")
        return ses, wall

    per_shards = []
    elastic_top = None
    top_ses = None
    for shards in shards_list:
        ses, wall = run(shards, rebalance=True)
        stats = ses.shard_stats()
        if shards > 1 and stats["migrations"] <= 0:
            raise AssertionError(
                f"shards={shards}: no migrations observed on the "
                f"skewed workload — the elastic planner never fired")
        # key is NOT "orders_per_sec" on purpose: the gate regex-scrapes
        # artifact text for GATED_METRICS names, and CI wall-clock
        # throughput would flap the shards gate — only the
        # deterministic shard_imbalance is meant to enforce here
        rec = {"shards": shards, "wall_s": round(wall, 3),
               "msgs_per_sec": round(len(msgs) / wall, 1),
               "parity": "byte-exact", "dispatch": ses.dispatch,
               **stats}
        if ses.dispatch == "async":
            # per-shard-count copies use NON-gated names on purpose:
            # per_shards serializes before the top-level detail keys
            # and the gate regex takes the FIRST occurrence of each
            # GATED_METRICS name in the artifact text
            rec.update({f"run_{k}": v
                        for k, v in ses.stall_stats().items()})
        per_shards.append(rec)
        if shards == need:
            elastic_top = rec
            top_ses = ses
    _static_ses, static_wall = run(need, rebalance=False)
    static = _static_ses.shard_stats()
    detail = {
        "suite": "shards", "workload": workload, "events": len(msgs),
        "slice_size": slice_size, "shard_counts": list(shards_list),
        "dispatch": elastic_top["dispatch"],
        "per_shards": per_shards,
        "shard_imbalance": elastic_top["imbalance"],
        "shard_imbalance_static": static["imbalance"],
        "static_wall_s": round(static_wall, 3),
        "migrations": elastic_top["migrations"],
        "rebalances": elastic_top["rebalances"],
        "backend": jax.devices()[0].platform,
        "note": "byte parity asserted vs the scalar oracle at every "
                "shard count; migrations required at shards > 1",
    }
    if top_ses is not None and top_ses.dispatch == "async":
        stall = top_ses.stall_stats()
        # the stall fractions come from the deterministic dispatch
        # simulation (weighted message costs, both schedules replayed
        # on the same placements) — replay-stable, so chip_stall_frac
        # is safe to gate and safe to hard-assert against its own
        # lockstep twin on the skewed workload
        detail.update(stall)
        if (workload == "zipf-hot" and need > 1
                and stall["chip_stall_frac"]
                >= stall["chip_stall_frac_lockstep"]):
            raise AssertionError(
                f"async dispatch did not reduce chip stall on "
                f"zipf-hot at shards={need}: async "
                f"{stall['chip_stall_frac']} >= lockstep "
                f"{stall['chip_stall_frac_lockstep']}")
        # async-vs-lockstep parity leg: the legacy mesh scan must still
        # produce the same bytes (run() asserts vs the oracle, which
        # both modes must match — transitively async == lockstep)
        _lock_ses, lock_wall = run(need, rebalance=True,
                                   mode="lockstep")
        detail["lockstep_wall_s"] = round(lock_wall, 3)
        # wall_feed advisory leg: real per-shard walls folded into the
        # rebalancer EWMA; parity holds (placement-independent), but
        # the resulting imbalance rides wall clocks so it is reported,
        # never gated
        _wf_ses, wf_wall = run(need, rebalance=True, wall_feed=True)
        detail["wall_feed_wall_s"] = round(wf_wall, 3)
        detail["wall_feed_imbalance"] = _wf_ses.shard_stats()[
            "imbalance"]
    if detail["shard_imbalance"] >= detail["shard_imbalance_static"]:
        detail["imbalance_warning"] = (
            f"elastic imbalance {detail['shard_imbalance']} did not "
            f"beat static {detail['shard_imbalance_static']}")
        print(f"kme-bench: WARNING {detail['imbalance_warning']}",
              file=sys.stderr)
    return {
        "metric": "shard_imbalance",
        "value": elastic_top["imbalance"],
        "unit": "max/mean",
        "vs_baseline": round(
            elastic_top["msgs_per_sec"] / REFERENCE_BASELINE_OPS, 3),
        "detail": detail,
    }


def bench_groups(events: int = 20_000, symbols: int = 1024,
                 accounts: int = 256, seed: int = 0,
                 workload: str = "zipf", cross_frac: float = 0.5,
                 group_counts=(1, 2, 4), slots: int = 128,
                 max_fills: int = 16, prefund: int = 8,
                 reps: int = 3) -> dict:
    """Multi-leader scale-out suite (`--suite groups`, ISSUE 9): the
    stream is split by the front door (bridge/front.py — rendezvous
    symbol routing + chunked reserve→settle transfer injection) and
    each group's substream runs through its own fresh engine. In the
    deployed topology the N groups are N separate leader HOSTS, so the
    deployment's throughput is bounded by its critical path — the
    slowest group. The bench models exactly that: per-group walls are
    measured SERIALLY (best of `reps`, after a process-level warmup
    run) and accepted-orders/s = accepted / max(per-group wall). A CI
    box with one core measures the same thing a multi-host deployment
    would, without pretending threads on one core are machines. At
    every group count the merged MatchOut is byte-compared against the
    single-leader oracle partitioned by the same router
    (front.verify_groups: THE COMPAT.md global-order convention).

    Deterministic seed-derived metrics (transfer fraction, shortfalls,
    parity) are the gated surface — wall-clock accepted-orders/s is
    reported per count (the ≥ 2x acceptance check at the top group
    count) but deliberately NOT under a GATED_METRICS name, same
    policy as bench_shards."""
    from kme_tpu.bridge import front
    from kme_tpu.native.oracle import NativeOracleEngine, \
        native_available
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.wire import dumps_order, parse_order
    from kme_tpu.workload import cross_account_stream, \
        zipf_symbol_stream

    top = max(group_counts)
    if workload == "cross-account":
        msgs = cross_account_stream(events, symbols, accounts, top,
                                    seed=seed, cross_frac=cross_frac)
    else:
        msgs = zipf_symbol_stream(events, num_symbols=symbols,
                                  num_accounts=accounts, seed=seed)
    lines = [dumps_order(m) for m in msgs]
    native = native_available()

    def make_engine():
        if native:
            return NativeOracleEngine("fixed", book_slots=slots,
                                      max_fills=max_fills)
        return OracleEngine("fixed", book_slots=slots,
                            max_fills=max_fills)

    def run_engine(eng, parsed):
        if native:
            out = eng.process_wire(parsed)
            return [ln for per_msg in out for ln in per_msg]
        return [r.wire() for m in parsed for r in eng.process(m)]

    # one throwaway run pays the process-level first-call costs
    # (library load, allocator growth) so no group count eats them
    run_engine(make_engine(),
               [parse_order(ln) for ln in lines[:2000]])

    per_counts = []
    base_ops = None
    accepted = None
    for n in group_counts:
        per_group, router = front.split_lines(lines, n,
                                              prefund=prefund)
        # parse is front-door work, identical at every group count —
        # kept outside the timed engine region
        parsed = [[parse_order(ln) for ln in sub] for sub in per_group]
        outs = [None] * n
        walls = []
        for k in range(n):
            best = None
            for _ in range(max(1, reps)):
                eng = make_engine()
                t0 = time.perf_counter()
                out = run_engine(eng, parsed[k])
                w = time.perf_counter() - t0
                best = w if best is None else min(best, w)
                outs[k] = out
            walls.append(best)
        wall = max(walls)
        rep = front.verify_groups(lines, outs, compat="fixed",
                                  book_slots=slots,
                                  max_fills=max_fills,
                                  prefund=prefund)
        if not rep["ok"]:
            raise AssertionError(
                f"groups={n}: merged MatchOut diverged from the "
                f"single-leader oracle: {rep['mismatches'][:1]}")
        if accepted is None:
            # accepted orders are identical at every group count (the
            # parity assertion above pins that) — count once
            accepted = sum(
                1 for g in outs for ln in g
                if ln.startswith("OUT ")
                and not front.is_internal_line(ln)
                and any(f'"action":{a},' in ln for a in (2, 3, 5, 6)))
        ops = accepted / wall
        if base_ops is None:
            base_ops = ops
        per_counts.append({
            "groups": n,
            "group_walls_s": [round(w, 4) for w in walls],
            "wall_s": round(wall, 4),
            "accepted_per_sec": round(ops, 1),
            "speedup": round(ops / base_ops, 2),
            "substream_lines": [len(s) for s in per_group],
            "transfers": router.counters["cross_shard_transfers_total"],
            "shortfalls": router.counters["transfer_shortfall_total"],
            "parity": "byte-exact"})
    topc = per_counts[-1]
    orders = sum(1 for m in msgs if m.action in (2, 3))
    frac = round(topc["transfers"] / max(1, orders), 4)
    detail = {
        "suite": "groups", "workload": workload, "events": len(msgs),
        "orders": orders, "group_counts": list(group_counts),
        "prefund": prefund, "engine": "native" if native else "oracle",
        "per_groups": per_counts,
        "cross_shard_transfer_frac": frac,
        "transfer_shortfalls": topc["shortfalls"],
        "accepted_orders": accepted,
        "speedup_top": topc["speedup"],
        "note": "byte parity vs the partitioned single-leader oracle "
                "asserted at every group count; accepted-orders/s is "
                "wall-clock (ungated), transfer metrics deterministic "
                "(gated)",
        # engine identity doubles as the perfgate backend marker: a
        # python-oracle run is not comparable to a native baseline, so
        # a mismatch demotes the gate to advisory (same rule as
        # TPU-vs-CPU elsewhere)
        "backend": "native" if native else "oracle",
    }
    if topc["speedup"] < 2.0 and native:
        detail["speedup_warning"] = (
            f"groups={topc['groups']} accepted-orders/s only "
            f"{topc['speedup']}x the single-leader run")
        print(f"kme-bench: WARNING {detail['speedup_warning']}",
              file=sys.stderr)
    return {
        "metric": "cross_shard_transfer_frac",
        "value": frac,
        "unit": "transfers/order",
        "vs_baseline": round(
            topc["accepted_per_sec"] / REFERENCE_BASELINE_OPS, 3),
        "detail": detail,
    }


def bench_multihost(events: int = 6000, symbols: int = 512,
                    accounts: int = 128, seed: int = 0,
                    groups: int = 2, groups_to: int = 4,
                    cross_frac: float = 0.5, slots: int = 128,
                    max_fills: int = 16, prefund: int = 8) -> dict:
    """Multi-host transport suite (`--suite multihost`, ROADMAP item
    2a): the same split workload is run twice —

    - IN-PROCESS: per-group fresh oracle engines over the front split,
      serially timed (the bench_groups model: deployment throughput is
      the slowest group);
    - CROSS-HOST: one real `kme-serve` subprocess per group on its own
      TCP port, fed over `front.FrontLinks` (the stamped multi-host
      produce path with reconnect-with-resume off the out_seq cursor),
      timed from first produce to every group's heartbeat reporting
      its substream drained, then byte-verified from the durable logs
      against the partitioned single-leader oracle.

    The throughput pair (and their ratio — what the wire, framing and
    checkpoint machinery cost over raw engines) is reported but NOT
    gated: it is wall-clock. The gated surface is deterministic:
    `moved_key_frac`, the fraction of the symbol+account key universe
    the N→M reshard plan moves (bridge/reshard.plan_reshard). Rendez-
    vous assignment keeps it at the minimal (m-n)/m; a consistent-
    hashing regression (salt drift, modulo hashing) jumps it toward
    1.0 and fails the gate long before a live reshard would hurt."""
    import json as _json
    import os
    import shutil
    import socket
    import subprocess
    import tempfile

    from kme_tpu.bridge import front
    from kme_tpu.bridge.provision import group_topics
    from kme_tpu.bridge.reshard import plan_reshard
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.wire import dumps_order, parse_order
    from kme_tpu.workload import cross_account_stream

    msgs = cross_account_stream(events, symbols, accounts, groups,
                                seed=seed, cross_frac=cross_frac)
    lines = [dumps_order(m) for m in msgs]
    per_group, router = front.split_lines(lines, groups,
                                          prefund=prefund)
    sizes = [len(s) for s in per_group]

    # -- leg 1: in-process per-group engines (the raw-engine bound) ---
    outs = []
    walls = []
    for k in range(groups):
        parsed = [parse_order(ln) for ln in per_group[k]]
        eng = OracleEngine("fixed", book_slots=slots,
                           max_fills=max_fills)
        t0 = time.perf_counter()
        out = [r.wire() for m in parsed for r in eng.process(m)]
        walls.append(time.perf_counter() - t0)
        outs.append(out)
    rep = front.verify_groups(lines, outs, compat="fixed",
                              book_slots=slots, max_fills=max_fills,
                              prefund=prefund)
    if not rep["ok"]:
        raise AssertionError(f"in-process groups diverged from the "
                             f"single-leader oracle: "
                             f"{rep['mismatches'][:1]}")
    accepted = sum(
        1 for g in outs for ln in g
        if ln.startswith("OUT ") and not front.is_internal_line(ln)
        and any(f'"action":{a},' in ln for a in (2, 3, 5, 6)))
    inproc_ops = accepted / max(walls)

    # -- leg 2: per-group kme-serve processes over real TCP -----------
    def _free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    work = tempfile.mkdtemp(prefix="kme-bench-multihost-")
    ports = [_free_port() for _ in range(groups)]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("KME_FAULTS", None)
    srvs = []
    try:
        for k in range(groups):
            gdir = os.path.join(work, f"group{k}")
            os.makedirs(gdir, exist_ok=True)
            srvs.append(subprocess.Popen(
                [sys.executable, "-m", "kme_tpu.cli", "serve",
                 "--engine", "oracle", "--compat", "fixed",
                 "--batch", "64", "--slots", str(slots),
                 "--max-fills", str(max_fills),
                 "--group", f"{k}/{groups}",
                 "--checkpoint-dir", gdir,
                 "--checkpoint-every", "600",
                 "--auto-provision",
                 "--listen", f"127.0.0.1:{ports[k]}",
                 "--idle-exit", "3",
                 "--health-file", os.path.join(gdir, "serve.health"),
                 "--health-every", "0.1"],
                env=env))
        links = front.FrontLinks(
            [f"127.0.0.1:{p}" for p in ports], retries=200,
            backoff_s=0.1)
        t0 = time.perf_counter()
        for k in range(groups):
            for ln in per_group[k]:
                links.send(k, ln)
        # drained = every group's heartbeat reports its full substream
        # consumed (outputs are produced before the offset advances)
        deadline = time.time() + 300.0
        drained = [False] * groups
        while time.time() < deadline and not all(drained):
            for k in range(groups):
                if drained[k]:
                    continue
                try:
                    with open(os.path.join(work, f"group{k}",
                                           "serve.health")) as f:
                        hb = _json.load(f)
                    drained[k] = int(hb.get("offset", 0)) >= sizes[k]
                except (OSError, ValueError):
                    pass
            if not all(drained):
                time.sleep(0.05)
        tcp_wall = time.perf_counter() - t0
        if not all(drained):
            raise AssertionError(
                f"cross-host groups never drained: {drained}")
        link_state = links.snapshot()
        links.close()
        for s in srvs:     # idle-exit lapses, clean shutdown
            if s.wait(timeout=60) != 0:
                raise AssertionError(
                    f"kme-serve exited rc={s.returncode}")
        srvs = []
        # byte parity from the durable logs (crossing the wire must
        # change nothing)
        from kme_tpu.bridge.broker import BrokerError, InProcessBroker
        actual = []
        for k in range(groups):
            b = InProcessBroker(persist_dir=os.path.join(
                work, f"group{k}", "broker-log"))
            merged = []
            for topic in (group_topics(k)[1], group_topics(k)[2]):
                off = 0
                try:
                    while True:
                        recs = b.fetch(topic, off, 4096, timeout=0.0)
                        if not recs:
                            break
                        merged.extend(recs)
                        off += len(recs)
                except BrokerError:
                    pass
            merged.sort(key=lambda r: (r.out_seq
                                       if r.out_seq is not None
                                       else -1))
            actual.append([f"{r.key} {r.value}" for r in merged])
        trep = front.verify_groups(lines, actual, compat="fixed",
                                   book_slots=slots,
                                   max_fills=max_fills,
                                   prefund=prefund)
        if not trep["ok"]:
            raise AssertionError(
                f"cross-host run diverged from the single-leader "
                f"oracle: {trep['mismatches'][:1]}")
    finally:
        for s in srvs:
            s.kill()
            s.wait()
        shutil.rmtree(work, ignore_errors=True)
    tcp_ops = accepted / tcp_wall

    # -- the gated deterministic surface: the reshard move plan -------
    plan = plan_reshard(groups, groups_to, range(symbols),
                        range(accounts))
    detail = {
        "suite": "multihost", "events": len(msgs),
        "groups": groups, "groups_to": groups_to,
        "symbols": symbols, "accounts": accounts,
        "prefund": prefund, "seed": seed,
        "substream_lines": sizes,
        "accepted_orders": accepted,
        "inproc_accepted_per_sec": round(inproc_ops, 1),
        "tcp_accepted_per_sec": round(tcp_ops, 1),
        "tcp_over_inproc": round(tcp_ops / inproc_ops, 4),
        "tcp_wall_s": round(tcp_wall, 3),
        "front_links": link_state,
        "moved_key_frac": round(plan["moved_key_frac"], 6),
        "rendezvous_minimal_frac": plan["rendezvous_minimal_frac"],
        "moved_symbols": len(plan["moved_symbols"]),
        "moved_accounts": len(plan["moved_accounts"]),
        "parity": "byte-exact",
        "note": "throughput pair is wall-clock (ungated); "
                "moved_key_frac is the deterministic gated surface — "
                "rendezvous keeps it minimal, hashing regressions "
                "push it toward 1.0",
        "backend": "oracle",
    }
    return {
        "metric": "moved_key_frac",
        "value": detail["moved_key_frac"],
        "unit": f"keys moved, {groups}->{groups_to}",
        "vs_baseline": round(tcp_ops / REFERENCE_BASELINE_OPS, 3),
        "detail": detail,
    }


def bench_storms(events: int = 4000, seed: int = 0,
                 high_lag: int = 32,
                 drain_per_msg: float = 2.0) -> dict:
    """Adversarial-storm shed-policy suite (`--suite storms`): run every
    STORM_PROFILES stream through the broker's deterministic overload
    replay (bridge/broker.simulate_overload — no wall clock, no RNG, no
    threads) and report each profile's shed fraction as a gated metric
    `shed_frac_<profile>` (perfgate, down-is-better, CPU-deterministic
    like shard_imbalance). A drift in admission policy, priority
    classing or the profile generators moves these numbers; nothing
    else can.

    Each profile's admitted stream is also replayed through the Python
    oracle — shedding must be a pure input filter, so the surviving
    sequence has to be processable without crash for every profile (the
    byte-parity end-to-end proof lives in the kme-chaos storm
    scenarios; this is the fast in-process survival check), and the
    whole simulation is run twice to assert determinism.
    """
    import time

    from kme_tpu.bridge.broker import OverloadController, simulate_overload
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.wire import dumps_order, parse_order
    from kme_tpu.workload import (STORM_PROFILES, storm_stream,
                                  storm_windows)

    # reduced-but-sheds scale: small enough for CI seconds, large
    # enough that EVERY profile's burst overwhelms the modeled drain
    # (perfgate skips zero baselines, so shed_frac must be > 0)
    scale = {"payout-storm-wide": (64, 32),
             "flash-crowd": (32, 32),
             "cancel-storm": (16, 32),
             "hot-book": (8, 32),
             "liquidation-cascade": (32, 32)}
    t0 = time.perf_counter()
    per_profile: dict = {}
    metrics: dict = {}
    for name in STORM_PROFILES:
        symbols, accounts = scale[name]
        msgs = storm_stream(name, events, num_symbols=symbols,
                            num_accounts=accounts, seed=seed)
        lines = [dumps_order(m) for m in msgs]
        windows = storm_windows(name, events, num_symbols=symbols,
                                num_accounts=accounts)
        runs = []
        for _rep in range(2):       # determinism: identical twice
            ctl = OverloadController(high_lag=high_lag)
            runs.append(simulate_overload(lines, windows, ctl,
                                          drain_per_msg=drain_per_msg))
        sim, sim2 = runs
        assert sim["admitted_idx"] == sim2["admitted_idx"] \
            and sim["shed_frac"] == sim2["shed_frac"], (
                f"simulate_overload is nondeterministic for {name}")
        if sim["shed"] == 0:
            raise AssertionError(
                f"storm profile {name} shed nothing at the suite "
                f"scale — the gate would silently skip it")
        # oracle survival of the admitted stream (pure input filter)
        eng = OracleEngine("fixed")
        out_lines = 0
        for i in sim["admitted_idx"]:
            out_lines += len(eng.process(parse_order(lines[i])))
        mname = "shed_frac_" + name.replace("-", "_")
        metrics[mname] = sim["shed_frac"]
        per_profile[name] = {
            "records": sim["total"],
            "admitted": sim["admitted"],
            "shed": sim["shed"],
            "shed_frac": sim["shed_frac"],
            "max_backlog": sim["max_backlog"],
            "windows": [list(w) for w in windows],
            "symbols": symbols, "accounts": accounts,
            "oracle_out_lines": out_lines,
            "controller": sim["controller"],
        }
    elapsed = time.perf_counter() - t0
    worst = max(metrics.values())
    detail = {
        "suite": "storms", "events": events, "seed": seed,
        "high_lag": high_lag, "drain_per_msg": drain_per_msg,
        "elapsed_s": round(elapsed, 3),
        "profiles": per_profile,
        **{k: round(v, 4) for k, v in metrics.items()},
    }
    print(f"kme-bench storms: "
          + " ".join(f"{k}={v:.4f}" for k, v in sorted(metrics.items()))
          + f" ({elapsed:.1f}s)", file=sys.stderr)
    return {
        "metric": "storm_shed_frac_max",
        "value": round(worst, 4),
        "unit": "shed fraction",
        "vs_baseline": 0.0,
        "detail": detail,
    }


def bench_wire(events: int = 20_000, seed: int = 0,
               batch: int = 512, repeats: int = 3) -> dict:
    """Binary-wire ingress suite (`--suite wire`): the SAME seeded
    harness stream is driven into a real loopback kme TCP broker twice
    at matched batching — once as JSON `produce_batch` rows (the
    pre-PR-11 bulk path), once as 72-byte binary frames through
    `produce_frames` — and the suite reports both ingress rates.
    `ingress_msgs_per_sec` (binary, up-is-better) and `wire_parse_s`
    (cumulative frame-decode wall for the timed binary run,
    down-is-better) are perfgate-gated vs BASELINE_wire.json on CPU.

    Parity is structural, not statistical: both modes must leave the
    broker with BYTE-IDENTICAL stored values (the binary path decodes
    to the canonical order_json before anything durable sees it), and
    the stored stream replays through the Python oracle to identical
    MatchOut lines — so the speedup can never come from changing what
    gets admitted. The binary/JSON ratio is also asserted >= 1.5 on
    CPU (the ISSUE's floor for the whole exercise).

    A third timed pass drives the SAME frames with per-order client
    trace ids attached (80-byte FLAG_TID frames, dtrace
    client_trace_id): `trace_overhead_frac` is the ingress-rate cost
    of tracing, reported as an ADVISORY in the tail (soft 5% budget —
    printed, never gated) with the sample trace ids a kme-loadgen run
    over the same stream would report."""
    import tempfile
    import time

    from kme_tpu.bridge import tcp as tcpmod
    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.telemetry.dtrace import (client_trace_id,
                                          client_trace_ids)
    from kme_tpu.wire import dumps_order, encode_frames, parse_order
    from kme_tpu.workload import harness_stream

    msgs = harness_stream(events, seed=seed, num_accounts=64,
                          num_symbols=16, validate=True)
    n = len(msgs)
    lines = [dumps_order(m) for m in msgs]
    chunks = [(lo, msgs[lo:lo + batch]) for lo in range(0, n, batch)]
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        broker = InProcessBroker(persist_dir=td)
        srv, _ = tcpmod.serve_broker(port=0, broker=broker)
        host, port = srv.server_address
        cli = tcpmod.TcpBroker(host, port)
        runs = {"json": [], "binary": [], "traced": []}
        parse_s = None
        stored = {}
        try:
            for rep in range(repeats):
                for mode in ("json", "binary", "traced"):
                    topic = f"wire_{mode}_r{rep}"
                    cli.create_topic(topic)
                    pns0 = broker.wire_parse_ns
                    t1 = time.perf_counter()
                    if mode == "json":
                        for _, ch in chunks:
                            cli.produce_batch(
                                topic,
                                [(None, dumps_order(m)) for m in ch])
                    elif mode == "binary":
                        for _, ch in chunks:
                            cli.produce_frames(topic, None,
                                               encode_frames(ch))
                    else:
                        # the traced pass pays the FULL client cost:
                        # minting the ids (vectorized, like loadgen)
                        # and the wider 80-byte frames
                        for lo, ch in chunks:
                            tids = client_trace_ids(
                                lo, [m.aid for m in ch],
                                [m.oid for m in ch])
                            cli.produce_frames(
                                topic, None,
                                encode_frames(ch, tids=tids))
                    dt = time.perf_counter() - t1
                    assert broker.end_offset(topic) == n, (
                        f"{mode} ingress lost records: "
                        f"{broker.end_offset(topic)} != {n}")
                    runs[mode].append(dt)
                    if mode == "binary" and (parse_s is None
                                             or dt <= min(runs["binary"])):
                        parse_s = (broker.wire_parse_ns - pns0) / 1e9
                    if rep == 0:
                        vals = []
                        off = 0
                        while off < n:
                            recs = broker.fetch(topic, off, 4096)
                            vals.extend(r.value for r in recs)
                            off = recs[-1].offset + 1
                        stored[mode] = vals
        finally:
            cli.close()
            srv.shutdown()
    # byte parity: the encoding must be invisible past admission —
    # including the trace words (tid is transport metadata, never part
    # of the stored value)
    assert stored["json"] == stored["binary"], (
        "binary ingress altered the stored record bytes")
    assert stored["json"] == stored["traced"], (
        "trace-id carriage altered the stored record bytes")
    oracle_out = {}
    for mode, vals in stored.items():
        eng = OracleEngine("fixed")
        out = []
        for v in vals:
            out.extend(eng.process(parse_order(v)))
        oracle_out[mode] = out
    assert oracle_out["json"] == oracle_out["binary"], (
        "oracle replay diverged between ingress encodings")
    json_s = min(runs["json"])
    bin_s = min(runs["binary"])
    traced_s = min(runs["traced"])
    json_mps = n / json_s
    bin_mps = n / bin_s
    traced_mps = n / traced_s
    speedup = bin_mps / json_mps
    overhead = max(0.0, 1.0 - traced_mps / bin_mps)
    import jax

    backend = jax.default_backend()
    if backend == "cpu" and speedup < 1.5:
        raise AssertionError(
            f"binary ingress speedup {speedup:.2f}x < 1.5x floor "
            f"(json {json_mps:,.0f} msg/s, binary {bin_mps:,.0f} msg/s)")
    elapsed = time.perf_counter() - t0
    detail = {
        "suite": "wire", "events": events, "records": n,
        "seed": seed, "batch": batch, "repeats": repeats,
        "backend": backend,
        "elapsed_s": round(elapsed, 3),
        "json_s": round(json_s, 4), "binary_s": round(bin_s, 4),
        "json_msgs_per_sec": round(json_mps, 1),
        "speedup_binary": round(speedup, 3),
        "oracle_out_lines": len(oracle_out["binary"]),
        # gated metrics (perfgate reads the detail root)
        "ingress_msgs_per_sec": round(bin_mps, 1),
        "wire_parse_s": round(parse_s, 6),
        # advisory, never gated: the cost of carrying client trace ids
        # (80-byte FLAG_TID frames) on the binary ingress path, plus
        # the ids the tail quotes — the SAME deterministic
        # client_trace_id values a kme-loadgen run over this stream
        # reports in its slow_samples section
        "traced_msgs_per_sec": round(traced_mps, 1),
        "trace_overhead_frac": round(overhead, 4),
        "trace_sample_ids": [
            f"0x{client_trace_id(j, msgs[j].aid, msgs[j].oid):016x}"
            for j in range(min(4, n))],
    }
    over_tag = (" ** over 5% advisory budget **"
                if overhead > 0.05 else "")
    print(f"kme-bench wire: json={json_mps:,.0f} msg/s "
          f"binary={bin_mps:,.0f} msg/s ({speedup:.2f}x) "
          f"traced={traced_mps:,.0f} msg/s "
          f"(overhead {overhead:.1%}{over_tag}) "
          f"parse={parse_s:.4f}s ({elapsed:.1f}s)", file=sys.stderr)
    print(f"kme-bench wire: sample trace ids "
          f"{' '.join(detail['trace_sample_ids'])}", file=sys.stderr)
    return {
        "metric": "ingress_msgs_per_sec",
        "value": round(bin_mps, 1),
        "unit": "msgs/sec",
        "vs_baseline": round(bin_mps / REFERENCE_BASELINE_OPS, 3),
        "detail": detail,
    }


def bench_feed(events: int = 20_000, seed: int = 0,
               subs: int = 10_000, symbols: int = 1_000,
               profile: str = "flash-crowd",
               queue_bytes: int = 64 * 1024,
               depth_every: int = 256, depth_levels: int = 8) -> dict:
    """Market-data fan-out suite (`--suite feed`): a storm write
    profile replays through the Python oracle into an in-process
    broker, one FeedServer derives sequenced book frames from the
    MatchOut stream, and `subs` TCP subscribers (each pinned to one
    symbol, plus two wildcard auditors that take the whole feed)
    reconstruct their books from the wire bytes.

    Correctness is structural, not statistical:

      * the deriver is run TWICE from scratch over the same stream and
        must emit byte-identical concatenated frames (determinism —
        the failover guarantee);
      * every subscriber's reconstructed book must be byte-exact
        (`canonical_books`) against the oracle's resting-order store
        restricted to its subscription — including subscribers that
        went through conflation/resync cycles;
      * the wildcard auditors are additionally checked level-by-level
        at every depth (top-1, top-`depth_levels`, full) and on their
        top-of-book view;
      * per-symbol sequence accounting must show zero gaps and zero
        duplicates on every subscriber.

    `feed_msgs_per_sec` (frames delivered to subscriber sockets per
    second of fan-out wall, up-is-better) and `feed_lag_p99_ms`
    (admission-stamp -> frame-derivation p99, down-is-better) are
    perfgate-gated vs BASELINE_feed.json on CPU."""
    import resource
    import selectors
    import socket
    import tempfile

    from kme_tpu import opcodes as op
    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.feed.client import subscribe_line
    from kme_tpu.feed.derive import (BookBuilder, BookState, FeedDeriver,
                                     books_from_oracle, canonical_books)
    from kme_tpu.feed.server import FeedServer
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.telemetry import Registry
    from kme_tpu.workload import storm_stream

    # fd headroom: every subscriber is TWO sockets (client + accepted
    # server end). Never silently shrink the fleet — print what was
    # dropped when the rlimit wins.
    try:
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        want = 2 * subs + 512
        if soft < want:
            lift = want if hard == resource.RLIM_INFINITY \
                else min(want, hard)
            resource.setrlimit(resource.RLIMIT_NOFILE, (lift, hard))
            soft = lift
        cap = max(16, (soft - 256) // 2)
        if subs > cap:
            print(f"kme-bench feed: RLIMIT_NOFILE={soft} caps "
                  f"subscribers at {cap} (asked {subs})",
                  file=sys.stderr)
            subs = cap
    except (ValueError, OSError):
        pass

    msgs = storm_stream(profile, events, num_symbols=symbols, seed=seed)
    eng = OracleEngine("fixed")
    lines = []
    for m in msgs:
        lines.extend(r.wire() for r in eng.process(m))
    oracle_levels = books_from_oracle(eng)
    oracle_state = BookState()
    oracle_state.levels = oracle_levels
    all_sids = sorted({m.sid for m in msgs
                       if m.action == op.ADD_SYMBOL}) or [1]

    # determinism: two fresh derivers over the same stream must emit
    # byte-identical frames — this IS the failover guarantee
    streams = []
    for _ in range(2):
        d = FeedDeriver(depth_every=depth_every,
                        depth_levels=depth_levels)
        streams.append(b"".join(
            f.raw for i, ln in enumerate(lines)
            for f in d.on_line(ln, 1, i)))
    assert streams[0] == streams[1], (
        "feed derivation is nondeterministic: two derivers over the "
        "same MatchOut stream emitted different bytes")
    ref_deriver = d

    t_all = time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        broker = InProcessBroker(persist_dir=td)
        topic = "MatchOut"
        broker.create_topic(topic)
        registry = Registry()
        srv = FeedServer(broker, port=0, topic=topic,
                         depth_every=depth_every,
                         depth_levels=depth_levels,
                         queue_bytes=queue_bytes, registry=registry)
        host, port = srv.address

        # subscriber fleet: mostly 1-symbol subs spread round-robin,
        # plus two wildcard auditors holding the full feed
        plan = [None, None] + [
            {all_sids[i % len(all_sids)]} for i in range(max(0, subs - 2))]
        plan = plan[:max(2, subs)]
        csel = selectors.DefaultSelector()
        clients = []

        class _C:
            __slots__ = ("sock", "symbols", "out", "buf", "live", "eof")

            def __init__(self, symbols) -> None:
                self.symbols = symbols
                self.out = subscribe_line(symbols)
                self.buf = []
                self.live = False
                self.eof = False
                self.sock = socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)
                self.sock.setblocking(False)
                self.sock.connect_ex((host, port))

        def pump_clients(timeout: float) -> int:
            moved = 0
            for key, mask in csel.select(timeout=timeout):
                c = key.data
                if not c.live:
                    if mask & selectors.EVENT_WRITE:
                        try:
                            n = c.sock.send(c.out)
                        except (BlockingIOError, InterruptedError):
                            continue
                        c.out = c.out[n:]
                        if not c.out:
                            c.live = True
                            csel.modify(c.sock, selectors.EVENT_READ, c)
                    continue
                try:
                    data = c.sock.recv(1 << 16)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    data = b""
                if not data:
                    c.eof = True
                    csel.unregister(c.sock)
                    continue
                c.buf.append(data)
                moved += len(data)
            return moved

        try:
            # connect in waves so the listen backlog never overflows,
            # stepping the server so it accepts + handshakes as we go
            for lo in range(0, len(plan), 512):
                for want in plan[lo:lo + 512]:
                    c = _C(want)
                    clients.append(c)
                    csel.register(c.sock, selectors.EVENT_WRITE, c)
                for _ in range(200):
                    srv.step(0.001)
                    pump_clients(0.0)
                    if all(c.live for c in clients):
                        break
            deadline = time.monotonic() + 60
            while (len(srv._subs) < len(clients)
                   and time.monotonic() < deadline):
                srv.step(0.001)
                pump_clients(0.0)
            assert len(srv._subs) == len(clients), (
                f"only {len(srv._subs)}/{len(clients)} subscribers "
                f"live after the connect phase")

            # timed fan-out phase: produce the stamped MatchOut stream,
            # then run the (single-threaded) server + client pumps
            # until everything derived is on the wire
            t0 = time.perf_counter()
            for i, ln in enumerate(lines):
                broker.produce(topic, None, ln, epoch=1, out_seq=i,
                               ats=time.time_ns() // 1000)
            end = len(lines)
            deadline = time.monotonic() + 600
            while time.monotonic() < deadline:
                n = srv.step(0.0)
                pump_clients(0.0)
                if (n == 0 and srv.offset >= end
                        and not any(s.queue or s.conflating
                                    for s in srv._subs.values())):
                    break
            elapsed = time.perf_counter() - t0
            assert srv.offset >= end, (
                f"feed server stalled at offset {srv.offset}/{end}")
            stats = srv.stats()
            lag = registry.latency("feed_lag").quantiles()
        finally:
            srv.close()   # EOF to every subscriber
        # drain the client side to EOF: TCP buffers may still hold
        # frames the server already counted as delivered
        deadline = time.monotonic() + 60
        while (any(not c.eof for c in clients)
               and time.monotonic() < deadline):
            if pump_clients(0.05) == 0 and all(
                    not c.live or c.eof for c in clients):
                break
        csel.close()
        for c in clients:
            try:
                c.sock.close()
            except OSError:
                pass

    # reconstruction: every subscriber's book must be byte-exact vs
    # the oracle store restricted to its subscription
    conflated_subs = 0
    total_frames_rx = 0
    for ci, c in enumerate(clients):
        blob = b"".join(c.buf)
        bb = BookBuilder()
        used = bb.apply_buffer(blob)
        assert used == len(blob), (
            f"sub {ci}: {len(blob) - used} trailing bytes did not "
            f"decode as frames")
        assert not bb.errors, f"sub {ci}: {bb.errors}"
        assert not bb.gaps, f"sub {ci}: sequence gaps {bb.gaps[:4]}"
        assert bb.dups == 0, f"sub {ci}: {bb.dups} duplicate seqs"
        total_frames_rx += bb.frames
        if bb.resyncs:
            conflated_subs += 1
        if c.symbols is None:
            want_levels = oracle_levels
        else:
            want_levels = {k: v for k, v in oracle_levels.items()
                           if k[0] in c.symbols}
        assert canonical_books(bb.book) == canonical_books(
            want_levels), (
            f"sub {ci} (symbols={c.symbols}): reconstructed book "
            f"diverged from the oracle store")
        if c.symbols is None:
            # auditors: level-by-level at every depth + the TOB view
            for sid in sorted({s for s, _ in oracle_levels}):
                for nd in (1, depth_levels, 0):
                    assert bb.book.depth(sid, nd) == \
                        oracle_state.depth(sid, nd), (
                            f"auditor {ci}: depth-{nd} mismatch on "
                            f"symbol {sid}")
                assert bb.tob.get(sid) == oracle_state.tob(sid), (
                    f"auditor {ci}: TOB mismatch on symbol {sid}")
    delivered = stats["delivered"]
    fan_mps = delivered / elapsed if elapsed > 0 else 0.0
    lag_p99_ms = lag[0.99] * 1e3
    import jax

    backend = jax.default_backend()
    total_s = time.perf_counter() - t_all
    detail = {
        "suite": "feed", "events": events, "records": len(lines),
        "seed": seed, "profile": profile,
        "subscribers": len(clients), "symbols": len(all_sids),
        "queue_bytes": queue_bytes, "depth_every": depth_every,
        "depth_levels": depth_levels,
        "backend": backend,
        "elapsed_s": round(total_s, 3),
        "fanout_s": round(elapsed, 4),
        "frames_derived": stats["frames"],
        "frames_delivered": delivered,
        "frames_received": total_frames_rx,
        "deriver_frames": ref_deriver.frames_out,
        "conflations": stats["conflations"],
        "resyncs": stats["resyncs"],
        "conflated_subs": conflated_subs,
        "feed_lag_p50_ms": round(lag[0.5] * 1e3, 3),
        # gated metrics (perfgate reads the detail root)
        "feed_msgs_per_sec": round(fan_mps, 1),
        "feed_lag_p99_ms": round(lag_p99_ms, 3),
    }
    print(f"kme-bench feed: {len(clients)} subs x {len(all_sids)} "
          f"symbols [{profile}]: {fan_mps:,.0f} frames/s delivered "
          f"({stats['frames']} derived, {stats['conflations']} "
          f"conflations, {stats['resyncs']} resyncs) "
          f"lag p50={detail['feed_lag_p50_ms']}ms "
          f"p99={detail['feed_lag_p99_ms']}ms ({total_s:.1f}s)",
          file=sys.stderr)
    print(f"kme-bench feed: all {len(clients)} books byte-exact vs "
          f"oracle (2 auditors at every depth), 0 gaps, 0 dups",
          file=sys.stderr)
    return {
        "metric": "feed_msgs_per_sec",
        "value": round(fan_mps, 1),
        "unit": "frames/sec",
        "vs_baseline": round(fan_mps / REFERENCE_BASELINE_OPS, 3),
        "detail": detail,
    }


def bench_prof(events: int = 20_000, seed: int = 0,
               batch: int = 512, repeats: int = 3,
               overhead_ceiling: float = 0.03) -> dict:
    """Continuous-profiling overhead suite (`--suite prof`, ISSUE 16):
    the SAME seeded stream is served twice through an in-process
    MatchService — once with observability off, once with the full
    always-on plane (host sampling profiler + heartbeat thread + TSDB
    history + transfer/compute artifact + an armed watchpoint,
    ISSUE 17) — at matched batching.

    Three hard assertions, not statistics:
    - overhead: best-of-`repeats` serve walls must agree within
      `overhead_ceiling` (3% — the "always-on" budget the ISSUE sets;
      a profiler you must turn off under load is a debugger, not
      telemetry);
    - byte parity: both runs must leave BYTE-IDENTICAL MatchOut
      values — profiling must be invisible to the matched stream
      (COMPAT.md: the wire contract does not move);
    - artifact round-trip: the per-backend transfer-vs-compute JSON
      written at close must parse back with this backend's plane
      (the ROADMAP item-4 autotuner input).
    `prof_overhead_frac` reports ADVISORY (a ratio of two wall clocks
    on shared runners); the ceiling assert is the enforcement."""
    import os
    import tempfile
    import time

    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.bridge.provision import provision
    from kme_tpu.bridge.service import (MatchService, TOPIC_IN,
                                        TOPIC_OUT)
    from kme_tpu.telemetry import tsdb as tsdbmod
    from kme_tpu.telemetry.profiler import read_transfer_artifact
    from kme_tpu.wire import dumps_order
    from kme_tpu.workload import harness_stream

    t0 = time.perf_counter()
    msgs = harness_stream(events, seed=seed, num_accounts=64,
                          num_symbols=16, validate=True)
    lines = [dumps_order(m) for m in msgs]
    n = len(lines)

    def run_once(td: str, observe: bool):
        broker = InProcessBroker()
        provision(broker)
        for ln in lines:
            broker.produce(TOPIC_IN, None, ln)
        kw = {}
        health = None
        if observe:
            kw = dict(tsdb=os.path.join(td, "tsdb"), profile=True,
                      profile_artifact=os.path.join(td, "xfer.json"),
                      # a representative armed watchpoint rides the
                      # observe run: the 3% ceiling + MatchOut parity
                      # asserts below now also bound the watch plane
                      # (ISSUE 17: watchpoints must be free)
                      watch=["balance[1]<0"],
                      capture_dir=os.path.join(td, "captures"))
            health = os.path.join(td, "serve.health")
        svc = MatchService(broker, engine="oracle", compat="fixed",
                           batch=batch, **kw)
        t1 = time.perf_counter()
        svc.run(max_messages=n, idle_exit=5.0, health_file=health,
                health_every=0.2)
        wall = time.perf_counter() - t1
        svc.close()
        out = []
        off = 0
        while True:
            recs = broker.fetch(TOPIC_OUT, off, 4096)
            if not recs:
                break
            out.extend(r.value for r in recs)
            off = recs[-1].offset + 1
        return wall, out

    walls = {"off": [], "on": []}
    stored = {}
    with tempfile.TemporaryDirectory() as td:
        on_dir = os.path.join(td, "on")
        os.makedirs(on_dir)
        for rep in range(repeats):
            for mode, observe in (("off", False), ("on", True)):
                wall, out = run_once(on_dir if observe else td,
                                     observe)
                walls[mode].append(wall)
                if rep == 0:
                    stored[mode] = out
        # MatchOut byte parity: the observability plane must be
        # invisible to the matched stream
        assert stored["off"] == stored["on"], (
            "profiling altered the MatchOut record bytes")
        samples = sum(1 for _ in tsdbmod.read_samples(
            os.path.join(on_dir, "tsdb"), source="serve"))
        assert samples > 0, "TSDB recorded no heartbeat samples"
        summary = tsdbmod.window_summary(os.path.join(on_dir, "tsdb"),
                                         source="serve")
        art = read_transfer_artifact(os.path.join(on_dir, "xfer.json"))
    import jax

    backend = jax.default_backend()
    assert backend in art, (
        f"transfer/compute artifact lacks the {backend!r} plane: "
        f"{sorted(art)}")
    plane = art[backend]
    off_s, on_s = min(walls["off"]), min(walls["on"])
    overhead = max(0.0, 1.0 - off_s / on_s)
    if overhead > overhead_ceiling:
        raise AssertionError(
            f"always-on profiling overhead {overhead:.1%} > "
            f"{overhead_ceiling:.0%} ceiling (off {off_s:.3f}s, "
            f"on {on_s:.3f}s)")
    mps = n / on_s
    elapsed = time.perf_counter() - t0
    detail = {
        "suite": "prof", "events": events, "records": n,
        "seed": seed, "batch": batch, "repeats": repeats,
        "backend": backend, "elapsed_s": round(elapsed, 3),
        "off_s": round(off_s, 4), "on_s": round(on_s, 4),
        "orders_per_sec": round(mps, 1),
        "tsdb_samples": samples,
        "prof_overhead_frac": round(overhead, 4),
        "overhead_ceiling": overhead_ceiling,
        # host-plane attribution from the on-run's own history
        "prof_stage_fracs": {
            s: round(summary.get(f"prof_stage_frac_{s}", 0.0), 4)
            for s in ("parse", "plan", "dispatch", "collect",
                      "produce")},
        # device-plane advisories for the ROADMAP item-4 autotuner
        # (CPU CI records the real CPU ratio; a TPU run overwrites its
        # own backend key in place)
        "h2d_bytes_per_s": plane.get("h2d_bytes_per_s"),
        "transfer_compute_ratio": plane.get("transfer_compute_ratio"),
        "h2d_overlap_frac": plane.get("h2d_overlap_frac"),
    }
    print(f"kme-bench prof: off={off_s:.3f}s on={on_s:.3f}s "
          f"(overhead {overhead:.2%}, ceiling "
          f"{overhead_ceiling:.0%}) {mps:,.0f} orders/s, "
          f"{samples} history samples, artifact[{backend}] ok "
          f"({elapsed:.1f}s)", file=sys.stderr)
    return {
        "metric": "orders_per_sec",
        "value": round(mps, 1),
        "unit": "orders/sec",
        "vs_baseline": round(mps / REFERENCE_BASELINE_OPS, 3),
        "detail": detail,
    }


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="kme-bench")
    p.add_argument("--suite", choices=("lanes", "parity", "native",
                                       "latency", "pipeline",
                                       "shards", "groups", "storms",
                                       "wire", "feed", "multihost",
                                       "prof"),
                   default="lanes")
    p.add_argument("--subs", type=int, default=10_000,
                   help="feed suite: subscriber count (two of them "
                        "are wildcard auditors; the rest pin one "
                        "symbol each)")
    p.add_argument("--pipeline", type=int, default=2, metavar="N",
                   help="pipeline suite: in-flight batch window depth "
                        "(how many submits may run ahead of collect)")
    p.add_argument("--engine", choices=("seq", "sweep"), default="seq",
                   help="lanes-suite engine: the sequential mega-kernel "
                        "(default) or the vectorized sweep engine")
    p.add_argument("--events", type=int, default=None)
    p.add_argument("--symbols", type=int, default=1024)
    p.add_argument("--accounts", type=int, default=2048)
    p.add_argument("--zipf", type=float, default=1.2)
    p.add_argument("--shards", type=int, default=1)
    p.add_argument("--slots", type=int, default=None,
                   help="resting-order slots per book side (H2 envelope; "
                        "default: 8192 for the seq engine, 128 for sweep)")
    p.add_argument("--max-fills", type=int, default=16,
                   help="makers swept per taker (H3 envelope)")
    p.add_argument("--steps", type=int, default=64,
                   help="scan-length bucket granularity of dispatch windows")
    p.add_argument("--width", type=int, default=DEFAULT_WIDTH,
                   help="active-lane compaction: messages per scan step "
                        "(0 = full-width)")
    p.add_argument("--workload",
                   choices=("zipf", "cancel", "zipf-hot",
                            "payout-storm", "cross-account")
                   + STORM_WORKLOADS,
                   default="zipf",
                   help="stream profile: Zipf-skewed, bursty cancel/"
                        "replace (BASELINE.md rows), one-symbol hot "
                        "book (zipf-hot), mass-settlement bursts "
                        "(payout-storm), or one of the five named "
                        "adversarial storm profiles "
                        "(workload.STORM_PROFILES) — all "
                        "seed-deterministic")
    p.add_argument("--window", type=int, default=1024,
                   help="max scan steps per dispatch window")
    p.add_argument("--parity-prefix", type=int, default=20000,
                   help="sweep-suite only: post-preamble messages "
                        "checked against the quirk-exact replica (the "
                        "seq suite always checks the FULL stream)")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="dump a jax.profiler trace of the timed run to DIR")
    p.add_argument("--batch", type=int, default=DEFAULT_LATENCY_BATCH,
                   help="micro-batch size (latency suite batches; parity "
                        "suite scan length)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--cross-frac", type=float, default=0.5,
                   help="groups suite, cross-account workload: "
                        "fraction of orders forced onto non-home "
                        "accounts (1.0 = the 100%% cross-shard worst "
                        "case)")
    p.add_argument("--prefund", type=int, default=8,
                   help="groups suite: orders' worth of worst-case "
                        "margin granted per cross-shard transfer pair "
                        "(front.py chunked reserve->settle; 1 = exact "
                        "per-order grants)")
    p.add_argument("--dispatch", choices=("auto", "async", "lockstep"),
                   default="auto",
                   help="shards suite: mesh dispatch mode (auto "
                        "resolves to per-chip async on a single-host "
                        "mesh; lockstep is the legacy barrier scan)")
    # None -> per-suite default: the native/parity suites judge java
    # (their reason to exist); the lanes/seq headline is fixed-mode
    # unless java is explicitly requested
    p.add_argument("--compat", choices=("java", "fixed"), default=None)
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write a Chrome trace-event JSON (chrome://"
                        "tracing / Perfetto) of the session phase "
                        "timeline here at exit")
    p.add_argument("--journal-out", default=None, metavar="PATH",
                   help="seq suite: write the best run's order-"
                        "lifecycle journal here (post-hoc — the timed "
                        "runs are untouched) and report the cost as "
                        "journal_overhead_frac. Query with kme-trace")
    p.add_argument("--audit", action="store_true",
                   help="seq suite: run the invariant auditor over the "
                        "best run's stream and report audit_s / "
                        "audit_overhead_frac / audit_violations")
    p.add_argument("--baseline", default=None, metavar="BENCH.json",
                   help="recorded benchmark artifact to compare "
                        "against (a BENCH_r0N.json driver artifact, a "
                        "detail JSON, or raw bench output)")
    p.add_argument("--gate", action="store_true",
                   help="with --baseline: exit 1 when a gated metric "
                        "regressed beyond --tolerance (backend "
                        "mismatch demotes to advisory, exit 0)")
    p.add_argument("--tolerance", type=float, default=0.25,
                   metavar="FRAC",
                   help="allowed fractional degradation before the "
                        "gate fails (0.25 = 25%%)")
    p.add_argument("--gate-report", default=None, metavar="PATH",
                   help="write the gate comparison report JSON here "
                        "(CI uploads it as an artifact)")
    p.add_argument("--gate-current", default=None, metavar="PATH",
                   help="gate a PRE-RECORDED artifact against "
                        "--baseline instead of running a bench (e.g. "
                        "re-judge a CI artifact offline)")
    args = p.parse_args(argv)
    if (args.gate or args.gate_current) and args.baseline is None:
        p.error("--gate/--gate-current require --baseline")
    if args.gate_current is not None:
        from kme_tpu import perfgate

        current = perfgate.load_artifact(args.gate_current)
        if not current["metrics"]:
            print(f"kme-bench --gate: no metrics found in "
                  f"{args.gate_current!r}", file=sys.stderr)
            return 2
        return perfgate.run_gate(args.baseline, current,
                                 tolerance=args.tolerance,
                                 report_path=args.gate_report)
    tracer = None
    if args.trace_out is not None:
        from kme_tpu.telemetry import TraceRecorder, install

        tracer = TraceRecorder()
        install(tracer)   # session PhaseTimers pick it up process-wide
    if args.suite == "lanes" and args.engine == "seq":
        rec = bench_seq_engine(args.events or 100_000, args.symbols,
                               args.accounts, args.seed, args.zipf,
                               slots=args.slots or SEQ_DEFAULT_SLOTS,
                               max_fills=args.max_fills,
                               workload=args.workload,
                               compat=args.compat or "fixed",
                               journal_out=args.journal_out,
                               audit=args.audit)
    elif args.suite == "lanes":
        rec = bench_lane_engine(args.events or 100_000, args.symbols,
                                args.accounts, args.seed, args.zipf,
                                steps=args.steps, slots=args.slots or 128,
                                max_fills=args.max_fills, shards=args.shards,
                                parity_prefix=args.parity_prefix,
                                width=args.width, workload=args.workload,
                                window=args.window,
                                profile_dir=args.profile)
    elif args.suite == "native":
        rec = bench_native_engine(args.events or 100_000, args.seed,
                                  max(args.batch, 1),
                                  args.compat or "java")
    elif args.suite == "pipeline":
        rec = bench_pipeline(args.events or 40_960, args.symbols,
                             args.accounts, args.seed, args.zipf,
                             batch=args.batch, depth=args.pipeline)
    elif args.suite == "groups":
        rec = bench_groups(args.events or 20_000,
                           symbols=args.symbols,
                           accounts=min(args.accounts, 256),
                           seed=args.seed,
                           workload=args.workload,
                           cross_frac=args.cross_frac,
                           slots=args.slots or 128,
                           max_fills=args.max_fills,
                           prefund=args.prefund)
    elif args.suite == "shards":
        rec = bench_shards(args.events or 4000,
                           symbols=min(args.symbols, 8),
                           accounts=min(args.accounts, 128),
                           seed=args.seed,
                           workload=(args.workload
                                     if args.workload != "zipf"
                                     else "zipf-hot"),
                           slots=args.slots or 128,
                           max_fills=args.max_fills,
                           dispatch=args.dispatch)
    elif args.suite == "storms":
        rec = bench_storms(args.events or 4000, seed=args.seed)
    elif args.suite == "multihost":
        rec = bench_multihost(args.events or 6000,
                              symbols=min(args.symbols, 512),
                              accounts=min(args.accounts, 128),
                              seed=args.seed,
                              cross_frac=args.cross_frac,
                              slots=args.slots or 128,
                              max_fills=args.max_fills,
                              prefund=args.prefund)
    elif args.suite == "wire":
        rec = bench_wire(args.events or 20_000, seed=args.seed,
                         batch=max(args.batch, 1))
    elif args.suite == "prof":
        rec = bench_prof(args.events or 20_000, seed=args.seed,
                         batch=max(args.batch, 1))
    elif args.suite == "feed":
        rec = bench_feed(args.events or 20_000, seed=args.seed,
                         subs=args.subs, symbols=args.symbols,
                         profile=(args.workload
                                  if args.workload in STORM_WORKLOADS
                                  else "flash-crowd"))
    elif args.suite == "latency":
        rec = bench_latency(args.events or 20_000, args.symbols,
                            args.accounts, args.seed, args.zipf,
                            slots=args.slots or 128,
                            max_fills=args.max_fills,
                            width=args.width, shards=args.shards,
                            batch=args.batch, engine=args.engine)
    else:
        rec = bench_parity_engine(args.events or 4096, args.seed,
                                  args.batch, args.compat or "java")
    if tracer is not None:
        tracer.save(args.trace_out)
        print(f"kme-bench: trace written to {args.trace_out}",
              file=sys.stderr)
    out = {k: rec[k] for k in ("metric", "value", "unit", "vs_baseline")}
    print(json.dumps(out))
    print(json.dumps(rec["detail"]), file=sys.stderr)
    if args.gate:
        from kme_tpu import perfgate

        # the headline scalar participates too (it carries the suite's
        # one-number summary, e.g. orders_per_sec)
        doc = dict(rec["detail"])
        if rec.get("unit") == "orders/sec":
            doc.setdefault("orders_per_sec", rec["value"])
        return perfgate.run_gate(args.baseline,
                                 perfgate.detail_to_artifact(doc),
                                 tolerance=args.tolerance,
                                 report_path=args.gate_report)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
