"""Benchmark suite (BASELINE.md matrix).

The reference publishes no numbers (BASELINE.md); its structural bound is
single-digit-thousands of orders/sec (serial awaited produce per order,
commit per record, JSON serde, RocksDB round-trips — BASELINE.md table).
`REFERENCE_BASELINE_OPS` pins the top of that band (5k orders/sec) as the
denominator for `vs_baseline`, documented here so the ratio is honest and
reproducible.

The headline metric is matched orders/sec through the device engine on
the reference harness distribution (exchange_test.js), measured
steady-state (post-compile) on whatever backend is active — the real TPU
under the driver, host CPU elsewhere.
"""

from __future__ import annotations

import json
import sys
import time

REFERENCE_BASELINE_OPS = 5_000.0  # orders/sec, derived bound (BASELINE.md)


def bench_parity_engine(events: int = 4096, seed: int = 0, batch: int = 256,
                        compat: str = "java") -> dict:
    """Throughput of the serial device parity engine on the stock harness
    workload. Returns the bench record (one JSON-able dict)."""
    from kme_tpu.engine.parity import ParityCaps, ParityEngine
    from kme_tpu.workload import harness_stream

    caps = ParityCaps(balances=32, positions=8192, books=32, buckets=1024,
                      orders=16384, max_events=64, batch=batch)
    msgs = harness_stream(events, seed=seed)
    eng = ParityEngine(compat, caps)
    # warmup: compile + first dispatch
    eng.process_batch(msgs[:batch])
    t0 = time.perf_counter()
    eng.process_batch(msgs[batch:])
    dt = time.perf_counter() - t0
    n = len(msgs) - batch
    ops = n / dt
    import jax
    return {
        "metric": "orders_per_sec_serial_parity",
        "value": round(ops, 1),
        "unit": "orders/s",
        "vs_baseline": round(ops / REFERENCE_BASELINE_OPS, 3),
        "detail": {
            "events": n, "seconds": round(dt, 3), "batch": batch,
            "compat": compat, "backend": jax.devices()[0].platform,
            "baseline_assumption_ops": REFERENCE_BASELINE_OPS,
        },
    }


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="kme-bench")
    p.add_argument("--events", type=int, default=4096)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--compat", choices=("java", "fixed"), default="java")
    args = p.parse_args(argv)
    rec = bench_parity_engine(args.events, args.seed, args.batch, args.compat)
    out = {k: rec[k] for k in ("metric", "value", "unit", "vs_baseline")}
    print(json.dumps(out))
    print(json.dumps(rec["detail"]), file=sys.stderr)
    return 0
