"""kme-supervise: failure detection + automatic restart for kme-serve.

The reference gets this for free from Kafka Streams group membership —
a died instance's partitions are reassigned and state is restored from
changelogs (KProcessor.java:59-60, library behavior). Here the same
role is played by a supervisor process: it launches `kme-serve` as a
child with a heartbeat file (--health-file) and a checkpoint directory,
and restarts the child from its newest checkpoint whenever

- the child process exits with a non-zero status, or
- the heartbeat goes STALE (mtime older than --stale-after seconds:
  the process froze or died), or
- the serve LOOP TICK in the heartbeat stops advancing for
  --stall-after seconds (the loop iterates even when idle, so a frozen
  tick means a hang inside step() — e.g. a stuck device call — even
  while the heartbeat thread keeps the mtime fresh).

Durability is the existing checkpoint/resume contract: broker topic
logs persist under the checkpoint dir, the child resumes from the
newest fsync'd snapshot, and at-least-once replay of the input tail
reproduces the byte-exact output stream
(tests/test_supervise.py kills the child mid-stream and requires the
completed MatchOut stream to equal the oracle's).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time


def _alive(proc: subprocess.Popen) -> bool:
    return proc.poll() is None


def _hb_age(path: str) -> float:
    try:
        return time.time() - os.stat(path).st_mtime
    except OSError:
        return float("inf")


def _hb_tick(path: str):
    try:
        with open(path) as f:
            return json.load(f).get("tick")
    except (OSError, ValueError):
        return None


def supervise(serve_args, checkpoint_dir: str, stale_after: float = 10.0,
              max_restarts: int = 5, grace: float = 5.0,
              poll: float = 0.5, echo: bool = True,
              stall_after: float = 300.0) -> int:
    """Run kme-serve under supervision; returns the child's final rc.

    serve_args: argv tail passed to `kme-serve` verbatim (the supervisor
    adds --checkpoint-dir and --health-file itself; a user-supplied
    occurrence of either inside serve_args would silently WIN under
    argparse's last-occurrence rule, leaving the supervisor watching a
    heartbeat file the child never writes — so both are rejected)."""
    reserved = ("--checkpoint-dir", "--health-file")
    for a in serve_args:
        flag = a.split("=", 1)[0]
        # argparse abbreviation: any prefix of a reserved flag resolves
        # to it in the child (allow_abbrev default), so prefixes are
        # rejected too
        if (flag.startswith("--") and len(flag) > 2
                and any(r.startswith(flag) for r in reserved)):
            raise ValueError(
                f"{flag} is managed by the supervisor and cannot appear "
                f"in serve_args (the child must write the heartbeat/"
                f"checkpoints the supervisor watches)")
    hb = os.path.join(checkpoint_dir, "serve.health")
    base = [sys.executable, "-m", "kme_tpu.cli", "serve",
            "--checkpoint-dir", checkpoint_dir,
            "--health-file", hb] + list(serve_args)
    restarts = 0
    while True:
        if os.path.exists(hb):
            os.unlink(hb)
        if echo:
            print(f"kme-supervise: starting kme-serve "
                  f"(restart {restarts}/{max_restarts})", file=sys.stderr)
        child = subprocess.Popen(base)
        start = time.time()
        failed = None
        # stall detection ARMS only once the loop has ticked at least
        # once: a first batch can legitimately sit in an XLA/Pallas
        # compile for minutes before the first step() returns, and
        # killing it mid-compile would loop forever
        last_tick, tick_since, armed = None, time.time(), False
        while True:
            time.sleep(poll)
            if not _alive(child):
                rc = child.returncode
                if rc == 0:
                    if echo:
                        print("kme-supervise: child exited cleanly",
                              file=sys.stderr)
                    return 0
                failed = f"child exited rc={rc}"
                break
            age = _hb_age(hb)
            # allow a startup grace window before the first heartbeat
            if age == float("inf") and time.time() - start < grace:
                continue
            if age > stale_after:
                failed = f"heartbeat stale ({age:.1f}s > {stale_after}s)"
                break
            tick = _hb_tick(hb)
            if tick != last_tick:
                if last_tick is not None:
                    armed = True
                last_tick, tick_since = tick, time.time()
            elif armed and time.time() - tick_since > stall_after:
                failed = (f"serve loop stalled (tick {tick} frozen "
                          f"{time.time() - tick_since:.0f}s)")
                break
        if echo:
            print(f"kme-supervise: FAILURE DETECTED: {failed}",
                  file=sys.stderr)
        if _alive(child):
            child.send_signal(signal.SIGKILL)
            child.wait()
        restarts += 1
        if restarts > max_restarts:
            print("kme-supervise: restart budget exhausted", file=sys.stderr)
            return 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kme-supervise", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--checkpoint-dir", required=True,
                   help="checkpoint + broker-log + heartbeat directory "
                        "(the restart state root)")
    p.add_argument("--stale-after", type=float, default=10.0,
                   help="heartbeat age that counts as a frozen process")
    p.add_argument("--stall-after", type=float, default=300.0,
                   help="seconds without a loop-tick advance that count "
                        "as a hang inside step()")
    p.add_argument("--max-restarts", type=int, default=5)
    p.add_argument("--grace", type=float, default=5.0,
                   help="startup seconds before the first heartbeat is due")
    p.add_argument("serve_args", nargs=argparse.REMAINDER,
                   help="arguments after '--' go to kme-serve verbatim")
    args = p.parse_args(argv)
    serve_args = args.serve_args
    if serve_args and serve_args[0] == "--":
        serve_args = serve_args[1:]
    os.makedirs(args.checkpoint_dir, exist_ok=True)
    try:
        return supervise(serve_args, args.checkpoint_dir,
                         stale_after=args.stale_after,
                         max_restarts=args.max_restarts, grace=args.grace,
                         stall_after=args.stall_after)
    except ValueError as e:
        print(f"kme-supervise: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
