"""kme-supervise: failure detection + automatic restart for kme-serve.

The reference gets this for free from Kafka Streams group membership —
a died instance's partitions are reassigned and state is restored from
changelogs (KProcessor.java:59-60, library behavior). Here the same
role is played by a supervisor process: it launches `kme-serve` as a
child with a heartbeat file (--health-file) and a checkpoint directory,
and restarts the child from its newest checkpoint whenever

- the child process exits with a non-zero status, or
- the heartbeat goes STALE (mtime older than --stale-after seconds:
  the process froze or died), or
- the serve LOOP TICK in the heartbeat stops advancing for
  --stall-after seconds (the loop iterates even when idle, so a frozen
  tick means a hang inside step() — e.g. a stuck device call — even
  while the heartbeat thread keeps the mtime fresh).

Restart policy (the production-shaped part):

- restarts are paced by JITTERED EXPONENTIAL BACKOFF keyed on the
  failure FINGERPRINT (exit:<rc> / stale / stall): a crash LOOP — the
  same fingerprint repeating — doubles the delay each round up to
  --backoff-cap, while a novel failure resets to --backoff-base, so a
  one-off blip restarts fast and a deterministic crash does not spin.
- the restart BUDGET (--max-restarts) counts failures but DECAYS: each
  --healthy-decay seconds of continuous healthy child uptime refunds
  one unit. A service that crashes once a day never exhausts a budget
  of 5; only a crash loop does. (`restarts_total` stays lifetime for
  reporting — only the budget decays.)
- each incarnation is stamped via environment: KME_RESTART_ORDINAL
  (lifetime restart count) and KME_FAILED_AT (wall time the failure
  was detected), which the child surfaces as the restarts_total and
  recovery_seconds telemetry gauges.
- supervisor state (restarts, budget, fingerprints, per-recovery
  timings) is mirrored to <checkpoint-dir>/supervisor.json after every
  transition — the kme-chaos report reads it post-mortem.

Hot-standby failover (--standby): the supervisor also keeps a
`kme-standby` replica (bridge/replica.py) running against the same
checkpoint dir. The replica restores the newest snapshot and tails the
durable MatchIn log, staying within one batch of the leader. When the
leader FAILS and the standby looks ready (alive + writing its
heartbeat), the supervisor skips the cold restart entirely: it writes
<checkpoint-dir>/promote.json and ADOPTS the standby process as the
serving child — the replica acquires the next leader epoch, fences the
old one at the broker, binds the leader's endpoint and keeps serving
(no backoff, no snapshot reload, no input replay from disk). The
recovery entry is marked promoted:true with its failover_seconds; a
replacement standby is then launched behind the new leader. Failures
with no ready standby fall back to the ordinary restart path.

Durability is the existing checkpoint/resume contract: broker topic
logs persist under the checkpoint dir, the child resumes from the
newest fsync'd snapshot, and at-least-once replay of the input tail
reproduces the byte-exact output stream
(tests/test_supervise.py kills the child mid-stream and requires the
completed MatchOut stream to equal the oracle's).

The Supervisor class takes injectable clock / sleep / popen / mtime
hooks so the detection and policy logic is unit-testable in
milliseconds (tests/test_supervise_unit.py) — the defaults are the
real OS facilities.
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import os
import random
import signal
import subprocess
import sys
import time
from typing import Optional

STATE_FILE = "supervisor.json"


class Supervisor:
    def __init__(self, serve_args, checkpoint_dir: str,
                 stale_after: float = 10.0, max_restarts: int = 5,
                 grace: float = 5.0, poll: float = 0.5, echo: bool = True,
                 stall_after: float = 300.0,
                 backoff_base: float = 0.25, backoff_cap: float = 10.0,
                 healthy_decay: float = 60.0, standby: bool = False,
                 tag: str = "",
                 popen=None, clock=None, sleep=None, mtime=None,
                 rng=None) -> None:
        """serve_args: argv tail passed to `kme-serve` verbatim (the
        supervisor adds --checkpoint-dir and --health-file itself; a
        user-supplied occurrence of either inside serve_args would
        silently WIN under argparse's last-occurrence rule, leaving the
        supervisor watching a heartbeat file the child never writes —
        so both are rejected)."""
        reserved = ("--checkpoint-dir", "--health-file")
        for a in serve_args:
            flag = a.split("=", 1)[0]
            # argparse abbreviation: any prefix of a reserved flag
            # resolves to it in the child (allow_abbrev default), so
            # prefixes are rejected too
            if (flag.startswith("--") and len(flag) > 2
                    and any(r.startswith(flag) for r in reserved)):
                raise ValueError(
                    f"{flag} is managed by the supervisor and cannot "
                    f"appear in serve_args (the child must write the "
                    f"heartbeat/checkpoints the supervisor watches)")
        self.checkpoint_dir = checkpoint_dir
        self.tag = tag          # log prefix, e.g. "[g0]" in groups mode
        self.stale_after = stale_after
        self.max_restarts = max_restarts
        self.grace = grace
        self.poll = poll
        self.echo = echo
        self.stall_after = stall_after
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.healthy_decay = healthy_decay
        # injectable OS facilities (unit tests script these)
        self._popen = popen or (
            lambda cmd, env: subprocess.Popen(cmd, env=env))
        self._clock = clock or time.time
        self._sleep = sleep or time.sleep
        self._mtime = mtime or (lambda p: os.stat(p).st_mtime)
        self._rng = rng or random.Random()
        self.hb = os.path.join(checkpoint_dir, "serve.health")
        self.base_cmd = [sys.executable, "-m", "kme_tpu.cli", "serve",
                         "--checkpoint-dir", checkpoint_dir,
                         "--health-file", self.hb] + list(serve_args)
        # hot-standby failover (module docstring): the standby child is
        # a kme-standby replica over the SAME serve_args — it parses the
        # engine-shape flags and loudly ignores serve-only ones
        self.standby = standby
        self.promote_file = os.path.join(checkpoint_dir, "promote.json")
        self.standby_hb = os.path.join(checkpoint_dir, "standby.health")
        self.standby_cmd = [sys.executable, "-m", "kme_tpu.cli",
                            "standby",
                            "--checkpoint-dir", checkpoint_dir,
                            "--health-file", self.standby_hb,
                            ] + list(serve_args)
        self._standby_proc = None
        self._adopted_pid = None     # pid the live promote file targets
        self.standby_restarts = 0
        # policy state
        self.restarts_total = 0      # lifetime, for reporting
        self.budget_used = 0         # decays over healthy uptime
        self.fingerprints: dict = {}
        self.recoveries: list = []
        self._last_fingerprint: Optional[str] = None
        self._streak = 0
        # control-plane flight recorder (telemetry/events.py): every
        # supervision decision — spawn, crash fingerprint, backoff,
        # restart, promotion/adoption — lands in the durable timeline.
        # The group ordinal is recovered from the "[gK]" tag so merged
        # timelines anchor per group; ts comes from the injected clock
        # (fake-clock unit tests get deterministic stamps for free).
        from kme_tpu.telemetry import events as cpevents

        self._group = -1
        if tag.startswith("[g") and tag.endswith("]"):
            with contextlib.suppress(ValueError):
                self._group = int(tag[2:-1])
        src = ("supervisor" if self._group < 0
               else f"supervisor.g{self._group}")
        self.events = cpevents.open_log(checkpoint_dir, src,
                                        clock=self._clock)

    def _event(self, kind: str, severity: str = "info",
               **detail) -> None:
        """Append one timeline event; the recorder must never be able
        to kill supervision."""
        try:
            self.events.emit(kind, severity=severity,
                             group=self._group, **detail)
        except Exception:
            pass

    # -- small injectable-friendly primitives --------------------------

    def _say(self, msg: str) -> None:
        if self.echo:
            print(f"kme-supervise{self.tag}: {msg}", file=sys.stderr)

    def _hb_age(self) -> float:
        try:
            return self._clock() - self._mtime(self.hb)
        except OSError:
            return float("inf")

    def _hb_tick(self):
        try:
            with open(self.hb) as f:
                return json.load(f).get("tick")
        except (OSError, ValueError):
            return None

    def _hb_closing(self) -> bool:
        """True when the child's FINAL heartbeat says the serve loop
        ended ON PURPOSE (idle-exit / max-messages): its tick is frozen
        by definition, so the stall detector stands down and lets the
        exit (or, if teardown truly hangs, the stale branch) decide."""
        try:
            with open(self.hb) as f:
                return bool(json.load(f).get("closing"))
        except (OSError, ValueError):
            return False

    def _write_state(self) -> None:
        """Mirror policy state to <checkpoint-dir>/supervisor.json
        (atomic replace) — the chaos report reads it post-mortem."""
        path = os.path.join(self.checkpoint_dir, STATE_FILE)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"restarts_total": self.restarts_total,
                           "budget_used": self.budget_used,
                           "max_restarts": self.max_restarts,
                           "fingerprints": self.fingerprints,
                           "recoveries": self.recoveries,
                           "standby": self.standby,
                           "standby_restarts": self.standby_restarts},
                          f, indent=1)
            os.replace(tmp, path)
        except OSError:
            pass    # reporting surface only; never kill supervision

    # -- hot-standby management (module docstring) ---------------------

    def _ensure_standby(self, env) -> None:
        """(Re)launch the kme-standby replica if it is not running. A
        stale promote file or heartbeat from a previous incarnation is
        removed FIRST — a fresh standby reading yesterday's promote.json
        would instantly (and wrongly) promote itself."""
        if not self.standby:
            return
        if self._standby_proc is not None \
                and self._standby_proc.poll() is None:
            return
        if self._standby_proc is not None:
            self.standby_restarts += 1
            self._say(f"standby died rc="
                      f"{self._standby_proc.returncode}; relaunching")
        # drop a STALE promote file (from a previous run) — but never
        # one addressed to the child we just adopted: it has not
        # necessarily read its promotion order yet, and deleting it
        # here would strand the adoptee following forever
        with contextlib.suppress(OSError, ValueError):
            with open(self.promote_file) as f:
                pid = json.load(f).get("pid")
            if pid is None or pid != self._adopted_pid:
                os.unlink(self.promote_file)
        with contextlib.suppress(OSError):
            os.unlink(self.standby_hb)
        self._say("starting kme-standby replica")
        self._standby_proc = self._popen(self.standby_cmd, env)
        self._event("supervisor.standby_spawn",
                    restarts=self.standby_restarts)

    def _standby_ready(self) -> bool:
        """Promotable = the replica process is alive AND has written a
        heartbeat (it restored a snapshot and entered the follow loop)."""
        return (self._standby_proc is not None
                and self._standby_proc.poll() is None
                and os.path.exists(self.standby_hb))

    def _stop_standby(self) -> None:
        proc, self._standby_proc = self._standby_proc, None
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=5.0)
        except Exception:
            proc.kill()
            proc.wait()

    def _write_promote(self, failed_at: float, pid: int) -> None:
        """The promotion trigger: atomic so the replica never reads a
        torn JSON mid-write, and ADDRESSED to the adoptee's pid so no
        other (older, replacement) standby ever acts on it."""
        tmp = self.promote_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"failed_at": failed_at, "pid": pid,
                       "fingerprint": self._last_fingerprint}, f)
        os.replace(tmp, self.promote_file)
        self._adopted_pid = pid

    def _backoff(self) -> float:
        """Jittered exponential delay keyed on the fingerprint streak:
        1st occurrence waits ~base, each repeat doubles up to cap, and
        the 0.5–1.5x jitter decorrelates a fleet restarting off the
        same shared-dependency failure."""
        delay = min(self.backoff_cap,
                    self.backoff_base * (2 ** max(0, self._streak - 1)))
        return delay * (0.5 + self._rng.random())

    def _note_failure(self, fingerprint: str) -> None:
        self.restarts_total += 1
        self.budget_used += 1
        self.fingerprints[fingerprint] = \
            self.fingerprints.get(fingerprint, 0) + 1
        if fingerprint == self._last_fingerprint:
            self._streak += 1
        else:
            self._last_fingerprint, self._streak = fingerprint, 1
        self._write_state()

    # -- the supervision loop ------------------------------------------

    def run(self) -> int:
        """Run kme-serve under supervision; returns the child's final
        rc (0 = clean exit, 1 = restart budget exhausted)."""
        failed_at: Optional[float] = None    # wall time of last failure
        adopt = None          # a promoted standby becoming the child
        was_promoted = False
        while True:
            with contextlib.suppress(OSError):
                os.unlink(self.hb)
            env = dict(os.environ)
            env["KME_RESTART_ORDINAL"] = str(self.restarts_total)
            if failed_at is not None:
                env["KME_FAILED_AT"] = repr(failed_at)
            else:
                env.pop("KME_FAILED_AT", None)
            if adopt is not None:
                # hot failover: the standby replica is promoting itself
                # right now — adopt it as the serving child, skip the
                # cold start AND the backoff (there is no crash loop to
                # pace: the failed incarnation is a different process)
                child, adopt = adopt, None
                self._say("failing over to the hot standby "
                          "(promote.json written)")
                self._event("supervisor.adopt", pid=child.pid,
                            fingerprint=self._last_fingerprint)
            else:
                was_promoted = False
                self._say(f"starting kme-serve (restart "
                          f"{self.budget_used}/{self.max_restarts})")
                child = self._popen(self.base_cmd, env)
                self._event(
                    "supervisor.restart" if self.restarts_total
                    else "supervisor.spawn",
                    ordinal=self.restarts_total, pid=child.pid)
            self._ensure_standby(env)
            start = self._clock()
            failed = fingerprint = None
            recovering = failed_at    # measure to the first heartbeat
            # stall detection ARMS only once the loop has ticked at
            # least once: a first batch can legitimately sit in an
            # XLA/Pallas compile for minutes before the first step()
            # returns, and killing it mid-compile would loop forever
            last_tick, tick_since, armed = None, self._clock(), False
            last_decay = self._clock()
            while True:
                self._sleep(self.poll)
                now = self._clock()
                # healthy-uptime budget decay: each healthy_decay
                # seconds of continuous uptime refunds one budget unit
                # (a crash LOOP never stays up long enough to refund)
                if (self.budget_used > 0
                        and now - last_decay >= self.healthy_decay):
                    last_decay = now
                    self.budget_used -= 1
                    self._say(f"healthy for {self.healthy_decay:.0f}s; "
                              f"restart budget refunded "
                              f"({self.budget_used}/{self.max_restarts} "
                              f"used)")
                    self._write_state()
                self._ensure_standby(env)    # relaunch a dead replica
                if child.poll() is not None:
                    rc = child.returncode
                    if rc == 0:
                        self._say("child exited cleanly")
                        self._event("supervisor.exit", rc=0)
                        self._stop_standby()
                        self._write_state()
                        return 0
                    failed = f"child exited rc={rc}"
                    fingerprint = f"exit:{rc}"
                    break
                age = self._hb_age()
                if age == float("inf"):
                    # allow a startup grace window before the first
                    # heartbeat is due
                    if now - start < self.grace:
                        continue
                    failed = (f"no heartbeat within grace "
                              f"({self.grace}s)")
                    fingerprint = "stale"
                    break
                if recovering is not None:
                    # first heartbeat of a restarted incarnation: the
                    # service is serving again — close the recovery
                    # window opened at failure detection
                    took = now - recovering
                    entry = {"fingerprint": self._last_fingerprint,
                             "detected_at": recovering,
                             "recovered_in": round(took, 3)}
                    if was_promoted:
                        # failure detected -> promoted standby serving:
                        # the bounded-failover number the chaos harness
                        # asserts on
                        entry["promoted"] = True
                        entry["failover_seconds"] = round(took, 3)
                    self.recoveries.append(entry)
                    self._say(f"recovered in {took:.2f}s"
                              + (" (hot failover)" if was_promoted
                                 else ""))
                    self._event("supervisor.recover",
                                recovered_in=entry["recovered_in"],
                                fingerprint=self._last_fingerprint,
                                promoted=was_promoted,
                                **({"failover_seconds":
                                    entry["failover_seconds"]}
                                   if was_promoted else {}))
                    recovering = None
                    self._write_state()
                if age > self.stale_after:
                    failed = (f"heartbeat stale ({age:.1f}s > "
                              f"{self.stale_after}s)")
                    fingerprint = "stale"
                    break
                tick = self._hb_tick()
                if tick != last_tick:
                    if last_tick is not None:
                        armed = True
                    last_tick, tick_since = tick, now
                elif self._hb_closing():
                    # deliberate shutdown in progress — a frozen tick is
                    # expected; keep the stall timer from accruing so a
                    # slow final checkpoint is not read as a hang
                    tick_since = now
                elif armed and now - tick_since > self.stall_after:
                    failed = (f"serve loop stalled (tick {tick} frozen "
                              f"{now - tick_since:.0f}s)")
                    fingerprint = "stall"
                    break
            failed_at = self._clock()
            self._say(f"FAILURE DETECTED: {failed}")
            self._event("supervisor.crash", severity="error",
                        fingerprint=fingerprint, reason=failed)
            if child.poll() is None:
                child.send_signal(signal.SIGKILL)
                child.wait()
            self._note_failure(fingerprint)
            if self.budget_used > self.max_restarts:
                self._say("restart budget exhausted")
                self._event("supervisor.giveup", severity="error",
                            restarts=self.restarts_total)
                self._stop_standby()
                return 1
            if self._standby_ready():
                # hot failover: hand the stream to the replica instead
                # of cold-restarting. The promote file carries the
                # detection time so the replica can report
                # failover_seconds from ITS side too.
                with contextlib.suppress(OSError):
                    os.unlink(self.standby_hb)
                adopt, self._standby_proc = self._standby_proc, None
                self._write_promote(failed_at, adopt.pid)
                was_promoted = True
                self._event("supervisor.promote", pid=adopt.pid,
                            failed_at=failed_at,
                            fingerprint=self._last_fingerprint)
                continue    # no backoff: not the same process crashing
            delay = self._backoff()
            if delay > 0:
                self._say(f"backing off {delay:.2f}s "
                          f"(failure streak {self._streak} "
                          f"x {self._last_fingerprint})")
                self._event("supervisor.backoff", severity="warn",
                            seconds=round(delay, 3),
                            streak=self._streak,
                            fingerprint=self._last_fingerprint)
                self._sleep(delay)


def supervise(serve_args, checkpoint_dir: str, stale_after: float = 10.0,
              max_restarts: int = 5, grace: float = 5.0,
              poll: float = 0.5, echo: bool = True,
              stall_after: float = 300.0, **kw) -> int:
    """Functional wrapper over Supervisor (the original API)."""
    return Supervisor(serve_args, checkpoint_dir, stale_after=stale_after,
                      max_restarts=max_restarts, grace=grace, poll=poll,
                      echo=echo, stall_after=stall_after, **kw).run()


def _autoscale_monitor(state_root: str, groups: int, stop, cfg,
                       poll: float, echo: bool) -> None:
    """`--groups auto` policy loop: each tick reads every group's
    heartbeat, feeds the pure AutoscaleController the `group{k}_lag`
    and `overload_state` gauges, appends the raw sample to
    autoscale.trace.jsonl (the replay input for simulate_autoscale)
    and any proposal to autoscale.json. The supervisor PROPOSES only:
    executing a proposal is a drain + kme-reshard + restart under the
    new topology — an operator/drill decision, never a background one
    (the running serves' topology is immutable by construction)."""
    from kme_tpu.bridge.autoscale import AutoscaleController, tick_event
    from kme_tpu.telemetry import events as cpevents

    ctl = AutoscaleController(cfg)
    dec_path = os.path.join(state_root, "autoscale.json")
    trace_path = os.path.join(state_root, "autoscale.trace.jsonl")
    evlog = cpevents.open_log(state_root, "autoscale")

    def write_decisions() -> None:
        tmp = dec_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"groups": groups, "ticks": ctl.ticks,
                       "config": dataclasses.asdict(ctl.cfg),
                       "decisions": ctl.decisions}, f, indent=1)
        os.replace(tmp, dec_path)

    with open(trace_path, "a", encoding="utf-8") as trace:
        while not stop.wait(poll):
            lags, states = [], []
            for k in range(groups):
                lag = state = 0
                try:
                    with open(os.path.join(state_root, f"group{k}",
                                           "serve.health")) as f:
                        g = json.load(f).get("metrics", {}).get(
                            "gauges", {})
                    lag = float(g.get(f"group{k}_lag", 0) or 0)
                    state = int(g.get("overload_state", 0) or 0)
                except (OSError, ValueError, TypeError):
                    pass    # no heartbeat yet: count as idle
                lags.append(lag)
                states.append(state)
            sample = {"groups": groups, "lags": lags,
                      "overload": states}
            trace.write(json.dumps(sample) + "\n")
            trace.flush()
            d = ctl.observe(groups, lags, states)
            try:
                evlog.emit("autoscale.propose" if d is not None
                           else "autoscale.observe",
                           severity="warn" if d is not None else "info",
                           **tick_event(ctl, groups, lags, states, d))
            except Exception:
                pass    # the recorder never kills the policy loop
            if d is not None:
                write_decisions()
                if echo:
                    print(f"[autoscale] propose {d['action']} "
                          f"{d['from']}→{d['to']} (max_lag "
                          f"{d['max_lag']:.0f}, imbalance "
                          f"{d['imbalance']})", file=sys.stderr)
    write_decisions()
    evlog.close()


def supervise_groups(serve_args, state_root: str, groups: int,
                     port_base: int = 9092, host: str = "127.0.0.1",
                     echo: bool = True, autoscale_cfg=None,
                     autoscale_poll: float = 1.0, **kw) -> int:
    """Multi-leader scale-out (ISSUE 9): run `groups` independent
    leader/standby pairs under ONE supervisor process. Group k gets its
    own checkpoint root <state_root>/group{k} (lease, snapshots, broker
    log, journal all disjoint), its own broker endpoint at
    port_base + k, and `--group k/N` on its serve/standby children so
    every durable broker topic is namespaced. Each pair has its OWN
    Supervisor instance — backoff fingerprints, restart budgets and
    promotion decisions never couple across groups, which is exactly
    the failure-isolation property the shard-failover drill asserts.
    Returns the max exit code across groups (0 = all healthy exits)."""
    import threading

    if groups < 1:
        raise ValueError(f"--groups wants >= 1, got {groups}")
    for a in serve_args:
        flag = a.split("=", 1)[0]
        if (flag.startswith("--") and len(flag) > 2
                and any(r.startswith(flag)
                        for r in ("--listen", "--group"))):
            raise ValueError(
                f"{flag} is managed per group by the supervisor in "
                f"--groups mode and cannot appear in serve_args")
    sups = []
    for k in range(groups):
        gdir = os.path.join(state_root, f"group{k}")
        os.makedirs(gdir, exist_ok=True)
        gargs = list(serve_args) + [
            "--group", f"{k}/{groups}",
            "--listen", f"{host}:{port_base + k}"]
        sups.append(Supervisor(gargs, gdir, echo=echo,
                               tag=f"[g{k}]", **kw))
    monitor = stop_monitor = None
    if autoscale_cfg is not None:
        stop_monitor = threading.Event()
        monitor = threading.Thread(
            target=_autoscale_monitor,
            args=(state_root, groups, stop_monitor, autoscale_cfg,
                  autoscale_poll, echo),
            daemon=True)
        monitor.start()
    if groups == 1 and monitor is None:
        return sups[0].run()
    rcs = [0] * groups
    threads = []
    for k, sup in enumerate(sups):
        def _run(k=k, sup=sup):
            try:
                rcs[k] = sup.run()
            except ValueError as e:
                print(f"kme-supervise[g{k}]: {e}", file=sys.stderr)
                rcs[k] = 2
        th = threading.Thread(target=_run, name=f"supervise-g{k}",
                              daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    if monitor is not None:
        stop_monitor.set()
        monitor.join(timeout=10.0)
    return max(rcs)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kme-supervise", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--checkpoint-dir", required=True,
                   help="checkpoint + broker-log + heartbeat directory "
                        "(the restart state root)")
    p.add_argument("--stale-after", type=float, default=10.0,
                   help="heartbeat age that counts as a frozen process")
    p.add_argument("--stall-after", type=float, default=300.0,
                   help="seconds without a loop-tick advance that count "
                        "as a hang inside step()")
    p.add_argument("--max-restarts", type=int, default=5,
                   help="restart budget; refunded by healthy uptime "
                        "(--healthy-decay), so only a crash LOOP "
                        "exhausts it")
    p.add_argument("--grace", type=float, default=5.0,
                   help="startup seconds before the first heartbeat is due")
    p.add_argument("--backoff-base", type=float, default=0.25,
                   help="restart delay for a first/novel failure; "
                        "repeats of the same failure fingerprint double "
                        "it up to --backoff-cap (with 0.5-1.5x jitter)")
    p.add_argument("--backoff-cap", type=float, default=10.0)
    p.add_argument("--healthy-decay", type=float, default=60.0,
                   help="seconds of continuous healthy uptime that "
                        "refund one restart-budget unit")
    p.add_argument("--standby", action="store_true",
                   help="keep a kme-standby hot replica tailing the "
                        "durable input; on failure, promote it (write "
                        "promote.json, adopt the process) instead of "
                        "cold-restarting — bounded failover with "
                        "exactly-once output preserved")
    p.add_argument("--poll", type=float, default=0.5,
                   help="watch-loop poll interval (failure detection "
                        "latency bound)")
    p.add_argument("--groups", default="1", metavar="N|auto",
                   help="multi-leader scale-out: run N independent "
                        "leader(/standby) pairs, group k rooted at "
                        "<checkpoint-dir>/group{k} with --group k/N "
                        "and its own broker port (--port-base + k); "
                        "backoff fingerprints and restart budgets "
                        "never couple across groups. 'auto' starts "
                        "--groups-initial groups and runs the "
                        "deterministic autoscale policy over the group "
                        "heartbeats, appending split/merge proposals "
                        "to <checkpoint-dir>/autoscale.json (executed "
                        "via kme-reshard, never in the background)")
    p.add_argument("--groups-initial", type=int, default=2, metavar="N",
                   help="group count '--groups auto' starts with")
    p.add_argument("--autoscale-high-lag", type=float, default=48.0,
                   help="per-group input lag that votes split "
                        "(pairs with kme-serve --overload-high-lag)")
    p.add_argument("--autoscale-low-lag", type=float, default=4.0,
                   help="cluster-wide lag ceiling that votes merge")
    p.add_argument("--autoscale-dwell", type=int, default=3,
                   help="consecutive hot/cold policy ticks before a "
                        "proposal (hysteresis)")
    p.add_argument("--autoscale-cooldown", type=int, default=8,
                   help="quiet policy ticks after any proposal")
    p.add_argument("--port-base", type=int, default=9092,
                   help="first group's broker port in --groups mode "
                        "(group k listens on --port-base + k)")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address for the per-group broker "
                        "endpoints in --groups mode")
    p.add_argument("serve_args", nargs=argparse.REMAINDER,
                   help="arguments after '--' go to kme-serve verbatim")
    args = p.parse_args(argv)
    serve_args = args.serve_args
    if serve_args and serve_args[0] == "--":
        serve_args = serve_args[1:]
    os.makedirs(args.checkpoint_dir, exist_ok=True)
    policy = dict(stale_after=args.stale_after,
                  max_restarts=args.max_restarts, grace=args.grace,
                  poll=args.poll,
                  stall_after=args.stall_after,
                  backoff_base=args.backoff_base,
                  backoff_cap=args.backoff_cap,
                  healthy_decay=args.healthy_decay,
                  standby=args.standby)
    autoscale_cfg = None
    if args.groups == "auto":
        from kme_tpu.bridge.autoscale import AutoscaleConfig

        groups = args.groups_initial
        autoscale_cfg = AutoscaleConfig(
            high_lag=args.autoscale_high_lag,
            low_lag=args.autoscale_low_lag,
            dwell=args.autoscale_dwell,
            cooldown=args.autoscale_cooldown)
    else:
        try:
            groups = int(args.groups)
        except ValueError:
            p.error(f"--groups wants an integer or 'auto', "
                    f"got {args.groups!r}")
    try:
        if groups > 1 or autoscale_cfg is not None:
            return supervise_groups(serve_args, args.checkpoint_dir,
                                    groups,
                                    port_base=args.port_base,
                                    host=args.host,
                                    autoscale_cfg=autoscale_cfg,
                                    **policy)
        return supervise(serve_args, args.checkpoint_dir, **policy)
    except ValueError as e:
        print(f"kme-supervise: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
