"""kme-front: the multi-leader front door (ROADMAP item 2).

PAPER.md §7's scale-out shape: symbols are independent books with
exactly one cross-symbol coupling (account balances), so the symbol
space partitions across N leader/standby groups. This module is the
seam between "one stream" and "N groups":

- **Assignment** — rendezvous (highest-random-weight) hashing over a
  shared splitmix64 mixer maps `abs(sid) -> group` (orders, symbol
  lifecycle) and `aid -> home group` (balance custody). The C++
  columnar twin is `kme_group_assign` (native/kme_router.cpp); the two
  are bit-identical and pinned by tests/test_front.py.
- **Split** — `GroupRouter.route_line` turns one MatchIn line into
  per-group substream lines. The original line lands on exactly ONE
  group; everything else it emits is *internal plumbing* marked with
  `prev == XFER_MARK` (a value no organic stream carries — oids from
  the reference harness are < 2^53):
    * CREATE_BALANCE broadcasts marked copies to the non-home groups
      (every group must know the account exists),
    * a BUY/SELL whose account home differs from its symbol group gets
      a reserve→settle TRANSFER pair injected ahead of it: a debit leg
      (-grant) into the home group and a credit leg (+grant) into the
      symbol group, `grant = min(worst_case_margin, shadow_home_cash)`.
  Injected lines are ordinary durable MatchIn records in each group's
  topic, so a crash-replay regenerates the identical transfers with
  the identical `(epoch, out_seq)` stamps — the broker's idempotent
  dedup layer (PR 4) is the cross-shard dedup key, exactly as KIP-98
  uses it.
- **Merge** — per-group MatchOut streams concatenate in group-id order
  (≡ a stable sort on `(group, out_seq)`), with internal-marked lines
  filtered. This is THE documented global-order convention (COMPAT.md
  "Multi-leader global ordering").
- **Parity** — `oracle_partition` computes the single-leader oracle's
  output restricted to each group's assigned messages; `verify_groups`
  byte-compares a real N-group run against it. Exact whenever accounts
  stay funded at or above their worst-case open margin (all shipped
  workloads); when the shadow ledger cannot cover a grant the front
  counts a `transfer_shortfall_total` instead of guessing.

The worst-case margin bound is exact, not heuristic: checkBalance
reserves `(size + adj) * price` for buys and `(size - adj) * (100 -
price)` for sells with `adj` netting against opposite holdings, so
`size * price` (buy) / `size * (100 - price)` (sell) always dominates
the reserve. The shadow ledger debits that bound for EVERY valid order
(home or cross) and never credits fills back, so it is a conservative
lower bound on the home group's real cash.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from kme_tpu import opcodes as op
from kme_tpu.wire import order_json, parse_order

# distinct salts keep the symbol->group and account->group spaces
# independently balanced (same key, different map)
SALT_SYMBOL = 0x53594D42    # "SYMB"
SALT_ACCOUNT = 0x41434354   # "ACCT"

# internal-line marker: rides the POJO's pass-through `prev` pointer
# field, which the engine echoes unmodified for TRANSFER and
# CREATE_BALANCE (no book interaction ever mutates them). Outside the
# organic oid range (reference harness oids are < 2^53), so no stream
# the reference can produce collides with it.
XFER_MARK = 0x4B4D452D46524E54   # "KME-FRNT"

_MASK = (1 << 64) - 1
_INT64_MIN = -(1 << 63)
_MARK_SUB = f'"prev":{XFER_MARK}'


def _mix64(z: int) -> int:
    """splitmix64 finalizer — bit-identical twin of mix64 in
    native/kme_router.cpp (see the warning there: assignment is part of
    the durable stream split, the two must never drift)."""
    z = (z + 0x9E3779B97F4A7C15) & _MASK
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
    return z ^ (z >> 31)


def group_of(key: int, ngroups: int, salt: int) -> int:
    """Rendezvous choice: argmax over per-(key, group) scores, ties to
    the smaller group id (C++ uses strict `>` replacement)."""
    if ngroups <= 1:
        return 0
    key &= _MASK
    best, best_score = 0, -1
    for g in range(ngroups):
        score = _mix64(key ^ _mix64((salt + g) & _MASK))
        if score > best_score:
            best, best_score = g, score
    return best


def assign_groups(keys, ngroups: int, salt: int):
    """Columnar assignment over an int64 array: the native pass when
    the library is built, the vectorized numpy twin otherwise. Returns
    int32 group ids."""
    import ctypes

    import numpy as np

    from kme_tpu.native import check_buffer, load_library

    keys = np.ascontiguousarray(keys, np.int64)
    out = np.zeros(len(keys), np.int32)
    if ngroups <= 1 or not len(keys):
        return out
    lib = load_library()
    if lib is not None:
        check_buffer("keys", keys, np.int64, len(keys))
        lib.kme_group_assign(
            len(keys), keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ngroups, salt,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out

    def mix(z):
        z = z + np.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))

    with np.errstate(over="ignore"):
        k = keys.view(np.uint64)
        scores = np.stack([
            mix(k ^ mix(np.uint64((salt + g) & _MASK)))
            for g in range(ngroups)])
    # np.argmax takes the FIRST max — same tie-break as the scalar/C++
    return scores.argmax(axis=0).astype(np.int32)


def symbol_key(sid: int) -> int:
    """The symbol identity a payout shares with its book: abs(sid),
    except INT64_MIN (un-negatable; the engines host-reject it, we just
    need a deterministic bucket)."""
    return sid if sid == _INT64_MIN or sid >= 0 else -sid


def symbol_group(sid: int, ngroups: int) -> int:
    return group_of(symbol_key(sid), ngroups, SALT_SYMBOL)


def account_group(aid: int, ngroups: int) -> int:
    return group_of(aid, ngroups, SALT_ACCOUNT)


def accept_routes(action, oid, aid, sid, ngroups: int):
    """Vectorized stateless group routing over batch columns: account
    ops (CREATE/TRANSFER) by aid under SALT_ACCOUNT; CANCEL by oid and
    everything else by symbol_key(sid) under SALT_SYMBOL — the same
    bucket route_line uses for records it has no per-oid state for, so
    duplicates and replays land identically. Returns int32 group ids.
    This is the semantics authority for the native acceptor's routing
    select (kme_front.cpp)."""
    import numpy as np

    action = np.asarray(action)
    keys = np.where(
        action == op.CANCEL, oid,
        np.where(np.asarray(sid) == _INT64_MIN, sid, np.abs(sid)))
    gsym = assign_groups(keys, ngroups, SALT_SYMBOL)
    gacct = assign_groups(np.ascontiguousarray(aid, np.int64), ngroups,
                          SALT_ACCOUNT)
    return np.where((action == op.CREATE_BALANCE)
                    | (action == op.TRANSFER), gacct, gsym).astype(
                        np.int32)


def accept_frames(buf: bytes, ngroups: int, router=None, B: int = 0):
    """The front door: one buffer of binary order frames -> (WireBatch,
    int32 group route per row, plan-or-None), taking the GIL once per
    batch. With the native library this is a single kme_front_accept
    call that validates, decodes, group-routes and — when `router` (a
    NativeSeqRouter) is given — chains kme_plan_batch to pack the
    (K, B) scan planes in the same call; `plan` is then the
    (cols, host_rejects, stacked, cnts, K) tuple with
    sched.plan_batch's exact contract. Without the library the
    byte-exact fallback is parse_frames + accept_routes (plan comes
    back None and callers use their numpy plan path, as everywhere
    else). Raises wire.WireFrameError on the first invalid frame —
    always through the Python authority, so native and fallback
    surface identical errors."""
    import numpy as np

    from kme_tpu.native import load_library
    from kme_tpu.wire import WireBatch, decode_frames

    lib = load_library()
    if lib is None:
        wb = WireBatch.parse_frames(buf)
        return wb, accept_routes(wb.action, wb.oid, wb.aid, wb.sid,
                                 ngroups), None
    pack = rh = None
    if router is not None:
        from kme_tpu.native import sched as _sched

        pack, rh = _sched.ensure_pack(router), router._h
    h = lib.kme_front_new()
    try:
        rc = lib.kme_front_accept(h, buf, len(buf), ngroups,
                                  SALT_SYMBOL, SALT_ACCOUNT, pack, rh,
                                  B)
        if rc < 0:
            decode_frames(buf)  # raises the authoritative error
            raise AssertionError(
                "native rejected a buffer the authority accepts "
                f"(code {rc} at offset {lib.kme_front_err_off(h)})")
        n = int(rc)
        if n == 0:
            return WireBatch._empty(), np.zeros(0, np.int32), None
        cols = [np.ctypeslib.as_array(
            lib.kme_front_col(h, i), (n,)).copy() for i in range(8)]
        hnext = np.ctypeslib.as_array(lib.kme_front_hnext(h),
                                      (n,)).copy()
        hprev = np.ctypeslib.as_array(lib.kme_front_hprev(h),
                                      (n,)).copy()
        wb = WireBatch(n, cols, hnext, hprev)
        groups = np.ctypeslib.as_array(lib.kme_front_groups(h),
                                       (n,)).copy()
        plan = None
        if router is not None:
            from kme_tpu.native import sched as _sched

            plan = _sched.collect_plan(lib, router, pack,
                                       int(lib.kme_front_plan_k(h)), B,
                                       wb.price, wb.size)
        return wb, groups, plan
    finally:
        lib.kme_front_free(h)


def make_internal_transfer(aid: int, amount: int, xid: int) -> str:
    """One leg of a reserve→settle pair: an ordinary TRANSFER wire line
    carrying the internal marker (prev) and the deterministic transfer
    ordinal (next) for post-mortem attribution."""
    return order_json(op.TRANSFER, 0, aid, 0, 0, amount,
                      next=xid, prev=XFER_MARK)


def make_internal_create(aid: int, xid: int) -> str:
    return order_json(op.CREATE_BALANCE, 0, aid, 0, 0, 0,
                      next=xid, prev=XFER_MARK)


def is_internal_line(line: str) -> bool:
    """True for front-injected plumbing — input lines AND the engine's
    IN/OUT echoes of them (the marker rides into both)."""
    return _MARK_SUB in line


class GroupRouter:
    """Stateful splitter: MatchIn lines -> per-group substream lines.

    Every decision is a pure function of the input prefix (no clock, no
    RNG), so re-running the split from offset 0 regenerates the
    byte-identical substreams — which is what makes the injected
    transfer legs replay-safe: they are durable MatchIn records in each
    group's topic, regenerated identically by any crash-replay.
    """

    def __init__(self, ngroups: int, transfers: bool = True,
                 prefund: int = 8) -> None:
        self.n = max(1, int(ngroups))
        self.transfers = transfers
        # chunked reserve→settle: each transfer pair grants up to
        # `prefund` orders' worth of worst-case margin, and the
        # UNCONSUMED remainder is tracked per (account, group) so
        # repeat cross-shard traffic rides the residual instead of
        # paying a fresh pair per order (the dominant transfer-path
        # cost — see bench_groups' transfer_frac). prefund=1 degrades
        # to exact per-order grants.
        self.prefund = max(1, int(prefund))
        self.oid_group: Dict[int, int] = {}    # oid -> routed group
        self.oid_sid: Dict[int, int] = {}      # oid -> its symbol (reshard)
        self.home: Dict[int, int] = {}         # aid -> home group
        self.cash: Dict[int, int] = {}         # aid -> shadow home cash
        self.reserve: Dict[Tuple[int, int], int] = {}  # (aid, g) -> margin
        self.xid = 0                           # injected-line ordinal
        self.counters = {
            "cross_shard_transfers_total": 0,
            "transfer_shortfall_total": 0,
            "transfer_volume_total": 0,
            "balance_broadcasts_total": 0,
        }

    def account_home(self, aid: int) -> int:
        h = self.home.get(aid)
        if h is None:
            h = account_group(aid, self.n)
            self.home[aid] = h
        return h

    def _margin_bound(self, msg) -> int:
        """Worst-case reserve of a valid order (dominates checkBalance's
        adj-netted reserve; see module docstring). 0 for orders fixed
        mode rejects before the balance check."""
        if not (0 <= msg.price < 126) or msg.size <= 0:
            return 0
        if msg.action == op.BUY:
            return msg.size * msg.price
        return msg.size * (100 - msg.price)

    def route_line(self, line: str) -> List[Tuple[int, str]]:
        """One input line -> [(group, line), ...] in substream order.
        The original line appears exactly once; every other entry is an
        internal-marked injection. Raises ValueError on malformed input
        (callers own the strict/drop decision, like the service does)."""
        msg = parse_order(line)
        a, n = msg.action, self.n
        if n <= 1:
            return [(0, line)]
        if a in (op.BUY, op.SELL):
            g = symbol_group(msg.sid, n)
            self.oid_group[msg.oid] = g
            self.oid_sid[msg.oid] = msg.sid
            h = self.account_home(msg.aid)
            out: List[Tuple[int, str]] = []
            w = self._margin_bound(msg) if self.transfers else 0
            if w > 0:
                have = self.cash.get(msg.aid, 0)
                if h != g:
                    r = self.reserve.get((msg.aid, g), 0)
                    if r >= w:
                        # residual from an earlier chunked grant covers
                        # this order outright — no legs injected
                        self.reserve[(msg.aid, g)] = r - w
                    else:
                        need = w - r
                        grant = min(have, need + (self.prefund - 1) * w)
                        if grant < need:
                            self.counters[
                                "transfer_shortfall_total"] += 1
                        self.reserve[(msg.aid, g)] = max(
                            0, r + grant - w)
                        if grant > 0:
                            self.cash[msg.aid] = have - grant
                            out.append((h, make_internal_transfer(
                                msg.aid, -grant, self.xid)))
                            out.append((g, make_internal_transfer(
                                msg.aid, grant, self.xid + 1)))
                            self.xid += 2
                            self.counters[
                                "cross_shard_transfers_total"] += 1
                            self.counters[
                                "transfer_volume_total"] += grant
                else:
                    # a home-group order consumes home cash directly —
                    # debit the shadow too, or a later grant could
                    # exceed what the home engine really holds
                    self.cash[msg.aid] = have - min(w, have)
            out.append((g, line))
            return out
        if a == op.CANCEL:
            g = self.oid_group.get(msg.oid)
            if g is None:
                # unknown oid: the engine rejects it wherever it lands —
                # pick the bucket its oid hashes to so duplicates and
                # replays route identically
                g = group_of(msg.oid, n, SALT_SYMBOL)
            return [(g, line)]
        if a == op.CREATE_BALANCE:
            h = self.account_home(msg.aid)
            self.cash.setdefault(msg.aid, 0)
            out = []
            for g in range(n):
                if g == h:
                    out.append((g, line))
                else:
                    out.append((g, make_internal_create(msg.aid,
                                                        self.xid)))
                    self.xid += 1
                    self.counters["balance_broadcasts_total"] += 1
            return out
        if a == op.TRANSFER:
            h = self.account_home(msg.aid)
            # deposits raise the shadow; withdrawals lower it (clamped —
            # the engine never lets a balance go negative)
            self.cash[msg.aid] = max(
                0, self.cash.get(msg.aid, 0) + msg.size)
            return [(h, line)]
        # symbol lifecycle (and unknown actions, which every engine
        # rejects): bucket by symbol identity
        return [(symbol_group(msg.sid, n), line)]

    def split(self, lines: Iterable[str]) -> List[List[str]]:
        """Whole-stream convenience: per-group substream line lists."""
        per: List[List[str]] = [[] for _ in range(self.n)]
        for line in lines:
            for g, ln in self.route_line(line):
                per[g].append(ln)
        return per

    def reshard(self, m: int) -> dict:
        """Re-point this router at an M-group topology IN PLACE,
        carrying the split state across the boundary (live N→M
        re-splitting, ROADMAP item 2). Matches what the reshard
        coordinator does to the engines at the same barrier:

        - resting orders follow their symbol: `oid -> group` remaps via
          `symbol_group(oid_sid[oid], m)` (a CANCEL for a pre-reshard
          order must land where the coordinator moved its book);
        - account custody remaps to `account_group(aid, m)`;
        - the coordinator consolidates EVERY account's full cash at its
          new home (bridge/reshard.py settlement legs), so unconsumed
          reserve residuals parked at old symbol groups fold back into
          home shadow cash — the shadow stays a conservative lower
          bound on the new home engine's real balance.

        Deterministic (pure function of prior routing state + m), so a
        replay of the same prefix + the same reshard barrier regenerates
        byte-identical post-reshard substreams. Returns a summary of
        moved keys for reports."""
        m = max(1, int(m))
        old_n, moved_oids, moved_homes = self.n, 0, 0
        for oid, g in list(self.oid_group.items()):
            sid = self.oid_sid.get(oid)
            ng = (symbol_group(sid, m) if sid is not None
                  else group_of(oid, m, SALT_SYMBOL))
            if ng != g:
                moved_oids += 1
            self.oid_group[oid] = ng
        for aid, h in list(self.home.items()):
            nh = account_group(aid, m)
            if nh != h:
                moved_homes += 1
            self.home[aid] = nh
        for (aid, _g), r in self.reserve.items():
            if r > 0:
                self.cash[aid] = self.cash.get(aid, 0) + r
        self.reserve.clear()
        self.n = m
        return {"old_groups": old_n, "new_groups": m,
                "moved_oids": moved_oids, "moved_homes": moved_homes,
                "tracked_oids": len(self.oid_group),
                "tracked_accounts": len(self.home)}


def split_lines(lines: Iterable[str], ngroups: int,
                transfers: bool = True, prefund: int = 8):
    """(per_group substreams, the router that built them)."""
    router = GroupRouter(ngroups, transfers=transfers, prefund=prefund)
    return router.split(lines), router


def merge_records(records: Iterable[Tuple[int, int, str]]) -> List[str]:
    """THE global-order convention (COMPAT.md): stable sort on
    `(group, out_seq)`, internal-marked lines dropped. `records` may
    arrive in any interleaving — per-group consumers race — and the
    result is identical."""
    recs = sorted(records, key=lambda r: (r[0], r[1]))
    return [r[2] for r in recs if not is_internal_line(r[2])]


def merge_streams(per_group: Sequence[Sequence[str]]) -> List[str]:
    """Merge per-group MatchOut streams already in per-group order:
    concatenation in group-id order ≡ merge_records with out_seq = the
    line's index in its group stream."""
    out: List[str] = []
    for lines in per_group:
        out.extend(ln for ln in lines if not is_internal_line(ln))
    return out


def oracle_partition(lines: Sequence[str], ngroups: int,
                     compat: str = "fixed",
                     book_slots: Optional[int] = None,
                     max_fills: Optional[int] = None,
                     transfers: bool = True, prefund: int = 8):
    """Single-leader ground truth, partitioned by the front's own
    assignment: per_group[g] is the single oracle's output stream
    restricted to the messages the front routes to g. Returns
    (per_group expected wire lines, the GroupRouter used). The injected
    internal legs have no expected lines — their echoes are suppressed
    on merge."""
    from kme_tpu.oracle import OracleEngine

    router = GroupRouter(ngroups, transfers=transfers, prefund=prefund)
    eng = OracleEngine(compat, book_slots, max_fills)
    per: List[List[str]] = [[] for _ in range(max(1, ngroups))]
    for line in lines:
        routed = router.route_line(line)
        prim = [g for g, ln in routed if not is_internal_line(ln)]
        assert len(prim) == 1, "input line carries the internal marker"
        per[prim[0]].extend(
            rec.wire() for rec in eng.process(parse_order(line)))
    return per, router


def verify_groups(lines: Sequence[str],
                  actual_per_group: Sequence[Sequence[str]],
                  compat: str = "fixed",
                  book_slots: Optional[int] = None,
                  max_fills: Optional[int] = None,
                  prefund: int = 8) -> dict:
    """Byte-compare an N-group run against the partitioned single-leader
    oracle. `actual_per_group[g]` is group g's raw MatchOut lines
    (internal echoes still present — filtered here). Returns a report;
    report["ok"] is the parity verdict."""
    ngroups = len(actual_per_group)
    want, router = oracle_partition(lines, ngroups, compat=compat,
                                    book_slots=book_slots,
                                    max_fills=max_fills,
                                    prefund=prefund)
    report: dict = {"groups": ngroups, "ok": True, "mismatches": [],
                    "counters": dict(router.counters)}
    for g in range(ngroups):
        got = [ln for ln in actual_per_group[g]
               if not is_internal_line(ln)]
        if got == want[g]:
            continue
        report["ok"] = False
        n = min(len(got), len(want[g]))
        div = next((i for i in range(n) if got[i] != want[g][i]), n)
        report["mismatches"].append({
            "group": g, "at": div, "got_lines": len(got),
            "want_lines": len(want[g]),
            "got": got[div] if div < len(got) else None,
            "want": want[g][div] if div < len(want[g]) else None})
    merged = merge_streams(actual_per_group)
    report["merged_lines"] = len(merged)
    report["expected_merged_lines"] = sum(len(w) for w in want)
    return report


def oracle_partition_reshard(lines: Sequence[str], n: int, m: int,
                             split_at: int, compat: str = "fixed",
                             book_slots: Optional[int] = None,
                             max_fills: Optional[int] = None,
                             transfers: bool = True, prefund: int = 8):
    """Ground truth for a live N→M reshard at a batch barrier: ONE
    single-leader oracle processes the whole stream, and its output is
    partitioned by the routed group of each message — `lines[:split_at]`
    under the N-topology router, `lines[split_at:]` under the SAME
    router re-pointed at M groups (`GroupRouter.reshard`, mirroring the
    coordinator's state migration). Because resharding is pure topology
    (COMPAT.md), the oracle's wire bytes are untouched; only their
    group attribution changes. Returns (pre_per_group[n],
    post_per_group[m], router)."""
    from kme_tpu.oracle import OracleEngine

    split_at = max(0, min(int(split_at), len(lines)))
    router = GroupRouter(n, transfers=transfers, prefund=prefund)
    eng = OracleEngine(compat, book_slots, max_fills)
    pre: List[List[str]] = [[] for _ in range(max(1, n))]
    post: List[List[str]] = [[] for _ in range(max(1, m))]
    for i, line in enumerate(lines):
        if i == split_at:
            router.reshard(m)
        routed = router.route_line(line)
        prim = [g for g, ln in routed if not is_internal_line(ln)]
        assert len(prim) == 1, "input line carries the internal marker"
        dest = pre if i < split_at else post
        dest[prim[0]].extend(
            rec.wire() for rec in eng.process(parse_order(line)))
    if split_at >= len(lines) and router.n != max(1, m):
        router.reshard(m)
    return pre, post, router


def verify_groups_reshard(lines: Sequence[str], split_at: int,
                          actual_pre: Sequence[Sequence[str]],
                          actual_post: Sequence[Sequence[str]],
                          compat: str = "fixed",
                          book_slots: Optional[int] = None,
                          max_fills: Optional[int] = None,
                          prefund: int = 8) -> dict:
    """Byte-compare a live N→M reshard run against the partitioned
    single-leader oracle: `actual_pre[g]` is old-generation group g's
    raw MatchOut lines (everything it emitted before the barrier
    drained it), `actual_post[g]` the new generation's. Internal-marked
    echoes — including the coordinator's settlement legs — are filtered
    before comparison, exactly like `verify_groups`. report["ok"] is
    the parity verdict across BOTH generations."""
    n, m = len(actual_pre), len(actual_post)
    want_pre, want_post, router = oracle_partition_reshard(
        lines, n, m, split_at, compat=compat, book_slots=book_slots,
        max_fills=max_fills, prefund=prefund)
    report: dict = {"old_groups": n, "new_groups": m,
                    "split_at": int(split_at), "ok": True,
                    "mismatches": [],
                    "counters": dict(router.counters)}
    for gen, want, actual in (("pre", want_pre, actual_pre),
                              ("post", want_post, actual_post)):
        for g in range(len(want)):
            got = [ln for ln in actual[g] if not is_internal_line(ln)]
            if got == want[g]:
                continue
            report["ok"] = False
            k = min(len(got), len(want[g]))
            div = next((i for i in range(k) if got[i] != want[g][i]), k)
            report["mismatches"].append({
                "generation": gen, "group": g, "at": div,
                "got_lines": len(got), "want_lines": len(want[g]),
                "got": got[div] if div < len(got) else None,
                "want": want[g][div] if div < len(want[g]) else None})
    report["merged_lines"] = (len(merge_streams(actual_pre))
                              + len(merge_streams(actual_post)))
    report["expected_merged_lines"] = (
        sum(len(w) for w in want_pre) + sum(len(w) for w in want_post))
    return report


class FrontLinks:
    """Front-door produce links to per-group `kme-serve` brokers over
    real TCP (bridge/tcp.py) — the multi-host half of ROADMAP item 2.

    One `TcpBroker` client per group. Link g's produces into its
    MatchIn topic carry a monotone per-link `out_seq` cursor, which is
    exactly the broker's idempotent dedup key (PR 4): on a transport
    fault the client invalidates the connection, reconnects on the next
    call, and re-sends the SAME stamped record — if the first attempt
    actually landed before the link died, the durable watermark
    suppresses the copy. That is reconnect-with-resume off the
    `(epoch, out_seq)` cursor with zero duplicate records.

    The live front leaves `epoch=None` (a sequence-only stamp): the
    broker's fence is BROKER-WIDE and owned by the serving leader's
    lease epoch, so a front-door epoch would either get fenced or —
    worse — advance the fence under the leader. The reshard
    coordinator, which runs while no leader is up, is the one caller
    that passes an epoch (it stamps settlement legs at epoch 1 on the
    fresh logs, below any future leader's lease). Exactly one stamping
    front per group topic: the cursor is a per-topic watermark, not a
    per-producer one."""

    def __init__(self, addrs: Sequence, topic_fmt: str = "MatchIn.g{g}",
                 epoch: Optional[int] = None, timeout: float = 10.0,
                 provision: bool = True, retries: int = 8,
                 backoff_s: float = 0.05,
                 cursors: Optional[Sequence[int]] = None) -> None:
        from kme_tpu.bridge.tcp import parse_addr

        self.addrs = [parse_addr(a) if isinstance(a, str)
                      else (a[0], int(a[1])) for a in addrs]
        self.n = len(self.addrs)
        self.topics = [topic_fmt.format(g=g) for g in range(self.n)]
        self.epoch = epoch
        self._timeout = timeout
        self._provision = provision
        self._retries = max(1, int(retries))
        self._backoff = backoff_s
        self.cursor = ([int(c) for c in cursors] if cursors is not None
                       else [0] * self.n)
        if len(self.cursor) != self.n:
            raise ValueError("cursors must match the address count")
        self._clients: List[Optional[object]] = [None] * self.n
        self.health = [{"addr": f"{h}:{p}", "topic": self.topics[g],
                        "connects": 0, "transport_faults": 0,
                        "produced": 0, "dup_suppressed": 0,
                        "overload_waits": 0, "last_error": None}
                       for g, (h, p) in enumerate(self.addrs)]

    def _client(self, g: int):
        if self._clients[g] is None:
            from kme_tpu.bridge.broker import BrokerError
            from kme_tpu.bridge.tcp import TcpBroker

            c = TcpBroker(*self.addrs[g], timeout=self._timeout)
            if self._provision:
                try:
                    c.create_topic(self.topics[g])
                except BrokerError:
                    pass    # already provisioned
            self._clients[g] = c
            self.health[g]["connects"] += 1
        return self._clients[g]

    def send(self, g: int, line: str) -> int:
        """Produce one substream line on link g with the next cursor
        stamp; retries transport faults and overload pushback with the
        same stamp. Returns the broker offset (-1 when the dedup
        watermark swallowed a replayed copy). BrokerFenced propagates —
        it is a topology verdict, not a link fault."""
        import time as _time

        from kme_tpu.bridge.broker import (BrokerError, BrokerFenced,
                                           BrokerOverload)

        h = self.health[g]
        seq = self.cursor[g]
        last: Optional[Exception] = None
        for attempt in range(self._retries):
            try:
                off = self._client(g).produce(
                    self.topics[g], None, line,
                    epoch=self.epoch, out_seq=seq)
            except BrokerOverload as e:
                h["overload_waits"] += 1
                back = getattr(e, "backoff_ms", None)
                _time.sleep((back or 50) / 1000.0)
                last = e
                continue
            except BrokerFenced:
                raise
            except (BrokerError, OSError) as e:
                # transport fault (or the serve is still coming up — the
                # client connects eagerly, so a refused connect surfaces
                # as a raw OSError): the client invalidates itself and
                # reconnects on the next call; the retry re-sends the
                # SAME (epoch, out_seq) record, so an attempt that
                # landed before the fault dedups instead of duplicating
                h["transport_faults"] += 1
                h["last_error"] = str(e)
                last = e
                _time.sleep(self._backoff * (attempt + 1))
                continue
            self.cursor[g] = seq + 1
            h["produced"] += 1
            if off < 0:
                h["dup_suppressed"] += 1
            return off
        raise (last if last is not None else
               BrokerError(f"link {g}: produce failed"))

    def route(self, router: GroupRouter,
              line: str) -> List[Tuple[int, int]]:
        """Split one MatchIn line through `router` and produce every
        substream record on its group link. Returns [(group, offset)]."""
        return [(g, self.send(g, ln))
                for g, ln in router.route_line(line)]

    def end_offsets(self) -> List[int]:
        """Per-link topic end offsets (drain-barrier probe)."""
        return [self._client(g).end_offset(self.topics[g])
                for g in range(self.n)]

    def snapshot(self) -> dict:
        """Per-link health + cursors, for reports and health files."""
        return {"groups": self.n, "epoch": self.epoch,
                "cursors": list(self.cursor),
                "links": [dict(h) for h in self.health]}

    def close(self) -> None:
        for c in self._clients:
            if c is not None:
                try:
                    c.close()
                except OSError:
                    pass
        self._clients = [None] * self.n


def write_front_trace(path: str, lines: Sequence[str], ngroups: int,
                      transfers: bool = True, prefund: int = 8) -> int:
    """Record the front door's own trace spans: one front_accept and
    one route span per input line, stamped with the order's GLOBAL
    deterministic trace id and its routed (group, local index) — the
    anchor `kme-trace --cluster` joins group-side spans against. Spans
    are zero-width position marks (the split is a deterministic
    function, not a runtime hop); what matters is the identity they
    carry. Returns the number of spans written."""
    import time

    from kme_tpu.telemetry.dtrace import route_map
    from kme_tpu.telemetry.journal import Journal

    entries, _router = route_map(lines, ngroups, transfers=transfers,
                                 prefund=prefund)
    now = time.time_ns() // 1000
    spans = []
    for ent in entries:
        if ent is None:
            continue
        base = {"g": -1, "off": ent["off"], "oid": ent["oid"],
                "aid": ent["aid"], "tid": ent["tid"], "ptid": 0,
                "t0": now, "t1": now, "li": ent["li"]}
        spans.append(dict(base, kind="front_accept"))
        spans.append(dict(base, kind="route", g=ent["g"],
                          ptid=ent["tid"]))
    j = Journal(path, resume=False)
    try:
        j.record_spans(spans)
    finally:
        j.close()
    return len(spans)


# -- CLI ---------------------------------------------------------------


def _read_lines(path: Optional[str]):
    fh = sys.stdin if path in (None, "-") else open(path)
    try:
        return [ln.strip() for ln in fh if ln.strip()]
    finally:
        if fh is not sys.stdin:
            fh.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kme-front",
        description="multi-leader front door: split a MatchIn stream "
                    "into per-group substreams (with cross-shard "
                    "balance-transfer injection), merge per-group "
                    "MatchOut streams into the canonical global feed, "
                    "or verify an N-group run against the single-leader "
                    "oracle")
    p.add_argument("mode", choices=("split", "merge", "verify", "route"))
    p.add_argument("--groups", type=int, required=True, metavar="N")
    p.add_argument("--brokers", default=None, metavar="H:P,H:P,...",
                   help="route: comma-separated per-group broker "
                        "addresses (group k feeds the k-th address over "
                        "real TCP with reconnect-with-resume off the "
                        "idempotent out_seq cursor)")
    p.add_argument("--input", default=None, metavar="PATH",
                   help="order-JSONL input stream (default stdin; "
                        "split and verify)")
    p.add_argument("--out-dir", default=None, metavar="DIR",
                   help="split: write group{K}.in substream files here")
    p.add_argument("--in-dir", default=None, metavar="DIR",
                   help="merge/verify: read group{K}.out per-group "
                        "MatchOut line files from here")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="split: record front_accept/route trace spans "
                        "(deterministic per-order trace ids, "
                        "telemetry/dtrace.py) to this journal; "
                        "kme-trace --cluster reads it as "
                        "<state-root>/front.trace")
    p.add_argument("--no-transfers", action="store_true",
                   help="split symbols only; skip balance-transfer "
                        "injection (parity then requires every account "
                        "to be funded in every group)")
    p.add_argument("--compat", choices=("java", "fixed"),
                   default="fixed", help="oracle compat for verify")
    p.add_argument("--slots", type=int, default=None,
                   help="capacity envelope for verify (match the "
                        "serving engines' --slots)")
    p.add_argument("--max-fills", type=int, default=None)
    p.add_argument("--tsdb", default=None, metavar="DIR",
                   help="route: sample the per-link routing counters "
                        "into the shared on-disk time-series store "
                        "every 1000 routed lines (source 'front')")
    p.add_argument("--prefund", type=int, default=8,
                   help="orders' worth of worst-case margin granted "
                        "per reserve->settle transfer pair (residual "
                        "tracked per account x group; 1 = exact "
                        "per-order grants)")
    args = p.parse_args(argv)
    import json

    n = args.groups
    if n < 1:
        p.error("--groups must be >= 1")
    if args.mode == "split":
        lines = _read_lines(args.input)
        per, router = split_lines(lines, n,
                                  transfers=not args.no_transfers,
                                  prefund=args.prefund)
        if args.out_dir is None:
            p.error("split needs --out-dir")
        os.makedirs(args.out_dir, exist_ok=True)
        for g in range(n):
            with open(os.path.join(args.out_dir,
                                   f"group{g}.in"), "w") as f:
                f.write("\n".join(per[g]) + ("\n" if per[g] else ""))
        if args.trace_out is not None:
            write_front_trace(args.trace_out, lines, n,
                              transfers=not args.no_transfers,
                              prefund=args.prefund)
        doc = {"groups": n, "input_lines": len(lines),
               "per_group": [len(x) for x in per]}
        doc.update(router.counters)
        print(json.dumps(doc), file=sys.stderr)
        return 0
    if args.mode == "route":
        if args.brokers is None:
            p.error("route needs --brokers")
        addrs = [a for a in args.brokers.split(",") if a]
        if len(addrs) != n:
            p.error(f"--brokers lists {len(addrs)} addresses for "
                    f"--groups {n}")
        lines = _read_lines(args.input)
        router = GroupRouter(n, transfers=not args.no_transfers,
                             prefund=args.prefund)
        links = FrontLinks(addrs)
        tsdb = None
        tsdb_seq = 0
        if args.tsdb is not None:
            from kme_tpu.telemetry import TSDB

            try:
                tsdb = TSDB(args.tsdb, source="front")
                tsdb_seq = tsdb.next_seq()
            except (OSError, ValueError) as e:
                print(f"kme-front: TSDB disabled: {e}",
                      file=sys.stderr)

        def _tsdb_sample(routed):
            nonlocal tsdb, tsdb_seq
            if tsdb is None:
                return
            snap = links.snapshot()
            vals = {"front_routed_lines_total": routed,
                    "front_epoch": snap["epoch"]}
            for g, cur in enumerate(snap["cursors"]):
                vals[f"front_cursor.g{g}"] = cur
            for g, h in enumerate(snap["links"]):
                for hk, hv in h.items():
                    if isinstance(hv, (int, float)) \
                            and not isinstance(hv, bool):
                        vals[f"front_{hk}.g{g}"] = hv
            try:
                tsdb.append_values(vals, tsdb_seq)
                tsdb_seq += 1
            except OSError:
                tsdb = None     # history is best-effort
        try:
            for i, line in enumerate(lines):
                links.route(router, line)
                if (i + 1) % 1000 == 0:
                    # the front door is a batch process, not a serve
                    # loop: history samples ride routing progress
                    # instead of wall-clock heartbeats
                    _tsdb_sample(i + 1)
        finally:
            doc = links.snapshot()
            doc["input_lines"] = len(lines)
            doc.update(router.counters)
            _tsdb_sample(len(lines))
            if tsdb is not None:
                tsdb.close()
            print(json.dumps(doc), file=sys.stderr)
            links.close()
        return 0
    if args.in_dir is None:
        p.error(f"{args.mode} needs --in-dir")
    per_out = []
    for g in range(n):
        path = os.path.join(args.in_dir, f"group{g}.out")
        per_out.append(_read_lines(path) if os.path.exists(path) else [])
    if args.mode == "merge":
        for ln in merge_streams(per_out):
            print(ln)
        return 0
    # verify
    lines = _read_lines(args.input)
    report = verify_groups(lines, per_out, compat=args.compat,
                           book_slots=args.slots,
                           max_fills=args.max_fills,
                           prefund=args.prefund)
    print(json.dumps(report, indent=2), file=sys.stderr)
    print(f"kme-front: parity "
          f"{'OK' if report['ok'] else 'DIVERGED'}", file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
