"""Live N→M group re-splitting: the reshard coordinator (ROADMAP item 2).

PR 9 froze the group count at startup; this module makes the topology
breathe. The life of a reshard (drain → fence → migrate → settle →
resume) is split so every step is either PURE or IDEMPOTENT, and a
coordinator SIGKILL at any byte re-runs to the identical result:

- **Drain** is the caller's job (the chaos drill, an operator): stop
  feeding, wait until every old group's heartbeat offset reaches its
  substream end, let the serves `--idle-exit` cleanly. The coordinator
  only ever touches checkpoints of STOPPED groups — a batch barrier,
  exactly like the paper's device step boundary.
- **Fence** steals each old group's lease (bridge/lease.py) and appends
  one stamped tombstone to a `Retired` topic in the old broker log.
  The broker's fence is recovered from log stamps, so the tombstone
  makes the re-fence DURABLE: any zombie leader replaying its old
  epoch against the retired log raises BrokerFenced forever after.
- **Migrate** is a pure function: load every old group's oracle
  snapshot, partition the five stores by the NEW rendezvous topology
  (`partition_engines` — the canonical codec is the checkpoint codec,
  runtime/checkpoint.py), write each new group's snapshot at offset 0.
  Balances are NOT copied: every new engine gets a zero balance for
  every known account (the CREATE-broadcast invariant), and the per
  account totals come back as...
- **Settle**: one internal-marked TRANSFER leg per account, stamped
  `(epoch, out_seq)` and produced straight into the new home group's
  durable MatchIn log over the fenced idempotent produce path. Stamps
  are a deterministic function of the consolidation map, so a crashed
  settle re-runs byte-identically and the broker watermark suppresses
  every leg that already landed — transfers are exactly-once across
  any number of coordinator deaths. The serving side counts them into
  the `pending_reserve` checkpoint ledger like any other cross-shard
  leg (bridge/service.py).

Ordering matters once: settle stamps epoch 1 (after the coordinator's
own lease acquire) and the first new leader acquires epoch >= 2 and
fences the broker BROKER-WIDE — so the coordinator must finish before
the new generation starts. The journal (reshard.json, fsync'd after
every phase) records where a dead coordinator got to; `run()` resumes
from there and refuses topologies that do not match it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from kme_tpu.bridge.front import (account_group, make_internal_transfer,
                                  symbol_group)

JOURNAL = "reshard.json"
RETIRED_TOPIC = "Retired"
# settlement xids live far outside the front door's injected-line
# ordinals (router.xid counts from 0) so post-mortem attribution can
# tell a migration leg from an organic reserve->settle leg
XID_BASE = 1 << 40


def rendezvous_minimal_frac(n: int, m: int) -> float:
    """Expected moved-key fraction of a minimal (rendezvous) N→M
    re-split over a uniform key space: growing to a superset of group
    ids moves a key iff its argmax lands on a NEW id — (m-n)/m; a merge
    moves the keys whose old argmax disappeared — (n-m)/n."""
    n, m = max(1, int(n)), max(1, int(m))
    if m >= n:
        return (m - n) / m
    return (n - m) / n


def plan_reshard(n: int, m: int, symbols: Sequence[int],
                 accounts: Sequence[int]) -> dict:
    """Deterministic re-split plan over explicit key universes: which
    symbols change their book's group, which accounts change custody,
    and the headline `moved_key_frac` the multihost bench gates against
    the rendezvous-minimal expectation (a consistent-hashing regression
    — e.g. a salt drift remapping everything — shows up here as
    moved_key_frac ≈ 1)."""
    moved_symbols = [int(s) for s in symbols
                     if symbol_group(s, n) != symbol_group(s, m)]
    moved_accounts = [int(a) for a in accounts
                      if account_group(a, n) != account_group(a, m)]
    total = len(symbols) + len(accounts)
    moved = len(moved_symbols) + len(moved_accounts)
    return {
        "old_groups": int(n), "new_groups": int(m),
        "symbols": len(symbols), "accounts": len(accounts),
        "moved_symbols": moved_symbols,
        "moved_accounts": moved_accounts,
        "moved_key_frac": (moved / total) if total else 0.0,
        "rendezvous_minimal_frac": rendezvous_minimal_frac(n, m),
    }


def partition_engines(engines: Sequence, m: int):
    """The canonical state codec of a reshard: old fixed-mode oracle
    engines -> (new_engines[m], consolidation {aid: total cash}).

    Books, buckets, resting orders and positions follow their symbol to
    `symbol_group(sid, m)` — each symbol's state lives in exactly one
    old engine, so the move is a disjoint re-bucketing, byte-identical
    values. Balances are deliberately NOT moved here: every new engine
    gets a zero balance for every account either generation has ever
    seen (the CREATE-broadcast invariant — margin releases and fill
    credits at a symbol group need the key to exist), and the summed
    cash comes back as the consolidation map for `settlement_legs`.

    Fixed-mode only: java mode's Q11 garbage position keys make
    symbol attribution ill-defined (COMPAT.md), and grouped serving is
    a fixed-mode deployment anyway."""
    from kme_tpu.oracle import OracleEngine

    m = max(1, int(m))
    for eng in engines:
        if eng.java:
            raise ValueError("reshard surgery is fixed-mode only "
                             "(java position keys are untyped, Q11)")
    slots = engines[0].book_slots if engines else None
    fills = engines[0].max_fills if engines else None
    new = [OracleEngine("fixed", slots, fills) for _ in range(m)]
    consolidation: Dict[int, int] = {}
    for eng in engines:
        for aid, bal in eng.balances.items():
            consolidation[aid] = consolidation.get(aid, 0) + bal
        for bk, bits in eng.books.items():
            # fixed-mode book key is 2*sid + side (engine.py codec)
            new[symbol_group(bk // 2, m)].books[bk] = bits
        for bkt, ptrs in eng.buckets.items():
            # bucket key is book_key*256 + price, price in [0, 126)
            new[symbol_group((bkt // 256) // 2, m)].buckets[bkt] = ptrs
        for oid, rec in eng.orders.items():
            new[symbol_group(rec.sid, m)].orders[oid] = rec.copy()
        for key, pos in eng.positions.items():
            new[symbol_group(key[1], m)].positions[key] = pos
    for aid in consolidation:
        for eng in new:
            eng.balances[aid] = 0
    return new, consolidation


def settlement_legs(consolidation: Dict[int, int],
                    m: int) -> List[List]:
    """Deterministic settlement plan: one internal-marked TRANSFER
    crediting each account's consolidated cash at its NEW home group.
    Entries are [group, out_seq, xid, aid, amount, line]; out_seq is
    the leg's position within its group's MatchIn stamp sequence —
    replay-stable, so a re-run regenerates identical stamps and the
    broker dedups instead of doubling."""
    per_seq = [0] * max(1, int(m))
    legs: List[List] = []
    for i, aid in enumerate(sorted(consolidation)):
        amount = consolidation[aid]
        if amount <= 0:
            continue        # engine balances are never negative
        g = account_group(aid, m)
        xid = XID_BASE + i
        legs.append([g, per_seq[g], xid, aid, amount,
                     make_internal_transfer(aid, amount, xid)])
        per_seq[g] += 1
    return legs


def probe_fenced(gdir: str, epoch: int = 1) -> bool:
    """Post-mortem stale-epoch probe against a retired group's durable
    broker log: True when a produce at `epoch` raises BrokerFenced
    (the re-fence held). Never appends: an unfenced probe's stamp
    collides with the tombstone's watermark and is dedup-suppressed."""
    from kme_tpu.bridge.broker import (BrokerError, BrokerFenced,
                                       InProcessBroker)

    log_dir = os.path.join(gdir, "broker-log")
    b = InProcessBroker(persist_dir=log_dir)
    try:
        b.produce(RETIRED_TOPIC, None, "probe", epoch=epoch, out_seq=0)
    except BrokerFenced:
        return True
    except BrokerError:
        return False    # tombstone topic missing: fence never ran
    return False


class ReshardCoordinator:
    """Journaled fence → migrate → settle executor over STOPPED groups.

    `old_root`/`new_root` are supervisor state roots (group k at
    <root>/group{k}); every phase is recorded in <new_root>/reshard.json
    with an fsync before the next phase starts, so a coordinator killed
    at any point re-runs to the identical end state: fence re-steals
    (epochs only grow), migrate is a pure overwrite of offset-0
    snapshots, and settle's stamped legs dedup on the broker watermark.
    """

    def __init__(self, old_root: str, new_root: str, old_groups: int,
                 new_groups: int, clock=None) -> None:
        self.old_root, self.new_root = old_root, new_root
        self.n, self.m = int(old_groups), int(new_groups)
        if self.n < 1 or self.m < 1:
            raise ValueError("group counts must be >= 1")
        self.journal_path = os.path.join(new_root, JOURNAL)
        # injected clock (zero-arg seconds float) stamps the phase
        # events and measures the phase walls; the sim passes its
        # virtual clock so the timeline digest stays seed-stable
        if clock is None:
            import time as _time

            clock = _time.time
        self._clock = clock

    def _old_dir(self, k: int) -> str:
        return os.path.join(self.old_root, f"group{k}")

    def _new_dir(self, k: int) -> str:
        return os.path.join(self.new_root, f"group{k}")

    def _load_journal(self) -> dict:
        try:
            with open(self.journal_path, encoding="utf-8") as f:
                j = json.load(f)
        except (OSError, ValueError):
            return {}
        if (j.get("old_root") != self.old_root
                or j.get("new_root") != self.new_root
                or j.get("old_groups") != self.n
                or j.get("new_groups") != self.m):
            raise ValueError(
                f"{self.journal_path} records a different reshard "
                f"({j.get('old_groups')}→{j.get('new_groups')}); "
                f"refusing to mix topologies")
        return j

    def _save_journal(self, j: dict) -> None:
        os.makedirs(self.new_root, exist_ok=True)
        tmp = self.journal_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(j, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.journal_path)

    # -- phases --------------------------------------------------------

    def _fence_old(self) -> dict:
        from kme_tpu.bridge import lease
        from kme_tpu.bridge.broker import BrokerError, InProcessBroker

        out = {"stolen_epochs": [], "done": True}
        for k in range(self.n):
            gdir = self._old_dir(k)
            prev = lease.current_epoch(gdir)
            epoch = lease.steal(gdir)
            log_dir = os.path.join(gdir, "broker-log")
            if os.path.isdir(log_dir):
                b = InProcessBroker(persist_dir=log_dir)
                try:
                    b.create_topic(RETIRED_TOPIC)
                except BrokerError:
                    pass
                # durable re-fence: the tombstone's epoch stamp is
                # recovered into the broker-wide fence on every future
                # reload of this log (re-runs dedup on out_seq 0)
                b.produce(RETIRED_TOPIC, None,
                          json.dumps({"retired_by": "reshard",
                                      "new_root": self.new_root,
                                      "epoch": epoch}),
                          epoch=epoch, out_seq=0)
                b.sync()
            out["stolen_epochs"].append({"group": k, "prev": prev,
                                         "epoch": epoch})
        return out

    def _migrate(self) -> Tuple[dict, List[List]]:
        from kme_tpu.runtime import checkpoint as ck

        engines, offsets = [], []
        for k in range(self.n):
            eng, off = ck.load_oracle(self._old_dir(k))
            if eng is None:
                raise ValueError(
                    f"no oracle snapshot in {self._old_dir(k)} — "
                    f"reshard needs cleanly drained old groups")
            engines.append(eng)
            offsets.append(off)
        new_engines, consolidation = partition_engines(engines, self.m)
        zero = {"legs": 0, "credits": 0, "debits": 0, "rejected": 0,
                "broadcasts": 0}
        for k, eng in enumerate(new_engines):
            gdir = self._new_dir(k)
            os.makedirs(gdir, exist_ok=True)
            ck.save_oracle(gdir, eng, 0,
                           extra={"epoch": 0, "out_seq": 0,
                                  "pending_reserve": dict(zero)})
        legs = settlement_legs(consolidation, self.m)
        plan = plan_reshard(
            self.n, self.m,
            sorted({bk // 2 for e in engines for bk in e.books}),
            sorted(consolidation))
        info = {"done": True, "old_offsets": offsets,
                "accounts": len(consolidation),
                "cash_total": sum(consolidation.values()),
                "per_group": [
                    {"orders": len(e.orders), "books": len(e.books),
                     "positions": len(e.positions)}
                    for e in new_engines],
                "plan": plan, "legs": legs}
        return info, legs

    def _settle(self, legs: List[List],
                kill_after_legs: Optional[int] = None) -> dict:
        import signal

        from kme_tpu.bridge import lease
        from kme_tpu.bridge.broker import BrokerError, InProcessBroker

        armed = (kill_after_legs is not None
                 and os.environ.get("KME_TEST_HOOKS") == "1")
        produced = suppressed = 0
        epochs = []
        for k in range(self.m):
            gdir = self._new_dir(k)
            # the coordinator's own lease grant: settle stamps ride
            # this epoch, and the first new leader's acquire lands
            # strictly above it — its broker-wide fence then retires
            # any still-running coordinator instead of racing it
            epoch = lease.acquire(gdir)
            epochs.append(epoch)
            log_dir = os.path.join(gdir, "broker-log")
            os.makedirs(log_dir, exist_ok=True)
            b = InProcessBroker(persist_dir=log_dir)
            try:
                b.create_topic(f"MatchIn.g{k}")
            except BrokerError:
                pass
            for g, seq, _xid, _aid, _amt, line in legs:
                if g != k:
                    continue
                off = b.produce(f"MatchIn.g{k}", None, line,
                                epoch=epoch, out_seq=seq)
                if off < 0:
                    suppressed += 1
                produced += 1
                if armed and produced >= kill_after_legs:
                    # the drill's mid-migration SIGKILL: a real kill -9
                    # of the coordinator process, nothing staged
                    os.kill(os.getpid(), signal.SIGKILL)
            b.sync()
        return {"done": True, "legs": produced,
                "dup_suppressed": suppressed, "epochs": epochs,
                "resume_cursors": [
                    sum(1 for leg in legs if leg[0] == k)
                    for k in range(self.m)]}

    # one canonical ordinal per coordinator phase: the flight-recorder
    # event seq IS the ordinal (durable identity, never a counter), so
    # a SIGKILL'd coordinator's re-run re-emits every completed phase
    # and the log's replay dedup keeps the first copy — the merged
    # timeline shows each phase exactly once however many times the
    # coordinator died (the reshard-under-storm drill asserts this)
    PHASES = ("fence", "migrate", "settle", "done")

    def _phase_event(self, evlog, phase: str, j: dict) -> None:
        info = j.get(phase) or {}
        offsets = (j.get("migrate") or {}).get("old_offsets") or []
        detail = {"old_groups": self.n, "new_groups": self.m}
        wall = (j.get("walls") or {}).get(f"{phase}_s")
        if wall is not None:
            detail["wall_s"] = wall
        epoch = None
        if phase == "fence":
            detail["stolen"] = [e["epoch"] for e in
                                info.get("stolen_epochs", [])]
            epoch = max(detail["stolen"], default=None)
        elif phase == "migrate":
            detail["accounts"] = info.get("accounts")
            detail["moved_key_frac"] = (info.get("plan") or {}).get(
                "moved_key_frac")
        elif phase == "settle":
            detail["legs"] = info.get("legs")
            detail["dup_suppressed"] = info.get("dup_suppressed")
            epoch = max(info.get("epochs", []), default=None)
        try:
            evlog.emit(f"reshard.{phase}",
                       seq=self.PHASES.index(phase), epoch=epoch,
                       offset=(max(offsets) if offsets
                               and phase != "fence" else None),
                       **{k: v for k, v in detail.items()
                          if v is not None})
        except Exception:
            pass    # the recorder never blocks a reshard

    def run(self, kill_after_legs: Optional[int] = None) -> dict:
        from kme_tpu.telemetry import events as cpevents

        os.makedirs(self.new_root, exist_ok=True)
        evlog = cpevents.open_log(self.new_root, "reshard",
                                  clock=self._clock)
        j = self._load_journal()
        j.update({"old_root": self.old_root, "new_root": self.new_root,
                  "old_groups": self.n, "new_groups": self.m})
        # per-phase walls (reshard_pause_ms decomposed): each phase
        # that RUNS in this incarnation records its wall into the
        # journal; a completed phase's wall survives a coordinator
        # SIGKILL via the journal, so the final document always carries
        # the wall of the run that actually did the work
        walls = j.setdefault("walls", {})
        if not j.get("fence", {}).get("done"):
            t0 = self._clock()
            j["fence"] = self._fence_old()
            walls["fence_s"] = round(self._clock() - t0, 6)
            self._save_journal(j)
        self._phase_event(evlog, "fence", j)
        if not j.get("migrate", {}).get("done"):
            t0 = self._clock()
            info, legs = self._migrate()
            j["migrate"] = info
            walls["migrate_s"] = round(self._clock() - t0, 6)
            self._save_journal(j)
        else:
            legs = j["migrate"]["legs"]
        self._phase_event(evlog, "migrate", j)
        if not j.get("settle", {}).get("done"):
            t0 = self._clock()
            j["settle"] = self._settle(legs,
                                       kill_after_legs=kill_after_legs)
            walls["settle_s"] = round(self._clock() - t0, 6)
            self._save_journal(j)
        self._phase_event(evlog, "settle", j)
        j["done"] = True
        self._save_journal(j)
        self._phase_event(evlog, "done", j)
        evlog.close()
        return j


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kme-reshard",
        description="re-split N stopped leader groups into M: fence the "
                    "old epochs, migrate book/position state through "
                    "the checkpoint codec, settle balances with stamped "
                    "exactly-once transfer legs, journal every phase")
    p.add_argument("--old-root", required=True,
                   help="supervisor state root of the drained old "
                        "generation (group k at <root>/group{k})")
    p.add_argument("--new-root", required=True,
                   help="state root the new generation will start from")
    p.add_argument("--old-groups", type=int, required=True, metavar="N")
    p.add_argument("--new-groups", type=int, required=True, metavar="M")
    p.add_argument("--test-kill-after-legs", type=int, default=None,
                   metavar="J",
                   help="chaos hook (armed only under KME_TEST_HOOKS=1):"
                        " SIGKILL this process after producing J "
                        "settlement legs — the drill's crash-during-"
                        "migration fault")
    args = p.parse_args(argv)
    try:
        coord = ReshardCoordinator(args.old_root, args.new_root,
                                   args.old_groups, args.new_groups)
        j = coord.run(kill_after_legs=args.test_kill_after_legs)
    except (ValueError, OSError) as e:
        print(f"kme-reshard: {e}", file=sys.stderr)
        return 2
    doc = {k: j[k] for k in ("old_groups", "new_groups", "done")
           if k in j}
    doc["moved_key_frac"] = j.get("migrate", {}).get(
        "plan", {}).get("moved_key_frac")
    doc["legs"] = j.get("settle", {}).get("legs")
    doc["resume_cursors"] = j.get("settle", {}).get("resume_cursors")
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
