"""Topic provisioner — the topic.js role (/root/reference/topic.js:14-25):
create `MatchIn` and `MatchOut`, one partition each, against a broker."""

from __future__ import annotations

import argparse

from kme_tpu.bridge.service import TOPIC_IN, TOPIC_OUT


def provision(broker) -> dict:
    """Create both topics; returns {topic: created?}."""
    return {t: broker.create_topic(t, partitions=1)
            for t in (TOPIC_IN, TOPIC_OUT)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kme-provision", description=__doc__)
    p.add_argument("--broker", default="127.0.0.1:9092",
                   metavar="HOST:PORT",
                   help="broker address (a running kme-serve)")
    args = p.parse_args(argv)
    from kme_tpu.bridge.tcp import TcpBroker, parse_addr

    host, port = parse_addr(args.broker)
    client = TcpBroker(host, port)
    try:
        for topic, created in provision(client).items():
            state = "created" if created else "exists"
            print(f"{topic}: {state} (partitions=1)")
    finally:
        client.close()
    return 0
