"""Topic provisioner — the topic.js role (/root/reference/topic.js:14-25):
create `MatchIn` and `MatchOut`, one partition each, against a broker."""

from __future__ import annotations

import argparse

from kme_tpu.bridge.service import TOPIC_IN, TOPIC_OUT


def provision(broker, topics=None) -> dict:
    """Create the topics (default: the classic MatchIn/MatchOut pair);
    returns {topic: created?}."""
    return {t: broker.create_topic(t, partitions=1)
            for t in (topics or (TOPIC_IN, TOPIC_OUT))}


def group_topics(k: int) -> tuple:
    """The namespaced durable topics of shard group k (bridge/service.py
    --group mode): its input/output substreams plus the stamped
    cross-shard transfer evidence log."""
    return (f"{TOPIC_IN}.g{k}", f"{TOPIC_OUT}.g{k}", f"Xfer.g{k}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kme-provision", description=__doc__)
    p.add_argument("--broker", default="127.0.0.1:9092",
                   metavar="HOST:PORT",
                   help="broker address (a running kme-serve)")
    p.add_argument("--group", default=None, metavar="K/N",
                   help="provision shard group K's namespaced topics "
                        "(MatchIn.gK/MatchOut.gK/Xfer.gK) instead of "
                        "the classic pair")
    args = p.parse_args(argv)
    from kme_tpu.bridge.tcp import TcpBroker, parse_addr

    host, port = parse_addr(args.broker)
    topics = None
    if args.group is not None:
        k = int(args.group.split("/", 1)[0])
        topics = group_topics(k)
    client = TcpBroker(host, port)
    try:
        for topic, created in provision(client, topics=topics).items():
            state = "created" if created else "exists"
            print(f"{topic}: {state} (partitions=1)")
    finally:
        client.close()
    return 0
