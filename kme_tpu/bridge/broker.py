"""In-process broker core: ordered topic logs with offset fetch.

Semantics mirror what the reference actually uses of Kafka
(/root/reference/topic.js:14-25, exchange_test.js:14-16, consumer.js:13-17):
- named topics created explicitly (1 partition each — the provisioner
  pins `numPartitions: 1`, so each topic is ONE totally-ordered log);
- producers append (key, value) string records;
- consumers fetch by offset (fromBeginning => offset 0) and poll
  blocking with a timeout.

Thread-safe; `fetch` blocks on a condition variable until data arrives
or the timeout lapses — the poll-loop shape of a Kafka consumer without
the broker round-trip.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional


class BrokerError(RuntimeError):
    pass


@dataclasses.dataclass(frozen=True)
class Record:
    offset: int
    key: Optional[str]
    value: str


class _Topic:
    def __init__(self, partitions: int = 1) -> None:
        self.partitions = partitions
        self.log: List[Record] = []


class InProcessBroker:
    """The broker API the rest of the bridge codes against. The TCP
    client (tcp.TcpBroker) implements the same three methods."""

    def __init__(self) -> None:
        self._topics: Dict[str, _Topic] = {}
        self._lock = threading.Lock()
        self._data = threading.Condition(self._lock)

    # -- admin ----------------------------------------------------------

    def create_topic(self, name: str, partitions: int = 1) -> bool:
        """Create a topic; False if it already exists (kafkajs
        createTopics semantics: returns false when nothing was created)."""
        if partitions != 1:
            raise BrokerError("only 1 partition per topic is supported "
                              "(the reference provisions exactly 1)")
        with self._lock:
            if name in self._topics:
                return False
            self._topics[name] = _Topic(partitions)
            return True

    def topics(self) -> Dict[str, int]:
        with self._lock:
            return {n: t.partitions for n, t in self._topics.items()}

    # -- data path ------------------------------------------------------

    def produce(self, topic: str, key: Optional[str], value: str) -> int:
        """Append one record; returns its offset."""
        with self._data:
            t = self._topics.get(topic)
            if t is None:
                raise BrokerError(f"unknown topic {topic!r}")
            off = len(t.log)
            t.log.append(Record(off, key, value))
            self._data.notify_all()
            return off

    def fetch(self, topic: str, offset: int, max_records: int = 1024,
              timeout: float = 0.0) -> List[Record]:
        """Records from `offset` (at most max_records). Blocks up to
        `timeout` seconds while the log end is <= offset."""
        with self._data:
            t = self._topics.get(topic)
            if t is None:
                raise BrokerError(f"unknown topic {topic!r}")
            if timeout > 0 and len(t.log) <= offset:
                self._data.wait_for(lambda: len(t.log) > offset,
                                    timeout=timeout)
            return t.log[offset:offset + max_records]

    def end_offset(self, topic: str) -> int:
        with self._lock:
            t = self._topics.get(topic)
            if t is None:
                raise BrokerError(f"unknown topic {topic!r}")
            return len(t.log)
