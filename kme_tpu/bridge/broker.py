"""In-process broker core: ordered topic logs with offset fetch.

Semantics mirror what the reference actually uses of Kafka
(/root/reference/topic.js:14-25, exchange_test.js:14-16, consumer.js:13-17):
- named topics created explicitly (1 partition each — the provisioner
  pins `numPartitions: 1`, so each topic is ONE totally-ordered log);
- producers append (key, value) string records;
- consumers fetch by offset (fromBeginning => offset 0) and poll
  blocking with a timeout.

Thread-safe; `fetch` blocks on a condition variable until data arrives
or the timeout lapses — the poll-loop shape of a Kafka consumer without
the broker round-trip.

`persist_dir` makes the logs DURABLE: each topic appends to an
append-only JSONL file and the broker reloads every topic at startup —
the Kafka-retains-the-log property the engine's checkpoint/resume
contract depends on (the restored MatchIn offset must still address the
same records after a broker restart). A torn trailing line (crash mid-
append) is dropped on reload.

Exactly-once visible output (the path the reference commented out at
KProcessor.java:29) is built from two broker-side rules applied to
records carrying an ``(epoch, out_seq)`` produce stamp:

- **fencing**: a produce stamped with an epoch below the broker's fence
  raises BrokerFenced — a deposed leader can never make a write
  visible. The fence advances to any higher epoch seen (produce or an
  explicit ``fence()`` from a newly promoted leader) and is recovered
  from the stamps in the log on reload.
- **idempotent produce**: per topic, a stamped record whose ``out_seq``
  is at or below the durable watermark is suppressed (no append,
  ``dup_suppressed`` counts it) — a restarted leader deterministically
  re-produces its post-snapshot tail with the SAME stamps, so the
  durable log itself stays duplicate-free.

Unstamped produces behave exactly as before; log lines stay
``[key,value]`` for them and gain two elements (``[key,value,epoch,
out_seq]``) only when stamped, so pre-existing logs load unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
from typing import Dict, IO, List, Optional

from kme_tpu import faults


class BrokerError(RuntimeError):
    pass


class BrokerOverload(BrokerError):
    """The bounded ingress queue shed this produce (wire-level
    `rej_overload`, wire.py rej table code 9). Producers should back
    off and retry; the broker never blocks them."""

    code = "rej_overload"


class BrokerFenced(BrokerError):
    """A produce stamped with a stale leader epoch. Not retryable: the
    producer has been deposed and must exit so its supervisor can
    restart it under a fresh epoch (serve exits 75)."""

    code = "fenced"


@dataclasses.dataclass(frozen=True)
class Record:
    offset: int
    key: Optional[str]
    value: str
    epoch: Optional[int] = None
    out_seq: Optional[int] = None
    # broker-admission wall clock, microseconds since epoch — the
    # INTENDED-START stamp for coordinated-omission-safe latency
    # (stamped at produce time, before any queueing the consumer's
    # dequeue rate would hide). In-memory only: log rows keep their
    # [key,value(,epoch,out_seq)] shape, so records reloaded after a
    # restart carry ats=None and latency attribution simply skips them.
    ats: Optional[int] = None


class _Topic:
    def __init__(self, partitions: int = 1,
                 logfile: Optional[IO] = None) -> None:
        self.partitions = partitions
        self.log: List[Record] = []
        self.logfile = logfile
        # idempotent-produce watermark: highest out_seq made durable on
        # this topic (-1 = no stamped record yet); recovered from the
        # log stamps on reload.
        self.max_out_seq = -1


class InProcessBroker:
    """The broker API the rest of the bridge codes against. The TCP
    client (tcp.TcpBroker) implements the same three methods."""

    def __init__(self, persist_dir: Optional[str] = None,
                 max_lag: Optional[int] = None) -> None:
        self._topics: Dict[str, _Topic] = {}
        self._lock = threading.Lock()
        self._data = threading.Condition(self._lock)
        self._persist_dir = persist_dir
        # bounded ingress: once a consumer has committed a watermark for
        # a topic (MatchService commits MatchIn each batch), producing
        # more than `max_lag` records past it is refused with
        # BrokerOverload instead of growing the backlog without bound —
        # shed load, never stall. Topics without a watermark (MatchOut)
        # are unbounded.
        self._max_lag = max_lag
        self._commits: Dict[str, int] = {}
        self.overload_rejects = 0
        # exactly-once state (recovered from log stamps on reload)
        self._fence_epoch = 0
        self.fenced_produces = 0
        self.dup_suppressed = 0
        # latency attribution hook: fn(topic, records, now_us) called
        # after each non-empty fetch DELIVERS records to a consumer —
        # the serving process hosts the broker, so consumer receipt of
        # MatchOut is observable here (MatchService wires this to the
        # lat_consume histogram). Called outside the broker lock.
        self.deliver_observer = None
        if persist_dir is not None:
            os.makedirs(persist_dir, exist_ok=True)
            for name in sorted(os.listdir(persist_dir)):
                if name.endswith(".log"):
                    self._load_topic(name[:-4])

    # -- durability -----------------------------------------------------

    def _log_path(self, name: str) -> str:
        return os.path.join(self._persist_dir, f"{name}.log")

    def _load_topic(self, name: str) -> None:
        """Reload a topic log. Committed records are NEVER rewritten: a
        torn FINAL line (crash mid-append) is repaired crash-safely by
        truncating the file at the torn line's byte offset; an
        undecodable INTERIOR line is corruption of committed data and
        refuses to load (silently dropping everything after it would
        permanently lose records the checkpoint offset still addresses)."""
        path = self._log_path(name)
        topic = _Topic()
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        torn_at = None
        while pos < len(data):
            nl = data.find(b"\n", pos)
            if nl < 0:
                torn_at = pos  # unterminated trailing append
                break
            try:
                row = json.loads(data[pos:nl].decode("utf-8"))
                if len(row) not in (2, 4):
                    raise ValueError(f"bad row arity {len(row)}")
                key, value = row[0], row[1]
                epoch = row[2] if len(row) == 4 else None
                out_seq = row[3] if len(row) == 4 else None
            except (ValueError, TypeError, UnicodeDecodeError):
                # produce() appends each record as ONE newline-terminated
                # write, and partial writes are prefixes — so any line
                # that HAS its newline was committed whole; failing to
                # decode it means committed data corruption, not a crash
                # artifact, wherever it sits in the file.
                raise BrokerError(
                    f"corrupt record in {path} at byte {pos}: refusing "
                    f"to load (only an unterminated final line is "
                    f"repairable; committed records are immutable)")
            topic.log.append(Record(len(topic.log), key, value,
                                    epoch, out_seq))
            if out_seq is not None:
                topic.max_out_seq = max(topic.max_out_seq, int(out_seq))
            if epoch is not None:
                self._fence_epoch = max(self._fence_epoch, int(epoch))
            pos = nl + 1
        if torn_at is not None:
            print(f"broker: dropping torn tail of {path} at byte {torn_at} "
                  f"({len(data) - torn_at} bytes)", file=sys.stderr)
            with open(path, "r+b") as f:
                f.truncate(torn_at)
        topic.logfile = open(path, "a", encoding="utf-8")
        self._topics[name] = topic

    # -- admin ----------------------------------------------------------

    def create_topic(self, name: str, partitions: int = 1) -> bool:
        """Create a topic; False if it already exists (kafkajs
        createTopics semantics: returns false when nothing was created)."""
        if partitions != 1:
            raise BrokerError("only 1 partition per topic is supported "
                              "(the reference provisions exactly 1)")
        if "/" in name or name.startswith("."):
            raise BrokerError(f"invalid topic name {name!r}")
        with self._lock:
            if name in self._topics:
                return False
            logfile = None
            if self._persist_dir is not None:
                logfile = open(self._log_path(name), "a", encoding="utf-8")
            self._topics[name] = _Topic(partitions, logfile)
            return True

    def topics(self) -> Dict[str, int]:
        with self._lock:
            return {n: t.partitions for n, t in self._topics.items()}

    # -- data path ------------------------------------------------------

    def produce(self, topic: str, key: Optional[str], value: str,
                epoch: Optional[int] = None,
                out_seq: Optional[int] = None) -> int:
        """Append one record; returns its offset. With an
        ``(epoch, out_seq)`` stamp the append is fenced and idempotent:
        a stale epoch raises BrokerFenced, and an ``out_seq`` at or
        below the topic's durable watermark is suppressed (returns -1,
        nothing appended) — replayed tails after a crash vanish here
        instead of surfacing to consumers."""
        if faults.should("broker.produce"):
            raise BrokerError("injected fault: broker.produce")
        with self._data:
            t = self._topics.get(topic)
            if t is None:
                raise BrokerError(f"unknown topic {topic!r}")
            if epoch is not None:
                if epoch < self._fence_epoch:
                    self.fenced_produces += 1
                    raise BrokerFenced(
                        f"fenced: produce to {topic!r} from stale epoch "
                        f"{epoch} < fence {self._fence_epoch}")
                self._fence_epoch = epoch
            if out_seq is not None and out_seq <= t.max_out_seq:
                self.dup_suppressed += 1
                return -1
            if (self._max_lag is not None and topic in self._commits
                    and len(t.log) - self._commits[topic]
                    >= self._max_lag):
                self.overload_rejects += 1
                raise BrokerOverload(
                    f"rej_overload: topic {topic!r} backlog "
                    f"{len(t.log) - self._commits[topic]} >= max_lag "
                    f"{self._max_lag}")
            off = len(t.log)
            import time as _time

            t.log.append(Record(off, key, value, epoch, out_seq,
                                _time.time_ns() // 1000))
            if out_seq is not None:
                t.max_out_seq = out_seq
            if t.logfile is not None:
                row = ([key, value] if epoch is None and out_seq is None
                       else [key, value, epoch, out_seq])
                t.logfile.write(json.dumps(row,
                                           separators=(",", ":")) + "\n")
                t.logfile.flush()
            self._data.notify_all()
            return off

    def fence(self, epoch: int) -> None:
        """Advance the fence so every produce stamped below `epoch` is
        rejected. A newly promoted leader calls this at startup: the
        reloaded log only teaches the broker its PREDECESSORS' epochs,
        so without an explicit fence a zombie old leader holding the
        previous epoch would still get through."""
        with self._lock:
            self._fence_epoch = max(self._fence_epoch, int(epoch))

    @property
    def fence_epoch(self) -> int:
        with self._lock:
            return self._fence_epoch

    def fetch(self, topic: str, offset: int, max_records: int = 1024,
              timeout: float = 0.0) -> List[Record]:
        """Records from `offset` (at most max_records). Blocks up to
        `timeout` seconds while the log end is <= offset."""
        if faults.should("broker.fetch"):
            raise BrokerError("injected fault: broker.fetch")
        with self._data:
            t = self._topics.get(topic)
            if t is None:
                raise BrokerError(f"unknown topic {topic!r}")
            if timeout > 0 and len(t.log) <= offset:
                self._data.wait_for(lambda: len(t.log) > offset,
                                    timeout=timeout)
            recs = t.log[offset:offset + max_records]
        obs = self.deliver_observer
        if obs is not None and recs:
            import time as _time

            try:
                obs(topic, recs, _time.time_ns() // 1000)
            except Exception:
                pass        # observability must never fail a fetch
        return recs

    def commit(self, topic: str, offset: int) -> None:
        """Advance a consumer watermark (arms the `max_lag` ingress
        bound for `topic`). Monotonic; unknown topics raise."""
        with self._lock:
            if topic not in self._topics:
                raise BrokerError(f"unknown topic {topic!r}")
            cur = self._commits.get(topic, 0)
            self._commits[topic] = max(cur, int(offset))

    def end_offset(self, topic: str) -> int:
        with self._lock:
            t = self._topics.get(topic)
            if t is None:
                raise BrokerError(f"unknown topic {topic!r}")
            return len(t.log)

    def sync(self) -> None:
        """fsync every topic log to stable storage. `produce` only
        flush()es (process-crash durability); callers that are about to
        commit an offset DERIVED from these records (MatchService
        checkpoints) call sync() first so an fsync'd snapshot offset can
        never address records the OS lost in a power failure. The
        persist directory is fsync'd too: a freshly created topic log is
        a new directory entry, and POSIX only makes those durable after
        a directory fsync."""
        with self._lock:
            any_file = False
            for t in self._topics.values():
                if t.logfile is not None:
                    t.logfile.flush()
                    os.fsync(t.logfile.fileno())
                    any_file = True
            if any_file:
                dfd = os.open(self._persist_dir, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
